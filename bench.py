"""Benchmark: flagship BERT-base pretraining step, tokens/sec/chip.

North star (BASELINE.md): ERNIE/BERT-base pretrain tokens/sec/chip at
>=35% MFU.  The reference publishes no in-repo numbers (BASELINE.json
"published": {}), so vs_baseline reports measured-MFU / 0.35 — the ratio to
the target; 1.0 means the 35% MFU goal is met.

Self-validation (round-2, after VERDICT r1 flagged an impossible 179% MFU):
- timing fetches the loss *value* to host every step, so the wall clock can
  never be shorter than true device compute (defeats any async-dispatch or
  remote-platform distortion in ``block_until_ready``);
- the FLOP model counts only matmul params (embedding gather tables
  excluded; the word-embedding table counts once because it is tied to the
  MLM decoder matmul) plus the attention term 12*L*S*h per token;
- asserts implied MFU <= 100% before printing; per-step latency and the
  full accounting go to stderr.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time

import numpy as np


def _marginal_step_time(step, state, batches, k_short, k_long, reps):
    """Shared timing harness: min-of-segments marginal step time.

    Each segment chains K steps through the donated state and ends with a
    host fetch of the loss VALUE, so a segment cannot finish before the
    device executed every step in it (honest regardless of how the
    platform implements block_until_ready — the axon tunnel's did not
    wait in round 1, implying 179% MFU).  The marginal cost between long
    and short segments cancels the fixed per-segment dispatch/fetch RTT a
    production input pipeline would overlap.  Returns (dt, dt_worst,
    state); dt_worst includes all fixed overhead.
    """
    def seg(k, i0):
        nonlocal state
        t0 = time.perf_counter()
        loss = None
        for i in range(i0, i0 + k):
            state, loss = step(state, batches[i % len(batches)])
        lv = float(loss)
        if not np.isfinite(lv):
            raise RuntimeError("bench loss went non-finite")
        return time.perf_counter() - t0

    shorts, longs = [], []
    i0 = 0
    for _ in range(reps):
        shorts.append(seg(k_short, i0))
        i0 += k_short
        longs.append(seg(k_long, i0))
        i0 += k_long
    dt = (min(longs) - min(shorts)) / (k_long - k_short)
    dt_worst = max(longs) / k_long
    # plain raise, not assert: the guards must survive python -O
    if dt <= 0:
        raise RuntimeError(
            "non-positive marginal step time (%.1f ms): RTT noise swamped "
            "the measurement; segment times shorts=%s longs=%s"
            % (dt * 1e3, shorts, longs))
    return dt, dt_worst, state


def _flops_per_step(cfg, params, B, S, P):
    """Training FLOPs for one step: 6 per matmul-param-use + exact
    attention term.

    The MLM head (tied word-embedding decoder + the D x D mlm_transform)
    runs only on the P masked positions per sequence (the reference
    BERT/ERNIE static graph gathers mask_pos before the decoder); the
    transformer trunk runs on all S positions.  Embedding gather tables
    (word/position/token-type lookups) cost no matmul FLOPs.
    Attention scores+context: 2*S*h MACs per token per layer forward
    = 12*L*S*h FLOPs per token for fwd+bwd.
    """
    d, v = cfg.hidden_size, cfg.vocab_size
    head = v * d + d * d + d + v  # tied decoder + mlm_transform (+biases)
    gather_only = 0
    trunk = 0
    for name, arr in params.items():
        n = int(np.prod(arr.shape))
        if ("position" in name or "token_type" in name
                or "word" in name or "mlm" in name):
            gather_only += n
        else:
            trunk += n
    attn = 12.0 * cfg.num_hidden_layers * cfg.hidden_size * S
    per_token_trunk = 6.0 * trunk + attn
    per_masked = 6.0 * head
    total = B * S * per_token_trunk + B * P * per_masked
    return total, trunk, head


def _skip(reason):
    """The driver parses stdout: any infrastructure failure must yield
    ONE structured skip line and rc 0, never a raw traceback."""
    print(json.dumps({"skipped": True, "reason": reason}))
    return 0


# substrings that mark a backend/tunnel failure (vs a bug in the bench
# itself, which must still traceback loudly)
_BACKEND_ERR_MARKERS = (
    "UNAVAILABLE",
    "Unable to initialize backend",
    "backend setup",
    "DEADLINE_EXCEEDED",
    "failed to connect",
    "Connection reset",
    "Socket closed",
)


def _is_backend_failure(e):
    """True when the exception is the platform dying, not the bench
    being wrong.  BENCH_r05 regression: the guard only covered import
    time, but the axon tunnel can die at ANY jax call — default_backend,
    first compile, a mid-segment execute — and every such failure
    surfaces as a JaxRuntimeError/XlaRuntimeError or carries an XLA
    status marker in the message chain."""
    seen = set()
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        if type(e).__name__ in ("JaxRuntimeError", "XlaRuntimeError"):
            return True
        msg = str(e)
        if any(m in msg for m in _BACKEND_ERR_MARKERS):
            return True
        e = e.__cause__ or e.__context__
    return False


def _metrics_snapshot():
    """Compact observability dump for the output line: compile counts
    and device/host memory as the run ends — the before/after numbers a
    perf investigation starts from."""
    try:
        from paddle_tpu import observability as obs

        obs.SystemMetricsSampler().sample_once()
        snap = obs.default_registry().snapshot()
        out = {}
        for name, key in (("xla_compilations_total", "value"),
                          ("xla_compile_ms", "sum"),
                          ("host_rss_bytes", "value"),
                          ("jax_live_arrays", "value")):
            fam = snap.get(name)
            if fam and fam["series"]:
                out[name] = fam["series"][0].get(key)
        mem = snap.get("device_memory_bytes_in_use")
        if mem and mem["series"]:
            out["device_memory_bytes_in_use"] = {
                s["labels"].get("device", "?"): s.get("value")
                for s in mem["series"]
            }
        mfu = snap.get("mfu")
        if mfu and mfu["series"]:
            out["mfu"] = {
                s["labels"].get("executable", "?"): s.get("value")
                for s in mfu["series"]
            }
        return out
    except Exception as e:  # telemetry must never sink the bench
        return {"error": repr(e)[:200]}


def main():
    if "--recsys" in sys.argv:
        return _run_recsys()
    if "--generate" in sys.argv:
        return _run_generate()
    multichip = "--multichip" in sys.argv
    if multichip:
        n = 8
        idx = sys.argv.index("--multichip")
        if idx + 1 < len(sys.argv) and sys.argv[idx + 1].isdigit():
            n = int(sys.argv[idx + 1])
        # when real accelerator hardware is plausibly present — an
        # explicit non-cpu JAX_PLATFORMS (the axon site), a libtpu
        # install, or /dev/accel* device nodes — leave the platform
        # alone: that IS the reserved on-hardware capture.  Otherwise
        # simulate n chips on the CPU backend; the env must be set
        # BEFORE any jax import initializes a platform (same
        # discipline as __graft_entry__.dryrun_multichip)
        if (os.environ.get("JAX_PLATFORMS", "cpu") in ("", "cpu")
                and not _accelerator_plausible()):
            os.environ["JAX_PLATFORMS"] = "cpu"
            flag = "--xla_force_host_platform_device_count"
            if flag not in os.environ.get("XLA_FLAGS", ""):
                os.environ["XLA_FLAGS"] = (
                    os.environ.get("XLA_FLAGS", "") + " %s=%d" % (flag, n))
    try:
        if os.getenv("BENCH_FORCE_BACKEND_FAIL") == "init":
            raise RuntimeError(
                "Unable to initialize backend 'axon': UNAVAILABLE: "
                "injected by BENCH_FORCE_BACKEND_FAIL=init")
        import jax

        on_tpu = jax.default_backend() == "tpu"
        jax.devices()
    except Exception as e:
        return _skip("backend init failed: %s: %s"
                     % (type(e).__name__, str(e)[:300]))
    try:
        if multichip:
            return _run_multichip(n)
        return _run(on_tpu)
    except Exception as e:
        # BENCH_r05 regression: init succeeded but the tunnel died at
        # the first real compile — still an infra skip, not a bench bug
        if _is_backend_failure(e):
            return _skip("backend failed mid-run: %s: %s"
                         % (type(e).__name__, str(e)[:300]))
        raise


def _run(on_tpu):
    import jax

    if os.getenv("BENCH_FORCE_BACKEND_FAIL") == "late":
        raise RuntimeError(
            "TPU backend setup/compile error (Unavailable): injected by "
            "BENCH_FORCE_BACKEND_FAIL=late")

    # arm the compile-event hooks so the output line's metrics_snapshot
    # carries compile count/time for THIS run
    from paddle_tpu.observability import install_jax_compile_hooks

    install_jax_compile_hooks()

    from paddle_tpu import distributed as dist
    from paddle_tpu import models
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.fluid.optimizer import AdamWOptimizer

    if on_tpu:
        cfg = models.BertConfig(  # BERT-base
            vocab_size=30528,  # pad to multiple of 64 for lane alignment
            hidden_size=768, num_hidden_layers=12, num_attention_heads=12,
            intermediate_size=3072, max_position_embeddings=512,
            hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
        )
        # masked-position MLM shrinks the logits buffer ~6x, which is what
        # previously capped the batch at 16; B is env-sweepable
        B, S, P = int(os.getenv("BENCH_B", "60")), 512, 80
        k_short, k_long, reps = 10, 30, 2
        # bf16 peak TFLOP/s for one v5e chip (public spec: 197 bf16)
        peak = 197e12
    else:  # CPU smoke path so the bench never hangs off-TPU
        cfg = models.BertConfig.tiny()
        B, S, P = 4, 32, 8
        k_short, k_long, reps = 1, 3, 1
        peak = 1e12

    with dygraph.guard():
        model = models.BertForPretraining(cfg)
        opt = AdamWOptimizer(learning_rate=1e-4, weight_decay=0.01)
        mesh = dist.auto_mesh(1)

        def loss_fn(m, batch):
            logits, nsp_logits = m(
                batch["input_ids"], batch["token_type_ids"],
                batch["position_ids"],
                masked_positions=batch["masked_positions"],
            )
            return m.loss(
                logits, nsp_logits, batch["mlm_labels"],
                batch["mlm_weights"], batch["nsp_labels"],
            )

        step = dist.ShardedTrainStep(
            model, opt, loss_fn, mesh, zero_stage=0,
            amp="bf16" if on_tpu else None,
        )
        state = step.init()
        n_params = sum(int(np.prod(v.shape)) for v in state["params"].values())
        flops_step, trunk_params, head_params = _flops_per_step(
            cfg, state["params"], B, S, P
        )

        rng = np.random.RandomState(0)

        def make_batch():
            pos = np.stack([
                np.sort(rng.choice(S, size=P, replace=False))
                for _ in range(B)
            ]).astype(np.int32)
            return {
                "input_ids": rng.randint(
                    0, cfg.vocab_size, (B, S)).astype(np.int32),
                "token_type_ids": np.zeros((B, S), np.int32),
                "position_ids": np.tile(
                    np.arange(S, dtype=np.int32), (B, 1)),
                "masked_positions": pos,
                "mlm_labels": rng.randint(
                    0, cfg.vocab_size, (B, P)).astype(np.int32),
                "mlm_weights": np.ones((B, P), np.float32),
                "nsp_labels": rng.randint(0, 2, (B, 1)).astype(np.int32),
            }

        batches = [make_batch() for _ in range(4)]

        # warmup (compile + two real executes, value-fetched)
        for i in range(2):
            state, loss = step(state, batches[i % 4])
        float(loss)

        # measured FLOPs: what the fused HLO actually contains per step
        # (cost_analysis of the compiled executable), vs the hand model
        cost = step.cost_analysis(state, batches[0])

        # pre-place the batches on device (a production input pipeline
        # double-buffers transfers; over the axon tunnel an in-loop
        # device_put would bill network bandwidth to the step time)
        np_batches = batches
        batches = [step.place_batch(b) for b in batches]

        dt, dt_worst, state = _marginal_step_time(
            step, state, batches, k_short, k_long, reps)

        autotune = None
        if "--autotune" in sys.argv:
            try:
                autotune = _autotune_bert_step(
                    cfg, mesh, loss_fn, np_batches, k_short, k_long,
                    reps, dt, on_tpu, B, S)
            except Exception as e:  # search must never sink the bench
                print("bench autotune failed: %r" % (e,), file=sys.stderr)
                autotune = {"error": repr(e)[:300]}

    tokens_per_sec = B * S / dt
    mfu = (flops_step / dt) / peak
    mfu_measured = None
    if cost and cost.get("flops"):
        from paddle_tpu.observability.xla_cost import record_mfu

        mfu_measured = record_mfu(
            "bench.bert_step", cost["flops"], dt, peak=peak)
        print(
            "bench: XLA cost_analysis %.1f GFLOP/step (hand model %.1f), "
            "measured MFU %s"
            % (cost["flops"] / 1e9, flops_step / 1e9,
               "%.1f%%" % (mfu_measured * 100)
               if mfu_measured is not None else "n/a"),
            file=sys.stderr,
        )
    print(
        "bench: B=%d S=%d P=%d marginal step %.2f ms over %dx(%d,%d)-step "
        "segments (conservative incl. dispatch RTT: %.2f ms), %.0f "
        "tokens/s, params=%.1fM (trunk %.1fM, head %.1fM on P rows), "
        "%.1f GFLOP/step, implied MFU %.1f%%"
        % (B, S, P, dt * 1e3, reps, k_short, k_long, dt_worst * 1e3,
           tokens_per_sec, n_params / 1e6, trunk_params / 1e6,
           head_params / 1e6, flops_step / 1e9, mfu * 100),
        file=sys.stderr,
    )
    if mfu > 1.0:
        raise RuntimeError(
            "implied MFU %.1f%% exceeds physical peak — measurement or FLOP "
            "accounting is wrong; refusing to report" % (mfu * 100)
        )

    resnet = None
    if on_tpu or os.getenv("BENCH_RESNET"):
        try:
            resnet = _bench_resnet(on_tpu, peak)
        except Exception as e:  # the headline metric must still report
            print("resnet bench failed: %r" % (e,), file=sys.stderr)

    out = {
        "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.35, 4),
        "mfu_model": round(mfu, 4),
        # a CPU capture is the tiny smoke config, not a number of record
        # — consumers must be able to tell without guessing from scale
        "platform": jax.default_backend(),
        "smoke_config": not on_tpu,
    }
    if mfu_measured is not None:
        out["mfu_measured"] = round(mfu_measured, 4)
        out["flops_per_step_xla"] = cost["flops"]
    if autotune is not None:
        out["autotune"] = autotune
    if resnet is not None:
        out["extra"] = resnet
    out["metrics_snapshot"] = _metrics_snapshot()
    print(json.dumps(out))
    return 0


def _run_recsys():
    """--recsys: the online-learning capture — events/sec +
    minutes-to-freshness, the pipelined-vs-sync embedding A/B and the
    hot-row cache, via benchmarks/streaming_bench (one JSON line with
    the same skip/platform/smoke_config conventions as the headline
    bench; remaining flags pass through, e.g. --autotune)."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    import streaming_bench

    return streaming_bench.main(
        [a for a in sys.argv[1:] if a != "--recsys"])


def _run_generate():
    """--generate: the autoregressive-decoding capture — tokens/s,
    TTFT, ITL, the KV-cache-vs-recompute-prefix A/B, and the
    paged-vs-dense KV A/B (block-pool bytes/occupancy, prefix-cache
    hit rate, speculative acceptance), via benchmarks/generation_bench
    (one JSON line with the same skip/platform/smoke_config
    conventions as the headline bench; remaining flags pass through,
    e.g. --autotune / --slots N / --block-size 16 / --prefix-cache /
    --kv-dtype int8 / --draft-len 3 / --dense)."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks"))
    import generation_bench

    return generation_bench.main(
        [a for a in sys.argv[1:] if a != "--generate"])


def _accelerator_plausible():
    """Cheap pre-jax-import probe for real TPU hardware: /dev/accel*
    (or vfio-bound) device NODES — an installed libtpu wheel is not a
    signal, the toolchain image bakes it in on TPU-less boxes.
    Deciding for sure needs jax, which would lock the platform before
    --multichip can pin the CPU simulator, so device nodes are the
    best available heuristic."""
    import glob as _glob

    return bool(_glob.glob("/dev/accel*") or _glob.glob("/dev/vfio/*"))


def _run_multichip(n):
    """--multichip N: time the n-device dryrun train step per ZeRO
    stage and report the per-collective op counts + bytes extracted
    from the COMPILED HLO — so the multichip capture carries real
    collective traffic, not just an rc.  One JSON line, same
    skip/platform/smoke_config conventions as the headline bench."""
    import jax

    from paddle_tpu import distributed as dist
    from paddle_tpu.distributed import _zero_harness as zh

    on_cpu = jax.default_backend() == "cpu"
    devices = jax.devices("cpu") if on_cpu else jax.devices()
    if len(devices) < n:
        return _skip("multichip wants %d %s devices, have %d%s"
                     % (n, jax.default_backend(), len(devices),
                        " (stale XLA_FLAGS in this process)"
                        if on_cpu else ""))
    devices = devices[:n]
    mesh = dist.auto_mesh(n, devices=devices)

    # same workload/contract as the dryrun's ZeRO parity section (one
    # shared harness — the bench measures what the dryrun validates);
    # local batch 4 so accumulate_steps=4 divides
    B, S = 4 * n, 32
    batches = zh.bert_batches(zh.tiny_bert_config(), B, S, 2, seed=0)

    def build_and_time(params, want_stats=False):
        def body(step, state):
            loss = None
            for i in range(2):
                state, loss = step(state, batches[i % 2])
            float(loss)
            placed = [step.place_batch(b) for b in batches]
            dt, _w, state2 = _marginal_step_time(
                step, state, placed, 1, 3, 1)
            stats = (step.collective_stats(state2, batches[0])
                     if want_stats else None)
            est = step.comm_estimate() if want_stats else None
            return dt, stats, est

        return zh.run_deterministic(mesh, body, lr=1e-4, **params)

    stages = {}
    for label, params in (
            ("zero1", {"zero_stage": 1}),
            ("zero2", {"zero_stage": 2}),
            ("zero3", {"zero_stage": 3}),
            ("zero2_acc4", {"zero_stage": 2, "accumulate_steps": 4})):
        dt, stats, est = build_and_time(params, want_stats=True)
        entry = {"step_ms": round(dt * 1e3, 3)}
        if stats:
            entry["collectives"] = {
                k: {kk: (round(vv, 1) if isinstance(vv, float) else vv)
                    for kk, vv in v.items()}
                for k, v in stats.items() if isinstance(v, dict)}
            entry["hlo_wire_bytes"] = round(stats.get(
                "wire_bytes_total", 0.0), 1)
        if est:
            entry["est_wire_bytes"] = round(est["wire_bytes_total"], 1)
        stages[label] = entry

    autotune = None
    if "--autotune" in sys.argv:
        from paddle_tpu import tune

        report = tune.search_train_step(
            lambda p: build_and_time(p)[0], mesh=mesh,
            workload="bench.multichip:n%d.B%d.S%d" % (n, B, S))
        print("multichip autotune:\n%s" % report.format(),
              file=sys.stderr)
        w = report.winner
        autotune = {
            "cache_hit": report.cache_hit,
            "winner": w.to_dict() if w else None,
            "default_s": report.default_s,
            "counts": report.counts(),
        }

    out = {
        "metric": "multichip_dryrun_bert_step_ms",
        "value": stages["zero2"]["step_ms"],
        "unit": "ms",
        "n_devices": n,
        "platform": jax.default_backend(),
        "smoke_config": jax.default_backend() != "tpu",
        "stages": stages,
    }
    if autotune is not None:
        out["autotune"] = autotune
    print(json.dumps(out))
    return 0


def _autotune_bert_step(cfg, mesh, loss_fn, np_batches, k_short, k_long,
                        reps, default_dt, on_tpu, B, S):
    """--autotune: measured search over the train step's honest knobs
    (remat, donation, the fused single-block flash backward), each
    variant timed under the SAME marginal-step harness as the headline
    number.  The already-measured default step time is reused for the
    default variant (identical harness, zero extra cost), so "tuned"
    can never beat "default" by harness mismatch.  Winners persist in
    the tuning cache; the platform/smoke_config fields on the output
    line keep a CPU capture from impersonating TPU tuning numbers."""
    import jax

    from paddle_tpu import distributed as dist
    from paddle_tpu import models, tune
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.fluid.optimizer import AdamWOptimizer

    variants = [
        ("default", {"remat": False, "donate": True, "fused_bwd": True,
                     "fused_ffn": False, "head_layout": "BSHD"}),
        ("remat", {"remat": True, "donate": True, "fused_bwd": True,
                   "fused_ffn": False, "head_layout": "BSHD"}),
        ("no_fused_flash_bwd",
         {"remat": False, "donate": True, "fused_bwd": False,
          "fused_ffn": False, "head_layout": "BSHD"}),
        # fused-epilogue FFN (matmul_bias_act, the MatmulBiasActFusePass
        # target) vs XLA's own fusion of the unfused chain
        ("fused_ffn", {"remat": False, "donate": True, "fused_bwd": True,
                       "fused_ffn": True, "head_layout": "BSHD"}),
        # the head-major layout that MATERIALIZES the [B,S,H,D]<->
        # [B,H,S,D] transposes — the negative control for the
        # transpose-free default (what TransposeFoldPass restores)
        ("bhsd_head_transposes",
         {"remat": False, "donate": True, "fused_bwd": True,
          "fused_ffn": False, "head_layout": "BHSD"}),
    ]

    _ENV_KNOBS = (
        ("PADDLE_TPU_FLASH_FUSED_BWD",
         lambda p: "1" if p.get("fused_bwd", True) else "0"),
        ("PADDLE_TPU_FUSED_FFN",
         lambda p: "1" if p.get("fused_ffn") else "0"),
        ("PADDLE_TPU_BERT_HEAD_LAYOUT",
         lambda p: p.get("head_layout", "BSHD")),
    )

    def build_and_time(params):
        if params == variants[0][1]:
            return default_dt          # measured by the headline harness
        prev = {k: os.environ.get(k) for k, _v in _ENV_KNOBS}
        for k, val in _ENV_KNOBS:
            os.environ[k] = val(params)
        try:
            with dygraph.guard():
                model = models.BertForPretraining(cfg)
                opt = AdamWOptimizer(learning_rate=1e-4, weight_decay=0.01)
                step = dist.ShardedTrainStep(
                    model, opt, loss_fn, mesh, zero_stage=0,
                    donate=params.get("donate", True),
                    remat=params.get("remat", False),
                    amp="bf16" if on_tpu else None)
                state = step.init()
                for i in range(2):
                    state, loss = step(state, np_batches[i % len(np_batches)])
                float(loss)
                placed = [step.place_batch(b) for b in np_batches]
                v_dt, _w, _s = _marginal_step_time(
                    step, state, placed, k_short, k_long, reps)
            return v_dt
        finally:
            for k, old in prev.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old

    workload = "bench.bert_step:B%d.S%d.L%d.h%d" % (
        B, S, cfg.num_hidden_layers, cfg.hidden_size)
    report = tune.search_step(build_and_time, variants, workload=workload,
                              mesh=mesh)
    print("bench autotune:\n%s" % report.format(), file=sys.stderr)
    winner = report.winner
    return {
        "cache_hit": report.cache_hit,
        "default_step_ms": round(default_dt * 1e3, 3),
        "tuned_step_ms": (round(winner.measured_s * 1e3, 3)
                          if winner and winner.measured_s else None),
        "winner": winner.to_dict() if winner else None,
        "counts": report.counts(),
        "platform": jax.default_backend(),
    }


def _bench_resnet(on_tpu, peak):
    """Milestone-5 metric (BASELINE.md): ResNet-50 train images/sec on one
    chip.  FLOP model: 4.09 GFLOP forward per 224x224 image (the standard
    published count for ResNet-50 v1.5), x3 for fwd+bwd."""
    import time

    import jax

    from paddle_tpu import distributed as dist
    from paddle_tpu import models
    from paddle_tpu.fluid import dygraph, layers
    from paddle_tpu.fluid.optimizer import MomentumOptimizer

    if on_tpu:
        B, HW, k_short, k_long, reps = (
            int(os.getenv("BENCH_RESNET_B", "128")), 224, 10, 30, 2)
        depth, flops_img = 50, 3 * 4.089e9
    else:
        B, HW, k_short, k_long, reps = 4, 32, 1, 3, 1
        depth, flops_img = 18, 3 * 0.3e9

    with dygraph.guard():
        model = models.ResNet(depth=depth, num_classes=1000)
        opt = MomentumOptimizer(learning_rate=0.1, momentum=0.9)
        mesh = dist.auto_mesh(1)

        def loss_fn(m, batch):
            logits = m(batch["image"])
            return layers.mean(layers.softmax_with_cross_entropy(
                logits, batch["label"]))

        step = dist.ShardedTrainStep(
            model, opt, loss_fn, mesh, zero_stage=0,
            amp="bf16" if on_tpu else None,
        )
        state = step.init()
        rng = np.random.RandomState(0)
        batches = [{
            "image": rng.randn(B, 3, HW, HW).astype(np.float32),
            "label": rng.randint(0, 1000, (B, 1)).astype(np.int32),
        } for _ in range(2)]
        for i in range(2):
            state, loss = step(state, batches[i % 2])
        float(loss)
        cost = step.cost_analysis(state, batches[0])
        batches = [step.place_batch(b) for b in batches]

        dt, _dt_worst, state = _marginal_step_time(
            step, state, batches, k_short, k_long, reps)
    imgs = B / dt
    mfu = imgs * flops_img / peak
    print("resnet%d bench: B=%d step %.2f ms, %.1f images/s, implied "
          "MFU %.1f%%" % (depth, B, dt * 1e3, imgs, mfu * 100),
          file=sys.stderr)
    out = {
        "resnet50_train_images_per_sec_per_chip": round(imgs, 2),
        "resnet50_implied_mfu": round(mfu, 4),
    }
    if cost and cost.get("flops"):
        from paddle_tpu.observability.xla_cost import record_mfu

        m = record_mfu("bench.resnet_step", cost["flops"], dt, peak=peak)
        if m is not None:
            out["resnet50_measured_mfu"] = round(m, 4)
    return out


if __name__ == "__main__":
    sys.exit(main())
