"""Benchmark: flagship BERT-base pretraining step, tokens/sec/chip.

North star (BASELINE.md): ERNIE/BERT-base pretrain tokens/sec/chip at
>=35% MFU.  The reference publishes no in-repo numbers (BASELINE.json
"published": {}), so vs_baseline reports measured-MFU / 0.35 — the ratio to
the target; 1.0 means the 35% MFU goal is met.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np


def main():
    import jax

    on_tpu = jax.default_backend() == "tpu"

    from paddle_tpu import distributed as dist
    from paddle_tpu import models
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.fluid.optimizer import AdamWOptimizer

    if on_tpu:
        cfg = models.BertConfig(  # BERT-base
            vocab_size=30528,  # pad to multiple of 64 for lane alignment
            hidden_size=768, num_hidden_layers=12, num_attention_heads=12,
            intermediate_size=3072, max_position_embeddings=512,
            hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
        )
        B, S, iters = 8, 512, 20
    else:  # CPU smoke path so the bench never hangs off-TPU
        cfg = models.BertConfig.tiny()
        B, S, iters = 4, 32, 3

    with dygraph.guard():
        model = models.BertForPretraining(cfg)
        opt = AdamWOptimizer(learning_rate=1e-4, weight_decay=0.01)
        mesh = dist.auto_mesh(1)

        def loss_fn(m, batch):
            logits, nsp_logits = m(
                batch["input_ids"], batch["token_type_ids"],
                batch["position_ids"],
            )
            return m.loss(
                logits, nsp_logits, batch["mlm_labels"],
                batch["mlm_weights"], batch["nsp_labels"],
            )

        step = dist.ShardedTrainStep(model, opt, loss_fn, mesh, zero_stage=0)
        state = step.init()
        n_params = sum(int(np.prod(v.shape)) for v in state["params"].values())

        rng = np.random.RandomState(0)
        batch = {
            "input_ids": rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32),
            "token_type_ids": np.zeros((B, S), np.int32),
            "position_ids": np.tile(np.arange(S, dtype=np.int32), (B, 1)),
            "mlm_labels": rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32),
            "mlm_weights": (rng.rand(B, S) < 0.15).astype(np.float32),
            "nsp_labels": rng.randint(0, 2, (B, 1)).astype(np.int32),
        }

        # warmup (compile)
        for _ in range(2):
            state, loss = step(state, batch)
        loss.block_until_ready()

        t0 = time.perf_counter()
        for _ in range(iters):
            state, loss = step(state, batch)
        loss.block_until_ready()
        dt = time.perf_counter() - t0

    tokens_per_sec = B * S * iters / dt
    # MFU: ~6 flops per param per token (fwd+bwd), v5e peak 197 TFLOP/s bf16
    flops_per_tok = 6.0 * n_params
    peak = 197e12 if on_tpu else 1e12
    mfu = tokens_per_sec * flops_per_tok / peak
    print(json.dumps({
        "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.35, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
