"""Benchmark: flagship BERT-base pretraining step, tokens/sec/chip.

North star (BASELINE.md): ERNIE/BERT-base pretrain tokens/sec/chip at
>=35% MFU.  The reference publishes no in-repo numbers (BASELINE.json
"published": {}), so vs_baseline reports measured-MFU / 0.35 — the ratio to
the target; 1.0 means the 35% MFU goal is met.

Self-validation (round-2, after VERDICT r1 flagged an impossible 179% MFU):
- timing fetches the loss *value* to host every step, so the wall clock can
  never be shorter than true device compute (defeats any async-dispatch or
  remote-platform distortion in ``block_until_ready``);
- the FLOP model counts only matmul params (embedding gather tables
  excluded; the word-embedding table counts once because it is tied to the
  MLM decoder matmul) plus the attention term 12*L*S*h per token;
- asserts implied MFU <= 100% before printing; per-step latency and the
  full accounting go to stderr.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import sys
import time

import numpy as np


def _flops_per_token(cfg, params):
    """Training FLOPs/token: 6 per matmul-param + exact attention term.

    Matmul params = everything except embedding gather tables
    (position/token-type) and the word embedding, which IS counted because
    BertForPretraining ties it to the MLM output projection (one matmul
    use).  LayerNorm scales/biases are counted too — they are a <0.1%
    overstatement, dwarfed by what padding/masking understates.
    Attention scores+context: 2*S*h MACs per token per layer forward
    (S*h for QK^T + S*h for AV) = 4*S*h FLOPs, 3x for fwd+bwd
    = 12*L*S*h per token (S = sequence length).
    """
    gather_only = 0
    matmul = 0
    for name, v in params.items():
        n = int(np.prod(v.shape))
        if "position" in name or "token_type" in name:
            gather_only += n
        else:
            matmul += n
    attn = 12.0 * cfg.num_hidden_layers * 1.0 * cfg.hidden_size
    return lambda seq_len: 6.0 * matmul + attn * seq_len, matmul, gather_only


def main():
    import jax

    on_tpu = jax.default_backend() == "tpu"

    from paddle_tpu import distributed as dist
    from paddle_tpu import models
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.fluid.optimizer import AdamWOptimizer

    if on_tpu:
        cfg = models.BertConfig(  # BERT-base
            vocab_size=30528,  # pad to multiple of 64 for lane alignment
            hidden_size=768, num_hidden_layers=12, num_attention_heads=12,
            intermediate_size=3072, max_position_embeddings=512,
            hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
        )
        # B=16 is the single-chip MXU sweet spot (B=8: 37.5% MFU, B=16:
        # 39.2%, B=32: 37.9% measured on v5e)
        B, S = 16, 512
        k_short, k_long, reps = 10, 30, 2
        # bf16 peak TFLOP/s for one v5e chip (public spec: 197 bf16)
        peak = 197e12
    else:  # CPU smoke path so the bench never hangs off-TPU
        cfg = models.BertConfig.tiny()
        B, S = 4, 32
        k_short, k_long, reps = 1, 3, 1
        peak = 1e12

    with dygraph.guard():
        model = models.BertForPretraining(cfg)
        opt = AdamWOptimizer(learning_rate=1e-4, weight_decay=0.01)
        mesh = dist.auto_mesh(1)

        def loss_fn(m, batch):
            logits, nsp_logits = m(
                batch["input_ids"], batch["token_type_ids"],
                batch["position_ids"],
            )
            return m.loss(
                logits, nsp_logits, batch["mlm_labels"],
                batch["mlm_weights"], batch["nsp_labels"],
            )

        step = dist.ShardedTrainStep(
            model, opt, loss_fn, mesh, zero_stage=0,
            amp="bf16" if on_tpu else None,
        )
        state = step.init()
        n_params = sum(int(np.prod(v.shape)) for v in state["params"].values())
        per_tok, matmul_params, gather_params = _flops_per_token(
            cfg, state["params"]
        )

        rng = np.random.RandomState(0)

        def make_batch():
            return {
                "input_ids": rng.randint(
                    0, cfg.vocab_size, (B, S)).astype(np.int32),
                "token_type_ids": np.zeros((B, S), np.int32),
                "position_ids": np.tile(
                    np.arange(S, dtype=np.int32), (B, 1)),
                "mlm_labels": rng.randint(
                    0, cfg.vocab_size, (B, S)).astype(np.int32),
                "mlm_weights": (rng.rand(B, S) < 0.15).astype(np.float32),
                "nsp_labels": rng.randint(0, 2, (B, 1)).astype(np.int32),
            }

        batches = [make_batch() for _ in range(4)]

        # warmup (compile + two real executes, value-fetched)
        for i in range(2):
            state, loss = step(state, batches[i % 4])
        float(loss)

        # Timing: segments of K chained steps, each ending with a host
        # fetch of the loss *value*.  The final loss depends on the whole
        # donated-state chain, so a segment cannot finish before the device
        # executed every step in it — each segment time is an honest lower
        # bound regardless of how the platform implements
        # block_until_ready (the axon remote tunnel's did not wait in
        # round 1, implying 179% MFU).  Steady-state step time is the
        # marginal cost between a long and a short segment, which cancels
        # the fixed per-segment dispatch/fetch RTT (~150 ms over the
        # tunnel) that a production input pipeline would overlap.
        def timed_segment(k, i0):
            t0 = time.perf_counter()
            nonlocal state
            loss = None
            for i in range(i0, i0 + k):
                state, loss = step(state, batches[i % 4])
            lv = float(loss)
            if not np.isfinite(lv):
                raise RuntimeError("bench loss went non-finite")
            return time.perf_counter() - t0

        shorts, longs = [], []
        i0 = 0
        for _ in range(reps):
            shorts.append(timed_segment(k_short, i0))
            i0 += k_short
            longs.append(timed_segment(k_long, i0))
            i0 += k_long
        dt = (min(longs) - min(shorts)) / (k_long - k_short)
        dt_worst = max(longs) / k_long  # includes all fixed overhead
        # plain raise, not assert: the guards must survive python -O
        if dt <= 0:
            raise RuntimeError(
                "non-positive marginal step time (%.1f ms): RTT noise "
                "swamped the measurement; segment times shorts=%s longs=%s"
                % (dt * 1e3, shorts, longs)
            )

    tokens_per_sec = B * S / dt
    flops_per_tok = per_tok(S)
    mfu = tokens_per_sec * flops_per_tok / peak
    print(
        "bench: marginal step %.2f ms over %dx(%d,%d)-step segments "
        "(conservative incl. dispatch RTT: %.2f ms), %.0f tokens/s, "
        "params=%.1fM (matmul %.1fM, gather-only %.1fM), "
        "%.0f MFLOP/token, implied MFU %.1f%%"
        % (dt * 1e3, reps, k_short, k_long, dt_worst * 1e3,
           tokens_per_sec, n_params / 1e6, matmul_params / 1e6,
           gather_params / 1e6, flops_per_tok / 1e6, mfu * 100),
        file=sys.stderr,
    )
    if mfu > 1.0:
        raise RuntimeError(
            "implied MFU %.1f%% exceeds physical peak — measurement or FLOP "
            "accounting is wrong; refusing to report" % (mfu * 100)
        )
    print(json.dumps({
        "metric": "bert_base_pretrain_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.35, 4),
    }))


if __name__ == "__main__":
    sys.exit(main())
