"""SIGKILL-mid-stream worker for the delta-checkpoint loss-bound drill.

Driven by test_perf_gate.py: trains a streaming loop with per-window
delta checkpoints, then SIGKILLs ITSELF (no cleanup, no atexit — the
preemption case) after a given number of windows.  A second invocation
with ``restore`` rebuilds the table from the committed chain and
prints the restored ``events_done`` so the driver can assert the loss
bound: at most ONE window of events between the last commit and the
kill is gone.

Deterministic data: windows are generated from a fixed seed, so the
restored table must be BIT-identical to an uninterrupted run truncated
at the restored event count — which the driver also verifies via the
printed table digest.
"""

import hashlib
import json
import os
import signal
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

V, D, T, B = 2000, 8, 4, 8
STEPS_PER_WINDOW = 4


def _build():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[-1, T], dtype="int64",
                          append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], append_batch_size=False)
        emb = layers.embedding(ids, size=[V, D], is_distributed=True,
                               param_attr="cw.emb")
        pred = layers.fc(layers.reduce_mean(emb, dim=1), size=1,
                         param_attr="cw.fc.w", bias_attr="cw.fc.b")
        loss = layers.reduce_mean(layers.square(pred - y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    table, _slot = main._host_embeddings["cw.emb"]
    return main, startup, loss, table


def _window_feeds(window_no):
    rng = np.random.RandomState(1000 + window_no)
    return [{"ids": rng.randint(0, V, (B, T)).astype(np.int64),
             "y": rng.randn(B, 1).astype(np.float32)}
            for _ in range(STEPS_PER_WINDOW)]


def _digest(table):
    return hashlib.sha256(
        np.ascontiguousarray(table._rows).tobytes()).hexdigest()[:16]


def main():
    import paddle_tpu.fluid as fluid
    from paddle_tpu import streaming

    mode = sys.argv[1]                  # train | restore
    root = sys.argv[2]
    windows = int(sys.argv[3])
    kill_after = int(sys.argv[4]) if len(sys.argv) > 4 else -1

    main_prog, startup, loss, table = _build()
    ck = streaming.DeltaCheckpointer(root, [table], full_every=3)

    if mode == "restore":
        meta = ck.restore()
        print(json.dumps({"events_done": meta["events_done"],
                          "window": meta["window"],
                          "digest": _digest(table)}))
        return 0

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    from paddle_tpu.fluid.host_embedding import HostEmbeddingSession

    with fluid.scope_guard(scope):
        exe.run(startup)
        sess = HostEmbeddingSession(exe, main_prog, loss=loss)
        events = 0
        for w in range(windows):
            for f in _window_feeds(w):
                sess.run(f, fetch_list=[loss], lr=0.1)
                events += B
            ck.save(step=(w + 1) * STEPS_PER_WINDOW, events_done=events,
                    window=w + 1)
            if kill_after >= 0 and w + 1 == kill_after:
                # half a window of post-commit work, then die mid-stream
                for f in _window_feeds(w + 1)[: STEPS_PER_WINDOW // 2]:
                    sess.run(f, fetch_list=[loss], lr=0.1)
                sys.stdout.flush()
                os.kill(os.getpid(), signal.SIGKILL)
    print(json.dumps({"events_done": events, "digest": _digest(table)}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
