"""Oracles for the search-ranking/tree op tail (reference unittest
patterns: test_lod_reset_op.py, test_filter_by_instag_op.py,
test_sample_logits_op.py, test_rank_attention_op.py,
test_tree_conv_op.py, test_var_conv_2d.py, test_pyramid_hash_op.py)."""

import numpy as np
import pytest

from op_test import check_grad, run_single_op

rng = np.random.RandomState(5)


def test_lod_reset_identity_data_new_lens():
    x = rng.randn(6, 1).astype(np.float32)
    # reference Example 2: offsets via Y
    outs, _ = run_single_op(
        "lod_reset", {"X": x, "Y": np.array([0, 2, 6], np.int32)}, {},
        ["Out", "OutLens"])
    np.testing.assert_allclose(outs["Out"], x)
    np.testing.assert_array_equal(outs["OutLens"], [2, 4])
    # reference Example 1: offsets via attr
    outs, _ = run_single_op(
        "lod_reset", {"X": x}, {"target_lod": [0, 4, 6]},
        ["Out", "OutLens"])
    np.testing.assert_array_equal(outs["OutLens"], [4, 2])
    check_grad("lod_reset", {"X": x, "Y": np.array([0, 3, 6], np.int32)},
               {}, ["Out", "OutLens"], ["X"], rtol=1e-2, atol=1e-3)


def test_filter_by_instag_masks_dropped_sequences():
    # 4 sequences of 1/2/3/4 rows; tags 1,2,1,2; filter tag = 2
    x = np.arange(10 * 3, dtype=np.float32).reshape(10, 3)
    lens = np.array([1, 2, 3, 4], np.int64)
    tags = np.array([[1, -1], [2, -1], [1, -1], [2, 3]], np.int64)
    outs, _ = run_single_op(
        "filter_by_instag",
        {"Ins": x, "SeqLens": lens, "InsTag": tags,
         "FilterTag": np.array([2], np.int64)},
        {"out_val_if_empty": 0}, ["Out", "LossWeight", "IndexMap"])
    np.testing.assert_array_equal(outs["IndexMap"], [0, 1, 0, 1])
    np.testing.assert_allclose(outs["LossWeight"].reshape(-1), [0, 1, 0, 1])
    out = outs["Out"]
    np.testing.assert_allclose(out[0], 0)              # seq0 dropped
    np.testing.assert_allclose(out[1:3], x[1:3])       # seq1 kept
    np.testing.assert_allclose(out[3:6], 0)            # seq2 dropped
    np.testing.assert_allclose(out[6:10], x[6:10])     # seq3 kept
    # grad flows only through kept rows
    _, grads = run_single_op(
        "filter_by_instag",
        {"Ins": x, "SeqLens": lens, "InsTag": tags,
         "FilterTag": np.array([2], np.int64)},
        {}, ["Out", "LossWeight", "IndexMap"], grad_of=[("Ins", 0)])
    g = grads["ins_0@GRAD"]
    assert np.all(g[1:3] == 1) and np.all(g[6:10] == 1)
    assert np.all(g[0] == 0) and np.all(g[3:6] == 0)


def test_sample_logits_structure_and_correction():
    n, k, nt, s = 4, 50, 1, 8
    logits = rng.randn(n, k).astype(np.float32)
    labels = rng.randint(0, k, (n, nt)).astype(np.int64)
    outs, _ = run_single_op(
        "sample_logits", {"Logits": logits, "Labels": labels},
        {"num_samples": s, "remove_accidental_hits": True},
        ["Samples", "Probabilities", "SampledLogits", "SampledLabels"])
    samples = outs["Samples"]
    assert samples.shape == (n, nt + s)
    np.testing.assert_array_equal(samples[:, :nt], labels)   # true first
    # negatives are shared across the batch and DISTINCT (uniq contract)
    negs = samples[0, nt:]
    assert len(set(negs.tolist())) == s
    np.testing.assert_array_equal(samples[:, nt:],
                                  np.tile(negs, (n, 1)))
    # probability is the log-uniform q(k)
    q = (np.log(samples + 2.0) - np.log(samples + 1.0)) / np.log(k + 1.0)
    np.testing.assert_allclose(outs["Probabilities"], q, rtol=1e-5)
    # sampled logits = logits[sample] - log q, except accidental hits
    sl = outs["SampledLogits"]
    for i in range(n):
        for j in range(nt + s):
            c = samples[i, j]
            want = logits[i, c] - np.log(q[i, j])
            if j >= nt and c in labels[i]:
                assert sl[i, j] < -1e19                  # knocked out
            else:
                np.testing.assert_allclose(sl[i, j], want, rtol=2e-5,
                                           atol=1e-5)
    np.testing.assert_array_equal(outs["SampledLabels"],
                                  np.tile(np.arange(nt), (n, 1)))


def test_sample_logits_customized_samples():
    n, k, nt, s = 2, 10, 1, 3
    logits = rng.randn(n, k).astype(np.float32)
    labels = rng.randint(0, k, (n, nt)).astype(np.int64)
    cs = rng.randint(0, k, (n, nt + s)).astype(np.int64)
    cs[:, :nt] = labels
    cp = np.full((n, nt + s), 0.1, np.float32)
    outs, _ = run_single_op(
        "sample_logits",
        {"Logits": logits, "Labels": labels, "CustomizedSamples": cs,
         "CustomizedProbabilities": cp},
        {"num_samples": s, "use_customized_samples": True,
         "remove_accidental_hits": False},
        ["Samples", "Probabilities", "SampledLogits", "SampledLabels"])
    np.testing.assert_array_equal(outs["Samples"], cs)
    want = np.take_along_axis(logits, cs, 1) - np.log(0.1)
    np.testing.assert_allclose(outs["SampledLogits"], want, rtol=1e-5)


def _np_rank_attention(x, ro, param, max_rank):
    """Ported oracle (reference test_rank_attention_op.py
    np_rank_attention)."""
    n, d = x.shape
    p = param.shape[1]
    out = np.zeros((n, p), np.float64)
    for i in range(n):
        lower = ro[i, 0] - 1
        if lower < 0:
            continue
        for kk in range(max_rank):
            faster = ro[i, 2 * kk + 1] - 1
            if faster < 0:
                continue
            index = ro[i, 2 * kk + 2]
            blk = param[(lower * max_rank + faster) * d:
                        (lower * max_rank + faster + 1) * d]
            out[i] += x[index] @ blk
    return out


@pytest.mark.slow
def test_rank_attention_matches_oracle():
    max_rank, d, p = 3, 4, 5
    # 2 pvs: ranks [2, 1] and [1, 3, 2] -> 5 instances
    ro = np.full((5, 1 + 2 * max_rank), -1, np.int32)
    pv0, pv1 = [0, 1], [2, 3, 4]
    for group in (pv0, pv1):
        ranks = list(range(1, len(group) + 1))
        for a, ins_i in enumerate(group):
            ro[ins_i, 0] = ranks[a]
            for kk, peer in enumerate(group):
                ro[ins_i, 2 * kk + 1] = ranks[kk]
                ro[ins_i, 2 * kk + 2] = peer
    x = rng.randn(5, d).astype(np.float32)
    param = rng.randn(max_rank * max_rank * d, p).astype(np.float32)
    outs, _ = run_single_op(
        "rank_attention", {"X": x, "RankOffset": ro, "RankParam": param},
        {"MaxRank": max_rank}, ["Out", "InputHelp", "InsRank"])
    want = _np_rank_attention(x.astype(np.float64), ro,
                              param.astype(np.float64), max_rank)
    np.testing.assert_allclose(outs["Out"], want, rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(outs["InsRank"].reshape(-1),
                                  ro[:, 0].astype(np.float32))
    # RankParam is the trainable input (reference grad op)
    check_grad("rank_attention",
               {"X": x, "RankOffset": ro, "RankParam": param},
               {"MaxRank": max_rank}, ["Out", "InputHelp", "InsRank"],
               ["RankParam"], rtol=2e-2, atol=1e-2)


def _np_tree_conv(nodes, edges, w, max_depth):
    """Ported oracle (reference test_tree_conv_op.py naive patches)."""
    b, n, f = nodes.shape
    _, _, o, c = w.shape
    wt = np.transpose(w, (1, 0, 2, 3))                 # [3, F, O, C]
    out = np.zeros((b, n, o, c))
    for bi in range(b):
        og = [[] for _ in range(n + 2)]
        for e0, e1 in edges[bi]:
            if e0 > 0 and e1 > 0:
                og[int(e0)].append(int(e1))

        def patch_of(u):
            collected = [(u, 1, 1, 0)]

            def rec(node, depth):
                if depth > max_depth:
                    return
                l = len(og[node])
                for idx, ch in enumerate(og[node], 1):
                    if depth + 1 < max_depth:
                        collected.append((ch, idx, l, depth + 1))
                        rec(ch, depth + 1)
            rec(u, 0)
            return collected

        for u in range(1, n + 1):
            res = np.zeros((o, c))
            for (node, idx, l, depth) in patch_of(u):
                eta_t = float(max_depth - depth) / max_depth
                eta_l = (1 - eta_t) * (0.5 if l == 1
                                       else (idx - 1.0) / (l - 1.0))
                eta_r = (1 - eta_t) * (1 - eta_l)
                eta = np.array([eta_l, eta_r, eta_t]).reshape(3, 1)
                wmix = np.tensordot(eta, wt, axes=([0], [0]))[0]
                res += np.tensordot(nodes[bi, node - 1], wmix, axes=1)
            out[bi, u - 1] = res
    return out


@pytest.mark.slow
def test_tree_conv_matches_oracle():
    n, f, o, c, depth, b = 9, 3, 2, 2, 2, 2
    adj = np.array([1, 2, 1, 3, 1, 4, 2, 5, 2, 6, 4, 7, 7, 8, 7, 9],
                   np.int32).reshape(1, 8, 2)
    adj = np.tile(adj, (b, 1, 1))
    nodes = rng.randn(b, n, f).astype(np.float32)
    w = rng.randn(f, 3, o, c).astype(np.float32)
    outs, _ = run_single_op(
        "tree_conv", {"NodesVector": nodes, "EdgeSet": adj, "Filter": w},
        {"max_depth": depth}, ["Out"])
    want = _np_tree_conv(nodes.astype(np.float64), adj,
                         w.astype(np.float64), depth)
    np.testing.assert_allclose(outs["Out"], want, rtol=1e-4, atol=1e-4)
    # deeper receptive field
    outs3, _ = run_single_op(
        "tree_conv", {"NodesVector": nodes, "EdgeSet": adj, "Filter": w},
        {"max_depth": 3}, ["Out"])
    want3 = _np_tree_conv(nodes.astype(np.float64), adj,
                          w.astype(np.float64), 3)
    np.testing.assert_allclose(outs3["Out"], want3, rtol=1e-4, atol=1e-4)
    check_grad("tree_conv",
               {"NodesVector": nodes, "EdgeSet": adj, "Filter": w},
               {"max_depth": depth}, ["Out"], ["NodesVector", "Filter"],
               rtol=2e-2, atol=1e-2)


def _np_var_conv_2d(x, rows, cols, w, kh, kw, sh, sw):
    """Dense-layout port of the reference Im2Col + gemm oracle."""
    b, c, hm, wm = x.shape
    o = w.shape[0]
    ho = (hm - 1) // sh + 1
    wo = (wm - 1) // sw + 1
    out = np.zeros((b, o, ho, wo))
    wf = w.reshape(o, c, kh, kw)
    for bi in range(b):
        h, ww = int(rows[bi]), int(cols[bi])
        if h == 0 or ww == 0:
            continue
        toy, tox = (h - 1) // sh + 1, (ww - 1) // sw + 1
        for oy in range(toy):
            for ox in range(tox):
                acc = np.zeros(o)
                for z in range(c):
                    for ky in range(kh):
                        for kx in range(kw):
                            iy = oy * sh + ky - kh // 2
                            ix = ox * sw + kx - kw // 2
                            if 0 <= iy < h and 0 <= ix < ww:
                                acc += wf[:, z, ky, kx] * x[bi, z, iy, ix]
                out[bi, :, oy, ox] = acc
    return out


@pytest.mark.slow
def test_var_conv_2d_matches_oracle():
    b, c, hm, wm, o = 2, 3, 5, 6, 4
    kh, kw, sh, sw = 2, 3, 1, 2
    rows = np.array([4, 5], np.int64)
    cols = np.array([6, 3], np.int64)
    x = rng.randn(b, c, hm, wm).astype(np.float32)
    w = rng.randn(o, c * kh * kw).astype(np.float32)
    outs, _ = run_single_op(
        "var_conv_2d",
        {"X": x, "RowLens": rows, "ColLens": cols, "W": w},
        {"KernelH": kh, "KernelW": kw, "StrideH": sh, "StrideW": sw},
        ["Out"])
    want = _np_var_conv_2d(x.astype(np.float64), rows, cols,
                           w.astype(np.float64), kh, kw, sh, sw)
    np.testing.assert_allclose(outs["Out"], want, rtol=1e-4, atol=1e-4)
    check_grad("var_conv_2d",
               {"X": x, "RowLens": rows, "ColLens": cols, "W": w},
               {"KernelH": kh, "KernelW": kw, "StrideH": sh,
                "StrideW": sw},
               ["Out"], ["X", "W"], rtol=2e-2, atol=1e-2)


@pytest.mark.slow
def test_pyramid_hash_shapes_determinism_and_masking():
    b, t, space, rand_len, num_emb = 2, 6, 256, 4, 8
    toks = rng.randint(0, 1000, (b, t)).astype(np.int32)
    lens = np.array([6, 3], np.int64)
    w = rng.randn(space, 1).astype(np.float32)
    attrs = {"num_emb": num_emb, "rand_len": rand_len,
             "pyramid_layer": 3, "space_len": space}
    outs, _ = run_single_op(
        "pyramid_hash",
        {"X": toks, "SeqLens": lens, "W": w}, attrs, ["Out"])
    out = outs["Out"]
    assert out.shape == (b, t, num_emb)
    # deterministic: same inputs, same embedding
    outs2, _ = run_single_op(
        "pyramid_hash", {"X": toks, "SeqLens": lens, "W": w}, attrs,
        ["Out"])
    np.testing.assert_allclose(out, outs2["Out"])
    # positions whose every gram crosses the sequence end embed to zero
    np.testing.assert_allclose(out[1, 2:], 0.0)        # len 3: t>=2 dead
    assert np.abs(out[1, 0]).sum() > 0
    # different token at a position changes (only) grams covering it
    toks2 = toks.copy()
    toks2[0, 5] = toks[0, 5] + 7
    outs3, _ = run_single_op(
        "pyramid_hash", {"X": toks2, "SeqLens": lens, "W": w}, attrs,
        ["Out"])
    assert np.abs(outs3["Out"][0, 5] - out[0, 5]).sum() > 0 or \
        np.abs(outs3["Out"][0, 4] - out[0, 4]).sum() > 0
    np.testing.assert_allclose(outs3["Out"][0, :3], out[0, :3])
    # the table is trainable
    check_grad("pyramid_hash",
               {"X": toks[:1, :4], "SeqLens": np.array([4], np.int64),
                "W": w[:64]},
               {"num_emb": 4, "rand_len": 2, "pyramid_layer": 2,
                "space_len": 64},
               ["Out"], ["W"], rtol=5e-2, atol=1e-2)
