"""Op-level golden tests vs numpy oracles + finite-difference grad checks.

Mirrors the reference's per-op test files (tests/unittests/test_*_op.py):
outputs pinned by numpy, analytic grads (auto-VJP path) pinned by central
finite differences.
"""

import numpy as np
import pytest

from op_test import check_grad, check_output


def _rand(*shape, seed=0):
    return np.random.RandomState(seed).uniform(-1, 1, shape).astype(np.float32)


class TestElementwise:
    def test_add_same_shape(self):
        x, y = _rand(3, 4), _rand(3, 4, seed=1)
        check_output("elementwise_add", {"X": x, "Y": y}, {}, {"Out": x + y})

    def test_add_broadcast_axis(self):
        x, y = _rand(2, 3, 4), _rand(3, seed=1)
        check_output(
            "elementwise_add", {"X": x, "Y": y}, {"axis": 1},
            {"Out": x + y.reshape(1, 3, 1)},
        )

    def test_sub_grad(self):
        x, y = _rand(3, 4), _rand(3, 4, seed=1)
        check_grad("elementwise_sub", {"X": x, "Y": y}, {}, ["Out"], ["X", "Y"])

    def test_mul_grad(self):
        x, y = _rand(3, 4), _rand(3, 4, seed=1)
        check_grad("elementwise_mul", {"X": x, "Y": y}, {}, ["Out"], ["X", "Y"])

    def test_div(self):
        x = _rand(3, 4)
        y = _rand(3, 4, seed=1) + 2.0
        check_output("elementwise_div", {"X": x, "Y": y}, {}, {"Out": x / y})


class TestActivations:
    def test_relu(self):
        x = _rand(4, 5)
        check_output("relu", {"X": x}, {}, {"Out": np.maximum(x, 0)})

    def test_sigmoid_grad(self):
        x = _rand(3, 4)
        check_grad("sigmoid", {"X": x}, {}, ["Out"], ["X"])

    def test_tanh(self):
        x = _rand(3, 4)
        check_output("tanh", {"X": x}, {}, {"Out": np.tanh(x)})
        check_grad("tanh", {"X": x}, {}, ["Out"], ["X"])

    def test_gelu(self):
        from scipy.stats import norm

        x = _rand(3, 4)
        check_output(
            "gelu", {"X": x}, {}, {"Out": x * norm.cdf(x)}, rtol=1e-4, atol=1e-5
        )

    def test_square_grad(self):
        x = _rand(3, 4)
        check_grad("square", {"X": x}, {}, ["Out"], ["X"])


class TestMatmul:
    def test_matmul(self):
        x, y = _rand(3, 4), _rand(4, 5, seed=1)
        check_output("matmul", {"X": x, "Y": y}, {}, {"Out": x @ y})

    def test_matmul_transpose(self):
        x, y = _rand(4, 3), _rand(5, 4, seed=1)
        check_output(
            "matmul", {"X": x, "Y": y},
            {"transpose_X": True, "transpose_Y": True},
            {"Out": x.T @ y.T},
        )

    def test_matmul_batched(self):
        x, y = _rand(2, 3, 4), _rand(2, 4, 5, seed=1)
        check_output("matmul", {"X": x, "Y": y}, {}, {"Out": x @ y})

    def test_matmul_grad(self):
        x, y = _rand(3, 4), _rand(4, 5, seed=1)
        check_grad("matmul", {"X": x, "Y": y}, {}, ["Out"], ["X", "Y"])

    def test_mul_flatten(self):
        x, y = _rand(2, 3, 4), _rand(12, 5, seed=1)
        check_output(
            "mul", {"X": x, "Y": y}, {"x_num_col_dims": 1, "y_num_col_dims": 1},
            {"Out": x.reshape(2, 12) @ y},
        )


class TestConvPool:
    def test_conv2d(self):
        import scipy.signal

        x = _rand(1, 1, 5, 5)
        w = _rand(1, 1, 3, 3, seed=1)
        ref = scipy.signal.correlate2d(x[0, 0], w[0, 0], mode="valid")
        check_output(
            "conv2d", {"Input": x, "Filter": w},
            {"strides": [1, 1], "paddings": [0, 0]},
            {"Output": ref[None, None]}, rtol=1e-4, atol=1e-5,
        )

    def test_conv2d_grad(self):
        x = _rand(2, 2, 4, 4)
        w = _rand(3, 2, 3, 3, seed=1)
        check_grad(
            "conv2d", {"Input": x, "Filter": w},
            {"strides": [1, 1], "paddings": [1, 1]},
            ["Output"], ["Input", "Filter"], rtol=1e-2, atol=1e-3,
        )

    def test_pool2d_max(self):
        x = _rand(1, 1, 4, 4)
        ref = x.reshape(1, 1, 2, 2, 2, 2).max(axis=(3, 5))
        check_output(
            "pool2d", {"X": x},
            {"pooling_type": "max", "ksize": [2, 2], "strides": [2, 2]},
            {"Out": ref},
        )

    def test_pool2d_avg(self):
        x = _rand(1, 1, 4, 4)
        ref = x.reshape(1, 1, 2, 2, 2, 2).mean(axis=(3, 5))
        check_output(
            "pool2d", {"X": x},
            {"pooling_type": "avg", "ksize": [2, 2], "strides": [2, 2]},
            {"Out": ref},
        )


class TestNorms:
    def test_layer_norm(self):
        x = _rand(4, 10)
        mean = x.mean(axis=1, keepdims=True)
        var = x.var(axis=1, keepdims=True)
        ref = (x - mean) / np.sqrt(var + 1e-5)
        check_output(
            "layer_norm", {"X": x}, {"begin_norm_axis": 1, "epsilon": 1e-5},
            {"Y": ref}, rtol=1e-4, atol=1e-5,
        )

    def test_layer_norm_grad(self):
        x = _rand(3, 6)
        s = _rand(6, seed=1)
        b = _rand(6, seed=2)
        check_grad(
            "layer_norm", {"X": x, "Scale": s, "Bias": b},
            {"begin_norm_axis": 1}, ["Y"], ["X", "Scale", "Bias"],
            rtol=1e-2, atol=1e-3,
        )

    def test_batch_norm_train(self):
        x = _rand(4, 3, 2, 2)
        scale = np.ones(3, np.float32)
        bias = np.zeros(3, np.float32)
        mean = np.zeros(3, np.float32)
        var = np.ones(3, np.float32)
        mu = x.mean(axis=(0, 2, 3))
        v = x.var(axis=(0, 2, 3))
        ref = (x - mu.reshape(1, 3, 1, 1)) / np.sqrt(v.reshape(1, 3, 1, 1) + 1e-5)
        check_output(
            "batch_norm",
            {"X": x, "Scale": scale, "Bias": bias, "Mean": mean, "Variance": var},
            {"momentum": 0.9, "epsilon": 1e-5},
            {"Y": ref}, rtol=1e-4, atol=1e-4,
        )


class TestSoftmaxXent:
    def test_softmax(self):
        x = _rand(3, 5)
        e = np.exp(x - x.max(axis=-1, keepdims=True))
        check_output("softmax", {"X": x}, {}, {"Out": e / e.sum(-1, keepdims=True)})

    def test_softmax_with_cross_entropy(self):
        logits = _rand(4, 6)
        label = np.array([[0], [2], [5], [1]], dtype=np.int64)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        loss = -np.log(sm[np.arange(4), label[:, 0]])[:, None]
        outs, _ = None, None
        from op_test import run_single_op

        outs, _ = run_single_op(
            "softmax_with_cross_entropy",
            {"Logits": logits, "Label": label},
            {}, ["Softmax", "Loss"],
        )
        np.testing.assert_allclose(outs["Softmax"], sm, rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(outs["Loss"], loss, rtol=1e-5, atol=1e-6)

    def test_xent_grad_is_softmax_minus_onehot(self):
        logits = _rand(3, 4)
        label = np.array([[1], [0], [3]], dtype=np.int64)
        from op_test import run_single_op

        _, grads = run_single_op(
            "softmax_with_cross_entropy",
            {"Logits": logits, "Label": label},
            {}, ["Loss", "Softmax"],  # loss first => sum(Loss) differentiated
            grad_of=[("Logits", 0)],
        )
        e = np.exp(logits - logits.max(-1, keepdims=True))
        sm = e / e.sum(-1, keepdims=True)
        onehot = np.eye(4, dtype=np.float32)[label[:, 0]]
        np.testing.assert_allclose(
            grads["logits_0@GRAD"], sm - onehot, rtol=1e-4, atol=1e-5
        )


class TestReduce:
    def test_reduce_sum_dims(self):
        x = _rand(2, 3, 4)
        check_output(
            "reduce_sum", {"X": x}, {"dim": [1]}, {"Out": x.sum(axis=1)}
        )

    def test_reduce_mean_all(self):
        x = _rand(2, 3)
        check_output(
            "reduce_mean", {"X": x}, {"reduce_all": True},
            {"Out": np.array(x.mean(), dtype=np.float32)},
        )

    def test_reduce_max_grad(self):
        x = np.array([[1.0, 5.0], [7.0, 2.0]], dtype=np.float32)
        check_grad("reduce_max", {"X": x}, {"dim": [1]}, ["Out"], ["X"])


class TestManip:
    def test_reshape(self):
        x = _rand(2, 6)
        check_output("reshape2", {"X": x}, {"shape": [3, 4]}, {"Out": x.reshape(3, 4)})

    def test_reshape_zero_and_minus1(self):
        x = _rand(2, 3, 4)
        check_output(
            "reshape2", {"X": x}, {"shape": [0, -1]}, {"Out": x.reshape(2, 12)}
        )

    def test_transpose(self):
        x = _rand(2, 3, 4)
        check_output(
            "transpose2", {"X": x}, {"axis": [2, 0, 1]},
            {"Out": x.transpose(2, 0, 1)},
        )

    def test_concat_grad(self):
        a, b = _rand(2, 3), _rand(2, 5, seed=1)
        check_grad("concat", {"X": [a, b]}, {"axis": 1}, ["Out"], ["X"])

    def test_slice(self):
        x = _rand(4, 5)
        check_output(
            "slice", {"Input": x},
            {"axes": [0, 1], "starts": [1, 0], "ends": [3, 2]},
            {"Out": x[1:3, 0:2]},
        )

    def test_stack(self):
        a, b = _rand(2, 3), _rand(2, 3, seed=1)
        from op_test import run_single_op

        outs, _ = run_single_op("stack", {"X": [a, b]}, {"axis": 0}, ["Y"])
        np.testing.assert_allclose(outs["Y"], np.stack([a, b]))


class TestEmbedding:
    def test_lookup(self):
        w = _rand(10, 4)
        ids = np.array([[1], [3], [7]], dtype=np.int64)
        check_output(
            "lookup_table", {"W": w, "Ids": ids}, {"padding_idx": -1},
            {"Out": w[ids[:, 0]]},
        )

    def test_lookup_grad(self):
        w = _rand(6, 3)
        ids = np.array([[0], [2], [2]], dtype=np.int64)
        from op_test import run_single_op

        _, grads = run_single_op(
            "lookup_table", {"W": w, "Ids": ids}, {"padding_idx": -1},
            ["Out"], grad_of=[("W", 0)],
        )
        expected = np.zeros_like(w)
        for i in ids[:, 0]:
            expected[i] += 1.0
        np.testing.assert_allclose(grads["w_0@GRAD"], expected)


class TestDropout:
    def test_dropout_test_mode(self):
        x = _rand(4, 5)
        check_output(
            "dropout", {"X": x},
            {"dropout_prob": 0.3, "is_test": True,
             "dropout_implementation": "upscale_in_train"},
            {"Out": x},
        )

    def test_dropout_train_mask_consistency(self):
        from op_test import run_single_op

        x = np.ones((100, 100), dtype=np.float32)
        outs, grads = run_single_op(
            "dropout", {"X": x},
            {"dropout_prob": 0.5, "dropout_implementation": "upscale_in_train"},
            ["Out", "Mask"], grad_of=[("X", 0)],
        )
        mask = outs["Mask"].astype(np.float32)
        # forward uses the mask
        np.testing.assert_allclose(outs["Out"], x * mask / 0.5, rtol=1e-5)
        # grad reuses the SAME mask (custom grad op, not fresh rng)
        np.testing.assert_allclose(grads["x_0@GRAD"], mask / 0.5, rtol=1e-5)
        assert 0.3 < mask.mean() < 0.7


class TestOptimizerOps:
    def test_sgd(self):
        from op_test import run_single_op

        p, g = _rand(4), _rand(4, seed=1)
        lr = np.array([0.1], dtype=np.float32)
        outs, _ = run_single_op(
            "sgd", {"Param": p, "Grad": g, "LearningRate": lr}, {}, ["ParamOut"]
        )
        np.testing.assert_allclose(outs["ParamOut"], p - 0.1 * g, rtol=1e-6)

    def test_adam_step(self):
        from op_test import run_single_op

        p, g = _rand(4), _rand(4, seed=1)
        lr = np.array([0.01], dtype=np.float32)
        m1 = np.zeros(4, np.float32)
        m2 = np.zeros(4, np.float32)
        b1p = np.array([0.9], np.float32)
        b2p = np.array([0.999], np.float32)
        outs, _ = run_single_op(
            "adam",
            {"Param": p, "Grad": g, "LearningRate": lr, "Moment1": m1,
             "Moment2": m2, "Beta1Pow": b1p, "Beta2Pow": b2p},
            {"beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8},
            ["ParamOut", "Moment1Out", "Moment2Out"],
        )
        m1_ref = 0.1 * g
        m2_ref = 0.001 * g * g
        lr_t = 0.01 * np.sqrt(1 - 0.999) / (1 - 0.9)
        ref = p - lr_t * m1_ref / (np.sqrt(m2_ref) + 1e-8)
        np.testing.assert_allclose(outs["ParamOut"], ref, rtol=1e-5, atol=1e-6)


class TestConvTranspose:
    def test_conv2d_transpose_output_shape_and_value(self):
        import torch
        import torch.nn.functional as F

        x = _rand(2, 3, 4, 4)
        w = _rand(3, 5, 3, 3, seed=1)  # IOHW: [Cin, Cout, kh, kw]
        ref = F.conv_transpose2d(
            torch.from_numpy(x), torch.from_numpy(w), stride=2, padding=1
        ).numpy()
        check_output(
            "conv2d_transpose", {"Input": x, "Filter": w},
            {"strides": [2, 2], "paddings": [1, 1]},
            {"Output": ref}, rtol=1e-4, atol=1e-4,
        )

    def test_conv2d_transpose_grad(self):
        x = _rand(1, 2, 3, 3)
        w = _rand(2, 2, 2, 2, seed=1)
        check_grad(
            "conv2d_transpose", {"Input": x, "Filter": w},
            {"strides": [1, 1], "paddings": [0, 0]},
            ["Output"], ["Input", "Filter"], rtol=1e-2, atol=1e-3,
        )


class TestEmbeddingPadding:
    def test_negative_padding_idx_resolved_by_layer(self):
        import paddle_tpu.fluid as fluid
        from paddle_tpu.fluid import layers

        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            ids = layers.data("ids", shape=[1], dtype="int64")
            emb = layers.embedding(ids, size=[10, 4], padding_idx=-1)
        exe = fluid.Executor(fluid.CPUPlace())
        exe.run(startup)
        out, = exe.run(
            main, feed={"ids": np.array([[9], [1]], dtype=np.int64)},
            fetch_list=[emb],
        )
        assert np.all(out[0] == 0.0)  # row 9 == vocab-1 is the padding row
        assert np.any(out[1] != 0.0)


class TestDataFormatNHWC:
    """NHWC paths added for the TPU-fast ResNet trunk (conv2d/pool2d/
    batch_norm data_format attr) must agree with the NCHW reference."""

    def test_conv2d_nhwc_matches_nchw(self):
        from op_test import run_single_op

        x = _rand(2, 3, 6, 6)
        w = _rand(4, 3, 3, 3, seed=1)
        ref, _ = run_single_op(
            "conv2d", {"Input": x, "Filter": w},
            {"strides": [2, 2], "paddings": [1, 1]}, ["Output"])
        got, _ = run_single_op(
            "conv2d", {"Input": x.transpose(0, 2, 3, 1), "Filter": w},
            {"strides": [2, 2], "paddings": [1, 1], "data_format": "NHWC"},
            ["Output"])
        np.testing.assert_allclose(
            got["Output"].transpose(0, 3, 1, 2), ref["Output"],
            rtol=1e-4, atol=1e-5)

    def test_pool2d_nhwc_matches_nchw(self):
        from op_test import run_single_op

        x = _rand(2, 3, 6, 6)
        for ptype in ("max", "avg"):
            ref, _ = run_single_op(
                "pool2d", {"X": x},
                {"pooling_type": ptype, "ksize": [3, 3], "strides": [2, 2],
                 "paddings": [1, 1]}, ["Out"])
            got, _ = run_single_op(
                "pool2d", {"X": x.transpose(0, 2, 3, 1)},
                {"pooling_type": ptype, "ksize": [3, 3], "strides": [2, 2],
                 "paddings": [1, 1], "data_format": "NHWC"}, ["Out"])
            np.testing.assert_allclose(
                got["Out"].transpose(0, 3, 1, 2), ref["Out"],
                rtol=1e-5, atol=1e-5)

    def test_batch_norm_nhwc_train_and_grad(self):
        from op_test import run_single_op

        x = _rand(4, 3, 2, 5)  # NHWC: C=5
        scale = _rand(5, seed=1)
        bias = _rand(5, seed=2)
        mean = np.zeros(5, np.float32)
        var = np.ones(5, np.float32)
        mu = x.mean(axis=(0, 1, 2))
        v = x.var(axis=(0, 1, 2))
        ref = ((x - mu) / np.sqrt(v + 1e-5)) * scale + bias
        outs, _ = run_single_op(
            "batch_norm",
            {"X": x, "Scale": scale, "Bias": bias, "Mean": mean,
             "Variance": var},
            {"momentum": 0.9, "epsilon": 1e-5, "data_layout": "NHWC"},
            ["Y"])
        np.testing.assert_allclose(outs["Y"], ref, rtol=1e-4, atol=1e-4)
        # EMA outputs
        outs2, _ = run_single_op(
            "batch_norm",
            {"X": x, "Scale": scale, "Bias": bias, "Mean": mean,
             "Variance": var},
            {"momentum": 0.9, "epsilon": 1e-5, "data_layout": "NHWC"},
            ["MeanOut", "VarianceOut"])
        np.testing.assert_allclose(outs2["MeanOut"], 0.1 * mu, rtol=1e-4,
                                   atol=1e-5)
        np.testing.assert_allclose(outs2["VarianceOut"], 0.9 + 0.1 * v,
                                   rtol=1e-4, atol=1e-5)

    def test_batch_norm_fused_grad_matches_numeric(self):
        x = _rand(3, 4, 2, 2)  # NCHW path goes through the same custom vjp
        scale = np.ones(4, np.float32) + 0.1 * _rand(4, seed=3)
        bias = _rand(4, seed=4)
        mean = np.zeros(4, np.float32)
        var = np.ones(4, np.float32)
        check_grad(
            "batch_norm",
            {"X": x, "Scale": scale, "Bias": bias, "Mean": mean,
             "Variance": var},
            {"momentum": 0.9, "epsilon": 1e-5},
            ["Y"], ["X", "Scale", "Bias"], rtol=2e-2, atol=2e-3,
        )


class TestGroupedConvTransposeAndAdaptivePool:
    def test_grouped_conv2d_transpose(self):
        import torch
        import torch.nn.functional as F

        x = _rand(2, 4, 4, 4)
        w = _rand(4, 3, 3, 3, seed=1)  # [Cin, Cout/g, kh, kw], g=2
        ref = F.conv_transpose2d(
            torch.from_numpy(x), torch.from_numpy(w), stride=2, padding=1,
            groups=2).numpy()
        check_output(
            "conv2d_transpose", {"Input": x, "Filter": w},
            {"strides": [2, 2], "paddings": [1, 1], "groups": 2},
            {"Output": ref}, rtol=1e-4, atol=1e-4,
        )

    def test_adaptive_pool_non_divisible(self):
        from op_test import run_single_op

        x = _rand(1, 2, 7, 5)
        for ptype in ("max", "avg"):
            outs, _ = run_single_op(
                "pool2d", {"X": x},
                {"pooling_type": ptype, "ksize": [3, 2], "adaptive": True},
                ["Out"])
            got = outs["Out"]
            assert got.shape == (1, 2, 3, 2)
            red = np.max if ptype == "max" else np.mean
            for i in range(3):
                r0, r1 = i * 7 // 3, -(-(i + 1) * 7 // 3)
                for j in range(2):
                    c0, c1 = j * 5 // 2, -(-(j + 1) * 5 // 2)
                    ref = red(x[:, :, r0:r1, c0:c1], axis=(2, 3))
                    np.testing.assert_allclose(got[:, :, i, j], ref,
                                               rtol=1e-5, atol=1e-5)
