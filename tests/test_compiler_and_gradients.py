"""CompiledProgram data parallel + calc_gradient-style gradients()
(cf. reference tests/unittests/test_parallel_executor_mnist.py,
test_calc_gradient.py, test_double_grad — `compiler.py:87`,
`backward.py:1601`)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def _build_regression():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 4], append_batch_size=False)
        yt = layers.data("yt", shape=[8, 1], append_batch_size=False)
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square(pred - yt))
        opt = fluid.optimizer.SGDOptimizer(learning_rate=0.1)
        opt.minimize(loss)
    return main, startup, loss


def test_compiled_program_dp_matches_single_device():
    import jax

    # conftest forces 8 host devices; guard against silently degenerating
    # to a single-device-vs-single-device comparison
    assert len(jax.local_devices()) >= 2
    rng = np.random.RandomState(0)
    xv = rng.randn(8, 4).astype(np.float32)
    yv = rng.randn(8, 1).astype(np.float32)

    losses = {}
    for mode in ("single", "dp"):
        main, startup, loss = _build_regression()
        main.random_seed = 7
        startup.random_seed = 7
        scope = fluid.Scope()
        exe = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope):
            exe.run(startup)
            prog = main
            if mode == "dp":
                prog = fluid.CompiledProgram(main).with_data_parallel(
                    loss_name=loss.name
                )
            vals = []
            for _ in range(5):
                (lv,) = exe.run(
                    prog, feed={"x": xv, "yt": yv}, fetch_list=[loss]
                )
                vals.append(float(lv))
        losses[mode] = vals

    # GSPMD batch sharding computes the same global program: losses match
    np.testing.assert_allclose(losses["single"], losses["dp"], rtol=1e-5)
    assert losses["dp"][-1] < losses["dp"][0]  # actually trained


def test_compiled_program_requires_program():
    with pytest.raises(TypeError):
        fluid.CompiledProgram("not a program")


def test_gradients_multi_target_and_target_gradients():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3], append_batch_size=False)
        x.stop_gradient = False
        y1 = layers.scale(x, scale=2.0)       # dy1/dx = 2
        y2 = layers.square(x)                 # dy2/dx = 2x
        g1 = layers.fill_constant([3], "float32", 3.0)
        g1.stop_gradient = True
        # d(3*y1 + 1*y2)/dx = 6 + 2x
        (gx,) = fluid.gradients([y1, y2], [x], target_gradients=[g1, None])
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([1.0, -2.0, 0.5], np.float32)
    (out,) = exe.run(main, feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(out, 6.0 + 2.0 * xv, rtol=1e-6)


def test_double_grad():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], append_batch_size=False)
        x.stop_gradient = False
        # y = x^3  =>  dy/dx = 3x^2,  d2y/dx2 = 6x
        y = layers.elementwise_mul(layers.square(x), x)
        (gx,) = fluid.gradients(y, [x])
        (ggx,) = fluid.gradients(gx, [x])
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([1.0, -1.0, 2.0, 0.5], np.float32)
    g, gg = exe.run(main, feed={"x": xv}, fetch_list=[gx, ggx])
    np.testing.assert_allclose(g, 3.0 * xv**2, rtol=1e-5)
    np.testing.assert_allclose(gg, 6.0 * xv, rtol=1e-5)


def test_double_grad_through_chain():
    # z = sum(tanh(x)^2): second grad must chain THROUGH the first-order
    # grad vars (they are differentiable, not stop_gradient)
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[5], append_batch_size=False)
        x.stop_gradient = False
        t = layers.tanh(x)
        z = layers.reduce_sum(layers.square(t))
        (gx,) = fluid.gradients(z, [x])
        (ggx,) = fluid.gradients(gx, [x])
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.linspace(-1.5, 1.5, 5).astype(np.float32)
    g, gg = exe.run(main, feed={"x": xv}, fetch_list=[gx, ggx])
    th, sech2 = np.tanh(xv), 1.0 / np.cosh(xv) ** 2
    np.testing.assert_allclose(g, 2 * th * sech2, rtol=1e-5, atol=1e-6)
    # d/dx [2 tanh sech^2] = 2 sech^4 - 4 tanh^2 sech^2
    np.testing.assert_allclose(
        gg, 2 * sech2**2 - 4 * th**2 * sech2, rtol=1e-4, atol=1e-5
    )
