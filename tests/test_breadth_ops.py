"""Numpy-oracle OpTests for the breadth batch: linalg decompositions,
math tail, interpolate modes, pad2d/3d, metric ops (auc/precision_recall/
detection_map), RPN/FPN detection tail, tensor/loss extras (reference
OpTest pattern: outputs pinned by independent numpy computation)."""

import numpy as np
import pytest

from op_test import check_grad, run_single_op


def _r(rng, *shape):
    return rng.randn(*shape).astype(np.float32)


# ---------------------------------------------------------------------------
# linalg
# ---------------------------------------------------------------------------


def test_linalg_decompositions(rng):
    a = _r(rng, 6, 4)
    outs, _ = run_single_op("qr", {"X": a}, {}, ["Q", "R"])
    np.testing.assert_allclose(outs["Q"] @ outs["R"], a, atol=1e-5)

    outs, _ = run_single_op("svd", {"X": a}, {}, ["U", "S", "VH"])
    np.testing.assert_allclose(
        outs["U"] @ np.diag(outs["S"]) @ outs["VH"], a, atol=1e-5)

    sym = a.T @ a
    outs, _ = run_single_op("eigh", {"X": sym}, {},
                            ["Eigenvalues", "Eigenvectors"])
    w, v = np.linalg.eigh(sym)
    np.testing.assert_allclose(outs["Eigenvalues"], w, atol=1e-4)
    outs2, _ = run_single_op("eigvalsh", {"X": sym}, {}, ["Eigenvalues"])
    np.testing.assert_allclose(outs2["Eigenvalues"], w, atol=1e-4)


def test_linalg_det_solve(rng):
    a = _r(rng, 4, 4) + 4 * np.eye(4, dtype=np.float32)
    outs, _ = run_single_op("determinant", {"Input": a}, {}, ["Out"])
    np.testing.assert_allclose(outs["Out"], np.linalg.det(a), rtol=1e-4)

    outs, _ = run_single_op("slogdeterminant", {"Input": a}, {},
                            ["Sign", "Out"])
    sign, logdet = np.linalg.slogdet(a)
    np.testing.assert_allclose(outs["Sign"], sign, rtol=1e-5)
    np.testing.assert_allclose(outs["Out"], logdet, rtol=1e-4)

    b = _r(rng, 4, 2)
    outs, _ = run_single_op("solve", {"X": a, "Y": b}, {}, ["Out"])
    np.testing.assert_allclose(outs["Out"], np.linalg.solve(a, b),
                               rtol=1e-3, atol=1e-4)

    m = _r(rng, 5, 3)
    outs, _ = run_single_op("pinv", {"X": m}, {}, ["Out"])
    np.testing.assert_allclose(outs["Out"], np.linalg.pinv(m), rtol=1e-3,
                               atol=1e-4)

    outs, _ = run_single_op("lstsq", {"X": m, "Y": _r(rng, 5, 2)}, {},
                            ["Solution", "Residuals"])
    assert outs["Solution"].shape == (3, 2)

    outs, _ = run_single_op("matrix_rank", {"X": m}, {}, ["Out"])
    assert int(outs["Out"]) == np.linalg.matrix_rank(m)

    outs, _ = run_single_op("mv", {"X": a, "Vec": _r(rng, 4)}, {}, ["Out"])
    assert outs["Out"].shape == (4,)

    outs, _ = run_single_op("lu", {"X": a}, {}, ["Out", "Pivots"])
    assert outs["Out"].shape == (4, 4) and outs["Pivots"].shape == (4,)


def test_cholesky_solve(rng):
    a = _r(rng, 4, 4)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    L = np.linalg.cholesky(spd).astype(np.float32)
    b = _r(rng, 4, 2)
    outs, _ = run_single_op("cholesky_solve", {"X": b, "Y": L},
                            {"upper": False}, ["Out"])
    np.testing.assert_allclose(outs["Out"], np.linalg.solve(spd, b),
                               rtol=1e-3, atol=1e-4)


# ---------------------------------------------------------------------------
# math tail
# ---------------------------------------------------------------------------

_MATH_BIN = [
    ("elementwise_fmax", np.fmax), ("elementwise_fmin", np.fmin),
    ("remainder", np.remainder), ("heaviside", np.heaviside),
    ("logaddexp", np.logaddexp),
]


@pytest.mark.parametrize("op,fn", _MATH_BIN, ids=[o for o, _ in _MATH_BIN])
def test_math_binary(rng, op, fn):
    x, y = _r(rng, 3, 4), _r(rng, 3, 4) + 0.5
    outs, _ = run_single_op(op, {"X": x, "Y": y}, {}, ["Out"])
    np.testing.assert_allclose(outs["Out"], fn(x, y), rtol=1e-5, atol=1e-6)


def test_math_reductions(rng):
    x = _r(rng, 3, 5)
    x[0, 0] = np.nan
    for op, fn in [("nansum", np.nansum), ("nanmean", np.nanmean)]:
        outs, _ = run_single_op(op, {"X": x}, {"axis": 1}, ["Out"])
        np.testing.assert_allclose(outs["Out"], fn(x, axis=1), rtol=1e-5)
    y = _r(rng, 4, 6)
    for op, fn in [("reduce_amax", np.amax), ("reduce_amin", np.amin),
                   ("median", np.median)]:
        outs, _ = run_single_op(op, {"X": y}, {"axis": 1}, ["Out"])
        np.testing.assert_allclose(outs["Out"], fn(y, axis=1), rtol=1e-5)
    outs, _ = run_single_op("quantile", {"X": y}, {"q": 0.3, "axis": 1},
                            ["Out"])
    np.testing.assert_allclose(outs["Out"], np.quantile(y, 0.3, axis=1),
                               rtol=1e-4)
    for op, fn in [("reduce_std", np.std), ("reduce_var", np.var)]:
        outs, _ = run_single_op(op, {"X": y},
                                {"axis": 1, "unbiased": True}, ["Out"])
        np.testing.assert_allclose(outs["Out"], fn(y, axis=1, ddof=1),
                                   rtol=1e-4)


def test_math_unary_extras(rng):
    p = rng.uniform(0.05, 0.95, (3, 4)).astype(np.float32)
    outs, _ = run_single_op("logit", {"X": p}, {}, ["Out"])
    np.testing.assert_allclose(outs["Out"], np.log(p / (1 - p)),
                               rtol=1e-4, atol=1e-5)
    check_grad("logit", {"X": p.astype(np.float64)}, {}, ["Out"], ["X"])

    x = _r(rng, 3, 4)
    outs, _ = run_single_op("brelu", {"X": x * 10},
                            {"t_min": 1.0, "t_max": 4.0}, ["Out"])
    np.testing.assert_allclose(outs["Out"], np.clip(x * 10, 1, 4))

    outs, _ = run_single_op("soft_relu", {"X": x}, {}, ["Out"])
    np.testing.assert_allclose(outs["Out"], np.log1p(np.exp(x)),
                               rtol=1e-5, atol=1e-6)

    outs, _ = run_single_op("logcumsumexp", {"X": x}, {"axis": 1}, ["Out"])
    np.testing.assert_allclose(
        outs["Out"], np.log(np.cumsum(np.exp(x), axis=1)), rtol=1e-4,
        atol=1e-5)

    a = rng.randint(1, 40, (3, 4))
    b = rng.randint(1, 40, (3, 4))
    outs, _ = run_single_op("gcd", {"X": a, "Y": b}, {}, ["Out"])
    np.testing.assert_array_equal(outs["Out"], np.gcd(a, b))
    outs, _ = run_single_op("lcm", {"X": a, "Y": b}, {}, ["Out"])
    np.testing.assert_array_equal(outs["Out"], np.lcm(a, b))


# ---------------------------------------------------------------------------
# interpolate / pad / channel ops
# ---------------------------------------------------------------------------


def test_interp_linear_ramp_exact(rng):
    """Linear functions are reproduced exactly by (tri)linear resampling
    with align_corners=True — an oracle independent of any resize lib."""
    w = 8
    x = np.arange(w, dtype=np.float32)[None, None, :] * 2.0 + 1.0
    outs, _ = run_single_op("linear_interp", {"X": x},
                            {"out_w": 15, "align_corners": True}, ["Out"])
    expect = np.linspace(x[0, 0, 0], x[0, 0, -1], 15)
    np.testing.assert_allclose(outs["Out"][0, 0], expect, rtol=1e-5)

    d = h = w = 4
    grid = np.mgrid[0:d, 0:h, 0:w].astype(np.float32)
    vol = (1.5 * grid[0] + 0.5 * grid[1] - grid[2])[None, None]
    outs, _ = run_single_op(
        "trilinear_interp", {"X": vol},
        {"out_d": 7, "out_h": 7, "out_w": 7, "align_corners": True},
        ["Out"])
    g7 = np.mgrid[0:7, 0:7, 0:7].astype(np.float32) * (3.0 / 6.0)
    expect = (1.5 * g7[0] + 0.5 * g7[1] - g7[2])
    np.testing.assert_allclose(outs["Out"][0, 0], expect, atol=1e-4)


def test_bicubic_identity_and_shape(rng):
    x = _r(rng, 1, 2, 6, 6)
    outs, _ = run_single_op("bicubic_interp", {"X": x},
                            {"out_h": 6, "out_w": 6}, ["Out"])
    np.testing.assert_allclose(outs["Out"], x, atol=1e-5)
    outs, _ = run_single_op("bicubic_interp", {"X": x},
                            {"out_h": 12, "out_w": 9}, ["Out"])
    assert outs["Out"].shape == (1, 2, 12, 9)


def test_pad2d_pad3d(rng):
    x = _r(rng, 2, 3, 4, 5)
    outs, _ = run_single_op(
        "pad2d", {"X": x},
        {"paddings": [1, 2, 3, 0], "mode": "constant", "pad_value": 7.0},
        ["Out"])
    expect = np.pad(x, ((0, 0), (0, 0), (1, 2), (3, 0)),
                    constant_values=7.0)
    np.testing.assert_array_equal(outs["Out"], expect)
    outs, _ = run_single_op("pad2d", {"X": x},
                            {"paddings": [1, 1, 1, 1], "mode": "reflect"},
                            ["Out"])
    np.testing.assert_array_equal(
        outs["Out"], np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)),
                            mode="reflect"))

    v = _r(rng, 1, 2, 3, 4, 5)
    outs, _ = run_single_op(
        "pad3d", {"X": v},
        {"paddings": [1, 0, 0, 1, 2, 0], "mode": "replicate"}, ["Out"])
    np.testing.assert_array_equal(
        outs["Out"], np.pad(v, ((0, 0), (0, 0), (1, 0), (0, 1), (2, 0)),
                            mode="edge"))


def test_channel_ops(rng):
    x = _r(rng, 2, 8, 4, 4)
    outs, _ = run_single_op("shuffle_channel", {"X": x}, {"group": 2},
                            ["Out"])
    expect = x.reshape(2, 2, 4, 4, 4).transpose(0, 2, 1, 3, 4).reshape(
        2, 8, 4, 4)
    np.testing.assert_array_equal(outs["Out"], expect)

    # pixel_unshuffle inverts pixel_shuffle
    y = _r(rng, 2, 4, 6, 6)
    shuf, _ = run_single_op("pixel_shuffle", {"X": y},
                            {"upscale_factor": 2}, ["Out"])
    unshuf, _ = run_single_op("pixel_unshuffle", {"X": shuf["Out"]},
                              {"downscale_factor": 2}, ["Out"])
    np.testing.assert_array_equal(unshuf["Out"], y)

    # maxout
    outs, _ = run_single_op("maxout", {"X": x}, {"groups": 2}, ["Out"])
    np.testing.assert_array_equal(
        outs["Out"], x.reshape(2, 4, 2, 4, 4).max(axis=2))


def test_temporal_shift(rng):
    n, t, c, h, w = 2, 4, 8, 2, 2
    x = _r(rng, n * t, c, h, w)
    outs, _ = run_single_op("temporal_shift", {"X": x},
                            {"seg_num": t, "shift_ratio": 0.25}, ["Out"])
    xr = x.reshape(n, t, c, h, w)
    expect = np.zeros_like(xr)
    c1, c2 = c // 4, c // 2
    expect[:, :-1, :c1] = xr[:, 1:, :c1]      # shift back
    expect[:, 1:, c1:c2] = xr[:, :-1, c1:c2]  # shift forward
    expect[:, :, c2:] = xr[:, :, c2:]
    np.testing.assert_array_equal(outs["Out"], expect.reshape(n * t, c, h, w))


def test_lrn(rng):
    x = _r(rng, 2, 6, 3, 3)
    outs, _ = run_single_op(
        "lrn", {"X": x}, {"n": 5, "k": 2.0, "alpha": 1e-4, "beta": 0.75},
        ["Out"])
    expect = np.zeros_like(x)
    for ci in range(6):
        lo, hi = max(0, ci - 2), min(6, ci + 3)
        den = 2.0 + 1e-4 * np.sum(x[:, lo:hi] ** 2, axis=1)
        expect[:, ci] = x[:, ci] / den ** 0.75
    np.testing.assert_allclose(outs["Out"], expect, rtol=1e-4, atol=1e-6)


def test_row_conv(rng):
    B, T, D, K = 2, 6, 3, 3
    x, f = _r(rng, B, T, D), _r(rng, K, D)
    lens = np.array([6, 4], np.int64)
    outs, _ = run_single_op("row_conv",
                            {"X": x, "Filter": f, "SeqLens": lens}, {},
                            ["Out"])
    expect = np.zeros_like(x)
    for b in range(B):
        for t in range(int(lens[b])):
            for i in range(K):
                if t + i < int(lens[b]):
                    expect[b, t] += x[b, t + i] * f[i]
    np.testing.assert_allclose(outs["Out"], expect, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# metric ops
# ---------------------------------------------------------------------------


def test_auc_matches_rank_oracle(rng):
    n, buckets = 400, 4096
    scores = rng.rand(n).astype(np.float32)
    labels = (rng.rand(n) < scores).astype(np.int64)  # correlated
    stat = np.zeros(buckets + 1, np.float32)
    outs, _ = run_single_op(
        "auc", {"Predict": scores[:, None], "Label": labels[:, None],
                "StatPos": stat.copy(), "StatNeg": stat.copy()},
        {}, ["AUC", "StatPosOut", "StatNegOut"])
    # exact rank-based AUC oracle
    pos = scores[labels == 1]
    neg = scores[labels == 0]
    cmp_ = (pos[:, None] > neg[None, :]).sum() \
        + 0.5 * (pos[:, None] == neg[None, :]).sum()
    oracle = cmp_ / (len(pos) * len(neg))
    np.testing.assert_allclose(float(outs["AUC"][0]), oracle, atol=2e-3)


def test_auc_streaming_accumulates(rng):
    buckets = 1024
    sp = np.zeros(buckets + 1, np.float32)
    sn = np.zeros(buckets + 1, np.float32)
    all_s, all_l = [], []
    for i in range(3):
        s = rng.rand(100).astype(np.float32)
        l = (rng.rand(100) < s).astype(np.int64)
        outs, _ = run_single_op(
            "auc", {"Predict": s[:, None], "Label": l[:, None],
                    "StatPos": sp, "StatNeg": sn},
            {}, ["AUC", "StatPosOut", "StatNegOut"])
        sp, sn = outs["StatPosOut"], outs["StatNegOut"]
        all_s.append(s)
        all_l.append(l)
    s = np.concatenate(all_s)
    l = np.concatenate(all_l)
    pos, neg = s[l == 1], s[l == 0]
    oracle = ((pos[:, None] > neg[None, :]).sum()
              + 0.5 * (pos[:, None] == neg[None, :]).sum()) / (
                  len(pos) * len(neg))
    np.testing.assert_allclose(float(outs["AUC"][0]), oracle, atol=5e-3)


def test_precision_recall(rng):
    C, n = 4, 60
    idx = rng.randint(0, C, n).astype(np.int64)
    lab = rng.randint(0, C, n).astype(np.int64)
    probs = rng.rand(n).astype(np.float32)
    outs, _ = run_single_op(
        "precision_recall",
        {"MaxProbs": probs[:, None], "Indices": idx[:, None],
         "Labels": lab[:, None]},
        {"class_number": C}, ["BatchMetrics", "AccumMetrics",
                              "AccumStatesInfo"])
    # numpy oracle
    P, R = [], []
    stp = sfp = sfn = 0.0
    for c in range(C):
        tp = np.sum((idx == c) & (lab == c))
        fp = np.sum((idx == c) & (lab != c))
        fn = np.sum((idx != c) & (lab == c))
        P.append(tp / (tp + fp) if tp + fp else 0.0)
        R.append(tp / (tp + fn) if tp + fn else 0.0)
        stp += tp
        sfp += fp
        sfn += fn
    bm = outs["BatchMetrics"]
    np.testing.assert_allclose(bm[0], np.mean(P), rtol=1e-4)
    np.testing.assert_allclose(bm[1], np.mean(R), rtol=1e-4)
    np.testing.assert_allclose(bm[3], stp / (stp + sfp), rtol=1e-4)
    np.testing.assert_allclose(bm[4], stp / (stp + sfn), rtol=1e-4)


def test_detection_map_perfect_and_miss():
    # one image, 2 classes; det 0 matches gt exactly, det 1 misses
    det = np.array([[[0, 0.9, 0, 0, 10, 10],
                     [1, 0.8, 50, 50, 60, 60]]], np.float32)
    gt = np.array([[[0, 0, 0, 10, 10],
                    [1, 0, 0, 10, 10]]], np.float32)
    outs, _ = run_single_op(
        "detection_map", {"DetectRes": det, "Label": gt},
        {"class_num": 2, "overlap_threshold": 0.5, "ap_type": "integral"},
        ["MAP"])
    # class 0: AP=1; class 1: AP=0 -> mAP 0.5
    np.testing.assert_allclose(float(outs["MAP"][0]), 0.5, atol=1e-5)

    det2 = np.array([[[0, 0.9, 0, 0, 10, 10],
                      [1, 0.8, 0, 0, 10, 10]]], np.float32)
    outs, _ = run_single_op(
        "detection_map", {"DetectRes": det2, "Label": gt},
        {"class_num": 2, "overlap_threshold": 0.5, "ap_type": "integral"},
        ["MAP"])
    np.testing.assert_allclose(float(outs["MAP"][0]), 1.0, atol=1e-5)


# ---------------------------------------------------------------------------
# detection tail
# ---------------------------------------------------------------------------


def test_generate_proposals_properties(rng):
    N, A, H, W = 1, 3, 4, 4
    scores = rng.rand(N, A, H, W).astype(np.float32)
    deltas = (0.1 * rng.randn(N, A * 4, H, W)).astype(np.float32)
    base = np.array([[0, 0, 15, 15], [4, 4, 11, 11], [2, 2, 13, 13]],
                    np.float32)
    anchors = np.tile(base[None, None], (H, W, 1, 1)).reshape(H, W, A, 4)
    im_info = np.array([[32, 32, 1.0]], np.float32)
    outs, _ = run_single_op(
        "generate_proposals",
        {"Scores": scores, "BboxDeltas": deltas, "ImInfo": im_info,
         "Anchors": anchors},
        {"pre_nms_topN": 24, "post_nms_topN": 8, "nms_thresh": 0.6},
        ["RpnRois", "RpnRoiProbs"])
    rois, probs = outs["RpnRois"][0], outs["RpnRoiProbs"][0]
    assert rois.shape == (8, 4) and probs.shape == (8,)
    # scores descend, boxes clipped to image
    valid = probs > 0
    pv = probs[valid]
    assert np.all(pv[:-1] >= pv[1:] - 1e-6)
    assert rois[valid].min() >= 0 and rois[valid].max() <= 31


def test_distribute_fpn_proposals():
    rois = np.array([
        [0, 0, 16, 16],      # tiny -> min level
        [0, 0, 224, 224],    # refer scale -> level 4
        [0, 0, 1000, 1000],  # huge -> max level
    ], np.float32)
    outs, _ = run_single_op(
        "distribute_fpn_proposals", {"FpnRois": rois},
        {"min_level": 2, "max_level": 5, "refer_scale": 224,
         "refer_level": 4},
        ["MultiFpnRois", "RestoreIndex", "LevelIds"])
    lvls = outs["LevelIds"]
    assert list(lvls) == [2, 4, 5]
    restore = outs["RestoreIndex"][:, 0]
    np.testing.assert_array_equal(outs["MultiFpnRois"][restore], rois)


def test_collect_fpn_proposals(rng):
    r1, r2 = _r(rng, 4, 4), _r(rng, 4, 4)
    s1 = np.array([0.9, 0.1, 0.5, 0.3], np.float32)
    s2 = np.array([0.8, 0.2, 0.6, 0.4], np.float32)
    outs, _ = run_single_op(
        "collect_fpn_proposals",
        {"MultiLevelRois": [r1, r2], "MultiLevelScores": [s1, s2]},
        {"post_nms_topN": 3}, ["FpnRois"])
    allr = np.concatenate([r1, r2])
    alls = np.concatenate([s1, s2])
    np.testing.assert_allclose(outs["FpnRois"],
                               allr[np.argsort(-alls)[:3]])


def test_sigmoid_focal_loss(rng):
    N, C = 6, 3
    x = _r(rng, N, C)
    label = rng.randint(0, C + 1, (N, 1)).astype(np.int64)
    fg = np.array([max((label > 0).sum(), 1)], np.int64)
    outs, _ = run_single_op(
        "sigmoid_focal_loss", {"X": x, "Label": label, "FgNum": fg},
        {"gamma": 2.0, "alpha": 0.25}, ["Out"])
    p = 1 / (1 + np.exp(-x))
    t = (label == (np.arange(C) + 1)[None, :]).astype(np.float32)
    ce = -(t * np.log(p) + (1 - t) * np.log(1 - p))
    pt = t * p + (1 - t) * (1 - p)
    at = t * 0.25 + (1 - t) * 0.75
    expect = at * (1 - pt) ** 2 * ce / fg[0]
    np.testing.assert_allclose(outs["Out"], expect, rtol=1e-3, atol=1e-5)


def test_polygon_box_transform(rng):
    x = _r(rng, 1, 4, 2, 3)
    outs, _ = run_single_op("polygon_box_transform", {"Input": x}, {},
                            ["Output"])
    for i in range(2):
        for j in range(3):
            np.testing.assert_allclose(
                outs["Output"][0, 0, i, j], j * 4.0 - x[0, 0, i, j],
                rtol=1e-5)
            np.testing.assert_allclose(
                outs["Output"][0, 1, i, j], i * 4.0 - x[0, 1, i, j],
                rtol=1e-5)


def test_target_assign():
    x = np.arange(2 * 3 * 2, dtype=np.float32).reshape(2, 3, 2)
    match = np.array([[0, -1, 2, 1], [1, 1, -1, 0]], np.int64)
    outs, _ = run_single_op(
        "target_assign", {"X": x, "MatchIndices": match},
        {"mismatch_value": -5.0}, ["Out", "OutWeight"])
    assert outs["Out"].shape == (2, 4, 2)
    np.testing.assert_array_equal(outs["Out"][0, 0], x[0, 0])
    np.testing.assert_array_equal(outs["Out"][0, 1], [-5, -5])
    np.testing.assert_array_equal(outs["OutWeight"][0, :, 0], [1, 0, 1, 1])


# ---------------------------------------------------------------------------
# tensor / loss extras
# ---------------------------------------------------------------------------


def test_tensor_extras(rng):
    x = _r(rng, 4, 6)
    outs, _ = run_single_op("crop_tensor", {"X": x},
                            {"offsets": [1, 2], "shape": [2, 3]}, ["Out"])
    np.testing.assert_array_equal(outs["Out"], x[1:3, 2:5])

    outs, _ = run_single_op("size", {"Input": x}, {}, ["Out"])
    assert int(outs["Out"]) == 24

    m = (rng.rand(4, 6) > 0.5)
    outs, _ = run_single_op("masked_fill", {"X": x, "Mask": m},
                            {"value": 9.0}, ["Out"])
    np.testing.assert_array_equal(outs["Out"], np.where(m, 9.0, x))

    a, b = _r(rng, 2, 6), _r(rng, 2, 6)
    outs, _ = run_single_op("partial_sum", {"X": [a, b]},
                            {"start_index": 1, "length": 3}, ["Out"])
    np.testing.assert_allclose(outs["Out"], a[:, 1:4] + b[:, 1:4])
    outs, _ = run_single_op("partial_concat", {"X": [a, b]},
                            {"start_index": 1, "length": 3}, ["Out"])
    np.testing.assert_allclose(outs["Out"],
                               np.concatenate([a[:, 1:4], b[:, 1:4]], 1))


def test_gather_tree():
    # T=3, B=1, W=2 beams
    ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]], np.int64)
    parents = np.array([[[0, 0]], [[0, 0]], [[1, 0]]], np.int64)
    outs, _ = run_single_op("gather_tree", {"Ids": ids, "Parents": parents},
                            {}, ["Out"])
    # beam 0 at t=2 came from parent 1: path = ids[0][p(p)], ids[1][1]=4, 5
    np.testing.assert_array_equal(outs["Out"][:, 0, 0], [1, 4, 5])
    np.testing.assert_array_equal(outs["Out"][:, 0, 1], [1, 3, 6])


def test_center_loss(rng):
    N, D, C = 5, 4, 3
    x = _r(rng, N, D)
    label = rng.randint(0, C, (N, 1)).astype(np.int64)
    centers = _r(rng, C, D)
    alpha = np.array([0.5], np.float32)
    outs, _ = run_single_op(
        "center_loss",
        {"X": x, "Label": label, "Centers": centers,
         "CenterUpdateRate": alpha},
        {"need_update": True},
        ["Loss", "SampleCenterDiff", "CentersOut"])
    diff = x - centers[label[:, 0]]
    np.testing.assert_allclose(
        outs["Loss"], 0.5 * np.sum(diff ** 2, 1, keepdims=True), rtol=1e-4)
    # center update oracle
    new_c = centers.copy()
    for c in range(C):
        sel = label[:, 0] == c
        if sel.any():
            new_c[c] += 0.5 * diff[sel].sum(0) / (sel.sum() + 1.0)
    np.testing.assert_allclose(outs["CentersOut"], new_c, rtol=1e-4,
                               atol=1e-5)


def test_losses(rng):
    x = rng.uniform(0.1, 0.9, (3, 4, 4)).astype(np.float32)
    lab = (rng.rand(3, 4, 4) > 0.5).astype(np.float32)
    outs, _ = run_single_op("dice_loss", {"X": x, "Label": lab},
                            {"epsilon": 1e-5}, ["Out"])
    inter = (x * lab).sum((1, 2))
    union = x.sum((1, 2)) + lab.sum((1, 2))
    np.testing.assert_allclose(outs["Out"],
                               1 - (2 * inter + 1e-5) / (union + 1e-5),
                               rtol=1e-4)

    logits = _r(rng, 6, 1)
    soft = rng.uniform(0, 1, (6, 1)).astype(np.float32)
    outs, _ = run_single_op("teacher_student_sigmoid_loss",
                            {"X": logits, "Label": soft}, {}, ["Y"])
    z = logits.reshape(-1)
    l = soft.reshape(-1)
    expect = np.maximum(z, 0) - z * l + np.log1p(np.exp(-np.abs(z)))
    np.testing.assert_allclose(outs["Y"][:, 0], expect, rtol=1e-4,
                               atol=1e-5)

    a, p = _r(rng, 4, 5), _r(rng, 4, 5)
    labels = np.array([0, 1, 0, 2], np.int64)
    outs, _ = run_single_op("npair_loss",
                            {"Anchor": a, "Positive": p, "Labels": labels},
                            {"l2_reg": 0.002}, ["Out"])
    sim = a @ p.T
    t = (labels[:, None] == labels[None, :]).astype(np.float32)
    t = t / t.sum(1, keepdims=True)
    lse = np.log(np.exp(sim - sim.max(1, keepdims=True)).sum(1)) \
        + sim.max(1)
    xe = (-(t * (sim - lse[:, None])).sum(1)).mean()
    reg = 0.002 * ((a ** 2).sum() + (p ** 2).sum()) / 4
    np.testing.assert_allclose(float(outs["Out"]), xe + reg, rtol=1e-4)


def test_fsp_and_sq_l2(rng):
    x, y = _r(rng, 2, 3, 4, 4), _r(rng, 2, 5, 4, 4)
    outs, _ = run_single_op("fsp", {"X": x, "Y": y}, {}, ["Out"])
    expect = np.einsum("nchw,ndhw->ncd", x.reshape(2, 3, 4, 4),
                       y.reshape(2, 5, 4, 4)) / 16.0
    np.testing.assert_allclose(outs["Out"], expect, rtol=1e-4)

    a, b = _r(rng, 3, 4), _r(rng, 3, 4)
    outs, _ = run_single_op("squared_l2_distance", {"X": a, "Y": b}, {},
                            ["Out", "sub_result"])
    np.testing.assert_allclose(outs["Out"][:, 0],
                               ((a - b) ** 2).sum(1), rtol=1e-4)


def test_unbind(rng):
    # variadic output: exercise the lowering directly
    import jax.numpy as jnp

    from paddle_tpu.fluid.core.registry import LowerContext, get_op_def

    x = _r(rng, 3, 4, 2)
    outs = get_op_def("unbind").lower(
        LowerContext(), {"X": [jnp.asarray(x)]}, {"axis": 0})
    assert len(outs["Out"]) == 3
    for i in range(3):
        np.testing.assert_array_equal(np.asarray(outs["Out"][i]), x[i])


# ---------------------------------------------------------------------------
# layer-level smoke: wrappers build + run inside a program
# ---------------------------------------------------------------------------


def test_layer_wrappers_run(rng):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 4, 8, 8], append_batch_size=False)
        r1 = layers.resize_bilinear(x, out_shape=[16, 16])
        r2 = layers.resize_bicubic(x, out_shape=[4, 4])
        p = layers.pad2d(x, [1, 1, 2, 2], mode="reflect")
        l = layers.lrn(x)
        m = layers.maxout(x, groups=2)
        s = layers.shuffle_channel(x, group=2)
        u = layers.pixel_unshuffle(x, downscale_factor=2)
        c = layers.crop_tensor(x, shape=[-1, 2, 4, 4], offsets=[0, 1, 2, 2])
        fetches = [r1, r2, p, l, m, s, u, c]
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = rng.randn(2, 4, 8, 8).astype(np.float32)
    outs = exe.run(main, feed={"x": xv}, fetch_list=fetches)
    assert outs[0].shape == (2, 4, 16, 16)
    assert outs[1].shape == (2, 4, 4, 4)
    assert outs[2].shape == (2, 4, 10, 12)
    assert outs[4].shape == (2, 2, 8, 8)
    assert outs[6].shape == (2, 16, 4, 4)
    assert outs[7].shape == (2, 2, 4, 4)


def test_auc_layer_streaming(rng):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pred = layers.data("pred", shape=[-1, 1], append_batch_size=False)
        label = layers.data("label", shape=[-1, 1], dtype="int64",
                            append_batch_size=False)
        auc_out, _states = layers.auc(pred, label, num_thresholds=1023)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    all_s, all_l = [], []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for i in range(3):
            s = rng.rand(80, 1).astype(np.float32)
            l = (rng.rand(80, 1) < s).astype(np.int64)
            all_s.append(s)
            all_l.append(l)
            (aucv,) = exe.run(main, feed={"pred": s, "label": l},
                              fetch_list=[auc_out])
    s = np.concatenate(all_s).reshape(-1)
    l = np.concatenate(all_l).reshape(-1)
    pos, neg = s[l == 1], s[l == 0]
    oracle = ((pos[:, None] > neg[None, :]).sum()
              + 0.5 * (pos[:, None] == neg[None, :]).sum()) / (
                  len(pos) * len(neg))
    np.testing.assert_allclose(float(aucv[0]), oracle, atol=5e-3)


def test_detection_layers_build(rng):
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        scores = layers.data("s", shape=[-1, 3, 4, 4],
                             append_batch_size=False)
        deltas = layers.data("d", shape=[-1, 12, 4, 4],
                             append_batch_size=False)
        im_info = layers.data("ii", shape=[-1, 3], append_batch_size=False)
        anchors = layers.data("a", shape=[4, 4, 3, 4],
                              append_batch_size=False)
        var = layers.data("v", shape=[4, 4, 3, 4], append_batch_size=False)
        rois, probs = layers.detection.generate_proposals(
            scores, deltas, im_info, anchors, var,
            pre_nms_top_n=16, post_nms_top_n=4)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    outs = exe.run(main, feed={
        "s": rng.rand(1, 3, 4, 4).astype(np.float32),
        "d": (0.1 * rng.randn(1, 12, 4, 4)).astype(np.float32),
        "ii": np.array([[32, 32, 1.0]], np.float32),
        "a": np.tile(np.array([[0, 0, 15, 15]], np.float32),
                     (4, 4, 3, 1)).reshape(4, 4, 3, 4),
        "v": np.ones((4, 4, 3, 4), np.float32),
    }, fetch_list=[rois, probs])
    assert outs[0].shape == (1, 4, 4)


def test_metric_classes():
    from paddle_tpu.fluid import metrics

    ce = metrics.ChunkEvaluator()
    ce.update(np.array([10]), np.array([8]), np.array([6]))
    p, r, f1 = ce.eval()
    assert p == 0.6 and r == 0.75
    np.testing.assert_allclose(f1, 2 * 0.6 * 0.75 / 1.35)

    ed = metrics.EditDistance()
    ed.update(np.array([[0.0], [2.0], [1.0]]), np.array([3]))
    avg, err = ed.eval()
    assert avg == 1.0 and err == pytest.approx(2 / 3)

    dm = metrics.DetectionMAP()
    dm.update(0.5)
    dm.update(0.7)
    assert dm.eval() == pytest.approx(0.6)


def test_box_decoder_and_assign(rng):
    R, C = 5, 3
    prior = np.abs(_r(rng, R, 4)) * 10
    prior[:, 2:] += prior[:, :2] + 5  # well-formed boxes
    pvar = np.full((R, 4), 0.1, np.float32)
    target = (0.1 * rng.randn(R, C * 4)).astype(np.float32)
    score = rng.rand(R, C).astype(np.float32)
    outs, _ = run_single_op(
        "box_decoder_and_assign",
        {"PriorBox": prior, "PriorBoxVar": pvar, "TargetBox": target,
         "BoxScore": score}, {}, ["DecodeBox", "OutputAssignBox"])
    assert outs["DecodeBox"].shape == (R, C * 4)
    best = score.argmax(1)
    for r in range(R):
        np.testing.assert_allclose(
            outs["OutputAssignBox"][r],
            outs["DecodeBox"][r, best[r] * 4:(best[r] + 1) * 4], rtol=1e-5)


def test_matrix_rank_absolute_tol(rng):
    # singular values ~ [100, 0.5]: absolute tol=1.0 must give rank 1
    u, _ = np.linalg.qr(_r(rng, 2, 2))
    v, _ = np.linalg.qr(_r(rng, 2, 2))
    m = (u @ np.diag([100.0, 0.5]) @ v).astype(np.float32)
    outs, _ = run_single_op("matrix_rank", {"X": m}, {"tol": 1.0}, ["Out"])
    assert int(outs["Out"]) == 1
