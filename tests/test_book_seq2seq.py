"""Book test: seq2seq with attention — train AND decode (greedy + beam).

Capability parity: reference `tests/book/test_machine_translation.py`
(WMT14-style encoder-decoder with attention, trained with loss-decrease
assertion, then beam-search decode).  Synthetic copy-reverse task stands in
for WMT14 (no dataset downloads in this environment); the model structure
is the same: GRU encoder, attention decoder over StaticRNN, beam_search /
beam_search_decode ops for inference.
"""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.optimizer import AdamOptimizer

V = 16        # vocab (0=PAD/EOS, 1=GO)
E, H = 16, 24
TS, TD = 6, 7  # src len, tgt len (GO + 6 tokens)
EOS, GO = 0, 1


def _batch(rng, B):
    """Source: random ids in [2, V); target: reversed source + EOS."""
    lens = rng.randint(3, TS + 1, size=B).astype(np.int32)
    src = np.zeros((B, TS), np.int64)
    tgt_in = np.zeros((B, TD), np.int64)
    tgt_out = np.zeros((B, TD), np.int64)
    for b in range(B):
        s = rng.randint(2, V, size=lens[b])
        src[b, :lens[b]] = s
        rev = s[::-1]
        tgt_in[b, 0] = GO
        tgt_in[b, 1:lens[b] + 1] = rev
        tgt_out[b, :lens[b]] = rev
        tgt_out[b, lens[b]] = EOS
    tgt_lens = (lens + 1).astype(np.int32)
    return src, lens, tgt_in, tgt_out, tgt_lens


def _encoder(src, src_lens):
    emb = layers.embedding(src, size=[V, E],
                           param_attr=fluid.ParamAttr(name="src_emb"))
    proj = layers.fc(emb, 3 * H, num_flatten_dims=2, bias_attr=False,
                     param_attr=fluid.ParamAttr(name="enc_proj"))
    enc = layers.dynamic_gru(proj, H, seq_lens=src_lens,
                             param_attr=fluid.ParamAttr(name="enc_gru"),
                             bias_attr=fluid.ParamAttr(name="enc_gru_b"))
    h0 = layers.sequence_last_step(enc, src_lens)
    return enc, h0


def _attend(h, enc, src_lens):
    """Dot attention: h [B,H] or [N,H] vs enc [B,T,H] -> context [.,H]."""
    scores = layers.reduce_sum(
        layers.elementwise_mul(enc, layers.unsqueeze(h, [1])), dim=2)
    w = layers.sequence_softmax(scores, src_lens)
    return layers.reduce_sum(
        layers.elementwise_mul(enc, layers.unsqueeze(w, [2])), dim=1)


def _dec_step(x_emb, h_prev, enc, src_lens):
    """One decoder step shared by train/decode: returns new hidden."""
    att = _attend(h_prev, enc, src_lens)
    inp = layers.concat([x_emb, att], axis=1)
    pre = layers.fc(inp, 3 * H, bias_attr=False,
                    param_attr=fluid.ParamAttr(name="dec_proj"))
    return layers.gru_unit(pre, h_prev, 3 * H,
                           param_attr=fluid.ParamAttr(name="dec_gru"),
                           bias_attr=fluid.ParamAttr(name="dec_gru_b"))


def _logits_of(h):
    return layers.fc(h, V, param_attr=fluid.ParamAttr(name="out_w"),
                     bias_attr=fluid.ParamAttr(name="out_b"))


def _build_train():
    src = layers.data("src", shape=[TS], dtype="int64")
    src_lens = layers.data("src_lens", shape=[], dtype="int32")
    tgt_in = layers.data("tgt_in", shape=[TD], dtype="int64")
    tgt_out = layers.data("tgt_out", shape=[TD], dtype="int64")
    tgt_lens = layers.data("tgt_lens", shape=[], dtype="int32")

    enc, h0 = _encoder(src, src_lens)
    temb = layers.embedding(tgt_in, size=[V, E],
                            param_attr=fluid.ParamAttr(name="tgt_emb"))
    temb_tm = layers.transpose(temb, [1, 0, 2])  # [TD, B, E]

    srnn = layers.StaticRNN()
    with srnn.step():
        x_t = srnn.step_input(temb_tm)
        h_prev = srnn.memory(init=h0)
        h = _dec_step(x_t, h_prev, enc, src_lens)
        srnn.update_memory(h_prev, h)
        srnn.step_output(h)
    dec = layers.transpose(srnn(), [1, 0, 2])  # [B, TD, H]
    logits = layers.fc(dec, V, num_flatten_dims=2,
                       param_attr=fluid.ParamAttr(name="out_w"),
                       bias_attr=fluid.ParamAttr(name="out_b"))
    flat = layers.reshape(logits, [-1, V])
    lab = layers.reshape(tgt_out, [-1, 1])
    ce = layers.softmax_with_cross_entropy(flat, lab)
    mask = layers.cast(
        layers.sequence_mask(tgt_lens, TD, dtype="int64"), "float32")
    ce = layers.reshape(ce, [-1, TD]) * mask
    loss = layers.reduce_sum(ce) / (layers.reduce_sum(mask) + 1e-6)
    return loss


def _build_greedy(max_len):
    src = layers.data("src", shape=[TS], dtype="int64")
    src_lens = layers.data("src_lens", shape=[], dtype="int32")
    enc, h = _encoder(src, src_lens)
    tok = layers.fill_constant_batch_size_like(src, [-1, 1], "int64", GO)
    outs = []
    for _ in range(max_len):
        emb = layers.embedding(tok, size=[V, E],
                               param_attr=fluid.ParamAttr(name="tgt_emb"))
        emb = layers.reshape(emb, [-1, E])
        h = _dec_step(emb, h, enc, src_lens)
        logit = _logits_of(h)
        tok = layers.reshape(layers.argmax(logit, axis=-1), [-1, 1])
        outs.append(tok)
    return layers.concat(outs, axis=1)  # [B, max_len]


def _build_beam(max_len, beam):
    src = layers.data("src", shape=[TS], dtype="int64")
    src_lens = layers.data("src_lens", shape=[], dtype="int32")
    enc, h0 = _encoder(src, src_lens)  # [B,T,H], [B,H]

    # tile encoder state over beams: [B,T,H] -> [B*beam,T,H]
    enc_t = layers.reshape(
        layers.expand(layers.unsqueeze(enc, [1]), [1, beam, 1, 1]),
        [-1, TS, H])
    lens_t = layers.reshape(
        layers.expand(layers.unsqueeze(src_lens, [1]), [1, beam]), [-1])
    h = layers.reshape(
        layers.expand(layers.unsqueeze(h0, [1]), [1, beam, 1]), [-1, H])

    pre_ids = layers.fill_constant_batch_size_like(h0, [-1, beam], "int64", GO)
    # beam 0 live, others -inf so step 0 has no duplicates
    neg = layers.fill_constant_batch_size_like(
        h0, [-1, beam - 1], "float32", -1e9)
    zero = layers.fill_constant_batch_size_like(h0, [-1, 1], "float32", 0.0)
    pre_scores = layers.concat([zero, neg], axis=1)

    ids_steps, parent_steps = [], []
    for _ in range(max_len):
        emb = layers.embedding(layers.reshape(pre_ids, [-1, 1]),
                               size=[V, E],
                               param_attr=fluid.ParamAttr(name="tgt_emb"))
        emb = layers.reshape(emb, [-1, E])
        h = _dec_step(emb, h, enc_t, lens_t)
        logp = layers.log_softmax(_logits_of(h))          # [B*beam, V]
        logp = layers.reshape(logp, [-1, beam, V])
        acc = layers.elementwise_add(
            logp, layers.unsqueeze(pre_scores, [2]))       # accumulated
        sel_ids, sel_scores, parents = layers.beam_search(
            pre_ids, pre_scores, acc, beam_size=beam, end_id=EOS)
        # reorder hidden by parent beam: one_hot(parent) @ h
        oh = layers.cast(layers.one_hot(parents, beam), "float32")  # [B,b,b]
        h = layers.matmul(oh, layers.reshape(h, [-1, beam, H]))
        h = layers.reshape(h, [-1, H])
        pre_ids, pre_scores = sel_ids, sel_scores
        ids_steps.append(layers.unsqueeze(sel_ids, [0]))
        parent_steps.append(layers.unsqueeze(parents, [0]))
    ids = layers.concat(ids_steps, axis=0)        # [T, B, beam]
    parents = layers.concat(parent_steps, axis=0)
    sent_ids, sent_scores = layers.beam_search_decode(ids, parents,
                                                      pre_scores)
    return sent_ids, sent_scores


class TestBookSeq2Seq:
    def test_train_decode_saveload(self, rng):
        B, steps = 32, 300
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            loss = _build_train()
            AdamOptimizer(learning_rate=5e-3).minimize(loss)

        exe = fluid.Executor()
        exe.run(startup)
        first = last = None
        for i in range(steps):
            src, lens, tin, tout, tlens = _batch(rng, B)
            l, = exe.run(main, feed={
                "src": src, "src_lens": lens, "tgt_in": tin,
                "tgt_out": tout, "tgt_lens": tlens}, fetch_list=[loss])
            if first is None:
                first = float(l)
            last = float(l)
        assert np.isfinite(last)
        assert last < first * 0.7, (
            "seq2seq loss did not decrease: %.4f -> %.4f" % (first, last))

        # save -> fresh scope -> load -> greedy + beam decode
        with tempfile.TemporaryDirectory() as d:
            fluid.io.save_persistables(exe, d, main_program=main)

            infer = fluid.Program()
            istart = fluid.Program()
            with fluid.program_guard(infer, istart):
                greedy = _build_greedy(max_len=TD)
            exe.run(istart)
            fluid.io.load_persistables(exe, d, main_program=infer)
            src, lens, _tin, tout, _tl = _batch(rng, 4)
            g, = exe.run(infer, feed={"src": src, "src_lens": lens},
                         fetch_list=[greedy])
            assert g.shape == (4, TD)
            assert ((g >= 0) & (g < V)).all()
            # trained model should reproduce a good chunk of the reversal
            valid = tout[:, :-1] != 0
            acc = (g[:, :valid.shape[1]] == tout[:, :-1])[valid].mean()
            assert acc > 0.5, "greedy decode accuracy %.2f too low" % acc

            beam_prog = fluid.Program()
            bstart = fluid.Program()
            with fluid.program_guard(beam_prog, bstart):
                sent_ids, sent_scores = _build_beam(max_len=TD, beam=3)
            exe.run(bstart)
            fluid.io.load_persistables(exe, d, main_program=beam_prog)
            si, ss = exe.run(beam_prog,
                             feed={"src": src, "src_lens": lens},
                             fetch_list=[sent_ids, sent_scores])
            assert si.shape == (4, 3, TD)
            # best beam should be at least as good as greedy on average
            assert np.isfinite(ss).all()
            b0 = si[:, 0, :]
            bacc = (b0[:, :valid.shape[1]] == tout[:, :-1])[valid].mean()
            assert bacc >= acc - 0.1, (
                "beam-0 accuracy %.2f far below greedy %.2f" % (bacc, acc))
