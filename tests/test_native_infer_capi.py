"""C-ABI inference surface (reference `inference/capi/c_api.cc` +
`go/paddle/predictor.go` capability): build libpaddle_tpu_capi.so and a
pure-C client, serve the MNIST book model, and match the Python
Predictor's outputs bit-for-bit."""

import os
import shutil
import struct
import subprocess
import sysconfig

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.optimizer import AdamOptimizer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "paddle_tpu", "native")


def _embed_flags():
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION")
    return (["-I%s" % inc, "-I%s" % NATIVE],
            ["-L%s" % libdir, "-lpython%s" % ver, "-ldl", "-lm"])


def _save_mnist_model(tmp_path):
    from test_book_mnist import lenet5, make_synthetic_digits

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[1, 28, 28])
        label = layers.data("label", shape=[1], dtype="int64")
        avg_loss, acc, logits = lenet5(img, label)
        infer_prog = main.clone(for_test=True)
        AdamOptimizer(1e-3).minimize(avg_loss)
    exe = fluid.Executor()
    scope = fluid.Scope()
    imgs, labels = make_synthetic_digits(128)
    with fluid.scope_guard(scope):
        exe.run(startup)
        for i in range(0, 128, 32):
            exe.run(main, feed={"img": imgs[i:i + 32],
                                "label": labels[i:i + 32]},
                    fetch_list=[avg_loss])
        model_dir = str(tmp_path / "model")
        fluid.io.save_inference_model(
            model_dir, ["img"],
            [infer_prog.global_block.var(logits.name)], exe, infer_prog)
    return model_dir, imgs[:4]


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")
def test_capi_client_matches_python_predictor(tmp_path):
    incs, libs = _embed_flags()
    so = str(tmp_path / "libpaddle_tpu_capi.so")
    b1 = subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC",
         os.path.join(NATIVE, "infer_capi.cc")] + incs + libs + ["-o", so],
        capture_output=True, text=True, timeout=300)
    assert b1.returncode == 0, b1.stderr
    client = str(tmp_path / "infer_demo")
    b2 = subprocess.run(
        ["gcc", "-O2", os.path.join(NATIVE, "infer_demo.c"),
         "-I%s" % NATIVE, so, "-Wl,-rpath," + str(tmp_path), "-o", client]
        + libs, capture_output=True, text=True, timeout=300)
    assert b2.returncode == 0, b2.stderr

    model_dir, x = _save_mnist_model(tmp_path)

    # python-side reference outputs
    from paddle_tpu.inference import AnalysisConfig, create_predictor

    pred = create_predictor(AnalysisConfig(model_dir))
    want, = pred.run([x])

    # the C client reads one tensor from a flat binary file
    inp = str(tmp_path / "input.bin")
    with open(inp, "wb") as f:
        f.write(struct.pack("<q", x.ndim))
        for d in x.shape:
            f.write(struct.pack("<q", d))
        f.write(np.ascontiguousarray(x, np.float32).tobytes())

    env = dict(os.environ)
    # CPU-only subprocess: drop the axon TPU site hook entirely — its
    # register() initializes the tunnel plugin during `import jax`
    # regardless of JAX_PLATFORMS, so a stuck/absent tunnel would hang
    # this test even though it never uses the chip
    env["PYTHONPATH"] = REPO
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # conftest pins matmul precision to full f32 in THIS process; the
    # client process must match or conv outputs differ at the 5e-3 level
    env["JAX_DEFAULT_MATMUL_PRECISION"] = "highest"
    run = subprocess.run([client, model_dir, inp], capture_output=True,
                         text=True, timeout=600, env=env)
    assert run.returncode == 0, (run.stdout, run.stderr)
    assert "C inference demo OK" in run.stdout
    assert "second run ok" in run.stdout
    assert "inputs 1: img" in run.stdout

    out_line = next(l for l in run.stdout.splitlines()
                    if l.startswith("out 0 shape"))
    toks = out_line.split()
    sh_end = toks.index("data")
    shape = tuple(int(t) for t in toks[3:sh_end])
    vals = np.array([float(t) for t in toks[sh_end + 1:]],
                    np.float32).reshape(shape)
    assert shape == want.shape
    np.testing.assert_allclose(vals, want, rtol=1e-4, atol=1e-5)
