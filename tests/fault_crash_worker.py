"""Mid-commit-crash worker: dies by SIGKILL INSIDE the commit rename.

Driven by test_fault_injection.py: the FaultyFS kills the process on
the first `mv` — the tmp directory is fully serialized, the rename that
would make it a checkpoint never happens.  A second invocation without
the fault must find only the prior commit (atomicity across a crash at
the worst possible instant)."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    from paddle_tpu.incubate.checkpoint.checkpoint_saver import (
        CheckpointSaver,
        StateSnapshot,
    )
    from paddle_tpu.incubate.fault import FaultPlan

    root = sys.argv[1]
    value = float(sys.argv[2])
    plan = FaultPlan.from_env(rank=0, generation=0)
    saver = CheckpointSaver(root=root, fs=plan.wrap_fs(),
                            max_num_checkpoints=0)
    snap = StateSnapshot({"a": np.full((4,), value, np.float32)})
    n = saver.save_checkpoint([snap], epoch=0)
    print("committed checkpoint_%d" % n)
    return 0


if __name__ == "__main__":
    sys.exit(main())
