"""CRF / CTC / edit-distance / chunk-eval op tests: brute-force numpy
oracles + finite-difference gradients (reference OpTest pattern,
`tests/unittests/test_linear_chain_crf_op.py`, `test_crf_decoding_op.py`,
`test_chunk_eval_op.py`, `test_edit_distance_op.py`, `test_warpctc_op.py`)."""

import itertools

import numpy as np
import pytest

from op_test import check_grad, run_single_op


# ---------------------------------------------------------------------------
# brute-force oracles
# ---------------------------------------------------------------------------

def _crf_enumerate(emission, transition, lens):
    """logZ and best path by enumerating ALL tag sequences (tiny N, T)."""
    B, T, N = emission.shape
    start, end, trans = transition[0], transition[1], transition[2:]
    logZ = np.zeros(B)
    best_paths = np.zeros((B, T), np.int64)
    for b in range(B):
        L = int(lens[b])
        scores = []
        paths = list(itertools.product(range(N), repeat=L))
        for path in paths:
            s = start[path[0]] + end[path[L - 1]]
            for t in range(L):
                s += emission[b, t, path[t]]
            for t in range(1, L):
                s += trans[path[t - 1], path[t]]
            scores.append(s)
        scores = np.array(scores)
        logZ[b] = np.log(np.sum(np.exp(scores - scores.max()))) + scores.max()
        best = paths[int(np.argmax(scores))]
        best_paths[b, :L] = best
    return logZ, best_paths


def _crf_gold_score(emission, transition, label, lens):
    B, T, N = emission.shape
    start, end, trans = transition[0], transition[1], transition[2:]
    out = np.zeros(B)
    for b in range(B):
        L = int(lens[b])
        s = start[label[b, 0]] + end[label[b, L - 1]]
        for t in range(L):
            s += emission[b, t, label[b, t]]
        for t in range(1, L):
            s += trans[label[b, t - 1], label[b, t]]
        out[b] = s
    return out


def _levenshtein(a, b):
    d = np.zeros((len(a) + 1, len(b) + 1))
    d[:, 0] = np.arange(len(a) + 1)
    d[0, :] = np.arange(len(b) + 1)
    for i in range(1, len(a) + 1):
        for j in range(1, len(b) + 1):
            d[i, j] = min(d[i - 1, j] + 1, d[i, j - 1] + 1,
                          d[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
    return d[len(a), len(b)]


def _ctc_enumerate(logits, llen, label, label_len, blank=0):
    """-log P(label) by enumerating every frame path (tiny T, C)."""
    B, T, C = logits.shape
    out = np.zeros(B)
    for b in range(B):
        L = int(llen[b])
        lab = tuple(label[b, : int(label_len[b])])
        p = np.exp(logits[b, :L] - logits[b, :L].max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        total = 0.0
        for path in itertools.product(range(C), repeat=L):
            # collapse: remove repeats then blanks
            col = []
            prev = None
            for s in path:
                if s != prev:
                    col.append(s)
                prev = s
            col = tuple(s for s in col if s != blank)
            if col == lab:
                pr = 1.0
                for t, s in enumerate(path):
                    pr *= p[t, s]
                total += pr
        out[b] = -np.log(total)
    return out


def _chunks_of(tags, scheme, num_types):
    """Independent per-sequence chunk extractor (sequential python loop)."""
    n_tag = {"plain": 1, "IOB": 2, "IOE": 2, "IOBES": 4}[scheme]
    other = num_types * n_tag
    chunks = []
    start = cur_type = None

    def close(end_t):
        nonlocal start, cur_type
        if start is not None:
            chunks.append((start, end_t, cur_type))
        start, cur_type = None, None

    for t, tag in enumerate(tags):
        inside = tag < other
        if not inside:
            close(t - 1)
            continue
        ty, tt = tag // n_tag, tag % n_tag
        if scheme == "plain":
            close(t - 1)
            chunks.append((t, t, ty))
        elif scheme == "IOB":  # B=0, I=1
            if tt == 0 or start is None or cur_type != ty:
                close(t - 1)
                start, cur_type = t, ty
        elif scheme == "IOE":  # I=0, E=1
            if start is None or cur_type != ty:
                close(t - 1)
                start, cur_type = t, ty
            if tt == 1:
                close(t)
        else:  # IOBES: B=0, I=1, E=2, S=3
            if tt in (0, 3) or start is None or cur_type != ty:
                close(t - 1)
                start, cur_type = t, ty
            if tt in (2, 3):
                close(t)
    close(len(tags) - 1)
    return set(chunks)


# ---------------------------------------------------------------------------
# linear_chain_crf
# ---------------------------------------------------------------------------

def test_linear_chain_crf_vs_enumeration(rng):
    B, T, N = 3, 4, 3
    emission = rng.randn(B, T, N).astype(np.float32)
    transition = (0.3 * rng.randn(N + 2, N)).astype(np.float32)
    lens = np.array([4, 2, 3], np.int64)
    label = rng.randint(0, N, (B, T)).astype(np.int64)

    logZ, _ = _crf_enumerate(emission, transition, lens)
    gold = _crf_gold_score(emission, transition, label, lens)
    expect = (logZ - gold)[:, None]

    outs, _ = run_single_op(
        "linear_chain_crf",
        {"Emission": emission, "Transition": transition,
         "Label": label, "Length": lens},
        {}, ["LogLikelihood", "Alpha"],
    )
    np.testing.assert_allclose(outs["LogLikelihood"], expect,
                               rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_linear_chain_crf_grad(rng):
    B, T, N = 2, 3, 3
    inputs = {
        "Emission": rng.randn(B, T, N).astype(np.float64),
        "Transition": (0.3 * rng.randn(N + 2, N)).astype(np.float64),
        "Label": rng.randint(0, N, (B, T)).astype(np.int64),
        "Length": np.array([3, 2], np.int64),
    }
    check_grad("linear_chain_crf", inputs, {},
               ["LogLikelihood", "Alpha"], ["Emission", "Transition"],
               rtol=1e-2, atol=1e-3)


# ---------------------------------------------------------------------------
# crf_decoding
# ---------------------------------------------------------------------------

def test_crf_decoding_vs_enumeration(rng):
    B, T, N = 4, 4, 3
    emission = rng.randn(B, T, N).astype(np.float32)
    transition = (0.5 * rng.randn(N + 2, N)).astype(np.float32)
    lens = np.array([4, 3, 2, 1], np.int64)
    _, best = _crf_enumerate(emission, transition, lens)

    outs, _ = run_single_op(
        "crf_decoding",
        {"Emission": emission, "Transition": transition, "Length": lens},
        {}, ["ViterbiPath"],
    )
    np.testing.assert_array_equal(outs["ViterbiPath"], best)


def test_crf_decoding_with_label_marks(rng):
    B, T, N = 2, 3, 3
    emission = rng.randn(B, T, N).astype(np.float32)
    transition = (0.5 * rng.randn(N + 2, N)).astype(np.float32)
    lens = np.array([3, 2], np.int64)
    _, best = _crf_enumerate(emission, transition, lens)
    label = best.copy()
    label[0, 0] = (label[0, 0] + 1) % N  # one wrong position

    outs, _ = run_single_op(
        "crf_decoding",
        {"Emission": emission, "Transition": transition,
         "Label": label, "Length": lens},
        {}, ["ViterbiPath"],
    )
    marks = outs["ViterbiPath"]
    assert marks[0, 0] == 0
    assert marks[0, 1] == 1 and marks[0, 2] == 1
    assert marks[1, 0] == 1 and marks[1, 1] == 1
    assert marks[1, 2] == 0  # padding


# ---------------------------------------------------------------------------
# chunk_eval
# ---------------------------------------------------------------------------

def _chunk_oracle(inf, lab, lens, scheme, num_types):
    n_inf = n_lab = n_corr = 0
    for b in range(inf.shape[0]):
        L = int(lens[b])
        ci = _chunks_of(inf[b, :L], scheme, num_types)
        cl = _chunks_of(lab[b, :L], scheme, num_types)
        n_inf += len(ci)
        n_lab += len(cl)
        n_corr += len(ci & cl)
    return n_inf, n_lab, n_corr


@pytest.mark.parametrize("scheme", ["IOB", "IOE", "IOBES", "plain"])
def test_chunk_eval_vs_oracle(rng, scheme):
    num_types = 3
    n_tag = {"plain": 1, "IOB": 2, "IOE": 2, "IOBES": 4}[scheme]
    B, T = 4, 10
    hi = num_types * n_tag + 1  # include the "other" tag
    inf = rng.randint(0, hi, (B, T)).astype(np.int64)
    lab = rng.randint(0, hi, (B, T)).astype(np.int64)
    lens = rng.randint(1, T + 1, (B,)).astype(np.int64)

    n_inf, n_lab, n_corr = _chunk_oracle(inf, lab, lens, scheme, num_types)
    outs, _ = run_single_op(
        "chunk_eval",
        {"Inference": inf, "Label": lab, "Length": lens},
        {"chunk_scheme": scheme, "num_chunk_types": num_types},
        ["Precision", "Recall", "F1-Score", "NumInferChunks",
         "NumLabelChunks", "NumCorrectChunks"],
    )
    assert int(outs["NumInferChunks"][0]) == n_inf
    assert int(outs["NumLabelChunks"][0]) == n_lab
    assert int(outs["NumCorrectChunks"][0]) == n_corr
    if n_inf and n_lab:
        p = n_corr / n_inf
        r = n_corr / n_lab
        np.testing.assert_allclose(outs["Precision"][0], p, rtol=1e-5)
        np.testing.assert_allclose(outs["Recall"][0], r, rtol=1e-5)
        if p + r:
            np.testing.assert_allclose(
                outs["F1-Score"][0], 2 * p * r / (p + r), rtol=1e-5)


def test_chunk_eval_excluded_types(rng):
    """excluded_chunk_types drops those chunks from all three counts."""
    num_types = 3
    B, T = 4, 10
    hi = num_types * 2 + 1
    inf = rng.randint(0, hi, (B, T)).astype(np.int64)
    lab = rng.randint(0, hi, (B, T)).astype(np.int64)
    lens = rng.randint(1, T + 1, (B,)).astype(np.int64)
    excl = [1]

    def drop(chunks):
        return {c for c in chunks if c[2] not in excl}

    n_inf = n_lab = n_corr = 0
    for b in range(B):
        L = int(lens[b])
        ci = drop(_chunks_of(inf[b, :L], "IOB", num_types))
        cl = drop(_chunks_of(lab[b, :L], "IOB", num_types))
        n_inf += len(ci)
        n_lab += len(cl)
        n_corr += len(ci & cl)

    outs, _ = run_single_op(
        "chunk_eval",
        {"Inference": inf, "Label": lab, "Length": lens},
        {"chunk_scheme": "IOB", "num_chunk_types": num_types,
         "excluded_chunk_types": excl},
        ["Precision", "Recall", "F1-Score", "NumInferChunks",
         "NumLabelChunks", "NumCorrectChunks"],
    )
    assert int(outs["NumInferChunks"][0]) == n_inf
    assert int(outs["NumLabelChunks"][0]) == n_lab
    assert int(outs["NumCorrectChunks"][0]) == n_corr


def test_chunk_eval_identical_sequences(rng):
    """inference == label => precision = recall = f1 = 1."""
    B, T, num_types = 3, 8, 2
    lab = rng.randint(0, num_types * 2 + 1, (B, T)).astype(np.int64)
    lens = np.array([8, 5, 6], np.int64)
    outs, _ = run_single_op(
        "chunk_eval",
        {"Inference": lab, "Label": lab, "Length": lens},
        {"chunk_scheme": "IOB", "num_chunk_types": num_types},
        ["Precision", "Recall", "F1-Score", "NumInferChunks",
         "NumLabelChunks", "NumCorrectChunks"],
    )
    if int(outs["NumLabelChunks"][0]):
        assert float(outs["Precision"][0]) == 1.0
        assert float(outs["Recall"][0]) == 1.0
        assert float(outs["F1-Score"][0]) == pytest.approx(1.0)


# ---------------------------------------------------------------------------
# edit_distance
# ---------------------------------------------------------------------------

def test_edit_distance_vs_oracle(rng):
    B, T1, T2 = 5, 6, 7
    hyps = rng.randint(1, 5, (B, T1)).astype(np.int64)
    refs = rng.randint(1, 5, (B, T2)).astype(np.int64)
    hlen = rng.randint(1, T1 + 1, (B,)).astype(np.int64)
    rlen = rng.randint(1, T2 + 1, (B,)).astype(np.int64)
    expect = np.array([
        _levenshtein(hyps[b, : hlen[b]], refs[b, : rlen[b]])
        for b in range(B)
    ])[:, None]

    outs, _ = run_single_op(
        "edit_distance",
        {"Hyps": hyps, "HypsLength": hlen, "Refs": refs, "RefsLength": rlen},
        {"normalized": False}, ["Out", "SequenceNum"],
    )
    np.testing.assert_allclose(outs["Out"], expect, rtol=1e-6)
    assert int(outs["SequenceNum"][0]) == B

    outs_n, _ = run_single_op(
        "edit_distance",
        {"Hyps": hyps, "HypsLength": hlen, "Refs": refs, "RefsLength": rlen},
        {"normalized": True}, ["Out", "SequenceNum"],
    )
    np.testing.assert_allclose(
        outs_n["Out"], expect / rlen[:, None], rtol=1e-6)


# ---------------------------------------------------------------------------
# warpctc (CTC loss)
# ---------------------------------------------------------------------------

def test_warpctc_vs_enumeration(rng):
    B, T, C, Lmax = 3, 4, 3, 2
    logits = rng.randn(B, T, C).astype(np.float64)
    llen = np.array([4, 3, 4], np.int64)
    label = rng.randint(1, C, (B, Lmax)).astype(np.int64)
    label_len = np.array([2, 1, 2], np.int64)

    expect = _ctc_enumerate(logits, llen, label, label_len)[:, None]
    outs, _ = run_single_op(
        "warpctc",
        {"Logits": logits, "LogitsLength": llen,
         "Label": label, "LabelLength": label_len},
        {"blank": 0}, ["Loss"],
    )
    np.testing.assert_allclose(outs["Loss"], expect, rtol=1e-5, atol=1e-6)


def test_warpctc_empty_label(rng):
    """label_len == 0: loss = -log P(all-blank path), counted once."""
    B, T, C = 1, 3, 3
    logits = rng.randn(B, T, C).astype(np.float64)
    llen = np.array([3], np.int64)
    label = np.zeros((B, 2), np.int64)
    label_len = np.array([0], np.int64)
    p = np.exp(logits[0] - logits[0].max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    expect = -np.log(p[0, 0] * p[1, 0] * p[2, 0])
    outs, _ = run_single_op(
        "warpctc",
        {"Logits": logits, "LogitsLength": llen,
         "Label": label, "LabelLength": label_len},
        {"blank": 0}, ["Loss"],
    )
    np.testing.assert_allclose(outs["Loss"][0, 0], expect, rtol=1e-6)


@pytest.mark.slow
def test_warpctc_grad(rng):
    B, T, C, Lmax = 2, 3, 3, 2
    inputs = {
        "Logits": rng.randn(B, T, C).astype(np.float64),
        "LogitsLength": np.array([3, 2], np.int64),
        "Label": rng.randint(1, C, (B, Lmax)).astype(np.int64),
        "LabelLength": np.array([2, 1], np.int64),
    }
    check_grad("warpctc", inputs, {"blank": 0}, ["Loss"], ["Logits"],
               rtol=1e-2, atol=1e-3)
