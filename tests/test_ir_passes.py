"""IR pass framework (reference ir/pass.h + graph_pattern_detector.h):
registry, dead-op elimination, pattern fusion — applied to real Programs
and verified by execution."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import ir, layers


def _op_types(prog):
    return [op.type for op in prog.current_block().ops]


def test_pass_registry_and_unknown():
    p = ir.get_pass("dead_op_elimination")
    assert isinstance(p, ir.Pass)
    with pytest.raises(KeyError):
        ir.get_pass("no_such_pass")


def test_dead_op_elimination_keeps_semantics():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 4], append_batch_size=False)
        kept = layers.fc(x, 3, param_attr="irp_fc.w")
        dead = layers.relu(layers.fc(x, 7))     # nothing consumes this
        out = layers.reduce_sum(kept)
    n_before = len(_op_types(main))
    ir.apply_passes(main, [ir.get_pass("dead_op_elimination")
                           .set("keep", [out.name])])
    types = _op_types(main)
    assert len(types) < n_before
    assert "relu" not in types                   # dead branch removed
    exe = fluid.Executor()
    xv = np.ones((2, 4), np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (got,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    assert np.isfinite(got).all()


def test_batch_norm_act_fuse_matches_unfused():
    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 9
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[-1, 6], append_batch_size=False)
            h = layers.batch_norm(layers.fc(x, 6, param_attr="irf.w"),
                                  act="relu")
            out = layers.reduce_sum(h)
        return main, startup, out

    xv = np.random.RandomState(0).randn(4, 6).astype(np.float32)

    def run(prog, startup, out):
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            (v,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
        return float(v)

    m0, s0, o0 = build()
    ref = run(m0, s0, o0)

    m1, s1, o1 = build()
    assert "relu" in _op_types(m1)
    ir.apply_passes(m1, ["batch_norm_act_fuse"])
    types = _op_types(m1)
    assert "fused_batch_norm_act" in types and "relu" not in types
    got = run(m1, s1, o1)
    assert got == pytest.approx(ref, rel=1e-5)
