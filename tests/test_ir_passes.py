"""IR pass framework (reference ir/pass.h + graph_pattern_detector.h):
registry, dead-op elimination, pattern fusion — applied to real Programs
and verified by execution."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import ir, layers


def _op_types(prog):
    return [op.type for op in prog.current_block().ops]


def test_pass_registry_and_unknown():
    p = ir.get_pass("dead_op_elimination")
    assert isinstance(p, ir.Pass)
    with pytest.raises(KeyError):
        ir.get_pass("no_such_pass")


def test_dead_op_elimination_keeps_semantics():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 4], append_batch_size=False)
        kept = layers.fc(x, 3, param_attr="irp_fc.w")
        dead = layers.relu(layers.fc(x, 7))     # nothing consumes this
        out = layers.reduce_sum(kept)
    n_before = len(_op_types(main))
    ir.apply_passes(main, [ir.get_pass("dead_op_elimination")
                           .set("keep", [out.name])])
    types = _op_types(main)
    assert len(types) < n_before
    assert "relu" not in types                   # dead branch removed
    exe = fluid.Executor()
    xv = np.ones((2, 4), np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (got,) = exe.run(main, feed={"x": xv}, fetch_list=[out])
    assert np.isfinite(got).all()


def test_batch_norm_act_fuse_matches_unfused():
    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 9
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[-1, 6], append_batch_size=False)
            h = layers.batch_norm(layers.fc(x, 6, param_attr="irf.w"),
                                  act="relu")
            out = layers.reduce_sum(h)
        return main, startup, out

    xv = np.random.RandomState(0).randn(4, 6).astype(np.float32)

    def run(prog, startup, out):
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            (v,) = exe.run(prog, feed={"x": xv}, fetch_list=[out])
        return float(v)

    m0, s0, o0 = build()
    ref = run(m0, s0, o0)

    m1, s1, o1 = build()
    assert "relu" in _op_types(m1)
    ir.apply_passes(m1, ["batch_norm_act_fuse"])
    types = _op_types(m1)
    assert "fused_batch_norm_act" in types and "relu" not in types
    got = run(m1, s1, o1)
    assert got == pytest.approx(ref, rel=1e-5)


# ---------------------------------------------------------------------------
# MatmulBiasActFusePass: matmul/mul -> add -> act => matmul_bias_act
# ---------------------------------------------------------------------------


def _run_clone_parity(main, startup, fetch, feed, pipeline):
    """Apply `pipeline` to a verified CLONE and run original + clone on
    ONE scope (params initialized once, shared by name) — the parity
    harness every pass test shares."""
    clone = ir.clone_and_apply(main, pipeline, verify=True)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (ref,) = exe.run(main, feed=feed, fetch_list=[fetch])
        (got,) = exe.run(clone, feed=feed, fetch_list=[fetch.name])
    return clone, np.asarray(ref), np.asarray(got)


@pytest.mark.parametrize("act", ["gelu", "tanh", "relu"])
def test_matmul_bias_act_fuse_matches_unfused(act):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 6, 16], append_batch_size=False)
        w = layers.create_parameter([16, 32], name="mbf.%s.w" % act)
        b = layers.create_parameter([32], name="mbf.%s.b" % act)
        h = layers.elementwise_add(
            layers.mul(x, w, x_num_col_dims=2), b, axis=2)
        out = getattr(layers, act)(h)
    xv = np.random.RandomState(0).randn(4, 6, 16).astype(np.float32)
    clone, ref, got = _run_clone_parity(
        main, startup, out, {"x": xv}, ["matmul_bias_act_fuse"])
    types = [op.type for op in clone.global_block.ops]
    assert "matmul_bias_act" in types
    assert "elementwise_add" not in types and act not in types
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_matmul_bias_act_fuse_matmul_variant_with_transpose():
    # matmul-style source op: transpose_Y attr must survive the rewrite
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 16], append_batch_size=False)
        w = layers.create_parameter([32, 16], name="mbm.w")
        b = layers.create_parameter([32], name="mbm.b")
        out = layers.gelu(layers.matmul(x, w, transpose_y=True) + b)
    xv = np.random.RandomState(1).randn(8, 16).astype(np.float32)
    clone, ref, got = _run_clone_parity(
        main, startup, out, {"x": xv}, ["matmul_bias_act_fuse"])
    fused = [op for op in clone.global_block.ops
             if op.type == "matmul_bias_act"]
    assert fused and fused[0].attrs.get(
        "transpose_Y", fused[0].attrs.get("transpose_y"))
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_matmul_bias_act_fuse_through_reshape():
    """The reshape-interposed chain the BERT FFN can emit: the epilogue
    commutes with a last-dim-preserving reshape, so the act moves into
    the matmul and the reshape slides after it."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 6, 16], append_batch_size=False)
        w = layers.create_parameter([16, 32], name="mbr.w")
        b = layers.create_parameter([32], name="mbr.b")
        mm = layers.mul(x, w, x_num_col_dims=2)        # [4, 6, 32]
        r = layers.reshape(mm, [24, 32])               # keeps last dim
        out = layers.gelu(layers.elementwise_add(r, b, axis=1))
    xv = np.random.RandomState(2).randn(4, 6, 16).astype(np.float32)
    clone, ref, got = _run_clone_parity(
        main, startup, out, {"x": xv}, ["matmul_bias_act_fuse"])
    types = [op.type for op in clone.global_block.ops]
    assert "matmul_bias_act" in types and "reshape2" in types
    assert "elementwise_add" not in types and "gelu" not in types
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_matmul_bias_act_fuse_skips_reused_intermediate():
    # bias-add output consumed twice: fusing would change/recompute it
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 16], append_batch_size=False)
        w = layers.create_parameter([16, 32], name="mbs.w")
        b = layers.create_parameter([32], name="mbs.b")
        h = layers.elementwise_add(layers.mul(x, w), b, axis=1)
        layers.gelu(h)
        layers.reduce_sum(h)
    clone = ir.clone_and_apply(main, ["matmul_bias_act_fuse"],
                               verify=True)
    assert "matmul_bias_act" not in [op.type
                                     for op in clone.global_block.ops]


def test_matmul_bias_act_fuse_skips_non_vector_bias():
    # a full-tensor add is not a bias epilogue: left alone
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 16], append_batch_size=False)
        y2 = layers.data("y2", shape=[8, 32], append_batch_size=False)
        w = layers.create_parameter([16, 32], name="mbv.w")
        layers.gelu(layers.elementwise_add(layers.mul(x, w), y2))
    clone = ir.clone_and_apply(main, ["matmul_bias_act_fuse"],
                               verify=True)
    assert "matmul_bias_act" not in [op.type
                                     for op in clone.global_block.ops]


# ---------------------------------------------------------------------------
# TransposeFoldPass
# ---------------------------------------------------------------------------


def test_transpose_fold_adjacent_inverse_pair():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[4, 8, 16], append_batch_size=False)
        t2 = layers.transpose(layers.transpose(a, [0, 2, 1]), [0, 2, 1])
        out = layers.reduce_sum(t2 * 2.0)
    av = np.random.RandomState(3).randn(4, 8, 16).astype(np.float32)
    clone, ref, got = _run_clone_parity(
        main, startup, out, {"a": av}, ["transpose_fold"])
    types = [op.type for op in clone.global_block.ops]
    assert "transpose2" not in types          # pair cancelled
    assert "assign" in types                  # downstream name kept
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_transpose_fold_keeps_non_inverse_pair():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[4, 8, 16], append_batch_size=False)
        t = layers.transpose(layers.transpose(a, [1, 0, 2]), [0, 2, 1])
        layers.reduce_sum(t)
    clone = ir.clone_and_apply(main, ["transpose_fold"], verify=True)
    assert [op.type for op in clone.global_block.ops].count(
        "transpose2") == 2


def test_transpose_fold_flash_attention_layout():
    """transpose([0,2,1,3]) x3 -> flash_attention(BHSD) ->
    transpose([0,2,1,3]) folds to ONE flash_attention(BSHD) op — the
    model never materializes [B,S,H,D]<->[B,H,S,D]."""
    from paddle_tpu.fluid.layers.common import append_simple_op

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        q = layers.data("q", shape=[2, 256, 2, 64], append_batch_size=False)
        k = layers.data("k", shape=[2, 256, 2, 64], append_batch_size=False)
        v = layers.data("v", shape=[2, 256, 2, 64], append_batch_size=False)
        ctx = append_simple_op(
            "flash_attention",
            {"Q": layers.transpose(q, [0, 2, 1, 3]),
             "K": layers.transpose(k, [0, 2, 1, 3]),
             "V": layers.transpose(v, [0, 2, 1, 3])},
            {"scale": 64 ** -0.5, "causal": False, "layout": "BHSD"})
        out = layers.reduce_sum(layers.transpose(ctx, [0, 2, 1, 3]))
    rng = np.random.RandomState(4)
    feed = {n: rng.randn(2, 256, 2, 64).astype(np.float32) * 0.1
            for n in "qkv"}
    clone, ref, got = _run_clone_parity(
        main, startup, out, feed, ["transpose_fold"])
    types = [op.type for op in clone.global_block.ops]
    assert "transpose2" not in types
    flash = [op for op in clone.global_block.ops
             if op.type == "flash_attention"][0]
    assert flash.attrs["layout"] == "BSHD"
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_transpose_fold_into_matmul_flag():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[8, 16], append_batch_size=False)
        c = layers.data("c", shape=[8, 32], append_batch_size=False)
        out = layers.reduce_sum(
            layers.matmul(layers.transpose(a, [1, 0]), c))
    feed = {"a": np.random.RandomState(5).randn(8, 16).astype(np.float32),
            "c": np.random.RandomState(6).randn(8, 32).astype(np.float32)}
    clone, ref, got = _run_clone_parity(
        main, startup, out, feed, ["transpose_fold"])
    types = [op.type for op in clone.global_block.ops]
    assert "transpose2" not in types
    mm = [op for op in clone.global_block.ops if op.type == "matmul"][0]
    assert mm.attrs.get("transpose_X") is True
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_transpose_fold_keeps_fetched_intermediate_produced():
    """The cancelled pair's OUTPUT name may be a fetch target: the
    assign rewrite must keep it produced (missing-fetch stays green)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[4, 8], append_batch_size=False)
        t2 = layers.transpose(layers.transpose(a, [1, 0]), [1, 0])
        layers.reduce_sum(t2)
    clone = ir.clone_and_apply(main, ["transpose_fold"], verify=True)
    exe = fluid.Executor()
    av = np.random.RandomState(7).randn(4, 8).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (got,) = exe.run(clone, feed={"a": av}, fetch_list=[t2.name])
    np.testing.assert_allclose(got, av, rtol=0, atol=0)


def test_transpose_fold_flash_layout_shared_kv_transpose():
    """K and V fed from ONE transposed tensor (shared-KV attention):
    every read of the shared transpose's output is a Q/K/V slot of the
    same flash op, so the fold still fires."""
    from paddle_tpu.fluid.layers.common import append_simple_op

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        q = layers.data("q", shape=[2, 256, 2, 64], append_batch_size=False)
        kv = layers.data("kv", shape=[2, 256, 2, 64],
                         append_batch_size=False)
        kvt = layers.transpose(kv, [0, 2, 1, 3])
        ctx = append_simple_op(
            "flash_attention",
            {"Q": layers.transpose(q, [0, 2, 1, 3]), "K": kvt, "V": kvt},
            {"scale": 64 ** -0.5, "causal": False, "layout": "BHSD"})
        out = layers.reduce_sum(layers.transpose(ctx, [0, 2, 1, 3]))
    rng = np.random.RandomState(11)
    feed = {n: rng.randn(2, 256, 2, 64).astype(np.float32) * 0.1
            for n in ("q", "kv")}
    clone, ref, got = _run_clone_parity(
        main, startup, out, feed, ["transpose_fold"])
    types = [op.type for op in clone.global_block.ops]
    assert "transpose2" not in types
    flash = [op for op in clone.global_block.ops
             if op.type == "flash_attention"][0]
    assert flash.attrs["layout"] == "BSHD"
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
