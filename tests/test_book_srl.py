"""Book model 8/8: label_semantic_roles (reference
`tests/book/test_label_semantic_roles.py:1` — CoNLL05 SRL: 8 feature
embeddings, stacked bidirectional LSTM, CRF cost, Viterbi decode +
chunk_eval).  Padded-dense TPU layout: every feature is [B, T] int64 with
an explicit length array instead of LoD."""

import numpy as np

import paddle_tpu
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.layer_helper import ParamAttr

T_MAX = 18
FEATS = ["word", "ctx_n2", "ctx_n1", "ctx_0", "ctx_p1", "ctx_p2", "pred",
         "mark"]


def _pad_batch(batch):
    """9-slot conll05 examples -> dict of [B, T] arrays + length."""
    B = len(batch)
    arrs = {f: np.zeros((B, T_MAX), np.int64) for f in FEATS}
    label = np.zeros((B, T_MAX), np.int64)
    lens = np.zeros((B,), np.int64)
    for i, ex in enumerate(batch):
        L = min(len(ex[0]), T_MAX)
        lens[i] = L
        for j, f in enumerate(FEATS):
            arrs[f][i, :L] = ex[j][:L]
        label[i, :L] = ex[8][:L]
    feed = {f: arrs[f] for f in FEATS}
    feed["target"] = label
    feed["length"] = lens
    return feed


def _db_lstm(emb_dim=16, hidden=32, depth=2):
    """Scaled-down reference db_lstm: sum of feature embeddings -> stacked
    alternating-direction LSTMs -> per-position tag emissions."""
    from paddle_tpu.dataset import conll05

    word_n = conll05.WORD_VOCAB
    pred_n = conll05.PRED_VOCAB
    n_labels = len(conll05.label_dict())

    feats = {
        f: layers.data(f, shape=[-1, T_MAX], dtype="int64",
                       append_batch_size=False)
        for f in FEATS
    }
    length = layers.data("length", shape=[-1], dtype="int64",
                         append_batch_size=False)
    target = layers.data("target", shape=[-1, T_MAX], dtype="int64",
                         append_batch_size=False)

    embs = []
    for f in FEATS:
        vocab = {"pred": pred_n, "mark": 2}.get(f, word_n)
        embs.append(layers.embedding(feats[f], size=[vocab, emb_dim],
                                     param_attr="emb_%s" % f))
    hidden0 = layers.fc(layers.sums(embs), size=hidden * 4,
                        num_flatten_dims=2)
    inp = hidden0
    lstm, _ = layers.dynamic_lstm(inp, size=hidden * 4, seq_lens=length)
    for i in range(1, depth):
        mix = layers.fc(lstm, size=hidden * 4, num_flatten_dims=2)
        lstm, _ = layers.dynamic_lstm(
            mix, size=hidden * 4, seq_lens=length, is_reverse=(i % 2) == 1)
    emission = layers.fc(lstm, size=n_labels, num_flatten_dims=2)
    return emission, target, length


def test_label_semantic_roles(tmp_path):
    from paddle_tpu.dataset import conll05

    n_labels = len(conll05.label_dict())
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.program_guard(main, startup):
        emission, target, length = _db_lstm()
        crf_cost = layers.linear_chain_crf(
            emission, target, length,
            param_attr=ParamAttr(name="crfw"))
        avg_cost = layers.mean(crf_cost)
        # decode + chunk metrics on the SAME transition param (reference
        # crf_decoding(param_attr='crfw') + chunk_eval flow)
        decode = layers.crf_decoding(emission, length,
                                     param_attr=ParamAttr(name="crfw"))
        (prec, rec, f1, n_infer, n_label, n_correct) = layers.chunk_eval(
            decode, target, length, chunk_scheme="IOB",
            num_chunk_types=conll05.CHUNK_TYPES)
        test_prog = main.clone(for_test=True)
        fluid.optimizer.AdamOptimizer(learning_rate=1e-2).minimize(avg_cost)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    reader = paddle_tpu.batch(conll05.train(n=128), batch_size=16,
                              drop_last=True)
    losses = []
    for epoch in range(8):
        for batch in reader():
            (lv,) = exe.run(main, feed=_pad_batch(batch),
                            fetch_list=[avg_cost])
            losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

    # chunk F1 on held-out data should beat chance after training
    test_batch = list(conll05.test(n=32)())
    f1v, pv, rv = exe.run(
        test_prog, feed=_pad_batch(test_batch),
        fetch_list=[f1, prec, rec])[0:3]
    assert float(f1v[0]) > 0.3, (f1v, pv, rv)

    # save/load_inference_model round trip on the decode path
    path = str(tmp_path / "srl.model")
    feed_names = FEATS + ["length"]
    fluid.io.save_inference_model(path, feed_names, [decode], exe, main)
    scope2 = fluid.Scope()
    exe2 = fluid.Executor(fluid.CPUPlace())
    feed = _pad_batch(test_batch[:4])
    with fluid.scope_guard(scope2):
        prog, feeds, fetches = fluid.io.load_inference_model(path, exe2)
        (dec2,) = exe2.run(
            prog, feed={n: feed[n] for n in feed_names},
            fetch_list=fetches)
    (dec1,) = exe.run(test_prog, feed=feed, fetch_list=[decode])
    np.testing.assert_array_equal(dec2, dec1)
