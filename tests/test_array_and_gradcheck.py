"""TensorArray (fixed-capacity LoDTensorArray cover) + gradient_checker
(reference lod_array ops, gradient_checker.py)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.gradient_checker import double_grad_check, grad_check


def test_array_write_read_static_index():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3], append_batch_size=False)
        arr = layers.create_array("float32", capacity=4, element_shape=[3])
        i0 = layers.fill_constant([1], "int64", 0)
        i2 = layers.fill_constant([1], "int64", 2)
        arr = layers.array_write(x, i0, arr)
        arr = layers.array_write(x * 2.0, i2, arr)
        r0 = layers.array_read(arr, i0)
        r2 = layers.array_read(arr, i2)
        n = layers.array_length(arr)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([1.0, 2.0, 3.0], np.float32)
    a, b, ln = exe.run(main, feed={"x": xv}, fetch_list=[r0, r2, n])
    np.testing.assert_allclose(a, xv)
    np.testing.assert_allclose(b, xv * 2)
    assert int(ln) == 4


def test_array_inside_while_loop():
    # accumulate x*t into slot t for t in 0..3, then read back
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2], append_batch_size=False)
        arr0 = layers.create_array("float32", capacity=4, element_shape=[2])
        i0 = layers.fill_constant([1], "float32", 0.0)

        def cond(i, arr):
            return i < 4.0

        def body(i, arr):
            arr = layers.array_write(
                x * i, layers.cast(i, "int64"), arr)
            return i + 1.0, arr

        _, arr = layers.while_loop(cond, body, [i0, arr0])
        r3 = layers.array_read(arr, layers.fill_constant([1], "int64", 3))
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([1.0, -2.0], np.float32)
    (out,) = exe.run(main, feed={"x": xv}, fetch_list=[r3])
    np.testing.assert_allclose(out, xv * 3.0)


def test_grad_check_passes_and_catches():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4], append_batch_size=False)
        x.stop_gradient = False
        y = layers.tanh(layers.square(x))
    feed = {"x": np.linspace(-1, 1, 4).astype(np.float32)}
    assert grad_check(x, y, feed, program=main)


def test_double_grad_check():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3], append_batch_size=False)
        x.stop_gradient = False
        y = layers.elementwise_mul(layers.square(x), x)  # x^3
    feed = {"x": np.array([0.5, -0.7, 1.2], np.float32)}
    assert double_grad_check(x, y, feed, program=main)
