"""Serving hot path: shape bucketing, pipelined dispatch, head-of-line
fairness, feed validation, HTTP status codes, stats.

Mirrors the reference's TF-Serving-style adaptive batching concerns,
redone TPU-first: the compile-count tests prove the bucket ladder bounds
XLA compiles under ragged traffic; the pipelining test proves host-side
coalescing overlaps an in-flight device call (same slow-fake drill style
as the async-checkpoint SlowFS tests)."""

import json as _json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.inference import AnalysisConfig, create_predictor
from paddle_tpu.inference.server import InferenceServer


# ---------------------------------------------------------------------------
# model builders
# ---------------------------------------------------------------------------


def _save_ragged_model(tmp_path, with_mask=False):
    """x: (batch, ragged_len) -> per-row scalar; zero-padding-safe
    (square(0)=0), so bucketed results must match unpadded exactly."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, -1], append_batch_size=False)
        feeds = ["x"]
        if with_mask:
            mask = layers.data(
                "mask", shape=[-1, -1], append_batch_size=False)
            out = layers.reduce_sum(layers.elementwise_mul(x, mask), dim=1)
            feeds.append("mask")
        else:
            out = layers.reduce_sum(layers.square(x), dim=1)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    path = str(tmp_path / "ragged.model")
    fluid.io.save_inference_model(path, feeds, [out], exe, main)
    return path


def _save_fc_model(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 8], append_batch_size=False)
        pred = layers.fc(layers.fc(x, 16, act="relu"), 2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    path = str(tmp_path / "fc.model")
    fluid.io.save_inference_model(path, ["x"], [pred], exe, main)
    return path


# ---------------------------------------------------------------------------
# tentpole: bucketing bounds the compile count under ragged traffic
# ---------------------------------------------------------------------------


def test_bucketing_bounds_compile_count_under_ragged_traffic(tmp_path):
    """N ragged requests (variable batch AND length) must compile at most
    |batch ladder| x |length ladder| executables — the compile-storm
    elimination that motivates the whole subsystem."""
    pred = create_predictor(
        AnalysisConfig(_save_ragged_model(tmp_path)))
    batch_buckets = [1, 2, 4, 8]
    seq_buckets = [4, 8, 16]
    server = InferenceServer(
        pred, max_batch=8, batch_timeout_ms=5,
        batch_buckets=batch_buckets,
        ragged_dims={"x": {1: seq_buckets}}).start()
    try:
        rng = np.random.RandomState(7)
        cases = [(int(rng.randint(1, 6)), int(rng.randint(3, 17)))
                 for _ in range(40)]
        xs = [rng.randn(n, l).astype(np.float32) for n, l in cases]
        results = [None] * len(xs)
        errors = []

        def call(i):
            try:
                results[i] = server.infer({"x": xs[i]}, timeout=60)[0]
            except Exception as e:  # surfaced below
                errors.append((i, e))

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(xs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
        for x, got in zip(xs, results):
            np.testing.assert_allclose(
                got, (x * x).sum(axis=1), rtol=1e-5, atol=1e-5)
        assert pred.compile_count <= len(batch_buckets) * len(seq_buckets), \
            pred.compile_count
        s = server.summary()
        assert s["requests"] == len(xs)
        assert s["errors"] == 0
        assert s["compile_count"] == pred.compile_count
        assert 0.0 < s["padding_waste"]["mean"] < 1.0
        assert s["latency_ms"]["count"] == len(xs)
    finally:
        server.stop()


def test_warmup_precompiles_the_full_ladder(tmp_path):
    """After warmup over the bucket ladder, ragged traffic adds ZERO new
    compiles (AOT warmup at server start)."""
    pred = create_predictor(
        AnalysisConfig(_save_ragged_model(tmp_path)))
    server = InferenceServer(
        pred, max_batch=4, batch_timeout_ms=1,
        batch_buckets=[1, 2, 4], ragged_dims={"x": {1: [4, 8]}}).start()
    try:
        n0 = server.warmup({"x": np.zeros((1, 4), np.float32)})
        assert n0 == pred.compile_count and n0 <= 3 * 2
        rng = np.random.RandomState(1)
        for n, l in [(1, 3), (2, 7), (3, 8), (4, 5), (1, 8)]:
            x = rng.randn(n, l).astype(np.float32)
            out, = server.infer({"x": x})
            np.testing.assert_allclose(
                out, (x * x).sum(axis=1), rtol=1e-5, atol=1e-5)
        assert pred.compile_count == n0, \
            (pred.compile_count, n0)
    finally:
        server.stop()


def test_mask_feed_is_synthesized_for_padded_positions(tmp_path):
    """Models not neutral to zero padding declare a mask feed: the server
    builds the (padded_batch, padded_len) validity mask itself."""
    pred = create_predictor(
        AnalysisConfig(_save_ragged_model(tmp_path, with_mask=True)))
    server = InferenceServer(
        pred, max_batch=4, batch_timeout_ms=1,
        batch_buckets=[2, 4], ragged_dims={"x": {1: [6, 12]}},
        mask_feed="mask").start()
    try:
        rng = np.random.RandomState(2)
        for n, l in [(1, 3), (2, 6), (3, 9), (1, 12)]:
            x = rng.randn(n, l).astype(np.float32)
            out, = server.infer({"x": x})
            np.testing.assert_allclose(
                out, x.sum(axis=1), rtol=1e-5, atol=1e-5)
        # the synthesized feed must not be client-settable
        with pytest.raises(ValueError, match="mask"):
            server.infer({"x": np.zeros((1, 4), np.float32),
                          "mask": np.ones((1, 4), np.float32)})
    finally:
        server.stop()
    # axis 0 is the batch dim — batch_buckets' job, not ragged_dims'
    with pytest.raises(ValueError, match="batch dim"):
        InferenceServer(pred, ragged_dims={"x": {0: [2, 4]}})


def test_persistent_compilation_cache_writes_entries(tmp_path):
    """AnalysisConfig.enable_compilation_cache wires jax's persistent
    cache: compiles leave on-disk entries a restarted server reloads."""
    import os

    import jax

    model = _save_fc_model(tmp_path)
    cache = str(tmp_path / "xla_cache")
    cfg = AnalysisConfig(model)
    cfg.enable_compilation_cache(cache)
    try:
        pred = create_predictor(cfg)
        pred.run({"x": np.zeros((2, 8), np.float32)})
        assert os.listdir(cache), "no persistent cache entries written"
    finally:  # global knob: restore so other tests don't write here
        from jax.experimental.compilation_cache import compilation_cache
        compilation_cache.reset_cache()
        jax.config.update("jax_compilation_cache_dir", None)


# ---------------------------------------------------------------------------
# tentpole: pipelined dispatch (slow-fake-predictor drill)
# ---------------------------------------------------------------------------


class _LazyOut:
    """Device-array stand-in: materialization blocks on a gate, like a
    jax array whose computation is still in flight."""

    def __init__(self, arr, gate):
        self._arr = arr
        self._gate = gate

    def __array__(self, dtype=None, copy=None):
        assert self._gate.wait(10), "gate never opened"
        return np.asarray(self._arr, dtype=dtype)

    def __getitem__(self, idx):
        assert self._gate.wait(10), "gate never opened"
        return self._arr[idx]


class _FakeAsyncPredictor:
    """run_async returns immediately (async dispatch); the output only
    materializes once the per-call gate opens."""

    def __init__(self, n_gates):
        self.gates = [threading.Event() for _ in range(n_gates)]
        self.calls = []
        self._lock = threading.Lock()

    def run_async(self, feed):
        with self._lock:
            i = len(self.calls)
            self.calls.append(
                {k: tuple(v.shape) for k, v in feed.items()})
        rows = feed["x"].shape[0]
        out = np.arange(rows, dtype=np.float32).reshape(rows, 1)
        return [_LazyOut(out, self.gates[min(i, len(self.gates) - 1)])]


def test_dispatch_overlaps_inflight_device_call():
    """While batch N is dispatched but unmaterialized (gate closed), the
    dispatch thread must accept, coalesce, and dispatch batch N+1 — the
    host never blocks on device completion between batches."""
    pred = _FakeAsyncPredictor(n_gates=2)
    server = InferenceServer(
        pred, max_batch=4, batch_timeout_ms=1, batch_buckets=False,
        pipeline_depth=2).start()
    try:
        results = {}

        def call(name, arr):
            results[name] = server.infer({"x": arr}, timeout=30)

        t1 = threading.Thread(
            target=call, args=("a", np.zeros((2, 3), np.float32)))
        t1.start()
        deadline = time.monotonic() + 5
        while len(pred.calls) < 1 and time.monotonic() < deadline:
            time.sleep(0.001)
        assert len(pred.calls) == 1, "first batch never dispatched"
        # batch 1 is in flight (gate closed); submit batch 2
        t2 = threading.Thread(
            target=call, args=("b", np.zeros((3, 3), np.float32)))
        t2.start()
        deadline = time.monotonic() + 5
        while len(pred.calls) < 2 and time.monotonic() < deadline:
            time.sleep(0.001)
        assert len(pred.calls) == 2, \
            "dispatch stalled behind the in-flight device call"
        assert not pred.gates[0].is_set()  # batch 1 STILL unmaterialized
        pred.gates[0].set()
        pred.gates[1].set()
        t1.join(10)
        t2.join(10)
        assert results["a"][0].shape == (2, 1)
        assert results["b"][0].shape == (3, 1)
        assert server.summary()["batches"] == 2
    finally:
        for g in pred.gates:
            g.set()
        server.stop()


# ---------------------------------------------------------------------------
# satellite: head-of-line fairness across signatures
# ---------------------------------------------------------------------------


class _SlowPredictor:
    def __init__(self, delay=0.005):
        self.delay = delay

    def run(self, feed):
        time.sleep(self.delay)
        rows = feed["x"].shape[0]
        width = feed["x"].shape[1]
        return [np.full((rows, 1), float(width), np.float32)]


def test_minority_signature_is_not_starved_by_a_steady_stream():
    """Regression: the old loop re-queued an incompatible request at the
    BACK of the queue, so a steady compatible stream starved it forever.
    Per-signature deques served in arrival order must let both shapes
    make progress under load."""
    server = InferenceServer(
        _SlowPredictor(), max_batch=8, batch_timeout_ms=1,
        batch_buckets=False).start()
    try:
        stop_flood = threading.Event()
        flood_errors = []

        def flood():
            x = np.zeros((1, 4), np.float32)
            while not stop_flood.is_set():
                try:
                    server.infer({"x": x}, timeout=30)
                except Exception as e:
                    flood_errors.append(e)
                    return

        floods = [threading.Thread(target=flood) for _ in range(3)]
        for t in floods:
            t.start()
        time.sleep(0.05)  # flood is established
        t0 = time.monotonic()
        out, = server.infer({"x": np.zeros((1, 6), np.float32)}, timeout=5)
        minority_latency = time.monotonic() - t0
        stop_flood.set()
        for t in floods:
            t.join(10)
        assert not flood_errors, flood_errors[:1]
        assert out[0, 0] == 6.0          # the minority shape's own result
        assert minority_latency < 2.0, minority_latency
    finally:
        stop_flood.set()
        server.stop()


# ---------------------------------------------------------------------------
# satellite: Predictor feed validation
# ---------------------------------------------------------------------------


def test_predictor_rejects_mismatched_feeds(tmp_path):
    pred = create_predictor(AnalysisConfig(_save_fc_model(tmp_path)))
    x = np.zeros((2, 8), np.float32)
    with pytest.raises(ValueError, match=r"expects 1 feeds.*'x'"):
        pred.run([x, x])                     # silently zip-dropped before
    with pytest.raises(ValueError, match=r"expects 1 feeds"):
        pred.run([])
    with pytest.raises(ValueError, match=r"unknown \['bogus'\]"):
        pred.run({"x": x, "bogus": x})
    with pytest.raises(ValueError, match=r"missing \['x'\]"):
        pred.run({})
    out, = pred.run({"x": x})                # valid feeds still fine
    assert out.shape == (2, 2)


# ---------------------------------------------------------------------------
# satellite: HTTP status codes + /stats
# ---------------------------------------------------------------------------


def _post(url, body):
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, _json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, _json.loads(e.read())


def test_http_distinguishes_client_errors_from_server_errors(tmp_path):
    pred = create_predictor(AnalysisConfig(_save_fc_model(tmp_path)))
    server = InferenceServer(pred, batch_timeout_ms=1).start()
    httpd = server.serve_http(port=0, block=False)
    try:
        base = "http://127.0.0.1:%d" % httpd.server_address[1]
        # malformed JSON -> 400
        code, out = _post(base + "/predict", b"{not json")
        assert code == 400 and "error" in out
        # missing "inputs" -> 400
        code, out = _post(base + "/predict", _json.dumps({"x": 1}).encode())
        assert code == 400
        # unknown feed name -> 400 (client's fault, not a 500)
        code, out = _post(base + "/predict", _json.dumps(
            {"inputs": {"bogus": [[1.0] * 8]}}).encode())
        assert code == 400
        # valid request -> 200
        code, out = _post(base + "/predict", _json.dumps(
            {"inputs": {"x": [[0.5] * 8] * 3}}).encode())
        assert code == 200 and len(out["outputs"][0]) == 3
        # /stats surfaces the serving counters
        with urllib.request.urlopen(base + "/stats", timeout=10) as resp:
            stats = _json.loads(resp.read())
        assert stats["requests"] >= 1
        assert stats["batches"] >= 1
        assert "latency_ms" in stats and "padding_waste" in stats
        assert stats["compile_count"] == pred.compile_count
    finally:
        httpd.shutdown()
        server.stop()


class _FailingPredictor:
    def run(self, feed):
        raise RuntimeError("device OOM")  # internal failure, not client's


def test_http_internal_inference_failure_returns_500():
    server = InferenceServer(
        _FailingPredictor(), batch_timeout_ms=1,
        batch_buckets=False).start()
    httpd = server.serve_http(port=0, block=False)
    try:
        base = "http://127.0.0.1:%d" % httpd.server_address[1]
        code, out = _post(base + "/predict", _json.dumps(
            {"inputs": {"x": [[1.0, 2.0]]}}).encode())
        assert code == 500, (code, out)   # was conflated with 400 before
        assert "device OOM" in out["error"]
        assert server.summary()["errors"] == 1
    finally:
        httpd.shutdown()
        server.stop()


def test_http_dispatch_time_shape_error_returns_400(tmp_path):
    """Correct feed NAMES but wrong feature width: the error surfaces
    inside the predictor during dispatch, yet it's the client's fault —
    the ValueError type must survive to the HTTP layer as a 400."""
    pred = create_predictor(AnalysisConfig(_save_fc_model(tmp_path)))
    server = InferenceServer(pred, batch_timeout_ms=1).start()
    httpd = server.serve_http(port=0, block=False)
    try:
        base = "http://127.0.0.1:%d" % httpd.server_address[1]
        code, out = _post(base + "/predict", _json.dumps(
            {"inputs": {"x": [[1.0, 2.0]]}}).encode())  # width 2, wants 8
        assert code == 400, (code, out)
    finally:
        httpd.shutdown()
        server.stop()


def test_stop_start_cycle_and_stop_before_start_are_safe():
    """Regression: stop() used to leave a sentinel in the bounded done
    queue, wedging the completion thread spawned by the next start()."""
    pred = _SlowPredictor(delay=0.001)
    server = InferenceServer(
        pred, max_batch=2, batch_timeout_ms=1,
        batch_buckets=False, pipeline_depth=1)
    server.stop()                      # stop before start: no-op
    server.stop()
    x = np.zeros((1, 4), np.float32)
    for _ in range(2):                 # two full start/serve/stop cycles
        server.start()
        # pipeline_depth=1: more batches than depth proves the completer
        # is draining (a wedged completer would block the dispatcher)
        for _ in range(4):
            out, = server.infer({"x": x}, timeout=10)
            assert out.shape == (1, 1)
        server.stop()
        server.stop()                  # double stop: no-op


def test_graceful_shutdown_drains_inflight_and_rejects_new_with_503():
    """SIGTERM semantics: /readyz flips to 503 first, queued+in-flight
    batches finish (zero drop), NEW requests get 503 + Retry-After
    instead of a dead socket."""
    from paddle_tpu.inference.server import ServerClosing

    pred = _SlowPredictor(delay=0.08)
    server = InferenceServer(pred, max_batch=2, batch_timeout_ms=1,
                             batch_buckets=False).start()
    httpd = server.serve_http(port=0, block=False, install_sigterm=False)
    base = "http://127.0.0.1:%d" % httpd.server_address[1]
    try:
        with urllib.request.urlopen(base + "/readyz", timeout=10) as resp:
            assert resp.status == 200

        inflight = {}

        def slow_call():
            inflight["result"] = _post(base + "/predict", _json.dumps(
                {"inputs": {"x": [[1.0] * 4]}}).encode())

        t = threading.Thread(target=slow_call)
        t.start()
        time.sleep(0.02)                 # the request is being served
        shut = threading.Thread(
            target=server.begin_graceful_shutdown, kwargs={
                "drain_timeout": 10})
        shut.start()
        time.sleep(0.02)
        try:
            with urllib.request.urlopen(base + "/readyz",
                                        timeout=10) as resp:
                code = resp.status
        except urllib.error.HTTPError as e:
            code, payload = e.code, _json.loads(e.read())
            assert payload["reason"] == "draining"
        assert code == 503
        # a NEW request during the drain: 503 + Retry-After
        code, out = _post(base + "/predict", _json.dumps(
            {"inputs": {"x": [[1.0] * 4]}}).encode())
        assert code == 503, (code, out)
        with pytest.raises(ServerClosing):
            server.infer({"x": np.zeros((1, 4), np.float32)})
        shut.join(20)
        t.join(20)
        # the in-flight request was drained, not dropped
        code, out = inflight["result"]
        assert code == 200, (code, out)
        assert not server.ready()
    finally:
        httpd.shutdown()
        server.stop()


def test_sigterm_handler_drains_then_chains_previous_handler():
    """serve_http(install_sigterm=True) arms graceful shutdown on
    SIGTERM and chains whatever handler was installed before it (the
    PR-6 flight-recorder convention: exit semantics survive)."""
    import signal

    chained = []
    original = signal.getsignal(signal.SIGTERM)
    signal.signal(signal.SIGTERM, lambda s, f: chained.append(s))
    try:
        pred = _SlowPredictor(delay=0.001)
        server = InferenceServer(pred, max_batch=2, batch_timeout_ms=1,
                                 batch_buckets=False).start()
        httpd = server.serve_http(port=0, block=False,
                                  install_sigterm=True, drain_timeout=5)
        base = "http://127.0.0.1:%d" % httpd.server_address[1]
        code, out = _post(base + "/predict", _json.dumps(
            {"inputs": {"x": [[1.0] * 4]}}).encode())
        assert code == 200
        handler = signal.getsignal(signal.SIGTERM)
        assert callable(handler)
        # deliver the signal semantics synchronously (the handler runs
        # on the main thread exactly as a real SIGTERM would)
        handler(signal.SIGTERM, None)
        assert chained == [signal.SIGTERM]     # previous handler ran
        assert not server.ready()              # drained + stopped
        # the listener closed: a fresh connection must fail
        with pytest.raises(Exception):
            urllib.request.urlopen(base + "/health", timeout=2)
    finally:
        signal.signal(signal.SIGTERM, original)


def test_timed_out_request_is_dropped_not_dispatched():
    """A waiter that times out while queued is abandoned: the dispatcher
    drops it instead of burning device work, and it never skews the
    latency histogram."""
    pred = _SlowPredictor(delay=0.3)
    server = InferenceServer(
        pred, max_batch=1, batch_timeout_ms=1, batch_buckets=False,
        pipeline_depth=1).start()
    try:
        x = np.zeros((1, 4), np.float32)
        blocker = threading.Thread(
            target=lambda: server.infer({"x": x}, timeout=10))
        blocker.start()                # occupies the device 0.3s
        time.sleep(0.05)
        with pytest.raises(TimeoutError):
            server.infer({"x": x}, timeout=0.05)   # dies in the queue
        blocker.join(10)
        out, = server.infer({"x": x}, timeout=10)  # server still healthy
        assert out.shape == (1, 1)
        s = server.summary()
        assert s["abandoned"] == 1
        # blocker + the healthy request served; the abandoned one wasn't
        assert s["latency_ms"]["count"] == 2
    finally:
        server.stop()
