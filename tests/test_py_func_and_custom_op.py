"""py_func + the public custom-op extension story (reference
`tests/unittests/test_py_func_op.py` and `tests/custom_op/`)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def test_py_func_forward_and_backward():
    """Ported reference pattern: tanh via py_func with a hand backward;
    grads flow through the host callback."""

    def my_tanh(x):
        return np.tanh(x)

    def my_tanh_grad(x, y, dy):
        return dy * (1.0 - np.square(np.tanh(x)))

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 4], append_batch_size=False)
        hidden = layers.fc(x, size=4, param_attr="pyf_fc.w")
        out_var = layers.nn.create_tmp_var("pyf_out", "float32", [-1, 4])
        layers.py_func(my_tanh, hidden, out_var,
                       backward_func=my_tanh_grad)
        loss = layers.reduce_mean(layers.square(out_var))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)

    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    xv = rng.randn(8, 4).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        losses = []
        for _ in range(6):
            (lv,) = exe.run(main, feed={"x": xv}, fetch_list=[loss])
            losses.append(float(lv))
    # training through the py_func backward reduces the loss
    assert losses[-1] < losses[0] * 0.9, losses


def test_py_func_output_value_matches_numpy():
    def double_plus(x, y):
        return x * 2.0 + y

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[-1, 3], append_batch_size=False)
        b = layers.data("b", shape=[-1, 3], append_batch_size=False)
        o = layers.nn.create_tmp_var("pyf_o2", "float32", [-1, 3])
        layers.py_func(double_plus, [a, b], o)
        out = o * 1.0
    exe = fluid.Executor()
    rng = np.random.RandomState(1)
    av = rng.randn(2, 3).astype(np.float32)
    bv = rng.randn(2, 3).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        (got,) = exe.run(main, feed={"a": av, "b": bv}, fetch_list=[out])
    np.testing.assert_allclose(got, av * 2 + bv, rtol=1e-6)


def test_py_func_without_backward_stops_gradients():
    def ident(x):
        return x

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 3], append_batch_size=False)
        h = layers.fc(x, size=3, param_attr="pyf_fc2.w", bias_attr=False)
        o = layers.nn.create_tmp_var("pyf_o3", "float32", [-1, 3])
        layers.py_func(ident, h, o)
        loss = layers.reduce_mean(layers.square(o))
        fluid.optimizer.SGDOptimizer(0.5).minimize(loss)
    exe = fluid.Executor()
    xv = np.ones((4, 3), np.float32)
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        w0 = np.asarray(scope.find_var("pyf_fc2.w")).copy()
        exe.run(main, feed={"x": xv}, fetch_list=[loss])
        w1 = np.asarray(scope.find_var("pyf_fc2.w"))
    np.testing.assert_allclose(w0, w1)  # no grads flowed


def test_custom_op_registration_from_user_code():
    """The public extension API (reference tests/custom_op/): a USER
    module registers a brand-new op type with register_op; JAX AD gives
    its gradient; layers drive it through a Program."""
    import jax.numpy as jnp

    from paddle_tpu.fluid.core.registry import get_op_def, register_op

    if not hasattr(get_op_def, "_test_relu3_registered"):
        @register_op("user_relu3", inputs=["X"], outputs=["Out"])
        def _user_relu3(ctx, ins, attrs):
            """User op: relu(x)^3, scaled by an attr."""
            x = ins["X"][0]
            s = float(attrs.get("scale", 1.0))
            return {"Out": [jnp.maximum(x, 0.0) ** 3 * s]}

        get_op_def._test_relu3_registered = True

    from paddle_tpu.fluid.layers.common import append_simple_op

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 5], append_batch_size=False)
        x.stop_gradient = False
        y = append_simple_op("user_relu3", {"X": x}, {"scale": 2.0})
        loss = layers.reduce_sum(y)
        grads = fluid.backward.gradients([loss], [x])
    exe = fluid.Executor()
    rng = np.random.RandomState(2)
    xv = rng.randn(3, 5).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        got_y, got_gx = exe.run(
            main, feed={"x": xv}, fetch_list=[y, grads[0]])
    ref_y = np.maximum(xv, 0) ** 3 * 2.0
    ref_gx = 3 * np.maximum(xv, 0) ** 2 * 2.0 * (xv > 0)
    np.testing.assert_allclose(got_y, ref_y, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_gx, ref_gx, rtol=1e-4, atol=1e-5)
