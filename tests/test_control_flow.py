"""Control flow: cond / case / switch_case / while_loop lower to lax
control flow inside ONE compiled program.

Mirrors reference tests test_cond.py / test_while_loop.py (value parity
with python control flow, gradients through cond).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import dygraph, layers
from paddle_tpu.fluid.optimizer import SGDOptimizer


def test_cond_value_and_both_branches():
    for flag, expected in [(1.0, 10.0), (-1.0, 20.0)]:
        prog, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(prog, startup):
            x = fluid.data("x", [1], "float32")
            pred = layers.greater_than(x, layers.zeros([1]))

            out = layers.cond(
                pred,
                lambda: x * 10.0,
                lambda: x * (-20.0),
            )
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            r, = exe.run(prog, feed={"x": np.array([flag], np.float32)},
                         fetch_list=[out])
        assert float(r[0]) == expected


def test_cond_gradient_flows_through_taken_branch():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data("x", [1], "float32")
        w = prog.global_block.create_parameter("w_cf", [1], "float32")
        sb = startup.global_block
        sb.create_parameter("w_cf", [1], "float32")
        sb.append_op("fill_constant", outputs={"Out": ["w_cf"]},
                     attrs={"shape": [1], "value": 3.0, "dtype": "float32"},
                     infer=False)
        pred = layers.greater_than(x, layers.zeros([1]))
        out = layers.cond(pred, lambda: w * x * 2.0, lambda: w * x * 5.0)
        loss = layers.reduce_sum(out)
        SGDOptimizer(0.0).minimize(loss, startup)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run_startup(startup)
        _, g = exe.run(prog, feed={"x": np.array([4.0], np.float32)},
                       fetch_list=[loss, "w_cf@GRAD"])
        assert float(g[0]) == 8.0  # taken branch: d(w*x*2)/dw = 2x
        _, g = exe.run(prog, feed={"x": np.array([-4.0], np.float32)},
                       fetch_list=[loss, "w_cf@GRAD"])
        assert float(g[0]) == -20.0  # other branch: 5x


def test_while_loop_accumulates():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        i = layers.fill_constant([1], "int64", 0)
        acc = layers.fill_constant([1], "float32", 0.0)
        ten = layers.fill_constant([1], "int64", 10)

        def cond_fn(i, acc):
            return layers.less_than(i, ten)

        def body_fn(i, acc):
            return [i + 1, acc + 2.5]

        i_out, acc_out = layers.while_loop(cond_fn, body_fn, [i, acc])
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        iv, av = exe.run(prog, feed={}, fetch_list=[i_out, acc_out])
    assert int(iv[0]) == 10
    assert abs(float(av[0]) - 25.0) < 1e-6


def test_case_and_switch_case():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        idx = fluid.data("idx", [1], "int64")
        out = layers.switch_case(
            idx,
            {0: lambda: layers.fill_constant([1], "float32", 100.0),
             1: lambda: layers.fill_constant([1], "float32", 200.0)},
            default=lambda: layers.fill_constant([1], "float32", -1.0),
        )
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        for i, want in [(0, 100.0), (1, 200.0), (7, -1.0)]:
            r, = exe.run(prog, feed={"idx": np.array([i], np.int64)},
                         fetch_list=[out])
            assert float(r[0]) == want


def test_dygraph_cond_and_while():
    with dygraph.guard():
        x = dygraph.to_variable(np.array([2.0], np.float32))
        out = layers.cond(
            layers.greater_than(x, layers.zeros([1])),
            lambda: x * 3.0, lambda: x,
        )
        assert float(out.numpy()[0]) == 6.0
        i = dygraph.to_variable(np.array([0], np.int64))
        n = dygraph.to_variable(np.array([5], np.int64))
        vals = layers.while_loop(
            lambda i: layers.less_than(i, n), lambda i: i + 1, [i]
        )
        assert int(vals[0].numpy()[0]) == 5
