"""Pallas fused-epilogue GEMM (`ops.pallas.matmul`) vs the naive jnp
composition (interpret mode on CPU): forward + gradients for every
activation, the bf16-operand tolerance policy (mirrors the flash
kernels' PADDLE_TPU_FLASH_ACC discipline), the explicit-block-size
contract (explicit beats env, non-divisors raise), the naive fallback
for untileable shapes, and the op-level lowering.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas import matmul as M
from paddle_tpu.ops.pallas.matmul import (
    matmul_bias_act,
    naive_matmul_bias_act,
)

# FFN-shaped aspect (M=B*S, K=hidden, N=intermediate) scaled down so the
# interpreter stays fast; every dim is 128-tileable and the 128-block
# choice exercises the multi-block accumulation schedules (2x4x2 grid)
MKN = (256, 256, 512)
BLOCKS = dict(block_m=128, block_n=128, block_k=128)


def _operands(dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    m, k, n = MKN
    x = jnp.asarray(rng.randn(m, k).astype(dtype) * 0.1)
    w = jnp.asarray(rng.randn(k, n).astype(dtype) * 0.1)
    b = jnp.asarray(rng.randn(n).astype(dtype) * 0.1)
    return x, w, b


@pytest.mark.parametrize("act", ["none", "relu", "tanh", "gelu"])
@pytest.mark.parametrize("with_bias", [True, False])
def test_forward_matches_naive(act, with_bias):
    x, w, b = _operands()
    bias = b if with_bias else None
    out = matmul_bias_act(x, w, bias, activation=act, interpret=True,
                          **BLOCKS)
    ref = naive_matmul_bias_act(x, w, bias, activation=act)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("act", ["none", "relu", "tanh", "gelu"])
def test_grads_match_naive(act):
    """The custom-VJP backward (dZ recomputed in-register, dbias as the
    dW kernel's reduction epilogue) vs jax differentiating the naive
    composition — all three gradients."""
    x, w, b = _operands()

    def f_fused(x, w, b):
        return jnp.sum(matmul_bias_act(x, w, b, activation=act,
                                       interpret=True, **BLOCKS) * 0.01)

    def f_naive(x, w, b):
        return jnp.sum(naive_matmul_bias_act(x, w, b, activation=act)
                       * 0.01)

    gf = jax.grad(f_fused, argnums=(0, 1, 2))(x, w, b)
    gn = jax.grad(f_naive, argnums=(0, 1, 2))(x, w, b)
    for a, r, name in zip(gf, gn, ("dx", "dw", "dbias")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(r), rtol=2e-4, atol=2e-5,
            err_msg="%s mismatch (%s)" % (name, act))


def test_grads_no_bias():
    x, w, _ = _operands()
    gf = jax.grad(
        lambda x, w: jnp.sum(matmul_bias_act(
            x, w, activation="gelu", interpret=True, **BLOCKS) * 0.01),
        argnums=(0, 1))(x, w)
    gn = jax.grad(
        lambda x, w: jnp.sum(naive_matmul_bias_act(
            x, w, activation="gelu") * 0.01), argnums=(0, 1))(x, w)
    for a, r, name in zip(gf, gn, ("dx", "dw")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-4, atol=2e-5, err_msg=name)


def test_approximate_gelu_fwd_and_grad():
    x, w, b = _operands()
    out = matmul_bias_act(x, w, b, activation="gelu", approximate=True,
                          interpret=True, **BLOCKS)
    ref = naive_matmul_bias_act(x, w, b, activation="gelu",
                                approximate=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    gf = jax.grad(lambda x: jnp.sum(matmul_bias_act(
        x, w, b, activation="gelu", approximate=True, interpret=True,
        **BLOCKS) * 0.01))(x)
    gn = jax.grad(lambda x: jnp.sum(naive_matmul_bias_act(
        x, w, b, activation="gelu", approximate=True) * 0.01))(x)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gn),
                               rtol=2e-4, atol=2e-5)


def test_bf16_operand_tolerance_policy():
    """bf16 operands with f32 accumulation: the documented bound
    mirrors the flash PADDLE_TPU_FLASH_ACC policy — forward within
    2e-2, gradients within 5e-2 of the f32 oracle."""
    x, w, b = _operands()
    xb, wb, bb = (x.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
                  b.astype(jnp.bfloat16))
    out = matmul_bias_act(xb, wb, bb, activation="gelu", interpret=True,
                          **BLOCKS)
    ref = naive_matmul_bias_act(x, w, b, activation="gelu")
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=2e-2, atol=2e-2)

    gf = jax.grad(lambda x_: jnp.sum(matmul_bias_act(
        x_, wb, bb, activation="gelu", interpret=True,
        **BLOCKS).astype(jnp.float32) * 0.01))(xb)
    gn = jax.grad(lambda x_: jnp.sum(naive_matmul_bias_act(
        x_, w, b, activation="gelu") * 0.01))(x)
    np.testing.assert_allclose(np.asarray(gf, np.float32),
                               np.asarray(gn), rtol=5e-2, atol=5e-2)


# ---------------------------------------------------------------------------
# block-size contract (the tune.search_gemm_blocks knob)
# ---------------------------------------------------------------------------


def test_explicit_non_divisor_block_raises():
    x, w, b = _operands()
    with pytest.raises(ValueError, match="must divide"):
        matmul_bias_act(x, w, b, interpret=True, block_m=96)
    with pytest.raises(ValueError, match="must divide"):
        matmul_bias_act(x, w, b, interpret=True, block_n=200,
                        block_m=128, block_k=128)


def test_explicit_beats_env(monkeypatch):
    """A valid env override must NOT rescue an invalid explicit block:
    explicit args are a hard contract (the tuner must never time a
    different grid than it requested)."""
    x, w, b = _operands()
    monkeypatch.setenv("PADDLE_TPU_GEMM_BLOCKS", "128,128,128")
    with pytest.raises(ValueError, match="must divide"):
        matmul_bias_act(x, w, b, interpret=True, block_m=100)
    # and a valid explicit choice wins over a DIFFERENT valid env one
    grids = []
    real = M.pl.pallas_call

    def spy(kernel, *a, **kw):
        grids.append(kw.get("grid"))
        return real(kernel, *a, **kw)

    monkeypatch.setattr(M.pl, "pallas_call", spy)
    matmul_bias_act(x, w, b, interpret=True, block_m=256, block_n=256,
                    block_k=256)
    m, k, n = MKN
    assert grids[-1] == (m // 256, n // 256, k // 256)


def test_env_applies_when_no_explicit(monkeypatch):
    x, w, b = _operands()
    grids = []
    real = M.pl.pallas_call

    def spy(kernel, *a, **kw):
        grids.append(kw.get("grid"))
        return real(kernel, *a, **kw)

    monkeypatch.setattr(M.pl, "pallas_call", spy)
    monkeypatch.setenv("PADDLE_TPU_GEMM_BLOCKS", "128,128,128")
    matmul_bias_act(x, w, b, interpret=True)
    m, k, n = MKN
    assert grids[-1] == (m // 128, n // 128, k // 128)
    # non-divisible env falls back to the heuristic with a warning
    monkeypatch.setenv("PADDLE_TPU_GEMM_BLOCKS", "96,96,96")
    with pytest.warns(UserWarning, match="does not divide"):
        matmul_bias_act(x, w, b, interpret=True)
    assert grids[-1] == (m // 256, n // 512, k // 256)


def test_partial_explicit_keeps_env_for_other_dims(monkeypatch):
    x, w, b = _operands()
    grids = []
    real = M.pl.pallas_call

    def spy(kernel, *a, **kw):
        grids.append(kw.get("grid"))
        return real(kernel, *a, **kw)

    monkeypatch.setattr(M.pl, "pallas_call", spy)
    monkeypatch.setenv("PADDLE_TPU_GEMM_BLOCKS", "128,128,128")
    matmul_bias_act(x, w, b, interpret=True, block_n=256)
    m, k, n = MKN
    assert grids[-1] == (m // 128, n // 256, k // 128)


def test_untileable_shape_falls_back_to_naive():
    """Dims no block divides run the unfused composition (a PERF
    fallback with a one-time warning, never a silent truncate)."""
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(5, 48).astype(np.float32))
    w = jnp.asarray(rng.randn(48, 33).astype(np.float32))
    b = jnp.asarray(rng.randn(33).astype(np.float32))
    out = matmul_bias_act(x, w, b, activation="relu", interpret=True)
    ref = naive_matmul_bias_act(x, w, b, activation="relu")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


def test_bad_activation_and_shapes_raise():
    x, w, b = _operands()
    with pytest.raises(ValueError, match="activation"):
        matmul_bias_act(x, w, b, activation="softmax", interpret=True)
    with pytest.raises(ValueError, match="2-D"):
        matmul_bias_act(x[None], w, b, interpret=True)
    with pytest.raises(ValueError, match="bias"):
        matmul_bias_act(x, w, b[:-1], interpret=True)


# ---------------------------------------------------------------------------
# op-level lowering (the MatmulBiasActFusePass / fused_linear target)
# ---------------------------------------------------------------------------


def test_op_lowering_matches_composed_chain_static():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers
    from paddle_tpu.nn import functional as F

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 8, 16], append_batch_size=False)
        w = layers.create_parameter([16, 32], name="tpm.w")
        b = layers.create_parameter([32], name="tpm.b")
        fused = F.fused_linear(x, w, b, activation="gelu")
        chain = layers.gelu(layers.elementwise_add(
            layers.mul(x, w, x_num_col_dims=2), b, axis=2))
    exe = fluid.Executor()
    xv = np.random.RandomState(0).randn(4, 8, 16).astype(np.float32)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        got, ref = exe.run(main, feed={"x": xv},
                           fetch_list=[fused, chain])
    assert got.shape == (4, 8, 32)
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_static_backward_through_fused_op_matches_chain():
    """append_backward's generic vjp_grad differentiates the fused op's
    lowering (custom-VJP on TPU, jnp composition elsewhere): parameter
    grads must match the unfused chain's exactly."""
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers
    from paddle_tpu.nn import functional as F

    def build(fused):
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[8, 16], append_batch_size=False)
            w = layers.create_parameter([16, 32], name="bwp.w%d" % fused)
            b = layers.create_parameter([32], name="bwp.b%d" % fused)
            if fused:
                out = F.fused_linear(x, w, b, activation="gelu")
            else:
                out = layers.gelu(layers.elementwise_add(
                    layers.mul(x, w), b, axis=1))
            loss = layers.mean(out)
            pg = fluid.append_backward(loss)
        grads = {p.name.rsplit(".", 1)[-1]: g for p, g in pg}
        return main, startup, grads

    xv = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    wv = np.random.RandomState(1).randn(16, 32).astype(np.float32)
    bv = np.random.RandomState(2).randn(32).astype(np.float32)

    results = {}
    for fused in (0, 1):
        import paddle_tpu.fluid as fluid

        main, startup, grads = build(fused)
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            scope = fluid.global_scope()
            scope.set("bwp.w%d" % fused, wv)
            scope.set("bwp.b%d" % fused, bv)
            gw, gb = exe.run(
                main, feed={"x": xv},
                fetch_list=[grads["w%d" % fused], grads["b%d" % fused]])
        results[fused] = (np.asarray(gw), np.asarray(gb))
    np.testing.assert_allclose(results[1][0], results[0][0],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(results[1][1], results[0][1],
                               rtol=1e-5, atol=1e-6)


def test_partial_explicit_with_untileable_dim_names_the_dim():
    """When an explicit block is given but a NON-explicit dim has no
    supported tile, the error blames that dim (not the explicit args
    the caller actually passed)."""
    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(100, 256).astype(np.float32))
    w = jnp.asarray(rng.randn(256, 512).astype(np.float32))
    with pytest.raises(ValueError, match="M=100"):
        matmul_bias_act(x, w, interpret=True, block_n=256)


def test_unknown_activation_raises_on_every_path():
    """The naive fallback and the op lowering must reject unknown
    activations exactly like the kernel — never silently return
    un-activated output on one platform while raising on another."""
    x, w, b = _operands()
    with pytest.raises(ValueError, match="activation"):
        naive_matmul_bias_act(x, w, b, activation="sigmoid")

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers
    from paddle_tpu.fluid.layers.common import append_simple_op

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        xd = layers.data("x", shape=[4, 16], append_batch_size=False)
        wp = layers.create_parameter([16, 32], name="ua.w")
        # the shape-inference wrapper re-raises with context, so match
        # the message rather than the exact exception type
        with pytest.raises(Exception, match="act_type"):
            append_simple_op("matmul_bias_act", {"X": xd, "Y": wp},
                             {"act_type": "sigmoid",
                              "x_num_col_dims": 1, "y_num_col_dims": 1})


def test_env_blocks_zero_or_negative_raise(monkeypatch):
    x, w, b = _operands()
    monkeypatch.setenv("PADDLE_TPU_GEMM_BLOCKS", "0,128,128")
    with pytest.raises(ValueError, match="POSITIVE"):
        matmul_bias_act(x, w, b, interpret=True)
    monkeypatch.setenv("PADDLE_TPU_GEMM_BLOCKS", "-128,128,128")
    with pytest.raises(ValueError, match="POSITIVE"):
        matmul_bias_act(x, w, b, interpret=True)
