"""Flight-recorder SIGTERM drill worker: arm the recorder, train an
endless loop of real `Executor.run` steps under a `StepTimer`, and tell
the parent when enough steps are in the ring.  The parent then SIGTERMs
us mid-train; the recorder must leave ONE loadable chrome-trace dump
behind while the process still dies by signal.

Env knobs:

  FLT_DUMP_DIR   where the recorder dumps (required)
  FLT_READY      file touched once >=3 steps have trained ("" = never)
  FLT_FAIL_AT    step index at which the train step raises (first-
                 failed-step dump path; "" = never fail, loop forever)
"""

import os
import re

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", _flags)
os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=1"

import numpy as np


def main():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers
    from paddle_tpu.observability import StepTimer
    from paddle_tpu.observability.flight_recorder import (
        install_flight_recorder,
    )

    install_flight_recorder(dump_dir=os.environ["FLT_DUMP_DIR"],
                            span_capacity=512)

    main_p, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main_p, startup):
        x = layers.data("x", shape=[-1, 4], append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], append_batch_size=False)
        h = layers.fc(x, 8, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.reduce_mean(layers.square(pred - y))
        fluid.optimizer.SGDOptimizer(0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    ready = os.environ.get("FLT_READY", "")
    fail_at = int(os.environ.get("FLT_FAIL_AT", "-1") or "-1")
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 4).astype(np.float32),
            "y": rng.randn(8, 1).astype(np.float32)}
    timer = StepTimer(name="flight.drill")
    step = 0
    while True:
        with timer.step():
            if step == fail_at:
                raise RuntimeError("injected step failure at %d" % step)
            exe.run(main_p, feed=feed, fetch_list=[loss])
        step += 1
        if step == 3 and ready:
            tmp = ready + ".tmp"
            with open(tmp, "w") as f:
                f.write(str(step))
            os.replace(tmp, ready)


if __name__ == "__main__":
    main()
