"""GEO-SGD semantics (reference geo_sgd_transpiler.py + GeoCommunicator):
k-step local updates, delta fold across workers, loss parity within delta
vs fully-synchronous training."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.distributed.geo import GeoSGDCommunicator
from paddle_tpu.fluid import layers


def _build(seed=13):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 8], append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], append_batch_size=False)
        h = layers.fc(x, size=16, act="relu", param_attr="g_fc1.w",
                      bias_attr="g_fc1.b")
        pred = layers.fc(h, size=1, param_attr="g_fc2.w", bias_attr="g_fc2.b")
        loss = layers.reduce_mean(layers.square(pred - y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.05).minimize(loss)
    return main, startup, loss


def _data(steps=12, G=16, seed=3):
    rng = np.random.RandomState(seed)
    xs = rng.randn(steps, G, 8).astype(np.float32)
    w = rng.randn(8, 1).astype(np.float32)
    ys = xs @ w + 0.05 * rng.randn(steps, G, 1).astype(np.float32)
    return xs, ys


def test_geo_two_workers_track_sync_baseline():
    """2 GEO workers (k=3) end within delta of the fully-synchronous
    2-worker run and both converge; workers agree after each sync."""
    xs, ys = _data()
    steps, G = xs.shape[0], xs.shape[1]
    W = 2
    B = G // W

    # --- fully synchronous baseline: train on the global batch ---------
    main, startup, loss = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    sync_losses = []
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for t in range(steps):
            (lv,) = exe.run(main, feed={"x": xs[t], "y": ys[t]},
                            fetch_list=[loss])
            sync_losses.append(float(lv))
        w_sync = np.asarray(scope.find_var("g_fc1.w"))

    # --- GEO: 2 workers, local SGD, delta fold every k=3 ----------------
    import paddle_tpu.fluid.framework as fw

    k = 3
    workers = []
    for wid in range(W):
        fw.reset_default_programs()
        main_w, startup_w, loss_w = _build()   # same seeds => same init
        scope_w = fluid.Scope()
        exe_w = fluid.Executor(fluid.CPUPlace())
        with fluid.scope_guard(scope_w):
            exe_w.run(startup_w)
        workers.append(dict(main=main_w, loss=loss_w, scope=scope_w,
                            exe=exe_w))

    # lockstep delta fold: deposit every worker's delta first, then each
    # worker's sync applies the full sum (emulating the cross-process
    # all-reduce in-process)
    comms = []
    deltas = [dict() for _ in range(W)]
    for wid, w in enumerate(workers):
        with fluid.scope_guard(w["scope"]):
            comms.append(GeoSGDCommunicator(
                w["main"], scope=w["scope"], k_steps=k,
                reduce_fn=lambda name, d: d))  # replaced before each sync

    geo_losses = []
    for t in range(steps):
        locs = []
        for wid, w in enumerate(workers):
            lo, hi = wid * B, (wid + 1) * B
            with fluid.scope_guard(w["scope"]):
                (lv,) = w["exe"].run(
                    w["main"], feed={"x": xs[t, lo:hi], "y": ys[t, lo:hi]},
                    fetch_list=[w["loss"]])
            locs.append(float(lv))
        geo_losses.append(float(np.mean(locs)))
        if (t + 1) % k == 0:
            # lockstep fold: deposit all deltas first (worker order), then
            # each worker applies the full sum
            for wid in range(W):
                deltas[wid].clear()
            for wid, w in enumerate(workers):
                for n in comms[wid]._params:
                    deltas[wid][n] = (
                        np.asarray(w["scope"].find_var(n))
                        - comms[wid]._snapshot[n])
            for wid, w in enumerate(workers):
                comms[wid]._reduce = lambda name, d, _w=wid: sum(
                    deltas[i][name] for i in range(W))
                comms[wid].sync()

    # workers hold identical params after the last sync
    w0 = np.asarray(workers[0]["scope"].find_var("g_fc1.w"))
    w1 = np.asarray(workers[1]["scope"].find_var("g_fc1.w"))
    np.testing.assert_allclose(w0, w1, rtol=1e-6, atol=1e-7)

    # GEO converges and lands near the synchronous solution
    assert geo_losses[-1] < geo_losses[0] * 0.5
    assert abs(geo_losses[-1] - sync_losses[-1]) < 0.5 * max(
        sync_losses[0], 1.0)
    np.testing.assert_allclose(w0, w_sync, atol=0.5)


def test_geo_single_worker_is_local_training():
    """World size 1: GEO sync is the identity fold — training proceeds
    exactly like plain local SGD (reference one-trainer behavior)."""
    xs, ys = _data(steps=6)
    main, startup, loss = _build(seed=29)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        comm = GeoSGDCommunicator(main, scope=scope, k_steps=2)
        losses = []
        n_syncs = 0
        for t in range(6):
            (lv,) = exe.run(main, feed={"x": xs[t], "y": ys[t]},
                            fetch_list=[loss])
            losses.append(float(lv))
            n_syncs += int(comm.step())
        assert n_syncs == 3
        assert losses[-1] < losses[0]
        # snapshot tracks the params after each sync
        np.testing.assert_allclose(
            comm._snapshot["g_fc1.w"],
            np.asarray(scope.find_var("g_fc1.w")), rtol=1e-6)
