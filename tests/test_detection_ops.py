"""Detection op family oracles (reference tests/unittests/
test_iou_similarity_op.py, test_box_coder_op.py, test_prior_box_op.py,
test_yolo_box_op.py, test_multiclass_nms_op.py, test_roi_align_op.py,
test_bipartite_match_op.py patterns)."""

import numpy as np
import pytest

from op_test import check_output, run_single_op

rng = np.random.RandomState(3)


def _boxes(n):
    xy = rng.rand(n, 2) * 50
    wh = rng.rand(n, 2) * 30 + 2
    return np.concatenate([xy, xy + wh], axis=1).astype(np.float32)


def _iou(a, b):
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.maximum(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area = lambda x: (x[:, 2] - x[:, 0]) * (x[:, 3] - x[:, 1])
    return inter / (area(a)[:, None] + area(b)[None, :] - inter + 1e-10)


def test_iou_similarity():
    a, b = _boxes(5), _boxes(7)
    check_output("iou_similarity", {"X": a, "Y": b}, {},
                 {"Out": _iou(a, b)}, rtol=1e-5)


def test_box_clip():
    boxes = (_boxes(6) - 10)[None]  # [1, 6, 4], some negative coords
    im_info = np.array([[40.0, 60.0, 1.0]], np.float32)
    outs, _ = run_single_op(
        "box_clip", {"Input": boxes, "ImInfo": im_info}, {}, ["Output"]
    )
    o = outs["Output"]
    assert (o[..., 0] >= 0).all() and (o[..., 2] <= 59.0).all()
    assert (o[..., 1] >= 0).all() and (o[..., 3] <= 39.0).all()


def test_prior_box_shapes_and_bounds():
    feat = np.zeros((1, 8, 4, 4), np.float32)
    img = np.zeros((1, 3, 64, 64), np.float32)
    outs, _ = run_single_op(
        "prior_box", {"Input": feat, "Image": img},
        {"min_sizes": [16.0], "max_sizes": [32.0],
         "aspect_ratios": [2.0], "flip": True, "clip": True},
        ["Boxes", "Variances"],
    )
    boxes = outs["Boxes"]  # [4, 4, P, 4]; P = 1 + 2 + 1 = 4
    assert boxes.shape == (4, 4, 4, 4)
    assert (boxes >= 0).all() and (boxes <= 1).all()
    # center of cell (0,0) prior 0: ~ (8/64, 8/64)
    cx = (boxes[0, 0, 0, 0] + boxes[0, 0, 0, 2]) / 2
    assert abs(cx - 8.0 / 64) < 1e-5
    assert outs["Variances"].shape == boxes.shape


def test_box_coder_encode_decode_roundtrip():
    prior = _boxes(6)
    pvar = np.full((6, 4), 0.1, np.float32)
    target = _boxes(3)
    enc, _ = run_single_op(
        "box_coder", {"PriorBox": prior, "PriorBoxVar": pvar,
                      "TargetBox": target},
        {"code_type": "encode_center_size"}, ["OutputBox"],
    )
    assert enc["OutputBox"].shape == (3, 6, 4)
    dec, _ = run_single_op(
        "box_coder", {"PriorBox": prior, "PriorBoxVar": pvar,
                      "TargetBox": enc["OutputBox"]},
        {"code_type": "decode_center_size"}, ["OutputBox"],
    )
    # decode(encode(t)) reproduces the target for every prior column
    for j in range(6):
        np.testing.assert_allclose(dec["OutputBox"][:, j], target,
                                   rtol=1e-4, atol=1e-3)


def test_anchor_generator():
    feat = np.zeros((1, 8, 2, 3), np.float32)
    outs, _ = run_single_op(
        "anchor_generator", {"Input": feat},
        {"anchor_sizes": [32.0, 64.0], "aspect_ratios": [1.0],
         "stride": [16.0, 16.0]},
        ["Anchors", "Variances"],
    )
    a = outs["Anchors"]
    assert a.shape == (2, 3, 2, 4)
    np.testing.assert_allclose(a[0, 0, 0], [8 - 16, 8 - 16, 8 + 16, 8 + 16])


def test_yolo_box_shapes():
    N, A, C, H, W = 1, 2, 3, 4, 4
    x = rng.randn(N, A * (5 + C), H, W).astype(np.float32)
    img = np.array([[128, 128]], np.int32)
    outs, _ = run_single_op(
        "yolo_box", {"X": x, "ImgSize": img},
        {"anchors": [10, 13, 16, 30], "class_num": C,
         "conf_thresh": 0.0, "downsample_ratio": 32},
        ["Boxes", "Scores"],
    )
    assert outs["Boxes"].shape == (N, A * H * W, 4)
    assert outs["Scores"].shape == (N, A * H * W, C)
    assert (outs["Scores"] >= 0).all() and (outs["Scores"] <= 1).all()


def test_multiclass_nms_suppresses_overlaps():
    # two heavily overlapping boxes + one separate; the lower-scoring
    # overlap must be suppressed
    boxes = np.array([[
        [0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60],
    ]], np.float32)
    scores = np.array([[[0.9, 0.8, 0.7]]], np.float32)  # [N=1, C=1, M=3]
    outs, _ = run_single_op(
        "multiclass_nms", {"BBoxes": boxes, "Scores": scores},
        {"score_threshold": 0.01, "nms_threshold": 0.5, "nms_top_k": 3,
         "keep_top_k": 5, "background_label": -1},
        ["Out"],
    )
    out = outs["Out"][0]  # [5, 6]
    kept = out[out[:, 0] >= 0]
    assert len(kept) == 2  # overlap suppressed
    np.testing.assert_allclose(sorted(kept[:, 1]), [0.7, 0.9], rtol=1e-5)


def test_roi_align_constant_region():
    x = np.zeros((1, 2, 8, 8), np.float32)
    x[0, 0, 2:6, 2:6] = 3.0  # constant over pixel coords [2, 5]
    # roi stays inside [2, 5] so every bilinear sample reads the constant
    rois = np.array([[0, 2.0, 2.0, 5.0, 5.0]], np.float32)
    outs, _ = run_single_op(
        "roi_align", {"X": x, "ROIs": rois},
        {"pooled_height": 2, "pooled_width": 2, "spatial_scale": 1.0,
         "sampling_ratio": 2},
        ["Out"],
    )
    o = outs["Out"]
    assert o.shape == (1, 2, 2, 2)
    # interior of a constant region averages to the constant
    np.testing.assert_allclose(o[0, 0], 3.0, rtol=1e-4)
    np.testing.assert_allclose(o[0, 1], 0.0, atol=1e-6)


def test_bipartite_match_greedy():
    # dist[gt, prior]
    dist = np.array([
        [0.9, 0.1, 0.3],
        [0.8, 0.7, 0.2],
    ], np.float32)
    outs, _ = run_single_op(
        "bipartite_match", {"DistMat": dist}, {},
        ["ColToRowMatchIndices", "ColToRowMatchDist"],
    )
    cols = outs["ColToRowMatchIndices"][0]
    # greedy: (0,0)=0.9 first, then row1 takes col1 (0.7)
    assert cols[0] == 0 and cols[1] == 1 and cols[2] == -1
    np.testing.assert_allclose(
        outs["ColToRowMatchDist"][0], [0.9, 0.7, 0.0], rtol=1e-5
    )


# --- round-4: training-side target assignment ------------------------------


def test_rpn_target_assign_labels_and_deltas():
    anchors = np.array([[0, 0, 10, 10], [20, 20, 30, 30],
                        [100, 100, 110, 110], [21, 21, 31, 31]],
                       np.float32)
    gt = np.array([[[19, 19, 31, 31], [0, 0, 0, 0]]], np.float32)
    outs, _ = run_single_op(
        "rpn_target_assign",
        {"Anchor": anchors, "GtBoxes": gt,
         "ImInfo": np.array([[128, 128, 1]], np.float32)},
        {"rpn_positive_overlap": 0.7, "rpn_negative_overlap": 0.3,
         "rpn_batch_size_per_im": 4, "use_random": False},
        ["TargetLabel", "TargetBBox", "BBoxInsideWeight",
         "LocationIndex"])
    lab = outs["TargetLabel"][0]
    # anchor 1 and 3 overlap the gt strongly -> positive; 0/2 negative
    assert lab[1] == 1 and lab[3] == 1, lab
    assert lab[0] == 0 and lab[2] == 0, lab
    # deltas on a positive anchor match the closed form
    a = anchors[1]
    g = gt[0, 0]
    aw, ah = a[2] - a[0], a[3] - a[1]
    gw, gh = g[2] - g[0], g[3] - g[1]
    ref = [((g[0] + gw / 2) - (a[0] + aw / 2)) / aw,
           ((g[1] + gh / 2) - (a[1] + ah / 2)) / ah,
           np.log(gw / aw), np.log(gh / ah)]
    np.testing.assert_allclose(outs["TargetBBox"][0, 1], ref, rtol=1e-4,
                               atol=1e-4)
    # inside weights 1 exactly on positives
    np.testing.assert_allclose(outs["BBoxInsideWeight"][0, 1],
                               np.ones(4), rtol=1e-6)
    np.testing.assert_allclose(outs["BBoxInsideWeight"][0, 0],
                               np.zeros(4), rtol=1e-6)
    np.testing.assert_array_equal(outs["LocationIndex"][0],
                                  (lab == 1).astype(np.int32))


def test_rpn_target_assign_subsampling_caps_batch():
    rng = np.random.RandomState(0)
    anchors = np.concatenate(
        [np.tile([[5, 5, 15, 15]], (6, 1)) + rng.rand(6, 4),
         np.tile([[50, 50, 60, 60]], (10, 1)) + rng.rand(10, 4)],
        axis=0).astype(np.float32)
    gt = np.array([[[5, 5, 15, 15]]], np.float32)
    outs, _ = run_single_op(
        "rpn_target_assign",
        {"Anchor": anchors, "GtBoxes": gt,
         "ImInfo": np.array([[64, 64, 1]], np.float32)},
        {"rpn_batch_size_per_im": 4, "rpn_fg_fraction": 0.5,
         "use_random": False},
        ["TargetLabel"])
    lab = outs["TargetLabel"][0]
    assert (lab == 1).sum() <= 2          # fg capped at batch*fraction
    assert (lab >= 0).sum() <= 4          # total capped at batch


def test_retinanet_target_assign_class_labels():
    anchors = np.array([[0, 0, 10, 10], [20, 20, 30, 30],
                        [100, 100, 110, 110]], np.float32)
    gt = np.array([[[0, 0, 10, 10], [20, 20, 30, 30]]], np.float32)
    gl = np.array([[3, 7]], np.int64)
    outs, _ = run_single_op(
        "retinanet_target_assign",
        {"Anchor": anchors, "GtBoxes": gt, "GtLabels": gl,
         "ImInfo": np.array([[128, 128, 1]], np.float32)},
        {"positive_overlap": 0.5, "negative_overlap": 0.4},
        ["TargetLabel", "ForegroundNumber"])
    lab = outs["TargetLabel"][0]
    assert lab[0] == 3 and lab[1] == 7    # class ids, not binary
    assert lab[2] == 0                    # background
    assert int(outs["ForegroundNumber"][0, 0]) == 2


def test_generate_proposal_labels_targets():
    rois = np.array([[[0, 0, 10, 10], [50, 50, 60, 60],
                      [200, 200, 210, 210]]], np.float32)
    gt = np.array([[[1, 1, 11, 11]]], np.float32)
    gtc = np.array([[5]], np.int64)
    C = 8
    outs, _ = run_single_op(
        "generate_proposal_labels",
        {"RpnRois": rois, "GtClasses": gtc, "GtBoxes": gt,
         "ImInfo": np.array([[256, 256, 1]], np.float32)},
        {"batch_size_per_im": 4, "fg_fraction": 0.5, "fg_thresh": 0.5,
         "bg_thresh_hi": 0.5, "bg_thresh_lo": 0.0, "class_nums": C,
         "use_random": False},
        ["LabelsInt32", "BboxTargets", "BboxInsideWeights"])
    lab = outs["LabelsInt32"][0]
    assert lab[0] == 5                    # matched roi carries gt class
    assert (lab[1] == 0) and (lab[2] == 0)
    assert lab[3] == 5                    # the appended gt box itself
    # targets live only on the matched class's 4-slot block
    tgt = outs["BboxTargets"][0, 0].reshape(C, 4)
    biw = outs["BboxInsideWeights"][0, 0].reshape(C, 4)
    assert np.abs(tgt[5]).sum() > 0
    assert np.abs(np.delete(tgt, 5, axis=0)).sum() == 0
    np.testing.assert_allclose(biw[5], np.ones(4))
    assert np.abs(np.delete(biw, 5, axis=0)).sum() == 0


def test_generate_proposal_labels_no_gt_samples_background():
    rois = np.array([[[0, 0, 10, 10], [50, 50, 60, 60]]], np.float32)
    gt = np.zeros((1, 1, 4), np.float32)          # all-padding gt
    gtc = np.zeros((1, 1), np.int64)
    outs, _ = run_single_op(
        "generate_proposal_labels",
        {"RpnRois": rois, "GtClasses": gtc, "GtBoxes": gt,
         "ImInfo": np.array([[64, 64, 1]], np.float32)},
        {"batch_size_per_im": 4, "fg_thresh": 0.5, "bg_thresh_hi": 0.5,
         "bg_thresh_lo": 0.0, "class_nums": 4, "use_random": False},
        ["LabelsInt32"])
    # candidates = proposals + appended gt rows; with no valid gt ALL
    # sampled candidates are background, none foreground/ignored
    lab = outs["LabelsInt32"][0]
    assert lab.shape == (3,)                      # R + G candidates
    assert (lab == 0).all()


# --- round-5: NMS reference-compat + Index semantics ------------------------


def _reference_greedy_nms(boxes, scores, score_thr, nms_thr, nms_top_k,
                          keep_top_k, background=0):
    """Sequential greedy NMS, the reference algorithm
    (multiclass_nms_op.cc NMSFast + keep_top_k re-sort), for ONE image.
    Returns list of (label, score, box_idx)."""
    selected = []  # (label, score, idx)
    C, M = scores.shape
    for c in range(C):
        if c == background:                # reference skips background
            continue
        order = np.argsort(-scores[c], kind="stable")[:nms_top_k]
        kept = []
        for i in order:
            if scores[c, i] <= score_thr:
                continue
            ok = True
            for j in kept:
                if _iou(boxes[i:i + 1], boxes[j:j + 1])[0, 0] > nms_thr:
                    ok = False
                    break
            if ok:
                kept.append(i)
        selected += [(c, scores[c, i], i) for i in kept]
    selected.sort(key=lambda t: -t[1])
    return selected[:keep_top_k]


def test_multiclass_nms_masked_consumer_matches_reference_set():
    """Weak-item pin: the fixed-shape [N, keep_top_k, 6] output, consumed
    through the label>=0 mask, recovers exactly the detection set the
    reference's LoD-compacted variable-length output carries on a shared
    fixture."""
    r = np.random.RandomState(7)
    N, M, C = 2, 12, 3
    boxes = np.sort(r.rand(N, M, 2, 2) * 60, axis=2).reshape(N, M, 4)
    boxes[..., 2:] += 1.0
    boxes = boxes.astype(np.float32)
    scores = r.rand(N, C, M).astype(np.float32)
    attrs = {"score_threshold": 0.3, "nms_threshold": 0.4,
             "nms_top_k": 8, "keep_top_k": 6}
    outs, _ = run_single_op(
        "multiclass_nms", {"BBoxes": boxes, "Scores": scores},
        attrs, ["Out"])
    for n in range(N):
        ref = _reference_greedy_nms(
            boxes[n], scores[n], attrs["score_threshold"],
            attrs["nms_threshold"], attrs["nms_top_k"],
            attrs["keep_top_k"])
        out = outs["Out"][n]
        kept = out[out[:, 0] >= 0]              # the masked-consumer view
        assert len(kept) == len(ref), (kept, ref)
        # same (label, score) multiset, same boxes, score-descending
        got = sorted(
            [(int(l), round(float(s), 5)) for l, s in kept[:, :2]])
        want = sorted([(c, round(float(s), 5)) for c, s, _ in ref])
        assert got == want
        for (c, s, i), row in zip(ref, kept):
            assert int(row[0]) == c
            np.testing.assert_allclose(row[2:], boxes[n, i], rtol=1e-6)


def test_multiclass_nms2_index_gathers_source_boxes():
    """Index = image_idx * M + box_idx into the flattened input batch
    (reference [N,C,M] addressing, multiclass_nms_op.cc offset = i * M):
    gathering input boxes with Index must reproduce the output boxes."""
    r = np.random.RandomState(11)
    N, M, C = 2, 10, 2
    boxes = np.sort(r.rand(N, M, 2, 2) * 40, axis=2).reshape(N, M, 4)
    boxes[..., 2:] += 1.0
    boxes = boxes.astype(np.float32)
    scores = r.rand(N, C, M).astype(np.float32)
    outs, _ = run_single_op(
        "multiclass_nms2", {"BBoxes": boxes, "Scores": scores},
        {"score_threshold": 0.25, "nms_threshold": 0.5, "nms_top_k": 6,
         "keep_top_k": 5},
        ["Out", "Index"])
    out, idx = outs["Out"], outs["Index"][..., 0]
    flat = boxes.reshape(-1, 4)
    valid = out[..., 0] >= 0
    assert ((idx >= 0) == valid).all()
    # every valid slot's Index points at its own source box
    np.testing.assert_allclose(
        flat[idx[valid]], out[valid][:, 2:], rtol=1e-6)
    # and Index rows stay inside their own image's [i*M, (i+1)*M) range
    for n in range(N):
        v = idx[n][valid[n]]
        assert ((v >= n * M) & (v < (n + 1) * M)).all()


def test_rpn_target_assign_straddle_before_best_anchor_forcing():
    """ADVICE r4: with rpn_straddle_thresh=0 a gt whose BEST anchor
    crosses the image border must still get its best IN-BOUNDS anchor
    forced positive (reference filters straddlers before assignment)."""
    # anchor 0 straddles the border and overlaps the gt best; anchor 1 is
    # in-bounds with moderate (sub-threshold) overlap; anchor 2 is far.
    anchors = np.array([[-5, -5, 12, 12],      # straddler, best IoU
                        [0, 0, 10, 10],        # in-bounds, IoU ~0.47
                        [30, 30, 40, 40]], np.float32)
    gt = np.array([[[1, 1, 12, 12]]], np.float32)
    outs, _ = run_single_op(
        "rpn_target_assign",
        {"Anchor": anchors, "GtBoxes": gt,
         "ImInfo": np.array([[20, 20, 1]], np.float32)},
        {"rpn_positive_overlap": 0.7, "rpn_negative_overlap": 0.3,
         "rpn_batch_size_per_im": 4, "rpn_straddle_thresh": 0.0,
         "use_random": False},
        ["TargetLabel"])
    lab = outs["TargetLabel"][0]
    assert lab[0] == -1, lab   # straddler excluded entirely
    assert lab[1] == 1, lab    # best in-bounds anchor forced positive
