"""Fleet-wide generation observability (PR-19): the token-level SLO
engine against hand oracles, the regression sentinel (platform
matching + canary auto-reject through `ModelRegistry.promote`),
cross-process trace context + the merged per-request fleet timeline,
the injected-stall alert drill, the requeue-keeps-the-trace fix, and
the EP-MoE expert-load stats."""

import json
import http.client
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.tp_serving as tps
from paddle_tpu import models
from paddle_tpu.analysis import comm as comm_mod
from paddle_tpu.fluid import dygraph
from paddle_tpu.incubate.fault import FaultPlan
from paddle_tpu.observability import trace as T
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.observability.slo import (
    Objective,
    RegressionSentinel,
    SLOEngine,
    default_objectives,
    percentile,
)
from paddle_tpu.serving.registry import (
    READY,
    REJECTED,
    ModelRegistry,
    TransitionError,
)

gen = paddle_tpu.generation
serving = paddle_tpu.serving

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
CFG = models.TransformerLMConfig.tiny()


@pytest.fixture(scope="module")
def lm():
    with dygraph.guard():
        np.random.seed(0)
        model = models.TransformerLM(CFG)
    return model


@pytest.fixture
def tracer():
    tr = T.enable_tracing()
    tr.clear()
    yield tr
    T.disable_tracing()
    T.default_tracer().clear()


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def rec(i, outcome="ok", ttft=50.0, itl=5.0, n_tokens=8, dur=90.0,
        t_wall=1000.0):
    r = {"request_id": "r%d" % i, "trace_id": "req-0-%d" % i,
         "t_wall": t_wall, "outcome": outcome, "ttft_ms": None,
         "itl_ms": None, "n_tokens": 0, "duration_ms": None}
    if outcome == "ok":
        r.update(ttft_ms=ttft, itl_ms=itl, n_tokens=n_tokens,
                 duration_ms=dur)
    return r


def sample_requests(n, max_new=6):
    rng = np.random.RandomState(7)
    return [gen.GenerationRequest(
        rng.randint(0, CFG.vocab_size, int(rng.randint(2, 12))),
        max_new_tokens=max_new, request_id="slo%d" % i)
        for i in range(n)]


# ---------------------------------------------------------------------------
# percentile + SLO math vs hand oracles
# ---------------------------------------------------------------------------


class TestPercentile:
    def test_nearest_rank_oracle(self):
        vs = list(range(1, 11))              # 1..10
        assert percentile(vs, 50) == 5       # ceil(0.5*10) = 5th
        assert percentile(vs, 90) == 9
        assert percentile(vs, 99) == 10
        assert percentile(vs, 0) == 1
        assert percentile(vs, 100) == 10
        assert percentile([42.0], 99) == 42.0
        assert percentile([], 99) is None

    def test_order_independent(self):
        rng = np.random.RandomState(0)
        vs = list(rng.randn(37))
        shuffled = list(vs)
        rng.shuffle(shuffled)
        for q in (1, 25, 50, 75, 99):
            assert percentile(vs, q) == percentile(shuffled, q)


class TestSLOEngine:
    def _engine(self, objectives=None, **kw):
        kw.setdefault("registry", MetricsRegistry())
        kw.setdefault("clock", lambda: 1000.0)
        return SLOEngine(objectives, **kw)

    def test_objective_values_match_hand_oracle(self):
        slo = self._engine(default_objectives(
            ttft_ms_p99=100.0, itl_ms_p99=10.0))
        # 10 ok records, ttft 10..100ms; 1 shed; 1 error
        for i in range(10):
            slo.record(rec(i, ttft=10.0 * (i + 1), itl=float(i + 1)))
        slo.record(rec(10, outcome="shed"))
        slo.record(rec(11, outcome="error"))
        rep = slo.evaluate(now=1000.0)
        by = {o["name"]: o for o in rep["objectives"]}
        assert by["ttft_p99"]["value"] == 100.0       # p99 of 10 = max
        assert by["ttft_p99"]["ok"] is True
        assert by["itl_p99"]["value"] == 10.0
        assert by["shed_rate"]["value"] == pytest.approx(1 / 12)
        assert by["error_rate"]["value"] == pytest.approx(1 / 12)
        assert rep["window"] == 12

    def test_goodput_counts_per_request_not_percentile(self):
        """Goodput is per-request: 2 of 10 okay requests over the TTFT
        threshold cost goodput even while the p50 objective passes."""
        slo = self._engine([Objective("ttft_p50", "ttft_ms", 100.0,
                                      percentile=50.0)])
        for i in range(8):
            slo.record(rec(i, ttft=50.0))
        slo.record(rec(8, ttft=500.0))
        slo.record(rec(9, ttft=500.0))
        rep = slo.evaluate(now=1000.0)
        assert rep["objectives"][0]["ok"] is True     # p50 = 50ms
        assert rep["goodput"] == pytest.approx(0.8)

    def test_burn_rate_hand_oracle(self):
        """burn = bad_fraction(window) / (1 - target).  target 0.9,
        short window holds 2 bad of 4 -> 0.5/0.1 = 5.0; long window 2
        bad of 8 -> 0.25/0.1 = 2.5."""
        slo = self._engine(
            default_objectives(ttft_ms_p99=100.0, itl_ms_p99=1e9,
                               shed_rate=1.0, error_rate=1.0),
            target=0.9, burn_windows=(60.0, 600.0))
        now = 1000.0
        for i in range(4):                   # old traffic, all good
            slo.record(rec(i, ttft=50.0, t_wall=now - 300.0))
        for i in range(4, 8):                # recent: half bad
            slo.record(rec(i, ttft=(500.0 if i % 2 else 50.0),
                           t_wall=now - 10.0))
        rep = slo.evaluate(now=now)
        assert rep["burn_rate"]["60s"] == pytest.approx(5.0)
        assert rep["burn_rate"]["600s"] == pytest.approx(2.5)

    def test_empty_window_is_vacuously_met(self):
        slo = self._engine()
        rep = slo.evaluate()
        assert all(o["ok"] for o in rep["objectives"])
        assert rep["goodput"] is None
        assert rep["alerts"] == []

    def test_alert_latches_fires_once_and_clears(self, tracer):
        """The alert counter counts EDGES, not evaluations; the firing
        and clearing instants land in the tracer ring."""
        mr = MetricsRegistry()
        slo = self._engine([Objective("ttft_p99", "ttft_ms", 100.0)],
                           registry=mr, window=8)
        for i in range(8):
            slo.record(rec(i, ttft=500.0))
        slo.evaluate(now=1000.0)
        slo.evaluate(now=1001.0)             # still bad: no re-fire
        assert slo.alerts() == ["ttft_p99"]
        fired = mr.counter("slo_alerts_total", "",
                           ("slo", "objective"))
        assert fired.labels(slo.name, "ttft_p99").value == 1
        ok_g = mr.gauge("slo_objective_ok", "", ("slo", "objective"))
        assert ok_g.labels(slo.name, "ttft_p99").value == 0.0
        # clean traffic rolls the bad records out of the window
        for i in range(8):
            slo.record(rec(100 + i, ttft=10.0))
        rep = slo.evaluate(now=1002.0)
        assert rep["alerts"] == []
        assert fired.labels(slo.name, "ttft_p99").value == 1
        assert ok_g.labels(slo.name, "ttft_p99").value == 1.0
        names = [e["name"] for e in tracer.events()]
        assert "slo.alert" in names and "slo.alert_cleared" in names

    def test_live_summary_units(self):
        slo = self._engine()
        for i in range(10):
            slo.record(rec(i, ttft=10.0 * (i + 1), itl=2.0, n_tokens=10,
                           dur=100.0))
        s = slo.live_summary()
        assert s["window"] == 10
        assert s["ttft_ms_p99"] == 100.0
        assert s["itl_ms_p99"] == 2.0
        # 100 tokens over 10 * 100ms = 1s -> 100 tok/s
        assert s["tokens_per_s"] == pytest.approx(100.0)

    def test_shed_and_error_records_excluded_from_latency_math(self):
        slo = self._engine([Objective("ttft_p99", "ttft_ms", 100.0)])
        slo.record(rec(0, ttft=50.0))
        for i in range(1, 9):
            slo.record(rec(i, outcome="shed"))
        rep = slo.evaluate()
        assert rep["objectives"][0]["value"] == 50.0


# ---------------------------------------------------------------------------
# regression sentinel + canary auto-reject
# ---------------------------------------------------------------------------


class _FakeReplica:
    alive = True


def _ready_version(reg, name):
    mv = reg.begin_deploy(name, "/dev/null")
    mv.state = READY
    mv.replicas = [_FakeReplica()]
    return mv


class TestRegressionSentinel:
    BASE = {"platform": "cpu", "ttft_ms_p99": 100.0, "itl_ms_p99": 10.0,
            "tokens_per_s": 1000.0, "decode_executables": 1}

    def _sentinel(self, mr=None, **kw):
        kw.setdefault("platform", "cpu")
        return RegressionSentinel(dict(self.BASE),
                                  registry=mr or MetricsRegistry(), **kw)

    def test_within_tolerance_passes(self):
        mr = MetricsRegistry()
        s = self._sentinel(mr)
        v = s.check({"ttft_ms_p99": 120.0, "itl_ms_p99": 12.0,
                     "tokens_per_s": 900.0, "decode_executables": 1})
        assert v == {"checked": True, "regressed": False, "findings": [],
                     "platform": "cpu"}
        g = mr.gauge("serving_regression", "", ("sentinel",))
        assert g.labels(s.name).value == 0.0

    @pytest.mark.parametrize("live,metric", [
        ({"ttft_ms_p99": 130.0}, "ttft_ms_p99"),        # > 100 * 1.25
        ({"itl_ms_p99": 13.0}, "itl_ms_p99"),
        ({"tokens_per_s": 700.0}, "tokens_per_s"),      # < 1000 * 0.75
        ({"decode_executables": 2}, "decode_executables"),  # ANY growth
    ])
    def test_each_rule_fires(self, live, metric, tracer):
        mr = MetricsRegistry()
        s = self._sentinel(mr)
        v = s.check(live)
        assert v["regressed"] and \
            [f["metric"] for f in v["findings"]] == [metric]
        assert mr.gauge("serving_regression", "",
                        ("sentinel",)).labels(s.name).value == 1.0
        assert any(e["name"] == "sentinel.regression"
                   for e in tracer.events())
        # recovery clears the gauge
        s.check({metric: self.BASE[metric]})
        assert mr.gauge("serving_regression", "",
                        ("sentinel",)).labels(s.name).value == 0.0

    def test_platform_mismatch_never_gates(self):
        """A CPU smoke baseline can NOT judge a TPU fleet: the check is
        skipped, gauge untouched."""
        mr = MetricsRegistry()
        s = RegressionSentinel(dict(self.BASE), registry=mr,
                               platform="tpu")
        v = s.check({"ttft_ms_p99": 9999.0})
        assert v["checked"] is False and v["regressed"] is False
        assert "cpu" in v["skipped"] and "tpu" in v["skipped"]
        checks = mr.counter("serving_regression_checks_total", "",
                            ("sentinel", "verdict"))
        assert checks.labels(s.name, "skipped").value == 1

    def test_from_bench_file(self, tmp_path):
        p = tmp_path / "BENCH_r99.json"
        p.write_text(json.dumps([
            {"metric": "ttft_ms_p99", "value": 80.0, "platform": "cpu"},
            {"metric": "tokens_per_s", "value": 500.0, "platform": "cpu"},
            {"metric": "unrelated", "value": 1.0, "platform": "cpu"},
        ]))
        s = RegressionSentinel.from_bench_file(
            str(p), registry=MetricsRegistry(), platform="cpu")
        assert s.baseline == {"platform": "cpu", "ttft_ms_p99": 80.0,
                              "tokens_per_s": 500.0}
        assert s.check({"ttft_ms_p99": 79.0})["regressed"] is False
        assert s.check({"ttft_ms_p99": 200.0})["regressed"] is True

    def test_bench_records_without_platform_default_tpu(self, tmp_path):
        p = tmp_path / "BENCH_r04.json"
        p.write_text(json.dumps([{"metric": "itl_ms_p99", "value": 5.0}]))
        s = RegressionSentinel.from_bench_file(
            str(p), registry=MetricsRegistry(), platform="cpu")
        assert s.baseline["platform"] == "tpu"
        assert s.check({"itl_ms_p99": 9999.0})["checked"] is False

    def test_promote_gate_rejects_regressing_canary(self):
        """The acceptance drill: a canary burning the budget auto-
        rejects at promote; the stable pointer never moves."""
        reg = ModelRegistry()
        stable = _ready_version(reg, "v1")
        reg.promote("v1")
        canary = _ready_version(reg, "v2")
        mr = MetricsRegistry()
        slo = SLOEngine(registry=mr, name="canary",
                        clock=lambda: 1000.0)
        for i in range(16):
            slo.record(rec(i, ttft=400.0))   # 4x the baseline TTFT
        s = self._sentinel(mr, name="canary")
        with pytest.raises(TransitionError, match="SLO gate"):
            reg.promote("v2", slo_gate=s.gate(slo.live_summary))
        assert reg.stable == "v1" and stable.state == "serving"
        assert canary.state == REJECTED
        assert "ttft_ms_p99" in canary.error

    def test_promote_gate_passes_healthy_canary(self):
        reg = ModelRegistry()
        _ready_version(reg, "v1")
        reg.promote("v1")
        canary = _ready_version(reg, "v2")
        mr = MetricsRegistry()
        slo = SLOEngine(registry=mr, clock=lambda: 1000.0)
        for i in range(16):
            # dur chosen so tokens_per_s clears the throughput rule too
            slo.record(rec(i, ttft=50.0, itl=5.0, dur=8.0))
        s = self._sentinel(mr)
        old = reg.promote("v2", slo_gate=s.gate(slo.live_summary))
        assert reg.stable == "v2" and canary.state == "serving"
        assert old is not None and old.version == "v1"

    def test_promote_gate_raising_rejects(self):
        reg = ModelRegistry()
        _ready_version(reg, "v2")

        def broken():
            raise RuntimeError("scrape failed")

        with pytest.raises(TransitionError, match="gate raised"):
            reg.promote("v2", slo_gate=broken)
        assert reg.get("v2").state == REJECTED

    def test_promote_gate_rejects_on_active_alerts(self):
        reg = ModelRegistry()
        _ready_version(reg, "v2")
        with pytest.raises(TransitionError, match="active SLO alerts"):
            reg.promote("v2", slo_gate=lambda: {
                "regressed": False, "alerts": ["itl_p99"]})


# ---------------------------------------------------------------------------
# trace context + merged fleet timeline
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_wire_roundtrip(self):
        tc = T.TraceContext()
        wire = tc.to_wire()
        assert set(wire) == {"trace_id", "anchor_unix_time",
                             "anchor_clock"}
        json.dumps(wire)                      # JSON-safe by contract
        back = T.TraceContext.from_wire(wire)
        assert back.trace_id == tc.trace_id
        assert back.anchor == tc.anchor

    def test_child_carries_parent(self):
        tc = T.TraceContext(trace_id="req-1-1")
        ch = tc.child("prefill")
        assert ch.trace_id == "req-1-1" and ch.parent == "prefill"
        assert "parent" in ch.to_wire()

    def test_from_wire_none_and_passthrough(self):
        assert T.TraceContext.from_wire(None) is None
        tc = T.TraceContext()
        assert T.TraceContext.from_wire(tc) is tc


def _shard(pid, events, anchor):
    md = {"process_name": "p%d" % pid, "pid": pid}
    if anchor is not None:
        md.update(anchor_unix_time=anchor[0], anchor_clock=anchor[1])
    return {"traceEvents": events, "metadata": md}


def _async_ev(ph, name, tid, pid, ts):
    return {"ph": ph, "name": name, "id": tid, "cat": "generation",
            "pid": pid, "tid": 1, "ts": ts}


class TestMergeFleetTrace:
    def test_filters_to_one_request_and_aligns(self):
        """Two process shards with different anchors merge onto ONE
        clock; ?trace_id keeps only that request's events."""
        a = _shard(1, [_async_ev("b", "prefill", "req-1-1", 1, 0),
                       _async_ev("e", "prefill", "req-1-1", 1, 50),
                       _async_ev("b", "prefill", "req-1-2", 1, 60)],
                   anchor=(100.0, 0.0))
        # pid 2's clock started 1s later: its ts 0 is 1e6us after pid 1's
        b = _shard(2, [_async_ev("b", "handoff", "req-1-1", 2, 0)],
                   anchor=(101.0, 0.0))
        merged = T.merge_fleet_trace([a, b], trace_id="req-1-1")
        assert merged["metadata"]["trace_id"] == "req-1-1"
        assert merged["metadata"]["aligned"] is True
        evs = [e for e in merged["traceEvents"] if e["ph"] != "M"]
        assert all(e["id"] == "req-1-1" for e in evs)
        by = {(e["pid"], e["ph"], e["name"]): e["ts"] for e in evs}
        assert by[(2, "b", "handoff")] - by[(1, "b", "prefill")] \
            == 1_000_000

    def test_anchorless_shard_disables_alignment(self):
        a = _shard(1, [_async_ev("b", "x", "t", 1, 0)], anchor=(5.0, 0.0))
        b = _shard(2, [_async_ev("b", "y", "t", 2, 0)], anchor=None)
        merged = T.merge_fleet_trace([a, b])
        assert merged["metadata"]["aligned"] is False

    def test_save_roundtrip(self, tmp_path):
        a = _shard(1, [_async_ev("n", "token", "t", 1, 3)],
                   anchor=(5.0, 0.0))
        out = tmp_path / "fleet_trace.json"
        T.merge_fleet_trace([a], out_path=str(out))
        assert json.loads(out.read_text())["traceEvents"]


def async_events(evs, trace_id=None):
    out = [(e["ph"], e["name"]) for e in evs
           if e.get("ph") in ("b", "e", "n")
           and (trace_id is None or e.get("id") == trace_id)]
    return out


class TestRequestTimeline:
    def _engine(self, lm, **kw):
        kw.setdefault("slots", 2)
        kw.setdefault("max_len", 64)
        kw.setdefault("prefill_buckets", [8, 16])
        kw.setdefault("max_queue", 16)
        return gen.GenerationEngine(lm, **kw)

    def test_one_request_one_ordered_track(self, lm, tracer):
        """queue -> prefill -> per-token decode -> end, all under the
        handle's trace_id, schema-valid."""
        from test_trace import validate_chrome_trace

        eng = self._engine(lm).start()
        try:
            h = eng.submit(gen.GenerationRequest([1, 2, 3, 4],
                                                 max_new_tokens=4))
            h.result(timeout=30.0)
        finally:
            eng.stop()
        tid = h.trace.trace_id
        seq = async_events(tracer.events(), tid)
        assert seq[0] == ("b", "request")
        assert seq[-1] == ("e", "request")
        assert ("b", "queue") in seq and ("e", "queue") in seq
        assert seq.index(("b", "prefill")) < seq.index(("e", "prefill"))
        assert seq.count(("n", "token")) == 4
        assert seq.index(("e", "prefill")) \
            < seq.index(("n", "token"))
        validate_chrome_trace(tracer.chrome_trace())

    def test_disagg_handoff_rides_the_same_trace(self, lm, tracer):
        """The DistServe split: prefill engine -> KVHandoff -> decode
        engine, ONE timeline — handoff b/e brackets the inject, tokens
        follow, all on one trace_id."""
        prefill = self._engine(lm, block_size=16, kv_blocks=10)
        decode = self._engine(lm, block_size=16, kv_blocks=14)
        pair = tps.DisaggPair(prefill, decode, group_id=0)
        h = pair.submit(gen.GenerationRequest([1, 2, 3, 4, 5],
                                              max_new_tokens=3))
        pair.run_until_idle()
        assert len(h.result(timeout=30.0)) == 3
        tid = h.trace.trace_id
        assert getattr(h.trace, "parent", None) == "prefill"
        seq = async_events(tracer.events(), tid)
        for marker in [("b", "request"), ("b", "prefill"),
                       ("e", "prefill"), ("b", "handoff"),
                       ("e", "handoff"), ("n", "inject"),
                       ("n", "token"), ("e", "request")]:
            assert marker in seq, (marker, seq)
        assert seq.index(("e", "prefill")) < seq.index(("b", "handoff"))
        assert seq.index(("e", "handoff")) < seq.index(("n", "inject"))
        merged = T.merge_fleet_trace([tracer.chrome_trace()],
                                     trace_id=tid)
        assert merged["metadata"]["aligned"] is True
        assert all(e["ph"] == "M" or e.get("id") == tid
                   or e.get("args", {}).get("trace_id") == tid
                   for e in merged["traceEvents"])

    def test_requeue_after_death_keeps_original_trace(self, lm, tracer):
        """Satellite (b): the replacement replica's spans carry the
        ORIGINAL trace — death, requeue and restart are instants on the
        same track, not a fresh anonymous trace."""
        plan = FaultPlan([], rank=0)
        plan.add("kill_replica", replica=0, request=3)
        fleet = serving.GenerationFleet(
            lm, replicas=2, fault_plan=plan, slots=2, max_len=64,
            prefill_buckets=[8, 16], max_queue=32).start()
        try:
            handles = [fleet.submit(r)
                       for r in sample_requests(4, max_new=8)]
            for h in handles:
                h.result(timeout=60.0)
        finally:
            fleet.stop()
        requeued = [h for h in handles if h.requeued]
        assert requeued, "the dead replica held in-flight requests"
        for h in requeued:
            tid = h.trace.trace_id
            seq = async_events(tracer.events(), tid)
            assert ("n", "replica_death") in seq, seq
            assert ("n", "requeue") in seq, seq
            assert ("n", "restart") in seq, seq
            # one request track: exactly one b/e pair, re-queued between
            assert seq.count(("b", "request")) == 1
            assert seq.count(("e", "request")) == 1
            assert seq.count(("b", "queue")) == 2
            # token indices restart at 0 on the replacement replica
            toks = [e["args"]["index"] for e in tracer.events()
                    if e.get("ph") == "n" and e.get("id") == tid
                    and e["name"] == "token"]
            assert toks.count(0) == 2


# ---------------------------------------------------------------------------
# injected stall -> alert fires -> clean traffic clears it
# ---------------------------------------------------------------------------


class TestStallDrill:
    def test_stall_fires_and_clears_itl_alert(self, lm):
        """A 900ms decode stall on replica 0 blows a 50ms ITL p99
        objective; once clean traffic rolls the stalled requests out of
        the (small) window, the alert clears."""
        plan = FaultPlan([], rank=0)
        plan.add("stall_replica", replica=0, step=2, seconds=0.9)
        mr = MetricsRegistry()
        slo = SLOEngine(
            [Objective("itl_p99", "itl_ms", 50.0)],
            registry=mr, window=8, name="drill")
        fleet = serving.GenerationFleet(
            lm, replicas=1, fault_plan=plan, slo=slo, slots=2,
            max_len=64, prefill_buckets=[8, 16], max_queue=32,
            metrics_registry=mr).start()
        try:
            for h in [fleet.submit(r)
                      for r in sample_requests(2, max_new=6)]:
                h.result(timeout=60.0)
            rep = fleet.slo.report()
            assert rep["alerts"] == ["itl_p99"], rep
            assert rep["objectives"][0]["value"] > 50.0
            # clean traffic: the stall was one-shot, window rolls over
            for h in [fleet.submit(r)
                      for r in sample_requests(8, max_new=4)]:
                h.result(timeout=60.0)
            rep = fleet.slo.report()
            assert rep["alerts"] == [], rep
        finally:
            fleet.stop()
        fired = mr.counter("slo_alerts_total", "", ("slo", "objective"))
        assert fired.labels("drill", "itl_p99").value == 1


# ---------------------------------------------------------------------------
# /slo + /trace endpoints and serving_ctl contracts
# ---------------------------------------------------------------------------


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    return resp.status, body


def _ctl(port, *argv):
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serving_ctl.py"),
         "--endpoint", "http://127.0.0.1:%d" % port, "--json"] +
        list(argv),
        capture_output=True, text=True, timeout=120)
    out = json.loads(p.stdout) if p.stdout.strip() else None
    return p.returncode, out


class TestHTTPAndCtl:
    @pytest.fixture()
    def fleet_server(self, lm):
        # latency thresholds sky-high: CPU compile time must not flake
        # the rc contracts (the error-rate objective does the alerting)
        fleet = serving.GenerationFleet(
            lm, replicas=1, slots=2, max_len=64,
            prefill_buckets=[8, 16], max_queue=32,
            slo_objectives=default_objectives(
                ttft_ms_p99=1e9, itl_ms_p99=1e9)).start()
        port = free_port()
        httpd = serving.serve_generation_http(
            fleet, port=port, block=False)
        yield fleet, port
        httpd.shutdown()
        fleet.stop()

    def test_slo_and_trace_endpoints(self, fleet_server, tracer):
        fleet, port = fleet_server
        for h in [fleet.submit(r) for r in sample_requests(3)]:
            h.result(timeout=60.0)
        code, rep = _get(port, "/slo")
        assert code == 200 and rep["window"] == 3
        assert rep["goodput"] == 1.0 and rep["alerts"] == []
        tid = None
        for e in T.default_tracer().events():
            if e.get("ph") == "b" and e["name"] == "request":
                tid = e["id"]
        code, tr = _get(port, "/trace?trace_id=%s" % tid)
        assert code == 200
        assert tr["metadata"]["trace_id"] == tid
        assert tr["metadata"]["aligned"] is True
        assert any(e.get("ph") == "n" and e["name"] == "token"
                   for e in tr["traceEvents"])

    def test_trace_409_when_disabled(self, fleet_server):
        _, port = fleet_server
        code, body = _get(port, "/trace")
        assert code == 409 and "tracing disabled" in body["error"]
        rc, _out = _ctl(port, "trace")
        assert rc == 1

    def test_ctl_slo_rc_contract(self, fleet_server):
        fleet, port = fleet_server
        for h in [fleet.submit(r) for r in sample_requests(2)]:
            h.result(timeout=60.0)
        rc, out = _ctl(port, "slo")
        assert rc == 0 and out["response"]["window"] == 2
        # active alert -> rc 1 (the cron probe pages by exit code)
        fleet.slo.record(rec(99, ttft=1e9, itl=1e9))
        fleet.slo.record(rec(100, outcome="error"))
        fleet.slo.evaluate()
        rc, out = _ctl(port, "slo")
        assert rc == 1 and out["response"]["alerts"]

    def test_ctl_trace_out_writes_merged_json(self, fleet_server,
                                              tracer, tmp_path):
        from test_trace import validate_chrome_trace

        fleet, port = fleet_server
        for h in [fleet.submit(r) for r in sample_requests(1)]:
            h.result(timeout=60.0)
        out = tmp_path / "trace.json"
        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "serving_ctl.py"),
             "--endpoint", "http://127.0.0.1:%d" % port,
             "trace", "--out", str(out)],
            capture_output=True, text=True, timeout=120)
        assert p.returncode == 0, p.stderr
        validate_chrome_trace(json.loads(out.read_text()))


# ---------------------------------------------------------------------------
# EP-MoE expert-load stats (satellite a)
# ---------------------------------------------------------------------------


class TestExpertStats:
    def _build(self, e=8, d=16, h=32, top_k=2):
        with dygraph.guard():
            np.random.seed(3)
            moe = models.MoEFFN(d, h, num_experts=e,
                                capacity_factor=8.0, top_k=top_k)
            params = tps.moe.moe_params(moe)
        x = np.random.RandomState(5).randn(32, d).astype(np.float32)
        return params, x

    def test_counts_opt_in_and_output_identical(self):
        params, x = self._build()
        mesh = tps.tp_mesh(4)
        y0 = np.asarray(tps.build_ep_moe(
            mesh, 8, capacity_factor=8.0, top_k=2)(params, x))
        y1, counts = tps.build_ep_moe(
            mesh, 8, capacity_factor=8.0, top_k=2,
            expert_stats=True)(params, x)
        np.testing.assert_allclose(np.asarray(y1), y0, rtol=1e-6)
        counts = np.asarray(counts)
        assert counts.shape == (4, 8)        # [source chip, expert]
        # ample capacity: every token * top_k dispatched somewhere
        assert counts.sum() == 32 * 2

    def test_collective_pin_survives_expert_stats(self):
        """The counts reduce the one-hots already in hand: the compiled
        module still holds EXACTLY two all-to-alls."""
        params, x = self._build()
        mesh = tps.tp_mesh(4)
        fn = tps.build_ep_moe(mesh, 8, capacity_factor=8.0, top_k=2,
                              expert_stats=True)
        hlo = fn.lower(params, x).compile().as_text()
        stats = comm_mod.hlo_collective_stats(hlo, 4)
        assert stats["all-to-all"]["count"] == 2

    def test_record_expert_load_registry_series(self):
        mr = MetricsRegistry()
        out = tps.record_expert_load([[4.0, 0.0], [2.0, 2.0]],
                                     registry=mr, name="m0")
        assert out["counts"] == [6.0, 2.0]
        assert out["imbalance"] == pytest.approx(1.5)   # 6 / mean(4)
        c = mr.counter("ep_moe_expert_tokens_total", "",
                       ("moe", "expert"))
        assert c.labels("m0", "0").value == 6.0
        assert c.labels("m0", "1").value == 2.0
        g = mr.gauge("ep_moe_hot_expert_imbalance", "", ("moe",))
        assert g.labels("m0").value == pytest.approx(1.5)
        with pytest.raises(ValueError):
            tps.record_expert_load(np.zeros((2, 2, 2)), registry=mr)


# ---------------------------------------------------------------------------
# cross-process drill: prefill worker -> KVHandoff -> decode worker,
# ONE anchored timeline (slow: two real subprocesses load the model)
# ---------------------------------------------------------------------------


class _DrillWorker:
    """Parent end of one gen_trace_worker.py subprocess, speaking the
    serving pipe protocol over a private fd pair."""

    def __init__(self, role):
        from paddle_tpu.serving.replica import (
            WORKER_RFD_ENV,
            WORKER_WFD_ENV,
            read_frame,
            write_frame,
        )

        self._read_frame, self._write_frame = read_frame, write_frame
        c2w_r, c2w_w = os.pipe()
        w2c_r, w2c_w = os.pipe()
        env = dict(os.environ)
        env[WORKER_RFD_ENV] = str(c2w_r)
        env[WORKER_WFD_ENV] = str(w2c_w)
        env.setdefault("PYTHONPATH", REPO)
        self.proc = subprocess.Popen(
            [sys.executable, os.path.join(HERE, "gen_trace_worker.py"),
             role],
            env=env, pass_fds=(c2w_r, w2c_w), close_fds=True)
        os.close(c2w_r)
        os.close(w2c_w)
        self.w = os.fdopen(c2w_w, "wb")
        self.r = os.fdopen(w2c_r, "rb")
        kind, self.pid = self._read_frame(self.r)
        assert kind == "ready"

    def call(self, *msg):
        self._write_frame(self.w, msg)
        reply = self._read_frame(self.r)
        assert reply is not None and reply[0] == "ok", reply
        return reply[1]

    def close(self):
        try:
            self._write_frame(self.w, ("close",))
        except Exception:
            pass
        self.proc.wait(timeout=30)


@pytest.mark.slow
class TestCrossProcessDrill:
    def test_one_request_one_anchored_timeline_across_pids(self, tracer):
        """The tentpole acceptance drill: a disaggregated request whose
        prefill and decode run in DIFFERENT processes merges into ONE
        anchor-aligned timeline — handoff begins on the prefill pid,
        ends on the decode pid, tokens follow in order."""
        from test_trace import validate_chrome_trace

        prefill = _DrillWorker("prefill")
        decode = _DrillWorker("decode")
        try:
            tc = T.TraceContext()
            with T.span("drill.submit", cat="generation",
                        trace_id=tc.trace_id):
                handoff = prefill.call(
                    "prefill",
                    {"prompt_ids": [1, 2, 3, 4, 5],
                     "max_new_tokens": 4, "request_id": "xp0"},
                    tc.to_wire())
            # the handoff crossed the pipe carrying the SAME trace
            assert handoff.trace["trace_id"] == tc.trace_id
            assert handoff.trace["parent"] == "prefill"
            tokens = decode.call("decode", handoff)
            assert len(tokens) == 4
            shard_p = prefill.call("trace")
            shard_d = decode.call("trace")
        finally:
            prefill.close()
            decode.close()

        merged = T.merge_fleet_trace(
            [tracer.chrome_trace(), shard_p, shard_d],
            trace_id=tc.trace_id)
        assert merged["metadata"]["trace_id"] == tc.trace_id
        assert merged["metadata"]["aligned"] is True
        validate_chrome_trace(merged)
        evs = [e for e in merged["traceEvents"]
               if e.get("ph") in ("b", "e", "n")]
        assert {e["id"] for e in evs} == {tc.trace_id}
        assert {e["pid"] for e in evs} == {prefill.pid, decode.pid}

        def ts(ph, name, pid):
            hits = [e["ts"] for e in evs
                    if e["ph"] == ph and e["name"] == name
                    and e["pid"] == pid]
            assert hits, (ph, name, pid, evs)
            return hits[0]

        # the phase chain, on the ALIGNED clock, hopping processes:
        assert ts("b", "prefill", prefill.pid) \
            <= ts("e", "prefill", prefill.pid) \
            <= ts("b", "handoff", prefill.pid) \
            <= ts("e", "handoff", decode.pid) \
            <= ts("n", "inject", decode.pid)
        toks = sorted(
            (e["ts"], e["args"]["index"]) for e in evs
            if e["ph"] == "n" and e["name"] == "token")
        assert [i for _, i in toks] == [0, 1, 2, 3]
        assert toks[0][0] >= ts("n", "inject", decode.pid)
