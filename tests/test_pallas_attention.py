"""Pallas flash-attention kernels vs naive oracle (interpret mode on CPU).

Mirrors the reference fused-op test pattern (fused kernel vs composed ops,
cf. test_fused_multihead_matmul_op.py): forward + gradients, with/without
causal masking and padding bias.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.attention import _naive_attention
from paddle_tpu.ops.pallas.attention import flash_attention


def _rand(shape, seed):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_naive(causal):
    B, H, S, D = 2, 2, 256, 128
    q, k, v = _rand((B, H, S, D), 0), _rand((B, H, S, D), 1), _rand((B, H, S, D), 2)
    scale = D ** -0.5
    out = flash_attention(q, k, v, scale=scale, causal=causal, interpret=True)
    ref = _naive_attention(q, k, v, None, scale, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_flash_forward_with_padding_bias():
    B, H, S, D = 1, 2, 256, 128
    q, k, v = _rand((B, H, S, D), 3), _rand((B, H, S, D), 4), _rand((B, H, S, D), 5)
    mask = np.ones((B, 1, 1, S), np.float32)
    mask[:, :, :, S // 2:] = -10000.0  # pad out second half
    bias = jnp.asarray(mask * 0 + np.where(mask > 0, 0.0, -10000.0))
    bias = jnp.asarray(np.where(np.arange(S)[None, None, None, :] < S // 2, 0.0,
                                -10000.0).astype(np.float32))
    scale = D ** -0.5
    out = flash_attention(q, k, v, bias=bias, scale=scale, interpret=True)
    ref = _naive_attention(q, k, v, bias, scale, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_matches_naive(causal):
    B, H, S, D = 1, 1, 256, 128
    q, k, v = _rand((B, H, S, D), 6), _rand((B, H, S, D), 7), _rand((B, H, S, D), 8)
    scale = D ** -0.5

    def f_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, scale=scale, causal=causal, interpret=True)
            * 0.01
        )

    def f_naive(q, k, v):
        return jnp.sum(_naive_attention(q, k, v, None, scale, causal) * 0.01)

    g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    g_naive = jax.grad(f_naive, argnums=(0, 1, 2))(q, k, v)
    for gf, gn, name in zip(g_flash, g_naive, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gn), rtol=5e-4, atol=5e-4,
            err_msg="d%s mismatch" % name,
        )


def test_flash_non_divisible_seq_falls_back():
    """S=192 divides no supported block: must fall back to naive, never
    silently truncate."""
    B, H, S, D = 1, 1, 192, 128
    q, k, v = _rand((B, H, S, D), 20), _rand((B, H, S, D), 21), _rand((B, H, S, D), 22)
    out = flash_attention(q, k, v, interpret=True)
    ref = _naive_attention(q, k, v, None, D ** -0.5, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_backward_with_bias_grad():
    B, H, S, D = 1, 2, 256, 128
    q, k, v = _rand((B, H, S, D), 9), _rand((B, H, S, D), 10), _rand((B, H, S, D), 11)
    bias = jnp.zeros((B, 1, 1, S), jnp.float32)
    scale = D ** -0.5

    def f_flash(q, k, v, b):
        return jnp.sum(flash_attention(q, k, v, bias=b, scale=scale,
                                       interpret=True) * 0.01)

    def f_naive(q, k, v, b):
        return jnp.sum(_naive_attention(q, k, v, b, scale, False) * 0.01)

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v, bias)
    gn = jax.grad(f_naive, argnums=(0, 1, 2))(q, k, v, bias)
    for a, b_, name in zip(gf, gn, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg="d%s mismatch" % name)


@pytest.mark.parametrize("sq,sk", [(200, 200), (96, 96), (300, 260)])
def test_flash_pad_to_block_matches_naive(sq, sk):
    """Non-128-divisible seqs keep the kernel path via pad+slice."""
    B, H, D = 1, 2, 128
    q, k, v = _rand((B, H, sq, D), 20), _rand((B, H, sk, D), 21), _rand((B, H, sk, D), 22)
    scale = D ** -0.5
    out = flash_attention(q, k, v, scale=scale, interpret=True)
    ref = _naive_attention(q, k, v, None, scale, False)
    assert out.shape == (B, H, sq, D)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_flash_pad_causal_and_grads():
    import jax

    B, H, S, D = 1, 1, 200, 128
    q, k, v = _rand((B, H, S, D), 23), _rand((B, H, S, D), 24), _rand((B, H, S, D), 25)
    scale = D ** -0.5
    out = flash_attention(q, k, v, scale=scale, causal=True, interpret=True)
    ref = _naive_attention(q, k, v, None, scale, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    g1 = jax.grad(lambda q_: (flash_attention(q_, k, v, scale=scale,
                                              causal=True,
                                              interpret=True) ** 2).sum())(q)
    g2 = jax.grad(lambda q_: (_naive_attention(q_, k, v, None, scale,
                                               True) ** 2).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=2e-3, atol=2e-3)


def test_flash_pad_with_segments_and_bias():
    B, H, S, D = 1, 1, 200, 128
    q, k, v = _rand((B, H, S, D), 26), _rand((B, H, S, D), 27), _rand((B, H, S, D), 28)
    seg = jnp.asarray(
        np.repeat([1, 2], [80, 120])[None, :].astype(np.int32))
    scale = D ** -0.5
    from paddle_tpu.ops.attention import _segment_bias

    out = flash_attention(q, k, v, segment_ids=seg, scale=scale,
                          interpret=True)
    ref = _naive_attention(q, k, v, _segment_bias(seg), scale, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_causal_cross_attention_bottom_right_aligned():
    """sq != sk causal must match the naive tril(k=Sk-Sq) alignment."""
    B, H, D = 1, 1, 128
    for sq, sk in [(128, 256), (200, 260), (256, 128)]:
        q = _rand((B, H, sq, D), 30)
        k = _rand((B, H, sk, D), 31)
        v = _rand((B, H, sk, D), 32)
        scale = D ** -0.5
        out = flash_attention(q, k, v, scale=scale, causal=True,
                              interpret=True)
        ref = _naive_attention(q, k, v, None, scale, True)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4,
            err_msg="sq=%d sk=%d" % (sq, sk),
        )
        # causal CROSS-attention gradients (all three operands)
        import jax as _jax

        g1 = _jax.grad(
            lambda q_, k_, v_: (flash_attention(
                q_, k_, v_, scale=scale, causal=True, interpret=True,
            ) ** 2).sum(), argnums=(0, 1, 2))(q, k, v)
        g2 = _jax.grad(
            lambda q_, k_, v_: (_naive_attention(
                q_, k_, v_, None, scale, True) ** 2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a_, b_ in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a_), np.asarray(b_), rtol=2e-3, atol=2e-3,
                err_msg="grad sq=%d sk=%d" % (sq, sk),
            )


def test_flash_head_dim_64():
    """BERT-shaped heads (d=64) must take the kernel path (the head dim is
    never split; its block equals the full dim)."""
    import jax

    B, H, S, D = 1, 2, 256, 64
    q, k, v = _rand((B, H, S, D), 40), _rand((B, H, S, D), 41), _rand((B, H, S, D), 42)
    scale = D ** -0.5
    out = flash_attention(q, k, v, scale=scale, interpret=True)
    ref = _naive_attention(q, k, v, None, scale, False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    g1 = jax.grad(lambda q_: (flash_attention(q_, k, v, scale=scale,
                                              interpret=True) ** 2).sum())(q)
    g2 = jax.grad(lambda q_: (_naive_attention(q_, k, v, None, scale,
                                               False) ** 2).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_bshd_layout_matches_bhsd(causal):
    """BSHD (no-transpose) layout must agree with the BHSD path, forward
    and gradients, with segments + bias."""
    B, H, S, D = 2, 3, 256, 64
    q, k, v = (_rand((B, H, S, D), i) for i in range(3))
    bias = jnp.where(
        jnp.arange(S)[None, None, None, :] < S - 17, 0.0, -1e30
    ).astype(jnp.float32) * jnp.ones((B, 1, 1, S))
    seg = jnp.asarray(
        np.random.RandomState(7).randint(0, 3, (B, S)).cumsum(axis=1) // 7
    )

    def f_bhsd(q, k, v):
        return flash_attention(q, k, v, bias=bias, segment_ids=seg,
                               causal=causal, interpret=True).sum()

    def f_bshd(q, k, v):
        qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
        return flash_attention(qt, kt, vt, bias=bias, segment_ids=seg,
                               causal=causal, interpret=True,
                               layout="BSHD").sum()

    o1, g1 = jax.value_and_grad(f_bhsd, argnums=(0, 1, 2))(q, k, v)
    o2, g2 = jax.value_and_grad(f_bshd, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(float(o1), float(o2), rtol=1e-4)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-4)


def test_bshd_pad_path():
    B, H, S, D = 1, 2, 200, 64  # pads to 256
    q, k, v = (_rand((B, S, H, D), i) for i in range(3))
    out = flash_attention(q, k, v, interpret=True, layout="BSHD")
    ref = _naive_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), None, D ** -0.5, False
    ).transpose(0, 2, 1, 3)
    assert out.shape == (B, S, H, D)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


def test_bf16_accumulator_flag_tolerance_policy(monkeypatch):
    """PADDLE_TPU_FLASH_ACC=bf16 trades accumulator precision for VMEM
    on MULTI-block schedules.  Tolerance policy (the reference AMP
    white_list pattern — looser, documented bounds for a reduced-
    precision mode): forward rtol 2e-2 vs the f32-accumulator kernel;
    gradients rtol 5e-2.  The default (f32) path must be unaffected by
    the flag machinery."""
    rng = np.random.RandomState(0)
    B, H, S, D = 1, 2, 1024, 64    # S=1024, block 512 -> 2x2 blocks
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    scale = D ** -0.5

    def run(acc):
        if acc:
            monkeypatch.setenv("PADDLE_TPU_FLASH_ACC", acc)
        else:
            monkeypatch.delenv("PADDLE_TPU_FLASH_ACC", raising=False)

        def f(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, scale=scale, causal=True,
                                interpret=True) * 0.01)

        out = flash_attention(q, k, v, scale=scale, causal=True,
                              interpret=True)
        grads = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        return out, grads

    out32, g32 = run(None)
    out16, g16 = run("bf16")
    # f32 path tracks the oracle tightly
    ref = _naive_attention(q, k, v, None, scale, True)
    np.testing.assert_allclose(np.asarray(out32), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
    # the flag must actually take effect: bf16 accumulation noise makes
    # the outputs differ (a vacuous pass would mean the knob regressed)
    assert np.abs(np.asarray(out16) - np.asarray(out32)).max() > 0, \
        "PADDLE_TPU_FLASH_ACC=bf16 had no effect"
    # bf16 accumulators: documented looser bounds
    np.testing.assert_allclose(np.asarray(out16), np.asarray(out32),
                               rtol=2e-2, atol=2e-2)
    for a, b, name in zip(g16, g32, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-2, atol=5e-2,
            err_msg="bf16-acc grad tolerance exceeded for %s" % name)


def test_fused_single_block_backward_matches_two_kernel(monkeypatch):
    """The fused single-block backward (PADDLE_TPU_FLASH_FUSED_BWD,
    default on) must produce the same gradients as the two-kernel
    schedule on the shapes it serves (nq == nk == 1), including bias and
    segment ids."""
    rng = np.random.RandomState(3)
    B, H, S, D = 2, 2, 128, 64
    q = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    k = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    v = jnp.asarray(rng.randn(B, H, S, D).astype(np.float32)) * 0.3
    bias = jnp.asarray(
        np.where(rng.rand(B, 1, 1, S) < 0.2, -1e30, 0.0).astype(np.float32))
    scale = D ** -0.5

    seg = jnp.asarray(
        np.repeat(np.arange(4), S // 4)[None, :].repeat(B, 0)
        .astype(np.int32))            # 4 packed segments per row

    def grads(fused, with_seg):
        monkeypatch.setenv("PADDLE_TPU_FLASH_FUSED_BWD",
                           "1" if fused else "0")

        def f(q, k, v, bias):
            return jnp.sum(
                flash_attention(q, k, v, bias=bias,
                                segment_ids=seg if with_seg else None,
                                scale=scale, causal=True,
                                interpret=True) * 0.01)

        return jax.grad(f, argnums=(0, 1, 2, 3))(q, k, v, bias)

    for with_seg in (False, True):
        gf = grads(True, with_seg)
        gt = grads(False, with_seg)
        for a, b, name in zip(gf, gt, ["q", "k", "v", "bias"]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5,
                err_msg="fused-bwd grad mismatch for %s (seg=%s)"
                        % (name, with_seg))


def test_explicit_block_override_changes_lowered_grid(monkeypatch):
    """block_q/block_k are a hard contract: an explicit override must
    actually change the pallas grid (the knob the autotuner searches),
    not silently fall back to the heuristic."""
    from paddle_tpu.ops.pallas import attention as A

    B, H, S, D = 1, 2, 512, 64
    q = _rand((B, H, S, D), 11)
    grids = []
    orig = A.pl.pallas_call

    def spy(*args, **kw):
        grids.append(kw.get("grid"))
        return orig(*args, **kw)

    monkeypatch.setattr(A.pl, "pallas_call", spy)
    A.flash_attention(q, q, q, interpret=True)
    default_grid = grids[-1]
    grids.clear()
    A.flash_attention(q, q, q, interpret=True, block_q=128, block_k=256)
    override_grid = grids[-1]
    assert default_grid == (B * H, 1, 1)          # heuristic: one 512 block
    assert override_grid == (B * H, 512 // 128, 512 // 256)
    assert override_grid != default_grid


def test_explicit_block_override_matches_naive_fwd_bwd():
    B, H, S, D = 1, 2, 256, 64
    q, k, v = _rand((B, H, S, D), 12), _rand((B, H, S, D), 13), \
        _rand((B, H, S, D), 14)
    scale = D ** -0.5

    def f(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, scale=scale, causal=True, interpret=True,
            block_q=128, block_k=128) * 0.01)

    def f_ref(q, k, v):
        return jnp.sum(_naive_attention(q, k, v, None, scale, True) * 0.01)

    out = flash_attention(q, k, v, scale=scale, causal=True,
                          interpret=True, block_q=128, block_k=128)
    ref = _naive_attention(q, k, v, None, scale, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)
    g = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3,
            err_msg="block-override grad mismatch for %s" % name)


def test_explicit_block_invalid_raises_and_wins_over_env(monkeypatch):
    from paddle_tpu.ops.pallas.attention import _block_sizes

    B, H, S, D = 1, 1, 256, 64
    q = _rand((B, H, S, D), 15)
    # non-divisor: hard error, never a silent fallback
    with pytest.raises(ValueError, match="must divide"):
        flash_attention(q, q, q, interpret=True, block_q=100)
    # explicit argument beats the env override
    monkeypatch.setenv("PADDLE_TPU_FLASH_BLOCKS", "256,256")
    assert _block_sizes(256, 256, 128, 128) == (128, 128)
    # env still applies when no explicit argument is given
    assert _block_sizes(256, 256) == (256, 256)


def test_partial_explicit_block_keeps_env_for_other_side(monkeypatch):
    """Precedence holds per side: an explicit block_q plus a fleet-wide
    env pin means the env still governs block_k (heuristic only when
    the env side does not divide)."""
    from paddle_tpu.ops.pallas.attention import _block_sizes

    monkeypatch.setenv("PADDLE_TPU_FLASH_BLOCKS", "256,256")
    assert _block_sizes(512, 512, 128, None) == (128, 256)
    assert _block_sizes(512, 512, None, 128) == (256, 128)
    # env side that does not divide falls to the heuristic
    monkeypatch.setenv("PADDLE_TPU_FLASH_BLOCKS", "256,384")
    assert _block_sizes(512, 512, 128, None) == (128, 512)
    # malformed env still raises, even on the explicit branch
    monkeypatch.setenv("PADDLE_TPU_FLASH_BLOCKS", "nope")
    with pytest.raises(ValueError, match="two ints"):
        _block_sizes(512, 512, 128, None)
