"""Ring attention vs full-attention oracle on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import distributed as dist
from paddle_tpu.distributed.ring_attention import ring_attention_sharded
from paddle_tpu.ops.attention import _naive_attention


def _rand(shape, seed):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    mesh = dist.DeviceMesh({"sp": 8})
    B, H, S, D = 2, 2, 64, 16  # S sharded 8 ways -> 8 per shard
    q, k, v = _rand((B, H, S, D), 0), _rand((B, H, S, D), 1), _rand((B, H, S, D), 2)
    scale = D ** -0.5
    out = ring_attention_sharded(q, k, v, mesh.mesh, scale=scale, causal=causal)
    ref = _naive_attention(q, k, v, None, scale, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_grads_match_full():
    mesh = dist.DeviceMesh({"sp": 8})
    B, H, S, D = 1, 2, 32, 8
    q, k, v = _rand((B, H, S, D), 3), _rand((B, H, S, D), 4), _rand((B, H, S, D), 5)
    scale = D ** -0.5

    def f_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(q, k, v, mesh.mesh, scale=scale) ** 2)

    def f_full(q, k, v):
        return jnp.sum(_naive_attention(q, k, v, None, scale, False) ** 2)

    gr = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(f_full, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gf, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5,
                                   err_msg="d%s mismatch" % name)
