"""Inference: save_inference_model -> Predictor serving + StableHLO export.

Mirrors reference inference tests (analyzer_*_tester pattern: saved model
round-trip, output parity with the training-time network).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.optimizer import SGDOptimizer
from paddle_tpu.inference import (
    AnalysisConfig,
    create_predictor,
    export_stablehlo,
    load_stablehlo,
)


@pytest.fixture
def saved_model(tmp_path):
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data("x", [-1, 4], "float32")
        y = fluid.data("y", [-1, 1], "float32")
        h = layers.fc(x, 8, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        SGDOptimizer(0.1).minimize(loss, startup)
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 4).astype(np.float32),
            "y": rng.randn(8, 1).astype(np.float32)}
    model_dir = str(tmp_path / "model")
    with fluid.scope_guard(fluid.Scope()):
        exe.run_startup(startup)
        exe.run(prog, feed=feed, fetch_list=[loss])
        # training-time prediction for parity checking
        test_prog = prog.clone(for_test=True)
        x_new = rng.randn(5, 4).astype(np.float32)
        expected, = exe.run(
            test_prog,
            feed={"x": x_new, "y": np.zeros((5, 1), np.float32)},
            fetch_list=[pred],
        )
        fluid.io.save_inference_model(model_dir, ["x"], [pred], exe, test_prog)
    return model_dir, x_new, expected


def test_predictor_matches_training_network(saved_model):
    model_dir, x_new, expected = saved_model
    config = AnalysisConfig(model_dir)
    predictor = create_predictor(config)
    assert predictor.get_input_names() == ["x"]
    out, = predictor.run([x_new])
    np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)
    # second request reuses the compiled executable (NaiveExecutor property)
    out2, = predictor.run({"x": x_new})
    np.testing.assert_allclose(out2, expected, rtol=1e-5, atol=1e-6)


def test_stablehlo_export_roundtrip(saved_model, tmp_path):
    model_dir, x_new, expected = saved_model
    predictor = create_predictor(AnalysisConfig(model_dir))
    export_dir = str(tmp_path / "shlo")
    export_stablehlo(export_dir, predictor, [x_new])
    served = load_stablehlo(export_dir)
    out, = served({"x": x_new})
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# serving runner (reference AnalysisPredictor serving + capi/go surface ->
# batching front end + HTTP JSON endpoint)
# ---------------------------------------------------------------------------


def _train_and_save(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 8], append_batch_size=False)
        pred = layers.fc(layers.fc(x, 16, act="relu"), 2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    path = str(tmp_path / "srv.model")
    fluid.io.save_inference_model(path, ["x"], [pred], exe, main)
    return path


def test_inference_server_batches_concurrent_requests(tmp_path):
    import threading

    from paddle_tpu.inference import AnalysisConfig, create_predictor
    from paddle_tpu.inference.server import InferenceServer

    path = _train_and_save(tmp_path)
    pred = create_predictor(AnalysisConfig(path))
    server = InferenceServer(pred, max_batch=16, batch_timeout_ms=20).start()
    try:
        rng = np.random.RandomState(0)
        xs = [rng.randn(2, 8).astype(np.float32) for _ in range(8)]
        direct = [pred.run({"x": x})[0] for x in xs]
        results = [None] * 8

        def call(i):
            results[i] = server.infer({"x": xs[i]})[0]

        ts = [threading.Thread(target=call, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for got, want in zip(results, direct):
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    finally:
        server.stop()


def test_inference_server_http_endpoint(tmp_path):
    import json as _json
    import urllib.request

    from paddle_tpu.inference import AnalysisConfig, create_predictor
    from paddle_tpu.inference.server import InferenceServer

    path = _train_and_save(tmp_path)
    pred = create_predictor(AnalysisConfig(path))
    server = InferenceServer(pred).start()
    httpd = server.serve_http(port=0, block=False)
    try:
        port = httpd.server_address[1]
        x = np.random.RandomState(1).randn(3, 8).astype(np.float32)
        body = _json.dumps({
            "inputs": {"x": x.tolist()},
            "dtypes": {"x": "float32"},
        }).encode()
        req = urllib.request.Request(
            "http://127.0.0.1:%d/predict" % port, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = _json.loads(resp.read())
        want = pred.run({"x": x})[0]
        np.testing.assert_allclose(
            np.asarray(out["outputs"][0], np.float32), want,
            rtol=1e-5, atol=1e-6)
        with urllib.request.urlopen(
                "http://127.0.0.1:%d/health" % port, timeout=10) as resp:
            assert _json.loads(resp.read())["status"] == "ok"
    finally:
        httpd.shutdown()
        server.stop()


def test_encrypted_model_round_trip(tmp_path):
    """Encrypt a saved model dir, fail on wrong key, load after decrypt
    (reference io/crypto capability)."""
    from paddle_tpu.fluid import crypto

    path = _train_and_save(tmp_path)
    x = np.random.RandomState(2).randn(2, 8).astype(np.float32)
    from paddle_tpu.inference import AnalysisConfig, create_predictor

    want = create_predictor(AnalysisConfig(path)).run({"x": x})[0]

    crypto.encrypt_inference_model(path, key="s3cret")
    # ciphertext is not loadable
    with pytest.raises(Exception):
        create_predictor(AnalysisConfig(path))
    # wrong key detected by the integrity tag
    with pytest.raises(ValueError, match="wrong key|corrupted"):
        crypto.decrypt_inference_model(
            path, key="nope", out_dirname=str(tmp_path / "bad"))
    dec = str(tmp_path / "dec")
    crypto.decrypt_inference_model(path, key="s3cret", out_dirname=dec)
    got = create_predictor(AnalysisConfig(dec)).run({"x": x})[0]
    np.testing.assert_allclose(got, want, rtol=1e-6)
