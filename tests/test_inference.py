"""Inference: save_inference_model -> Predictor serving + StableHLO export.

Mirrors reference inference tests (analyzer_*_tester pattern: saved model
round-trip, output parity with the training-time network).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.optimizer import SGDOptimizer
from paddle_tpu.inference import (
    AnalysisConfig,
    create_predictor,
    export_stablehlo,
    load_stablehlo,
)


@pytest.fixture
def saved_model(tmp_path):
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data("x", [-1, 4], "float32")
        y = fluid.data("y", [-1, 1], "float32")
        h = layers.fc(x, 8, act="relu")
        pred = layers.fc(h, 1)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        SGDOptimizer(0.1).minimize(loss, startup)
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(8, 4).astype(np.float32),
            "y": rng.randn(8, 1).astype(np.float32)}
    model_dir = str(tmp_path / "model")
    with fluid.scope_guard(fluid.Scope()):
        exe.run_startup(startup)
        exe.run(prog, feed=feed, fetch_list=[loss])
        # training-time prediction for parity checking
        test_prog = prog.clone(for_test=True)
        x_new = rng.randn(5, 4).astype(np.float32)
        expected, = exe.run(
            test_prog,
            feed={"x": x_new, "y": np.zeros((5, 1), np.float32)},
            fetch_list=[pred],
        )
        fluid.io.save_inference_model(model_dir, ["x"], [pred], exe, test_prog)
    return model_dir, x_new, expected


def test_predictor_matches_training_network(saved_model):
    model_dir, x_new, expected = saved_model
    config = AnalysisConfig(model_dir)
    predictor = create_predictor(config)
    assert predictor.get_input_names() == ["x"]
    out, = predictor.run([x_new])
    np.testing.assert_allclose(out, expected, rtol=1e-5, atol=1e-6)
    # second request reuses the compiled executable (NaiveExecutor property)
    out2, = predictor.run({"x": x_new})
    np.testing.assert_allclose(out2, expected, rtol=1e-5, atol=1e-6)


def test_stablehlo_export_roundtrip(saved_model, tmp_path):
    model_dir, x_new, expected = saved_model
    predictor = create_predictor(AnalysisConfig(model_dir))
    export_dir = str(tmp_path / "shlo")
    export_stablehlo(export_dir, predictor, [x_new])
    served = load_stablehlo(export_dir)
    out, = served({"x": x_new})
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5, atol=1e-6)
