"""Comm-efficient multi-chip training: ZeRO-2/3 reduce-scatter sync,
microbatch accumulation, chunked gathers, and the collective cost model.

The proof obligations of the PR-13 tentpole, on the 8-virtual-device
CPU mesh (conftest):

  * ZeRO-2/3 steps match the GSPMD-oracle step (losses + params);
  * the compiled stage>=2 HLO contains reduce-scatter and NO
    gradient-sized all-reduce (only the scalar loss mean);
  * ``accumulate_steps=4`` matches the large-batch step numerically
    (tolerance documents f32 summation-order drift) and communicates
    gradients exactly once per outer step — every collective lives in
    the ENTRY computation, never inside the scan's while body, and the
    per-kind counts equal the k=1 step's;
  * donation stays in force under the scan (input state buffers are
    deleted — no param-buffer doubling);
  * the gather-chunk knob buckets collectives (chunk size chosen so the
    plan AND the HLO split);
  * the static comm model (`zero_comm_estimate`) agrees with the
    HLO-extracted collective bytes within 15%;
  * `_dp_shard_dim` prefers the LARGEST divisible dim (embedding rows)
    with the replicated fallback preserved;
  * the `replicated-gradient` perf-lint rule fires on dp>1 optimizer
    programs with unsharded grads and stays quiet otherwise;
  * `tools/program_cost.py --mesh/--ici-bw` prices c_* collectives;
  * `tune.search_train_step` enumerates/measures the zero/accumulation/
    chunk candidates with a cache round-trip.
"""

import importlib.util
import json
import os

import jax
import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import distributed as dist
from paddle_tpu import models
from paddle_tpu.analysis import comm as comm_mod
from paddle_tpu.distributed import zero as zero_mod
from paddle_tpu.distributed.sharding import _dp_shard_dim
from paddle_tpu.fluid import dygraph, layers
from paddle_tpu.fluid import framework as fw
from paddle_tpu.fluid.optimizer import AdamOptimizer


@pytest.fixture(autouse=True)
def _fresh_names():
    from paddle_tpu.fluid import unique_name

    old = unique_name.switch()
    yield
    unique_name.switch(old)


# ---------------------------------------------------------------------------
# layout math units
# ---------------------------------------------------------------------------


def test_dp_shard_dim_prefers_largest_divisible_dim():
    # the 30k-row embedding shards over rows, not the hidden dim
    assert _dp_shard_dim((30000, 768), 8) == 0
    assert _dp_shard_dim((768, 30000), 8) == 1
    # ties break toward the earlier dim (stable vs the old first-dim rule)
    assert _dp_shard_dim((64, 64), 8) == 0
    # only one divisible dim
    assert _dp_shard_dim((7, 64), 8) == 1
    # replicated fallback preserved: nothing divisible
    assert _dp_shard_dim((7, 3), 8) is None
    assert _dp_shard_dim((2,), 8) is None
    assert _dp_shard_dim((64,), 1) is None


def test_zero_layout_roundtrip_dim_and_flat():
    import jax.numpy as jnp

    # block-sharded layout
    x = np.arange(64, dtype=np.float32).reshape(4, 16)
    lay = zero_mod.ZeroLayout("w", x.shape, x.dtype, 8)
    assert lay.dim == 1 and lay.flat == 8 and lay.sharded
    rows = lay.full_to_rows(jnp.asarray(x))
    assert rows.shape == (8, 8)
    np.testing.assert_array_equal(np.asarray(lay.rows_to_full(rows)), x)
    # row r == rank r's block along dim 1
    np.testing.assert_array_equal(
        np.asarray(rows[3]),
        np.moveaxis(x[:, 6:8], 1, 0).reshape(-1))
    # local_flat slices the same block
    np.testing.assert_array_equal(
        np.asarray(lay.local_flat(jnp.asarray(x), 3)), np.asarray(rows[3]))
    # shard <-> flat round trip
    shard = x[:, 6:8]
    np.testing.assert_array_equal(
        np.asarray(lay.flat_to_shard(lay.shard_to_flat(
            jnp.asarray(shard)))), shard)

    # flat fallback: nothing divisible -> ravel + zero-pad
    y = np.arange(10, dtype=np.float32).reshape(5, 2)
    flay = zero_mod.ZeroLayout("b", y.shape, y.dtype, 8)
    assert not flay.sharded and flay.pad == 6 and flay.flat == 2
    rows = flay.full_to_rows(jnp.asarray(y))
    assert rows.shape == (8, 2)
    from jax.sharding import PartitionSpec as P

    assert lay.spec() == P(None, "dp")   # at-rest sharded placement
    assert flay.spec() == P()            # fallback stays replicated
    np.testing.assert_array_equal(
        np.asarray(flay.rows_to_full(rows)), y)


def test_plan_buckets_caps_and_dtype_separation():
    arrs = {
        "a": np.zeros((8, 4), np.float32),   # 128 B/shard... (32 elems/8=4*4B=16B)
        "b": np.zeros((8, 4), np.float32),
        "c": np.zeros((8, 4), np.int32),
        "big": np.zeros((8, 1024), np.float32),
    }
    lays = zero_mod.plan_layouts(arrs, 8)
    # cap small: every tensor alone
    assert zero_mod.plan_buckets(lays, chunk_bytes=1) == [
        ["a"], ["b"], ["c"], ["big"]]
    # generous cap: a+b coalesce, c splits off (dtype), big is oversize
    buckets = zero_mod.plan_buckets(lays, chunk_bytes=1 << 10)
    assert ["a", "b"] in buckets
    assert ["c"] in buckets
    assert ["big"] in buckets


# ---------------------------------------------------------------------------
# the sharded step: parity, collectives, accumulation, donation
# ---------------------------------------------------------------------------

# one harness for bench --multichip, the dryrun, and these tests — the
# drift the shared module exists to prevent; only the model size is
# test-local (smaller than the drill default, for suite runtime)
_CFG = dict(vocab_size=128, hidden_size=32, num_hidden_layers=1,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=32,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)


def _bert_cfg():
    return models.BertConfig(**_CFG)


def _batches(cfg, B, S, n, seed=0):
    from paddle_tpu.distributed import _zero_harness as zh

    return zh.bert_batches(cfg, B, S, n, seed=seed)


def _loss_fn(m, batch):
    from paddle_tpu.distributed import _zero_harness as zh

    return zh.bert_loss_fn(m, batch)


def _run(mesh, batches, n_steps=3, **kw):
    """Deterministic build+run over the SHARED drill harness, so every
    variant starts from bit-identical params."""
    from paddle_tpu.distributed import _zero_harness as zh

    def body(step, state):
        prev = None
        losses = []
        for b in batches[:n_steps]:
            prev = state
            state, loss = step(state, b)
            losses.append(float(loss))
        return step, state, losses, prev

    return zh.run_deterministic(mesh, body, cfg=_bert_cfg(), lr=1e-3,
                                **kw)


def _assert_state_close(a, b, rtol=2e-3, atol=1e-5, msg=""):
    for n in a["params"]:
        np.testing.assert_allclose(
            np.asarray(a["params"][n]), np.asarray(b["params"][n]),
            rtol=rtol, atol=atol, err_msg="%s param %s" % (msg, n))


@pytest.mark.slow
def test_zero23_match_gspmd_oracle_and_hlo_has_reduce_scatter():
    mesh = dist.auto_mesh(8)
    cfg = _bert_cfg()
    batches = _batches(cfg, 16, 16, 3)
    _o, o_state, o_losses, _ = _run(mesh, batches, zero_stage=1)
    for stage in (2, 3):
        step, state, losses, _ = _run(mesh, batches, zero_stage=stage)
        np.testing.assert_allclose(o_losses, losses, rtol=2e-4, atol=1e-5)
        _assert_state_close(o_state, state, msg="zero%d" % stage)
        # optimizer state parity (moments sharded, pows replicated)
        n0 = "bert.embeddings.word.weight"
        for slot in o_state["opt"][n0]:
            np.testing.assert_allclose(
                np.asarray(o_state["opt"][n0][slot]),
                np.asarray(state["opt"][n0][slot]),
                rtol=2e-3, atol=1e-6, err_msg=slot)
        hlo = step.compiled_hlo(state, batches[0])
        colls = comm_mod.hlo_collectives(hlo)
        assert any(c["kind"] == "reduce-scatter" for c in colls), (
            "stage %d compiled without reduce-scatter" % stage)
        big_ar = [c for c in colls if c["kind"] == "all-reduce"
                  and c["result_bytes"] > 1024]
        assert not big_ar, (
            "stage %d still all-reduces gradients: %s"
            % (stage, [c["line"][:100] for c in big_ar]))
    # stage 3 keeps sharded params sharded at rest
    step3, state3, _, _ = _run(mesh, batches, zero_stage=3, n_steps=1)
    w = state3["params"]["bert.embeddings.word.weight"]
    assert "dp" in str(w.sharding.spec)


def test_comm_estimate_matches_hlo_collective_bytes():
    mesh = dist.auto_mesh(8)
    cfg = _bert_cfg()
    batches = _batches(cfg, 16, 16, 1)
    step, state, _, _ = _run(mesh, batches, n_steps=1, zero_stage=2)
    stats = step.collective_stats(state, batches[0])
    est = step.comm_estimate()
    assert stats and stats["wire_bytes_total"] > 0
    rel = (abs(est["wire_bytes_total"] - stats["wire_bytes_total"])
           / stats["wire_bytes_total"])
    assert rel <= 0.15, (
        "static comm model off by %.0f%%: est %.0f vs HLO %.0f"
        % (rel * 100, est["wire_bytes_total"], stats["wire_bytes_total"]))


@pytest.mark.slow
def test_accumulate_matches_large_batch_and_syncs_once():
    """accumulate_steps=4 == the k=1 large-batch step up to f32
    summation order (tolerance: the scan sums k microbatch means in a
    different order than one fused reduction — rtol 1e-3 over 2 adam
    steps), and gradient sync stays ONE reduce-scatter per outer step:
    per-kind collective counts equal k=1's and every collective sits in
    the ENTRY computation, not the scan's while body."""
    mesh = dist.auto_mesh(8)
    cfg = _bert_cfg()
    batches = _batches(cfg, 32, 16, 2)   # local batch 4 => 4 microbatches
    s1, st1, l1, _ = _run(mesh, batches, n_steps=2, zero_stage=2)
    s4, st4, l4, prev4 = _run(mesh, batches, n_steps=2, zero_stage=2,
                              accumulate_steps=4)
    np.testing.assert_allclose(l1, l4, rtol=1e-3, atol=1e-5)
    _assert_state_close(st1, st4, rtol=5e-3, atol=1e-5, msg="acc4")
    # donation held under the scan: the previous state's buffers were
    # consumed by the donated step (no param-buffer doubling)
    assert all(v.is_deleted() for v in prev4["params"].values())
    stats1 = s1.collective_stats(st1, batches[0])
    stats4 = s4.collective_stats(st4, batches[0])
    for kind in ("reduce-scatter", "all-gather"):
        assert stats4[kind]["count"] == stats1[kind]["count"], kind
        # in ENTRY: runs once per step, NOT once per microbatch
        assert stats4[kind]["entry_count"] == stats4[kind]["count"], kind
    assert stats4["all-reduce"]["entry_count"] == \
        stats4["all-reduce"]["count"]


def test_accumulate_on_gspmd_path_single_device():
    """The GSPMD (zero_stage<=1) path supports accumulation too — dp=1
    reference semantics: scan-accumulated == large-batch."""
    mesh = dist.auto_mesh(1)
    cfg = _bert_cfg()
    batches = _batches(cfg, 8, 16, 2)
    _s1, st1, l1, _ = _run(mesh, batches, n_steps=2, zero_stage=1)
    _s4, st4, l4, _ = _run(mesh, batches, n_steps=2, zero_stage=1,
                           accumulate_steps=4)
    np.testing.assert_allclose(l1, l4, rtol=1e-3, atol=1e-5)
    _assert_state_close(st1, st4, rtol=5e-3, atol=1e-5, msg="gspmd-acc")


def test_gather_chunk_bytes_buckets_the_collectives():
    """A small chunk cap splits the gather/scatter into multiple
    independent collectives (the overlap-ready shape) — the HLO carries
    exactly as many reduce-scatters as the grad bucket plan."""
    mesh = dist.auto_mesh(8)
    cfg = _bert_cfg()
    batches = _batches(cfg, 16, 16, 1)
    step, state, _, _ = _run(mesh, batches, n_steps=1, zero_stage=2,
                             gather_chunk_bytes=2 << 10)
    layouts = step._zero_layouts
    n_grad_buckets = len(zero_mod.plan_buckets(
        layouts, list(layouts), 2 << 10))
    assert n_grad_buckets > 1, "chunk cap too big to exercise bucketing"
    stats = step.collective_stats(state, batches[0])
    assert stats["reduce-scatter"]["count"] == n_grad_buckets
    assert stats["all-gather"]["count"] > 1


def test_zero_stage_validation():
    mesh = dist.auto_mesh(8, tp=2)
    with dygraph.guard():
        model = models.BertForPretraining(_bert_cfg())
        with pytest.raises(NotImplementedError, match="pure-dp"):
            dist.ShardedTrainStep(
                model, AdamOptimizer(learning_rate=1e-3), _loss_fn,
                mesh, zero_stage=2)
        with pytest.raises(ValueError, match="zero_stage"):
            dist.ShardedTrainStep(
                model, AdamOptimizer(learning_rate=1e-3), _loss_fn,
                dist.auto_mesh(8), zero_stage=7)
        with pytest.raises(ValueError, match="accumulate_steps"):
            dist.ShardedTrainStep(
                model, AdamOptimizer(learning_rate=1e-3), _loss_fn,
                dist.auto_mesh(8), accumulate_steps=0)


# ---------------------------------------------------------------------------
# HLO parser units
# ---------------------------------------------------------------------------

_HLO_SAMPLE = """\
HloModule jit_step, entry_computation_layout={()->f32[]}

%region_0.1 (a: f32[], b: f32[]) -> f32[] {
  ROOT %add = f32[] add(f32[] %a, f32[] %b)
}

%body.2 (p: (f32[8])) -> (f32[8]) {
  %x = f32[8]{0} parameter(0)
  %all-gather.9 = f32[8]{0} all-gather(f32[1]{0} %x), replica_groups={}
}

ENTRY %main.3 (arg: f32[64]) -> f32[] {
  %reduce-scatter.1 = f32[8]{0} reduce-scatter(f32[64]{0} %arg), to_apply=%region_0.1
  %all-reduce.2 = f32[] all-reduce(f32[] %r), to_apply=%region_0.1
  %t = (f32[16]{0}, bf16[4]{0}) all-gather(f32[2]{0} %a, bf16[1]{0} %b)
}
"""


def test_hlo_collectives_parse_shapes_tuples_and_computations():
    rows = comm_mod.hlo_collectives(_HLO_SAMPLE)
    kinds = sorted(r["kind"] for r in rows)
    assert kinds == ["all-gather", "all-gather", "all-reduce",
                     "reduce-scatter"]
    by_kind = {}
    for r in rows:
        by_kind.setdefault(r["kind"], []).append(r)
    # shard result, 8 x f32
    assert by_kind["reduce-scatter"][0]["result_bytes"] == 32
    assert by_kind["reduce-scatter"][0]["entry"]
    # tuple result: 16*4 + 4*2
    entry_ag = [r for r in by_kind["all-gather"] if r["entry"]]
    assert entry_ag[0]["result_bytes"] == 72
    # the while-body all-gather is attributed to its computation
    body_ag = [r for r in by_kind["all-gather"] if not r["entry"]]
    assert body_ag and body_ag[0]["computation"].startswith("%body")
    stats = comm_mod.hlo_collective_stats(_HLO_SAMPLE, 8)
    # reduce-scatter: shard 32 B -> full 256 -> wire (n-1)/n*256 = 224
    assert stats["reduce-scatter"]["wire_bytes"] == pytest.approx(224.0)
    # all-reduce f32[]: 2*(7/8)*4 = 7
    assert stats["all-reduce"]["wire_bytes"] == pytest.approx(7.0)


def test_hlo_collectives_parse_tpu_layout_annotations():
    """TPU optimized HLO decorates result types with tiled layouts and
    memory-space markers (uppercase letters the CPU dump never emits) —
    the extractor must still see the collective."""
    hlo = """\
HloModule tpu

ENTRY %main (p: f32[64]) -> f32[8] {
  %ar = f32[8,128]{1,0:T(8,128)} all-reduce(f32[8,128]{1,0:T(8,128)} %p)
  %rs = f32[8]{0:T(256)S(1)} reduce-scatter(f32[64]{0:T(256)} %x)
}
"""
    rows = comm_mod.hlo_collectives(hlo)
    assert sorted(r["kind"] for r in rows) == ["all-reduce",
                                              "reduce-scatter"]
    ar = [r for r in rows if r["kind"] == "all-reduce"][0]
    assert ar["result_bytes"] == 8 * 128 * 4


def test_hlo_collectives_bill_async_pairs_at_the_done():
    """TPU HLO emits async start/done pairs whose -start result is a
    TUPLE of operand + result buffers — billing it would overcount;
    the pair is billed once, at the -done's result (the collective's
    actual result buffer)."""
    hlo = """\
HloModule async

ENTRY %main (p: f32[8]) -> f32[64] {
  %ags = (f32[8]{0}, f32[64]{0}) all-gather-start(f32[8]{0} %p)
  %agd = f32[64]{0} all-gather-done((f32[8]{0}, f32[64]{0}) %ags)
  %rss = (f32[64]{0}, f32[8]{0}) reduce-scatter-start(f32[64]{0} %agd)
  %rsd = f32[8]{0} reduce-scatter-done((f32[64]{0}, f32[8]{0}) %rss)
}
"""
    rows = comm_mod.hlo_collectives(hlo)
    assert sorted(r["kind"] for r in rows) == ["all-gather",
                                              "reduce-scatter"]
    ag = [r for r in rows if r["kind"] == "all-gather"][0]
    rs = [r for r in rows if r["kind"] == "reduce-scatter"][0]
    assert ag["result_bytes"] == 256     # the done's full buffer only
    assert rs["result_bytes"] == 32      # the done's shard only
    stats = comm_mod.hlo_collective_stats(hlo, 8)
    assert stats["all-gather"]["count"] == 1
    assert stats["reduce-scatter"]["wire_bytes"] == pytest.approx(224.0)


def test_legacy_zero_checkpoint_restores_across_rule_change(tmp_path):
    """Shard files written BEFORE the largest-dim rule carry no
    recorded dim and were sliced along the FIRST divisible dim; restore
    must reassemble them along that legacy dim (not the new rule's) and
    re-slice to the current layout."""
    from paddle_tpu.distributed.elastic.reshard import (
        ZeROShardCheckpoint,
        zero_shard_dim,
    )

    full = np.arange(8 * 32, dtype=np.float32).reshape(8, 32)
    old_n = 4
    # legacy rule: FIRST divisible dim = 0; new rule: largest = dim 1
    assert zero_shard_dim(full.shape, old_n) == 1
    for r in range(old_n):
        np.savez(tmp_path / ("zero_m_rank%d.npz" % r),
                 block=full[r * 2:(r + 1) * 2],   # legacy dim-0 block
                 meta=np.asarray([r, old_n]),
                 full_shape=np.asarray(full.shape))   # no `dim` key
    ck = ZeROShardCheckpoint(
        {"m": np.zeros((8, 8), np.float32)}, {"m": full.shape},
        trainer_id=1, num_trainers=old_n)
    ck.deserialize(str(tmp_path))
    # rank 1's block under the CURRENT (largest-dim) rule
    np.testing.assert_array_equal(ck.states["m"], full[:, 8:16])
    assert ck.restored_nranks == old_n


def test_program_cost_mesh_flag_rejects_malformed(tmp_path, capsys):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        layers.data("mx", shape=[4, 4], append_batch_size=False)
    path = str(tmp_path / "m.json")
    with open(path, "w") as f:
        f.write(main.to_json())
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "program_cost", os.path.join(repo, "tools", "program_cost.py"))
    pc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pc)
    assert pc.main([path, "--mesh", "8"]) == 1        # missing axis=
    capsys.readouterr()
    assert pc.main([path, "--mesh", "dp8"]) == 1      # typo'd
    capsys.readouterr()
    assert pc.main([path, "--mesh", "dp=8"]) == 0
    capsys.readouterr()


def test_collective_wire_bytes_factors():
    assert comm_mod.collective_wire_bytes("all-reduce", 800, 8) == \
        pytest.approx(2 * 7 / 8 * 800)
    assert comm_mod.collective_wire_bytes("all-gather", 800, 8) == \
        pytest.approx(7 / 8 * 800)
    assert comm_mod.collective_wire_bytes(
        "reduce-scatter", 100, 8, payload="shard") == pytest.approx(700.0)
    assert comm_mod.collective_wire_bytes("collective-permute", 64, 8) == 64
    assert comm_mod.collective_wire_bytes("all-reduce", 800, 1) == 0.0


# ---------------------------------------------------------------------------
# replicated-gradient lint + collective pricing
# ---------------------------------------------------------------------------


def _optimizer_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 16], append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], append_batch_size=False)
        pred = layers.fc(x, size=1, param_attr="rg_fc.w")
        loss = layers.reduce_mean(layers.square(pred - y))
        fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
    return main


def test_replicated_gradient_rule_fires_on_dp_mesh():
    from paddle_tpu.analysis import lint_program

    main = _optimizer_program()
    mesh = dist.auto_mesh(8)
    with dist.mesh_guard(mesh):
        diags = lint_program(main, categories=("perf",))
    hits = [d for d in diags if d.code == "replicated-gradient"]
    assert len(hits) == 1, "one aggregated diagnostic per program"
    assert "dp=8" in hits[0].message
    assert hits[0].fix == "zero_stage>=2"


def test_replicated_gradient_rule_quiet_without_mesh_or_when_sharded():
    from paddle_tpu.analysis import lint_program
    from paddle_tpu.analysis.perf_rules import ReplicatedGradientRule

    main = _optimizer_program()
    # no ambient mesh: quiet
    diags = lint_program(main, categories=("perf",))
    assert not [d for d in diags if d.code == "replicated-gradient"]
    # grads dp-sharded: quiet
    mesh = dist.auto_mesh(8)
    block = main.global_block
    for op in block.ops:
        if op.type == "adam":
            for g in op.inputs.get("Grad", []):
                v = block._find_var_recursive(g)
                v.dist_attr = ("dp",) + (None,) * (len(v.shape or ()) - 1)
    rule = ReplicatedGradientRule(mesh=mesh)
    from paddle_tpu.analysis.lint import LintContext

    diags = rule.check(LintContext(main))
    assert not list(diags)


def test_program_cost_prices_collective_ops(tmp_path, capsys):
    from paddle_tpu.fluid.framework import Operator

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("cx", shape=[1024, 32], append_batch_size=False)
        h = layers.scale(x, scale=2.0)
    block = main.global_block
    block.ops.append(Operator(
        block, "c_allreduce_sum",
        inputs={"X": [h.name]}, outputs={"Out": [h.name]},
        attrs={"ring_id": 0}))
    from paddle_tpu.analysis import perf

    # without a mesh the group is unknown -> no comm bytes
    rep0 = perf.program_cost(main, chip=perf.V5E)
    assert rep0.total_comm_bytes == 0.0
    rep = perf.program_cost(main, chip=perf.V5E, mesh_size=8)
    # the estimator bills the input payload once: 2*(n-1)/n * X bytes
    assert rep.total_comm_bytes == pytest.approx(2 * 7 / 8 * 1024 * 32 * 4)
    entry = [e for e in rep.entries if e.op_type == "c_allreduce_sum"][0]
    assert entry.bound == "comm"
    assert entry.comm_bytes > 0

    # the CLI: --mesh prices it, json carries comm_bytes
    path = str(tmp_path / "coll.json")
    with open(path, "w") as f:
        f.write(main.to_json())
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "program_cost", os.path.join(repo, "tools", "program_cost.py"))
    pc = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pc)
    rc = pc.main([path, "--json", "--no-ops", "--mesh", "dp=8",
                  "--ici-bw", "4.5e10"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["totals"]["comm_bytes"] > 0
    assert out["chip"]["ici_bw"] == 4.5e10
    row = [r for r in out["by_op_type"]
           if r["op_type"] == "c_allreduce_sum"][0]
    assert row["comm_bytes"] == pytest.approx(rep.total_comm_bytes)


# ---------------------------------------------------------------------------
# tune: the zero/accumulation/chunk candidates
# ---------------------------------------------------------------------------


def test_train_step_candidates_enumeration():
    from paddle_tpu import tune

    cands = tune.train_step_candidates(dp=8)
    labels = [c.label for c in cands]
    assert labels[0] == "zero1.acc1"              # default first
    assert any(l.startswith("zero2.acc4.chunk") for l in labels)
    assert any(l.startswith("zero3.acc1.chunk") for l in labels)
    # 1-chip box: the zero/chunk axes collapse by construction
    solo = tune.train_step_candidates(dp=1)
    assert all(c.params["zero_stage"] <= 1 for c in solo)
    assert all("gather_chunk_bytes" not in c.params for c in solo)


def test_search_train_step_measures_and_caches(tmp_path):
    from paddle_tpu import tune

    mesh = dist.auto_mesh(8)
    calls = []
    fake = {(1, 1): 0.010, (2, 1): 0.007, (3, 1): 0.008,
            (2, 4): 0.005, (1, 4): 0.009, (3, 4): 0.006}

    def build_and_time(params):
        key = (params["zero_stage"], params["accumulate_steps"])
        calls.append(params)
        return fake[key]

    rep = tune.search_train_step(
        build_and_time, workload="test.zero", mesh=mesh,
        cache_dir=str(tmp_path))
    assert not rep.cache_hit
    assert len(calls) == 6                      # every candidate measured
    assert rep.winner.params["zero_stage"] == 2
    assert rep.winner.params["accumulate_steps"] == 4
    assert rep.winner.params["gather_chunk_bytes"] == 4 << 20
    assert rep.default_s == pytest.approx(0.010)
    # cache round-trip: second search measures NOTHING
    calls.clear()
    rep2 = tune.search_train_step(
        build_and_time, workload="test.zero", mesh=mesh,
        cache_dir=str(tmp_path))
    assert rep2.cache_hit and not calls
    assert rep2.winner.params == rep.winner.params
    # a different mesh is a different workload (keyed) — re-opens
    rep3 = tune.search_train_step(
        build_and_time, workload="test.zero", mesh=dist.auto_mesh(4),
        cache_dir=str(tmp_path))
    assert not rep3.cache_hit


def test_zero_comm_estimate_layouts():
    arrs = {"w": np.zeros((64, 16), np.float32),
            "b": np.zeros((3,), np.float32)}
    lays = zero_mod.plan_layouts(arrs, 8)
    est = zero_mod.zero_comm_estimate(lays, 2, 8,
                                      state_slots_per_param=2)
    w_bytes = 64 * 16 * 4
    b_bytes = 8 * 1 * 4          # padded flat: 8 ranks x 1 elem
    assert est["reduce-scatter"]["payload_bytes"] == w_bytes + b_bytes
    # stage 2 regathers both params + the fallback param's 2 moments
    assert est["all-gather"]["payload_bytes"] == \
        w_bytes + b_bytes + 2 * b_bytes
    assert est["reduce-scatter"]["wire_bytes"] == pytest.approx(
        7 / 8 * (w_bytes + b_bytes))
    # stage 3: w gathers in the forward instead; same totals here
    est3 = zero_mod.zero_comm_estimate(lays, 3, 8,
                                       state_slots_per_param=2)
    assert est3["all-gather"]["payload_bytes"] == \
        w_bytes + b_bytes + 2 * b_bytes
