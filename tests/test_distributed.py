"""Distributed subsystem on the 8-device host-simulated mesh.

Mirrors the reference distributed test strategy (SURVEY §4.3): collective
ops compared against numpy on simulated ranks, and *loss parity* — the
sharded multi-device step must match the single-device run within delta
(cf. test_dist_base.check_with_place).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import distributed as dist
from paddle_tpu import models
from paddle_tpu.fluid import dygraph
from paddle_tpu.fluid.optimizer import AdamOptimizer, SGDOptimizer


def test_mesh_construction():
    mesh = dist.auto_mesh(8, tp=2)
    assert mesh.axis_size("tp") == 2
    assert mesh.axis_size("dp") == 4
    assert mesh.size == 8
    # tp innermost (ICI), dp outermost (cf. scaling-book recipe)
    assert mesh.axis_names[-1] == "tp"
    assert mesh.axis_names[0] == "dp"


def test_collectives_under_shard_map():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = dist.auto_mesh(8)

    x = np.arange(8, dtype=np.float32).reshape(8, 1)

    def body(x):
        s = dist.all_reduce(x, "sum", axis="dp")
        mx = dist.all_reduce(x, "max", axis="dp")
        g = dist.all_gather(x, axis="dp")
        return s, mx, g

    s, mx, g = shard_map(
        body, mesh=mesh.mesh,
        in_specs=(P("dp", None),),
        out_specs=(P("dp", None), P("dp", None), P("dp", None)),
    )(x)
    np.testing.assert_allclose(np.asarray(s)[:, 0], [28.0] * 8)
    np.testing.assert_allclose(np.asarray(mx)[:, 0], [7.0] * 8)
    assert np.asarray(g).shape == (64, 1)  # 8 ranks x tiled gather


def test_collective_program_ops_single_rank_identity():
    """c_* ops outside any mesh = world size 1 = identity (reference
    single-trainer behavior)."""
    from paddle_tpu.fluid.core.registry import LowerContext, get_op_def

    ctx = LowerContext()
    x = jnp.ones((3,))
    for op in ["c_allreduce_sum", "c_broadcast", "c_sync_comm_stream"]:
        out = get_op_def(op).lower(ctx, {"X": [x]}, {"ring_id": 0})
        np.testing.assert_allclose(np.asarray(out["Out"][0]), np.ones(3))


def test_send_recv_ring_shift():
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    mesh = dist.auto_mesh(8)
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    perm = [(i, (i + 1) % 8) for i in range(8)]

    def body(x):
        return dist.send_recv(x, perm, axis="dp")

    out = shard_map(body, mesh=mesh.mesh, in_specs=(P("dp", None),),
                    out_specs=P("dp", None))(x)
    np.testing.assert_allclose(
        np.asarray(out)[:, 0], [7, 0, 1, 2, 3, 4, 5, 6]
    )


def _bert_batch(cfg, B, S, seed):
    rng = np.random.RandomState(seed)
    return {
        "input_ids": rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int64),
        "token_type_ids": np.zeros((B, S), np.int64),
        "position_ids": np.tile(np.arange(S, dtype=np.int64), (B, 1)),
        "mlm_labels": rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int64),
        "mlm_weights": np.ones((B, S), np.float32),
        "nsp_labels": rng.randint(0, 2, (B, 1)).astype(np.int64),
    }


def _bert_loss_fn(model, batch):
    logits, nsp_logits = model(
        batch["input_ids"], batch["token_type_ids"], batch["position_ids"]
    )
    return model.loss(
        logits, nsp_logits, batch["mlm_labels"], batch["mlm_weights"],
        batch["nsp_labels"],
    )


def _run_steps(mesh_kw, n_steps=3, seed=0):
    cfg = models.BertConfig.tiny()
    with dygraph.guard():
        tr_framework = __import__(
            "paddle_tpu.fluid.framework", fromlist=["x"]
        )._dygraph_tracer
        tr_framework._base_key = jax.random.PRNGKey(7)  # deterministic init
        np.random.seed(seed)
        import paddle_tpu.fluid.unique_name as un

        model = models.BertForPretraining(cfg)
        opt = AdamOptimizer(learning_rate=1e-3)
        mesh = dist.auto_mesh(**mesh_kw)
        step = dist.ShardedTrainStep(model, opt, _bert_loss_fn, mesh)
        state = step.init()
        losses = []
        for i in range(n_steps):
            batch = _bert_batch(cfg, 8, 16, seed=100 + i)
            state, loss = step(state, batch)
            losses.append(float(loss))
        return losses


@pytest.fixture(autouse=True)
def _fresh_names():
    from paddle_tpu.fluid import unique_name

    old = unique_name.switch()
    yield
    unique_name.switch(old)


def test_dp_loss_parity_with_single_device():
    """8-way data parallel must match 1-device losses (test_dist_base
    pattern).  Model init must be identical: both runs seed the tracer the
    same way, and jax PRNG is deterministic."""
    single = _run_steps({"n_devices": 1})
    dp8 = _run_steps({"n_devices": 8})
    np.testing.assert_allclose(single, dp8, rtol=2e-3, atol=2e-4)


def test_tp_sp_loss_parity_with_single_device():
    """dp2 x tp2 x sp2 sharded step matches single device."""
    single = _run_steps({"n_devices": 1})
    mixed = _run_steps({"n_devices": 8, "tp": 2, "sp": 2})
    np.testing.assert_allclose(single, mixed, rtol=2e-3, atol=2e-4)


def test_zero_sharded_optimizer_state():
    """ZeRO-1: adam moments are dp-sharded across devices."""
    cfg = models.BertConfig.tiny()
    with dygraph.guard():
        model = models.BertForPretraining(cfg)
        opt = AdamOptimizer(learning_rate=1e-3)
        mesh = dist.auto_mesh(8)
        step = dist.ShardedTrainStep(model, opt, _bert_loss_fn, mesh, zero_stage=1)
        state = step.init()
        # find a large param's moment and check its sharding spans dp
        name = "bert.embeddings.word.weight"
        m1 = state["opt"][name]["Moment1"]
        assert "dp" in str(m1.sharding.spec)


def test_parallel_env_contract(monkeypatch):
    monkeypatch.setenv("PADDLE_TRAINER_ID", "3")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "8")
    monkeypatch.setenv("PADDLE_TRAINER_ENDPOINTS", ",".join(
        "127.0.0.1:617%d" % i for i in range(8)
    ))
    env = dist.ParallelEnv()
    assert env.rank == 3
    assert env.world_size == 8
    assert len(env.trainer_endpoints) == 8


def test_sharded_train_step_handles_changed_batch_shape():
    """A batch with a different shape (e.g. the last partial batch) gets
    its own compiled step with correct shardings instead of a stale
    retrace against the first batch's in_shardings."""
    cfg = models.BertConfig.tiny()
    with dygraph.guard():
        from paddle_tpu.fluid import framework as _fw

        _fw._dygraph_tracer._base_key = jax.random.PRNGKey(7)
        model = models.BertForPretraining(cfg)
        opt = AdamOptimizer(learning_rate=1e-3)
        mesh = dist.auto_mesh(8)
        step = dist.ShardedTrainStep(model, opt, _bert_loss_fn, mesh)
        state = step.init()
        state, l1 = step(state, _bert_batch(cfg, 8, 16, seed=1))
        state, l2 = step(state, _bert_batch(cfg, 4, 16, seed=2))  # smaller B
        state, l3 = step(state, _bert_batch(cfg, 8, 16, seed=3))  # back
        assert len(step._step_fns) == 2
        assert all(np.isfinite(x) for x in (float(l1), float(l2), float(l3)))
