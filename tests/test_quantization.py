"""Quantization: fake-quant ops (STE), QAT transform/freeze, and
post-training int8 (reference contrib/slim/quantization tests)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.contrib.slim.quantization import (
    PostTrainingQuantization,
    QuantizationFreezePass,
    QuantizationTransformPass,
)

from op_test import check_output, run_single_op


def _qdq_ref(x, scale=None, axis=None):
    if scale is None:
        scale = np.abs(x).max() if axis is None else np.abs(x).max(
            axis=tuple(i for i in range(x.ndim) if i != axis), keepdims=True
        )
    s = np.maximum(scale, 1e-9)
    return np.clip(np.round(x / s * 127.0), -127, 127) * s / 127.0


def test_fake_qdq_abs_max_forward_and_ste_grad():
    x = np.random.RandomState(0).randn(4, 5).astype(np.float32)
    outs, _ = run_single_op(
        "fake_quantize_dequantize_abs_max", {"X": x}, {}, ["Out", "OutScale"]
    )
    np.testing.assert_allclose(outs["Out"], _qdq_ref(x), rtol=1e-5, atol=1e-6)
    # STE: grad of sum(out) wrt x must be exactly ones
    _, grads = run_single_op(
        "fake_quantize_dequantize_abs_max", {"X": x}, {}, ["Out", "OutScale"],
        grad_of=[("X", 0)],
    )
    np.testing.assert_allclose(grads["x_0@GRAD"], np.ones_like(x))


def test_fake_channel_wise_qdq():
    w = np.random.RandomState(1).randn(6, 8).astype(np.float32) * 3
    outs, _ = run_single_op(
        "fake_channel_wise_quantize_dequantize_abs_max", {"X": w},
        {"quant_axis": 1}, ["Out", "OutScale"],
    )
    np.testing.assert_allclose(outs["Out"], _qdq_ref(w, axis=1),
                               rtol=1e-5, atol=1e-6)
    assert outs["OutScale"].shape == (8,)


def test_quantize_dequantize_linear_roundtrip():
    w = np.random.RandomState(2).randn(5, 4).astype(np.float32)
    scale = np.abs(w).max(axis=0)
    q, _ = run_single_op(
        "quantize_linear", {"X": w, "Scale": scale}, {"quant_axis": 1}, ["Y"]
    )
    assert q["Y"].dtype == np.int8
    dq, _ = run_single_op(
        "dequantize_linear", {"X": q["Y"], "Scale": scale},
        {"quant_axis": 1}, ["Y"],
    )
    np.testing.assert_allclose(dq["Y"], w, atol=np.abs(w).max() / 127.0)


def _train_tiny(main, startup, loss, feeds, steps=40, seed=0):
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(seed)
    losses = []
    for _ in range(steps):
        x = rng.randn(16, 8).astype(np.float32)
        y = (x[:, :1] * 2 - x[:, 1:2]).astype(np.float32)
        (lv,) = exe.run(main, feed={"x": x, "y": y}, fetch_list=[loss])
        losses.append(float(lv))
    return exe, losses


def test_qat_transform_train_freeze():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16, 8], append_batch_size=False)
        y = layers.data("y", shape=[16, 1], append_batch_size=False)
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square(pred - y))
    # QAT rewrite BEFORE optimizer insertion (reference flow)
    QuantizationTransformPass().apply(main, startup)
    with fluid.program_guard(main, startup):
        fluid.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(loss)

    types = [op.type for op in main.global_block.ops]
    assert "fake_channel_wise_quantize_dequantize_abs_max" in types
    assert "fake_quantize_dequantize_moving_average_abs_max" in types

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe, losses = _train_tiny(main, startup, loss, ["x", "y"])
        assert losses[-1] < losses[0], (losses[0], losses[-1])

        # inference clone + freeze to real int8 weights
        infer = main.clone(for_test=True)
        frozen = QuantizationFreezePass().apply(infer, scope)
        ftypes = [op.type for op in frozen.global_block.ops]
        assert "dequantize_linear" in ftypes
        assert "fake_channel_wise_quantize_dequantize_abs_max" not in ftypes

        xv = np.random.RandomState(9).randn(16, 8).astype(np.float32)
        yv = np.zeros((16, 1), np.float32)
        (qat_out,) = exe.run(
            main.clone(for_test=True), feed={"x": xv, "y": yv},
            fetch_list=[pred])
        (frozen_out,) = exe.run(frozen, feed={"x": xv, "y": yv},
                                fetch_list=[pred])
        # frozen int8 weights reproduce the QAT simulation (same grid)
        np.testing.assert_allclose(frozen_out, qat_out, rtol=1e-4, atol=1e-4)
        # weights really are int8 in the scope
        w_name = main.all_parameters()[0].name
        assert np.asarray(scope.find_var(w_name + "@INT8")).dtype == np.int8


def test_post_training_quantization():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16, 8], append_batch_size=False)
        y = layers.data("y", shape=[16, 1], append_batch_size=False)
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square(pred - y))
        infer = main.clone(for_test=True)
        fluid.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(loss)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe, _ = _train_tiny(main, startup, loss, ["x", "y"], steps=60)

        rng = np.random.RandomState(7)
        xv = rng.randn(16, 8).astype(np.float32)
        yv = np.zeros((16, 1), np.float32)
        (fp32_out,) = exe.run(infer, feed={"x": xv, "y": yv},
                              fetch_list=[pred])

        def calib():
            r = np.random.RandomState(13)
            for _ in range(4):
                yield {"x": r.randn(16, 8).astype(np.float32), "y": yv}

        ptq = PostTrainingQuantization(
            executor=exe, program=infer, feed_names=["x", "y"], scope=scope,
            batch_generator=calib,
        )
        qprog = ptq.quantize()
        types = [op.type for op in qprog.global_block.ops]
        assert "dequantize_linear" in types

        (int8_out,) = exe.run(qprog, feed={"x": xv, "y": yv},
                              fetch_list=[pred])
    # int8 within a few percent of fp32 (reference PTQ acceptance)
    np.testing.assert_allclose(int8_out, fp32_out, rtol=0.05, atol=0.02)


def test_predictor_int8(tmp_path):
    from paddle_tpu.inference.predictor import AnalysisConfig, create_predictor

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 8
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 8], append_batch_size=False)
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=3)
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        path = str(tmp_path / "m")
        fluid.io.save_inference_model(path, ["x"], [pred], exe, main)

    xv = np.random.RandomState(3).randn(4, 8).astype(np.float32)
    p32 = create_predictor(AnalysisConfig(path))
    (o32,) = p32.run([xv])
    cfg8 = AnalysisConfig(path)
    cfg8.enable_int8()
    p8 = create_predictor(cfg8)
    (o8,) = p8.run([xv])
    assert any(op.type == "dequantize_linear"
               for op in p8._program.global_block.ops)
    np.testing.assert_allclose(o8, o32, rtol=0.05, atol=0.02)


def test_post_training_quantization_percentile():
    """percentile calibration ignores a huge injected outlier that would
    blow up the abs_max scale."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16, 8], append_batch_size=False)
        y = layers.data("y", shape=[16, 1], append_batch_size=False)
        h = layers.fc(x, size=16, act="relu")
        pred = layers.fc(h, size=1)
        loss = layers.reduce_mean(layers.square(pred - y))
        infer = main.clone(for_test=True)
        fluid.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(loss)

    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe, _ = _train_tiny(main, startup, loss, ["x", "y"], steps=60)
        yv = np.zeros((16, 1), np.float32)

        def calib():
            r = np.random.RandomState(13)
            for i in range(4):
                xb = r.randn(16, 8).astype(np.float32)
                if i == 0:
                    xb[0, 0] = 1e4  # single wild outlier
                yield {"x": xb, "y": yv}

        scales = {}
        for algo in ("abs_max", "percentile"):
            ptq = PostTrainingQuantization(
                executor=exe, program=infer, feed_names=["x", "y"],
                scope=scope, batch_generator=calib, algo=algo,
                percentile=99.0)
            ptq.quantize()
            scales[algo] = ptq._act_scales if hasattr(
                ptq, "_act_scales") else None
        # behavioral check: percentile-calibrated program still close to
        # fp32 on clean data; abs_max is poisoned by the outlier scale
        rng = np.random.RandomState(7)
        xv = rng.randn(16, 8).astype(np.float32)
        (fp32_out,) = exe.run(infer, feed={"x": xv, "y": yv},
                              fetch_list=[pred])
        ptq_p = PostTrainingQuantization(
            executor=exe, program=infer, feed_names=["x", "y"], scope=scope,
            batch_generator=calib, algo="percentile", percentile=99.0)
        qp = ptq_p.quantize()
        (pct_out,) = exe.run(qp, feed={"x": xv, "y": yv}, fetch_list=[pred])
        ptq_a = PostTrainingQuantization(
            executor=exe, program=infer, feed_names=["x", "y"], scope=scope,
            batch_generator=calib, algo="abs_max")
        qa = ptq_a.quantize()
        (amax_out,) = exe.run(qa, feed={"x": xv, "y": yv}, fetch_list=[pred])
    err_p = np.abs(pct_out - fp32_out).mean()
    err_a = np.abs(amax_out - fp32_out).mean()
    assert err_p <= err_a + 1e-6
    assert err_p < 0.1
