"""C++ training demo (reference `train/demo/`,
`train/test_train_recognize_digits.cc`): compile the embedded-runtime
native program and run its training loop to convergence."""

import os
import shutil
import subprocess
import sys
import sysconfig

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SRC = os.path.join(REPO, "paddle_tpu", "native", "train_demo.cc")


def _embed_flags():
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION")
    return (["-I%s" % inc],
            ["-L%s" % libdir, "-lpython%s" % ver, "-ldl", "-lm"])


@pytest.mark.skipif(shutil.which("g++") is None, reason="no g++")
def test_cxx_train_demo_compiles_and_converges(tmp_path):
    incs, libs = _embed_flags()
    exe = str(tmp_path / "train_demo")
    build = subprocess.run(
        ["g++", "-O2", SRC] + incs + libs + ["-o", exe],
        capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr

    env = dict(os.environ)
    # CPU-only subprocess: drop the axon TPU site hook entirely — its
    # register() initializes the tunnel plugin during `import jax`
    # regardless of JAX_PLATFORMS, so a stuck/absent tunnel would hang
    # this test even though it never uses the chip
    env["PYTHONPATH"] = REPO
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    run = subprocess.run([exe], capture_output=True, text=True,
                         timeout=600, env=env)
    assert run.returncode == 0, (run.stdout, run.stderr)
    assert "C++ training demo OK" in run.stdout, run.stdout
