"""Aux subsystems: fleet checkpoints, flags, metrics, profiler, hapi Model.

Mirrors reference tests: test_fleet_checkpoint.py (numbered checkpoint
round-trip + TrainStatus), test_metrics.py, test_profiler.py smoke,
hapi test_model.py fit-loop.
"""

import os

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import dygraph, layers
from paddle_tpu.fluid.optimizer import SGDOptimizer


def _small_program():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data("x", [4, 3], "float32")
        y = fluid.data("y", [4, 1], "float32")
        loss = layers.reduce_mean(layers.square_error_cost(layers.fc(x, 1), y))
        SGDOptimizer(0.1).minimize(loss, startup)
    return prog, startup, loss


def test_fleet_checkpoint_roundtrip(tmp_path):
    from paddle_tpu.fleet import checkpoint as ckpt

    prog, startup, loss = _small_program()
    exe = fluid.Executor()
    root = str(tmp_path / "ckpts")
    from paddle_tpu.fluid.core import scope as scope_mod

    with fluid.scope_guard(fluid.Scope()):
        exe.run_startup(startup)
        w_name = prog.global_block.all_parameters()[0].name
        w0 = np.asarray(scope_mod.global_scope().find_var(w_name)).copy()
        n = ckpt.save_check_point(exe, root, ckpt.TrainStatus(2), prog)
        assert n == 0
        n = ckpt.save_check_point(exe, root, ckpt.TrainStatus(3), prog)
        assert n == 1
        assert ckpt.get_last_checkpoint_no(root) == 1
        # clobber the param, restore, compare
        scope_mod.global_scope().set(w_name, np.zeros_like(w0))
        ts = ckpt.load_check_point(exe, root, prog)
        assert ts.next() == 4
        w1 = np.asarray(scope_mod.global_scope().find_var(w_name))
        np.testing.assert_allclose(w0, w1, atol=1e-7)
        ckpt.clean_redundant_check_points(root, reserved_num=1)
        assert ckpt.get_last_checkpoint_no(root) == 1
        assert not os.path.isdir(os.path.join(root, "checkpoint_0"))


def test_sharded_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp

    from paddle_tpu.fleet import checkpoint as ckpt

    state = {"params": {"w": jnp.arange(6.0).reshape(2, 3)},
             "step": jnp.asarray(7)}
    path = str(tmp_path / "sharded")
    ckpt.save_sharded(state, path, step_meta={"epoch": 3})
    restored, meta = ckpt.load_sharded(path)
    np.testing.assert_allclose(np.asarray(restored["params"]["w"]),
                               np.arange(6.0).reshape(2, 3))
    assert meta["epoch"] == 3


def test_flags_set_get_and_nan_debug():
    import jax

    fluid.set_flags({"FLAGS_check_nan_inf": True})
    assert jax.config.jax_debug_nans
    assert fluid.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]
    fluid.set_flags({"FLAGS_check_nan_inf": False})
    assert not jax.config.jax_debug_nans
    with pytest.raises(ValueError):
        fluid.set_flags({"FLAGS_nonexistent": 1})


def test_metrics_accuracy_precision_recall_auc():
    from paddle_tpu.fluid.metrics import Accuracy, Auc, Precision, Recall

    acc = Accuracy()
    acc.update(0.8, 10)
    acc.update(0.6, 10)
    assert abs(acc.eval() - 0.7) < 1e-9

    p = Precision()
    p.update([1, 1, 0, 1], [1, 0, 0, 1])
    assert abs(p.eval() - 2 / 3) < 1e-9

    r = Recall()
    r.update([1, 0, 0, 1], [1, 1, 0, 1])
    assert abs(r.eval() - 2 / 3) < 1e-9

    auc = Auc()
    rng = np.random.RandomState(0)
    labels = rng.randint(0, 2, 1000)
    # informative scores -> auc well above 0.5
    scores = np.clip(labels * 0.5 + rng.rand(1000) * 0.5, 0, 1)
    auc.update(scores, labels)
    assert auc.eval() > 0.8


def test_profiler_smoke(tmp_path):
    from paddle_tpu.fluid import profiler as prof

    with dygraph.guard():
        with prof.profiler(log_dir=str(tmp_path / "trace")):
            with prof.RecordEvent("toy_region"):
                x = dygraph.to_variable(np.ones((4, 4), np.float32))
                (x * 2.0).numpy()
    assert os.path.isdir(str(tmp_path / "trace"))


def test_hapi_model_fit_evaluate_predict(tmp_path):
    from paddle_tpu import hapi
    from paddle_tpu.fluid.metrics import Accuracy
    from paddle_tpu.fluid.optimizer import AdamOptimizer

    rng = np.random.RandomState(0)
    x = rng.randn(64, 8).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.int64).reshape(-1, 1)

    with dygraph.guard():
        net = dygraph.Linear(8, 2)
        model = hapi.Model(net)

        def loss_fn(pred, label):
            return layers.reduce_mean(
                layers.softmax_with_cross_entropy(pred, label)
            )

        model.prepare(AdamOptimizer(1e-2), loss_fn, metrics=[Accuracy()])
        hist = model.fit((x, y), batch_size=16, epochs=3, verbose=0)
        assert hist["loss"][-1] < hist["loss"][0]
        ev = model.evaluate((x, y), batch_size=16)
        assert ev["Accuracy"] > 0.6
        pred = model.predict(x, batch_size=16)
        assert pred.shape == (64, 2)
        model.save(str(tmp_path / "m"))
        model.load(str(tmp_path / "m"))
