"""Multi-process loss/param parity over distributed/launch.py (reference
`tests/unittests/test_dist_base.py:506` check_with_place: spawn trainers,
compare against the single-process run within delta)."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "dist_worker.py")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_single(tmp_path):
    out = str(tmp_path / "single")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("PADDLE_TRAINERS_NUM", None)
    env.pop("PADDLE_TRAINER_ID", None)
    subprocess.run(
        [sys.executable, WORKER, out], env=env, check=True, timeout=300,
        capture_output=True,
    )
    with open(os.path.join(out, "result_0.json")) as f:
        return json.load(f)


def _run_multi(tmp_path, nproc=2):
    out = str(tmp_path / "multi")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.run(
        [
            sys.executable, "-m", "paddle_tpu.distributed.launch",
            "--nproc_per_node=%d" % nproc,
            "--started_port=%d" % _free_port(),
            WORKER, out,
        ],
        env=env, timeout=600, capture_output=True, text=True,
    )
    assert p.returncode == 0, "launch failed:\n%s\n%s" % (p.stdout, p.stderr)
    results = []
    for r in range(nproc):
        with open(os.path.join(out, "result_%d.json" % r)) as f:
            results.append(json.load(f))
    return results


@pytest.mark.needs_xla_multiprocess
def test_two_process_loss_parity(tmp_path):
    single = _run_single(tmp_path)
    multi = _run_multi(tmp_path, nproc=2)

    # params: every rank must end bit-close to the single-process params
    # (c_allreduce_sum made the updates globally identical)
    for r, res in enumerate(multi):
        np.testing.assert_allclose(
            res["w"], single["w"], rtol=1e-5, atol=1e-6,
            err_msg="rank %d params diverged from single-process" % r,
        )

    # losses: mean of the ranks' local losses == global-batch loss
    merged = np.mean([res["losses"] for res in multi], axis=0)
    np.testing.assert_allclose(merged, single["losses"], rtol=1e-5, atol=1e-6)
    # and training progressed
    assert single["losses"][-1] < single["losses"][0]


def test_mesh_mode_transpiled_parity_single_process():
    """Executor mesh mode on 8 virtual devices: the GradAllReduce-transpiled
    program (real psum inside shard_map) matches the plain single-device
    run on the same global batch."""
    import jax

    import paddle_tpu.fluid as fluid
    from paddle_tpu import distributed as dist
    from paddle_tpu.fluid import layers
    from paddle_tpu.fluid.transpiler.collective import GradAllReduce

    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[-1, 8], append_batch_size=False)
            y = layers.data("y", shape=[-1, 1], append_batch_size=False)
            h = layers.fc(x, size=16, act="relu")
            pred = layers.fc(h, size=1)
            loss = layers.reduce_mean(layers.square(pred - y))
            fluid.optimizer.SGDOptimizer(learning_rate=0.05).minimize(loss)
        return main, startup, loss

    rng = np.random.RandomState(0)
    xs = rng.randn(4, 16, 8).astype(np.float32)
    ys = rng.randn(4, 16, 1).astype(np.float32)

    # plain single-device
    main, startup, loss = build()
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    plain = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for t in range(4):
            (lv,) = exe.run(main, feed={"x": xs[t], "y": ys[t]},
                            fetch_list=[loss])
            plain.append(float(lv))
        w_plain = np.asarray(scope.find_var(main.all_parameters()[0].name))

    # transpiled + mesh mode over 8 virtual ranks
    main, startup, loss = build()
    eps = ["127.0.0.1:%d" % (6170 + i) for i in range(8)]
    GradAllReduce().transpile(startup_program=startup, main_program=main,
                              rank=0, endpoints=eps)
    assert any(op.type == "c_allreduce_sum"
               for op in main.global_block.ops)
    mesh = dist.DeviceMesh({"dp": 8}, devices=jax.devices())
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace(), mesh=mesh)
    sharded = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        for t in range(4):
            (lv,) = exe.run(main, feed={"x": xs[t], "y": ys[t]},
                            fetch_list=[loss])
            assert lv.shape[0] == 8  # one local loss per rank
            sharded.append(float(np.mean(lv)))
        w_mesh = np.asarray(scope.find_var(main.all_parameters()[0].name))

    np.testing.assert_allclose(sharded, plain, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w_mesh, w_plain, rtol=1e-5, atol=1e-6)
