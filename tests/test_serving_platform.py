"""paddle_tpu.serving drills: the fleet router under fire.

The acceptance bar (ISSUE 9): a hot swap under sustained load completes
with ZERO failed requests and a verify-gate-rejected version never
receives traffic; a killed replica loses no request and duplicates no
response (request-id accounting); overload yields 503 + Retry-After
with bounded behavior instead of queue collapse.  Every drill here
injects its fault (incubate.fault style) rather than asserting prose.
"""

import json as _json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.observability.metrics import MetricsRegistry
from paddle_tpu.serving import (
    AdmissionController,
    BatchingConfig,
    DeployError,
    Router,
    ShedError,
    TransitionError,
)
from paddle_tpu.serving.canary import canary_fraction
from paddle_tpu.serving.http_front import serve_http


# ---------------------------------------------------------------------------
# fakes + model builders
# ---------------------------------------------------------------------------


class EchoPredictor:
    """Output row j = [sum(x[j]) * scale]: responses are attributable to
    their requests (cross-wiring between coalesced requests would show
    up as a wrong value, not just a missing one)."""

    def __init__(self, scale=1.0, delay=0.0):
        self.scale = scale
        self.delay = delay

    def run(self, feed):
        if self.delay:
            time.sleep(self.delay)
        return [feed["x"].sum(axis=1, keepdims=True) * self.scale]

    def get_input_names(self):
        return ["x"]


def _router(scales=(1.0,), delay=0.0, **kw):
    """Router whose i-th DISTINCT model_dir gets scale scales[i] (every
    replica of a version shares its version's scale)."""
    mapping = {}

    def factory(model_dir):
        if model_dir not in mapping:
            mapping[model_dir] = scales[min(len(mapping),
                                            len(scales) - 1)]
        return EchoPredictor(scale=mapping[model_dir], delay=delay)

    kw.setdefault("max_batch", 4)
    kw.setdefault("batch_timeout_ms", 1)
    kw.setdefault("metrics_registry", MetricsRegistry())
    return Router(predictor_factory=factory, **kw)


def _save_fc_model(tmp_path, name, seed):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 8], append_batch_size=False)
        pred = layers.fc(layers.fc(x, 16, act="relu"), 2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    path = str(tmp_path / name)
    fluid.io.save_inference_model(path, ["x"], [pred], exe, main)
    return path


def _corrupt_model(model_dir):
    """Drop the fetch's producing op: structurally broken, exactly what
    the analysis verify gate exists to catch."""
    import os

    path = os.path.join(model_dir, "__model__.json")
    with open(path) as f:
        pj = _json.load(f)
    pj["blocks"][0]["ops"] = pj["blocks"][0]["ops"][:-1]
    with open(path, "w") as f:
        _json.dump(pj, f)


def _fam_total(reg, name):
    fam = reg.get(name)
    if fam is None:
        return 0
    total = 0
    for _labels, child in fam._series():
        v = child.value
        if isinstance(v, (int, float)):
            total += v
    return total


# ---------------------------------------------------------------------------
# tentpole: continuous batching across replicas
# ---------------------------------------------------------------------------


def test_fleet_spreads_batches_across_replicas_and_answers_correctly():
    reg = MetricsRegistry()
    r = _router(scales=(1.0,), delay=0.005, metrics_registry=reg)
    try:
        mv = r.deploy("v1", "m", replicas=3)
        r.promote("v1")
        results = {}
        lock = threading.Lock()

        def call(i):
            x = np.full((1, 3), float(i), np.float32)
            out, = r.infer({"x": x}, request_id="rq-%d" % i, timeout=30)
            with lock:
                results[i] = float(out[0, 0])

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(60)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 60
        for i, got in results.items():
            assert got == pytest.approx(3.0 * i), (i, got)
        # all three replicas pulled work (continuous batching: whichever
        # replica frees a slot takes the next oldest group)
        fam = reg.get("serving_fleet_batches_total")
        replicas_used = {labels[2] for labels, c in fam._series()
                         if c.value > 0}
        assert len(replicas_used) == 3, replicas_used
        assert len(mv.alive_replicas) == 3
        assert _fam_total(reg, "serving_fleet_errors_total") == 0
    finally:
        r.shutdown(drain_timeout=5)


def test_oldest_first_discipline_holds_across_signatures():
    """A minority signature must not be starved by a steady stream of a
    majority signature (the PR-2 head-of-line guarantee, now at the
    router tier)."""
    r = _router(scales=(1.0,), delay=0.004)
    try:
        r.deploy("v1", "m", replicas=1)
        r.promote("v1")
        stop = threading.Event()
        errors = []

        def flood():
            x = np.zeros((1, 4), np.float32)
            while not stop.is_set():
                try:
                    r.infer({"x": x}, timeout=30)
                except Exception as e:
                    errors.append(e)
                    return

        floods = [threading.Thread(target=flood) for _ in range(3)]
        for t in floods:
            t.start()
        time.sleep(0.05)
        t0 = time.monotonic()
        out, = r.infer({"x": np.ones((1, 6), np.float32)}, timeout=5)
        minority_latency = time.monotonic() - t0
        stop.set()
        for t in floods:
            t.join(10)
        assert not errors, errors[:1]
        assert out[0, 0] == pytest.approx(6.0)
        assert minority_latency < 2.0, minority_latency
    finally:
        r.shutdown(drain_timeout=5)


# ---------------------------------------------------------------------------
# tentpole: zero-downtime hot swap + rollback-on-bad-model
# ---------------------------------------------------------------------------


def test_hot_swap_under_load_zero_failed_requests(tmp_path):
    """Real models, sustained client load, deploy + promote mid-stream:
    every request succeeds, answers come from exactly the two versions,
    the old version drains to `retired` with its replicas closed."""
    m1 = _save_fc_model(tmp_path, "m1", seed=1)
    m2 = _save_fc_model(tmp_path, "m2", seed=2)

    from paddle_tpu.inference import AnalysisConfig, create_predictor

    p1 = create_predictor(AnalysisConfig(m1))
    p2 = create_predictor(AnalysisConfig(m2))
    x_probe = np.ones((1, 8), np.float32)
    want1, = p1.run([x_probe])
    want2, = p2.run([x_probe])
    assert not np.allclose(want1, want2)   # distinguishable versions

    reg = MetricsRegistry()
    r = Router(max_batch=4, batch_timeout_ms=1, metrics_registry=reg)
    try:
        r.deploy("v1", m1, replicas=2,
                 warmup_example={"x": np.zeros((1, 8), np.float32)})
        r.promote("v1")

        failures = []
        versions_seen = set()
        n_ok = [0]
        stop = threading.Event()
        lock = threading.Lock()

        def client(k):
            i = 0
            while not stop.is_set():
                i += 1
                try:
                    outs, info = r.infer_with_details(
                        {"x": x_probe}, request_id="c%d-%d" % (k, i),
                        timeout=30)
                except Exception as e:
                    failures.append(repr(e))
                    return
                got = outs[0]
                ok1 = np.allclose(got, want1, atol=1e-5)
                ok2 = np.allclose(got, want2, atol=1e-5)
                if not (ok1 or ok2):
                    failures.append("wrong value for %s" % info)
                    return
                with lock:
                    versions_seen.add(info["version"])
                    n_ok[0] += 1

        clients = [threading.Thread(target=client, args=(k,))
                   for k in range(4)]
        for t in clients:
            t.start()
        time.sleep(0.15)                       # sustained load running
        mv2 = r.deploy("v2", m2, replicas=2,
                       warmup_example={"x": np.zeros((1, 8), np.float32)})
        assert mv2.state == "ready"
        r.promote("v2", drain_timeout=30)      # default: drain-then-retire
        time.sleep(0.15)                       # traffic now on v2
        stop.set()
        for t in clients:
            t.join(30)

        assert not failures, failures[:3]
        assert n_ok[0] > 20, n_ok
        assert versions_seen == {"v1", "v2"}, versions_seen
        v1 = r.registry.get("v1")
        assert v1.state == "retired"
        assert len(v1.alive_replicas) == 0     # drained THEN closed
        assert r.registry.stable == "v2"
        assert _fam_total(reg, "serving_fleet_errors_total") == 0
        # v2 keeps serving after the cutover
        out, info = r.infer_with_details({"x": x_probe})
        assert info["version"] == "v2"
        np.testing.assert_allclose(out[0], want2, atol=1e-5)
    finally:
        r.shutdown(drain_timeout=5)


def test_verify_gate_rejects_bad_model_and_old_version_keeps_serving(
        tmp_path):
    """The rollback-on-gate-failure guarantee: a structurally broken
    model is rejected at deploy (analysis verify gate), receives zero
    traffic, and the serving version is untouched."""
    m1 = _save_fc_model(tmp_path, "m1", seed=1)
    m_bad = _save_fc_model(tmp_path, "m_bad", seed=3)
    _corrupt_model(m_bad)

    reg = MetricsRegistry()
    r = Router(max_batch=4, batch_timeout_ms=1, metrics_registry=reg)
    try:
        r.deploy("v1", m1, replicas=1)
        r.promote("v1")
        x = np.ones((2, 8), np.float32)
        before, = r.infer({"x": x})

        with pytest.raises(DeployError, match="rejected"):
            r.deploy("v2", m_bad, replicas=1)

        v2 = r.registry.get("v2")
        assert v2.state == "rejected"
        assert v2.error
        assert v2.requests == 0                # never received traffic
        assert not v2.alive_replicas           # replicas closed
        # promotion of a rejected version is refused
        with pytest.raises(TransitionError):
            r.promote("v2")
        # old version still serving, same answers
        assert r.registry.stable == "v1"
        after, info = r.infer_with_details({"x": x})
        assert info["version"] == "v1"
        np.testing.assert_allclose(after[0], before, atol=0)
        fam = reg.get("serving_fleet_requests_total")
        v2_requests = sum(c.value for labels, c in fam._series()
                          if labels[1] == "v2")
        assert v2_requests == 0
    finally:
        r.shutdown(drain_timeout=5)


def test_promote_keep_old_enables_rollback():
    r = _router(scales=(1.0, 2.0))
    try:
        r.deploy("v1", "m1")
        r.promote("v1")
        r.deploy("v2", "m2")
        r.promote("v2", keep_old=True)
        x = np.ones((1, 3), np.float32)
        out, info = r.infer_with_details({"x": x})
        assert info["version"] == "v2" and out[0][0, 0] == 6.0
        v1 = r.registry.get("v1")
        assert v1.state == "ready"             # warm standby, not retired
        assert v1.alive_replicas
        r.rollback()
        out, info = r.infer_with_details({"x": x})
        assert info["version"] == "v1" and out[0][0, 0] == 3.0
        assert r.registry.stable == "v1"
    finally:
        r.shutdown(drain_timeout=5)


def test_refused_transitions():
    r = _router(scales=(1.0, 1.0))
    try:
        r.deploy("v1", "m1")
        with pytest.raises(TransitionError, match="unknown version"):
            r.promote("ghost")
        r.promote("v1")
        # duplicate deploy of a live version
        with pytest.raises(TransitionError, match="already exists"):
            r.deploy("v1", "m1b")
        # canary/shadow to the stable version itself
        with pytest.raises(TransitionError):
            r.set_canary("v1", 10)
        with pytest.raises(TransitionError):
            r.set_shadow("v1")
        # retire the stable version
        with pytest.raises(TransitionError, match="refusing to retire"):
            r.retire("v1")
        # rollback with nothing kept
        with pytest.raises(TransitionError, match="roll back"):
            r.rollback()
        # promote an already-serving version
        with pytest.raises(TransitionError):
            r.promote("v1")
    finally:
        r.shutdown(drain_timeout=5)


# ---------------------------------------------------------------------------
# tentpole: kill-a-replica drill (request-id accounting)
# ---------------------------------------------------------------------------


def _id_accounting_drill(r, mv, n_requests, reg):
    """Run n_requests uniquely-valued requests through the router while
    one replica dies; assert every id answered exactly once with its
    own answer and nothing errored."""
    results = {}
    lock = threading.Lock()

    def call(i):
        rid = "acct-%d" % i
        x = np.full((1, 3), float(i), np.float32)
        try:
            out, = r.infer({"x": x}, request_id=rid, timeout=30)
            with lock:
                results.setdefault(rid, []).append(float(out[0, 0]))
        except Exception as e:
            with lock:
                results.setdefault(rid, []).append("ERR %r" % e)

    threads = [threading.Thread(target=call, args=(i,))
               for i in range(n_requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # exactly-once response accounting, with the RIGHT value per id
    assert len(results) == n_requests
    for i in range(n_requests):
        rid = "acct-%d" % i
        answers = results[rid]
        assert len(answers) == 1, (rid, answers)      # no duplicates
        assert answers[0] == pytest.approx(3.0 * i), (rid, answers)
    assert _fam_total(reg, "serving_fleet_errors_total") == 0
    assert _fam_total(reg, "serving_fleet_replica_deaths_total") == 1
    assert _fam_total(reg, "serving_fleet_requeued_total") >= 1
    assert len(mv.alive_replicas) == len(mv.replicas) - 1


def test_kill_a_replica_in_process_no_request_lost_or_duplicated():
    """In-process flavor: the fault plan's kill_replica event surfaces
    as ReplicaDeadError mid-request; the router detects the death,
    re-queues the in-flight group once, and every request id is
    answered exactly once."""
    from paddle_tpu.incubate.fault import FaultPlan

    reg = MetricsRegistry()
    r = Router(max_batch=2, batch_timeout_ms=1, metrics_registry=reg,
               predictor_factory=lambda d: EchoPredictor(delay=0.004))
    try:
        mv = r.deploy("v1", "m", replicas=2)
        r.promote("v1")
        # arm the drill: replica 0 dies serving its 3rd request
        plan = FaultPlan([{"kind": "kill_replica",
                           "replica": 0, "request": 3}])
        mv.replicas[0]._kill_at = plan.replica_kill_request(0)
        _id_accounting_drill(r, mv, n_requests=24, reg=reg)
    finally:
        r.shutdown(drain_timeout=5)


def test_kill_a_replica_process_level_real_sigkill(tmp_path):
    """Process flavor: a real subprocess worker dies by REAL SIGKILL
    mid-request (incubate.fault plan via env).  The router sees a dead
    pipe with an unanswered frame — the hardest crash shape — and the
    accounting still holds."""
    import os

    from paddle_tpu.incubate.fault import FaultPlan

    model = _save_fc_model(tmp_path, "m1", seed=1)
    reg = MetricsRegistry()
    r = Router(max_batch=2, batch_timeout_ms=1, metrics_registry=reg)
    try:
        plan = FaultPlan([{"kind": "kill_replica",
                           "replica": 0, "request": 1}])
        env = plan.to_env({})
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        mv = r.deploy("v1", model, replicas=2, kind="process", env=env)
        r.promote("v1")
        assert all(rep.kind == "process" for rep in mv.replicas)

        results = {}
        lock = threading.Lock()

        def call(i):
            rid = "proc-%d" % i
            x = np.full((1, 8), float(i) / 8.0, np.float32)
            try:
                out, = r.infer({"x": x}, request_id=rid, timeout=60)
                with lock:
                    results.setdefault(rid, []).append(out.shape)
            except Exception as e:
                with lock:
                    results.setdefault(rid, []).append("ERR %r" % e)

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(results) == 12
        for rid, answers in results.items():
            assert len(answers) == 1, (rid, answers)
            assert answers[0] == (1, 2), (rid, answers)
        assert _fam_total(reg, "serving_fleet_errors_total") == 0
        assert _fam_total(reg, "serving_fleet_replica_deaths_total") == 1
        assert len(mv.alive_replicas) == 1
        # the dead worker really is a dead PROCESS, killed by SIGKILL
        dead = [rep for rep in mv.replicas if not rep.alive][0]
        assert dead._proc.poll() == -9, dead._proc.poll()
    finally:
        r.shutdown(drain_timeout=5)


def test_request_surviving_two_deaths_fails_loudly():
    """Requeue-once, not requeue-forever: a request whose re-run also
    hits a dying replica errors out instead of looping."""
    reg = MetricsRegistry()
    r = Router(max_batch=1, batch_timeout_ms=1, metrics_registry=reg,
               predictor_factory=lambda d: EchoPredictor())
    try:
        mv = r.deploy("v1", "m", replicas=2)
        r.promote("v1")
        mv.replicas[0]._kill_at = 1            # dies on first request
        mv.replicas[1]._kill_at = 1            # and so does its backup
        with pytest.raises(RuntimeError, match="survived one replica"):
            r.infer({"x": np.ones((1, 3), np.float32)},
                    request_id="doomed", timeout=10)
        assert _fam_total(reg, "serving_fleet_replica_deaths_total") == 2
        assert not r.ready()                   # no alive replicas left
    finally:
        r.shutdown(drain_timeout=5)


# ---------------------------------------------------------------------------
# tentpole: canary + shadow
# ---------------------------------------------------------------------------


def test_canary_split_is_deterministic_and_proportional():
    r = _router(scales=(1.0, 2.0))
    try:
        r.deploy("v1", "m1", replicas=1)
        r.promote("v1")
        r.deploy("v2", "m2", replicas=1)
        r.set_canary("v2", 25.0)
        x = np.ones((1, 3), np.float32)
        routes = {}
        for i in range(200):
            rid = "cn-%d" % i
            out, info = r.infer_with_details({"x": x}, request_id=rid)
            expect = 6.0 if info["route"] == "canary" else 3.0
            assert out[0][0, 0] == pytest.approx(expect)
            assert info["route"] == (
                "canary" if canary_fraction(rid) < 0.25 else "stable")
            routes[rid] = info["route"]
        n_canary = sum(1 for v in routes.values() if v == "canary")
        assert 20 <= n_canary <= 80, n_canary   # ~25% of 200, loose CI
        # identical ids re-route identically (sticky retries)
        for rid in list(routes)[:20]:
            _, info = r.infer_with_details({"x": x}, request_id=rid)
            assert info["route"] == routes[rid]
        # graduation: promote clears the canary pointer
        r.promote("v2", keep_old=True)
        assert r.registry.canary is None
        _, info = r.infer_with_details({"x": x}, request_id="post")
        assert info["version"] == "v2" and info["route"] == "stable"
    finally:
        r.shutdown(drain_timeout=5)


def test_shadow_traffic_is_compared_never_returned():
    reg = MetricsRegistry()
    scales = iter([1.0, 1.5])     # shadow answers differ measurably
    r = Router(max_batch=4, batch_timeout_ms=1, metrics_registry=reg,
               predictor_factory=lambda d: EchoPredictor(
                   scale=next(scales)))
    try:
        r.deploy("v1", "m1")
        r.promote("v1")
        r.deploy("v2", "m2")
        r.set_shadow("v2")
        x = np.ones((1, 4), np.float32)
        for i in range(12):
            out, info = r.infer_with_details(
                {"x": x}, request_id="sh-%d" % i)
            # the client ALWAYS gets the primary's answer
            assert out[0][0, 0] == pytest.approx(4.0)
            assert info["version"] == "v1" and info["route"] == "stable"
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline and _fam_total(
                reg, "serving_fleet_shadow_compared_total") < 12):
            time.sleep(0.01)
        assert _fam_total(
            reg, "serving_fleet_shadow_compared_total") == 12
        # scale 1.5 vs 1.0 on sum=4 -> diff 2.0: every compare mismatched
        assert _fam_total(
            reg, "serving_fleet_shadow_mismatch_total") == 12
        fam = reg.get("serving_fleet_shadow_absdiff")
        diffs = [c.summary() for labels, c in fam._series()
                 if labels[1] == "v2"]
        assert diffs and diffs[0]["count"] == 12
        assert diffs[0]["max"] == pytest.approx(2.0)
        # shadow requests counted under route="shadow", never as errors
        assert _fam_total(reg, "serving_fleet_errors_total") == 0
    finally:
        r.shutdown(drain_timeout=5)


# ---------------------------------------------------------------------------
# tentpole: SLO-aware load shedding
# ---------------------------------------------------------------------------


def test_admission_controller_policy_math():
    adm = AdmissionController(max_queue_rows=10, slo_ms=100.0,
                              max_version_rows=6)
    # cold fleet admits (no evidence of overload)
    adm.check(4, 0, 0, 0.0)
    # hard queue bound
    with pytest.raises(ShedError) as ei:
        adm.check(4, 8, 2, 1000.0)
    assert ei.value.reason == "queue_full"
    assert ei.value.retry_after_s >= 1
    # per-version cap
    with pytest.raises(ShedError) as ei:
        adm.check(4, 4, 4, 1000.0)
    assert ei.value.reason == "version_cap"
    # SLO: 8 queued rows at 20 rows/s = 400ms est wait > 100ms
    with pytest.raises(ShedError) as ei:
        adm.check(1, 7, 0, 20.0)
    assert ei.value.reason == "slo"
    # same queue at a fast service rate: admitted
    adm.check(1, 7, 0, 1000.0)


def test_overload_sheds_with_retry_after_instead_of_collapsing():
    """Open-loop burst far beyond capacity: admitted requests keep
    bounded latency, the rest get ShedError with Retry-After, nothing
    errors, and the queue never exceeds its bound."""
    reg = MetricsRegistry()
    r = Router(max_batch=4, batch_timeout_ms=1,
               metrics_registry=reg,
               admission=AdmissionController(max_queue_rows=16,
                                             slo_ms=200.0),
               predictor_factory=lambda d: EchoPredictor(delay=0.02))
    try:
        r.deploy("v1", "m", replicas=1)
        r.promote("v1")
        # warm the service-rate estimate
        for _ in range(4):
            r.infer({"x": np.ones((1, 3), np.float32)}, timeout=10)

        ok_lat, shed, errors = [], [], []
        lock = threading.Lock()

        def call(i):
            t0 = time.perf_counter()
            try:
                r.infer({"x": np.ones((1, 3), np.float32)},
                        request_id="ov-%d" % i, timeout=30)
                with lock:
                    ok_lat.append(time.perf_counter() - t0)
            except ShedError as e:
                with lock:
                    shed.append(e)
            except Exception as e:
                with lock:
                    errors.append(e)

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(80)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
        assert shed, "overload never shed"
        assert ok_lat, "everything was shed"
        for e in shed:
            assert e.retry_after_s >= 1
            assert e.reason in ("queue_full", "slo")
        # bounded behavior for admitted requests: the queue bound caps
        # the worst case at ~(16 rows / 50 rows-per-s) + service; give
        # a generous CI margin — the point is NOT 30s collapse
        assert max(ok_lat) < 5.0, max(ok_lat)
        assert _fam_total(reg, "serving_fleet_shed_total") == len(shed)
        assert _fam_total(reg, "serving_fleet_errors_total") == 0
    finally:
        r.shutdown(drain_timeout=5)


# ---------------------------------------------------------------------------
# HTTP front + operator CLI
# ---------------------------------------------------------------------------


def _req(base, path, body=None):
    if body is None:
        rq = urllib.request.Request(base + path)
    else:
        rq = urllib.request.Request(
            base + path, data=_json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(rq, timeout=30) as resp:
            return resp.status, _json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, _json.loads(e.read()), dict(e.headers)


def test_http_front_lifecycle_readyz_and_shedding():
    r = _router(scales=(1.0, 2.0))
    httpd = serve_http(r, port=0, block=False, install_sigterm=False)
    base = "http://127.0.0.1:%d" % httpd.server_address[1]
    try:
        assert _req(base, "/healthz")[0] == 200
        code, out, _ = _req(base, "/readyz")
        assert code == 503 and out["ready"] is False
        # predict before any promote: 503 + Retry-After, not a 500
        code, out, hdr = _req(base, "/predict",
                              {"inputs": {"x": [[1.0] * 3]}})
        assert code == 503 and "Retry-After" in hdr

        code, out, _ = _req(base, "/admin/deploy",
                            {"version": "v1", "model_dir": "m1",
                             "replicas": 2})
        assert code == 200 and out["state"] == "ready"
        assert _req(base, "/admin/promote", {"version": "v1"})[0] == 200
        assert _req(base, "/readyz")[0] == 200

        code, out, _ = _req(base, "/predict",
                            {"inputs": {"x": [[1.0] * 3]},
                             "request_id": "h1"})
        assert code == 200
        assert out["outputs"][0][0] == [pytest.approx(3.0)]
        assert out["version"] == "v1" and out["route"] == "stable"
        assert out["request_id"] == "h1" and out["trace_id"]

        # canary via admin, then graduation
        assert _req(base, "/admin/deploy",
                    {"version": "v2", "model_dir": "m2"})[0] == 200
        code, out, _ = _req(base, "/admin/canary",
                            {"version": "v2", "percent": 50})
        assert code == 200 and out["canary"]["version"] == "v2"
        # refused transitions answer 409 with refused:true
        code, out, _ = _req(base, "/admin/retire", {"version": "v1"})
        assert code == 409 and out["refused"] is True
        code, out, _ = _req(base, "/admin/promote", {"version": "ghost"})
        assert code == 409
        # malformed admin bodies answer 400
        code, out, _ = _req(base, "/admin/promote", {})
        assert code == 400
        # stats + models + metrics all live
        assert _req(base, "/stats")[0] == 200
        code, models, _ = _req(base, "/admin/models")
        assert code == 200 and models["stable"] == "v1"
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
        assert "serving_fleet_requests_total" in text
    finally:
        httpd.shutdown()
        r.shutdown(drain_timeout=5)


def test_serving_ctl_cli_against_live_front(capsys):
    import sys

    sys.path.insert(0, "tools")
    try:
        import serving_ctl
    finally:
        sys.path.pop(0)

    r = _router(scales=(1.0, 2.0, 3.0))
    httpd = serve_http(r, port=0, block=False, install_sigterm=False)
    base = "http://127.0.0.1:%d" % httpd.server_address[1]
    try:
        def ctl(*args):
            return serving_ctl.main(["--endpoint", base] + list(args))

        assert ctl("deploy", "-v", "v1", "--model-dir", "m1",
                   "--replicas", "2") == 0
        assert ctl("promote", "-v", "v1") == 0
        assert ctl("deploy", "-v", "v2", "--model-dir", "m2") == 0
        assert ctl("canary", "-v", "v2", "--percent", "10") == 0
        assert ctl("shadow", "-v", "v2") == 0      # canary+shadow compose
        assert ctl("shadow", "--off") == 0
        assert ctl("list") == 0
        out = capsys.readouterr().out
        assert "stable:   v1" in out
        assert "canary:   v2 @ 10.0%" in out
        # refused transitions exit rc=1 (the CI contract)
        assert ctl("retire", "-v", "v1") == 1
        err = capsys.readouterr().err
        assert "refused" in err
        assert ctl("promote", "-v", "ghost") == 1
        assert ctl("rollback") == 1                # nothing kept yet
        # promote with standby, then rollback succeeds
        assert ctl("promote", "-v", "v2", "--keep-old") == 0
        assert ctl("rollback") == 0
        # drain (alias of retire) the now-standby v2
        assert ctl("drain", "-v", "v2") == 0
        assert ctl("stats") == 0
        capsys.readouterr()
        # --json emits a machine-readable envelope
        assert serving_ctl.main(
            ["--endpoint", base, "--json", "list"]) == 0
        payload = _json.loads(capsys.readouterr().out)
        assert payload["ok"] is True and payload["status"] == 200
        assert payload["response"]["stable"] == "v1"   # rolled back
        # unreachable endpoint exits rc=1
        assert serving_ctl.main(
            ["--endpoint", "http://127.0.0.1:1", "list"]) == 1
    finally:
        httpd.shutdown()
        r.shutdown(drain_timeout=5)


def test_http_graceful_shutdown_drains_and_answers_503():
    r = _router(scales=(1.0,), delay=0.05)
    httpd = serve_http(r, port=0, block=False, install_sigterm=False)
    base = "http://127.0.0.1:%d" % httpd.server_address[1]
    try:
        r.deploy("v1", "m", replicas=1)
        r.promote("v1")
        inflight = {}

        def slow_call():
            inflight["result"] = _req(
                base, "/predict", {"inputs": {"x": [[1.0] * 3]}})

        t = threading.Thread(target=slow_call)
        t.start()
        time.sleep(0.02)                   # request is in flight
        shut = threading.Thread(target=r.shutdown, kwargs={
            "drain_timeout": 10})
        shut.start()
        time.sleep(0.02)
        code, out, _ = _req(base, "/readyz")
        assert code == 503                 # readiness flips immediately
        code, out, hdr = _req(base, "/predict",
                              {"inputs": {"x": [[1.0] * 3]}})
        assert code == 503 and "Retry-After" in hdr
        assert out.get("reason") == "draining"
        shut.join(20)
        t.join(20)
        # the in-flight request was drained, not dropped
        code, out, _ = inflight["result"]
        assert code == 200, out
        assert out["outputs"][0][0] == [pytest.approx(3.0)]
    finally:
        httpd.shutdown()


# ---------------------------------------------------------------------------
# plumbing details worth pinning
# ---------------------------------------------------------------------------


def test_batching_config_is_shared_between_server_and_router():
    """The router and InferenceServer must make IDENTICAL shape
    decisions — both delegate to BatchingConfig."""
    from paddle_tpu.inference.server import InferenceServer

    cfg = BatchingConfig(max_batch=8, ragged_dims={"x": {1: [4, 8]}})
    # signature wildcards ragged axes
    a = {"x": np.zeros((1, 3), np.float32)}
    b = {"x": np.zeros((2, 7), np.float32)}
    assert cfg.signature(a) == cfg.signature(b)
    # coalesce pads batch to ladder and ragged dim to bucket
    feed, total, real, padded = cfg.coalesce([a, b])
    assert feed["x"].shape == (4, 8)       # 3 rows -> bucket 4; len -> 8
    assert total == 3
    assert real == 1 * 3 + 2 * 7
    assert padded == 4 * 8
    # ladder_specs is the warmup cross product
    specs = cfg.ladder_specs({"x": np.zeros((1, 4), np.float32)})
    shapes = {s["x"].shape for s in specs}
    assert shapes == {(b, l) for b in (1, 2, 4, 8) for l in (4, 8)}
    # the server delegates to the same class
    srv = InferenceServer(EchoPredictor(), max_batch=8,
                          ragged_dims={"x": {1: [4, 8]}})
    assert srv._cfg.signature(a) == cfg.signature(a)
    # and the ragged-axis validation is shared
    with pytest.raises(ValueError, match="batch dim"):
        BatchingConfig(ragged_dims={"x": {0: [2]}})


def test_router_validates_requests_like_the_server():
    r = _router(scales=(1.0,))
    try:
        r.deploy("v1", "m")
        r.promote("v1")
        with pytest.raises(ValueError, match="feed names"):
            r.infer({"bogus": np.ones((1, 3), np.float32)})
        with pytest.raises(ValueError, match="batch dim"):
            r.infer({"x": np.float32(3.0)})
    finally:
        r.shutdown(drain_timeout=5)


def test_per_request_traces_carry_version_and_replica():
    from paddle_tpu import observability

    r = _router(scales=(1.0,))
    observability.enable_tracing(capacity=4096)
    try:
        r.deploy("v1", "m", replicas=1)
        r.promote("v1")
        _, info = r.infer_with_details(
            {"x": np.ones((1, 3), np.float32)}, request_id="traced")
        tracer = observability.trace.default_tracer()
        evs = [e for e in tracer.events()
               if e.get("id") == info["trace_id"]]
        assert evs, "no events for the request's trace id"
        names = {e["name"] for e in evs}
        assert {"request", "queue", "replica_run"} <= names
        root = [e for e in evs if e["name"] == "request"
                and e["ph"] == "b"][0]
        assert root["args"]["version"] == "v1"
        assert root["args"]["replica"] == "v1/r0"
        assert root["args"]["request_id"] == "traced"
    finally:
        observability.disable_tracing()
        r.shutdown(drain_timeout=5)
