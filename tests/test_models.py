"""Model-zoo smoke + training tests (tiny configs).

Mirrors the reference book tests (SURVEY.md §4.2): few training iterations,
assert loss decreases; shapes pinned.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import models
from paddle_tpu.fluid import dygraph
from paddle_tpu.fluid.dygraph import to_variable


def test_lenet_forward_and_train_step():
    from paddle_tpu.fluid.optimizer import AdamOptimizer

    rng = np.random.RandomState(0)
    with dygraph.guard():
        net = models.LeNet5()
        opt = AdamOptimizer(learning_rate=1e-3)
        losses = []
        x = rng.randn(4, 1, 28, 28).astype(np.float32)
        y = rng.randint(0, 10, (4, 1)).astype(np.int64)
        for _ in range(5):
            logits = net(to_variable(x))
            assert logits.shape == (4, 10)
            loss = fluid.layers.reduce_mean(
                fluid.layers.softmax_with_cross_entropy(logits, to_variable(y))
            )
            loss.backward()
            opt.minimize(loss, parameter_list=net.parameters())
            net.clear_gradients()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]


def test_resnet18_forward_shape():
    with dygraph.guard():
        net = models.resnet18(num_classes=7)
        net.eval()
        x = to_variable(np.random.RandomState(1).randn(2, 3, 32, 32).astype(np.float32))
        out = net(x)
        assert out.shape == (2, 7)


def test_bert_tiny_forward_and_loss_decreases():
    from paddle_tpu.fluid.optimizer import AdamOptimizer

    cfg = models.BertConfig.tiny()
    rng = np.random.RandomState(2)
    B, S = 2, 16
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int64)
    seg = np.zeros((B, S), np.int64)
    pos = np.tile(np.arange(S, dtype=np.int64), (B, 1))
    mask = np.ones((B, S), np.int64)
    mlm_labels = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int64)
    mlm_w = (rng.rand(B, S) < 0.15).astype(np.float32)
    mlm_w[:, 0] = 1.0  # ensure nonzero
    nsp = rng.randint(0, 2, (B, 1)).astype(np.int64)

    with dygraph.guard():
        net = models.BertForPretraining(cfg)
        opt = AdamOptimizer(learning_rate=1e-3)
        losses = []
        for _ in range(4):
            logits, nsp_logits = net(
                to_variable(ids), to_variable(seg), to_variable(pos),
                to_variable(mask),
            )
            assert logits.shape == (B, S, cfg.vocab_size)
            assert nsp_logits.shape == (B, 2)
            loss = net.loss(
                logits, nsp_logits, to_variable(mlm_labels),
                to_variable(mlm_w), to_variable(nsp),
            )
            loss.backward()
            opt.minimize(loss, parameter_list=net.parameters())
            net.clear_gradients()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0], losses


def test_transformer_tiny_forward_and_loss_decreases():
    from paddle_tpu.fluid.optimizer import AdamOptimizer

    cfg = models.TransformerConfig.tiny()
    rng = np.random.RandomState(3)
    B, S = 2, 8
    src = rng.randint(0, cfg.src_vocab_size, (B, S)).astype(np.int64)
    tgt = rng.randint(0, cfg.tgt_vocab_size, (B, S)).astype(np.int64)
    lab = rng.randint(0, cfg.tgt_vocab_size, (B, S)).astype(np.int64)
    pos = np.tile(np.arange(S, dtype=np.int64), (B, 1))
    pad = np.ones((B, S), np.int64)

    with dygraph.guard():
        net = models.Transformer(cfg)
        opt = AdamOptimizer(learning_rate=2e-3)
        losses = []
        for _ in range(4):
            logits = net(
                to_variable(src), to_variable(pos), to_variable(tgt),
                to_variable(pos), to_variable(pad),
            )
            assert logits.shape == (B, S, cfg.tgt_vocab_size)
            loss = net.loss(logits, to_variable(lab))
            loss.backward()
            opt.minimize(loss, parameter_list=net.parameters())
            net.clear_gradients()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0], losses


def test_flash_attention_matches_naive_oracle():
    """Fused op vs hand-rolled numpy attention."""
    import jax.numpy as jnp

    from paddle_tpu.ops.attention import scaled_dot_product_attention

    rng = np.random.RandomState(4)
    q = rng.randn(2, 3, 5, 8).astype(np.float32)
    k = rng.randn(2, 3, 7, 8).astype(np.float32)
    v = rng.randn(2, 3, 7, 8).astype(np.float32)
    scale = 8 ** -0.5
    out = np.asarray(scaled_dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), scale=scale
    ))

    logits = np.einsum("bhqd,bhkd->bhqk", q, k) * scale
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", probs, v)
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-5)


def test_flash_attention_causal():
    import jax.numpy as jnp

    from paddle_tpu.ops.attention import scaled_dot_product_attention

    rng = np.random.RandomState(5)
    q = rng.randn(1, 1, 4, 4).astype(np.float32)
    k = rng.randn(1, 1, 4, 4).astype(np.float32)
    v = rng.randn(1, 1, 4, 4).astype(np.float32)
    out = np.asarray(scaled_dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True
    ))
    # position 0 attends only to key 0 -> output equals v[0]
    np.testing.assert_allclose(out[0, 0, 0], v[0, 0, 0], rtol=1e-5)


def test_bert_masked_positions_matches_full_head():
    """The gathered MLM head (masked_positions) must produce exactly the
    full head's logits at those positions (reference mask_pos gather)."""
    import jax

    from paddle_tpu import models
    from paddle_tpu.fluid import dygraph

    cfg = models.BertConfig.tiny()
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_probs_dropout_prob = 0.0
    rng = np.random.RandomState(0)
    B, S, P = 2, 16, 4
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    tt = np.zeros((B, S), np.int32)
    pos = np.tile(np.arange(S, dtype=np.int32), (B, 1))
    mpos = np.stack([np.sort(rng.choice(S, P, replace=False))
                     for _ in range(B)]).astype(np.int32)
    with dygraph.guard():
        import paddle_tpu.fluid.framework as fw

        fw._dygraph_tracer._base_key = jax.random.PRNGKey(3)
        from paddle_tpu.fluid.dygraph import to_variable

        model = models.BertForPretraining(cfg)
        model.eval()
        full, _ = model(to_variable(ids), to_variable(tt), to_variable(pos))
        gathered, _ = model(to_variable(ids), to_variable(tt),
                            to_variable(pos), masked_positions=mpos)
        fullv = np.asarray(full.data)
        gv = np.asarray(gathered.data)
    for b in range(B):
        np.testing.assert_allclose(
            gv[b], fullv[b, mpos[b]], rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_vgg_and_mobilenet_forward_and_train():
    """New vision zoo members produce logits and take a training step."""
    from paddle_tpu.fluid.dygraph import to_variable
    from paddle_tpu.fluid.optimizer import SGDOptimizer

    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 32, 32).astype(np.float32)
    y = rng.randint(0, 10, (2, 1)).astype(np.int64)
    with dygraph.guard():
        for net in (models.VGG(depth=11, num_classes=10),
                    models.MobileNetV1(num_classes=10, scale=0.25)):
            net.train()
            logits = net(to_variable(x))
            assert logits.shape == (2, 10)
            from paddle_tpu.fluid import layers as L

            loss = L.mean(L.softmax_with_cross_entropy(
                logits, to_variable(y)))
            loss.backward()
            SGDOptimizer(0.01).minimize(
                loss, parameter_list=net.parameters())
            net.clear_gradients()
            assert np.isfinite(float(loss.numpy()))
