"""Dygraph mode: eager ops, taped autograd, Layer system, optimizer updates.

Mirrors reference tests `test_imperative_basic.py`, `test_imperative_mnist.py`
(loss-decrease + grad correctness patterns).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import dygraph
from paddle_tpu.fluid.dygraph import to_variable


def test_eager_arithmetic_and_numpy():
    with dygraph.guard():
        x = to_variable(np.array([1.0, 2.0, 3.0], np.float32))
        y = x * 2.0 + 1.0
        np.testing.assert_allclose(y.numpy(), [3.0, 5.0, 7.0], rtol=1e-6)


def test_backward_simple_chain():
    with dygraph.guard():
        x = to_variable(np.array([2.0, 3.0], np.float32), stop_gradient=False)
        y = x * x  # dy/dx = 2x
        loss = fluid.layers.reduce_sum(y)
        loss.backward()
        np.testing.assert_allclose(x.gradient(), [4.0, 6.0], rtol=1e-6)


def test_backward_multi_consumer_accumulates():
    with dygraph.guard():
        x = to_variable(np.array([1.0, 2.0], np.float32), stop_gradient=False)
        a = x * 3.0
        b = x * 5.0
        loss = fluid.layers.reduce_sum(a + b)
        loss.backward()
        np.testing.assert_allclose(x.gradient(), [8.0, 8.0], rtol=1e-6)


def test_no_grad_blocks_tape():
    with dygraph.guard():
        x = to_variable(np.ones((2,), np.float32), stop_gradient=False)
        with dygraph.no_grad():
            y = x * 2.0
        assert y.stop_gradient


def test_matmul_grad_matches_numpy():
    rng = np.random.RandomState(0)
    a_np = rng.randn(3, 4).astype(np.float32)
    b_np = rng.randn(4, 5).astype(np.float32)
    with dygraph.guard():
        a = to_variable(a_np, stop_gradient=False)
        b = to_variable(b_np, stop_gradient=False)
        out = fluid.layers.matmul(a, b)
        loss = fluid.layers.reduce_sum(out)
        loss.backward()
        np.testing.assert_allclose(
            a.gradient(), np.ones((3, 5)) @ b_np.T, rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            b.gradient(), a_np.T @ np.ones((3, 5)), rtol=1e-5, atol=1e-5
        )


def test_linear_layer_and_state_dict():
    with dygraph.guard():
        lin = dygraph.Linear(4, 3)
        x = to_variable(np.ones((2, 4), np.float32))
        out = lin(x)
        assert out.shape == (2, 3)
        sd = lin.state_dict()
        assert len(sd) == 2
        # round-trip through set_state_dict
        w = {k: v.numpy() * 0 for k, v in sd.items()}
        lin.set_state_dict(w)
        out2 = lin(x)
        np.testing.assert_allclose(out2.numpy(), np.zeros((2, 3)), atol=1e-7)


def test_sgd_training_reduces_loss():
    from paddle_tpu.fluid.optimizer import SGDOptimizer

    rng = np.random.RandomState(1)
    x_np = rng.randn(16, 8).astype(np.float32)
    w_true = rng.randn(8, 1).astype(np.float32)
    y_np = x_np @ w_true

    with dygraph.guard():
        model = dygraph.Linear(8, 1)
        opt = SGDOptimizer(learning_rate=0.05)
        losses = []
        for _ in range(30):
            x = to_variable(x_np)
            y = to_variable(y_np)
            pred = model(x)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square_error_cost(pred, y)
            )
            loss.backward()
            opt.minimize(loss, parameter_list=model.parameters())
            model.clear_gradients()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.3, losses


def test_adam_training_reduces_loss():
    from paddle_tpu.fluid.optimizer import AdamOptimizer

    rng = np.random.RandomState(2)
    x_np = rng.randn(16, 4).astype(np.float32)
    y_np = (x_np.sum(1, keepdims=True) > 0).astype(np.float32)

    with dygraph.guard():
        model = dygraph.Sequential(
            dygraph.Linear(4, 8, act="relu"), dygraph.Linear(8, 1)
        )
        opt = AdamOptimizer(learning_rate=0.01)
        losses = []
        for _ in range(30):
            pred = model(to_variable(x_np))
            loss = fluid.layers.reduce_mean(
                fluid.layers.sigmoid_cross_entropy_with_logits(
                    pred, to_variable(y_np)
                )
            )
            loss.backward()
            opt.minimize(loss, parameter_list=model.parameters())
            model.clear_gradients()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]


def test_conv_bn_pool_forward_backward():
    with dygraph.guard():
        conv = dygraph.Conv2D(3, 6, 3, padding=1)
        bn = dygraph.BatchNorm(6)
        pool = dygraph.Pool2D(pool_size=2, pool_type="max", pool_stride=2)
        x = to_variable(np.random.RandomState(3).randn(2, 3, 8, 8).astype(np.float32))
        out = pool(bn(conv(x)))
        assert out.shape == (2, 6, 4, 4)
        loss = fluid.layers.reduce_mean(out)
        loss.backward()
        assert conv.weight.gradient() is not None
        assert bn.weight.gradient() is not None


def test_batchnorm_updates_running_stats():
    with dygraph.guard():
        bn = dygraph.BatchNorm(4, momentum=0.5)
        x = to_variable(
            np.random.RandomState(4).randn(8, 4, 2, 2).astype(np.float32) * 3 + 1
        )
        before = bn._mean.numpy().copy()
        bn(x)
        after = bn._mean.numpy()
        assert not np.allclose(before, after)


def test_dropout_train_eval():
    with dygraph.guard():
        drop = dygraph.Dropout(p=0.5, dropout_implementation="upscale_in_train")
        x = to_variable(np.ones((100, 100), np.float32))
        drop.train()
        y = drop(x)
        frac_zero = float((y.numpy() == 0).mean())
        assert 0.3 < frac_zero < 0.7
        drop.eval()
        y = drop(x)
        np.testing.assert_allclose(y.numpy(), np.ones((100, 100)), atol=1e-6)


def test_embedding_grad_only_on_used_rows():
    with dygraph.guard():
        emb = dygraph.Embedding([10, 4])
        ids = to_variable(np.array([[1], [3]], np.int64))
        out = emb(ids)
        loss = fluid.layers.reduce_sum(out)
        loss.backward()
        g = emb.weight.gradient()
        assert np.abs(g[[1, 3]]).sum() > 0
        assert np.abs(g[[0, 2, 4, 5, 6, 7, 8, 9]]).sum() == 0


def test_save_load_dygraph(tmp_path):
    with dygraph.guard():
        model = dygraph.Linear(4, 2)
        path = str(tmp_path / "ckpt" / "model")
        dygraph.save_dygraph(model.state_dict(), path)
        params, opt = dygraph.load_dygraph(path)
        assert opt is None
        model2 = dygraph.Linear(4, 2)
        model2.set_state_dict(params)
        np.testing.assert_allclose(
            model.weight.numpy(), model2.weight.numpy(), atol=1e-7
        )


def test_layernorm_matches_numpy():
    x_np = np.random.RandomState(5).randn(3, 6).astype(np.float32)
    with dygraph.guard():
        ln = dygraph.LayerNorm(6)
        out = ln(to_variable(x_np)).numpy()
    mean = x_np.mean(1, keepdims=True)
    var = x_np.var(1, keepdims=True)
    ref = (x_np - mean) / np.sqrt(var + 1e-5)
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


def test_jit_over_dygraph_layer():
    """A dygraph Layer forward is jax-traceable (TPU-native design goal)."""
    import jax
    import jax.numpy as jnp

    with dygraph.guard():
        model = dygraph.Linear(4, 2)
        params = {k: v.data for k, v in model.state_dict().items()}

        @jax.jit
        def fwd(params, x):
            out = model.functional_call(params, to_variable(x))
            return out.data

        x = jnp.ones((3, 4), jnp.float32)
        out = fwd(params, x)
        ref = model(to_variable(np.ones((3, 4), np.float32))).numpy()
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5)
