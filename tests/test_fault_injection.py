"""Every injected fault has a test asserting the SPECIFIC recovery
behavior: transient-I/O retries are bounded and all-or-nothing,
non-transient errors raise immediately, a mid-commit SIGKILL can never
tear a checkpoint, a stale heartbeat drives a full controller recovery
with events visible in the metrics registry and the trace timeline, and
generation fencing keeps superseded ranks from committing."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CRASH_WORKER = os.path.join(REPO, "tests", "fault_crash_worker.py")


def _snap(value):
    from paddle_tpu.incubate.checkpoint.checkpoint_saver import StateSnapshot

    return StateSnapshot({"a": np.full((4,), value, np.float32)})


def _load_a(root, saver=None):
    from paddle_tpu.incubate.checkpoint.checkpoint_saver import (
        CheckpointSaver,
        StateSnapshot,
    )

    saver = saver or CheckpointSaver(root=root, max_num_checkpoints=0)
    snap = StateSnapshot({})
    meta = saver.load_checkpoint([snap])
    return meta, snap.arrays.get("a") if meta else None


# ---------------------------------------------------------------------------
# Flaky-FS retry (CheckpointSaver transient-I/O robustness)
# ---------------------------------------------------------------------------


def test_transient_fs_error_retries_and_commits(tmp_path):
    """Two injected EIOs on the commit rename, three retries configured:
    the save must succeed, take exactly 3 mv attempts, and the committed
    checkpoint must be whole (all-or-nothing across retries)."""
    from paddle_tpu.incubate.checkpoint.checkpoint_saver import (
        CheckpointSaver,
    )
    from paddle_tpu.incubate.fault import FaultyFS

    root = str(tmp_path / "ckpt")
    fs = FaultyFS(events=[{"kind": "fs_error", "rank": 0, "op": "mv",
                           "times": 2}])
    saver = CheckpointSaver(root=root, fs=fs, max_num_checkpoints=0,
                            retry_attempts=3, retry_backoff_s=0.01)
    n = saver.save_checkpoint([_snap(7.0)], epoch=0)
    assert fs.calls("mv") == 3
    meta, a = _load_a(root)
    assert meta["no"] == n
    np.testing.assert_array_equal(a, np.full((4,), 7.0, np.float32))
    # no half-commit left behind: exactly one checkpoint_<n> dir
    ckpts = [d for d in os.listdir(root) if d.startswith("checkpoint_")]
    assert ckpts == ["checkpoint_%d" % n]


def test_transient_fs_error_budget_exhausted_is_all_or_nothing(tmp_path):
    """More failures than retries: the save raises the transient error
    and NOTHING is committed — a later clean save starts fresh."""
    from paddle_tpu.incubate.checkpoint.checkpoint_saver import (
        CheckpointSaver,
    )
    from paddle_tpu.incubate.fault import FaultyFS

    root = str(tmp_path / "ckpt")
    fs = FaultyFS(events=[{"kind": "fs_error", "rank": 0, "op": "mv",
                           "times": 10}])
    saver = CheckpointSaver(root=root, fs=fs, max_num_checkpoints=0,
                            retry_attempts=2, retry_backoff_s=0.01)
    with pytest.raises(OSError):
        saver.save_checkpoint([_snap(1.0)], epoch=0)
    assert fs.calls("mv") == 3               # initial + 2 retries
    assert not [d for d in os.listdir(root)
                if d.startswith("checkpoint_")]
    # the flake clears; a fresh save commits normally
    clean = CheckpointSaver(root=root, max_num_checkpoints=0,
                            retry_attempts=2, retry_backoff_s=0.01)
    clean.save_checkpoint([_snap(2.0)], epoch=0)
    meta, a = _load_a(root)
    assert meta is not None
    np.testing.assert_array_equal(a, np.full((4,), 2.0, np.float32))


def test_non_transient_fs_error_raises_immediately(tmp_path):
    """A PermissionError is not retried no matter the budget."""
    from paddle_tpu.incubate.checkpoint.checkpoint_saver import (
        CheckpointSaver,
    )
    from paddle_tpu.incubate.fault import FaultyFS

    fs = FaultyFS(events=[{"kind": "fs_error", "rank": 0, "op": "mv",
                           "times": 5, "fatal": True}])
    saver = CheckpointSaver(root=str(tmp_path / "ckpt"), fs=fs,
                            max_num_checkpoints=0, retry_attempts=5,
                            retry_backoff_s=0.01)
    with pytest.raises(PermissionError):
        saver.save_checkpoint([_snap(1.0)], epoch=0)
    assert fs.calls("mv") == 1               # zero retries


def test_slow_fs_rides_on_the_async_saver(tmp_path):
    """A stalling filesystem (fs_slow) must cost the TRAIN thread only
    the device->host snapshot — the serialize/commit stall rides the
    background thread — and the commit still verifies."""
    import time

    from paddle_tpu.incubate.checkpoint.checkpoint_saver import (
        AsyncCheckpointSaver,
        CheckpointSaver,
    )
    from paddle_tpu.incubate.fault import FaultPlan

    root = str(tmp_path / "ckpt")
    fs = FaultPlan([{"kind": "fs_slow", "rank": 0, "seconds": 0.25}],
                   rank=0, generation=0).wrap_fs()
    saver = AsyncCheckpointSaver(
        CheckpointSaver(root=root, fs=fs, max_num_checkpoints=0))
    t0 = time.perf_counter()
    saver.save_async([_snap(4.0)], epoch=0)
    issue_s = time.perf_counter() - t0
    assert issue_s < 0.2, issue_s          # stall not on the caller
    saver.wait()
    meta, a = _load_a(root)
    assert meta is not None
    np.testing.assert_array_equal(a, np.full((4,), 4.0, np.float32))


# ---------------------------------------------------------------------------
# Mid-commit crash (SIGKILL inside the rename)
# ---------------------------------------------------------------------------


def test_mid_commit_crash_never_tears_a_checkpoint(tmp_path):
    """SIGKILL INSIDE the commit: the tmp dir is fully written, the
    rename never happens — the root must show no new checkpoint, and a
    clean rerun must commit and load exactly its own state."""
    from paddle_tpu.incubate.fault import FaultPlan

    root = str(tmp_path / "ckpt")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    # a first clean commit to fall back to
    p = subprocess.run([sys.executable, CRASH_WORKER, root, "1.0"],
                       env=env, timeout=120, capture_output=True, text=True)
    assert p.returncode == 0, p.stderr

    crash_env = FaultPlan([{"kind": "crash", "rank": 0, "op": "mv",
                            "nth": 1}]).to_env(env)
    p = subprocess.run([sys.executable, CRASH_WORKER, root, "2.0"],
                       env=crash_env, timeout=120, capture_output=True,
                       text=True)
    assert p.returncode == -9, (p.returncode, p.stdout, p.stderr)

    # nothing committed beyond checkpoint_0; the attempt left only a
    # tmp dir invisible to the load path
    assert [d for d in sorted(os.listdir(root))
            if d.startswith("checkpoint_")] == ["checkpoint_0"]
    assert any(d.startswith(".tmp_checkpoint_") for d in os.listdir(root))
    meta, a = _load_a(root)
    assert meta["no"] == 0
    np.testing.assert_array_equal(a, np.full((4,), 1.0, np.float32))

    # recovery: the rerun commits checkpoint_1 (numbering advanced past
    # the dead attempt, never overwriting)
    p = subprocess.run([sys.executable, CRASH_WORKER, root, "3.0"],
                       env=env, timeout=120, capture_output=True, text=True)
    assert p.returncode == 0, p.stderr
    meta, a = _load_a(root)
    assert meta["no"] == 1
    np.testing.assert_array_equal(a, np.full((4,), 3.0, np.float32))


# ---------------------------------------------------------------------------
# Generation fencing
# ---------------------------------------------------------------------------


def test_generation_fence_rejects_stale_commit(tmp_path):
    """Once the controller bumps the generation, a saver fenced to the
    old one cannot commit — and nothing it wrote becomes visible."""
    from paddle_tpu.distributed.elastic import (
        GenerationFence,
        StaleGenerationError,
    )
    from paddle_tpu.incubate.checkpoint.checkpoint_saver import (
        CheckpointSaver,
    )

    ws = str(tmp_path)
    root = os.path.join(ws, "ckpt")
    fence = GenerationFence(ws, generation=0)
    saver = CheckpointSaver(root=root, max_num_checkpoints=0, fence=fence)
    saver.save_checkpoint([_snap(1.0)], epoch=0)   # same generation: fine

    GenerationFence(ws).bump()                      # the controller moves on
    with pytest.raises(StaleGenerationError):
        saver.save_checkpoint([_snap(2.0)], epoch=1)
    assert [d for d in sorted(os.listdir(root))
            if d.startswith("checkpoint_")] == ["checkpoint_0"]
    meta, a = _load_a(root)
    np.testing.assert_array_equal(a, np.full((4,), 1.0, np.float32))

    # fencing is never retried as if it were I/O flake
    from paddle_tpu.incubate.checkpoint.checkpoint_saver import (
        default_is_transient,
    )

    assert not default_is_transient(StaleGenerationError("stale"))


def test_fence_check_and_bump_roundtrip(tmp_path):
    from paddle_tpu.distributed.elastic import (
        GenerationFence,
        StaleGenerationError,
    )

    f0 = GenerationFence(str(tmp_path), generation=0)
    f0.check()                                     # current: fine
    assert GenerationFence(str(tmp_path)).bump() == 1
    with pytest.raises(StaleGenerationError):
        f0.check()
    f1 = GenerationFence(str(tmp_path))            # adopts the current gen
    f1.check()
    assert f1.generation == 1


# ---------------------------------------------------------------------------
# Stale heartbeat -> full controller recovery, events observable
# ---------------------------------------------------------------------------


def test_stale_heartbeat_recovery_visible_in_metrics_and_trace(tmp_path):
    """A rank that HANGS (heartbeat stalls, process alive) is detected
    by the watchdog, the gang is drained and re-formed, and the recovery
    is visible as `elastic_*` metrics and an `elastic_recovery` span."""
    from paddle_tpu.distributed.elastic.drill import run_drill
    from paddle_tpu.observability import trace as _trace
    from paddle_tpu.observability.metrics import default_registry

    reg = default_registry()
    tracer = _trace.enable_tracing()
    before = reg.counter(
        "elastic_recoveries_total",
        "Completed drain->fence->reshape->relaunch cycles").value
    report = run_drill(
        str(tmp_path / "ws"), world_sizes=(2, 2), kill_rank=None,
        fault_events=[{"kind": "hang", "rank": 1, "step": 5}],
        config={"n_samples": 48, "dim": 12, "global_batch": 12,
                "epochs": 2, "save_every": 2, "seed": 7,
                # the hung rank never exits on its own: only the
                # watchdog can see it, only SIGKILL clears it
                "hb_timeout_s": 4.0, "transport_timeout_s": 30.0,
                "drain_grace_s": 3.0},
        control=False)
    try:
        hist = report["controller"]["history"]
        assert hist[0]["event"]["kind"] == "stale_heartbeat", hist
        assert hist[0]["event"]["ranks"] == [1]
        assert report["controller"]["state"] == "DONE", hist
        assert report["checks"]["no_dup_no_drop"], report["checks"]

        # recovery events in the PR 4 registry...
        assert reg.counter(
            "elastic_recoveries_total",
            "Completed drain->fence->reshape->relaunch cycles"
        ).value == before + 1
        assert reg.gauge("elastic_generation", "").value == 1
        fails = reg.counter("elastic_rank_failures_total", "",
                            labelnames=("kind",))
        assert fails.labels("stale_heartbeat").value >= 1
        # ...and in the PR 6 trace timeline
        events = list(tracer.events())
        spans = [e for e in events
                 if e.get("name") == "elastic_recovery"]
        assert spans and spans[0]["args"]["cause"] == "stale_heartbeat"
        states = [e["args"]["state"] for e in events
                  if e.get("name") == "elastic_state"]
        for expected in ("RUNNING", "DRAINING", "FENCING", "RESHAPING",
                         "DONE"):
            assert expected in states, states
    finally:
        _trace.disable_tracing()


# ---------------------------------------------------------------------------
# Bounded retries
# ---------------------------------------------------------------------------


def test_controller_retry_budget_is_bounded(tmp_path):
    """A gang that dies in EVERY generation exhausts max_restarts and
    the controller reports FAILED instead of flapping forever."""
    from paddle_tpu.distributed.elastic.drill import run_drill

    report = run_drill(
        str(tmp_path / "ws"), world_sizes=(1,), kill_rank=None,
        fault_events=[
            {"kind": "kill", "rank": 0, "step": 2, "gen": g}
            for g in range(6)
        ],
        config={"n_samples": 48, "dim": 12, "global_batch": 12,
                "epochs": 2, "save_every": 2, "seed": 7},
        control=False)
    ctrl = report["controller"]
    assert ctrl["state"] == "FAILED"
    assert not report["passed"]
    # max_restarts (len(schedule)+1 = 2) bounds the attempts: the gang
    # launched exactly 3 times despite 6 scheduled kills
    assert len(ctrl["history"]) == 3
    assert all(h["event"]["kind"] == "rank_exit" for h in ctrl["history"])
