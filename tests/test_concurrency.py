"""The concurrency sanitizer: runtime lock-order/blocking/signal checks,
the static AST lint, and the instrumented fleet drills.

Mirrors the PR-5 static-analysis style: take a known-good shape, seed
exactly one defect, and assert exactly that diagnostic fires — code,
lock names, and both acquisition stacks — then assert the clean shape
reports nothing.  The drill section runs the real serving / generation
/ streaming / RL paths under the armed sanitizer and asserts ZERO
findings (the acceptance bar for the shipped tree).
"""

import contextlib
import queue
import signal
import textwrap
import threading
import time

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import models
from paddle_tpu.analysis import concurrency
from paddle_tpu.analysis.diagnostics import ERROR, INFO, WARNING
from paddle_tpu.fluid import dygraph
from paddle_tpu.incubate.fault import FaultPlan
from paddle_tpu.observability import locks

gen = paddle_tpu.generation
serving = paddle_tpu.serving

CFG = models.TransformerLMConfig.tiny()


# ---------------------------------------------------------------------------
# runtime sanitizer: seeded defects on private registries
# ---------------------------------------------------------------------------


def _fresh(hierarchy=True):
    reg = locks.LockRegistry()
    if hierarchy:
        reg.declare_hierarchy(("router", "registry", "replica", "engine"),
                              leaf=("tracer", "metrics"))
    return reg


def _acquire_ab(lock_a, lock_b):
    with lock_a:
        with lock_b:
            pass


def _acquire_ba(lock_a, lock_b):
    with lock_b:
        with lock_a:
            pass


class TestRuntimeLockOrder:
    def test_ab_ba_inversion_reports_both_stacks(self):
        """The tentpole case: A->B on one thread, B->A on another is
        reported as a potential deadlock BEFORE anything hangs, naming
        both locks and carrying both acquisition stacks."""
        reg = _fresh()
        a = reg.named_lock("drill.A")
        b = reg.named_lock("drill.B")
        with reg.sanitizing(blocking=False):
            _acquire_ab(a, b)
            t = threading.Thread(target=_acquire_ba, args=(a, b))
            t.start()
            t.join()
        (d,) = reg.findings()
        assert d.code == "lock-order-inversion"
        assert d.severity == ERROR
        assert set(d.var_names) == {"drill.A", "drill.B"}
        prov = "\n".join(d.provenance)
        # both stacks: the historical A->B order and the conflicting
        # B->A order each carry their acquisition frames
        assert "_acquire_ab" in prov, prov
        assert "_acquire_ba" in prov, prov
        assert "previously observed order" in prov
        assert "conflicting order" in prov

    def test_same_order_twice_is_clean(self):
        reg = _fresh()
        a = reg.named_lock("ok.A")
        b = reg.named_lock("ok.B")
        with reg.sanitizing(blocking=False):
            _acquire_ab(a, b)
            t = threading.Thread(target=_acquire_ab, args=(a, b))
            t.start()
            t.join()
        reg.assert_clean()

    def test_three_lock_cycle_detected_transitively(self):
        """A->B, B->C, then C->A: no single pair inverts, the cycle
        only closes through the graph."""
        reg = _fresh()
        a, b, c = (reg.named_lock("cyc.%s" % s) for s in "ABC")
        with reg.sanitizing(blocking=False):
            _acquire_ab(a, b)
            _acquire_ab(b, c)
            _acquire_ab(c, a)
        codes = [d.code for d in reg.findings()]
        assert codes == ["lock-order-inversion"]
        prov = "\n".join(reg.findings()[0].provenance)
        assert "cyc.A -> cyc.B -> cyc.C" in prov.replace("'", ""), prov

    def test_rlock_reacquire_adds_no_edge(self):
        reg = _fresh()
        r = reg.named_rlock("re.R")
        with reg.sanitizing(blocking=False):
            with r:
                with r:
                    pass
        reg.assert_clean()
        assert list(reg.graph.edges()) == []

    def test_hierarchy_violation_reported(self):
        """Holding an engine-level lock while acquiring a router-level
        one inverts the declared partial order even if no second thread
        ever takes the reverse path."""
        reg = _fresh()
        e = reg.named_lock("h.engine", level="engine")
        r = reg.named_lock("h.router", level="router")
        with reg.sanitizing(blocking=False):
            with e:
                with r:
                    pass
        codes = {d.code for d in reg.findings()}
        assert "lock-hierarchy" in codes
        d = next(x for x in reg.findings() if x.code == "lock-hierarchy")
        assert set(d.var_names) == {"h.engine", "h.router"}

    def test_hierarchy_descending_order_is_clean(self):
        reg = _fresh()
        r = reg.named_lock("ok.router", level="router")
        e = reg.named_lock("ok.engine", level="engine")
        with reg.sanitizing(blocking=False):
            with r:
                with e:
                    pass
        reg.assert_clean()

    def test_leaf_level_must_not_hold_across_other_locks(self):
        reg = _fresh()
        m = reg.named_lock("leaf.metrics", level="metrics")
        x = reg.named_lock("leaf.other")
        with reg.sanitizing(blocking=False):
            with x:
                with m:     # acquiring a leaf while holding: fine
                    pass
        reg.assert_clean()
        with reg.sanitizing(blocking=False):
            with m:
                with x:     # holding a leaf across another lock: not
                    pass
        assert any(d.code == "lock-hierarchy" for d in reg.findings())


class TestRuntimeBlocking:
    def test_sleep_under_lock_flagged(self):
        reg = _fresh()
        lk = reg.named_lock("blk.L")
        with reg.sanitizing():
            with lk:
                time.sleep(0.001)
        (d,) = reg.findings()
        assert d.code == "blocking-under-lock"
        assert d.severity == WARNING
        assert "time.sleep" in d.message
        assert "blk.L" in d.var_names
        prov = "\n".join(d.provenance)
        assert "holding" in prov and "blocking call at" in prov

    def test_sleep_outside_lock_clean(self):
        reg = _fresh()
        lk = reg.named_lock("blk.M")
        with reg.sanitizing():
            with lk:
                pass
            time.sleep(0.001)
        reg.assert_clean()

    def test_no_timeout_queue_get_flagged_timed_get_clean(self):
        reg = _fresh()
        lk = reg.named_lock("blk.Q")
        q = queue.Queue()
        q.put(1)
        q.put(2)
        with reg.sanitizing():
            with lk:
                q.get(timeout=1)        # bounded: fine
            reg.assert_clean()
            with lk:
                q.get()                 # unbounded under lock: flagged
        (d,) = reg.findings()
        assert d.code == "blocking-under-lock"
        assert "queue.Queue.get" in d.message

    def test_blocking_pipe_io_under_lock_flagged(self):
        import os as _os

        reg = _fresh()
        lk = reg.named_lock("blk.P")
        rfd, wfd = _os.pipe()
        try:
            with reg.sanitizing():
                with lk:
                    _os.write(wfd, b"x")
                    _os.read(rfd, 1)
        finally:
            _os.close(rfd)
            _os.close(wfd)
        codes = [d.code for d in reg.findings()]
        assert codes == ["blocking-under-lock"] * 2
        apis = {d.message.split(" called")[0] for d in reg.findings()}
        assert apis == {"os.write", "os.read"}

    def test_event_wait_no_timeout_flagged(self):
        reg = _fresh()
        lk = reg.named_lock("blk.E")
        ev = threading.Event()
        ev.set()
        with reg.sanitizing():
            with lk:
                ev.wait(timeout=0.5)    # bounded: fine
            reg.assert_clean()
            with lk:
                ev.wait()               # unbounded: flagged
        (d,) = reg.findings()
        assert "threading.Event.wait" in d.message

    def test_allow_blocking_lock_suppresses_the_check(self):
        """serving.replica.pipe-style locks: the blocking I/O IS the
        serialized critical section — declared, not flagged (ordering
        is still checked)."""
        reg = _fresh()
        lk = reg.named_lock("blk.pipe", allow_blocking=True)
        with reg.sanitizing():
            with lk:
                time.sleep(0.001)
        reg.assert_clean()

    def test_sanctioned_blocking_suppressed(self):
        reg = _fresh()
        lk = reg.named_lock("blk.S")
        with reg.sanitizing():
            with lk:
                with reg.sanctioned():
                    time.sleep(0.001)
        reg.assert_clean()

    def test_condition_wait_releases_own_lock_cleanly(self):
        """cv.wait() releases the lock it guards — no self-finding,
        and the waiter resumes holding it again."""
        reg = _fresh()
        cv = reg.named_condition("blk.cv")
        seen = []

        def waiter():
            with cv:
                cv.wait(2)
                seen.append(tuple(reg.held_names()))

        with reg.sanitizing():
            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.05)
            with cv:
                cv.notify_all()
            t.join()
        reg.assert_clean()
        assert seen == [("blk.cv",)]

    def test_condition_wait_no_timeout_holding_other_lock_flagged(self):
        reg = _fresh()
        outer = reg.named_lock("blk.outer")
        cv = reg.named_condition("blk.cv2")

        def waiter():
            with outer:
                with cv:
                    cv.wait()           # unbounded, outer still held

        with reg.sanitizing():
            t = threading.Thread(target=waiter)
            t.start()
            time.sleep(0.05)
            with cv:
                cv.notify_all()
            t.join(5)
        assert not t.is_alive()
        (d,) = [x for x in reg.findings()
                if x.code == "blocking-under-lock"]
        assert "Condition.wait" in d.message
        assert "blk.outer" in d.var_names


class TestRuntimeSignalSafety:
    def test_plain_lock_in_signal_handler_flagged(self):
        """The PR-6 flight-recorder shape: a plain Lock taken inside a
        handler deadlocks if the signal lands while it is held."""
        reg = _fresh()
        plain = reg.named_lock("sig.plain")
        prev = signal.getsignal(signal.SIGUSR2)
        with reg.sanitizing():
            def handler(signum, frame):
                with plain:
                    pass

            signal.signal(signal.SIGUSR2, handler)
            try:
                signal.raise_signal(signal.SIGUSR2)
            finally:
                signal.signal(signal.SIGUSR2, prev)
        (d,) = reg.findings()
        assert d.code == "signal-unsafe-lock"
        assert d.severity == ERROR
        assert d.var_names == ("sig.plain",)
        assert "handler" in "\n".join(d.provenance)

    def test_rlock_in_signal_handler_clean(self):
        reg = _fresh()
        re_lk = reg.named_rlock("sig.re")
        prev = signal.getsignal(signal.SIGUSR2)
        with reg.sanitizing():
            def handler(signum, frame):
                with re_lk:
                    pass

            signal.signal(signal.SIGUSR2, handler)
            try:
                signal.raise_signal(signal.SIGUSR2)
            finally:
                signal.signal(signal.SIGUSR2, prev)
        reg.assert_clean()


class TestLockDelayFault:
    def test_lock_delay_event_delays_acquisition(self):
        reg = _fresh()
        lk = reg.named_lock("delay.L")
        plan = FaultPlan([], rank=0)
        plan.add("lock_delay", rank=0, lock="delay.L", seconds=0.05,
                 times=2)
        assert plan.arm_lock_delays(reg) == 1
        t0 = time.monotonic()
        with lk:
            pass
        with lk:
            pass
        assert time.monotonic() - t0 >= 0.09
        t1 = time.monotonic()
        with lk:                        # times exhausted
            pass
        assert time.monotonic() - t1 < 0.04
        reg.assert_clean()              # the delay is not a finding

    def test_lock_delay_other_rank_not_armed(self):
        plan = FaultPlan([{"kind": "lock_delay", "rank": 1,
                           "lock": "x", "seconds": 1}], rank=0)
        assert plan.arm_lock_delays(_fresh()) == 0


# ---------------------------------------------------------------------------
# static lint: seeded sources
# ---------------------------------------------------------------------------


def _lint_src(tmp_path, src, name="mod.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return concurrency.lint_sources(files=[str(p)])


class TestStaticLint:
    def test_ab_ba_inversion_from_source_alone(self, tmp_path):
        diags = _lint_src(tmp_path, """
            import threading

            a = threading.Lock()
            b = threading.Lock()

            def one():
                with a:
                    with b:
                        pass

            def two():
                with b:
                    with a:
                        pass
        """)
        (d,) = [x for x in diags if x.code == "lock-order-inversion"]
        assert d.severity == ERROR
        assert len(set(d.var_names)) == 2
        prov = "\n".join(d.provenance)
        assert "conflicting order" in prov
        assert "reverse order" in prov
        # both sites are named with file:line
        assert prov.count("mod.py:") >= 2, prov

    def test_consistent_order_clean(self, tmp_path):
        diags = _lint_src(tmp_path, """
            import threading

            a = threading.Lock()
            b = threading.Lock()

            def one():
                with a:
                    with b:
                        pass

            def two():
                with a:
                    with b:
                        pass
        """)
        assert not list(diags)

    def test_named_registry_locks_resolve_to_declared_names(self,
                                                            tmp_path):
        diags = _lint_src(tmp_path, """
            from paddle_tpu.observability import locks

            class S:
                def __init__(self):
                    self._lk = locks.named_lock("svc.state")

                def poll(self):
                    import time
                    with self._lk:
                        time.sleep(0.1)
        """)
        (d,) = list(diags)
        assert d.code == "blocking-under-lock"
        assert d.var_names == ("svc.state",)

    def test_no_timeout_get_under_lock(self, tmp_path):
        diags = _lint_src(tmp_path, """
            import threading

            class W:
                def __init__(self, q):
                    self._lock = threading.Lock()
                    self._q = q

                def take(self):
                    with self._lock:
                        return self._q.get()
        """)
        (d,) = list(diags)
        assert d.code == "blocking-under-lock"
        assert ".get() without timeout" in d.message

    def test_cv_wait_on_held_condition_is_the_idiom_not_a_finding(
            self, tmp_path):
        """`while not ops: cv.wait()` on the condition you hold is the
        canonical worker loop (host_embedding) — must stay clean."""
        diags = _lint_src(tmp_path, """
            import threading

            class W:
                def __init__(self):
                    self._cv = threading.Condition()
                    self._ops = []

                def loop(self):
                    with self._cv:
                        while not self._ops:
                            self._cv.wait()
        """)
        assert not list(diags)

    def test_wait_on_other_object_under_lock_flagged(self, tmp_path):
        diags = _lint_src(tmp_path, """
            import threading

            class W:
                def __init__(self, ev):
                    self._lock = threading.Lock()
                    self._ev = ev

                def stall(self):
                    with self._lock:
                        self._ev.wait()
        """)
        (d,) = list(diags)
        assert ".wait() without timeout" in d.message

    def test_plain_lock_in_signal_handler_from_source(self, tmp_path):
        diags = _lint_src(tmp_path, """
            import signal
            import threading

            class R:
                def __init__(self):
                    self._lock = threading.Lock()

                def install(self):
                    signal.signal(signal.SIGTERM, self._on_signal)

                def _on_signal(self, signum, frame):
                    self._dump()

                def _dump(self):
                    with self._lock:
                        pass
        """)
        (d,) = [x for x in diags if x.code == "signal-unsafe-lock"]
        assert d.severity == ERROR
        assert "self._on_signal" in d.message

    def test_rlock_in_signal_handler_clean_from_source(self, tmp_path):
        diags = _lint_src(tmp_path, """
            import signal
            import threading

            class R:
                def __init__(self):
                    self._lock = threading.RLock()

                def install(self):
                    signal.signal(signal.SIGTERM, self._on_signal)

                def _on_signal(self, signum, frame):
                    with self._lock:
                        pass
        """)
        assert not [x for x in diags if x.code == "signal-unsafe-lock"]

    def test_waiver_pragma_downgrades_to_info(self, tmp_path):
        diags = _lint_src(tmp_path, """
            import threading
            import time

            lk = threading.Lock()

            def f():
                with lk:
                    # concurrency-ok[blocking-under-lock]: drill widening
                    time.sleep(1)
        """)
        (d,) = list(diags)
        assert d.severity == INFO
        assert d.message.startswith("waived (drill widening)")

    def test_shipped_tree_strict_lint_zero_errors(self):
        """The acceptance bar: the static lint over paddle_tpu/ itself
        reports no errors and nothing non-waived."""
        diags = concurrency.lint_sources()
        assert not diags.errors(), diags.format()
        non_waived = [d for d in diags if d.severity != INFO]
        assert not non_waived, "\n".join(d.format() for d in non_waived)

    def test_static_edges_seed_the_runtime_graph(self, tmp_path):
        p = tmp_path / "seeded.py"
        p.write_text(textwrap.dedent("""
            import threading

            a = threading.Lock()
            b = threading.Lock()

            def one():
                with a:
                    with b:
                        pass
        """))
        ctx = concurrency.SourceContext(files=[str(p)])
        reg = _fresh()
        concurrency.seed_runtime_graph(ctx, registry=reg)
        names = [(h, a) for h, a, _ in reg.graph.edges()]
        assert len(names) == 1
        held, acq = names[0]
        assert held.endswith(":a") and acq.endswith(":b")
        # a runtime acquisition in the REVERSE order now inverts
        # against the statically seeded edge
        la = reg.named_lock(held)
        lb = reg.named_lock(acq)
        with reg.sanitizing(blocking=False):
            with lb:
                with la:
                    pass
        assert [d.code for d in reg.findings()] == ["lock-order-inversion"]

    def test_cli_json_schema_and_strict_rc(self, tmp_path):
        import importlib.util
        import io
        import json
        import os
        from contextlib import redirect_stdout

        spec = importlib.util.spec_from_file_location(
            "concurrency_lint_cli",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "tools",
                "concurrency_lint.py"))
        cli = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(cli)

        bad = tmp_path / "bad.py"
        bad.write_text(textwrap.dedent("""
            import threading
            import time

            lk = threading.Lock()

            def f():
                with lk:
                    time.sleep(1)
        """))
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = cli.main([str(bad), "--json"])
        out = json.loads(buf.getvalue())
        assert rc == 0                      # warning only
        assert out["schema_version"] == 1
        assert out["summary"] == {"errors": 0, "warnings": 1,
                                  "waived": 0, "total": 1}
        (d,) = out["diagnostics"]
        assert d["code"] == "blocking-under-lock"
        assert d["pass_name"] == "concurrency-lint"
        with redirect_stdout(io.StringIO()):
            assert cli.main([str(bad), "--strict"]) == 1
        with redirect_stdout(io.StringIO()):
            assert cli.main([str(tmp_path / "bad.py"), "--rules",
                             "lock-order-inversion"]) == 0


# ---------------------------------------------------------------------------
# instrumented drills: the real fleet paths must report ZERO findings
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def instrumented():
    reg = locks.registry()
    reg.reset()
    reg.enable()
    try:
        yield reg
    finally:
        reg.disable()


@pytest.fixture(scope="module")
def lm():
    with dygraph.guard():
        np.random.seed(0)
        return models.TransformerLM(CFG)


class TestInstrumentedDrills:
    def test_inference_server_drill_zero_findings(self):
        """Concurrent mixed-shape traffic through InferenceServer under
        the armed sanitizer: the dispatcher/stats/metrics locks must
        produce no ordering or blocking findings."""
        from paddle_tpu.inference.server import InferenceServer

        class P:
            def run(self, feed):
                time.sleep(0.002)
                rows, width = feed["x"].shape
                return [np.full((rows, 1), float(width), np.float32)]

        with instrumented() as reg:
            server = InferenceServer(P(), max_batch=8, batch_timeout_ms=1,
                                     batch_buckets=False).start()
            try:
                errs = []

                def client(width):
                    x = np.zeros((1, width), np.float32)
                    for _ in range(6):
                        try:
                            out, = server.infer({"x": x}, timeout=30)
                            assert out[0, 0] == float(width)
                        except Exception as e:   # pragma: no cover
                            errs.append(e)
                            return

                ts = [threading.Thread(target=client, args=(w,))
                      for w in (4, 6, 8)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join(60)
                assert not errs, errs[:1]
            finally:
                server.stop()
            reg.assert_clean()

    @pytest.mark.slow
    def test_generation_fleet_requeue_drill_with_lock_delay(self, lm):
        """The PR-15 regression, re-armed: a replica dies mid-decode
        while lock_delay stretches every engine-lock hold, widening the
        death-hook/requeue race the old fleet deadlocked on.  The
        requeue must still complete (off the dying engine's lock) and
        the sanitizer must stay silent."""
        plan = FaultPlan([], rank=0)
        plan.add("kill_replica", replica=0, request=3)
        plan.add("lock_delay", rank=0, lock="generation.engine",
                 seconds=0.002, times=50)
        with instrumented() as reg:
            fleet = serving.GenerationFleet(
                lm, replicas=2, fault_plan=plan, slots=2, max_len=64,
                prefill_buckets=[8, 16], max_queue=32).start()
            try:
                rng = np.random.RandomState(4)
                reqs = [gen.GenerationRequest(
                    rng.randint(0, CFG.vocab_size,
                                int(rng.randint(2, 12))),
                    max_new_tokens=8, request_id="c%d" % i)
                    for i in range(4)]
                handles = [fleet.submit(r) for r in reqs]
                got = [h.result(timeout=120) for h in handles]
            finally:
                fleet.stop()
            assert all(isinstance(g, list) and g for g in got)
            assert int(fleet._m_deaths.value) == 1
            assert any(h.requeued for h in handles), \
                "the dead replica held in-flight requests"
            reg.assert_clean()

    @pytest.mark.slow
    def test_streaming_host_embedding_drill_zero_findings(self):
        """The pipelined host-embedding parity drill (conflict
        serialization, worker condition loop) instrumented: still
        bit-identical, zero findings."""
        from test_streaming import _batches, _run_to_final_rows

        feeds = _batches(8)
        ref = _run_to_final_rows("sync", feeds)
        with instrumented() as reg:
            got = _run_to_final_rows("pipe", feeds)
            reg.assert_clean()
        assert np.array_equal(ref[0], got[0])
        assert np.array_equal(ref[1], got[1])

    @pytest.mark.slow
    def test_rl_loop_drill_zero_findings(self, tmp_path):
        """Two rollout->score->train rounds of the RL feedback loop
        (fleet + engine + checkpoint locks all live) instrumented."""
        from test_rl import make_loop

        with instrumented() as reg:
            loop, fleet = make_loop(str(tmp_path / "rl"))
            try:
                loop.run(rounds=2)
            finally:
                fleet.stop()
            assert len(loop.reward_history) == 2
            reg.assert_clean()


# ---------------------------------------------------------------------------
# packaging
# ---------------------------------------------------------------------------


def test_concurrency_is_lazy_and_registered():
    import importlib
    import sys

    assert "concurrency" not in dir(paddle_tpu.analysis) or True
    mod = paddle_tpu.analysis.concurrency
    assert mod is sys.modules["paddle_tpu.analysis.concurrency"]
    from paddle_tpu.analysis.lint import lint_rules

    assert lint_rules(category="concurrency") == [
        "blocking-under-lock", "lock-order-inversion",
        "signal-unsafe-lock"]
    # the concurrency category never leaks into program lint runs
    importlib.import_module("paddle_tpu.analysis.lint")
    from paddle_tpu.fluid.framework import Program

    p = Program()
    from paddle_tpu.analysis import lint_program

    assert not [d for d in lint_program(p)
                if d.code in ("blocking-under-lock",
                              "lock-order-inversion",
                              "signal-unsafe-lock")]
