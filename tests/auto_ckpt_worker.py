"""auto_checkpoint drill worker: deterministic static-graph training
under `incubate.checkpoint.train_epoch_range`, with optional SIGKILL
mid-epoch (the preemption).  Env knobs:

  ACP_WORKSPACE    checkpoint root (TrainEpochRange keys a subdir by
                   program hash)
  ACP_EPOCHS       total epochs the JOB must complete
  ACP_KILL_EPOCH   epoch at which to SIGKILL ourselves mid-epoch (-1 off)
  ACP_RESULT       path for the result JSON (written only on completion)
  ACP_SYNC_SAVE    "1" forces synchronous saves (default async)
"""

import json
import os
import re
import signal

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", _flags)
os.environ["XLA_FLAGS"] = _flags + " --xla_force_host_platform_device_count=1"

import numpy as np


def main():
    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers
    from paddle_tpu.incubate.checkpoint import TrainEpochRange

    ws = os.environ["ACP_WORKSPACE"]
    epochs = int(os.getenv("ACP_EPOCHS", "6"))
    kill_epoch = int(os.getenv("ACP_KILL_EPOCH", "-1"))
    sync_save = os.getenv("ACP_SYNC_SAVE") == "1"
    steps_per_epoch = 4

    rng = np.random.RandomState(7)
    G = 16
    w_true = rng.randn(6, 1).astype(np.float32)
    data = []
    for _e in range(epochs):
        xs = rng.randn(steps_per_epoch, G, 6).astype(np.float32)
        data.append((xs, xs @ w_true))

    main_p, startup = fluid.Program(), fluid.Program()
    main_p.random_seed = startup.random_seed = 5
    with fluid.program_guard(main_p, startup):
        x = layers.data("x", shape=[-1, 6], append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], append_batch_size=False)
        pred = layers.fc(layers.fc(x, 16, act="relu"), 1,
                         param_attr="acp.w2", bias_attr="acp.b2")
        loss = layers.reduce_mean(layers.square(pred - y))
        fluid.optimizer.SGDOptimizer(0.05).minimize(loss)

    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    losses = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        tr = TrainEpochRange(
            epochs, checkpoint_dir=ws, main_program=main_p,
            async_save=not sync_save, verbose=True)
        for e in tr:
            for t in range(steps_per_epoch):
                if e == kill_epoch and t == 2:
                    os.kill(os.getpid(), signal.SIGKILL)  # preemption
                xs, ys = data[e]
                (lv,) = exe.run(main_p, feed={"x": xs[t], "y": ys[t]},
                                fetch_list=[loss])
                losses.append(float(np.mean(lv)))
        final_w = np.asarray(scope.find_var("acp.w2")).tolist()

    with open(os.environ["ACP_RESULT"], "w") as f:
        json.dump({
            "losses": losses,
            "start_epoch": tr.start_epoch,
            "restored_from": tr.restored_from,
            "final_w": final_w,
            "final_loss": losses[-1],
        }, f)


if __name__ == "__main__":
    main()
