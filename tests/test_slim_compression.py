"""slim compression suite: pruning, distillation, NAS, Compressor.

Capability parity: reference `contrib/slim/tests/test_filter_pruning.py`
(prune conv filters, program still trains), `test_slim_distillation_
strategy.py` (teacher merged, distill losses combine into training
loss), `test_light_nas.py` (controller searches a token space), plus
the prune-then-finetune-recovers and distilled-beats-scratch patterns
from the round-5 plan."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.contrib.slim import distillation, nas, prune
from paddle_tpu.fluid.optimizer import AdamOptimizer, MomentumOptimizer


def _digits(n, seed=0):
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, 10, size=(n,)).astype(np.int64)
    imgs = rs.randn(n, 1, 28, 28).astype(np.float32) * 0.3
    for i, c in enumerate(labels):
        r, col = divmod(int(c), 5)
        imgs[i, 0, 4 + r * 12: 12 + r * 12, 2 + col * 5: 7 + col * 5] += 2.0
    return imgs, labels.reshape(-1, 1)


def _lenet(img, label, prefix="p"):
    conv1 = layers.conv2d(img, num_filters=8, filter_size=5, padding=2,
                          act="relu", param_attr=prefix + "c1.w",
                          bias_attr=prefix + "c1.b")
    pool1 = layers.pool2d(conv1, pool_size=2, pool_stride=2)
    conv2 = layers.conv2d(pool1, num_filters=16, filter_size=5, act="relu",
                          param_attr=prefix + "c2.w",
                          bias_attr=prefix + "c2.b")
    pool2 = layers.pool2d(conv2, pool_size=2, pool_stride=2)
    fc1 = layers.fc(pool2, size=32, act="relu",
                    param_attr=prefix + "f1.w", bias_attr=prefix + "f1.b")
    logits = layers.fc(fc1, size=10,
                       param_attr=prefix + "f2.w", bias_attr=prefix + "f2.b")
    loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
    acc = layers.accuracy(layers.softmax(logits), label)
    return loss, acc, logits


def _train(exe, prog, imgs, labels, loss, acc, epochs, bs=32):
    accs = []
    for _ in range(epochs):
        for i in range(0, len(imgs), bs):
            lv, av = exe.run(prog, feed={"img": imgs[i:i + bs],
                                         "label": labels[i:i + bs]},
                             fetch_list=[loss, acc])
            accs.append(float(np.mean(av)))
    return accs


def test_structure_pruner_matches_numpy_oracle():
    """cf. prune/pruner.py StructurePruner: l1_norm ranking + axis prune."""
    p = prune.StructurePruner({"*": 0}, {"*": "l1_norm"})
    w = np.array([[1.0, 1.0], [0.1, 0.1], [5.0, 5.0], [0.2, 0.2]],
                 np.float32)
    idx = p.cal_pruned_idx("w", w, 0.5, axis=0)
    assert sorted(int(i) for i in idx) == [1, 3]      # two smallest rows
    out = p.prune_tensor(w, idx, 0)
    assert out.shape == (2, 2)
    np.testing.assert_allclose(out, [[1, 1], [5, 5]])
    lazy = p.prune_tensor(w, idx, 0, lazy=True)
    assert lazy.shape == w.shape and lazy[1].sum() == 0 and lazy[3].sum() == 0
    # axis 1 via pruning_axis table
    p2 = prune.StructurePruner({"*": 1}, {"*": "l1_norm"})
    idx2 = p2.cal_pruned_idx("w", np.array([[3.0, 0.1, 2.0]]), 1.0 / 3)
    assert list(idx2) == [1]


def test_prune_then_finetune_recovers_accuracy():
    """The VERDICT 'done' criterion: train LeNet, physically prune 50% of
    conv filters (shapes genuinely shrink), fine-tune, recover accuracy."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[1, 28, 28])
        label = layers.data("label", shape=[1], dtype="int64")
        loss, acc, _ = _lenet(img, label)
        MomentumOptimizer(0.02, 0.9).minimize(loss)

    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    imgs, labels = _digits(256)
    with fluid.scope_guard(scope):
        exe.run(startup)
        base = _train(exe, main, imgs, labels, loss, acc, epochs=3)
        base_acc = np.mean(base[-4:])

        pruned_idx = prune.prune_parameters(
            main, startup, scope, params=["pc1.w", "pc2.w"],
            ratios=[0.5, 0.5])
        # shapes really shrank: conv filters, biases, fc rows, velocity
        assert np.asarray(scope.find_var("pc1.w")).shape == (4, 1, 5, 5)
        assert np.asarray(scope.find_var("pc2.w")).shape == (8, 4, 5, 5)
        assert np.asarray(scope.find_var("pc1.b")).shape == (4,)
        # conv2 (unpadded 5x5 on 14x14 -> 10x10, pool/2 -> 5x5): 8*5*5 rows
        assert np.asarray(scope.find_var("pf1.w")).shape == (8 * 5 * 5, 32)
        assert len(pruned_idx["pc1.w"]) == 4
        vel = [n for n in main.global_block.vars
               if n.startswith("pc1.w_velocity")]
        assert vel and np.asarray(scope.find_var(vel[0])).shape[0] == 4

        # the pruned program still runs and fine-tunes back
        post = _train(exe, main, imgs, labels, loss, acc, epochs=3)
        assert np.mean(post[-4:]) >= base_acc - 0.05, (
            "fine-tune failed to recover: %.3f vs %.3f"
            % (np.mean(post[-4:]), base_acc))

        # startup initializers were rewritten: re-init recreates pruned
        # shapes, so checkpoints of the pruned model round-trip
        exe.run(startup)
        assert np.asarray(scope.find_var("pc1.w")).shape == (4, 1, 5, 5)


def test_lazy_prune_masks_survive_finetuning():
    """lazy=True zeroes channels, keeps shapes, and the appended mask ops
    keep them zero through optimizer updates."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[1, 28, 28])
        label = layers.data("label", shape=[1], dtype="int64")
        loss, acc, _ = _lenet(img, label, prefix="lz")
        AdamOptimizer(1e-3).minimize(loss)

    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    imgs, labels = _digits(128, seed=3)
    with fluid.scope_guard(scope):
        exe.run(startup)
        _train(exe, main, imgs, labels, loss, acc, epochs=1)
        idx = prune.prune_parameters(
            main, startup, scope, params=["lzc1.w"], ratios=[0.5],
            lazy=True)["lzc1.w"]
        w = np.asarray(scope.find_var("lzc1.w"))
        assert w.shape == (8, 1, 5, 5)                 # shape unchanged
        assert np.abs(w[idx]).sum() == 0
        _train(exe, main, imgs, labels, loss, acc, epochs=1)
        w2 = np.asarray(scope.find_var("lzc1.w"))
        assert np.abs(w2[idx]).sum() == 0, "masked channels revived"
        live = [i for i in range(8) if i not in set(int(v) for v in idx)]
        assert np.abs(w2[live]).sum() > 0


def test_prune_rejects_skip_connection_with_guidance():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[4, 8, 8])
        c1 = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                           param_attr="sk1.w", bias_attr=False)
        c2 = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                           param_attr="sk2.w", bias_attr=False)
        out = c1 + c2
        loss = layers.reduce_mean(out)
    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with pytest.raises(ValueError, match="skip connection"):
            prune.prune_parameters(main, startup, scope,
                                   params=["sk1.w"], ratios=[0.5])


def test_sensitivity_ranks_important_params():
    """cf. prune_strategy.py:761: sensitivity = metric drop under lazy
    pruning at each ratio, arrays restored between probes."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[1, 28, 28])
        label = layers.data("label", shape=[1], dtype="int64")
        loss, acc, _ = _lenet(img, label, prefix="sn")
        test_prog = main.clone(for_test=True)
        AdamOptimizer(2e-3).minimize(loss)
    scope = fluid.Scope()
    exe = fluid.Executor()
    imgs, labels = _digits(128, seed=9)
    with fluid.scope_guard(scope):
        exe.run(startup)
        _train(exe, main, imgs, labels, loss, acc, epochs=3)

        def eval_fn():
            _, av = exe.run(test_prog,
                            feed={"img": imgs, "label": labels},
                            fetch_list=[loss, acc])
            return float(np.mean(av))

        before = np.asarray(scope.find_var("snc1.w")).copy()
        sens = prune.sensitivity(main, scope, eval_fn,
                                 ["snc1.w"], ratios=(0.25, 0.75))
        np.testing.assert_allclose(
            np.asarray(scope.find_var("snc1.w")), before)  # restored
        s = sens["snc1.w"]
        assert s[0.75] >= s[0.25] - 1e-6   # heavier prune hurts more


def test_distilled_student_beats_from_scratch():
    """The VERDICT 'done' criterion: merge a trained teacher into the
    student program, train on a soft-label distill loss, and the student
    beats an identical from-scratch run at equal optimizer steps.  The
    scenario where distillation provably adds information: only 32
    labeled examples exist, but the teacher supplies soft targets for
    the full 256-image unlabeled pool (the classic semi-supervised
    distillation setup); both students take 64 Adam steps and are
    evaluated on a held-out set."""
    imgs, labels = _digits(256, seed=1)
    ho_imgs, ho_labels = _digits(256, seed=77)          # held out
    tr_imgs, tr_labels = imgs[:32], labels[:32]         # the labeled few

    # -- teacher: wider net, trained well ------------------------------
    t_main, t_startup = fluid.Program(), fluid.Program()
    t_main.random_seed = t_startup.random_seed = 2
    with fluid.program_guard(t_main, t_startup):
        img = layers.data("img", shape=[1, 28, 28])
        label = layers.data("label", shape=[1], dtype="int64")
        t_loss, t_acc, t_logits = _lenet(img, label, prefix="T")
        t_infer = t_main.clone(for_test=True)
        AdamOptimizer(2e-3).minimize(t_loss)
    t_scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(t_scope):
        exe.run(t_startup)
        _train(exe, t_main, imgs, labels, t_loss, t_acc, epochs=6)

    def build_student(seed, distill):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = seed
        with fluid.program_guard(main, startup):
            img = layers.data("img", shape=[1, 28, 28])
            label = layers.data("label", shape=[1], dtype="int64")
            conv = layers.conv2d(img, num_filters=4, filter_size=5,
                                 padding=2, act="relu")
            pool = layers.pool2d(conv, pool_size=4, pool_stride=4)
            logits = layers.fc(pool, size=10)
            loss = layers.mean(
                layers.softmax_with_cross_entropy(logits, label))
            acc = layers.accuracy(layers.softmax(logits), label)
            eval_prog = main.clone(for_test=True)
        scope = fluid.Scope()
        with fluid.scope_guard(scope):
            if distill:
                rename = distillation.merge(
                    t_infer, main, {"img": "img", "label": "label"},
                    scope=scope, teacher_scope=t_scope)
                with fluid.program_guard(main, startup):
                    total = distillation.SoftLabelDistiller(
                        logits.name, rename[t_logits.name],
                        student_temperature=1.0, teacher_temperature=1.0,
                        distillation_loss_weight=1.0,
                    ).distiller_loss(main, student_loss=None)
                    AdamOptimizer(2e-3).minimize(total)
                exe.run(startup)
                # unlabeled pool, teacher-supplied targets: 8 ep x 8 = 64
                _train(exe, main, imgs, labels, total, acc, epochs=8)
            else:
                with fluid.program_guard(main, startup):
                    AdamOptimizer(2e-3).minimize(loss)
                exe.run(startup)
                # labeled few only: 32 ep x 2 batches of 16 = 64 steps
                _train(exe, main, tr_imgs, tr_labels, loss, acc,
                       epochs=32, bs=16)
            _, av = exe.run(eval_prog,
                            feed={"img": ho_imgs, "label": ho_labels},
                            fetch_list=[loss, acc])
        return float(np.mean(av))

    scratch = build_student(31, distill=False)
    distilled = build_student(31, distill=True)
    assert distilled > scratch, (
        "distilled %.3f <= scratch %.3f" % (distilled, scratch))


def test_l2_and_fsp_distillers_build_and_decrease():
    """L2 on logits + FSP over a conv section: losses build, train, and
    the distill term itself decreases (teacher is being matched)."""
    imgs, labels = _digits(128, seed=4)
    t_main, t_startup = fluid.Program(), fluid.Program()
    t_main.random_seed = t_startup.random_seed = 8
    with fluid.program_guard(t_main, t_startup):
        img = layers.data("img", shape=[1, 28, 28])
        label = layers.data("label", shape=[1], dtype="int64")
        t_loss, t_acc, t_logits = _lenet(img, label, prefix="U")
        t_infer = t_main.clone(for_test=True)
        AdamOptimizer(2e-3).minimize(t_loss)
    t_scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(t_scope):
        exe.run(t_startup)
        _train(exe, t_main, imgs, labels, t_loss, t_acc, epochs=2)

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[1, 28, 28])
        label = layers.data("label", shape=[1], dtype="int64")
        loss, acc, logits = _lenet(img, label, prefix="S")
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        rename = distillation.merge(
            t_infer, main, {"img": "img", "label": "label"},
            scope=scope, teacher_scope=t_scope)
        with fluid.program_guard(main, startup):
            l2_total = distillation.L2Distiller(
                logits.name, rename[t_logits.name],
                distillation_loss_weight=0.5).distiller_loss(
                    main, student_loss=loss)
            AdamOptimizer(2e-3).minimize(l2_total)
        exe.run(startup)
        first = last = None
        for i in range(0, len(imgs), 32):
            lv, = exe.run(main, feed={"img": imgs[i:i + 32],
                                      "label": labels[i:i + 32]},
                          fetch_list=[l2_total])
            first = first if first is not None else float(np.mean(lv))
            last = float(np.mean(lv))
        assert last < first

    # FSP: teacher conv1->conv2 section vs student section (same C pair)
    main2, startup2 = fluid.Program(), fluid.Program()
    main2.random_seed = startup2.random_seed = 10
    with fluid.program_guard(main2, startup2):
        img = layers.data("img", shape=[1, 28, 28])
        label = layers.data("label", shape=[1], dtype="int64")
        c1 = layers.conv2d(img, num_filters=8, filter_size=5, padding=2,
                           act="relu")
        c2 = layers.conv2d(c1, num_filters=16, filter_size=5, padding=2,
                           act="relu")
        pool = layers.pool2d(c2, pool_size=4, pool_stride=4)
        logits2 = layers.fc(pool, size=10)
        loss2 = layers.mean(
            layers.softmax_with_cross_entropy(logits2, label))
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        # teacher section: conv1 output (8ch, 28x28) -> conv2 padded?
        # teacher's conv2 has no padding so spatial differs; use the
        # student's own maps against the teacher conv1 map (same 28x28)
        rename2 = distillation.merge(
            t_infer, main2, {"img": "img", "label": "label"},
            scope=scope2, teacher_scope=t_scope)
        t_c1 = rename2[t_infer.global_block.ops[0].outputs["Output"][0]]
        with fluid.program_guard(main2, startup2):
            fsp_total = distillation.FSPDistiller(
                [(c1.name, c2.name)], [(t_c1, c2.name)],
            ).distiller_loss(main2, student_loss=loss2)
            AdamOptimizer(1e-3).minimize(fsp_total)
        exe.run(startup2)
        lv, = exe.run(main2, feed={"img": imgs[:32], "label": labels[:32]},
                      fetch_list=[fsp_total])
        assert np.isfinite(np.mean(lv))


def test_sa_controller_and_sanas_find_optimum():
    """cf. searcher/controller.py + test_light_nas.py pattern: SA search
    over a small token space converges to (or near) the known optimum."""
    rng = np.random.RandomState(0)
    target = [3, 1, 4, 1, 5]
    rt = [6] * 5

    class Space(nas.SearchSpace):
        def init_tokens(self):
            return [0, 0, 0, 0, 0]

        def range_table(self):
            return rt

        def create_net(self, tokens):
            return tokens

    def reward(net, tokens):
        return -float(np.sum((np.array(tokens) - np.array(target)) ** 2))

    sanas = nas.SANAS(Space(), reward, search_steps=300, seed=0)
    best, best_r = sanas.search()
    assert best_r >= -2.0, (best, best_r)
    assert len(sanas.history) == 300

    # constraint hook: tokens with sum > 10 never proposed
    ctl = nas.SAController(seed=1)
    ctl.reset(rt, [0, 0, 0, 0, 0],
              constrain_func=lambda t: sum(t) <= 10)
    for _ in range(50):
        t = ctl.next_tokens()
        assert sum(t) <= 10
        ctl.update(t, -abs(sum(t) - 8))

    # fixed (range-1) slots never mutate and never crash the sampler
    ctl2 = nas.SAController(seed=2)
    ctl2.reset([6, 1, 6], [2, 0, 3])
    for _ in range(20):
        t = ctl2.next_tokens()
        assert t[1] == 0
    # an unsatisfiable constraint falls back to the valid current tokens
    ctl3 = nas.SAController(seed=3, max_try_number=5)
    ctl3.reset([6, 6], [1, 1], constrain_func=lambda t: t == [1, 1])
    assert ctl3.next_tokens() == [1, 1]


def test_compressor_runs_strategies_in_order():
    from paddle_tpu.fluid.contrib.slim.core import Compressor, Strategy

    calls = []

    class S(Strategy):
        def __init__(self, tag, start_epoch=0):
            super().__init__(start_epoch=start_epoch)
            self.tag = tag

        def on_compression_begin(self, context):
            calls.append(("begin", self.tag))

        def on_epoch_begin(self, context):
            calls.append(("eb", self.tag, context.epoch))

        def on_epoch_end(self, context):
            calls.append(("ee", self.tag, context.epoch))

        def on_compression_end(self, context):
            calls.append(("end", self.tag))

    def train_epoch(ctx):
        calls.append(("train", ctx.epoch))

    c = Compressor(scope=None, train_program=None,
                   train_epoch_fn=train_epoch, epochs=2)
    c.add_strategy(S("a"), S("b", start_epoch=1))
    c.run()
    assert calls == [
        ("begin", "a"), ("begin", "b"),
        ("eb", "a", 0), ("train", 0), ("ee", "a", 0),
        ("eb", "a", 1), ("eb", "b", 1), ("train", 1),
        ("ee", "a", 1), ("ee", "b", 1),
        ("end", "a"), ("end", "b"),
    ]

    # a bounded [start, end) strategy stops firing at end_epoch
    calls.clear()

    class R(S):
        def __init__(self):
            super().__init__("r")
            self.start_epoch, self.end_epoch = 1, 2

    c2 = Compressor(scope=None, train_program=None,
                    train_epoch_fn=lambda ctx: None, epochs=4)
    c2.add_strategy(R())
    c2.run()
    epochs_fired = [e for tag, _, e in
                    (x for x in calls if x[0] == "eb")]
    assert epochs_fired == [1], calls


def test_uniform_prune_strategy_in_compressor():
    """cf. prune_strategy.py UniformPruneStrategy: the strategy searches
    ONE ratio hitting the target parameter reduction and prunes at its
    start epoch inside the Compressor loop; training continues after."""
    from paddle_tpu.fluid.contrib.slim.core import Compressor
    from paddle_tpu.fluid.contrib.slim.prune import (
        UniformPruneStrategy,
        estimate_pruned_fraction,
    )

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 21
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[1, 28, 28])
        label = layers.data("label", shape=[1], dtype="int64")
        loss, acc, _ = _lenet(img, label, prefix="up")
        MomentumOptimizer(0.02, 0.9).minimize(loss)

    scope = fluid.Scope()
    exe = fluid.Executor()
    imgs, labels = _digits(192, seed=2)
    accs = []

    def train_epoch(ctx):
        accs.extend(_train(exe, ctx.train_program, imgs, labels, loss,
                           acc, epochs=1))

    with fluid.scope_guard(scope):
        exe.run(startup)
        strat = UniformPruneStrategy(
            start_epoch=1, target_ratio=0.3,
            pruned_params=["upc1.w", "upc2.w"])
        Compressor(scope, main, startup_program=startup,
                   train_epoch_fn=train_epoch,
                   epochs=4).add_strategy(strat).run()
        # strategy ran once, with a searched uniform ratio
        assert strat.ratios is not None
        assert strat.ratios[0] == strat.ratios[1] > 0
        # shapes really shrank and training recovered
        assert np.asarray(scope.find_var("upc1.w")).shape[0] < 8
        assert np.mean(accs[-4:]) > 0.9
        # dry-run estimator matches the direction of the target
        frac = estimate_pruned_fraction(
            main, scope, ["upc1.w"], [0.5])
        assert 0 < frac < 1


def test_sensitivity_ratio_allocation():
    """cf. SensitivePruneStrategy._get_best_ratios: a high-sensitivity
    param gets a LOWER ratio than an insensitive one at the same
    target."""
    from paddle_tpu.fluid.contrib.slim.prune import (
        get_ratios_by_sensitivity,
    )

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 22
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[1, 28, 28])
        label = layers.data("label", shape=[1], dtype="int64")
        loss, acc, _ = _lenet(img, label, prefix="sr")
    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        sens = {
            "src1.w": {0.2: 0.30, 0.4: 0.60, 0.6: 0.90},  # fragile
            "src2.w": {0.2: 0.01, 0.4: 0.02, 0.6: 0.04},  # robust
        }
        ratios = get_ratios_by_sensitivity(sens, 0.25, main, scope)
    assert ratios["src2.w"] > ratios["src1.w"]

def test_compressor_kill_and_resume_same_final_metric(tmp_path):
    """cf. reference compressor.py:238 checkpoint flow: a compression
    run killed mid-way resumes from the last per-epoch checkpoint (via
    incubate.checkpoint) and lands on the SAME final metric/weights as
    an uninterrupted run — including a prune that already rewrote the
    program before the kill."""
    from paddle_tpu.fluid.contrib.slim.core import Compressor
    from paddle_tpu.fluid.contrib.slim.prune import UniformPruneStrategy

    imgs, labels = _digits(192, seed=4)

    def build():
        # unique_name.guard: every (re)build names vars identically, as
        # a fresh process would — resume matches the checkpointed names
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 31
        with fluid.unique_name.guard():
            with fluid.program_guard(main, startup):
                img = layers.data("img", shape=[1, 28, 28])
                label = layers.data("label", shape=[1], dtype="int64")
                loss, acc, _ = _lenet(img, label, prefix="kr")
                MomentumOptimizer(0.02, 0.9).minimize(loss)
        return main, startup, loss, acc

    def run(ckpt_path, die_at_epoch=None):
        main, startup, loss, acc = build()
        scope = fluid.Scope()
        exe = fluid.Executor()
        accs = []

        def train_epoch(ctx):
            if die_at_epoch is not None and ctx.epoch == die_at_epoch:
                raise KeyboardInterrupt("simulated preemption")
            accs.append(np.mean(_train(exe, ctx.train_program, imgs,
                                       labels, loss, acc, epochs=1)))

        with fluid.scope_guard(scope):
            exe.run(startup)
            strat = UniformPruneStrategy(
                start_epoch=1, target_ratio=0.3,
                pruned_params=["krc1.w", "krc2.w"])
            c = Compressor(scope, main, startup_program=startup,
                           train_epoch_fn=train_epoch, epochs=4,
                           checkpoint_path=ckpt_path)
            c.add_strategy(strat)
            c.run()
            w = np.asarray(scope.find_var("krc1.w")).copy()
        return accs, w, strat

    control_accs, control_w, _ = run(str(tmp_path / "control"))

    ckpt = str(tmp_path / "faulted")
    with pytest.raises(KeyboardInterrupt):
        run(ckpt, die_at_epoch=2)          # epochs 0,1 checkpointed
    # fresh process state, same pipeline: resumes at epoch 2 (the prune
    # from epoch 1 comes back via the checkpointed program + state)
    resumed_accs, resumed_w, strat2 = run(ckpt)
    assert len(resumed_accs) == 2          # only epochs 2,3 re-ran
    assert strat2.ratios is not None       # strategy state restored
    assert resumed_w.shape == control_w.shape
    np.testing.assert_allclose(resumed_w, control_w, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(resumed_accs[-1], control_accs[-1],
                               rtol=1e-5)

def test_compressor_refuses_wrong_program_checkpoint(tmp_path):
    """Resuming a checkpoint dir written by a DIFFERENT model must fail
    loudly (program-hash guard), never silently train the wrong
    program."""
    from paddle_tpu.fluid.contrib.slim.core import Compressor
    from paddle_tpu.incubate.checkpoint import CheckpointLoadError

    def build(width):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 41
        with fluid.unique_name.guard():
            with fluid.program_guard(main, startup):
                x = layers.data("x", shape=[-1, 4],
                                append_batch_size=False)
                loss = layers.reduce_mean(
                    layers.square(layers.fc(x, width)))
        return main, startup

    ckpt = str(tmp_path / "c")
    main_a, startup_a = build(3)
    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup_a)
        Compressor(scope, main_a, startup_program=startup_a,
                   train_epoch_fn=lambda ctx: None, epochs=1,
                   checkpoint_path=ckpt).run()

    main_b, startup_b = build(5)           # different model, same dir
    scope_b = fluid.Scope()
    with fluid.scope_guard(scope_b):
        exe.run(startup_b)
        with pytest.raises(CheckpointLoadError):
            Compressor(scope_b, main_b, startup_program=startup_b,
                       train_epoch_fn=lambda ctx: None, epochs=1,
                       checkpoint_path=ckpt).run()


def test_compressor_yaml_config_builds_strategies(tmp_path):
    """cf. reference slim Compressor.config(config_path): strategies
    (and compressor knobs) come from a yaml file — class by name from
    the built-in registry, remaining keys as constructor kwargs."""
    from paddle_tpu.fluid.contrib.slim.core import Compressor
    from paddle_tpu.fluid.contrib.slim.prune import UniformPruneStrategy
    from paddle_tpu.fluid.contrib.slim.quantization import (
        QuantizationStrategy,
    )

    cfg = tmp_path / "compress.yaml"
    cfg.write_text(
        "version: 1.0\n"
        "strategies:\n"
        "  qat:\n"
        "    class: QuantizationStrategy\n"
        "    start_epoch: 1\n"
        "    moving_rate: 0.8\n"
        "  prune:\n"
        "    class: UniformPruneStrategy\n"
        "    start_epoch: 2\n"
        "    target_ratio: 0.3\n"
        "compressor:\n"
        "  epoch: 5\n"
        "  checkpoint_path: %s\n" % (tmp_path / "ckpt"))
    c = Compressor(scope=None, train_program=None,
                   train_epoch_fn=lambda ctx: None).config(str(cfg))
    assert c._epochs == 5
    assert c._checkpoint_path == str(tmp_path / "ckpt")
    assert [type(s) for s in c.strategies] == [QuantizationStrategy,
                                               UniformPruneStrategy]
    assert c.strategies[0].start_epoch == 1
    assert c.strategies[0].moving_rate == 0.8
    assert c.strategies[1].target_ratio == 0.3

    bad = tmp_path / "bad.yaml"
    bad.write_text("strategies:\n  x:\n    class: NoSuchStrategy\n")
    with pytest.raises(ValueError, match="NoSuchStrategy"):
        Compressor(scope=None, train_program=None).config(str(bad))


def test_qat_strategy_resumes_through_checkpoint(tmp_path):
    """QAT-as-strategy (yaml-configured), killed after the rewrite
    epoch, resumes from the Compressor's per-epoch checkpoint: the
    rewritten program + scale states come back, the rewrite does NOT
    re-apply, and the frozen int8 model matches the uninterrupted
    control run."""
    from paddle_tpu.fluid.contrib.slim.core import Compressor

    imgs, labels = _digits(192, seed=6)

    def build():
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 51
        with fluid.unique_name.guard():
            with fluid.program_guard(main, startup):
                img = layers.data("img", shape=[1, 28, 28])
                label = layers.data("label", shape=[1], dtype="int64")
                loss, acc, _ = _lenet(img, label, prefix="qs")
                MomentumOptimizer(0.02, 0.9).minimize(loss)
        return main, startup, loss, acc

    def run(ckpt_path, die_at_epoch=None):
        main, startup, loss, acc = build()
        scope = fluid.Scope()
        exe = fluid.Executor()
        accs = []

        def train_epoch(ctx):
            if die_at_epoch is not None and ctx.epoch == die_at_epoch:
                raise KeyboardInterrupt("simulated preemption")
            accs.append(np.mean(_train(exe, ctx.train_program, imgs,
                                       labels, loss, acc, epochs=1)))

        cfg = tmp_path / "qat.yaml"
        cfg.write_text(
            "strategies:\n"
            "  qat:\n"
            "    class: QuantizationStrategy\n"
            "    start_epoch: 1\n"
            "compressor:\n"
            "  epoch: 3\n")
        with fluid.scope_guard(scope):
            exe.run(startup)
            c = Compressor(scope, main, startup_program=startup,
                           train_epoch_fn=train_epoch,
                           checkpoint_path=ckpt_path).config(str(cfg))
            c.run()
            ctx = c.context
            int8 = np.asarray(ctx.scope.find_var("qsc1.w@INT8"))
        return accs, int8, c.strategies[0], ctx

    control_accs, control_int8, _s, _ctx = run(str(tmp_path / "control"))
    assert control_int8.dtype == np.int8

    ckpt = str(tmp_path / "faulted")
    with pytest.raises(KeyboardInterrupt):
        run(ckpt, die_at_epoch=2)          # epochs 0,1 checkpointed
    resumed_accs, resumed_int8, strat, ctx = run(ckpt)
    assert len(resumed_accs) == 1          # only epoch 2 re-ran
    assert strat.applied and strat.frozen  # restored mid-schedule state
    # the rewrite survived the checkpoint (not re-applied): exactly one
    # fake-quant op per quantized weight in the resumed program
    ops = [op.type for op in ctx.train_program.global_block.ops]
    assert ops.count("dequantize_linear") >= 1
    np.testing.assert_array_equal(resumed_int8, control_int8)
    np.testing.assert_allclose(resumed_accs[-1], control_accs[-1],
                               rtol=1e-5)
