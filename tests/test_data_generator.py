"""incubate.data_generator (reference MultiSlotDataGenerator parity,
VERDICT #4): raw log lines -> MultiSlot line protocol -> round trip
through the native Dataset channel engine."""

import io

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.dataset import DatasetFactory, pad_batch
from paddle_tpu.incubate.data_generator import (
    DataGenerator,
    MultiSlotDataGenerator,
)


class CtrGen(MultiSlotDataGenerator):
    """Raw line: "<click> <id> <id> ..." -> two slots (ids, label)."""

    def generate_sample(self, line):
        def gen():
            parts = line.split()
            if len(parts) < 2:
                return                      # malformed line dropped
            yield [("ids", [int(p) for p in parts[1:]]),
                   ("label", float(parts[0]))]
        return gen()


def _raw_lines():
    return ["1 4 7 9\n", "0 2\n", "bad\n", "1 11 3\n"]


def test_protocol_lines():
    gen = CtrGen()
    lines = list(gen.process(_raw_lines()))
    assert lines == ["3 4 7 9 1 1.0\n", "1 2 1 0.0\n", "2 11 3 1 1.0\n"]


def test_run_from_stdin_is_the_pipe_command_shape():
    gen = CtrGen()
    out = io.StringIO()
    gen.run_from_stdin(stdin=iter(_raw_lines()), stdout=out)
    assert out.getvalue().count("\n") == 3


def test_generate_batch_hook_sees_batches():
    """set_batch scopes the cross-sample hook (negative sampling et
    al.): generate_batch receives groups of batch_size samples."""
    sizes = []

    class BatchGen(CtrGen):
        def generate_batch(self, samples):
            sizes.append(len(samples))
            for s in samples:
                yield s

    g = BatchGen()
    g.set_batch(2)
    assert len(list(g.process(_raw_lines()))) == 3
    assert sizes == [2, 1]                   # 3 samples in groups of 2


def test_empty_slot_rejected():
    class BadGen(MultiSlotDataGenerator):
        def generate_sample(self, line):
            def gen():
                yield [("ids", [])]
            return gen()

    with pytest.raises(ValueError, match="zero values"):
        list(BadGen().process(["x\n"]))


def test_round_trip_through_native_dataset_engine(tmp_path):
    """Authoring -> protocol files -> native channel engine -> parsed
    batches: ids and labels survive bit-exact, ragged lengths intact."""
    raw = str(tmp_path / "raw.log")
    rng = np.random.RandomState(4)
    want = []
    with open(raw, "w") as fh:
        for _ in range(20):
            n = rng.randint(1, 5)
            ids = rng.randint(0, 100, n)
            click = int(rng.rand() < 0.5)
            fh.write("%d %s\n" % (click, " ".join(map(str, ids))))
            want.append((list(ids), float(click)))

    files = CtrGen().run_from_files([raw], str(tmp_path / "slots"))
    assert files and files[0].endswith(".slot")

    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        ids_v = fluid.data("ids", [-1, 1], "int64")
        lab_v = fluid.data("label", [-1, 1], "float32")
    ds = DatasetFactory().create_dataset("QueueDataset")
    ds.set_filelist(files)
    ds.set_batch_size(6)
    ds.set_thread(1)
    ds.set_use_var([ids_v, lab_v])

    got = []
    for batch in ds:
        vals, lod = batch["ids"]
        labels = batch["label"][0]
        dense, mask = pad_batch(vals, lod)
        for r in range(dense.shape[0]):
            got.append((list(dense[r][mask[r] > 0]), float(labels[r])))
    assert sorted(got) == sorted(want)
