"""Round-4 op-tail oracles (reference tests/unittests/test_*_op.py
patterns): numpy value checks + finite-difference grads for the
differentiable ops."""

import numpy as np
import pytest

from op_test import check_grad, check_output, run_single_op


def _rand(*shape, seed=0):
    return np.random.RandomState(seed).randn(*shape).astype(np.float32)


# --- math / tensor ---------------------------------------------------------


def test_tril_triu():
    x = _rand(4, 5)
    check_output("tril_triu", {"X": x}, {"lower": True, "diagonal": 1},
                 {"Out": np.tril(x, 1)})
    check_output("tril_triu", {"X": x}, {"lower": False, "diagonal": -1},
                 {"Out": np.triu(x, -1)})
    check_grad("tril_triu", {"X": x}, {"lower": True}, ["Out"], ["X"],
               rtol=1e-2, atol=1e-3)


def test_multiplex():
    xs = [_rand(4, 3, seed=i) for i in range(3)]
    ids = np.array([[2], [0], [1], [0]], np.int32)
    ref = np.stack([xs[ids[i, 0]][i] for i in range(4)])
    check_output("multiplex", {"X": xs, "Ids": ids}, {}, {"Out": ref})


def test_minus_and_reverse():
    x, y = _rand(3, 4), _rand(3, 4, seed=1)
    check_output("minus", {"X": x, "Y": y}, {}, {"Out": x - y})
    check_output("reverse", {"X": x}, {"axis": [1]},
                 {"Out": x[:, ::-1]})
    check_grad("reverse", {"X": x}, {"axis": [0, 1]}, ["Out"], ["X"],
               rtol=1e-2, atol=1e-3)


def test_eye_diag_fill():
    outs, _ = run_single_op("eye", {}, {"num_rows": 3, "num_columns": 4},
                            ["Out"])
    np.testing.assert_allclose(outs["Out"], np.eye(3, 4))
    d = _rand(5)
    outs, _ = run_single_op("diag", {"Diagonal": d}, {}, ["Out"])
    np.testing.assert_allclose(outs["Out"], np.diag(d), rtol=1e-6)
    outs, _ = run_single_op(
        "fill", {}, {"shape": [2, 3], "value": [1, 2, 3, 4, 5, 6],
                     "dtype": "float32"}, ["Out"])
    np.testing.assert_allclose(outs["Out"],
                               np.arange(1, 7).reshape(2, 3))


def test_fill_zeros_like2_and_range():
    x = _rand(2, 3)
    outs, _ = run_single_op("fill_zeros_like2", {"X": x},
                            {"dtype": "float32"}, ["Out"])
    assert (outs["Out"] == 0).all() and outs["Out"].shape == (2, 3)
    outs, _ = run_single_op("range", {}, {"start": 1, "end": 8, "step": 2},
                            ["Out"])
    np.testing.assert_allclose(outs["Out"], np.arange(1, 8, 2))


def test_unique_and_counts():
    x = np.array([3, 1, 3, 2, 1, 7], np.int64)
    outs, _ = run_single_op("unique", {"X": x}, {}, ["Out", "Index"])
    uniq = np.unique(x)
    np.testing.assert_allclose(outs["Out"][: len(uniq)], uniq)
    np.testing.assert_allclose(uniq[outs["Index"]], x)
    outs, _ = run_single_op("unique_with_counts", {"X": x}, {},
                            ["Out", "Index", "Count"])
    np.testing.assert_allclose(outs["Count"][: len(uniq)],
                               [2, 1, 2, 1])


def test_where_index_and_is_empty():
    c = np.array([[True, False], [False, True]])
    outs, _ = run_single_op("where_index", {"Condition": c}, {}, ["Out"])
    got = outs["Out"]
    np.testing.assert_allclose(got[:2], [[0, 0], [1, 1]])
    assert (got[2:] == -1).all()
    outs, _ = run_single_op("is_empty", {"X": np.zeros((2, 2))}, {},
                            ["Out"])
    assert not bool(outs["Out"])


def test_gaussian_random_batch_size_like_shape():
    outs, _ = run_single_op(
        "gaussian_random_batch_size_like", {"Input": _rand(6, 3)},
        {"shape": [99, 7], "input_dim_idx": 0, "output_dim_idx": 0,
         "mean": 10.0, "std": 0.1}, ["Out"])
    assert outs["Out"].shape == (6, 7)
    assert 9 < outs["Out"].mean() < 11


def test_bilinear_tensor_product():
    x, y = _rand(3, 4), _rand(3, 5, seed=1)
    w = _rand(2, 4, 5, seed=2)
    b = _rand(1, 2, seed=3)
    ref = np.einsum("bm,omn,bn->bo", x, w, y) + b
    check_output("bilinear_tensor_product",
                 {"X": x, "Y": y, "Weight": w, "Bias": b}, {},
                 {"Out": ref}, rtol=1e-5, atol=1e-5)
    check_grad("bilinear_tensor_product",
               {"X": x, "Y": y, "Weight": w, "Bias": b}, {}, ["Out"],
               ["X", "Weight"], rtol=1e-2, atol=1e-2)


def test_cross_entropy2():
    p = np.abs(_rand(4, 5)) + 0.1
    p = (p / p.sum(1, keepdims=True)).astype(np.float32)
    lab = np.array([[1], [0], [4], [2]], np.int64)
    ref = -np.log(p[np.arange(4), lab[:, 0]])[:, None]
    check_output("cross_entropy2", {"X": p, "Label": lab}, {},
                 {"Y": ref}, rtol=1e-5, atol=1e-6)


def test_conv_shift():
    x, y = _rand(2, 6), _rand(2, 3, seed=1)
    M, N = 6, 3
    ref = np.zeros((2, M), np.float32)
    for b in range(2):
        for i in range(M):
            for j in range(N):
                ref[b, i] += x[b, (i + j - N // 2) % M] * y[b, j]
    check_output("conv_shift", {"X": x, "Y": y}, {}, {"Out": ref},
                 rtol=1e-5, atol=1e-5)
    check_grad("conv_shift", {"X": x, "Y": y}, {}, ["Out"], ["X", "Y"],
               rtol=1e-2, atol=1e-3)


def test_bpr_loss():
    x = _rand(3, 4)
    lab = np.array([[0], [2], [3]], np.int64)
    ref = np.zeros((3, 1), np.float32)
    for b in range(3):
        pos = x[b, lab[b, 0]]
        o = [np.log(1 + np.exp(-(pos - x[b, j])))
             for j in range(4) if j != lab[b, 0]]
        ref[b, 0] = np.mean(o)
    check_output("bpr_loss", {"X": x, "Label": lab}, {}, {"Out": ref},
                 rtol=1e-5, atol=1e-5)
    check_grad("bpr_loss", {"X": x, "Label": lab}, {}, ["Out"], ["X"],
               rtol=1e-2, atol=1e-3)


def test_cvm():
    x = np.abs(_rand(3, 6)) + 0.5
    outs, _ = run_single_op("cvm", {"X": x, "CVM": x[:, :2]},
                            {"use_cvm": True}, ["Y"])
    np.testing.assert_allclose(outs["Y"][:, 0], np.log(x[:, 0] + 1),
                               rtol=1e-5)
    np.testing.assert_allclose(
        outs["Y"][:, 1], np.log(x[:, 1] + 1) - np.log(x[:, 0] + 1),
        rtol=1e-4, atol=1e-5)
    outs, _ = run_single_op("cvm", {"X": x, "CVM": x[:, :2]},
                            {"use_cvm": False}, ["Y"])
    np.testing.assert_allclose(outs["Y"], x[:, 2:], rtol=1e-6)


def test_hash_deterministic_in_range():
    x = np.array([[1, 2], [3, 4], [1, 2]], np.int64)
    outs, _ = run_single_op("hash", {"X": x},
                            {"num_hash": 2, "mod_by": 1000}, ["Out"])
    got = outs["Out"]
    assert got.shape == (3, 2, 1)
    assert (got >= 0).all() and (got < 1000).all()
    np.testing.assert_array_equal(got[0], got[2])  # same input, same hash
    assert (got[0] != got[1]).any()


def test_average_accumulates_window():
    p = _rand(3)
    z = np.zeros(3, np.float32)
    zi = np.zeros((1,), np.int64)
    ins = {"param": p, "in_sum_1": z, "in_sum_2": z, "in_sum_3": z,
           "in_num_accumulates": zi, "in_old_num_accumulates": zi,
           "in_num_updates": zi}
    outs, _ = run_single_op(
        "average_accumulates", ins,
        {"average_window": 1.0, "min_average_window": 1,
         "max_average_window": 100},
        ["out_sum_1", "out_sum_3", "out_num_accumulates",
         "out_old_num_accumulates"])
    # window closes on the first update: sum_3 = param, accumulators reset
    np.testing.assert_allclose(outs["out_sum_3"], p, rtol=1e-6)
    assert int(outs["out_num_accumulates"][0]) == 0
    assert int(outs["out_old_num_accumulates"][0]) == 1


def test_proximal_updates():
    p, g, m = _rand(4), _rand(4, seed=1), np.abs(_rand(4, seed=2)) + 0.1
    lr = np.array([0.1], np.float32)
    outs, _ = run_single_op(
        "proximal_gd", {"Param": p, "Grad": g, "LearningRate": lr},
        {"l1": 0.01, "l2": 0.02}, ["ParamOut"])
    prox = p - 0.1 * g
    ref = np.sign(prox) * np.maximum(np.abs(prox) - 0.1 * 0.01, 0) \
        / (1 + 0.1 * 0.02)
    np.testing.assert_allclose(outs["ParamOut"], ref, rtol=1e-5, atol=1e-6)
    outs, _ = run_single_op(
        "proximal_adagrad",
        {"Param": p, "Moment": m, "Grad": g, "LearningRate": lr},
        {"l1": 0.01, "l2": 0.02}, ["ParamOut", "MomentOut"])
    m2 = m + g * g
    lr_adj = 0.1 / np.sqrt(m2)
    prox = p - lr_adj * g
    ref = np.sign(prox) * np.maximum(np.abs(prox) - lr_adj * 0.01, 0) \
        / (1 + lr_adj * 0.02)
    np.testing.assert_allclose(outs["MomentOut"], m2, rtol=1e-5)
    np.testing.assert_allclose(outs["ParamOut"], ref, rtol=1e-4, atol=1e-5)


def test_selected_rows_helpers_and_misc():
    v = _rand(4, 3)
    ids = np.array([5, 2, 5, 9], np.int64)
    outs, _ = run_single_op("merge_selected_rows",
                            {"X": v, "RowIds": ids}, {}, ["Out"])
    ref = v.copy()
    ref[0] = v[0] + v[2]
    ref[2] = 0
    np.testing.assert_allclose(outs["Out"], ref, rtol=1e-6)
    outs, _ = run_single_op("get_tensor_from_selected_rows", {"X": v}, {},
                            ["Out"])
    np.testing.assert_allclose(outs["Out"], v)
    outs, _ = run_single_op("fake_init", {}, {"shape": [2, 2]}, ["Out"])
    assert (outs["Out"] == 0).all()
    outs, _ = run_single_op("seed", {}, {"seed": 42}, ["Out"])
    assert int(outs["Out"][0]) == 42
    outs, _ = run_single_op("broadcast", {"X": v}, {}, ["Out"])
    np.testing.assert_allclose(outs["Out"], v)


# --- nn tail ---------------------------------------------------------------


def test_conv3d_transpose():
    import torch
    import torch.nn.functional as F

    x = _rand(1, 2, 3, 4, 4)
    w = _rand(2, 3, 2, 2, 2, seed=1)
    ref = F.conv_transpose3d(torch.from_numpy(x), torch.from_numpy(w),
                             stride=2, padding=1).numpy()
    check_output("conv3d_transpose", {"Input": x, "Filter": w},
                 {"strides": [2, 2, 2], "paddings": [1, 1, 1]},
                 {"Output": ref}, rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_max_pool2d_with_index_and_unpool():
    x = _rand(2, 3, 4, 4)
    outs, _ = run_single_op(
        "max_pool2d_with_index", {"X": x},
        {"ksize": [2, 2], "strides": [2, 2]}, ["Out", "Mask"])
    ref = x.reshape(2, 3, 2, 2, 2, 2).max(axis=(3, 5))
    np.testing.assert_allclose(outs["Out"], ref, rtol=1e-6)
    # mask points at the argmax (flat in-plane index)
    flat = x.reshape(2, 3, 16)
    np.testing.assert_allclose(
        np.take_along_axis(flat, outs["Mask"].reshape(2, 3, 4), 2),
        ref.reshape(2, 3, 4), rtol=1e-6)
    # unpool round-trip: scatter pooled values back
    outs2, _ = run_single_op(
        "unpool", {"X": outs["Out"], "Indices": outs["Mask"]},
        {"unpooled_shape": [4, 4]}, ["Out"])
    up = outs2["Out"]
    np.testing.assert_allclose(up.reshape(2, 3, 16).sum(-1),
                               ref.reshape(2, 3, 4).sum(-1), rtol=1e-5)
    check_grad("max_pool2d_with_index", {"X": x},
               {"ksize": [2, 2], "strides": [2, 2]}, ["Out"], ["X"],
               rtol=1e-2, atol=1e-3)


def test_unpool_overlapping_windows_writes_not_sums():
    """ADVICE r4: stride < ksize lets two pooled cells record the SAME
    max index; the scatter must overwrite (reference single write), not
    sum the duplicates."""
    # one dominant peak: every overlapping window picks index 5 (=[1,1])
    x = np.zeros((1, 1, 3, 3), np.float32)
    x[0, 0, 1, 1] = 7.0
    outs, _ = run_single_op(
        "max_pool2d_with_index", {"X": x},
        {"ksize": [2, 2], "strides": [1, 1]}, ["Out", "Mask"])
    assert (outs["Mask"] == 4).all()          # all 4 windows hit (1,1)
    outs2, _ = run_single_op(
        "unpool", {"X": outs["Out"], "Indices": outs["Mask"]},
        {"unpooled_shape": [3, 3]}, ["Out"])
    up = outs2["Out"][0, 0]
    assert up[1, 1] == 7.0                    # written once, not 28.0
    assert up.sum() == 7.0


def test_max_pool3d_with_index():
    x = _rand(1, 2, 4, 4, 4)
    outs, _ = run_single_op(
        "max_pool3d_with_index", {"X": x},
        {"ksize": [2, 2, 2], "strides": [2, 2, 2]}, ["Out", "Mask"])
    ref = x.reshape(1, 2, 2, 2, 2, 2, 2, 2).max(axis=(3, 5, 7))
    np.testing.assert_allclose(outs["Out"], ref, rtol=1e-6)
    flat = x.reshape(1, 2, 64)
    np.testing.assert_allclose(
        np.take_along_axis(flat, outs["Mask"].reshape(1, 2, 8), 2),
        ref.reshape(1, 2, 8), rtol=1e-6)


@pytest.mark.slow
def test_crop_and_space_to_depth():
    x = _rand(2, 3, 6, 6)
    outs, _ = run_single_op(
        "crop", {"X": x}, {"shape": [2, 2, 3, 3],
                           "offsets": [0, 1, 2, 1]}, ["Out"])
    np.testing.assert_allclose(outs["Out"], x[:2, 1:3, 2:5, 1:4])
    check_grad("crop", {"X": x},
               {"shape": [1, 2, 3, 3], "offsets": [0, 0, 1, 1]},
               ["Out"], ["X"], rtol=1e-2, atol=1e-3)
    bs = 2
    outs, _ = run_single_op("space_to_depth", {"X": x},
                            {"blocksize": bs}, ["Out"])
    ref = x.reshape(2, 3, 3, 2, 3, 2).transpose(0, 3, 5, 1, 2, 4) \
        .reshape(2, 12, 3, 3)
    np.testing.assert_allclose(outs["Out"], ref)
    check_grad("space_to_depth", {"X": x}, {"blocksize": 2}, ["Out"],
               ["X"], rtol=1e-2, atol=1e-3)


def test_deformable_conv_zero_offset_matches_conv2d():
    """With zero offsets and unit mask, deformable conv == plain conv."""
    x = _rand(1, 2, 5, 5)
    w = _rand(3, 2, 3, 3, seed=1)
    Ho = Wo = 5
    off = np.zeros((1, 2 * 9, Ho, Wo), np.float32)
    msk = np.ones((1, 9, Ho, Wo), np.float32)
    ref, _ = run_single_op("conv2d", {"Input": x, "Filter": w},
                           {"strides": [1, 1], "paddings": [1, 1]},
                           ["Output"])
    got, _ = run_single_op(
        "deformable_conv", {"Input": x, "Offset": off, "Mask": msk,
                            "Filter": w},
        {"strides": [1, 1], "paddings": [1, 1], "deformable_groups": 1},
        ["Output"])
    np.testing.assert_allclose(got["Output"], ref["Output"], rtol=1e-4,
                               atol=1e-4)
    got1, _ = run_single_op(
        "deformable_conv_v1", {"Input": x, "Offset": off, "Filter": w},
        {"strides": [1, 1], "paddings": [1, 1], "deformable_groups": 1},
        ["Output"])
    np.testing.assert_allclose(got1["Output"], ref["Output"], rtol=1e-4,
                               atol=1e-4)


@pytest.mark.slow
def test_deformable_conv_offset_shifts():
    """An integer offset of (0, 1) everywhere equals convolving the
    x-shifted image (interior pixels)."""
    x = _rand(1, 1, 6, 6)
    w = _rand(1, 1, 1, 1, seed=1)
    off = np.zeros((1, 2, 6, 6), np.float32)
    off[:, 1] = 1.0  # shift x by +1
    got, _ = run_single_op(
        "deformable_conv_v1", {"Input": x, "Offset": off, "Filter": w},
        {"strides": [1, 1], "paddings": [0, 0]}, ["Output"])
    ref = x[:, :, :, 1:] * w[0, 0, 0, 0]
    np.testing.assert_allclose(got["Output"][:, :, :, :-1], ref,
                               rtol=1e-4, atol=1e-5)
    check_grad(
        "deformable_conv_v1",
        {"Input": x, "Offset": off, "Filter": w},
        {"strides": [1, 1], "paddings": [0, 0]}, ["Output"],
        ["Input", "Filter"], rtol=1e-2, atol=1e-2)


def test_nce_structure():
    x = _rand(4, 8)
    w = _rand(20, 8, seed=1)
    b = _rand(20, seed=2)
    lab = np.array([[3], [7], [0], [19]], np.int64)
    outs, _ = run_single_op(
        "nce", {"Input": x, "Label": lab, "Weight": w, "Bias": b},
        {"num_neg_samples": 5, "num_total_classes": 20},
        ["Cost", "SampleLogits", "SampleLabels"])
    assert outs["Cost"].shape == (4, 1) and (outs["Cost"] > 0).all()
    assert outs["SampleLogits"].shape == (4, 6)
    np.testing.assert_array_equal(outs["SampleLabels"][:, 0], lab[:, 0])
    # positive logit matches the manual projection
    ref0 = (x * w[lab[:, 0]]).sum(1) + b[lab[:, 0]]
    np.testing.assert_allclose(outs["SampleLogits"][:, 0], ref0,
                               rtol=1e-4, atol=1e-4)


def test_hierarchical_sigmoid_custom_tree():
    x = _rand(2, 4)
    w = _rand(5, 4, seed=1)
    lab = np.array([[0], [1]], np.int64)
    table = np.array([[0, 2, -1], [0, 3, 4]], np.int64)
    code = np.array([[1, 0, 0], [0, 1, 1]], np.float32)
    outs, _ = run_single_op(
        "hierarchical_sigmoid",
        {"X": x, "Label": lab, "W": w, "PathTable": table,
         "PathCode": code},
        {"num_classes": 5}, ["Out", "PreOut"])
    pre = np.einsum("bd,bld->bl", x, w[np.maximum(table, 0)])
    valid = (table >= 0)
    ce = np.log1p(np.exp(pre)) - code * pre
    ref = (ce * valid).sum(1, keepdims=True)
    np.testing.assert_allclose(outs["Out"], ref, rtol=1e-4, atol=1e-4)


def test_lstmp_projection_shape_and_identity():
    """lstmp with ProjWeight = I (P == D) must reduce to plain lstm."""
    B, T, D = 2, 4, 3
    x = _rand(B, T, 4 * D)
    W = _rand(D, 4 * D, seed=1) * 0.2
    bias = _rand(1, 4 * D, seed=2) * 0.1
    eye = np.eye(D, dtype=np.float32)
    ref, _ = run_single_op(
        "lstm", {"Input": x, "Weight": W, "Bias": bias},
        {}, ["Hidden", "Cell"])
    got, _ = run_single_op(
        "lstmp", {"Input": x, "Weight": W, "ProjWeight": eye,
                  "Bias": bias}, {}, ["Projection", "Cell"])
    np.testing.assert_allclose(got["Projection"], ref["Hidden"],
                               rtol=1e-4, atol=1e-5)
    # real projection changes the emitted width
    Wp = _rand(D, 2, seed=3)
    got2, _ = run_single_op(
        "lstmp", {"Input": x, "Weight": _rand(2, 4 * D, seed=4) * 0.2,
                  "ProjWeight": Wp, "Bias": bias}, {}, ["Projection"])
    assert got2["Projection"].shape == (B, T, 2)


def test_prroi_pool_constant_field():
    """On a constant feature map every bin averages to the constant."""
    x = np.full((1, 2, 8, 8), 3.0, np.float32)
    rois = np.array([[0, 1.0, 1.0, 6.0, 6.0]], np.float32)
    outs, _ = run_single_op(
        "prroi_pool", {"X": x, "ROIs": rois},
        {"pooled_height": 2, "pooled_width": 2, "spatial_scale": 1.0},
        ["Out"])
    np.testing.assert_allclose(outs["Out"], np.full((1, 2, 2, 2), 3.0),
                               rtol=1e-5)


def test_yolov3_loss_finite_and_masks():
    B, A, C, H = 2, 3, 4, 4
    x = _rand(B, A * (5 + C), H, H) * 0.1
    gtbox = np.zeros((B, 2, 4), np.float32)
    gtbox[0, 0] = [0.5, 0.5, 0.3, 0.4]
    gtbox[1, 0] = [0.25, 0.75, 0.2, 0.2]
    gtlabel = np.array([[1, 0], [3, 0]], np.int64)
    outs, _ = run_single_op(
        "yolov3_loss", {"X": x, "GTBox": gtbox, "GTLabel": gtlabel},
        {"anchors": [10, 13, 16, 30, 33, 23], "anchor_mask": [0, 1, 2],
         "class_num": C, "ignore_thresh": 0.7, "downsample_ratio": 32},
        ["Loss", "ObjectnessMask", "GTMatchMask"])
    assert outs["Loss"].shape == (B,)
    assert np.isfinite(outs["Loss"]).all() and (outs["Loss"] > 0).all()
    assert outs["GTMatchMask"].shape == (B, 2)
    assert outs["GTMatchMask"][0, 0] >= 0      # real gt matched
    assert outs["GTMatchMask"][0, 1] == -1     # zero-size gt unmatched


def test_multiclass_nms2_and_ctc_align():
    bboxes = np.array([[[0, 0, 10, 10], [50, 50, 60, 60]]], np.float32)
    scores = np.zeros((1, 2, 2), np.float32)
    scores[0, 1] = [0.9, 0.8]
    outs, _ = run_single_op(
        "multiclass_nms2", {"BBoxes": bboxes, "Scores": scores},
        {"score_threshold": 0.1, "nms_top_k": 2, "keep_top_k": 2,
         "nms_threshold": 0.3, "background_label": 0}, ["Out", "Index"])
    kept = outs["Out"][0][outs["Out"][0, :, 0] >= 0]
    assert len(kept) == 2
    assert (outs["Index"][0, :, 0] >= 0).sum() == 2
    seq = np.array([[0, 1, 1, 0, 2, 2, 3]], np.int32)
    outs, _ = run_single_op("ctc_align", {"Input": seq},
                            {"blank": 0, "padding_value": 0}, ["Output"])
    np.testing.assert_array_equal(outs["Output"][0][:3], [1, 2, 3])
    assert (outs["Output"][0][3:] == 0).all()


def test_positive_negative_pair():
    s = np.array([0.9, 0.2, 0.5, 0.7], np.float32)[:, None]
    lab = np.array([2, 0, 1, 0], np.float32)[:, None]
    q = np.array([1, 1, 1, 2], np.int64)[:, None]
    outs, _ = run_single_op(
        "positive_negative_pair", {"Score": s, "Label": lab, "QueryID": q},
        {}, ["PositivePair", "NegativePair", "NeutralPair"])
    # query 1 ordered label pairs: (0,1):pos, (0,2):pos, (2,1):pos
    assert float(outs["PositivePair"]) == 3
    assert float(outs["NegativePair"]) == 0


def test_mine_hard_examples():
    loss = np.array([[0.9, 0.1, 0.8, 0.2, 0.7]], np.float32)
    match = np.array([[2, -1, -1, -1, -1]], np.int32)
    outs, _ = run_single_op(
        "mine_hard_examples", {"ClsLoss": loss, "MatchIndices": match},
        {"neg_pos_ratio": 2.0}, ["NegIndices", "UpdatedMatchIndices"])
    negs = outs["NegIndices"][0]
    assert set(negs[negs >= 0].tolist()) == {2, 4}  # two hardest unmatched


def test_fused_bn_act_and_inplace_abn():
    x = _rand(4, 3, 2, 2)
    common = {"X": x, "Scale": np.ones(3, np.float32),
              "Bias": np.zeros(3, np.float32),
              "Mean": np.zeros(3, np.float32),
              "Variance": np.ones(3, np.float32)}
    mu = x.mean((0, 2, 3))
    v = x.var((0, 2, 3))
    norm = (x - mu.reshape(1, 3, 1, 1)) / np.sqrt(
        v.reshape(1, 3, 1, 1) + 1e-5)
    outs, _ = run_single_op("fused_batch_norm_act", common,
                            {"epsilon": 1e-5, "act_type": "relu"}, ["Y"])
    np.testing.assert_allclose(outs["Y"], np.maximum(norm, 0), rtol=1e-4,
                               atol=1e-4)
    outs, _ = run_single_op(
        "inplace_abn", common,
        {"epsilon": 1e-5, "activation": "leaky_relu", "alpha": 0.1},
        ["Y"])
    np.testing.assert_allclose(outs["Y"],
                               np.where(norm >= 0, norm, 0.1 * norm),
                               rtol=1e-4, atol=1e-4)


def test_tensor_array_to_tensor_lengths():
    a = [_rand(2, 3), _rand(3, 3, seed=1)]
    outs, _ = run_single_op("tensor_array_to_tensor", {"X": a},
                            {"axis": 0}, ["Out", "OutIndex"])
    np.testing.assert_allclose(outs["Out"], np.concatenate(a, 0),
                               rtol=1e-6)
    np.testing.assert_array_equal(outs["OutIndex"], [2, 3])
    outs, _ = run_single_op("lod_array_length", {"X": a}, {}, ["Out"])
    assert int(outs["Out"][0]) == 2
    outs, _ = run_single_op("max_sequence_len",
                            {"RankTable": _rand(2, 7, 3)}, {}, ["Out"])
    assert int(outs["Out"][0]) == 7


def test_prroi_pool_batch_roi_nums():
    """[R,4] ROIs + BatchRoINums route each ROI to its own image."""
    x = np.zeros((2, 1, 4, 4), np.float32)
    x[0] = 1.0
    x[1] = 5.0
    rois = np.array([[0.5, 0.5, 3.0, 3.0]] * 3, np.float32)
    nums = np.array([1, 2], np.int64)
    outs, _ = run_single_op(
        "prroi_pool", {"X": x, "ROIs": rois, "BatchRoINums": nums},
        {"pooled_height": 1, "pooled_width": 1, "spatial_scale": 1.0},
        ["Out"])
    np.testing.assert_allclose(outs["Out"][:, 0, 0, 0], [1.0, 5.0, 5.0],
                               rtol=1e-5)


def test_nce_noise_correction():
    """The NCE posterior subtracts log(k*q): with logits == log(k*q) the
    positive-term cost is exactly log(2)."""
    total, k = 10, 5
    x = np.ones((1, 2), np.float32)
    # craft weight/bias so the positive logit == log(k/total)
    w = np.zeros((total, 2), np.float32)
    b = np.full((total,), np.log(k / total), np.float32)
    lab = np.array([[0]], np.int64)
    outs, _ = run_single_op(
        "nce", {"Input": x, "Label": lab, "Weight": w, "Bias": b},
        {"num_neg_samples": k, "num_total_classes": total}, ["Cost"])
    # every sampled logit is log(k q) -> adjusted 0 -> each term log 2
    np.testing.assert_allclose(outs["Cost"][0, 0], (1 + k) * np.log(2),
                               rtol=1e-4)
