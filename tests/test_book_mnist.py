"""E2E book test: MNIST LeNet-5 static graph (milestone 1 / PR1 config).

Capability parity: reference `python/paddle/fluid/tests/book/
test_recognize_digits.py` — conv-pool x2 + fc LeNet, softmax cross-entropy,
loss-decrease assertion, save/load round trip.  Uses synthetic separable
data (no dataset downloads in this environment).
"""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.optimizer import AdamOptimizer


def make_synthetic_digits(n, seed=0):
    """10-class synthetic 28x28 images: class-dependent blob positions."""
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, 10, size=(n,)).astype(np.int64)
    imgs = rs.randn(n, 1, 28, 28).astype(np.float32) * 0.3
    for i, c in enumerate(labels):
        r, col = divmod(int(c), 5)
        imgs[i, 0, 4 + r * 12 : 12 + r * 12, 2 + col * 5 : 7 + col * 5] += 2.0
    return imgs, labels.reshape(-1, 1)


def lenet5(img, label):
    conv1 = layers.conv2d(img, num_filters=6, filter_size=5, padding=2, act="relu")
    pool1 = layers.pool2d(conv1, pool_size=2, pool_stride=2)
    conv2 = layers.conv2d(pool1, num_filters=16, filter_size=5, act="relu")
    pool2 = layers.pool2d(conv2, pool_size=2, pool_stride=2)
    fc1 = layers.fc(pool2, size=120, act="relu")
    fc2 = layers.fc(fc1, size=84, act="relu")
    logits = layers.fc(fc2, size=10)
    loss = layers.softmax_with_cross_entropy(logits, label)
    avg_loss = layers.mean(loss)
    acc = layers.accuracy(layers.softmax(logits), label)
    return avg_loss, acc, logits


def test_mnist_lenet_trains():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[1, 28, 28])
        label = layers.data("label", shape=[1], dtype="int64")
        avg_loss, acc, _ = lenet5(img, label)
        test_prog = main.clone(for_test=True)
        AdamOptimizer(learning_rate=1e-3).minimize(avg_loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)

    imgs, labels = make_synthetic_digits(256)
    bs = 32
    first_loss = last_loss = None
    for epoch in range(4):
        for i in range(0, len(imgs), bs):
            lv, av = exe.run(
                main,
                feed={"img": imgs[i : i + bs], "label": labels[i : i + bs]},
                fetch_list=[avg_loss, acc],
            )
            if first_loss is None:
                first_loss = float(lv)
            last_loss = float(lv)
    assert last_loss < first_loss * 0.5, (first_loss, last_loss)

    # eval on the cloned test program
    test_imgs, test_labels = make_synthetic_digits(64, seed=123)
    lv, av = exe.run(
        test_prog,
        feed={"img": test_imgs, "label": test_labels},
        fetch_list=[avg_loss.name, acc.name],
    )
    assert float(av) > 0.5, float(av)
