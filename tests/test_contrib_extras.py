"""contrib extras: decoder library, decoupled weight decay, program
stats (reference `contrib/decoder/beam_search_decoder.py`,
`extend_optimizer/`, `model_stat.py` / `memory_usage_calc.py` /
`op_frequence.py`)."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import contrib, layers
from paddle_tpu.fluid.contrib.decoder import (
    BeamSearchDecoder,
    InitState,
    StateCell,
    TrainingDecoder,
)
from paddle_tpu.fluid.optimizer import AdamOptimizer, SGDOptimizer

V, E, H = 12, 8, 16
GO, EOS = 0, 1


def _make_cell(boot):
    """A tiny GRU-ish cell: h' = tanh(W x + U h)."""
    cell = StateCell(
        inputs={"x": None},
        states={"h": InitState(init=boot)},
        out_state="h")

    @cell.state_updater
    def updater(c):
        x = c.get_input("x")
        h = c.get_state("h")
        nh = layers.tanh(
            layers.elementwise_add(
                layers.fc(x, size=H, param_attr="dec.w",
                          bias_attr="dec.b"),
                layers.fc(h, size=H, param_attr="dec.u",
                          bias_attr=False)))
        c.set_state("h", nh)

    return cell


def test_training_decoder_matches_manual_unroll():
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    T = 4
    with fluid.program_guard(main, startup):
        x_seq = layers.data("x_seq", shape=[-1, T, E],
                            append_batch_size=False)
        boot = layers.data("boot", shape=[-1, H], append_batch_size=False)
        cell = _make_cell(boot)
        dec_out = TrainingDecoder(cell).decode({"x": x_seq}, n_steps=T)

        # manual unroll with the SAME parameters
        cell2 = _make_cell(boot)
        outs = []
        for t in range(T):
            xt = layers.reshape(
                layers.slice(x_seq, axes=[1], starts=[t], ends=[t + 1]),
                [-1, E])
            cell2.compute_state({"x": xt})
            outs.append(layers.unsqueeze(cell2.out_state(), [1]))
        manual = layers.concat(outs, axis=1)

    rng = np.random.RandomState(0)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        a, b = exe.run(main, feed={
            "x_seq": rng.randn(3, T, E).astype(np.float32),
            "boot": np.zeros((3, H), np.float32),
        }, fetch_list=[dec_out, manual])
    assert np.asarray(a).shape == (3, T, H)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5)


def test_beam_search_decoder_decodes_and_beam1_is_greedy():
    def build(beam):
        main, startup = fluid.Program(), fluid.Program()
        main.random_seed = startup.random_seed = 7
        with fluid.program_guard(main, startup):
            boot = layers.data("boot", shape=[-1, H],
                               append_batch_size=False)
            cell = _make_cell(boot)

            def embed(prev_ids):
                emb = layers.embedding(prev_ids, size=[V, E],
                                       param_attr="dec.emb")
                return {"x": layers.reshape(emb, [-1, E])}

            def logits(c):
                return layers.fc(c.out_state(), size=V,
                                 param_attr="dec.out_w",
                                 bias_attr="dec.out_b")

            bsd = BeamSearchDecoder(cell, embed, logits, beam_size=beam,
                                    end_id=EOS, max_len=5, go_id=GO)
            ids, scores = bsd.decode()
        return main, startup, ids, scores

    rng = np.random.RandomState(1)
    boot = rng.randn(4, H).astype(np.float32)

    def run(beam):
        main, startup, ids, scores = build(beam)
        exe = fluid.Executor()
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            i, s = exe.run(main, feed={"boot": boot},
                           fetch_list=[ids, scores])
        return np.asarray(i), np.asarray(s)

    ids4, scores4 = run(4)
    assert ids4.shape == (4, 4, 5)
    assert np.isfinite(scores4).all()
    # beams are score-ordered best-first
    assert (scores4[:, 0] >= scores4[:, -1] - 1e-6).all()

    ids1, _ = run(1)
    assert ids1.shape == (4, 1, 5)
    # beam widths agree on the first step's top choice by construction
    # of score ordering: beam-4's best path scores >= beam-1's path
    _, s1 = run(1)
    assert (scores4[:, 0] >= s1[:, 0] - 1e-5).all()


def test_decoupled_weight_decay_shrinks_params():
    AdamW = contrib.extend_with_decoupled_weight_decay(AdamOptimizer)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 4], append_batch_size=False)
        pred = layers.fc(x, size=1, param_attr="wd.w", bias_attr=False)
        loss = layers.reduce_mean(layers.square(pred))
        opt = AdamW(learning_rate=0.0, coeff=0.1)   # lr 0: pure decay
        opt.minimize(loss)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        import paddle_tpu.fluid.executor as ex

        w0 = np.asarray(ex.global_scope().find_var("wd.w")).copy()
        exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss])
        w1 = np.asarray(ex.global_scope().find_var("wd.w"))
    np.testing.assert_allclose(w1, w0 * 0.9, rtol=1e-5)

    # filter hook: excluded params do not decay
    import pytest

    with pytest.raises(TypeError):
        contrib.extend_with_decoupled_weight_decay(object)


def test_program_stat_utils():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[1, 8, 8])
        h = layers.conv2d(img, num_filters=4, filter_size=3, padding=1,
                          bias_attr=False)
        h = layers.relu(h)
        logits = layers.fc(h, size=10)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(
                logits, layers.data("y", shape=[1], dtype="int64")))
        SGDOptimizer(0.1).minimize(loss)

    freq = contrib.op_freq_statistic(main)
    assert freq["conv2d"] == 1 and freq["relu"] >= 1

    lo, hi = contrib.memory_usage(main, batch_size=32)
    assert 0 < lo < hi

    rows, params, flops = contrib.summary(main, batch_size=1)
    # conv: 4*1*3*3 = 36; fc: 4*8*8*10 + 10
    assert params == 36 + 4 * 8 * 8 * 10 + 10
    assert flops > 0
    assert any(r["type"] == "conv2d" for r in rows)


def test_decoupled_decay_ops_pruned_from_eval_clone():
    """Review r5: the decay ops must carry op_role=optimize so
    clone(for_test=True) prunes them — eval runs must NOT decay
    weights."""
    AdamW = contrib.extend_with_decoupled_weight_decay(AdamOptimizer)
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 6
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 4], append_batch_size=False)
        pred = layers.fc(x, size=1, param_attr="ev.w", bias_attr=False)
        loss = layers.reduce_mean(layers.square(pred))
        AdamW(learning_rate=0.0, coeff=0.1).minimize(loss)
        eval_prog = main.clone(for_test=True)
    assert all(op.type not in ("assign", "elementwise_sub")
               for op in eval_prog.global_block.ops), [
        op.type for op in eval_prog.global_block.ops]
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        import paddle_tpu.fluid.executor as ex

        w0 = np.asarray(ex.global_scope().find_var("ev.w")).copy()
        exe.run(eval_prog, feed={"x": np.ones((2, 4), np.float32)},
                fetch_list=[loss])
        w1 = np.asarray(ex.global_scope().find_var("ev.w"))
    np.testing.assert_allclose(w1, w0)      # eval did not touch weights


def test_contrib_layers_surface():
    """cf. contrib/layers/nn.py: the niche-op layer wrappers build and
    run through the Executor (dense redesigns of the LoD inputs)."""
    from paddle_tpu.fluid.contrib import layers as cl

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 9
    with fluid.program_guard(main, startup):
        # text-matching chain: match matrix -> topk avg pooling
        xa = layers.data("xa", shape=[-1, 5, 6], append_batch_size=False)
        yb = layers.data("yb", shape=[-1, 7, 6], append_batch_size=False)
        mm, _tmp = cl.match_matrix_tensor(xa, yb, channel_num=3)
        rl = layers.data("rl", shape=[-1], dtype="int64",
                         append_batch_size=False)
        clens = layers.data("cl", shape=[-1], dtype="int64",
                            append_batch_size=False)
        pooled = cl.sequence_topk_avg_pooling(mm, rl, clens,
                                              topks=[1, 3], channel_num=3)
        # var conv over per-sample extents
        vx = layers.data("vx", shape=[-1, 2, 6, 6],
                         append_batch_size=False)
        vc = cl.var_conv_2d(vx, rl, clens, input_channel=2,
                            output_channel=4, filter_size=3)
        # tree conv
        nodes = layers.data("nodes", shape=[-1, 6, 6],
                            append_batch_size=False)
        edges = layers.data("edges", shape=[-1, 5, 2], dtype="int32",
                            append_batch_size=False)
        tc = cl.tree_conv(nodes, edges, output_size=4, num_filters=2)
        # pyramid hash embedding
        toks = layers.data("toks", shape=[-1, 8], dtype="int32",
                           append_batch_size=False)
        slens = layers.data("sl", shape=[-1], dtype="int64",
                            append_batch_size=False)
        ph = cl.search_pyramid_hash(toks, slens, num_emb=8, space_len=512,
                                    pyramid_layer=3, rand_len=4)
        # batch utilities
        x2 = layers.data("x2", shape=[-1, 6], append_batch_size=False)
        shuf = cl.shuffle_batch(x2)
        pc = cl.partial_concat([x2, x2], start_index=1, length=3)
        ps = cl.partial_sum([x2, x2], start_index=0, length=2)
        fe = cl.fused_elemwise_activation(
            x2, x2, ["relu", "elementwise_add"])
        ids = layers.data("ids", shape=[-1, 4, 1], dtype="int64",
                          append_batch_size=False)
        fp = cl.fused_embedding_seq_pool(ids, size=[50, 6])
        child, mask = cl.tdm_child(
            layers.reshape(ids, [-1, 4]), node_nums=50, child_nums=2)

    rng = np.random.RandomState(0)
    x2_feed = rng.randn(4, 6).astype(np.float32)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        outs = exe.run(main, feed={
            "xa": rng.randn(2, 5, 6).astype(np.float32),
            "yb": rng.randn(2, 7, 6).astype(np.float32),
            "rl": np.array([5, 4], np.int64),
            "cl": np.array([7, 6], np.int64),
            "vx": rng.randn(2, 2, 6, 6).astype(np.float32),
            "nodes": rng.randn(2, 6, 6).astype(np.float32),
            "edges": np.tile(np.array(
                [[1, 2], [1, 3], [2, 4], [2, 5], [3, 6]],
                np.int32), (2, 1, 1)),
            "toks": rng.randint(0, 99, (2, 8)).astype(np.int32),
            "sl": np.array([8, 5], np.int64),
            "x2": x2_feed,
            "ids": rng.randint(1, 50, (3, 4, 1)).astype(np.int64),
        }, fetch_list=[pooled, vc, tc, ph, shuf, pc, ps, fe, fp, child,
                       mask])
    pooled_v, vc_v, tc_v, ph_v, shuf_v, pc_v, ps_v, fe_v, fp_v, ch_v, \
        mk_v = (np.asarray(o) for o in outs)
    assert pooled_v.shape == (2, 5, 6)           # [B, R, C*K]
    assert vc_v.shape[:2] == (2, 4)
    assert tc_v.shape == (2, 6, 4, 2)
    assert ph_v.shape == (2, 8, 8)
    # shuffle preserves the multiset of rows
    assert shuf_v.shape == (4, 6)
    np.testing.assert_allclose(
        np.sort(shuf_v, axis=0), np.sort(x2_feed, axis=0), rtol=1e-6)
    np.testing.assert_allclose(
        pc_v, np.concatenate([x2_feed[:, 1:4]] * 2, axis=1), rtol=1e-6)
    np.testing.assert_allclose(ps_v, x2_feed[:, :2] * 2, rtol=1e-6)
    np.testing.assert_allclose(fe_v, np.maximum(x2_feed * 2, 0),
                               rtol=1e-6)          # relu(x + x)
    assert fp_v.shape == (3, 6)
    assert ch_v.shape == (3, 4, 2) and mk_v.shape == (3, 4, 2)
    assert set(np.unique(mk_v)) <= {0, 1}


def test_distributed_batch_reader_shards_stream(monkeypatch):
    """cf. contrib/reader/distributed_reader.py: trainer i gets batches
    i, i+N, ... of the shared stream."""
    from paddle_tpu.fluid.contrib import distributed_batch_reader

    def reader():
        for i in range(10):
            yield i

    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "3")
    monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
    assert list(distributed_batch_reader(reader)()) == [1, 4, 7]
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    assert list(distributed_batch_reader(reader)()) == [0, 3, 6, 9]
    monkeypatch.setenv("PADDLE_TRAINER_ID", "5")
    import pytest

    with pytest.raises(ValueError, match="out of range"):
        distributed_batch_reader(reader)

def _mix_hash_np(h, v):
    """numpy mirror of search_ops._mix_hash (uint32 wraparound)."""
    h = ((h ^ v) * np.uint32(0x9E3779B1)).astype(np.uint32)
    h = h ^ (h >> np.uint32(15))
    return (h * np.uint32(0x85EBCA77)).astype(np.uint32)


def test_pyramid_hash_matches_numpy_oracle():
    """Value oracle (VERDICT r5 item: shape/locality tests never pinned
    the numbers): mirror the xorshift-mix hash + windowed gather in
    numpy and demand exact agreement — a silent indexing or hashing
    regression cannot hide behind a learned table."""
    B, T, num_emb, rand_len, space, pyr = 3, 6, 8, 4, 128, 3

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 13
    with fluid.program_guard(main, startup):
        toks = layers.data("toks", shape=[-1, T], dtype="int32",
                           append_batch_size=False)
        slens = layers.data("sl", shape=[-1], dtype="int64",
                            append_batch_size=False)
        ph = contrib.layers.search_pyramid_hash(
            toks, slens, num_emb=num_emb, space_len=space,
            pyramid_layer=pyr, rand_len=rand_len, param_attr="orc.phw")

    rng = np.random.RandomState(3)
    toks_v = rng.randint(0, 997, (B, T)).astype(np.int32)
    lens_v = np.array([T, 4, 1], np.int64)   # full, partial, gram-free
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        import paddle_tpu.fluid.executor as ex

        w = np.asarray(ex.global_scope().find_var("orc.phw")).reshape(-1)
        (got,) = exe.run(main, feed={"toks": toks_v, "sl": lens_v},
                         fetch_list=[ph])
    got = np.asarray(got)

    expect = np.zeros((B, T, num_emb), np.float32)
    for n in range(2, pyr + 1):
        h = np.full((B, T), 2166136261, np.uint32)
        for j in range(n):
            h = _mix_hash_np(h, np.roll(toks_v, -j, axis=1).astype(
                np.uint32))
        gram = np.zeros((B, T, num_emb), np.float32)
        for cix in range(num_emb // rand_len):
            hc = _mix_hash_np(h, np.uint32(cix + 1))
            start = (hc % np.uint32(space - rand_len)).astype(np.int64)
            idx = start[:, :, None] + np.arange(rand_len)[None, None, :]
            gram[:, :, cix * rand_len:(cix + 1) * rand_len] = w[idx]
        ok = (np.arange(T)[None, :] + n) <= lens_v[:, None]
        expect += np.where(ok[:, :, None], gram, 0.0)
    np.testing.assert_allclose(got, expect, rtol=1e-6, atol=0)
    assert np.any(expect != 0)            # the oracle actually probed
    assert np.all(got[2] == 0)            # len-1 sequence has no gram


def test_tree_conv_matches_numpy_oracle():
    """Value oracle for tree_conv (TBCNN): replay the adjacency-power
    patch construction + eta_t/eta_l/eta_r position weights in numpy."""
    B, N, F, O, C, depth = 2, 6, 5, 4, 3, 3

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 17
    with fluid.program_guard(main, startup):
        nodes = layers.data("nodes", shape=[-1, N, F],
                            append_batch_size=False)
        edges = layers.data("edges", shape=[-1, N - 1, 2], dtype="int32",
                            append_batch_size=False)
        tc = contrib.layers.tree_conv(nodes, edges, output_size=O,
                                      num_filters=C, max_depth=depth,
                                      act=None, param_attr="orc.tcw")

    rng = np.random.RandomState(5)
    nodes_v = rng.randn(B, N, F).astype(np.float32)
    # sample 0: root 1 with children 2,3; 3 has children 4,5,6
    # sample 1: a chain 1->2->3->4->5->6 (one child each)
    edges_v = np.stack([
        np.array([[1, 2], [1, 3], [3, 4], [3, 5], [3, 6]], np.int32),
        np.array([[1, 2], [2, 3], [3, 4], [4, 5], [5, 6]], np.int32),
    ])
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        import paddle_tpu.fluid.executor as ex

        w = np.asarray(ex.global_scope().find_var("orc.tcw"))  # [F,3,O,C]
        (got,) = exe.run(main, feed={"nodes": nodes_v, "edges": edges_v},
                         fetch_list=[tc])
    got = np.asarray(got)

    expect = np.zeros((B, N, O, C), np.float32)
    for b in range(B):
        x, es = nodes_v[b], edges_v[b]
        adj = np.zeros((N, N), np.float32)
        for p, c in es:
            adj[p - 1, c - 1] = 1.0
        # per-node sibling geometry (1-based order among its parent's
        # edge list, and that parent's child count)
        idx_c = np.zeros(N)
        l_of = np.zeros(N)
        for ei, (p, c) in enumerate(es):
            idx_c[c - 1] = 1 + sum(1 for q, _ in es[:ei] if q == p)
            l_of[c - 1] = sum(1 for q, _ in es if q == p)
        alpha = np.where(l_of == 1, 0.5,
                         (idx_c - 1.0) / np.maximum(l_of - 1.0, 1.0))
        out = np.einsum("nf,foc->noc", x, w[:, 2])
        reach = np.eye(N, dtype=np.float32)
        for d in range(1, depth):
            reach = reach @ adj
            eta_t = float(depth - d) / depth
            eta_l = (1.0 - eta_t) * alpha
            eta_r = (1.0 - eta_t) * (1.0 - eta_l)
            mixed = (np.einsum("n,nf,foc->noc", eta_l, x, w[:, 0])
                     + np.einsum("n,nf,foc->noc", eta_r, x, w[:, 1])
                     + eta_t * np.einsum("nf,foc->noc", x, w[:, 2]))
            out = out + np.einsum("un,noc->uoc", reach, mixed)
        expect[b] = out
    np.testing.assert_allclose(got, expect, rtol=2e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# dense beam-op numpy value oracles (the pyramid_hash oracle discipline
# applied to the legacy decoder's two ops; `paddle_tpu.generation` is
# the recommended serving path — these pin the bridge it replaces)
# ---------------------------------------------------------------------------


def _np_beam_search_step(pre_ids, pre_scores, scores, beam, end_id):
    """Numpy oracle of ONE dense beam_search step (beam_search_op.cc
    semantics): finished beams contribute a single frozen end_id
    candidate; top-k over the flattened [beam*V] accumulated scores."""
    B, _, V = scores.shape
    total = scores.copy()
    for b in range(B):
        for k in range(beam):
            if pre_ids[b, k] == end_id:
                total[b, k, :] = -1e9
                total[b, k, end_id] = pre_scores[b, k]
    sel_ids = np.zeros((B, beam), np.int64)
    sel_scores = np.zeros((B, beam), np.float32)
    parents = np.zeros((B, beam), np.int64)
    for b in range(B):
        flat = total[b].reshape(-1)
        top = np.argsort(-flat, kind="stable")[:beam]
        sel_scores[b] = flat[top]
        parents[b] = top // V
        sel_ids[b] = top % V
    return sel_ids, sel_scores, parents


def _np_beam_search_decode(ids, parents):
    """Numpy oracle of the backtrack: [T, B, beam] -> [B, beam, T]."""
    T, B, beam = ids.shape
    out = np.zeros((B, beam, T), ids.dtype)
    for b in range(B):
        for k in range(beam):
            cur = k
            for t in range(T - 1, -1, -1):
                out[b, k, t] = ids[t, b, cur]
                cur = parents[t, b, cur]
    return out


def test_beam_search_ops_match_numpy_oracle():
    rng = np.random.RandomState(3)
    B, beam, Vv, end = 3, 4, 9, 1
    pre_ids = rng.randint(0, Vv, (B, beam)).astype(np.int64)
    pre_ids[0, 1] = end                      # one finished beam
    pre_scores = rng.randn(B, beam).astype(np.float32)
    scores = rng.randn(B, beam, Vv).astype(np.float32)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        pi = layers.data("pi", shape=[-1, beam], dtype="int64",
                         append_batch_size=False)
        ps = layers.data("ps", shape=[-1, beam],
                         append_batch_size=False)
        sc = layers.data("sc", shape=[-1, beam, Vv],
                         append_batch_size=False)
        si, ss, pa = layers.beam_search(pi, ps, sc, beam_size=beam,
                                        end_id=end)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        got_i, got_s, got_p = exe.run(
            main, feed={"pi": pre_ids, "ps": pre_scores, "sc": scores},
            fetch_list=[si, ss, pa])
    ref_i, ref_s, ref_p = _np_beam_search_step(
        pre_ids, pre_scores, scores, beam, end)
    np.testing.assert_allclose(np.asarray(got_s), ref_s, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(got_i), ref_i)
    np.testing.assert_array_equal(np.asarray(got_p), ref_p)


def test_beam_search_decode_matches_numpy_oracle():
    rng = np.random.RandomState(5)
    T, B, beam = 6, 2, 3
    ids = rng.randint(0, 11, (T, B, beam)).astype(np.int64)
    parents = rng.randint(0, beam, (T, B, beam)).astype(np.int64)
    final_scores = rng.randn(B, beam).astype(np.float32)

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        iv = layers.data("ids", shape=[T, B, beam], dtype="int64",
                         append_batch_size=False)
        pv = layers.data("par", shape=[T, B, beam], dtype="int64",
                         append_batch_size=False)
        fv = layers.data("fs", shape=[-1, beam],
                         append_batch_size=False)
        sent, sscore = layers.beam_search_decode(iv, pv, fv)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        got_ids, got_scores = exe.run(
            main, feed={"ids": ids, "par": parents, "fs": final_scores},
            fetch_list=[sent, sscore])
    np.testing.assert_array_equal(
        np.asarray(got_ids), _np_beam_search_decode(ids, parents))
    np.testing.assert_allclose(np.asarray(got_scores), final_scores,
                               rtol=1e-6)
