"""Observability floor: StatRegistry counters, Print op, graphviz dump,
per-op NaN localization, unused-var check (reference `platform/monitor.h`,
`operators/print_op.cc`, `python/paddle/fluid/debugger.py:1`,
`details/nan_inf_utils_detail.cc`, `framework/unused_var_check.cc`)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.core import monitor


def _simple_program():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 3], append_batch_size=False)
        h = layers.fc(x, size=5, act="relu")
        out = layers.reduce_sum(h)
    return main, startup, out


def test_stat_registry_counts_runs():
    monitor.reset()
    main, startup, out = _simple_program()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        for _ in range(3):
            exe.run(main, feed={"x": np.ones((4, 3), np.float32)},
                    fetch_list=[out])
    stats = monitor.stat_values()
    assert stats["STAT_executor_runs"] >= 4  # startup + 3 main runs
    assert stats["STAT_executor_programs_compiled"] >= 2
    monitor.stat_add("custom_counter", 5)
    assert monitor.stat_get("custom_counter") == 5
    monitor.reset("custom_counter")
    assert monitor.stat_get("custom_counter") == 0


def test_print_op_passthrough(capfd):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3], append_batch_size=False)
        y = layers.Print(x, message="DBGVAL", summarize=3)
        z = layers.scale(y, scale=2.0)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([1.0, 2.0, 3.0], np.float32)
    (out,) = exe.run(main, feed={"x": xv}, fetch_list=[z])
    np.testing.assert_allclose(out, xv * 2)  # identity pass-through
    captured = capfd.readouterr()
    assert "DBGVAL" in captured.out or "DBGVAL" in captured.err


def test_graphviz_dump(tmp_path):
    main, _, _ = _simple_program()
    path = str(tmp_path / "prog.dot")
    fluid.debugger.draw(main, path=path)
    dot = open(path).read()
    assert dot.startswith("digraph G {")
    assert "matmul" in dot or "mul" in dot  # the fc's compute op
    assert "shape=box" in dot and "shape=ellipse" in dot
    # parameters shaded
    assert "lightgrey" in dot


def test_pprint_program_codes():
    main, _, _ = _simple_program()
    listing = fluid.debugger.pprint_program_codes(main)
    assert "block_0 {" in listing
    assert "reduce_sum" in listing


def test_nan_localization_names_the_op():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3], append_batch_size=False)
        lg = layers.log(x)  # NaN for negative inputs
        out = layers.reduce_sum(lg)
    exe = fluid.Executor(fluid.CPUPlace())
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(Exception) as ei:
            exe.run(main, feed={"x": np.array([-1.0, 1.0, 2.0], np.float32)},
                    fetch_list=[out])
        assert "log" in str(ei.value)  # the guard names the culprit op
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})
    # and clean inputs still work with the flag off
    (ov,) = exe.run(main, feed={"x": np.array([1.0, 1.0, 2.0], np.float32)},
                    fetch_list=[out])
    assert np.isfinite(ov).all()


def test_unused_var_check_warns():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3], append_batch_size=False)
        _dead = layers.scale(x, scale=3.0)  # produced, never consumed
        out = layers.reduce_sum(x)
    exe = fluid.Executor(fluid.CPUPlace())
    fluid.set_flags({"FLAGS_enable_unused_var_check": True})
    try:
        with pytest.warns(UserWarning, match="unused op outputs"):
            exe.run(main, feed={"x": np.ones((3,), np.float32)},
                    fetch_list=[out])
    finally:
        fluid.set_flags({"FLAGS_enable_unused_var_check": False})


# ---------------------------------------------------------------------------
# failure detection (reference heart_beat_monitor.h:54, barrier_monitor.cc)
# ---------------------------------------------------------------------------


def test_heartbeat_monitor_detects_lost_worker(tmp_path):
    import time

    from paddle_tpu.distributed.monitor import (
        COMPLETED, LOST, RUNNING, UNINITED, HeartBeatMonitor,
    )

    ws = str(tmp_path)
    m0 = HeartBeatMonitor(ws, worker_id=0, worker_num=3, timeout_s=0.2)
    m1 = HeartBeatMonitor(ws, worker_id=1, worker_num=3, timeout_s=0.2)
    m0.update()
    m1.update()
    st = m0.worker_status()
    assert st[0] == RUNNING and st[1] == RUNNING and st[2] == UNINITED
    assert m0.lost_workers() == []
    # worker 1 stops pinging -> LOST after timeout; worker 0 keeps pinging
    time.sleep(0.3)
    m0.update()
    st = m0.worker_status()
    assert st[0] == RUNNING and st[1] == LOST
    assert m0.lost_workers() == [1]
    m1.complete()
    assert m0.worker_status()[1] == COMPLETED


def test_barrier_monitor_names_absent_ranks(tmp_path):
    from paddle_tpu.distributed.monitor import BarrierMonitor

    import threading

    b0 = BarrierMonitor(str(tmp_path), 0, 2, timeout_s=0.3)
    with pytest.raises(TimeoutError, match=r"absent ranks: \[1\]"):
        b0.wait("step1")
    # both present -> passes (second party joins from a thread)
    b0._timeout = 5.0
    b1 = BarrierMonitor(str(tmp_path), 1, 2, timeout_s=5.0)
    t = threading.Thread(target=lambda: b1.wait("step2"))
    t.start()
    b0.wait("step2")
    t.join(timeout=5)
    assert not t.is_alive()


def test_fleet_sync_batch_norm_rewrite():
    import paddle_tpu.fleet as fleet_mod
    from paddle_tpu.fleet import DistributedStrategy

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 4], append_batch_size=False)
        h = layers.batch_norm(layers.fc(x, size=4))
        loss = layers.reduce_mean(h)
        fleet = fleet_mod.fleet
        fleet.init(is_collective=True)
        s = DistributedStrategy()
        s.sync_batch_norm = True
        opt = fleet.distributed_optimizer(
            fluid.optimizer.SGDOptimizer(learning_rate=0.1), strategy=s)
        opt.minimize(loss)
    types = [op.type for op in main.global_block.ops]
    assert "sync_batch_norm" in types and "batch_norm" not in types
    # single-rank it still executes correctly
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        (lv,) = exe.run(main, feed={"x": np.ones((8, 4), np.float32)},
                        fetch_list=[loss])
    assert np.isfinite(lv)


def test_local_fs_roundtrip(tmp_path):
    from paddle_tpu.fluid.fs import LocalFS

    fs = LocalFS()
    d = str(tmp_path / "a" / "b")
    fs.mkdirs(d)
    assert fs.is_dir(d)
    f = str(tmp_path / "a" / "b" / "x.txt")
    fs.touch(f)
    assert fs.is_file(f) and fs.is_exist(f)
    dirs, files = fs.ls_dir(str(tmp_path / "a" / "b"))
    assert files == ["x.txt"]
    fs.upload(f, str(tmp_path / "copy.txt"))
    assert fs.is_file(str(tmp_path / "copy.txt"))
    fs.mv(str(tmp_path / "copy.txt"), str(tmp_path / "moved.txt"))
    assert fs.is_file(str(tmp_path / "moved.txt"))
    fs.delete(d)
    assert not fs.is_exist(d)


def test_nan_flag_toggle_after_first_run_takes_effect():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3], append_batch_size=False)
        out = layers.reduce_sum(layers.log(x))
    exe = fluid.Executor(fluid.CPUPlace())
    bad = np.array([-1.0, 1.0, 2.0], np.float32)
    # first run WITHOUT the flag: NaN passes through silently
    (v,) = exe.run(main, feed={"x": bad}, fetch_list=[out])
    assert np.isnan(v)
    # toggling the flag must invalidate the cached trace
    fluid.set_flags({"FLAGS_check_nan_inf": True})
    try:
        with pytest.raises(Exception, match="log"):
            exe.run(main, feed={"x": bad}, fetch_list=[out])
    finally:
        fluid.set_flags({"FLAGS_check_nan_inf": False})


def test_print_message_with_braces_is_safe(capfd):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2], append_batch_size=False)
        y = layers.Print(x, message="loss at {step}")
        z = layers.scale(y, scale=1.0)
    exe = fluid.Executor(fluid.CPUPlace())
    (out,) = exe.run(main, feed={"x": np.ones((2,), np.float32)},
                     fetch_list=[z])
    np.testing.assert_allclose(out, [1.0, 1.0])
    assert "loss at {step}" in capfd.readouterr().out


def test_barrier_id_reuse_raises(tmp_path):
    from paddle_tpu.distributed.monitor import BarrierMonitor

    b = BarrierMonitor(str(tmp_path), 0, 1, timeout_s=1.0)
    b.wait("once")
    with pytest.raises(ValueError, match="already used"):
        b.wait("once")
    b.wait()  # auto ids never collide
    b.wait()


def test_profiler_op_table_and_chrome_trace(tmp_path, capsys):
    """stop_profiler prints the reference-style aggregated per-op table
    (profiler.cc PrintProfiler) and exports a chrome://tracing-loadable
    JSON (tools/timeline.py:115 parity)."""
    import json

    import paddle_tpu.fluid as fluid
    from paddle_tpu.fluid import layers, profiler

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 16], append_batch_size=False)
        h = layers.fc(x, size=32, act="relu")
        loss = layers.reduce_mean(layers.square(h))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)

    trace_json = tmp_path / "chrome_trace.json"
    exe = fluid.Executor()
    rng = np.random.RandomState(0)
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        profiler.start_profiler("All", log_dir=str(tmp_path / "trace"))
        with profiler.RecordEvent("my_train_region"):
            for _ in range(3):
                exe.run(main, feed={"x": rng.randn(8, 16).astype("float32")},
                        fetch_list=[loss])
        profiler.stop_profiler(sorted_key="total",
                               profile_path=str(trace_json))

    out = capsys.readouterr().out
    assert "Profiling Report" in out
    assert "Calls" in out and "Total(us)" in out and "Ratio" in out
    # at least one real event row beyond the header
    body = [l for l in out.splitlines() if "%" in l]
    assert body, out
    # chrome trace loads and contains complete events
    data = json.loads(trace_json.read_text())
    evts = data["traceEvents"]
    assert any(e.get("ph") == "X" for e in evts)
    names = {e.get("name") for e in evts}
    assert any(n and "my_train_region" in str(n) for n in names)
