"""AMP (bf16/fp16 + loss scaling), recompute segments, gradient merge.

Mirrors reference tests test_mixed_precision.py / test_recompute.py /
test_gradient_merge patterns: program-structure assertions + loss-parity
with the unwrapped optimizer.
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.contrib.mixed_precision import decorate
from paddle_tpu.fluid.optimizer import (
    GradientMergeOptimizer,
    RecomputeOptimizer,
    SGDOptimizer,
)


def _build_mlp(seed=0):
    np.random.seed(seed)
    x = fluid.data("x", [8, 4], "float32")
    y = fluid.data("y", [8, 1], "float32")
    h = layers.fc(x, 16, act="relu")
    h2 = layers.fc(h, 16, act="relu")
    pred = layers.fc(h2, 1)
    loss = layers.reduce_mean(layers.square_error_cost(pred, y))
    return x, y, h, h2, loss


def _feed(seed=1):
    rng = np.random.RandomState(seed)
    return {
        "x": rng.randn(8, 4).astype(np.float32),
        "y": rng.randn(8, 1).astype(np.float32),
    }


def test_amp_bf16_inserts_casts_and_trains():
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        *_, loss = _build_mlp()
        opt = decorate(SGDOptimizer(0.01), dest_dtype="bfloat16")
        opt.minimize(loss, startup)
    types = [op.type for op in prog.global_block.ops]
    assert "cast" in types, "AMP must insert casts"
    # white-listed mul ops now consume bf16-cast inputs
    mul_ops = [op for op in prog.global_block.ops if op.type == "mul"]
    assert any(
        any(".cast_bfloat16" in n for n in op.all_input_names())
        for op in mul_ops
    )
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run_startup(startup)
        feed = _feed(1)
        losses = [
            float(exe.run(prog, feed=feed, fetch_list=[loss])[0])
            for _ in range(6)
        ]
    assert losses[-1] < losses[0]


def test_amp_fp16_dynamic_loss_scaling_program():
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        *_, loss = _build_mlp()
        opt = decorate(
            SGDOptimizer(0.01), dest_dtype="float16", init_loss_scaling=8.0
        )
        opt.minimize(loss, startup)
    types = [op.type for op in prog.global_block.ops]
    assert "check_finite_and_unscale" in types
    assert "update_loss_scaling" in types
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run_startup(startup)
        feed = _feed(0)
        l0 = float(exe.run(prog, feed=feed, fetch_list=[loss])[0])
        l5 = l0
        for _ in range(5):
            l5 = float(exe.run(prog, feed=feed, fetch_list=[loss])[0])
        # training proceeds under scaling
        assert np.isfinite(l5)
        from paddle_tpu.fluid.core.scope import global_scope

        ls = float(np.asarray(global_scope().find_var(opt.get_loss_scaling().name))[0])
        assert ls == 8.0  # no overflow on this toy problem


def test_recompute_segments_fold_and_match_baseline():
    # baseline
    prog_a = fluid.Program()
    startup_a = fluid.Program()
    with fluid.program_guard(prog_a, startup_a):
        fluid.framework.reset_default_programs  # no-op, clarity
        import paddle_tpu.fluid.unique_name as un

        with un.guard():
            *_, loss_a = _build_mlp()
            SGDOptimizer(0.05).minimize(loss_a, startup_a)

    prog_b = fluid.Program()
    startup_b = fluid.Program()
    with fluid.program_guard(prog_b, startup_b):
        import paddle_tpu.fluid.unique_name as un

        with un.guard():
            x, y, h, h2, loss_b = _build_mlp()
            opt = RecomputeOptimizer(SGDOptimizer(0.05))
            opt._set_checkpoints([h, h2])
            opt.minimize(loss_b, startup_b)
    types = [op.type for op in prog_b.global_block.ops]
    assert "recompute_segment" in types

    feeds = [_feed(i) for i in range(4)]
    exe_a = fluid.Executor()  # fresh executors: identical PRNG streams
    with fluid.scope_guard(fluid.Scope()):
        exe_a.run_startup(startup_a)
        la = [float(exe_a.run(prog_a, feed=f, fetch_list=[loss_a])[0]) for f in feeds]
    exe_b = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe_b.run_startup(startup_b)
        lb = [float(exe_b.run(prog_b, feed=f, fetch_list=[loss_b])[0]) for f in feeds]
    np.testing.assert_allclose(la, lb, rtol=1e-5, atol=1e-6)


def test_recompute_dropout_replays_same_mask():
    """Regression: the VJP re-lowering of a recompute segment must use the
    SAME dropout mask as the forward pass.  With w=1 and
    loss = sum(dropout(x) * w): sum(dw) == loss iff masks agree."""
    from paddle_tpu.fluid.initializer import ConstantInitializer
    from paddle_tpu.fluid.layer_helper import ParamAttr

    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data("x", [64], "float32")
        w_list = layers.fc(
            layers.reshape(x, [1, 64]), 64, bias_attr=False,
            param_attr=ParamAttr(initializer=ConstantInitializer(0.0)),
        )  # dummy route to make a trainable param; we use our own below
        h = layers.dropout(x, 0.5, dropout_implementation="upscale_in_train")
        helper_block = prog.global_block
        w = helper_block.create_parameter("w_direct", [64], "float32")
        sb = startup.global_block
        sb.create_parameter("w_direct", [64], "float32")
        sb.append_op(
            "fill_constant", outputs={"Out": ["w_direct"]},
            attrs={"shape": [64], "value": 1.0, "dtype": "float32"},
            infer=False,
        )
        prod = h * w
        loss = layers.reduce_sum(prod) + layers.reduce_sum(w_list) * 0.0
        opt = RecomputeOptimizer(SGDOptimizer(0.0))
        opt._set_checkpoints([prod])
        opt.minimize(loss, startup)
    types = [op.type for op in prog.global_block.ops]
    assert "recompute_segment" in types

    exe = fluid.Executor()
    rng = np.random.RandomState(7)
    feed = {"x": rng.randn(64).astype(np.float32)}
    from paddle_tpu.fluid.core import scope as scope_mod

    with fluid.scope_guard(fluid.Scope()):
        exe.run_startup(startup)
        lval, gw = exe.run(
            prog, feed=feed, fetch_list=[loss, "w_direct@GRAD"]
        )
    np.testing.assert_allclose(float(np.sum(gw)), float(lval), rtol=1e-5)


def test_gradient_merge_updates_every_k_steps():
    prog = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(prog, startup):
        x = fluid.data("x", [4, 3], "float32")
        y = fluid.data("y", [4, 1], "float32")
        pred = layers.fc(x, 1, bias_attr=False)
        loss = layers.reduce_mean(layers.square_error_cost(pred, y))
        opt = GradientMergeOptimizer(SGDOptimizer(0.1), k_steps=2, avg=True)
        opt.minimize(loss, startup)
        w_name = prog.global_block.all_parameters()[0].name

    exe = fluid.Executor()
    rng = np.random.RandomState(3)
    feed = {
        "x": rng.randn(4, 3).astype(np.float32),
        "y": rng.randn(4, 1).astype(np.float32),
    }
    from paddle_tpu.fluid.core.scope import global_scope

    with fluid.scope_guard(fluid.Scope()):
        exe.run_startup(startup)
        from paddle_tpu.fluid.core import scope as scope_mod

        w0 = np.asarray(scope_mod.global_scope().find_var(w_name)).copy()
        exe.run(prog, feed=feed, fetch_list=[loss])
        w1 = np.asarray(scope_mod.global_scope().find_var(w_name)).copy()
        exe.run(prog, feed=feed, fetch_list=[loss])
        w2 = np.asarray(scope_mod.global_scope().find_var(w_name)).copy()
    # step 1: accumulate only -> no param change; step 2: apply
    np.testing.assert_allclose(w0, w1, atol=1e-7)
    assert np.abs(w2 - w1).max() > 1e-6
