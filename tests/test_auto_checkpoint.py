"""incubate.checkpoint subsystem: atomic commits, CRC integrity,
async saves off the train step, auto-resume, multi-rank discipline.

Reference capability: `python/paddle/fluid/incubate/checkpoint/`
(auto_checkpoint.py, checkpoint_saver.py) + the crash-safety guarantees
of Orbax-style async checkpointing (snapshot-then-persist, commit by
rename)."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.fs import LocalFS
from paddle_tpu.incubate.checkpoint import (
    AsyncCheckpointSaver,
    CheckpointLoadError,
    CheckpointSaveError,
    CheckpointSaver,
    HostEmbeddingCheckpoint,
    StateSnapshot,
    TrainEpochRange,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "auto_ckpt_worker.py")


def _snap(**arrays):
    return StateSnapshot({k: np.asarray(v) for k, v in arrays.items()})


def _corrupt_payload(ckpt_dir):
    """Truncate the first payload file named in the meta manifest (the
    torn-write a preemption mid-flush leaves behind)."""
    with open(os.path.join(ckpt_dir, "meta.json")) as f:
        meta = json.load(f)
    fname = sorted(meta["files"])[0]
    path = os.path.join(ckpt_dir, fname)
    data = open(path, "rb").read()
    with open(path, "wb") as f:
        f.write(data[: max(len(data) // 2, 1)])
    return fname


# ---------------------------------------------------------------------------
# CheckpointSaver core
# ---------------------------------------------------------------------------


def test_atomic_commit_retention_and_meta(tmp_path):
    root = str(tmp_path / "ckpts")
    saver = CheckpointSaver(root=root, max_num_checkpoints=3)
    for e in range(5):
        n = saver.save_checkpoint(
            [_snap(w=np.full((4,), float(e)))], epoch=e,
            extra_meta={"program_hash": "h"})
        assert n == e
    dirs = sorted(os.listdir(root))
    # retention kept exactly the newest 3; no tmp dirs survive a commit
    assert dirs == ["checkpoint_2", "checkpoint_3", "checkpoint_4"]
    meta = json.load(open(os.path.join(root, "checkpoint_4", "meta.json")))
    assert meta["epoch"] == 4 and meta["program_hash"] == "h"
    rec = meta["files"]["payload.npz"]
    assert rec["size"] > 0 and 0 <= rec["crc32"] <= 0xFFFFFFFF
    assert saver.get_checkpoint_no() == 4

    out = StateSnapshot()
    m = saver.load_checkpoint([out])
    assert m["no"] == 4
    np.testing.assert_allclose(out.arrays["w"], 4.0)


def test_corrupt_checkpoint_skipped_and_all_corrupt_raises(tmp_path):
    root = str(tmp_path / "ckpts")
    saver = CheckpointSaver(root=root, max_num_checkpoints=5)
    saver.save_checkpoint([_snap(w=np.arange(3.0))], epoch=0)
    saver.save_checkpoint([_snap(w=np.arange(3.0) + 10)], epoch=1)
    _corrupt_payload(os.path.join(root, "checkpoint_1"))

    skips = []
    out = StateSnapshot()
    meta = saver.load_checkpoint(
        [out], on_skip=lambda n, why: skips.append((n, why)))
    # the torn newest was skipped, the previous COMMITTED one loads
    assert [n for n, _ in skips] == [1]
    assert meta["epoch"] == 0
    np.testing.assert_allclose(out.arrays["w"], np.arange(3.0))

    _corrupt_payload(os.path.join(root, "checkpoint_0"))
    with pytest.raises(CheckpointLoadError):
        saver.load_checkpoint([StateSnapshot()])


def test_crash_mid_save_leaves_no_visible_checkpoint(tmp_path):
    """A serialize() failure must not leave anything the load path (or
    a numbering scan) could mistake for a checkpoint."""
    root = str(tmp_path / "ckpts")
    saver = CheckpointSaver(root=root, max_num_checkpoints=3)

    class Boom(StateSnapshot):
        def serialize(self, path):
            super().serialize(path)
            raise IOError("disk gone")

    with pytest.raises(IOError):
        saver.save_checkpoint([Boom({"w": np.ones(2)})], epoch=0)
    assert saver.get_checkpoint_no() == -1
    assert saver.load_checkpoint([StateSnapshot()]) is None
    # stale tmp dirs from a hard crash are GC'd once old enough
    stale = os.path.join(root, ".tmp_checkpoint_9.dead")
    os.makedirs(stale)
    os.utime(stale, (time.time() - 7200, time.time() - 7200))
    saver.gc_stale_tmp()
    assert not os.path.exists(stale)


# ---------------------------------------------------------------------------
# Async path
# ---------------------------------------------------------------------------


class SlowFS(LocalFS):
    """LocalFS whose commit rename stalls — a slow remote mount."""

    def __init__(self, delay):
        self.delay = delay

    def mv(self, src, dst):
        time.sleep(self.delay)
        super().mv(src, dst)


class FailFS(LocalFS):
    def mv(self, src, dst):
        raise IOError("quota exceeded")


def test_async_save_keeps_train_step_off_the_write_path(tmp_path):
    """Acceptance: a step issued during an in-flight save must not block
    on FS I/O.  The fake FS stalls the commit 1.5s; the step (and the
    save_async call itself) complete orders of magnitude faster."""
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 4], append_batch_size=False)
        y = layers.fc(x, 4, param_attr="as.w", bias_attr="as.b")
        loss = layers.reduce_mean(layers.square(y))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)

    scope = fluid.Scope()
    exe = fluid.Executor()
    feed = {"x": np.ones((2, 4), np.float32)}
    delay = 1.5
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(main, feed=feed, fetch_list=[loss])  # compile outside timing

        saver = CheckpointSaver(root=str(tmp_path / "c"), fs=SlowFS(delay),
                                max_num_checkpoints=2)
        async_saver = AsyncCheckpointSaver(saver)
        snap = StateSnapshot.from_program(main, scope)

        t0 = time.perf_counter()
        async_saver.save_async([snap], epoch=0)
        t_issue = time.perf_counter() - t0
        assert t_issue < delay / 3, t_issue  # snapshot only, no FS wait

        t0 = time.perf_counter()
        exe.run(main, feed=feed, fetch_list=[loss])
        t_step = time.perf_counter() - t0
        assert t_step < delay / 3, t_step
        assert async_saver.in_flight  # the save really was concurrent

        n = async_saver.wait()
        assert n == 0 and saver.get_checkpoint_no() == 0


def test_async_error_surfaces_on_next_save_or_wait(tmp_path):
    saver = CheckpointSaver(root=str(tmp_path / "c"), fs=FailFS(),
                            max_num_checkpoints=2)
    a = AsyncCheckpointSaver(saver)
    a.save_async([_snap(w=np.ones(2))], epoch=0)
    with pytest.raises(CheckpointSaveError, match="quota"):
        a.wait()
    # error is consumed once, not sticky
    a.wait()
    a.save_async([_snap(w=np.ones(2))], epoch=1)
    with pytest.raises(CheckpointSaveError):
        a.save_async([_snap(w=np.ones(2))], epoch=2)


def test_async_snapshot_isolated_from_mutation(tmp_path):
    """The snapshot is taken at save_async time: mutating the source
    arrays afterwards must not leak into the committed payload."""
    w = np.zeros(4)
    scope = fluid.Scope()
    scope.set("w", w)
    saver = CheckpointSaver(root=str(tmp_path / "c"), fs=SlowFS(0.3),
                            max_num_checkpoints=2)
    a = AsyncCheckpointSaver(saver)
    a.save_async([StateSnapshot.from_scope(scope, ["w"])], epoch=0)
    scope.set("w", np.full(4, 9.0))      # train step mutates state
    a.wait()
    out = StateSnapshot()
    saver.load_checkpoint([out])
    np.testing.assert_allclose(out.arrays["w"], 0.0)


# ---------------------------------------------------------------------------
# Multi-rank discipline & host-embedding shards
# ---------------------------------------------------------------------------


def test_rank0_commits_other_ranks_barrier(tmp_path):
    from paddle_tpu.distributed.monitor import BarrierMonitor

    root = str(tmp_path / "shared_ckpt")
    bws = str(tmp_path / "barriers")
    results = {}

    def run_rank(rank):
        barrier = BarrierMonitor(bws, worker_id=rank, worker_num=2,
                                 timeout_s=30.0)
        saver = CheckpointSaver(root=root, max_num_checkpoints=2,
                                trainer_id=rank, num_trainers=2,
                                barrier=barrier)
        snap = StateSnapshot({"shard%d" % rank: np.full(3, float(rank))},
                             filename="shard_rank%d.npz" % rank)
        results[rank] = saver.save_checkpoint([snap], epoch=0)

    threads = [threading.Thread(target=run_rank, args=(r,))
               for r in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert results == {0: 0, 1: 0}
    d = os.path.join(root, "checkpoint_0")
    meta = json.load(open(os.path.join(d, "meta.json")))
    # rank 0 merged BOTH ranks' manifests before the single commit
    assert set(meta["files"]) == {"shard_rank0.npz", "shard_rank1.npz"}
    assert os.path.exists(os.path.join(d, "shard_rank1.npz"))
    # and the commit is valid end-to-end
    out = StateSnapshot(filename="shard_rank1.npz")
    CheckpointSaver(root=root, max_num_checkpoints=2).load_checkpoint([out])
    np.testing.assert_allclose(out.arrays["shard1"], 1.0)


def test_host_embedding_saves_sharded_per_rank(tmp_path):
    from paddle_tpu.fluid.host_embedding import HostEmbedding

    table = HostEmbedding("emb", num_rows=32, dim=4, seed=1)
    before = table._rows.copy()
    saver = CheckpointSaver(root=str(tmp_path / "c"), max_num_checkpoints=2)
    saver.save_checkpoint([HostEmbeddingCheckpoint([table])], epoch=0)
    d = os.path.join(str(tmp_path / "c"), "checkpoint_0")
    assert os.path.exists(os.path.join(d, "hostemb_emb_rank0.npz"))

    table._rows[:] = 0.0
    saver.load_checkpoint([HostEmbeddingCheckpoint([table])])
    np.testing.assert_allclose(table._rows, before)


# ---------------------------------------------------------------------------
# train_epoch_range / auto-resume
# ---------------------------------------------------------------------------


def _build_linreg(seed=5):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 6], append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], append_batch_size=False)
        pred = layers.fc(x, 1, param_attr="tr.w", bias_attr="tr.b")
        loss = layers.reduce_mean(layers.square(pred - y))
        fluid.optimizer.SGDOptimizer(0.05).minimize(loss)
    return main, startup, loss


def test_train_epoch_range_without_dir_is_plain_range():
    main, startup, _ = _build_linreg()
    scope = fluid.Scope()
    exe = fluid.Executor()
    os.environ.pop("PADDLE_TPU_CHECKPOINT_DIR", None)
    with fluid.scope_guard(scope):
        exe.run(startup)
        from paddle_tpu.incubate.checkpoint import train_epoch_range

        assert list(train_epoch_range(4, main_program=main)) == [0, 1, 2, 3]


def test_train_epoch_range_resumes_and_keys_by_program_hash(tmp_path):
    ws = str(tmp_path)
    main, startup, loss = _build_linreg()
    rng = np.random.RandomState(0)
    xs = rng.randn(8, 6).astype(np.float32)
    ys = (xs @ rng.randn(6, 1)).astype(np.float32)

    scope = fluid.Scope()
    exe = fluid.Executor()
    with fluid.scope_guard(scope):
        exe.run(startup)
        tr = TrainEpochRange(3, checkpoint_dir=ws, main_program=main,
                             async_save=False)
        seen = []
        for e in tr:
            seen.append(e)
            exe.run(main, feed={"x": xs, "y": ys}, fetch_list=[loss])
        assert seen == [0, 1, 2]
        w_end = np.asarray(scope.find_var("tr.w")).copy()

    # same program, fresh process state: silently fast-forwards past the
    # completed epochs and restores the trained weights
    scope2 = fluid.Scope()
    with fluid.scope_guard(scope2):
        exe.run(startup)
        tr2 = TrainEpochRange(3, checkpoint_dir=ws, main_program=main,
                              async_save=False)
        assert tr2.restored_from == 2 and tr2.start_epoch == 3
        assert list(tr2) == []
        np.testing.assert_allclose(
            np.asarray(scope2.find_var("tr.w")), w_end)

    # a DIFFERENT program hashes to a different key: no false resume
    main_b, startup_b, _ = _build_linreg(seed=6)
    with fluid.program_guard(main_b, startup_b):
        extra = layers.fc(layers.data("x2", shape=[-1, 2],
                                      append_batch_size=False), 2)
        del extra
    scope3 = fluid.Scope()
    with fluid.scope_guard(scope3):
        exe.run(startup_b)
        tr3 = TrainEpochRange(3, checkpoint_dir=ws, main_program=main_b,
                              async_save=False)
        assert tr3.restored_from == -1 and tr3.start_epoch == 0
        assert tr3.name != tr2.name


def _run_worker(ws, result, kill_epoch=-1, epochs=6):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ACP_WORKSPACE"] = ws
    env["ACP_EPOCHS"] = str(epochs)
    env["ACP_KILL_EPOCH"] = str(kill_epoch)
    env["ACP_RESULT"] = result
    return subprocess.run([sys.executable, WORKER], env=env, timeout=300,
                          capture_output=True, text=True)


def test_sigkill_and_restart_resumes_from_last_committed(tmp_path):
    """Acceptance drill: SIGKILL a run mid-epoch, corrupt the newest
    checkpoint on top (the partial the preemption could have left),
    restart — the job resumes from the last COMMITTED checkpoint and
    reaches the exact final loss of an uninterrupted control run."""
    control_ws = str(tmp_path / "control")
    control_res = str(tmp_path / "control.json")
    p = _run_worker(control_ws, control_res)
    assert p.returncode == 0, p.stderr
    control = json.load(open(control_res))
    assert control["restored_from"] == -1

    ws = str(tmp_path / "faulted")
    res = str(tmp_path / "faulted.json")
    p = _run_worker(ws, res, kill_epoch=4)
    assert p.returncode != 0          # SIGKILL'd itself mid-epoch 4
    assert not os.path.exists(res)    # died before any result

    # the committed checkpoints survived the kill; wound the newest one
    # to stand in for a torn in-flight write
    (key,) = os.listdir(ws)
    root = os.path.join(ws, key)
    ckpts = sorted((d for d in os.listdir(root)
                    if d.startswith("checkpoint_")),
                   key=lambda d: int(d.rsplit("_", 1)[1]))
    assert ckpts, "no checkpoint committed before the kill"
    corrupt_dir = os.path.join(root, ckpts[-1])
    _corrupt_payload(corrupt_dir)

    p = _run_worker(ws, res)
    assert p.returncode == 0, p.stderr
    out = json.load(open(res))
    # resumed from a COMMITTED checkpoint (the corrupt one was skipped)
    assert out["restored_from"] >= 0
    assert out["start_epoch"] == out["restored_from"] + 1
    assert "skipping" in p.stderr
    # and the resumed trajectory is bit-for-bit the control's tail
    np.testing.assert_allclose(out["final_loss"], control["final_loss"],
                               rtol=1e-6)
    np.testing.assert_allclose(out["final_w"], control["final_w"],
                               rtol=1e-6)
    n = len(out["losses"])
    np.testing.assert_allclose(out["losses"], control["losses"][-n:],
                               rtol=1e-5)


# ---------------------------------------------------------------------------
# hapi ModelCheckpoint wiring
# ---------------------------------------------------------------------------


class _FakeModel:
    def __init__(self, w):
        self.w = {"w": np.asarray(w)}

    def get_weights(self):
        return {k: v.copy() for k, v in self.w.items()}

    def set_weights(self, weights):
        self.w = {k: np.asarray(v) for k, v in weights.items()}


def test_hapi_model_checkpoint_async_and_load_latest(tmp_path):
    from paddle_tpu.hapi.callbacks import ModelCheckpoint

    m = _FakeModel(np.zeros(3))
    mc = ModelCheckpoint(save_dir=str(tmp_path / "mc"),
                         max_num_checkpoints=2, async_save=True)
    mc.set_model(m)
    for epoch in range(4):
        m.w["w"] = m.w["w"] + 1.0
        mc.on_epoch_end(epoch)
    mc.on_train_end()
    # retention held and commits are atomic checkpoint_<n> dirs
    dirs = sorted(os.listdir(str(tmp_path / "mc")))
    assert dirs == ["checkpoint_2", "checkpoint_3"]

    m2 = _FakeModel(np.zeros(3))
    meta = ModelCheckpoint(save_dir=str(tmp_path / "mc"),
                           max_num_checkpoints=2).load_latest(m2)
    assert meta["epoch"] == 3
    np.testing.assert_allclose(m2.w["w"], 4.0)

def test_refuses_to_overwrite_committed_checkpoint(tmp_path):
    """shutil.move onto an existing dir would NEST the tmp inside it and
    report success; the saver must refuse instead (review fix)."""
    saver = CheckpointSaver(root=str(tmp_path / "c"), max_num_checkpoints=3)
    saver.save_checkpoint([_snap(w=np.zeros(2))], epoch=0)
    with pytest.raises(CheckpointSaveError, match="refusing"):
        saver.save_checkpoint([_snap(w=np.ones(2))], epoch=9, no=0)
    out = StateSnapshot()
    assert saver.load_checkpoint([out])["epoch"] == 0  # intact
    np.testing.assert_allclose(out.arrays["w"], 0.0)


def test_multirank_save_retry_reuses_barrier_ids(tmp_path):
    """A failed collective save leaves residue (barrier markers, the
    attempt pointer, tmp payloads) for checkpoint number n; a retry
    reusing n must neither wedge on 'barrier id already used' nor merge
    the dead attempt's files (review fix: per-attempt tokens scoping
    the tmp dir + barrier tags, withdrawn on failure)."""
    from paddle_tpu.distributed.monitor import BarrierMonitor

    root = str(tmp_path / "shared")
    bws = str(tmp_path / "b")

    class Boom(StateSnapshot):
        def serialize(self, path):
            raise IOError("rank 1 disk error")

    def make(rank):
        return CheckpointSaver(
            root=root, max_num_checkpoints=2, trainer_id=rank,
            num_trainers=2,
            barrier=BarrierMonitor(bws, worker_id=rank, worker_num=2,
                                   timeout_s=3.0))

    def attempt(rank, slist, errs, results):
        try:
            results[rank] = make(rank).save_checkpoint(slist, epoch=0)
        except BaseException as e:
            errs[rank] = e

    # attempt 1: rank 1 dies serializing; rank 0 times out on the barrier
    errs, results = {}, {}
    ts = [threading.Thread(target=attempt, args=(
        r, [Boom({}) if r == 1 else StateSnapshot(
            {"a": np.zeros(2)}, filename="shard_rank0.npz")],
        errs, results)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert 0 in errs and 1 in errs          # both attempts failed loudly

    # attempt 2: same checkpoint number, same barrier ids — must succeed
    errs, results = {}, {}
    ts = [threading.Thread(target=attempt, args=(
        r, [StateSnapshot({"a%d" % r: np.full(2, float(r))},
                          filename="shard_rank%d.npz" % r)],
        errs, results)) for r in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert errs == {}, errs
    assert results == {0: 0, 1: 0}
    meta = json.load(open(os.path.join(root, "checkpoint_0", "meta.json")))
    assert set(meta["files"]) == {"shard_rank0.npz", "shard_rank1.npz"}
