"""Mini OpTest harness: numpy-oracle outputs + finite-difference gradients.

Capability parity: reference `tests/unittests/op_test.py` (OpTest:170 —
builds a one-op program from inputs/attrs, checks outputs vs numpy and
analytic grads vs numeric finite differences).
"""

import numpy as np

import paddle_tpu.fluid as fluid


def run_single_op(op_type, inputs, attrs, out_slots, grad_of=None):
    """Build a one-op program; return (outputs dict, grads dict or None).

    inputs: {slot: np.ndarray or [np.ndarray]}.
    grad_of: list of (slot, idx) input entries to return gradients for; the
    loss is sum(first output).
    """
    main = fluid.Program()
    startup = fluid.Program()
    feed = {}
    with fluid.program_guard(main, startup):
        in_names = {}
        for slot, arrs in inputs.items():
            arrs = arrs if isinstance(arrs, (list, tuple)) else [arrs]
            names = []
            for i, a in enumerate(arrs):
                a = np.asarray(a)
                name = "%s_%d" % (slot.lower(), i)
                v = fluid.layers.data(
                    name, shape=list(a.shape), dtype=str(a.dtype),
                    append_batch_size=False,
                )
                v.stop_gradient = False
                names.append(name)
                feed[name] = a
            in_names[slot] = names
        block = main.global_block
        out_names = {s: ["out_%s" % s.lower()] for s in out_slots}
        block.append_op(op_type, inputs=in_names, outputs=out_names, attrs=attrs)

        fetch = [out_names[s][0] for s in out_slots]
        grad_fetch = []
        if grad_of:
            first_out = block.var(out_names[out_slots[0]][0])
            loss = fluid.layers.reduce_sum(first_out)
            fluid.append_backward(loss, parameter_list=[])
            for slot, idx in grad_of:
                grad_fetch.append(in_names[slot][idx] + "@GRAD")

    exe = fluid.Executor(fluid.CPUPlace())
    res = exe.run(main, feed=feed, fetch_list=fetch + grad_fetch)
    outs = dict(zip(out_slots, res[: len(fetch)]))
    grads = dict(zip(grad_fetch, res[len(fetch) :])) if grad_of else None
    return outs, grads


def numeric_grad(op_type, inputs, attrs, out_slots, slot, idx, delta=5e-3):
    """Central finite difference of sum(first output) w.r.t. inputs[slot][idx]."""

    def loss_of(feed_inputs):
        outs, _ = run_single_op(op_type, feed_inputs, attrs, out_slots)
        return float(np.sum(outs[out_slots[0]]))

    base = {
        s: [np.asarray(a).copy() for a in (v if isinstance(v, (list, tuple)) else [v])]
        for s, v in inputs.items()
    }
    x = base[slot][idx]
    g = np.zeros_like(x, dtype=np.float64)
    flat = x.reshape(-1)
    gf = g.reshape(-1)
    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + delta
        lp = loss_of(base)
        flat[i] = orig - delta
        lm = loss_of(base)
        flat[i] = orig
        gf[i] = (lp - lm) / (2 * delta)
    return g


def check_output(op_type, inputs, attrs, expected, rtol=1e-5, atol=1e-6):
    outs, _ = run_single_op(op_type, inputs, attrs, list(expected))
    for slot, exp in expected.items():
        np.testing.assert_allclose(
            outs[slot], exp, rtol=rtol, atol=atol,
            err_msg="op %s output slot %s mismatch" % (op_type, slot),
        )
    return outs


def check_grad(op_type, inputs, attrs, out_slots, grad_slots, rtol=5e-3, atol=1e-4,
               delta=5e-3):
    grad_of = [(s, 0) for s in grad_slots]
    _, grads = run_single_op(op_type, inputs, attrs, out_slots, grad_of=grad_of)
    for slot in grad_slots:
        analytic = grads["%s_0@GRAD" % slot.lower()]
        numeric = numeric_grad(op_type, inputs, attrs, out_slots, slot, 0, delta)
        np.testing.assert_allclose(
            analytic, numeric, rtol=rtol, atol=atol,
            err_msg="op %s grad w.r.t. %s mismatch" % (op_type, slot),
        )
