"""Dygraph-to-static AST transformer (reference
`dygraph_to_static/ast_transformer.py:1`, `program_translator.py:1`):
data-dependent Python if/while/for/break must become cond / while_loop ops
in the captured program — ONE cached program whose branch is decided at
RUN time, not trace time."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import dygraph, layers
from paddle_tpu.fluid.dygraph import declarative, to_variable


def _collect_op_types(traced):
    return [op.type for op in traced.program.global_block.ops]


def test_data_dependent_if_becomes_cond():
    @declarative
    def f(x):
        s = layers.reduce_sum(x)
        if s > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    with dygraph.guard():
        pos = np.ones((2, 3), np.float32)
        neg = -np.ones((2, 3), np.float32)
        out_pos = f(to_variable(pos))
        out_neg = f(to_variable(neg))

    # ONE cached program serves both inputs (same spec)…
    assert len(f.program_cache) == 1
    traced = next(iter(f.program_cache.values()))
    # …and it contains a real cond op, not a baked branch
    assert "cond" in _collect_op_types(traced)
    # branch is decided at RUN time
    np.testing.assert_allclose(np.asarray(out_pos.data), pos * 2.0)
    np.testing.assert_allclose(np.asarray(out_neg.data), neg - 1.0)


def test_if_branch_only_assignment_with_prior_value():
    @declarative
    def f(x):
        y = x * 0.5
        if layers.reduce_sum(x) > 0:
            y = y + 10.0
        return y

    with dygraph.guard():
        pos = np.ones((2, 2), np.float32)
        neg = -np.ones((2, 2), np.float32)
        np.testing.assert_allclose(
            np.asarray(f(to_variable(pos)).data), pos * 0.5 + 10.0
        )
        np.testing.assert_allclose(
            np.asarray(f(to_variable(neg)).data), neg * 0.5
        )
    traced = next(iter(f.program_cache.values()))
    assert "cond" in _collect_op_types(traced)


def test_data_dependent_while_becomes_while_loop():
    @declarative
    def f(n):
        i = layers.fill_constant([1], "float32", 0.0)
        s = layers.fill_constant([1], "float32", 0.0)
        while i < n:
            s = s + i
            i = i + 1.0
        return s

    with dygraph.guard():
        out5 = f(to_variable(np.array([5.0], np.float32)))
        out3 = f(to_variable(np.array([3.0], np.float32)))
    assert len(f.program_cache) == 1
    traced = next(iter(f.program_cache.values()))
    assert "while_loop_op" in _collect_op_types(traced)
    assert float(np.asarray(out5.data)) == pytest.approx(10.0)  # 0+1+2+3+4
    assert float(np.asarray(out3.data)) == pytest.approx(3.0)   # 0+1+2


def test_for_range_with_break():
    @declarative
    def f(limit):
        s = layers.fill_constant([1], "float32", 0.0)
        t = layers.fill_constant([1], "float32", 0.0)
        for i in range(6):
            t = t + 1.0
            if s > limit:
                break
            s = s + 10.0
        return s, t

    with dygraph.guard():
        s, t = f(to_variable(np.array([15.0], np.float32)))
        # iter1: t=1, s=10; iter2: t=2, s=20; iter3: t=3, break (s>15)
        assert float(np.asarray(s.data)) == pytest.approx(20.0)
        assert float(np.asarray(t.data)) == pytest.approx(3.0)
        s2, t2 = f(to_variable(np.array([1000.0], np.float32)))
        assert float(np.asarray(s2.data)) == pytest.approx(60.0)
        assert float(np.asarray(t2.data)) == pytest.approx(6.0)
    assert len(f.program_cache) == 1
    traced = next(iter(f.program_cache.values()))
    assert "while_loop_op" in _collect_op_types(traced)


def test_python_control_flow_still_unrolls():
    # non-tensor conditions keep Python semantics (trace-time unrolling)
    @declarative
    def f(x, flag=True):
        acc = x
        for _ in range(3):
            acc = acc + 1.0
        if acc is not None and flag:
            acc = acc * 2.0
        return acc

    with dygraph.guard():
        out = f(to_variable(np.zeros((2,), np.float32)))
        np.testing.assert_allclose(np.asarray(out.data), [6.0, 6.0])
    traced = next(iter(f.program_cache.values()))
    types = _collect_op_types(traced)
    assert "while_loop_op" not in types and "cond" not in types


def test_logical_ops_in_tensor_condition():
    @declarative
    def f(x):
        a = layers.reduce_sum(x)
        if (a > 0.0) and (a < 10.0):
            y = x + 1.0
        else:
            y = x - 1.0
        return y

    with dygraph.guard():
        small = np.full((2,), 1.0, np.float32)   # sum=2 in (0,10) -> +1
        big = np.full((2,), 50.0, np.float32)    # sum=100 -> -1
        np.testing.assert_allclose(np.asarray(f(to_variable(small)).data),
                                   small + 1.0)
        np.testing.assert_allclose(np.asarray(f(to_variable(big)).data),
                                   big - 1.0)


def test_undefined_in_one_branch_raises():
    @declarative
    def f(x):
        if layers.reduce_sum(x) > 0:
            z = x * 2.0
        return z  # z undefined when the false branch runs

    with dygraph.guard():
        with pytest.raises((TypeError, NameError, RuntimeError)):
            f(to_variable(np.ones((2,), np.float32)))


def test_declarative_method_on_layer():
    class Net(dygraph.Layer):
        def __init__(self):
            super().__init__()
            self.fc = dygraph.Linear(4, 4)

        @declarative
        def forward(self, x):
            h = self.fc(x)
            if layers.reduce_sum(h) > 0:
                h = h * 2.0
            else:
                h = h * 0.5
            return h

    with dygraph.guard():
        net = Net()
        x = np.ones((2, 4), np.float32)
        out = net(to_variable(x))
        assert out.shape == (2, 4)
    # rewritten source is exposed for debugging (reference .code parity)
    assert "convert_ifelse" in Net.forward.code


def test_tensor_elif_chain():
    @declarative
    def f(x):
        s = layers.reduce_sum(x)
        if s > 10.0:
            y = x + 100.0
        elif s > 0.0:
            y = x + 10.0
        else:
            y = x - 1.0
        return y

    with dygraph.guard():
        big = np.full((4,), 5.0, np.float32)    # sum 20 -> +100
        mid = np.full((4,), 0.5, np.float32)    # sum 2  -> +10
        neg = np.full((4,), -1.0, np.float32)   # sum -4 -> -1
        np.testing.assert_allclose(np.asarray(f(to_variable(big)).data), big + 100.0)
        np.testing.assert_allclose(np.asarray(f(to_variable(mid)).data), mid + 10.0)
        np.testing.assert_allclose(np.asarray(f(to_variable(neg)).data), neg - 1.0)


def test_python_short_circuit_guard_preserved():
    @declarative
    def f(x, cfg=None):
        if cfg is not None and cfg["scale"] > 1:
            x = x * float(cfg["scale"])
        return x

    with dygraph.guard():
        out = f(to_variable(np.ones((2,), np.float32)))  # cfg None: no crash
        np.testing.assert_allclose(np.asarray(out.data), [1.0, 1.0])


def test_negative_step_range():
    @declarative
    def f(x):
        s = x
        for i in range(3, 0, -1):
            s = s + float(i)
        return s

    with dygraph.guard():
        out = f(to_variable(np.zeros((1,), np.float32)))
        assert float(np.asarray(out.data)[0]) == pytest.approx(6.0)  # 3+2+1


def test_loop_var_value_after_loop():
    @declarative
    def f(x):
        for i in range(3):
            x = x + 1.0
        return x + i  # python leaves i == 2

    with dygraph.guard():
        out = f(to_variable(np.zeros((1,), np.float32)))
        assert float(np.asarray(out.data)[0]) == pytest.approx(5.0)  # 3 + 2


def test_break_in_python_iterable_loop_keeps_python_semantics():
    @declarative
    def f(x):
        total = x
        for item in [1.0, 2.0, 3.0]:
            total = total + item
            if item >= 2.0:
                break
        return total

    with dygraph.guard():
        out = f(to_variable(np.zeros((1,), np.float32)))
        assert float(np.asarray(out.data)[0]) == pytest.approx(3.0)  # 1+2


# ---------------------------------------------------------------------------
# round-4 transformers: print / cast / len / assert / shape / list / call
# (reference dygraph_to_static print/cast/assert/tensor_shape/list/call
# transformers)
# ---------------------------------------------------------------------------


def test_cast_and_len_on_tensors():
    @declarative
    def f(x):
        n = len(x)              # static dim -> python int
        z = int(x)              # tensor -> cast to int64 (truncating)
        return float(z) + float(n)

    with dygraph.guard():
        xv = to_variable(np.full((4, 2), 2.7, np.float32))
        out = f(xv)
        # int(2.7) -> 2 per element; + len 4 => 6.0
        assert float(np.asarray(out.data)[0, 0]) == pytest.approx(6.0)


def test_shape_attribute_converts():
    @declarative
    def f(x):
        h = x.shape[1]          # static -> python int usable in reshape
        return x * 0.0 + h

    with dygraph.guard():
        out = f(to_variable(np.zeros((2, 5), np.float32)))
        assert float(np.asarray(out.data)[0, 0]) == pytest.approx(5.0)


def test_call_transformer_converts_helper_control_flow():
    def helper(y):
        s = layers.reduce_sum(y)
        if s > 0:               # tensor condition inside a CALLED fn
            out = y + 1.0
        else:
            out = y - 1.0
        return out

    @declarative
    def f(x):
        return helper(x)

    with dygraph.guard():
        up = f(to_variable(np.full((2,), 3.0, np.float32)))
        dn = f(to_variable(np.full((2,), -3.0, np.float32)))
        assert float(np.asarray(up.data)[0]) == pytest.approx(4.0)
        assert float(np.asarray(dn.data)[0]) == pytest.approx(-4.0)


def test_list_append_in_tensor_loop():
    @declarative
    def f(x):
        out = []
        for item in [1.0, 2.0, 3.0]:   # python loop: list stays a list
            out.append(x + item)
        return out[0] + out[1] + out[2]

    with dygraph.guard():
        got = f(to_variable(np.zeros((1,), np.float32)))
        assert float(np.asarray(got.data)[0]) == pytest.approx(6.0)


def test_print_and_assert_convert(capsys):
    @declarative
    def f(x):
        print("value is", x)
        s = layers.reduce_sum(x)
        assert s > -1e9, "must hold"
        return x + 1.0

    with dygraph.guard():
        out = f(to_variable(np.ones((2,), np.float32)))
        assert float(np.asarray(out.data)[0]) == pytest.approx(2.0)


def test_per_signature_program_cache():
    calls = {"n": 0}

    def helper(y):
        calls["n"] += 1
        return y * 2.0

    @declarative
    def f(x):
        return helper(x)

    with dygraph.guard():
        a = np.ones((2, 3), np.float32)
        b = np.ones((4, 3), np.float32)
        f(to_variable(a))
        n_after_first = calls["n"]
        f(to_variable(a))              # same signature: cached program
        assert calls["n"] == n_after_first
        f(to_variable(b))              # new shape: retrace
        assert calls["n"] > n_after_first
        assert len(f.program_cache) == 2


def test_convert_call_distinct_closures_and_methods():
    """Distinct closures of one def transform independently; Layer-method
    helpers with tensor control flow convert too (review regressions)."""
    def make_adder(k):
        def add(y):
            s = layers.reduce_sum(y)
            if s > -1e9:
                out = y + k
            else:
                out = y
            return out
        return add

    a1, a2 = make_adder(1.0), make_adder(2.0)

    @declarative
    def f(x):
        return a2(a1(x))

    with dygraph.guard():
        out = f(to_variable(np.zeros((2,), np.float32)))
        assert float(np.asarray(out.data)[0]) == pytest.approx(3.0)

    @declarative
    def g(x):
        acc = []
        alias = acc
        for v in [1.0, 2.0]:
            acc.append(x + v)
        return alias[0] + alias[1]   # aliasing preserved (in-place append)

    with dygraph.guard():
        out = g(to_variable(np.zeros((1,), np.float32)))
        assert float(np.asarray(out.data)[0]) == pytest.approx(3.0)


# --- round-5: early return (reference return_transformer.py patterns) -------


def test_early_return_under_tensor_if():
    """reference test_return.py test_return_if: a data-dependent early
    return becomes a cond output, ONE cached program serves both paths."""
    @declarative
    def f(x):
        if layers.reduce_sum(x) > 0:
            return x * 2.0
        return x - 1.0

    with dygraph.guard():
        pos = np.ones((2, 3), np.float32)
        neg = -np.ones((2, 3), np.float32)
        np.testing.assert_allclose(np.asarray(f(to_variable(pos)).data),
                                   pos * 2.0)
        np.testing.assert_allclose(np.asarray(f(to_variable(neg)).data),
                                   neg - 1.0)
    assert len(f.program_cache) == 1
    traced = next(iter(f.program_cache.values()))
    assert "cond" in _collect_op_types(traced)


def test_early_return_skips_downstream_statements():
    """reference test_return.py test_return_in_if: code after the taken
    return must not affect the result."""
    @declarative
    def f(x):
        y = x * 1.0
        if layers.reduce_sum(x) > 0:
            return y + 100.0
        y = y * 3.0
        return y

    with dygraph.guard():
        pos = np.ones((2, 2), np.float32)
        neg = -np.ones((2, 2), np.float32)
        np.testing.assert_allclose(np.asarray(f(to_variable(pos)).data),
                                   pos + 100.0)
        np.testing.assert_allclose(np.asarray(f(to_variable(neg)).data),
                                   neg * 3.0)


def test_early_return_elif_chain():
    """reference test_return.py test_return_if_elif_else pattern."""
    @declarative
    def f(x):
        s = layers.reduce_sum(x)
        if s > 10.0:
            return x * 4.0
        elif s > 0:
            return x * 2.0
        return x * 0.5

    with dygraph.guard():
        big = np.full((2, 3), 10.0, np.float32)
        small = np.ones((2, 3), np.float32)
        neg = -np.ones((2, 3), np.float32)
        np.testing.assert_allclose(np.asarray(f(to_variable(big)).data),
                                   big * 4.0)
        np.testing.assert_allclose(np.asarray(f(to_variable(small)).data),
                                   small * 2.0)
        np.testing.assert_allclose(np.asarray(f(to_variable(neg)).data),
                                   neg * 0.5)
    assert len(f.program_cache) == 1


def test_early_return_inside_tensor_loop():
    """reference test_return.py test_return_in_while: return inside a
    converted loop breaks the loop and carries the value out; the
    post-loop dispatch evaluates the return expression from the
    loop-carried state at break time."""
    @declarative
    def f(x):
        while layers.reduce_sum(x) < 6.0:
            x = x + 1.0
            if layers.reduce_sum(x) > 4.0:
                return x * 10.0
        return x

    with dygraph.guard():
        out = f(to_variable(np.zeros((2,), np.float32)))
        # sum climbs 2 per iter; first sum > 4 is 6 at x = [3,3] -> *10
        np.testing.assert_allclose(np.asarray(out.data), [30.0, 30.0])
    traced = next(iter(f.program_cache.values()))
    assert "while_loop_op" in _collect_op_types(traced)


def test_early_return_in_python_range_loop_unrolls():
    """A python-range loop with a tensor-guarded return unrolls at trace
    time into per-iteration conds — correct values, static control
    flow."""
    @declarative
    def f(x):
        for _ in range(8):
            x = x + 1.0
            if layers.reduce_sum(x) > 6.0:
                return x * 10.0
        return x

    with dygraph.guard():
        out = f(to_variable(np.zeros((2,), np.float32)))
        # sum after k increments = 2k; 2k > 6 first at k = 4 -> [4,4]*10
        np.testing.assert_allclose(np.asarray(out.data), [40.0, 40.0])
    traced = next(iter(f.program_cache.values()))
    assert "cond" in _collect_op_types(traced)


def test_early_return_tuple_values():
    """reference test_return.py test_return_tuple pattern: structured
    returns merge across paths."""
    @declarative
    def f(x):
        if layers.reduce_sum(x) > 0:
            return x * 2.0, x + 1.0
        return x * 3.0, x - 1.0

    with dygraph.guard():
        pos = np.ones((2,), np.float32)
        neg = -np.ones((2,), np.float32)
        a, b = f(to_variable(pos))
        np.testing.assert_allclose(np.asarray(a.data), pos * 2.0)
        np.testing.assert_allclose(np.asarray(b.data), pos + 1.0)
        a, b = f(to_variable(neg))
        np.testing.assert_allclose(np.asarray(a.data), neg * 3.0)
        np.testing.assert_allclose(np.asarray(b.data), neg - 1.0)


def test_early_return_python_condition_stays_python():
    """A plain-Python early return keeps trace-time semantics (two cache
    entries NOT needed — the flag guard folds at trace time)."""
    @declarative
    def f(x, flag):
        if flag:                       # python bool, trace-time
            return x + 10.0
        return x

    with dygraph.guard():
        x = np.ones((2,), np.float32)
        np.testing.assert_allclose(
            np.asarray(f(to_variable(x), True).data), x + 10.0)
        np.testing.assert_allclose(
            np.asarray(f(to_variable(x), False).data), x)


def test_nested_closure_with_early_return():
    """reference test_closure_analysis / convert_call pattern: a nested
    def closing over an enclosing local converts recursively, including
    ITS early return."""
    @declarative
    def f(x):
        scale = 3.0

        def inner(v):
            if layers.reduce_sum(v) > 0:
                return v * scale
            return v - scale

        return inner(x) + 1.0

    with dygraph.guard():
        pos = np.ones((2,), np.float32)
        neg = -np.ones((2,), np.float32)
        np.testing.assert_allclose(np.asarray(f(to_variable(pos)).data),
                                   pos * 3.0 + 1.0)
        np.testing.assert_allclose(np.asarray(f(to_variable(neg)).data),
                                   neg - 3.0 + 1.0)


def test_closure_mutation_of_enclosing_list():
    """reference test_closure_analysis pattern: a helper mutating an
    enclosing list (closure side effect) keeps Python semantics at trace
    time while tensor math still records ops."""
    @declarative
    def f(x):
        acc = []

        def push(v):
            acc.append(v * 2.0)

        push(x)
        push(x + 1.0)
        return acc[0] + acc[1]

    with dygraph.guard():
        x = np.ones((2,), np.float32)
        np.testing.assert_allclose(np.asarray(f(to_variable(x)).data),
                                   x * 2.0 + (x + 1.0) * 2.0)


def test_early_return_continuation_not_aliased():
    """Review r5: the continuation duplicated into both if-branches must
    be independent AST — a loop with break in the shared continuation
    still converts on every path."""
    def make(a, b):
        @declarative
        def f(x):
            if a:                      # python flags via closure snapshot
                if b:
                    return x * 2.0
            i = 0
            while i < 3:
                if i == 2:
                    break
                i = i + 1
            return x + float(i)
        return f

    with dygraph.guard():
        x = np.ones((2,), np.float32)
        np.testing.assert_allclose(
            np.asarray(make(False, False)(to_variable(x)).data), x + 2.0)
        np.testing.assert_allclose(
            np.asarray(make(True, True)(to_variable(x)).data), x * 2.0)
        np.testing.assert_allclose(
            np.asarray(make(True, False)(to_variable(x)).data), x + 2.0)


def test_early_return_with_statement_falls_back_cleanly():
    """Review r5: a `return` under `with` falls back to the PRISTINE
    function (python semantics), not a half-rewritten one."""
    import contextlib

    def make(flag):
        @declarative
        def g(x):
            if flag:                   # python flag via closure snapshot
                return x * 2.0
            with contextlib.nullcontext():
                return x + 1.0
        return g

    with dygraph.guard():
        x = np.ones((2,), np.float32)
        np.testing.assert_allclose(
            np.asarray(make(True)(to_variable(x)).data), x * 2.0)
        np.testing.assert_allclose(
            np.asarray(make(False)(to_variable(x)).data), x + 1.0)


def test_mixed_tuple_merges_across_tensor_branches():
    """Review r5: a (tensor, python scalar) tuple var assigned in both
    branches of a tensor `if` merges when structure and scalars agree."""
    @declarative
    def f(x):
        if layers.reduce_sum(x) > 0:
            pair = (x * 2.0, 5)
        else:
            pair = (x * 3.0, 5)
        return pair[0] * float(pair[1])

    with dygraph.guard():
        pos = np.ones((2,), np.float32)
        neg = -np.ones((2,), np.float32)
        np.testing.assert_allclose(np.asarray(f(to_variable(pos)).data),
                                   pos * 10.0)
        np.testing.assert_allclose(np.asarray(f(to_variable(neg)).data),
                                   neg * 15.0)
    traced = next(iter(f.program_cache.values()))
    assert "cond" in _collect_op_types(traced)


def test_nested_def_local_list_append_still_rewrites():
    """Review r5: a nested helper's OWN local list still gets the
    convert_append rewrite (only closed-over names keep real .append)."""
    @declarative
    def f(x):
        def tail_sums(v):
            acc = []
            for i in range(3):
                acc.append(layers.reduce_sum(v) + float(i))
            return acc[0] + acc[1] + acc[2]

        return tail_sums(x)

    with dygraph.guard():
        x = np.ones((2,), np.float32)
        out = f(to_variable(x))
        assert float(np.asarray(out.data)) == pytest.approx(2*3 + 0+1+2)


def test_deep_guard_chain_falls_back_not_hangs():
    """Review r5: many sequential guard clauses must not explode the
    continuation duplication — past the cap the function falls back to
    pristine tracing (python flags still work)."""
    import time

    def make(k):
        src_flags = ", ".join("f%d" % i for i in range(16))
        body = "\n".join(
            "    if f%d:\n        if f%d:\n            return x + %d.0"
            % (i, i, i) for i in range(16))
        code = ("def g(x, %s):\n%s\n    return x\n" % (src_flags, body))
        ns = {}
        exec(code, ns)
        return ns["g"]

    from paddle_tpu.fluid.dygraph.dygraph_to_static import (
        ast_transformer as at,
    )

    g = make(16)
    t0 = time.monotonic()
    new = at.transform_function(g)
    dt = time.monotonic() - t0
    assert dt < 10.0, "transform took %.1fs (blowup not capped)" % dt
    # fallback keeps python semantics
    fn = new if new is not None else g
    assert fn(1.0, *([False] * 16)) == 1.0
    args = [False] * 16
    args[3] = True
    assert fn(1.0, *args) == 4.0


def test_mixed_tuple_with_ndarray_element_merges():
    """Review r5: a shared non-scalar python element (ndarray) in a
    tuple slot must not crash the ambiguous-truth comparison."""
    meta = np.array([1.0, 2.0], np.float32)

    @declarative
    def f(x):
        if layers.reduce_sum(x) > 0:
            pair = (x * 2.0, meta)
        else:
            pair = (x * 3.0, meta)
        return pair[0] + float(pair[1][0])

    with dygraph.guard():
        pos = np.ones((2,), np.float32)
        neg = -np.ones((2,), np.float32)
        np.testing.assert_allclose(np.asarray(f(to_variable(pos)).data),
                                   pos * 2.0 + 1.0)
        np.testing.assert_allclose(np.asarray(f(to_variable(neg)).data),
                                   neg * 3.0 + 1.0)
