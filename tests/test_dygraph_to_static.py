"""Dygraph-to-static AST transformer (reference
`dygraph_to_static/ast_transformer.py:1`, `program_translator.py:1`):
data-dependent Python if/while/for/break must become cond / while_loop ops
in the captured program — ONE cached program whose branch is decided at
RUN time, not trace time."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import dygraph, layers
from paddle_tpu.fluid.dygraph import declarative, to_variable


def _collect_op_types(traced):
    return [op.type for op in traced.program.global_block.ops]


def test_data_dependent_if_becomes_cond():
    @declarative
    def f(x):
        s = layers.reduce_sum(x)
        if s > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    with dygraph.guard():
        pos = np.ones((2, 3), np.float32)
        neg = -np.ones((2, 3), np.float32)
        out_pos = f(to_variable(pos))
        out_neg = f(to_variable(neg))

    # ONE cached program serves both inputs (same spec)…
    assert len(f.program_cache) == 1
    traced = next(iter(f.program_cache.values()))
    # …and it contains a real cond op, not a baked branch
    assert "cond" in _collect_op_types(traced)
    # branch is decided at RUN time
    np.testing.assert_allclose(np.asarray(out_pos.data), pos * 2.0)
    np.testing.assert_allclose(np.asarray(out_neg.data), neg - 1.0)


def test_if_branch_only_assignment_with_prior_value():
    @declarative
    def f(x):
        y = x * 0.5
        if layers.reduce_sum(x) > 0:
            y = y + 10.0
        return y

    with dygraph.guard():
        pos = np.ones((2, 2), np.float32)
        neg = -np.ones((2, 2), np.float32)
        np.testing.assert_allclose(
            np.asarray(f(to_variable(pos)).data), pos * 0.5 + 10.0
        )
        np.testing.assert_allclose(
            np.asarray(f(to_variable(neg)).data), neg * 0.5
        )
    traced = next(iter(f.program_cache.values()))
    assert "cond" in _collect_op_types(traced)


def test_data_dependent_while_becomes_while_loop():
    @declarative
    def f(n):
        i = layers.fill_constant([1], "float32", 0.0)
        s = layers.fill_constant([1], "float32", 0.0)
        while i < n:
            s = s + i
            i = i + 1.0
        return s

    with dygraph.guard():
        out5 = f(to_variable(np.array([5.0], np.float32)))
        out3 = f(to_variable(np.array([3.0], np.float32)))
    assert len(f.program_cache) == 1
    traced = next(iter(f.program_cache.values()))
    assert "while_loop_op" in _collect_op_types(traced)
    assert float(np.asarray(out5.data)) == pytest.approx(10.0)  # 0+1+2+3+4
    assert float(np.asarray(out3.data)) == pytest.approx(3.0)   # 0+1+2


def test_for_range_with_break():
    @declarative
    def f(limit):
        s = layers.fill_constant([1], "float32", 0.0)
        t = layers.fill_constant([1], "float32", 0.0)
        for i in range(6):
            t = t + 1.0
            if s > limit:
                break
            s = s + 10.0
        return s, t

    with dygraph.guard():
        s, t = f(to_variable(np.array([15.0], np.float32)))
        # iter1: t=1, s=10; iter2: t=2, s=20; iter3: t=3, break (s>15)
        assert float(np.asarray(s.data)) == pytest.approx(20.0)
        assert float(np.asarray(t.data)) == pytest.approx(3.0)
        s2, t2 = f(to_variable(np.array([1000.0], np.float32)))
        assert float(np.asarray(s2.data)) == pytest.approx(60.0)
        assert float(np.asarray(t2.data)) == pytest.approx(6.0)
    assert len(f.program_cache) == 1
    traced = next(iter(f.program_cache.values()))
    assert "while_loop_op" in _collect_op_types(traced)


def test_python_control_flow_still_unrolls():
    # non-tensor conditions keep Python semantics (trace-time unrolling)
    @declarative
    def f(x, flag=True):
        acc = x
        for _ in range(3):
            acc = acc + 1.0
        if acc is not None and flag:
            acc = acc * 2.0
        return acc

    with dygraph.guard():
        out = f(to_variable(np.zeros((2,), np.float32)))
        np.testing.assert_allclose(np.asarray(out.data), [6.0, 6.0])
    traced = next(iter(f.program_cache.values()))
    types = _collect_op_types(traced)
    assert "while_loop_op" not in types and "cond" not in types


def test_logical_ops_in_tensor_condition():
    @declarative
    def f(x):
        a = layers.reduce_sum(x)
        if (a > 0.0) and (a < 10.0):
            y = x + 1.0
        else:
            y = x - 1.0
        return y

    with dygraph.guard():
        small = np.full((2,), 1.0, np.float32)   # sum=2 in (0,10) -> +1
        big = np.full((2,), 50.0, np.float32)    # sum=100 -> -1
        np.testing.assert_allclose(np.asarray(f(to_variable(small)).data),
                                   small + 1.0)
        np.testing.assert_allclose(np.asarray(f(to_variable(big)).data),
                                   big - 1.0)


def test_undefined_in_one_branch_raises():
    @declarative
    def f(x):
        if layers.reduce_sum(x) > 0:
            z = x * 2.0
        return z  # z undefined when the false branch runs

    with dygraph.guard():
        with pytest.raises((TypeError, NameError, RuntimeError)):
            f(to_variable(np.ones((2,), np.float32)))


def test_declarative_method_on_layer():
    class Net(dygraph.Layer):
        def __init__(self):
            super().__init__()
            self.fc = dygraph.Linear(4, 4)

        @declarative
        def forward(self, x):
            h = self.fc(x)
            if layers.reduce_sum(h) > 0:
                h = h * 2.0
            else:
                h = h * 0.5
            return h

    with dygraph.guard():
        net = Net()
        x = np.ones((2, 4), np.float32)
        out = net(to_variable(x))
        assert out.shape == (2, 4)
    # rewritten source is exposed for debugging (reference .code parity)
    assert "convert_ifelse" in Net.forward.code


def test_tensor_elif_chain():
    @declarative
    def f(x):
        s = layers.reduce_sum(x)
        if s > 10.0:
            y = x + 100.0
        elif s > 0.0:
            y = x + 10.0
        else:
            y = x - 1.0
        return y

    with dygraph.guard():
        big = np.full((4,), 5.0, np.float32)    # sum 20 -> +100
        mid = np.full((4,), 0.5, np.float32)    # sum 2  -> +10
        neg = np.full((4,), -1.0, np.float32)   # sum -4 -> -1
        np.testing.assert_allclose(np.asarray(f(to_variable(big)).data), big + 100.0)
        np.testing.assert_allclose(np.asarray(f(to_variable(mid)).data), mid + 10.0)
        np.testing.assert_allclose(np.asarray(f(to_variable(neg)).data), neg - 1.0)


def test_python_short_circuit_guard_preserved():
    @declarative
    def f(x, cfg=None):
        if cfg is not None and cfg["scale"] > 1:
            x = x * float(cfg["scale"])
        return x

    with dygraph.guard():
        out = f(to_variable(np.ones((2,), np.float32)))  # cfg None: no crash
        np.testing.assert_allclose(np.asarray(out.data), [1.0, 1.0])


def test_negative_step_range():
    @declarative
    def f(x):
        s = x
        for i in range(3, 0, -1):
            s = s + float(i)
        return s

    with dygraph.guard():
        out = f(to_variable(np.zeros((1,), np.float32)))
        assert float(np.asarray(out.data)[0]) == pytest.approx(6.0)  # 3+2+1


def test_loop_var_value_after_loop():
    @declarative
    def f(x):
        for i in range(3):
            x = x + 1.0
        return x + i  # python leaves i == 2

    with dygraph.guard():
        out = f(to_variable(np.zeros((1,), np.float32)))
        assert float(np.asarray(out.data)[0]) == pytest.approx(5.0)  # 3 + 2


def test_break_in_python_iterable_loop_keeps_python_semantics():
    @declarative
    def f(x):
        total = x
        for item in [1.0, 2.0, 3.0]:
            total = total + item
            if item >= 2.0:
                break
        return total

    with dygraph.guard():
        out = f(to_variable(np.zeros((1,), np.float32)))
        assert float(np.asarray(out.data)[0]) == pytest.approx(3.0)  # 1+2


# ---------------------------------------------------------------------------
# round-4 transformers: print / cast / len / assert / shape / list / call
# (reference dygraph_to_static print/cast/assert/tensor_shape/list/call
# transformers)
# ---------------------------------------------------------------------------


def test_cast_and_len_on_tensors():
    @declarative
    def f(x):
        n = len(x)              # static dim -> python int
        z = int(x)              # tensor -> cast to int64 (truncating)
        return float(z) + float(n)

    with dygraph.guard():
        xv = to_variable(np.full((4, 2), 2.7, np.float32))
        out = f(xv)
        # int(2.7) -> 2 per element; + len 4 => 6.0
        assert float(np.asarray(out.data)[0, 0]) == pytest.approx(6.0)


def test_shape_attribute_converts():
    @declarative
    def f(x):
        h = x.shape[1]          # static -> python int usable in reshape
        return x * 0.0 + h

    with dygraph.guard():
        out = f(to_variable(np.zeros((2, 5), np.float32)))
        assert float(np.asarray(out.data)[0, 0]) == pytest.approx(5.0)


def test_call_transformer_converts_helper_control_flow():
    def helper(y):
        s = layers.reduce_sum(y)
        if s > 0:               # tensor condition inside a CALLED fn
            out = y + 1.0
        else:
            out = y - 1.0
        return out

    @declarative
    def f(x):
        return helper(x)

    with dygraph.guard():
        up = f(to_variable(np.full((2,), 3.0, np.float32)))
        dn = f(to_variable(np.full((2,), -3.0, np.float32)))
        assert float(np.asarray(up.data)[0]) == pytest.approx(4.0)
        assert float(np.asarray(dn.data)[0]) == pytest.approx(-4.0)


def test_list_append_in_tensor_loop():
    @declarative
    def f(x):
        out = []
        for item in [1.0, 2.0, 3.0]:   # python loop: list stays a list
            out.append(x + item)
        return out[0] + out[1] + out[2]

    with dygraph.guard():
        got = f(to_variable(np.zeros((1,), np.float32)))
        assert float(np.asarray(got.data)[0]) == pytest.approx(6.0)


def test_print_and_assert_convert(capsys):
    @declarative
    def f(x):
        print("value is", x)
        s = layers.reduce_sum(x)
        assert s > -1e9, "must hold"
        return x + 1.0

    with dygraph.guard():
        out = f(to_variable(np.ones((2,), np.float32)))
        assert float(np.asarray(out.data)[0]) == pytest.approx(2.0)


def test_per_signature_program_cache():
    calls = {"n": 0}

    def helper(y):
        calls["n"] += 1
        return y * 2.0

    @declarative
    def f(x):
        return helper(x)

    with dygraph.guard():
        a = np.ones((2, 3), np.float32)
        b = np.ones((4, 3), np.float32)
        f(to_variable(a))
        n_after_first = calls["n"]
        f(to_variable(a))              # same signature: cached program
        assert calls["n"] == n_after_first
        f(to_variable(b))              # new shape: retrace
        assert calls["n"] > n_after_first
        assert len(f.program_cache) == 2


def test_convert_call_distinct_closures_and_methods():
    """Distinct closures of one def transform independently; Layer-method
    helpers with tensor control flow convert too (review regressions)."""
    def make_adder(k):
        def add(y):
            s = layers.reduce_sum(y)
            if s > -1e9:
                out = y + k
            else:
                out = y
            return out
        return add

    a1, a2 = make_adder(1.0), make_adder(2.0)

    @declarative
    def f(x):
        return a2(a1(x))

    with dygraph.guard():
        out = f(to_variable(np.zeros((2,), np.float32)))
        assert float(np.asarray(out.data)[0]) == pytest.approx(3.0)

    @declarative
    def g(x):
        acc = []
        alias = acc
        for v in [1.0, 2.0]:
            acc.append(x + v)
        return alias[0] + alias[1]   # aliasing preserved (in-place append)

    with dygraph.guard():
        out = g(to_variable(np.zeros((1,), np.float32)))
        assert float(np.asarray(out.data)[0]) == pytest.approx(3.0)
