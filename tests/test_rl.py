"""`paddle_tpu.rl`: the rollout -> score -> train -> hot-swap loop.

The load-bearing drills:

* **loss oracle** — `pg_loss_jnp`'s gradients (REINFORCE, PPO clip,
  KL k3) against hand-derived numpy formulas, and the dygraph
  `make_rl_loss_fn` mirror against `pg_loss_jnp` through a real model;
* **determinism** — a checkpointed loop restored into a FRESH
  model/fleet/loop continues bit-identically to an uninterrupted
  control (the lazy-batch design: round k's rollout always sees
  post-round-k-1 params, so there is no prefetch skew to lose);
* **fault** — a replica killed mid-rollout leaves the loop live with
  an exact ledger (submitted == completed + failed, requeues counted);
* **gates** — a poisoned candidate policy is rolled back at the verify
  gate and the fleet keeps answering with the old weights;
* **e2e** — on the verifiable `TokenAffinityReward`, measured reward
  improves over the run while policies ship through
  verify -> canary -> promote with zero failed requests and measured
  freshness.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import models
from paddle_tpu.fluid import dygraph
from paddle_tpu.fluid.optimizer import AdamOptimizer, SGDOptimizer
from paddle_tpu.incubate.fault import FaultPlan

rl = paddle_tpu.rl
serving = paddle_tpu.serving
gen = paddle_tpu.generation

CFG = models.TransformerLMConfig.tiny()
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_model():
    with dygraph.guard():
        np.random.seed(0)
        return models.TransformerLM(CFG)


@pytest.fixture(scope="module")
def lm():
    return make_model()


def make_fleet(model, replicas=1, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 32)
    kw.setdefault("prefill_buckets", [8, 16])
    kw.setdefault("logprobs", True)
    return serving.GenerationFleet(model, replicas=replicas, **kw)


# ---------------------------------------------------------------------------
# the loss formula: jnp reference vs numpy gradient oracle
# ---------------------------------------------------------------------------


class TestLossOracle:
    def _data(self, seed=0, b=3, t=6):
        rng = np.random.RandomState(seed)
        logp = -np.abs(rng.randn(b, t)).astype(np.float32) - 0.1
        old = logp + rng.uniform(-0.4, 0.4, (b, t)).astype(np.float32)
        ref = logp + rng.uniform(-0.3, 0.3, (b, t)).astype(np.float32)
        adv = rng.randn(b, t).astype(np.float32)
        mask = (rng.rand(b, t) > 0.3).astype(np.float32)
        return logp, old, ref, adv, mask

    def test_reinforce_grad_matches_numpy_oracle(self):
        import jax

        logp, old, ref, adv, mask = self._data()
        z = max(mask.sum(), 1.0)
        g = np.asarray(jax.grad(
            lambda lp: rl.pg_loss_jnp(lp, old, ref, adv, mask,
                                      kind="reinforce"))(logp))
        np.testing.assert_allclose(g, -adv * mask / z, rtol=1e-5,
                                   atol=1e-6)

    def test_kl_grad_matches_numpy_oracle(self):
        """d/dlogp of kl_coef*sum((exp(d)-d-1)*mask)/Z with
        d = ref - logp is kl_coef*(1 - exp(ref - logp))*mask/Z."""
        import jax

        coef = 0.7
        logp, old, ref, adv, mask = self._data(seed=1)
        z = max(mask.sum(), 1.0)
        g = np.asarray(jax.grad(
            lambda lp: rl.pg_loss_jnp(lp, old, ref, adv, mask,
                                      kind="reinforce",
                                      kl_coef=coef))(logp))
        oracle = (-adv * mask / z
                  + coef * (1.0 - np.exp(ref - logp)) * mask / z)
        np.testing.assert_allclose(g, oracle, rtol=1e-4, atol=1e-5)

    def test_ppo_grad_matches_numpy_oracle(self):
        """min(r*adv, clip(r)*adv): the active unclipped branch
        contributes -r*adv*mask/Z, a strictly-clipped branch 0 (jax's
        tie convention keeps the unclipped side when clip(r) == r)."""
        import jax

        eps = 0.2
        logp, old, ref, adv, mask = self._data(seed=2)
        z = max(mask.sum(), 1.0)
        ratio = np.exp(logp - old)
        unclipped = ratio * adv
        clipped = np.clip(ratio, 1 - eps, 1 + eps) * adv
        active = unclipped <= clipped
        oracle = -np.where(active, ratio * adv, 0.0) * mask / z
        g = np.asarray(jax.grad(
            lambda lp: rl.pg_loss_jnp(lp, old, ref, adv, mask,
                                      kind="ppo",
                                      clip_eps=eps))(logp))
        np.testing.assert_allclose(g, oracle, rtol=1e-4, atol=1e-5)

    def test_bad_kind_refused(self):
        with pytest.raises(ValueError):
            rl.pg_loss_jnp(np.zeros((1, 1)), None, None,
                           np.zeros((1, 1)), np.ones((1, 1)),
                           kind="a2c")
        with pytest.raises(ValueError):
            rl.make_rl_loss_fn(kind="a2c")

    @pytest.mark.parametrize("kind,kl", [("reinforce", 0.0),
                                         ("reinforce", 0.5),
                                         ("ppo", 0.0)])
    def test_dygraph_mirror_matches_jnp_through_model(self, lm, kind, kl):
        """`make_rl_loss_fn` through a real TransformerLM equals
        `pg_loss_jnp` over the model's own logprobs."""
        import jax.numpy as jnp

        from paddle_tpu.fluid import framework
        from paddle_tpu.generation.sampling import token_logprobs

        rng = np.random.RandomState(5)
        samples = [
            rl.RolloutSample([1, 2, 3], [4, 5], [-1.0, -0.8], "length", 0),
            rl.RolloutSample([6, 7], [8, 9, 1], [-0.5, -2.0, -0.3],
                             "length", 1),
        ]
        batch = rl.build_batch(samples, [0.7, -1.2],
                               [rng.randn(5).astype(np.float32)] * 2,
                               seq_len=6)
        loss_fn = rl.make_rl_loss_fn(kind=kind, kl_coef=kl)
        with dygraph.guard():
            framework._dygraph_tracer.train_mode = False
            for vb in lm.state_dict().values():
                framework._dygraph_tracer.register_var(vb)
            feed = {k: dygraph.to_variable(v) for k, v in batch.items()}
            out = loss_fn(lm, feed)
            got = float(np.asarray(out.data))

            logits = lm(dygraph.to_variable(batch["input_ids"]),
                        dygraph.to_variable(batch["position_ids"]))
        lp = np.stack([
            np.asarray(token_logprobs(
                jnp.asarray(logits.data)[i],
                jnp.asarray(batch["labels"][i])))
            for i in range(2)])
        want = float(rl.pg_loss_jnp(
            lp, batch["old_logp"], batch["ref_logp"], batch["adv"],
            batch["mask"], kind=kind, kl_coef=kl))
        np.testing.assert_allclose(got, want, rtol=5e-4, atol=5e-5)


def test_build_batch_layout():
    s = rl.RolloutSample([5, 6, 7], [1, 2], [-0.5, -0.25], "length", 9)
    b = rl.build_batch([s], [2.0], seq_len=6)
    np.testing.assert_array_equal(b["input_ids"][0],
                                  [5, 6, 7, 1, 0, 0])
    np.testing.assert_array_equal(b["labels"][0], [6, 7, 1, 2, 0, 0])
    np.testing.assert_array_equal(b["position_ids"][0],
                                  [0, 1, 2, 3, 0, 0])
    np.testing.assert_array_equal(b["mask"][0], [0, 0, 1, 1, 0, 0])
    np.testing.assert_array_equal(b["adv"][0], [0, 0, 2, 2, 0, 0])
    np.testing.assert_array_equal(b["old_logp"][0],
                                  [0, 0, -0.5, -0.25, 0, 0])
    with pytest.raises(ValueError):
        rl.build_batch([s], [2.0], seq_len=3)


def test_reference_scorer_matches_direct_forward(lm):
    import jax.numpy as jnp

    from paddle_tpu.fluid import framework
    from paddle_tpu.generation.sampling import token_logprobs

    seq = [3, 1, 4, 1, 5, 9, 2]
    scorer = rl.ReferenceScorer(lm, max_len=32)
    got = scorer.score([seq])[0]
    assert got.shape == (len(seq) - 1,)

    with dygraph.guard():
        framework._dygraph_tracer.train_mode = False
        for vb in lm.state_dict().values():
            framework._dygraph_tracer.register_var(vb)
        ids = np.asarray(seq[:-1], np.int64)[None]
        pos = np.arange(len(seq) - 1, dtype=np.int64)[None]
        logits = lm(dygraph.to_variable(ids), dygraph.to_variable(pos))
    want = np.asarray(token_logprobs(jnp.asarray(logits.data)[0],
                                     jnp.asarray(seq[1:], jnp.int32)))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# rollout: determinism + exact accounting
# ---------------------------------------------------------------------------


class TestRollout:
    def test_deterministic_and_exactly_accounted(self, lm):
        eng = gen.GenerationEngine(lm, slots=4, max_len=32,
                                   prefill_buckets=[8, 16],
                                   logprobs=True)
        ro = rl.RolloutEngine(eng, max_new_tokens=5, temperature=0.9,
                              top_k=10)
        prompts = [[1, 2, 3], [4, 5], [6, 7, 8, 9]]
        seeds = [11, 22, 33]
        s1, a1 = ro.rollout(prompts, seeds)
        s2, a2 = ro.rollout(prompts, seeds)
        assert a1["submitted"] == a1["completed"] == len(prompts)
        assert a1["failed"] == 0
        assert a1["tokens"] == sum(len(s.tokens) for s in s1)
        for x, y in zip(s1, s2):
            assert x.tokens == y.tokens and x.logprobs == y.logprobs
            assert len(x.logprobs) == len(x.tokens)
        assert ro.submitted == 6 and ro.completed == 6

    def test_engine_without_logprobs_refused(self, lm):
        eng = gen.GenerationEngine(lm, slots=2, max_len=32,
                                   prefill_buckets=[8])
        with pytest.raises(ValueError):
            rl.RolloutEngine(eng)

    def test_replica_kill_mid_rollout_keeps_ledger_exact(self, lm):
        """Fault-plan kill of replica 0 mid-rollout: affected requests
        requeue once onto the survivor, the ledger stays exact, and the
        loop's next rollout still works."""
        plan = FaultPlan([], rank=0)
        plan.add("kill_replica", replica=0, request=3)
        fleet = make_fleet(lm, replicas=2, fault_plan=plan).start()
        try:
            ro = rl.RolloutEngine(fleet, max_new_tokens=6, timeout=60.0)
            prompts = [[1 + i, 2 + i, 3 + i] for i in range(6)]
            samples, acct = ro.rollout(prompts, list(range(6)))
            assert acct["submitted"] == 6
            assert acct["completed"] + acct["failed"] == 6
            assert acct["failed"] == 0          # survivor absorbed all
            assert acct["requeued"] >= 1
            assert any(s.requeued for s in samples)
            assert int(fleet._m_deaths.value) == 1
            assert fleet.ready()
            s2, a2 = ro.rollout([[7, 7, 7]], [99])
            assert a2["completed"] == 1 and len(s2[0].tokens) == 6
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# checkpointing
# ---------------------------------------------------------------------------


def test_policy_checkpointer_full_delta_chain(tmp_path):
    state = {"a": np.arange(4, dtype=np.float32),
             "b": np.zeros(3, np.float32)}
    applied = {}
    ck = rl.PolicyCheckpointer(str(tmp_path), lambda: state,
                               applied.update, full_every=3)
    kinds = []
    for i in range(5):
        state = dict(state)
        state["a"] = state["a"] + 1.0       # "b" never changes
        kinds.append(ck.save(step=i, window=i))
    assert [k for _no, k in kinds] == \
        ["full", "delta", "delta", "full", "delta"]
    metas = ck._saver.list_checkpoints()
    by_no = dict(metas)
    assert by_no[kinds[1][0]]["n_arrays"] == 1      # delta: only "a"
    assert by_no[kinds[3][0]]["n_arrays"] == 2      # full: everything

    fresh = rl.PolicyCheckpointer(str(tmp_path), lambda: {},
                                  applied.update, full_every=3)
    meta = fresh.restore()
    assert meta["window"] == 4
    np.testing.assert_array_equal(applied["a"], state["a"])
    np.testing.assert_array_equal(applied["b"], state["b"])


# ---------------------------------------------------------------------------
# gated promotion
# ---------------------------------------------------------------------------


class TestPublisher:
    def test_gate_failure_rolls_back_and_old_policy_serves(self, lm):
        fleet = make_fleet(lm, replicas=2)
        try:
            probe = gen.GenerationRequest([2, 7, 1], max_new_tokens=4)
            h = fleet.submit(probe)
            for r in fleet.replicas:
                r.engine.run_until_idle()
            before = h.result(timeout=30)

            good = fleet.snapshot_params()
            poisoned = dict(good)
            name = next(iter(poisoned))
            bad = np.array(poisoned[name], copy=True)
            bad.flat[0] = np.nan
            poisoned[name] = bad
            pub = rl.PolicyPublisher(fleet, lambda: poisoned,
                                     probe_prompts=[[1, 2, 3]])
            with pytest.raises(rl.PublishError):
                pub.push(0)
            assert pub.pushed == []
            assert int(pub._m_rolled_back.value) == 1
            assert int(pub._m_promoted.value) == 0

            h = fleet.submit(gen.GenerationRequest([2, 7, 1],
                                                   max_new_tokens=4))
            for r in fleet.replicas:
                r.engine.run_until_idle()
            assert h.result(timeout=30) == before
        finally:
            fleet.stop()

    def test_push_promotes_through_canary_with_live_at(self, lm):
        fleet = make_fleet(lm, replicas=2)
        try:
            params = fleet.snapshot_params()
            rng = np.random.RandomState(3)
            cand = {k: (v + rng.normal(scale=0.05, size=v.shape)
                        .astype(v.dtype) if v.ndim >= 2 else v)
                    for k, v in params.items()}
            pub = rl.PolicyPublisher(fleet, lambda: cand,
                                     probe_prompts=[[1, 2, 3]],
                                     canary_replicas=1)
            rec = pub.push(1)
            assert rec["live_at"] <= time.time()
            assert len(rec["canary"]) == 1
            assert set(rec["replicas"]) == \
                {r.replica_id for r in fleet.replicas}
            assert int(pub._m_promoted.value) == 1
            for r in fleet.replicas:        # both serve the candidate
                swapped = r.engine.snapshot_params()
                for k in cand:
                    np.testing.assert_array_equal(
                        swapped[k], np.asarray(cand[k],
                                               swapped[k].dtype))
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# the loop: resume determinism, e2e drill, control plane
# ---------------------------------------------------------------------------


def make_loop(root, rounds_seen_model=None, **kw):
    model = rounds_seen_model or make_model()
    fleet = make_fleet(model, replicas=1)
    kw.setdefault("prompts", [[1, 2, 3], [4, 5], [6, 7, 8], [9, 10]])
    kw.setdefault("rollout_batch", 4)
    kw.setdefault("max_new_tokens", 4)
    kw.setdefault("base_seed", 42)
    kw.setdefault("checkpoint_every_windows", 1)
    loop = rl.FeedbackLoop(model, SGDOptimizer(learning_rate=0.5),
                           fleet, rl.TokenAffinityReward(target_ids=[7]),
                           checkpoint_root=root, **kw)
    return loop, fleet


class TestFeedbackLoop:
    def test_resume_matches_uninterrupted_control(self, tmp_path):
        """The fixed-seed determinism drill: run 6 rounds straight;
        run 3 rounds, then restore into a COMPLETELY fresh
        model/fleet/loop and run 3 more — parameters, rewards and
        round counters must match the control exactly."""
        control, fleet_a = make_loop(str(tmp_path / "a"))
        try:
            control.run(rounds=6)
        finally:
            fleet_a.stop()

        first, fleet_b = make_loop(str(tmp_path / "b"))
        try:
            first.run(rounds=3)
        finally:
            fleet_b.stop()

        resumed, fleet_c = make_loop(str(tmp_path / "b"))
        try:
            meta = resumed.restore()
            assert meta is not None and resumed.round == 3
            assert resumed.baseline.value == pytest.approx(
                first.baseline.value)
            resumed.run(rounds=3)
        finally:
            fleet_c.stop()

        assert resumed.round == control.round == 6
        assert resumed.reward_history == control.reward_history[3:]
        pc, pr = (control.session.host_params(),
                  resumed.session.host_params())
        assert set(pc) == set(pr)
        for k in pc:
            np.testing.assert_array_equal(pc[k], pr[k], err_msg=k)

    def test_e2e_drill_reward_improves_and_policy_ships(self, tmp_path):
        """The acceptance drill: measured reward improves over the run
        while updated policies ship verify -> canary -> promote with
        zero failed requests and measured freshness."""
        model = make_model()
        fleet = make_fleet(model, replicas=2)
        loop = rl.FeedbackLoop(
            model, AdamOptimizer(learning_rate=0.05), fleet,
            rl.TokenAffinityReward(target_ids=[7]),
            prompts=[[1, 2, 3], [4, 5], [6, 7, 8], [9, 10]],
            rollout_batch=8, max_new_tokens=6,
            checkpoint_root=str(tmp_path / "ckpt"),
            push_every_windows=2)
        try:
            report = loop.run(rounds=10)
        finally:
            fleet.stop()

        rewards = [r for _rnd, r in loop.reward_history]
        assert len(rewards) == 10
        assert np.mean(rewards[-3:]) > np.mean(rewards[:3]) + 0.1, rewards

        led = loop.rollout_engine.stats()
        assert led["submitted"] == report.events == 80
        assert led["failed"] == 0                  # zero failed requests
        assert len(report.pushes) == 5
        for p in report.pushes:
            assert p["freshness_oldest_s"] is not None
            assert p["live_at"] <= time.time()
            assert len(p["replicas"]) == 2
        assert report.freshness_s is not None      # the headline number
        assert int(loop.publisher._m_promoted.value) == 5
        assert int(loop.publisher._m_rolled_back.value) == 0
        assert [k for _no, k in report.checkpoints].count("full") >= 2

    def test_control_plane_and_ctl_rc_contract(self):
        """`serve_rl_http` + `tools/rl_ctl.py`: status/stats/start/stop
        with the rc contract (0 ok, 1 on 409 start-while-running)."""
        model = make_model()
        fleet = make_fleet(model, replicas=1)
        loop = rl.FeedbackLoop(
            model, SGDOptimizer(learning_rate=0.5), fleet,
            rl.TokenAffinityReward(target_ids=[7]),
            prompts=[[1, 2, 3]], rollout_batch=2, max_new_tokens=2)
        httpd = rl.serve_rl_http(loop, port=0, block=False)
        port = httpd.server_address[1]

        def ctl(*args):
            return subprocess.run(
                [sys.executable, os.path.join(REPO, "tools", "rl_ctl.py"),
                 "--endpoint", "http://127.0.0.1:%d" % port, "--json",
                 *args],
                capture_output=True, text=True, timeout=120)

        try:
            p = ctl("status")
            assert p.returncode == 0
            st = json.loads(p.stdout)
            assert st["healthy"] and st["ready"] and not st["running"]

            assert ctl("start", "--rounds", "2").returncode == 0
            p = ctl("start", "--rounds", "1")      # refused: 409 -> rc 1
            assert p.returncode == 1
            assert json.loads(p.stdout)["http"] == 409

            for _ in range(240):
                s = json.loads(ctl("stats").stdout)
                if not s["running"]:
                    break
                time.sleep(0.25)
            assert s["round"] == 2 and s["error"] is None, s
            assert ctl("stop").returncode == 0
        finally:
            httpd.shutdown()
            fleet.stop()


def test_rl_loop_bench_skip_convention():
    """The bench honors BENCH_FORCE_BACKEND_FAIL with the
    {"skipped": true} rc=0 convention."""
    env = dict(os.environ, BENCH_FORCE_BACKEND_FAIL="init",
               JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks",
                                      "rl_loop_bench.py")],
        capture_output=True, text=True, timeout=120, env=env)
    assert p.returncode == 0, p.stderr
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["skipped"] is True
    assert "injected by BENCH_FORCE_BACKEND_FAIL" in out["reason"]


def test_rl_is_lazy_and_in_api_spec():
    """`paddle_tpu.rl` loads via PEP 562 — a fresh interpreter that
    imports paddle_tpu does NOT pay for the rl/generation stack."""
    p = subprocess.run(
        [sys.executable, "-c",
         "import sys, paddle_tpu; "
         "assert 'paddle_tpu.rl' not in sys.modules; "
         "assert 'paddle_tpu.generation' not in sys.modules; "
         "m = paddle_tpu.rl; "
         "assert 'paddle_tpu.rl' in sys.modules and "
         "hasattr(m, 'FeedbackLoop')"],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), cwd=REPO)
    assert p.returncode == 0, p.stderr
