"""Native C++ dataset engine: MultiSlot parsing, shuffle, ragged batches.

Mirrors reference tests test_dataset.py (InMemoryDataset/QueueDataset with
generated slot files).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.dataset import DatasetFactory, pad_batch


def _write_slot_files(tmp_path, nfiles=3, lines_per_file=20, seed=0):
    """Two slots: int64 ids (ragged 1..4) + one float label."""
    rng = np.random.RandomState(seed)
    files = []
    all_samples = []
    for f in range(nfiles):
        path = str(tmp_path / ("part-%d.txt" % f))
        with open(path, "w") as fh:
            for _ in range(lines_per_file):
                n = rng.randint(1, 5)
                ids = rng.randint(0, 100, n)
                label = rng.rand()
                fh.write(
                    "%d %s 1 %.6f\n" % (n, " ".join(map(str, ids)), label)
                )
                all_samples.append((list(ids), label))
        files.append(path)
    return files, all_samples


def _make_vars():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        ids = fluid.data("ids", [-1, 1], "int64")
        label = fluid.data("label", [-1, 1], "float32")
    return [ids, label]


def test_inmemory_dataset_load_and_iterate(tmp_path):
    files, samples = _write_slot_files(tmp_path)
    ds = DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist(files)
    ds.set_batch_size(8)
    ds.set_thread(3)
    ds.set_use_var(_make_vars())
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 60
    assert ds.get_error_line_count() == 0

    seen = 0
    for batch in ds:
        ids_vals, ids_lod = batch["ids"]
        lab_vals, lab_lod = batch["label"]
        bsz = len(ids_lod) - 1
        assert bsz <= 8
        assert len(lab_vals) == bsz  # one label per sample
        assert ids_lod[-1] == len(ids_vals)
        seen += bsz
    assert seen == 60


def test_inmemory_dataset_shuffle_changes_order(tmp_path):
    files, _ = _write_slot_files(tmp_path, nfiles=1, lines_per_file=50)
    ds = DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist(files)
    ds.set_batch_size(50)
    ds.set_use_var(_make_vars())
    ds.load_into_memory()
    first = next(iter(ds))["label"][0].copy()
    ds.local_shuffle(seed=7)
    shuffled = next(iter(ds))["label"][0].copy()
    assert not np.allclose(first, shuffled)
    assert np.allclose(sorted(first), sorted(shuffled))  # same multiset


def test_queue_dataset_streams(tmp_path):
    files, _ = _write_slot_files(tmp_path, nfiles=2, lines_per_file=10)
    ds = DatasetFactory().create_dataset("QueueDataset")
    ds.set_filelist(files)
    ds.set_batch_size(4)
    ds.set_use_var(_make_vars())
    total = sum(len(b["label"][1]) - 1 for b in ds)
    assert total == 20


def test_bad_lines_counted(tmp_path):
    path = str(tmp_path / "bad.txt")
    with open(path, "w") as f:
        f.write("2 5 7 1 0.5\n")       # good
        f.write("3 1 2 1 0.25\n")      # bad: slot0 claims 3, has 2 + slot1
        f.write("not numbers at all\n")
    ds = DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist([path])
    ds.set_batch_size(4)
    ds.set_use_var(_make_vars())
    ds.load_into_memory()
    assert ds.get_memory_data_size() >= 1
    assert ds.get_error_line_count() >= 1


def test_pad_batch_lod_to_dense():
    vals = np.array([1, 2, 3, 4, 5, 6], np.int64)
    lod = np.array([0, 2, 3, 6])
    dense, mask = pad_batch(vals, lod, pad_value=0)
    np.testing.assert_array_equal(dense, [[1, 2, 0], [3, 0, 0], [4, 5, 6]])
    np.testing.assert_array_equal(mask, [[1, 1, 0], [1, 0, 0], [1, 1, 1]])


# ---------------------------------------------------------------------------
# train_from_dataset (reference executor.py:1448 RunFromDataset path)
# ---------------------------------------------------------------------------


def _write_ctr_files(tmp_path, nfiles=2, lines_per_file=40, seed=7):
    """CTR-style MultiSlot text: ragged id slot + one learnable float
    label = mean(ids)/100 (so training from files alone must converge)."""
    rng = np.random.RandomState(seed)
    files = []
    for f in range(nfiles):
        path = str(tmp_path / ("ctr-%d.txt" % f))
        with open(path, "w") as fh:
            for _ in range(lines_per_file):
                n = rng.randint(2, 7)
                ids = rng.randint(0, 100, n)
                label = ids.mean() / 100.0
                fh.write("%d %s 1 %.6f\n" % (n, " ".join(map(str, ids)), label))
        files.append(path)
    return files


def test_train_from_dataset_ctr(tmp_path, capsys):
    """End-to-end: text files -> native engine -> jitted program, no
    Python reader (reference train_from_dataset semantics)."""
    T = 8
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        ids = fluid.data("ids", [-1, T], "int64")
        ids_len = fluid.data("ids_length", [-1], "int64")
        label = fluid.data("label", [-1, 1], "float32")
        emb = fluid.layers.embedding(ids, size=[100, 16])
        pooled = fluid.layers.sequence_pool(emb, "AVERAGE", ids_len)
        pred = fluid.layers.fc(pooled, size=1)
        loss = fluid.layers.reduce_mean(
            fluid.layers.square(pred - label))
        fluid.optimizer.AdamOptimizer(learning_rate=0.01).minimize(loss)

    # dataset schema comes from program vars (reference set_use_var flow);
    # ids_length is derived by the trainer, not a file slot
    ds = DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist(_write_ctr_files(tmp_path))
    ds.set_batch_size(16)
    ds.set_thread(2)
    schema_prog = fluid.Program()
    with fluid.program_guard(schema_prog, fluid.Program()):
        s_ids = fluid.data("ids", [-1, 1], "int64")
        s_label = fluid.data("label", [-1, 1], "float32")
    ds.set_use_var([s_ids, s_label])
    ds.load_into_memory()
    ds.local_shuffle(seed=1)

    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run_startup(startup)
        first = exe.train_from_dataset(
            main, ds, fetch_list=[loss], fetch_info=["loss"],
            debug=True, print_period=2)
        for _ in range(14):
            last = exe.train_from_dataset(main, ds, fetch_list=[loss])
    out = capsys.readouterr().out
    assert "[train_from_dataset]" in out and "loss=" in out
    assert float(last[0]) < float(first[0]) * 0.5, (first, last)


def test_global_shuffle_redistributes_across_trainers(tmp_path):
    """2 emulated trainers: global_shuffle permutes the shared filelist so
    samples MOVE between trainers (file granularity), union stays complete
    (reference data_set.cc GlobalShuffle capability)."""
    files, _ = _write_slot_files(tmp_path, nfiles=6, lines_per_file=5)

    def load(tid, seed=None):
        ds = DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_filelist(files)
        ds.set_trainer_info(tid, 2)
        ds.set_batch_size(64)
        ds.set_use_var(_make_vars())
        if seed is not None:
            ds.global_shuffle(seed=seed)
        else:
            ds.load_into_memory()
        got = set()
        for batch in ds:
            vals, lod = batch["ids"]
            labs, _ = batch["label"]
            for i in range(len(lod) - 1):
                got.add((tuple(int(v) for v in vals[lod[i]:lod[i + 1]]),
                         round(float(labs[i]), 6)))
        return got

    before = [load(0), load(1)]
    after = [load(0, seed=123), load(1, seed=123)]
    # complete + disjoint in both arrangements
    assert before[0] | before[1] == after[0] | after[1]
    assert not (after[0] & after[1])
    # and the assignment actually changed
    assert before[0] != after[0]


def test_infer_from_dataset_does_not_touch_params(tmp_path):
    """Reference contract (executor.py:1519): gradient/optimizer ops do
    not run during infer_from_dataset."""
    T = 8
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 5
    with fluid.program_guard(main, startup):
        ids = fluid.data("ids", [-1, T], "int64")
        ids_len = fluid.data("ids_length", [-1], "int64")
        label = fluid.data("label", [-1, 1], "float32")
        emb = fluid.layers.embedding(ids, size=[100, 16])
        pooled = fluid.layers.sequence_pool(emb, "AVERAGE", ids_len)
        pred = fluid.layers.fc(pooled, size=1)
        loss = fluid.layers.reduce_mean(fluid.layers.square(pred - label))
        fluid.optimizer.AdamOptimizer(learning_rate=0.01).minimize(loss)

    ds = DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist(_write_ctr_files(tmp_path, nfiles=1, lines_per_file=20))
    ds.set_batch_size(10)
    schema_prog = fluid.Program()
    with fluid.program_guard(schema_prog, fluid.Program()):
        s_ids = fluid.data("ids", [-1, 1], "int64")
        s_label = fluid.data("label", [-1, 1], "float32")
    ds.set_use_var([s_ids, s_label])
    ds.load_into_memory()

    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run_startup(startup)
        pname = main.all_parameters()[0].name
        before = np.asarray(scope.find_var(pname)).copy()
        exe.infer_from_dataset(main, ds, fetch_list=[loss])
        after = np.asarray(scope.find_var(pname))
    np.testing.assert_array_equal(before, after)


# ---------------------------------------------------------------------------
# streaming engine (bounded channel, out-of-core; reference channel.h +
# QueueDataset semantics)
# ---------------------------------------------------------------------------


def test_queue_dataset_true_streaming_small_channel(tmp_path):
    """All samples arrive through a channel of capacity 4 — resident
    engine memory is bounded by the channel, not the corpus."""
    files, _ = _write_slot_files(tmp_path, nfiles=3, lines_per_file=20)
    ds = DatasetFactory().create_dataset("QueueDataset")
    ds.set_filelist(files)
    ds.set_batch_size(7)
    ds.set_thread(2)
    ds.set_use_var(_make_vars())
    ds.set_queue_capacity(4)
    total = 0
    labels = []
    for batch in ds:
        vals, lod = batch["label"]
        total += len(lod) - 1
        labels.extend(float(v) for v in vals)
    assert total == 60
    # nothing was materialized in the in-memory store
    assert ds._lib.ds_memory_data_size(ds._handle) == 0
    # re-iteration streams again from the files
    assert sum(len(b["label"][1]) - 1 for b in ds) == 60


def test_queue_dataset_shuffle_window_changes_order(tmp_path):
    files, _ = _write_slot_files(tmp_path, nfiles=1, lines_per_file=50)

    def run(window):
        ds = DatasetFactory().create_dataset("QueueDataset")
        ds.set_filelist(files)
        ds.set_batch_size(50)
        ds.set_use_var(_make_vars())
        if window:
            ds.set_shuffle_window(window, seed=5)
        out = []
        for b in ds:
            out.extend(float(v) for v in b["label"][0])
        return out

    plain = run(0)
    shuffled = run(16)
    assert sorted(plain) == sorted(shuffled)  # same multiset
    assert plain != shuffled                  # order differs


def test_pipe_command_preprocessing(tmp_path):
    """pipe_command runs each file through a shell preprocessor
    (reference data_feed pipe_command): sed doubles the label slot."""
    path = str(tmp_path / "p.txt")
    with open(path, "w") as f:
        f.write("2 5 7 1 0.5\n")
        f.write("1 3 1 0.25\n")
    ds = DatasetFactory().create_dataset("QueueDataset")
    ds.set_filelist([path])
    ds.set_batch_size(4)
    ds.set_use_var(_make_vars())
    ds.set_pipe_command("sed 's/0.5$/0.75/'")
    labels = []
    for b in ds:
        labels.extend(round(float(v), 4) for v in b["label"][0])
    assert 0.75 in labels and 0.25 in labels and 0.5 not in labels
