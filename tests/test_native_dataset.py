"""Native C++ dataset engine: MultiSlot parsing, shuffle, ragged batches.

Mirrors reference tests test_dataset.py (InMemoryDataset/QueueDataset with
generated slot files).
"""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.dataset import DatasetFactory, pad_batch


def _write_slot_files(tmp_path, nfiles=3, lines_per_file=20, seed=0):
    """Two slots: int64 ids (ragged 1..4) + one float label."""
    rng = np.random.RandomState(seed)
    files = []
    all_samples = []
    for f in range(nfiles):
        path = str(tmp_path / ("part-%d.txt" % f))
        with open(path, "w") as fh:
            for _ in range(lines_per_file):
                n = rng.randint(1, 5)
                ids = rng.randint(0, 100, n)
                label = rng.rand()
                fh.write(
                    "%d %s 1 %.6f\n" % (n, " ".join(map(str, ids)), label)
                )
                all_samples.append((list(ids), label))
        files.append(path)
    return files, all_samples


def _make_vars():
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        ids = fluid.data("ids", [-1, 1], "int64")
        label = fluid.data("label", [-1, 1], "float32")
    return [ids, label]


def test_inmemory_dataset_load_and_iterate(tmp_path):
    files, samples = _write_slot_files(tmp_path)
    ds = DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist(files)
    ds.set_batch_size(8)
    ds.set_thread(3)
    ds.set_use_var(_make_vars())
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 60
    assert ds.get_error_line_count() == 0

    seen = 0
    for batch in ds:
        ids_vals, ids_lod = batch["ids"]
        lab_vals, lab_lod = batch["label"]
        bsz = len(ids_lod) - 1
        assert bsz <= 8
        assert len(lab_vals) == bsz  # one label per sample
        assert ids_lod[-1] == len(ids_vals)
        seen += bsz
    assert seen == 60


def test_inmemory_dataset_shuffle_changes_order(tmp_path):
    files, _ = _write_slot_files(tmp_path, nfiles=1, lines_per_file=50)
    ds = DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist(files)
    ds.set_batch_size(50)
    ds.set_use_var(_make_vars())
    ds.load_into_memory()
    first = next(iter(ds))["label"][0].copy()
    ds.local_shuffle(seed=7)
    shuffled = next(iter(ds))["label"][0].copy()
    assert not np.allclose(first, shuffled)
    assert np.allclose(sorted(first), sorted(shuffled))  # same multiset


def test_queue_dataset_streams(tmp_path):
    files, _ = _write_slot_files(tmp_path, nfiles=2, lines_per_file=10)
    ds = DatasetFactory().create_dataset("QueueDataset")
    ds.set_filelist(files)
    ds.set_batch_size(4)
    ds.set_use_var(_make_vars())
    total = sum(len(b["label"][1]) - 1 for b in ds)
    assert total == 20


def test_bad_lines_counted(tmp_path):
    path = str(tmp_path / "bad.txt")
    with open(path, "w") as f:
        f.write("2 5 7 1 0.5\n")       # good
        f.write("3 1 2 1 0.25\n")      # bad: slot0 claims 3, has 2 + slot1
        f.write("not numbers at all\n")
    ds = DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_filelist([path])
    ds.set_batch_size(4)
    ds.set_use_var(_make_vars())
    ds.load_into_memory()
    assert ds.get_memory_data_size() >= 1
    assert ds.get_error_line_count() >= 1


def test_pad_batch_lod_to_dense():
    vals = np.array([1, 2, 3, 4, 5, 6], np.int64)
    lod = np.array([0, 2, 3, 6])
    dense, mask = pad_batch(vals, lod, pad_value=0)
    np.testing.assert_array_equal(dense, [[1, 2, 0], [3, 0, 0], [4, 5, 6]])
    np.testing.assert_array_equal(mask, [[1, 1, 0], [1, 0, 0], [1, 1, 1]])
