"""HAPI Model.fit/evaluate/predict + callbacks + vision/text/datasets
(reference incubate/hapi/model.py, callbacks.py, datasets/, vision/)."""

import os

import numpy as np
import pytest

import paddle_tpu.hapi as hapi
from paddle_tpu.fluid import dygraph
from paddle_tpu.fluid.optimizer import AdamOptimizer


def _loss_fn(pred, label):
    from paddle_tpu.fluid import layers

    return layers.mean(
        layers.softmax_with_cross_entropy(pred, layers.reshape(label,
                                                               [-1, 1])))


def test_hapi_fit_mnist_with_callbacks(tmp_path, capsys):
    with dygraph.guard():
        ds = hapi.datasets.MNIST(mode="train", n=256)
        eval_ds = hapi.datasets.MNIST(mode="test", n=64)
        model = hapi.Model(hapi.vision.LeNet())
        model.prepare(AdamOptimizer(learning_rate=1e-3), _loss_fn)
        ckpt_dir = str(tmp_path / "ckpts")
        os.makedirs(ckpt_dir)
        es = hapi.EarlyStopping(monitor="loss", patience=10)
        hist = model.fit(
            ds.as_arrays(), eval_data=eval_ds.as_arrays(),
            batch_size=64, epochs=3, eval_freq=2, log_freq=2,
            callbacks=[hapi.ModelCheckpoint(save_freq=1,
                                            save_dir=ckpt_dir), es])
        assert len(hist["loss"]) == 3
        assert hist["loss"][-1] < hist["loss"][0]
        # checkpoints written per epoch
        assert os.path.exists(os.path.join(ckpt_dir, "0.pdparams"))
        # eval scheduled on epochs 0, 2 (freq 2) and the last epoch
        out = capsys.readouterr().out
        assert "epoch 0" in out and "epoch 2 end" in out

        # predict + evaluate round out the API
        preds = model.predict(eval_ds.xs[:32], batch_size=16)
        assert preds.shape[0] == 32
        ev = model.evaluate(eval_ds.as_arrays(), batch_size=32)
        assert np.isfinite(ev["loss"])

        # save / load round trip
        path = str(tmp_path / "m")
        model.save(path)
        model2 = hapi.Model(hapi.vision.LeNet())
        model2.prepare(AdamOptimizer(learning_rate=1e-3), _loss_fn)
        model2.load(path)
        p2 = model2.predict(eval_ds.xs[:8], batch_size=8)
        np.testing.assert_allclose(p2, preds[:8], rtol=1e-5, atol=1e-6)


def test_hapi_early_stopping_restores_best(tmp_path):
    """EarlyStopping halts on a plateauing metric and restores the best
    weights (reference 2.0 EarlyStopping semantics)."""

    with dygraph.guard():
        ds = hapi.datasets.MNIST(mode="train", n=128)
        model = hapi.Model(hapi.vision.LeNet())
        model.prepare(AdamOptimizer(learning_rate=1e-3), _loss_fn)
        # min_delta=0.2: once per-epoch improvement drops under 0.2 the
        # patience counter runs out and fit halts early
        es = hapi.EarlyStopping(monitor="loss", patience=1, min_delta=0.2,
                                save_best_model=True)
        hist = model.fit(ds.as_arrays(), batch_size=64, epochs=12,
                         verbose=0, callbacks=[es])
        assert len(hist["loss"]) < 12, "early stopping never triggered"
        assert es.stopped_epoch is not None
        # best-weight restore leaves the model near its best epoch
        ev = model.evaluate(ds.as_arrays(), batch_size=64)
        assert ev["loss"] <= es.best + 0.2


def test_hapi_lr_scheduler_callback():
    with dygraph.guard():
        ds = hapi.datasets.MNIST(mode="train", n=64)
        model = hapi.Model(hapi.vision.LeNet())
        opt = AdamOptimizer(learning_rate=1e-3)
        model.prepare(opt, _loss_fn)
        sched = hapi.LRSchedulerCallback(lambda ep: 1e-3 * (0.5 ** ep))
        model.fit(ds.as_arrays(), batch_size=32, epochs=3, verbose=0,
                  callbacks=[sched])
        lr_var = opt._global_learning_rate()
        lr = float(np.asarray(getattr(lr_var, "data", lr_var)).reshape(-1)[0])
        np.testing.assert_allclose(lr, 1e-3 * 0.25, rtol=1e-6)


def test_hapi_text_and_vision_zoo_exposed():
    assert hapi.text.BertModel is not None
    assert hapi.text.Transformer is not None
    assert hapi.vision.resnet50 is not None
    x = np.random.RandomState(0).rand(2, 3, 8, 8).astype(np.float32)
    n = hapi.vision.transforms.normalize(x, [0.5] * 3, [0.5] * 3)
    assert n.shape == x.shape
    r = hapi.vision.transforms.resize(x, (16, 16))
    assert r.shape == (2, 3, 16, 16)
