"""HAPI Model.fit/evaluate/predict + callbacks + vision/text/datasets
(reference incubate/hapi/model.py, callbacks.py, datasets/, vision/)."""

import os

import numpy as np
import pytest

import paddle_tpu.hapi as hapi
from paddle_tpu.fluid import dygraph
from paddle_tpu.fluid.optimizer import AdamOptimizer


def _loss_fn(pred, label):
    from paddle_tpu.fluid import layers

    return layers.mean(
        layers.softmax_with_cross_entropy(pred, layers.reshape(label,
                                                               [-1, 1])))


def test_hapi_fit_mnist_with_callbacks(tmp_path, capsys):
    with dygraph.guard():
        ds = hapi.datasets.MNIST(mode="train", n=256)
        eval_ds = hapi.datasets.MNIST(mode="test", n=64)
        model = hapi.Model(hapi.vision.LeNet())
        model.prepare(AdamOptimizer(learning_rate=1e-3), _loss_fn)
        ckpt_dir = str(tmp_path / "ckpts")
        os.makedirs(ckpt_dir)
        es = hapi.EarlyStopping(monitor="loss", patience=10)
        hist = model.fit(
            ds.as_arrays(), eval_data=eval_ds.as_arrays(),
            batch_size=64, epochs=3, eval_freq=2, log_freq=2,
            callbacks=[hapi.ModelCheckpoint(save_freq=1,
                                            save_dir=ckpt_dir), es])
        assert len(hist["loss"]) == 3
        assert hist["loss"][-1] < hist["loss"][0]
        # checkpoints written per epoch
        assert os.path.exists(os.path.join(ckpt_dir, "0.pdparams"))
        # eval scheduled on epochs 0, 2 (freq 2) and the last epoch
        out = capsys.readouterr().out
        assert "epoch 0" in out and "epoch 2 end" in out

        # predict + evaluate round out the API
        preds = model.predict(eval_ds.xs[:32], batch_size=16)
        assert preds.shape[0] == 32
        ev = model.evaluate(eval_ds.as_arrays(), batch_size=32)
        assert np.isfinite(ev["loss"])

        # save / load round trip
        path = str(tmp_path / "m")
        model.save(path)
        model2 = hapi.Model(hapi.vision.LeNet())
        model2.prepare(AdamOptimizer(learning_rate=1e-3), _loss_fn)
        model2.load(path)
        p2 = model2.predict(eval_ds.xs[:8], batch_size=8)
        np.testing.assert_allclose(p2, preds[:8], rtol=1e-5, atol=1e-6)


def test_hapi_early_stopping_restores_best(tmp_path):
    """EarlyStopping halts on a plateauing metric and restores the best
    weights (reference 2.0 EarlyStopping semantics)."""

    with dygraph.guard():
        ds = hapi.datasets.MNIST(mode="train", n=128)
        model = hapi.Model(hapi.vision.LeNet())
        model.prepare(AdamOptimizer(learning_rate=1e-3), _loss_fn)
        # min_delta=0.2: once per-epoch improvement drops under 0.2 the
        # patience counter runs out and fit halts early
        es = hapi.EarlyStopping(monitor="loss", patience=1, min_delta=0.2,
                                save_best_model=True)
        hist = model.fit(ds.as_arrays(), batch_size=64, epochs=12,
                         verbose=0, callbacks=[es])
        assert len(hist["loss"]) < 12, "early stopping never triggered"
        assert es.stopped_epoch is not None
        # best-weight restore leaves the model near its best epoch
        ev = model.evaluate(ds.as_arrays(), batch_size=64)
        assert ev["loss"] <= es.best + 0.2


def test_hapi_lr_scheduler_callback():
    with dygraph.guard():
        ds = hapi.datasets.MNIST(mode="train", n=64)
        model = hapi.Model(hapi.vision.LeNet())
        opt = AdamOptimizer(learning_rate=1e-3)
        model.prepare(opt, _loss_fn)
        sched = hapi.LRSchedulerCallback(lambda ep: 1e-3 * (0.5 ** ep))
        model.fit(ds.as_arrays(), batch_size=32, epochs=3, verbose=0,
                  callbacks=[sched])
        lr_var = opt._global_learning_rate()
        lr = float(np.asarray(getattr(lr_var, "data", lr_var)).reshape(-1)[0])
        np.testing.assert_allclose(lr, 1e-3 * 0.25, rtol=1e-6)


def test_hapi_text_and_vision_zoo_exposed():
    assert hapi.text.BertModel is not None
    assert hapi.text.Transformer is not None
    assert hapi.vision.resnet50 is not None
    x = np.random.RandomState(0).rand(2, 3, 8, 8).astype(np.float32)
    n = hapi.vision.transforms.normalize(x, [0.5] * 3, [0.5] * 3)
    assert n.shape == x.shape
    r = hapi.vision.transforms.resize(x, (16, 16))
    assert r.shape == (2, 3, 16, 16)


# ---------------------------------------------------------------------------
# round-4: static-graph adapter, transforms pipeline, text encoders,
# 2.0 metric classes (reference incubate/hapi/model.py StaticGraphAdapter,
# vision/transforms/transforms.py, text/text.py, paddle/metric/metrics.py)
# ---------------------------------------------------------------------------


def _mnist_arrays(n=128, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.rand(n, 1, 28, 28).astype(np.float32)
    ys = rng.randint(0, 10, (n, 1)).astype(np.int64)
    # plant a learnable signal: class k brightens a distinct patch
    for i in range(n):
        k = ys[i, 0]
        xs[i, 0, k * 2:(k + 1) * 2 + 2, :8] += 2.0
    return xs, ys


def _ce_loss(pred, label):
    from paddle_tpu.fluid import layers

    return layers.mean(layers.softmax_with_cross_entropy(pred, label))


def test_hapi_static_mode_fit_mnist(tmp_path):
    """Model.fit in STATIC mode (no dygraph guard): programs built from
    Input specs, trained via Executor, save/load round trip."""
    from paddle_tpu import hapi
    from paddle_tpu.models.lenet import LeNet5

    xs, ys = _mnist_arrays()
    net = LeNet5(num_classes=10)
    model = hapi.Model(
        net,
        inputs=[hapi.Input([None, 1, 28, 28], "float32", "img")],
        labels=[hapi.Input([None, 1], "int64", "lbl")],
    )
    import paddle_tpu.fluid as fluid

    model.prepare(optimizer=fluid.optimizer.AdamOptimizer(2e-3),
                  loss_function=_ce_loss,
                  metrics=[fluid.metrics.Accuracy()])
    assert model.mode == "static"
    hist = model.fit((xs, ys), batch_size=32, epochs=4, verbose=0)
    assert hist["loss"][-1] < hist["loss"][0] * 0.7
    ev = model.evaluate((xs, ys), batch_size=64)
    assert ev["loss"] < hist["loss"][0]
    pred = model.predict(xs[:16], batch_size=8)
    assert pred.shape == (16, 10)
    model.save(str(tmp_path / "static_ck"))
    # perturb then restore
    import numpy as _np

    model._adapter.scope.set(
        net.state_dict() and list(model._adapter.scope.local_names())[0],
        _np.zeros_like(_np.asarray(model._adapter.scope.find_var(
            list(model._adapter.scope.local_names())[0]))))
    model.load(str(tmp_path / "static_ck"))
    ev2 = model.evaluate((xs, ys), batch_size=64)
    assert abs(ev2["loss"] - ev["loss"]) < 1e-4


def test_hapi_both_modes_same_api(tmp_path):
    """The SAME fit() call trains in dygraph mode under the guard."""
    from paddle_tpu import hapi
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.models.lenet import LeNet5
    import paddle_tpu.fluid as fluid

    xs, ys = _mnist_arrays(n=64, seed=1)
    with dygraph.guard():
        model = hapi.Model(LeNet5(num_classes=10))
        model.prepare(optimizer=fluid.optimizer.AdamOptimizer(2e-3),
                      loss_function=_ce_loss)
        assert model.mode == "dygraph"
        hist = model.fit((xs, ys), batch_size=32, epochs=3, verbose=0)
        assert hist["loss"][-1] < hist["loss"][0]


def test_vision_transform_pipeline():
    from paddle_tpu.hapi.vision import transforms as T

    img = np.random.RandomState(0).rand(28, 28, 3).astype(np.float32)
    pipe = T.Compose([
        T.ToTensor(),                     # HWC -> CHW
        T.Resize(32),
        T.RandomCrop(28, padding=2, seed=3),
        T.RandomHorizontalFlip(prob=1.0),
        T.ColorJitter(brightness=0.2, contrast=0.2, seed=5),
        T.Normalize([0.5] * 3, [0.25] * 3),
    ])
    out = pipe(img)
    assert out.shape == (3, 28, 28)
    # deterministic flip: applying twice with prob=1 restores orientation
    f = T.RandomHorizontalFlip(prob=1.0)
    x = T.ToTensor()(img)
    np.testing.assert_allclose(f(f(x)), x)
    c = T.CenterCrop(20)(x)
    assert c.shape == (3, 20, 20)


@pytest.mark.slow
def test_text_encoders_train():
    from paddle_tpu import hapi
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.hapi.text import (
        BOWEncoder, CNNEncoder, GRUEncoder, LSTMEncoder, TextClassifier)
    import paddle_tpu.fluid as fluid

    rng = np.random.RandomState(0)
    V, T, n = 50, 12, 96
    xs = rng.randint(2, V, (n, T)).astype(np.int64)
    ys = (xs[:, 0] % 2).reshape(-1, 1).astype(np.int64)  # first-token parity

    for enc_cls in (BOWEncoder, CNNEncoder, GRUEncoder, LSTMEncoder):
        with dygraph.guard():
            enc = (enc_cls(V, 16) if enc_cls in (BOWEncoder, CNNEncoder)
                   else enc_cls(V, 16, 24))
            net = TextClassifier(enc, num_classes=2)
            model = hapi.Model(net)
            model.prepare(optimizer=fluid.optimizer.AdamOptimizer(5e-3),
                          loss_function=_ce_loss)
            hist = model.fit((xs, ys), batch_size=32, epochs=3, verbose=0)
            assert hist["loss"][-1] < hist["loss"][0], enc_cls.__name__


def test_metric_20_classes():
    from paddle_tpu import metric

    p = metric.Precision()
    r = metric.Recall()
    preds = np.array([0.9, 0.8, 0.2, 0.6])
    labels = np.array([1, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert p.accumulate() == pytest.approx(2 / 3)   # tp=2 (0.9,0.6), fp=1
    assert r.accumulate() == pytest.approx(2 / 3)   # fn=1 (0.2)
    a = metric.Auc()
    rng = np.random.RandomState(0)
    y = rng.randint(0, 2, 2000)
    scores = np.clip(y * 0.6 + rng.rand(2000) * 0.5, 0, 1)  # informative
    a.update(scores, y)
    assert 0.8 < a.accumulate() <= 1.0
    # chance-level scores ~ 0.5
    a.reset()
    a.update(rng.rand(2000), y)
    assert 0.4 < a.accumulate() < 0.6


def test_summary_and_new_callbacks(tmp_path):
    from paddle_tpu import hapi
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.models.lenet import LeNet5
    import paddle_tpu.fluid as fluid

    xs, ys = _mnist_arrays(n=64, seed=2)
    csv = tmp_path / "log.csv"
    with dygraph.guard():
        net = LeNet5(num_classes=10)
        info = hapi.summary(net)
        assert info["total_params"] > 10000 and info["layers"] >= 4
        model = hapi.Model(net)
        opt = fluid.optimizer.SGDOptimizer(0.5)
        model.prepare(optimizer=opt, loss_function=_ce_loss)
        model.fit((xs, ys), batch_size=32, epochs=6, verbose=0,
                  callbacks=[
                      hapi.ReduceLROnPlateau(patience=0, factor=0.5,
                                             monitor="loss"),
                      hapi.CSVLogger(str(csv)),
                  ])
        lines = csv.read_text().strip().splitlines()
        assert lines[0].startswith("epoch") and len(lines) >= 3


def test_two_static_models_coexist():
    """Private program clones: a second static Model trains without
    colliding with the first (review regression)."""
    from paddle_tpu import hapi
    from paddle_tpu.models.lenet import LeNet5
    import paddle_tpu.fluid as fluid

    xs, ys = _mnist_arrays(n=32, seed=3)

    def make():
        m = hapi.Model(
            LeNet5(num_classes=10),
            inputs=[hapi.Input([None, 1, 28, 28], "float32")],
            labels=[hapi.Input([None, 1], "int64")])
        m.prepare(optimizer=fluid.optimizer.AdamOptimizer(1e-3),
                  loss_function=_ce_loss)
        return m

    m1, m2 = make(), make()
    l1, _ = m1.train_batch(xs, ys)
    l2, _ = m2.train_batch(xs, ys)
    assert np.isfinite(l1) and np.isfinite(l2)
