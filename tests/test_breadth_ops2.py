"""Oracle tests for the second breadth batch (roi/psroi pooling,
matrix_nms, affine_channel, im2sequence, spp, fold, mean_iou, tensor and
math extras)."""

import numpy as np
import pytest

from op_test import run_single_op


def _r(rng, *shape):
    return rng.randn(*shape).astype(np.float32)


def test_math_extras(rng):
    x, t1, t2 = _r(rng, 3, 4), _r(rng, 3, 4), _r(rng, 3, 4)
    outs, _ = run_single_op(
        "addcmul", {"Input": x, "Tensor1": t1, "Tensor2": t2},
        {"value": 0.5}, ["Out"])
    np.testing.assert_allclose(outs["Out"], x + 0.5 * t1 * t2, rtol=1e-5)

    w = rng.rand(3, 4).astype(np.float32)
    outs, _ = run_single_op("lerp", {"X": x, "Y": t1, "Weight": w}, {},
                            ["Out"])
    np.testing.assert_allclose(outs["Out"], x + w * (t1 - x), rtol=1e-5)

    from scipy import special as sp  # scipy ships with jax's deps

    outs, _ = run_single_op("i0", {"X": x}, {}, ["Out"])
    np.testing.assert_allclose(outs["Out"], sp.i0(x), rtol=1e-4)
    outs, _ = run_single_op("i1", {"X": x}, {}, ["Out"])
    np.testing.assert_allclose(outs["Out"], sp.i1(x), rtol=1e-4)

    y = x.copy()
    y[0, 0] = np.inf
    outs, _ = run_single_op("isinf", {"X": y}, {}, ["Out"])
    assert outs["Out"][0, 0] and not outs["Out"][1, 1]

    outs, _ = run_single_op("l1_norm", {"X": x}, {}, ["Out"])
    np.testing.assert_allclose(outs["Out"], np.abs(x).sum(), rtol=1e-5)
    outs, _ = run_single_op("frobenius_norm", {"X": x}, {}, ["Out"])
    np.testing.assert_allclose(outs["Out"], np.sqrt((x ** 2).sum()),
                               rtol=1e-5)

    mx = 1.5
    outs, _ = run_single_op("clip_by_norm", {"X": x * 10},
                            {"max_norm": mx}, ["Out"])
    np.testing.assert_allclose(
        np.sqrt((outs["Out"] ** 2).sum()), mx, rtol=1e-4)


def test_modified_huber_loss(rng):
    x = _r(rng, 6, 1)
    y = (rng.rand(6, 1) > 0.5).astype(np.float32)
    outs, _ = run_single_op("modified_huber_loss", {"X": x, "Y": y}, {},
                            ["Out", "IntermediateVal"])
    z = (2 * y - 1) * x
    expect = np.where(z >= -1, np.maximum(0, 1 - z) ** 2, -4 * z)
    np.testing.assert_allclose(outs["Out"], expect, rtol=1e-5)


def test_tensor_extras2(rng):
    x = _r(rng, 3, 4)
    idx = np.array([0, 5, 11, -1], np.int64)
    outs, _ = run_single_op("take", {"X": x, "Index": idx}, {}, ["Out"])
    np.testing.assert_allclose(outs["Out"], x.reshape(-1)[idx], rtol=1e-6)

    v = _r(rng, 2, 4)
    outs, _ = run_single_op(
        "index_add",
        {"X": x, "Index": np.array([0, 2], np.int64), "AddValue": v},
        {"axis": 0}, ["Out"])
    expect = x.copy()
    expect[[0, 2]] += v
    np.testing.assert_allclose(outs["Out"], expect, rtol=1e-5)

    m = _r(rng, 4, 4)
    outs, _ = run_single_op("fill_diagonal", {"X": m}, {"value": 9.0},
                            ["Out"])
    expect = m.copy()
    np.fill_diagonal(expect, 9.0)
    np.testing.assert_allclose(outs["Out"], expect)

    outs, _ = run_single_op("diagonal", {"Input": m}, {"offset": 1},
                            ["Out"])
    np.testing.assert_allclose(outs["Out"], np.diagonal(m, offset=1))

    outs, _ = run_single_op("rot90", {"X": m}, {"k": 1, "axes": [0, 1]},
                            ["Out"])
    np.testing.assert_allclose(outs["Out"], np.rot90(m))

    big, small = _r(rng, 3, 5), _r(rng, 2, 3)
    outs, _ = run_single_op("pad_constant_like",
                            {"X": big, "Y": small}, {"pad_value": 2.0},
                            ["Out"])
    expect = np.full((3, 5), 2.0, np.float32)
    expect[:2, :3] = small
    np.testing.assert_allclose(outs["Out"], expect)

    outs, _ = run_single_op("expand_v2", {"X": _r(rng, 1, 4)},
                            {"shape": [3, -1]}, ["Out"])
    assert outs["Out"].shape == (3, 4)


def test_shuffle_and_sampling_ops(rng):
    x = np.arange(8, dtype=np.float32).reshape(8, 1)
    outs, _ = run_single_op("shuffle_batch", {"X": x}, {},
                            ["Out", "ShuffleIdx"])
    assert sorted(outs["Out"].reshape(-1).tolist()) == list(range(8))
    np.testing.assert_allclose(
        outs["Out"].reshape(-1), x.reshape(-1)[outs["ShuffleIdx"]])

    p = np.zeros((4, 5), np.float32)
    p[:, 2] = 1.0  # deterministic: category 2
    outs, _ = run_single_op("sampling_id", {"X": p}, {}, ["Out"])
    assert (outs["Out"] == 2).all()

    outs, _ = run_single_op(
        "uniform_random_batch_size_like", {"Input": _r(rng, 6, 3)},
        {"shape": [0, 7], "min": 0.0, "max": 1.0}, ["Out"])
    assert outs["Out"].shape == (6, 7)
    assert 0 <= outs["Out"].min() and outs["Out"].max() <= 1


def test_batch_fc(rng):
    x, w, b = _r(rng, 2, 3, 4), _r(rng, 2, 4, 5), _r(rng, 2, 1, 5)
    outs, _ = run_single_op("batch_fc", {"Input": x, "W": w, "Bias": b},
                            {}, ["Out"])
    np.testing.assert_allclose(
        outs["Out"], np.einsum("sbi,sio->sbo", x, w) + b, rtol=1e-4)


# ---------------------------------------------------------------------------
# vision
# ---------------------------------------------------------------------------


def test_roi_pool_oracle(rng):
    x = _r(rng, 1, 2, 8, 8)
    rois = np.array([[0, 0, 3, 3], [2, 2, 7, 7]], np.float32)
    outs, _ = run_single_op(
        "roi_pool", {"X": x, "ROIs": rois},
        {"pooled_height": 2, "pooled_width": 2, "spatial_scale": 1.0},
        ["Out"])
    got = outs["Out"]
    assert got.shape == (2, 2, 2, 2)
    # oracle for roi 0 bin (0,0): rows 0..1, cols 0..1 of a 4x4 roi
    np.testing.assert_allclose(got[0, :, 0, 0],
                               x[0, :, 0:2, 0:2].max(axis=(1, 2)),
                               rtol=1e-5)
    np.testing.assert_allclose(got[0, :, 1, 1],
                               x[0, :, 2:4, 2:4].max(axis=(1, 2)),
                               rtol=1e-5)


def test_psroi_pool_shape_and_average(rng):
    ph = pw = 2
    oc = 3
    x = _r(rng, 1, ph * pw * oc, 6, 6)
    rois = np.array([[0, 0, 6, 6]], np.float32)
    outs, _ = run_single_op(
        "psroi_pool", {"X": x, "ROIs": rois},
        {"pooled_height": ph, "pooled_width": pw, "output_channels": oc,
         "spatial_scale": 1.0}, ["Out"])
    got = outs["Out"]
    assert got.shape == (1, oc, ph, pw)
    # bin (0,0) averages group-0 channels over rows/cols 0..2
    grp0 = x[0, :oc, 0:3, 0:3]
    np.testing.assert_allclose(got[0, :, 0, 0], grp0.mean(axis=(1, 2)),
                               rtol=1e-4)


def test_affine_channel(rng):
    x = _r(rng, 2, 3, 4, 4)
    s = _r(rng, 3)
    b = _r(rng, 3)
    outs, _ = run_single_op("affine_channel",
                            {"X": x, "Scale": s, "Bias": b}, {}, ["Out"])
    np.testing.assert_allclose(
        outs["Out"], x * s[None, :, None, None] + b[None, :, None, None],
        rtol=1e-5)


def test_matrix_nms_decay(rng):
    # two overlapping boxes of one class: the lower-scored one decays
    boxes = np.array([[[0, 0, 10, 10], [0, 0, 10, 9.5],
                       [50, 50, 60, 60]]], np.float32)
    scores = np.array([[[0.9, 0.8, 0.7]]], np.float32)  # [1, C=1, M=3]
    outs, _ = run_single_op(
        "matrix_nms", {"BBoxes": boxes, "Scores": scores},
        {"score_threshold": 0.05, "nms_top_k": 3, "keep_top_k": 3,
         "use_gaussian": True, "gaussian_sigma": 0.5,
         "background_label": -1},
        ["Out"])
    got = outs["Out"][0]          # [3, 6]
    assert got[0, 1] == pytest.approx(0.9, abs=1e-5)  # top survives intact
    # the overlapping second box decayed hard; the far box decayed ~0
    decayed = got[got[:, 0] >= 0]
    far = decayed[np.isclose(decayed[:, 2], 50)]
    near = decayed[np.isclose(decayed[:, 1], decayed[:, 1].min())]
    assert far[0, 1] == pytest.approx(0.7, abs=1e-3)
    assert near[0, 1] < 0.2  # heavy gaussian decay for IoU ~0.95


def test_im2sequence(rng):
    x = _r(rng, 1, 2, 4, 4)
    outs, _ = run_single_op(
        "im2sequence", {"X": x},
        {"kernels": [2, 2], "strides": [2, 2]}, ["Out"])
    got = outs["Out"]
    assert got.shape == (4, 8)
    np.testing.assert_allclose(
        got[0], x[0, :, 0:2, 0:2].reshape(-1), rtol=1e-6)


def test_spp(rng):
    x = _r(rng, 2, 3, 8, 8)
    outs, _ = run_single_op("spp", {"X": x},
                            {"pyramid_height": 2, "pooling_type": "max"},
                            ["Out"])
    got = outs["Out"]
    assert got.shape == (2, 3 * (1 + 4))
    np.testing.assert_allclose(got[:, :3], x.max(axis=(2, 3)), rtol=1e-5)
    np.testing.assert_allclose(
        got[:, 3:6], x[:, :, :4, :4].max(axis=(2, 3)), rtol=1e-5)


def test_fold_inverts_unfold_counts(rng):
    x = _r(rng, 1, 2, 6, 6)
    unf, _ = run_single_op(
        "unfold", {"X": x},
        {"kernel_sizes": [2, 2], "strides": [2, 2]}, ["Y"])
    fold, _ = run_single_op(
        "fold", {"X": unf["Y"]},
        {"output_sizes": [6, 6], "kernel_sizes": [2, 2],
         "strides": [2, 2]}, ["Y"])
    # non-overlapping stride == kernel: fold(unfold(x)) == x
    np.testing.assert_allclose(fold["Y"], x, rtol=1e-6)


def test_random_crop(rng):
    x = _r(rng, 2, 3, 8, 8)
    outs, _ = run_single_op("random_crop", {"X": x}, {"shape": [5, 5]},
                            ["Out"])
    assert outs["Out"].shape == (2, 3, 5, 5)


def test_mean_iou(rng):
    C = 3
    pred = np.array([0, 0, 1, 1, 2, 2], np.int32)
    lab = np.array([0, 1, 1, 1, 2, 0], np.int32)
    outs, _ = run_single_op(
        "mean_iou", {"Predictions": pred, "Labels": lab},
        {"num_classes": C}, ["OutMeanIou", "OutWrong", "OutCorrect"])
    # class ious: 0: inter 1, union 3 -> 1/3; 1: inter 2, union 3 -> 2/3;
    # 2: inter 1, union 2 -> 1/2
    expect = (1 / 3 + 2 / 3 + 1 / 2) / 3
    np.testing.assert_allclose(float(outs["OutMeanIou"][0]), expect,
                               rtol=1e-5)
