"""`paddle_tpu.generation`: KV cache, decode kernel, sampling, the
continuous-batching engine's exactness vs the sequential oracle, and
its compile-once discipline.

The load-bearing drills:

* **exactness** — more requests than slots with mixed greedy/sampled
  policies and staggered finish times, so slots free and REFILL
  mid-flight; every token stream must equal the one-request-at-a-time
  oracle's, token for token, at fixed seeds;
* **compile-once** — after the executable set is built (one prefill
  per bucket + ONE decode step), further traffic compiles NOTHING
  (PR-4 compile-event accumulator) and the decode jit cache holds
  exactly one entry per engine config;
* **failure paths** — slot exhaustion sheds with Retry-After;
  over-long requests are refused up front.
"""

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import models
from paddle_tpu.fluid import dygraph

gen = paddle_tpu.generation

CFG = models.TransformerLMConfig.tiny()


@pytest.fixture(scope="module")
def lm():
    with dygraph.guard():
        np.random.seed(0)
        model = models.TransformerLM(CFG)
    return model


def make_engine(model, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", [8, 16])
    kw.setdefault("max_queue", 64)
    return gen.GenerationEngine(model, **kw)


def mixed_requests(n, max_new=6, stop=()):
    rng = np.random.RandomState(1)
    reqs = []
    for i in range(n):
        plen = int(rng.randint(2, 14))
        prompt = rng.randint(0, CFG.vocab_size, plen)
        sp = (gen.SamplingParams.greedy() if i % 2 == 0 else
              gen.SamplingParams(temperature=0.9, top_k=20, top_p=0.9,
                                 seed=100 + i))
        reqs.append(gen.GenerationRequest(
            prompt, max_new_tokens=max_new + (i % 3), sampling=sp,
            stop_token_ids=stop, request_id="t%d" % i))
    return reqs


# ---------------------------------------------------------------------------
# decode-attention kernel
# ---------------------------------------------------------------------------


class TestDecodeAttention:
    def _data(self, n=3, t=256, h=4, d=16, seed=0):
        rng = np.random.RandomState(seed)
        q = rng.randn(n, h, d).astype(np.float32)
        k = rng.randn(n, t, h, d).astype(np.float32)
        v = rng.randn(n, t, h, d).astype(np.float32)
        return q, k, v

    def test_reference_matches_plain_softmax(self):
        from paddle_tpu.ops.pallas.decode_attention import (
            decode_attention_reference,
        )
        import jax.numpy as jnp

        q, k, v = self._data()
        lens = jnp.asarray([5, 1, 200], jnp.int32)
        out = np.asarray(decode_attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lens))
        for n, L in enumerate([5, 1, 200]):
            s = np.einsum("hd,thd->ht", q[n], k[n, :L]) * 16 ** -0.5
            p = np.exp(s - s.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            ref = np.einsum("ht,thd->hd", p, v[n, :L])
            np.testing.assert_allclose(out[n], ref, rtol=1e-5,
                                       atol=1e-5)

    def test_pallas_interpret_matches_reference(self):
        from paddle_tpu.ops.pallas.decode_attention import (
            decode_attention,
            decode_attention_reference,
        )
        import jax.numpy as jnp

        q, k, v = self._data()
        lens = jnp.asarray([5, 0, 256], jnp.int32)
        ref = decode_attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lens)
        pal = decode_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), lens, interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(pal),
                                   rtol=1e-5, atol=1e-6)

    def test_interpret_mode_handles_undividable_cache_len(self):
        """A cache length no standard block divides (e.g. 64) runs as a
        single block in interpret mode instead of crashing — the
        engine's own test configs use max_len=64."""
        from paddle_tpu.ops.pallas.decode_attention import (
            decode_attention,
            decode_attention_reference,
        )
        import jax.numpy as jnp

        q, k, v = self._data(t=64)
        lens = jnp.asarray([3, 64, 10], jnp.int32)
        ref = decode_attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lens)
        pal = decode_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), lens, interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(pal),
                                   rtol=1e-5, atol=1e-6)
        with pytest.raises(ValueError, match="does not divide"):
            decode_attention(jnp.asarray(q), jnp.asarray(k),
                             jnp.asarray(v), lens, interpret=True,
                             block_k=48)

    def test_empty_slot_emits_zeros(self):
        from paddle_tpu.ops.pallas.decode_attention import (
            decode_attention,
        )
        import jax.numpy as jnp

        q, k, v = self._data(n=2)
        lens = jnp.asarray([0, 3], jnp.int32)
        for interp in (None, True):
            out = np.asarray(decode_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lens,
                interpret=interp))
            assert np.all(out[0] == 0.0)
            assert np.any(out[1] != 0.0)


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------


class TestSampling:
    def _sample(self, logits, **kw):
        import jax.numpy as jnp

        n = logits.shape[0]
        keys = np.stack([gen.make_base_key(kw.get("seed", 0) + i)
                         for i in range(n)]).astype(np.uint32)
        return np.asarray(gen.sample_tokens(
            jnp.asarray(logits), jnp.asarray(keys),
            np.full(n, kw.get("step", 0), np.int32),
            np.full(n, kw.get("temperature", 1.0), np.float32),
            np.full(n, kw.get("top_k", 0), np.int32),
            np.full(n, kw.get("top_p", 1.0), np.float32)))

    def test_greedy_is_argmax(self):
        rng = np.random.RandomState(0)
        logits = rng.randn(4, 33).astype(np.float32)
        got = self._sample(logits, temperature=0.0)
        np.testing.assert_array_equal(got, logits.argmax(-1))

    def test_top_k_restricts_support(self):
        rng = np.random.RandomState(1)
        logits = rng.randn(64, 50).astype(np.float32)
        got = self._sample(logits, temperature=1.0, top_k=3, seed=5)
        top3 = np.argsort(-logits, axis=-1)[:, :3]
        for i, t in enumerate(got):
            assert t in top3[i]

    def test_top_p_always_keeps_argmax(self):
        rng = np.random.RandomState(2)
        logits = rng.randn(32, 40).astype(np.float32)
        got = self._sample(logits, temperature=1.0, top_p=1e-9, seed=7)
        np.testing.assert_array_equal(got, logits.argmax(-1))

    def test_stream_is_slot_position_independent(self):
        """The same (seed, step, logits) samples the same token in any
        row — the property engine-vs-oracle exactness rests on."""
        import jax.numpy as jnp

        rng = np.random.RandomState(3)
        row = rng.randn(17).astype(np.float32)
        key = gen.make_base_key(42).astype(np.uint32)
        outs = []
        for pos, n in ((0, 1), (2, 4), (5, 8)):
            logits = rng.randn(n, 17).astype(np.float32)
            logits[pos] = row
            keys = rng.randint(0, 2 ** 31, (n, 2)).astype(np.uint32)
            keys[pos] = key
            got = np.asarray(gen.sample_tokens(
                jnp.asarray(logits), jnp.asarray(keys),
                np.full(n, 3, np.int32), np.full(n, 0.8, np.float32),
                np.full(n, 10, np.int32), np.full(n, 0.95, np.float32)))
            outs.append(int(got[pos]))
        assert len(set(outs)) == 1


# ---------------------------------------------------------------------------
# model: decode path == full forward
# ---------------------------------------------------------------------------


class TestTransformerLM:
    def test_prefill_equals_plain_forward(self, lm):
        from paddle_tpu.fluid import framework

        rng = np.random.RandomState(0)
        ids = rng.randint(0, CFG.vocab_size, (2, 8)).astype(np.int64)
        pos = np.tile(np.arange(8, dtype=np.int64), (2, 1))
        with dygraph.guard():
            framework._dygraph_tracer.train_mode = False
            for vb in lm.state_dict().values():
                framework._dygraph_tracer.register_var(vb)
            full = lm(dygraph.to_variable(ids),
                      dygraph.to_variable(pos)).numpy()
            pf, kvs = lm(dygraph.to_variable(ids),
                         dygraph.to_variable(pos), use_cache=True)
        np.testing.assert_array_equal(pf.numpy(), full)
        assert len(kvs) == CFG.num_layers
        assert np.asarray(kvs[0][0]).shape == (
            2, 8, CFG.num_heads, CFG.head_dim)

    def test_decode_step_equals_full_forward_last_position(self, lm):
        import jax.numpy as jnp

        from paddle_tpu.fluid import framework

        rng = np.random.RandomState(0)
        B, S, T = 2, 8, 16
        L, H, Dh = CFG.num_layers, CFG.num_heads, CFG.head_dim
        ids = rng.randint(0, CFG.vocab_size, (B, S)).astype(np.int64)
        pos = np.tile(np.arange(S, dtype=np.int64), (B, 1))
        with dygraph.guard():
            framework._dygraph_tracer.train_mode = False
            for vb in lm.state_dict().values():
                framework._dygraph_tracer.register_var(vb)
            full = lm(dygraph.to_variable(ids),
                      dygraph.to_variable(pos)).numpy()
            _, kvs = lm(dygraph.to_variable(ids[:, :S - 1]),
                        dygraph.to_variable(pos[:, :S - 1]),
                        use_cache=True)
            k_stack = np.zeros((L, B, T, H, Dh), np.float32)
            v_stack = np.zeros((L, B, T, H, Dh), np.float32)
            for li, (k, v) in enumerate(kvs):
                k_stack[li, :, :S - 1] = np.asarray(k)
                v_stack[li, :, :S - 1] = np.asarray(v)
            logits, (k2, v2) = lm(
                dygraph.to_variable(ids[:, S - 1:S]),
                dygraph.to_variable(np.full((B, 1), S - 1, np.int64)),
                caches=(jnp.asarray(k_stack), jnp.asarray(v_stack)),
                cache_positions=jnp.asarray([S - 1] * B))
        # bit-identical: the cached path IS the full math at the last row
        np.testing.assert_array_equal(logits.numpy()[:, 0], full[:, -1])
        # and the step wrote this token's K/V at position S-1
        assert np.any(np.asarray(k2)[0, :, S - 1] != 0)


# ---------------------------------------------------------------------------
# engine: exactness, continuous batching, compile-once, failure paths
# ---------------------------------------------------------------------------


class TestEngine:
    def test_exact_vs_sequential_oracle_with_midflight_refill(self, lm):
        reqs = mixed_requests(7)
        eng = make_engine(lm)
        handles = [eng.submit(r) for r in reqs]
        refilled = False
        seen_busy = False
        while eng.step():
            occ = eng.occupancy()
            if occ["free"] == 0 and occ["pending"] > 0:
                seen_busy = True
            if seen_busy and occ["pending"] < len(reqs) - eng.slots:
                refilled = True
        got = [h.result() for h in handles]
        # 7 requests over 3 slots with staggered max_new: slots MUST
        # have freed and refilled while others kept decoding
        assert refilled or len(reqs) > eng.slots
        oracle = gen.sequential_oracle(lambda: make_engine(lm), reqs)
        assert got == oracle
        # mixed policies actually exercised both samplers
        assert any(r.sampling.temperature == 0 for r in reqs)
        assert any(r.sampling.temperature > 0 for r in reqs)

    def test_stop_token_ends_stream(self, lm):
        # greedy-decode once to learn the first emitted token, then use
        # it as the stop token — deterministic stop mid-stream
        probe = make_engine(lm)
        h = probe.submit(gen.GenerationRequest([5, 7, 9],
                                               max_new_tokens=6))
        probe.run_until_idle()
        first = h.result()[0]
        eng = make_engine(lm)
        h2 = eng.submit(gen.GenerationRequest(
            [5, 7, 9], max_new_tokens=6, stop_token_ids=(first,)))
        eng.run_until_idle()
        assert h2.result() == [first]
        assert h2.finish_reason == "stop_token"

    def test_compile_once_per_config(self, lm):
        from paddle_tpu.observability import install_jax_compile_hooks
        from paddle_tpu.observability.metrics import default_registry

        install_jax_compile_hooks()
        ctr = default_registry().counter(
            "xla_compilations_total",
            "XLA backend compilations (jax.monitoring)")
        eng = make_engine(lm)
        # build the whole executable set: both buckets + the decode step
        warm = [gen.GenerationRequest(list(range(1, b + 1)),
                                      max_new_tokens=2)
                for b in eng.prefill_buckets]
        for r in warm:
            eng.submit(r)
        eng.run_until_idle()
        c0 = ctr.value
        for r in mixed_requests(6, max_new=4):
            eng.submit(r)
        eng.run_until_idle()
        assert ctr.value == c0, (
            "traffic after warmup compiled %d executables; the decode "
            "loop must compile once per config" % (ctr.value - c0))
        assert eng._decode_cache_size() == 1

    def test_slot_exhaustion_sheds_with_retry_after(self, lm):
        from paddle_tpu.serving.admission import ShedError

        eng = make_engine(lm, slots=1, max_queue=2)
        for i in range(2):   # queue fills (slots claim at step time)
            eng.submit(gen.GenerationRequest([1, 2, 3],
                                             max_new_tokens=4))
        with pytest.raises(ShedError) as ei:
            eng.submit(gen.GenerationRequest([1, 2, 3],
                                             max_new_tokens=4))
        assert ei.value.reason == "slots_full"
        assert ei.value.retry_after_s >= 1
        eng.run_until_idle()

    def test_over_long_requests_refused(self, lm):
        eng = make_engine(lm)
        with pytest.raises(ValueError):
            eng.submit(gen.GenerationRequest(list(range(17)),
                                             max_new_tokens=2))
        with pytest.raises(ValueError):
            eng.submit(gen.GenerationRequest([1, 2],
                                             max_new_tokens=100))

    def test_background_thread_mode(self, lm):
        eng = make_engine(lm).start()
        try:
            handles = [eng.submit(r) for r in mixed_requests(4)]
            got = [h.result(timeout=60) for h in handles]
            assert all(len(g) > 0 for g in got)
        finally:
            eng.stop()

    def test_occupancy_and_stats(self, lm):
        eng = make_engine(lm)
        assert eng.occupancy() == {"slots": 3, "active": 0, "free": 3,
                                   "pending": 0, "chunking": 0}
        st = eng.stats()
        assert st["decode_executables"] in (0, 1)
        assert st["cache"]["bytes"] == eng.cache.nbytes
        assert st["cache"]["paged"] is True


# ---------------------------------------------------------------------------
# kv cache / cost model / tune
# ---------------------------------------------------------------------------


def test_kv_cache_shape_and_bytes():
    c = gen.KVCache(num_layers=2, slots=3, max_len=64, num_heads=4,
                    head_dim=8)
    assert c.shape == (2, 3, 64, 4, 8)
    assert c.nbytes == 2 * 2 * 3 * 64 * 4 * 8 * 4
    d = c.describe()
    assert d["bytes"] == c.nbytes and d["dtype"] == "float32"


def test_decode_step_cost_units():
    from paddle_tpu.analysis.perf import ChipSpec, decode_step_cost

    chip = ChipSpec("test", 100e12, 100e9)
    c = decode_step_cost(num_layers=2, hidden_size=64, num_heads=4,
                         vocab_size=100, intermediate_size=128,
                         slots=4, cache_len=32, chip=chip)
    assert c.kv_read_bytes == 2 * 2 * 4 * 32 * 64 * 4
    params = 2 * (4 * 64 * 64 + 2 * 64 * 128) + 100 * 64
    assert c.param_read_bytes == params * 4
    assert c.bound == "memory"
    assert c.tokens_per_s > 0
    assert c.to_dict()["schema_version"] == 1


def test_tune_generation_slot_search():
    from paddle_tpu import tune
    from paddle_tpu.tune.space import generation_config_candidates

    cands = generation_config_candidates(
        slot_counts=(4, 8, 16), max_len=128,
        hbm_budget_bytes=10 * 2 ** 20, cache_bytes_per_slot=2 ** 20)
    assert [c.label for c in cands] == ["slots4", "slots8"]  # 16 pruned
    assert cands[0].params == {"slots": 4, "max_len": 128}

    timings = {4: 0.010, 8: 0.004}
    report = tune.search_generation_config(
        lambda p: timings[p["slots"]], workload="test-gen-search",
        slot_counts=(4, 8), max_len=128, use_cache=False)
    assert report.winner.candidate.label == "slots8"
    assert report.default_s == pytest.approx(0.010)

    with pytest.raises(ValueError):
        tune.search_generation_config(
            lambda p: 1.0, workload="none", slot_counts=(64,),
            hbm_budget_bytes=1, cache_bytes_per_slot=2 ** 30)


# ---------------------------------------------------------------------------
# per-token logprobs (opt-in) + in-place weight hot-swap
# ---------------------------------------------------------------------------


class TestLogprobsAndSwap:
    def test_logprobs_match_full_forward_rescore(self, lm):
        """Engine logprobs are log-softmax of the RAW logits at the
        sampled token — verified against a full causal forward over
        (prompt + generation), the `rl.ReferenceScorer` semantics."""
        import jax.numpy as jnp

        from paddle_tpu.fluid import framework
        from paddle_tpu.generation.sampling import token_logprobs

        eng = make_engine(lm, logprobs=True)
        req = gen.GenerationRequest(
            [3, 1, 4, 1, 5], max_new_tokens=5,
            sampling=gen.SamplingParams(temperature=0.8, top_k=10,
                                        seed=77))
        h = eng.submit(req)
        eng.run_until_idle()
        toks, lps = h.result(), h.logprobs()
        assert len(lps) == len(toks) and all(lp <= 0.0 for lp in lps)

        seq = req.prompt_ids + toks
        with dygraph.guard():
            framework._dygraph_tracer.train_mode = False
            for vb in lm.state_dict().values():
                framework._dygraph_tracer.register_var(vb)
            ids = np.asarray(seq[:-1], np.int64)[None]
            pos = np.arange(len(seq) - 1, dtype=np.int64)[None]
            logits = lm(dygraph.to_variable(ids),
                        dygraph.to_variable(pos))
        ref = np.asarray(token_logprobs(
            jnp.asarray(logits.data)[0],
            jnp.asarray(seq[1:], jnp.int32)))
        g0 = len(req.prompt_ids) - 1
        np.testing.assert_allclose(lps, ref[g0:g0 + len(toks)],
                                   rtol=2e-4, atol=2e-4)

    def test_disabled_engine_streams_are_byte_identical(self, lm):
        """logprobs=False (the default) is the pre-logprob engine to
        the byte: 3-tuple token events, empty handle.logprobs(), and
        the SAME tokens as a logprob engine at the same seeds."""
        reqs = mixed_requests(4)
        plain = make_engine(lm)
        with_lp = make_engine(lm, logprobs=True)
        ev_plain, out_plain, out_lp = [], [], []
        for r in reqs:
            h = plain.submit(gen.GenerationRequest(
                r.prompt_ids, max_new_tokens=r.max_new_tokens,
                sampling=r.sampling))
            plain.run_until_idle()
            ev_plain.extend(e for e in h.events(timeout=5.0)
                            if e[0] == "token")
            out_plain.append(h.result())
            assert h.logprobs() == []
        for r in reqs:
            h = with_lp.submit(gen.GenerationRequest(
                r.prompt_ids, max_new_tokens=r.max_new_tokens,
                sampling=r.sampling))
            with_lp.run_until_idle()
            out_lp.append(h.result())
            assert len(h.logprobs()) == len(out_lp[-1])
        assert all(len(e) == 3 for e in ev_plain)
        assert out_plain == out_lp

    def test_swap_params_serves_new_weights_without_recompile(self, lm):
        """Hot-swap: same shapes -> zero new executables, next request
        decodes under the new weights; name/shape mismatches refused."""
        eng = make_engine(lm, logprobs=True)
        req = lambda: gen.GenerationRequest([2, 7, 1, 8], max_new_tokens=4)
        h0 = eng.submit(req())
        eng.run_until_idle()
        before = h0.result()
        snap = eng.snapshot_params()

        rng = np.random.RandomState(123)
        bumped = {k: (v + rng.normal(scale=0.5, size=v.shape)
                      .astype(v.dtype) if v.ndim >= 2 else v)
                  for k, v in snap.items()}
        eng.swap_params(bumped)
        h1 = eng.submit(req())
        eng.run_until_idle()
        after = h1.result()
        assert eng._decode_cache_size() == 1
        assert after != before          # tiny-vocab greedy path moved

        eng.swap_params(snap)           # rollback restores the stream
        h2 = eng.submit(req())
        eng.run_until_idle()
        assert h2.result() == before

        with pytest.raises(ValueError):
            eng.swap_params({k: v for k, v in snap.items()
                             if k != "word.weight"})
        bad = dict(snap)
        name = next(k for k in bad if bad[k].ndim == 2)
        bad[name] = bad[name][:, :-1]
        with pytest.raises(ValueError):
            eng.swap_params(bad)


# ---------------------------------------------------------------------------
# paged KV: kernels, block pool, prefix cache (PR-17)
# ---------------------------------------------------------------------------


class TestPagedKernels:
    def _pool_from_dense(self, k, v, bs, extra_blocks=2, seed=3):
        """Scatter a dense [N, T, H, D] cache into a PERMUTED block
        pool + table — paged reads must be layout-independent."""
        rng = np.random.RandomState(seed)
        n, t, h, d = k.shape
        nb_per = t // bs
        num_blocks = 1 + n * nb_per + extra_blocks
        perm = 1 + rng.permutation(num_blocks - 1)[: n * nb_per]
        k_pool = np.zeros((num_blocks, bs, h, d), np.float32)
        v_pool = np.zeros((num_blocks, bs, h, d), np.float32)
        tables = np.zeros((n, nb_per), np.int32)
        for i in range(n):
            for j in range(nb_per):
                b = perm[i * nb_per + j]
                tables[i, j] = b
                k_pool[b] = k[i, j * bs:(j + 1) * bs]
                v_pool[b] = v[i, j * bs:(j + 1) * bs]
        return k_pool, v_pool, tables

    def test_paged_reference_matches_dense_reference(self):
        import jax.numpy as jnp

        from paddle_tpu.ops.pallas.decode_attention import (
            decode_attention_reference,
        )
        from paddle_tpu.ops.pallas.paged_attention import (
            paged_decode_attention_reference,
        )

        rng = np.random.RandomState(0)
        n, t, h, d, bs = 3, 64, 4, 16, 16
        q = rng.randn(n, h, d).astype(np.float32)
        k = rng.randn(n, t, h, d).astype(np.float32)
        v = rng.randn(n, t, h, d).astype(np.float32)
        lens = jnp.asarray([5, 0, 64], jnp.int32)
        dense = decode_attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lens)
        k_pool, v_pool, tables = self._pool_from_dense(k, v, bs)
        paged = paged_decode_attention_reference(
            jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(tables), lens)
        np.testing.assert_allclose(np.asarray(paged), np.asarray(dense),
                                   rtol=1e-6, atol=1e-6)

    def test_pallas_interpret_matches_reference(self):
        """The scalar-prefetch kernel through the interpreter, at a
        TPU-tileable geometry (bs % 128, d % 64), against the jnp
        oracle — the same pin the dense decode kernel carries."""
        import jax.numpy as jnp

        from paddle_tpu.ops.pallas.paged_attention import (
            paged_decode_attention,
            paged_decode_attention_reference,
        )

        rng = np.random.RandomState(1)
        n, h, d, bs, nb_per = 2, 2, 64, 128, 2
        q = rng.randn(n, h, d).astype(np.float32)
        k = rng.randn(n, nb_per * bs, h, d).astype(np.float32)
        v = rng.randn(n, nb_per * bs, h, d).astype(np.float32)
        k_pool, v_pool, tables = self._pool_from_dense(k, v, bs)
        lens = jnp.asarray([3, 130], jnp.int32)
        ref = paged_decode_attention_reference(
            jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(tables), lens)
        pal = paged_decode_attention(
            jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(tables), lens, interpret=True)
        np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)
        # empty slot emits exact zeros through the kernel too
        pal0 = paged_decode_attention(
            jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
            jnp.asarray(tables), jnp.asarray([0, 1], jnp.int32),
            interpret=True)
        assert np.all(np.asarray(pal0)[0] == 0.0)

    def test_chunked_reference_c1_equals_decode_reference(self):
        import jax.numpy as jnp

        from paddle_tpu.ops.pallas.decode_attention import (
            decode_attention_reference,
        )
        from paddle_tpu.ops.pallas.paged_attention import (
            chunked_attention_reference,
        )

        rng = np.random.RandomState(2)
        n, t, h, d = 3, 32, 4, 16
        q = rng.randn(n, 1, h, d).astype(np.float32)
        k = rng.randn(n, t, h, d).astype(np.float32)
        v = rng.randn(n, t, h, d).astype(np.float32)
        lens = np.asarray([7, 1, 32], np.int32)
        # decode contract: row 0 sits at position len-1 (its K/V is in)
        chunk = chunked_attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(lens - 1))
        dec = decode_attention_reference(
            jnp.asarray(q[:, 0]), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(lens))
        np.testing.assert_allclose(np.asarray(chunk)[:, 0],
                                   np.asarray(dec), rtol=1e-5,
                                   atol=1e-6)

    def test_chunked_reference_per_row_causal_mask(self):
        """Row i attends exactly t <= start + i — against a literal
        per-row numpy softmax."""
        import jax.numpy as jnp

        from paddle_tpu.ops.pallas.paged_attention import (
            chunked_attention_reference,
        )

        rng = np.random.RandomState(3)
        n, c, t, h, d = 2, 3, 16, 2, 8
        q = rng.randn(n, c, h, d).astype(np.float32)
        k = rng.randn(n, t, h, d).astype(np.float32)
        v = rng.randn(n, t, h, d).astype(np.float32)
        start = np.asarray([4, 0], np.int32)
        out = np.asarray(chunked_attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
            jnp.asarray(start)))
        for i in range(n):
            for ci in range(c):
                lim = start[i] + ci + 1
                s = np.einsum("hd,thd->ht", q[i, ci],
                              k[i, :lim]) * d ** -0.5
                p = np.exp(s - s.max(-1, keepdims=True))
                p /= p.sum(-1, keepdims=True)
                ref = np.einsum("ht,thd->hd", p, v[i, :lim])
                np.testing.assert_allclose(out[i, ci], ref, rtol=1e-5,
                                           atol=1e-5)

    def test_int8_roundtrip_and_zero_rows(self):
        import jax.numpy as jnp

        from paddle_tpu.ops.pallas.paged_attention import (
            dequantize_kv,
            quantize_kv,
        )

        rng = np.random.RandomState(4)
        x = rng.randn(5, 3, 4, 16).astype(np.float32)
        q, s = quantize_kv(jnp.asarray(x))
        assert np.asarray(q).dtype == np.int8
        back = np.asarray(dequantize_kv(q, s))
        # symmetric 127-level quantization: error <= scale/2 per elem
        amax = np.abs(x).max(-1, keepdims=True)
        assert np.all(np.abs(back - x) <= amax / 127.0 + 1e-7)
        z, zs = quantize_kv(jnp.zeros((2, 4, 8)))
        assert np.all(np.asarray(dequantize_kv(z, zs)) == 0.0)


class TestBlockPool:
    def test_alloc_free_refcount_discipline(self):
        pool = gen.BlockPool(6)
        assert pool.free_blocks == 5 and pool.used_blocks == 0
        a = pool.alloc(3)
        assert sorted(a) == [1, 2, 3]      # lowest-id-first, 0 reserved
        assert pool.used_blocks == 3
        pool.incref([a[0]])                # shared block: two users now
        assert pool.refcount(a[0]) == 2
        freed = pool.decref(a)             # first user lets go of all
        assert freed == a[1:]              # shared block NOT freed
        assert pool.refcount(a[0]) == 1
        assert pool.decref([a[0]]) == [a[0]]   # last user -> freed
        assert pool.used_blocks == 0

    def test_exhaustion_and_misuse_raise(self):
        pool = gen.BlockPool(4)
        pool.alloc(3)
        with pytest.raises(gen.PoolExhausted):
            pool.alloc(1)
        with pytest.raises(ValueError):
            pool.decref([0])               # garbage block is pinned
        pool.decref([3])
        with pytest.raises(ValueError):
            pool.decref([3])               # double free
        with pytest.raises(ValueError):
            pool.incref([3])               # incref on a free block
        with pytest.raises(ValueError):
            gen.BlockPool(1)

    def test_freed_block_is_reused_lowest_first(self):
        pool = gen.BlockPool(5)
        a = pool.alloc(4)
        pool.decref([a[1]])
        assert pool.alloc(1) == [a[1]]


class TestPrefixCache:
    def _pc(self, num_blocks=10, bs=4):
        pool = gen.BlockPool(num_blocks)
        return pool, gen.PrefixCache(pool, bs)

    def test_register_lookup_and_cap(self):
        pool, pc = self._pc()
        prompt = list(range(100, 112))          # 3 full blocks of 4
        blocks = pool.alloc(3)
        pc.register(prompt, blocks)
        assert len(pc) == 3
        # registry holds its own reference on top of the slot's
        assert all(pool.refcount(b) == 2 for b in blocks)
        n, got = pc.lookup(prompt)
        # capped one token short of the prompt: 11 usable -> 2 blocks
        assert n == 8 and got == blocks[:2]
        assert all(pool.refcount(b) == 3 for b in blocks[:2])
        n2, got2 = pc.lookup(prompt[:4] + [999] * 8)   # diverges at b1
        assert n2 == 4 and got2 == blocks[:1]
        assert pc.lookup([1, 2, 3])[0] == 0            # sub-block miss
        st = pc.stats()
        assert st["hits"] == 2 and st["misses"] == 1
        assert st["hit_tokens"] == 12

    def test_shared_block_frees_only_at_refcount_zero(self):
        pool, pc = self._pc()
        prompt = list(range(8))
        mine = pool.alloc(2)
        pc.register(prompt, mine)
        pool.decref(mine)                  # slot releases -> registry holds
        assert all(pool.refcount(b) == 1 for b in mine)
        assert pool.used_blocks == 2       # STILL allocated (cache)
        n, shared = pc.lookup(prompt + [7])
        assert n == 8 and pool.refcount(shared[0]) == 2
        # eviction cannot touch blocks with outside users
        assert pc.evict(pool.num_blocks) == 0
        assert pool.used_blocks == 2
        pool.decref(shared)                # user done
        freed = pc.evict(pool.num_blocks - 1)
        assert freed == 2 and pool.used_blocks == 0
        assert len(pc) == 0

    def test_evict_is_lru_leaf_first(self):
        pool, pc = self._pc(num_blocks=4)       # 3 usable blocks
        old = pool.alloc(1)
        new = pool.alloc(1)
        pc.register(list(range(4)), old)        # registered earlier
        pc.register(list(range(50, 54)), new)
        pool.decref(old + new)                  # registry refs only
        # touch `new` so `old` is the LRU chain
        n, got = pc.lookup(list(range(50, 55)))
        assert n == 4
        pool.decref(got)
        # pressure for 2 free (1 free now): exactly the LRU chain goes
        assert pc.evict(2) == 1
        assert pc.lookup(list(range(4)) + [9])[0] == 0     # old gone
        n2, got2 = pc.lookup(list(range(50, 55)))          # new kept
        assert n2 == 4
        pool.decref(got2)


def test_paged_kv_cache_shapes_bytes_and_tables():
    c = gen.PagedKVCache(num_layers=2, num_blocks=9, block_size=16,
                         num_heads=4, head_dim=8, slots=3, max_len=64)
    assert c.shape == (2, 9, 16, 4, 8)
    assert len(c.arrays()) == 2
    assert c.nbytes == 2 * 2 * 9 * 16 * 4 * 8 * 4
    assert c.capacity_tokens == 8 * 16
    assert c.blocks_for(17) == 2
    b = c.pool.alloc(2)
    c.assign(0, 0, b[0])
    c.assign(0, 1, b[1])
    assert list(c.table_row(0)[:2]) == b
    c.clear_slot(0)
    assert np.all(c.table_row(0) == 0)
    d = c.describe()
    assert d["paged"] is True and d["kv_dtype"] == "float32"
    assert d["blocks_used"] == 2

    i8 = gen.PagedKVCache(num_layers=2, num_blocks=9, block_size=16,
                          num_heads=4, head_dim=8, slots=3, max_len=64,
                          kv_dtype="int8")
    assert len(i8.arrays()) == 4           # + per-head scale stacks
    assert i8.nbytes == (2 * 2 * 9 * 16 * 4 * 8 * 1
                         + 2 * 2 * 9 * 16 * 4 * 4)
    assert i8.nbytes < c.nbytes
    assert i8.describe()["kv_dtype"] == "int8"


# ---------------------------------------------------------------------------
# paged engine drills (PR-17)
# ---------------------------------------------------------------------------


def _run(engine, reqs):
    handles = [engine.submit(gen.GenerationRequest(
        r.prompt_ids, max_new_tokens=r.max_new_tokens,
        sampling=r.sampling, stop_token_ids=r.stop_token_ids))
        for r in reqs]
    engine.run_until_idle()
    return [h.result() for h in handles]


class TestPagedEngine:
    def test_paged_exact_vs_dense_mixed_traffic(self, lm):
        """The acceptance gate: the paged engine is token-for-token the
        PR-15 dense engine under mixed continuous-batching traffic at
        fixed seeds (7 requests over 3 slots: slots free and refill
        mid-flight, blocks migrate between requests)."""
        reqs = mixed_requests(7)
        paged = _run(make_engine(lm), reqs)             # paged default
        dense = _run(make_engine(lm, paged=False), reqs)
        assert paged == dense
        assert any(len(t) > 0 for t in paged)

    @pytest.mark.slow
    def test_chunked_prefill_exact_vs_dense(self, lm):
        reqs = mixed_requests(6)
        chunked = _run(make_engine(lm, prefill_chunk=4), reqs)
        dense = _run(make_engine(lm, paged=False), reqs)
        assert chunked == dense

    @pytest.mark.slow
    def test_prefix_cache_hits_and_exactness(self, lm):
        """Shared-system-prompt traffic: round 2 serves the prefix from
        cache (hits, hit_tokens > 0) and the streams still equal the
        dense engine's."""
        sysp = list(range(1, 34))
        reqs = [gen.GenerationRequest(sysp + [40 + i], max_new_tokens=4,
                                      request_id="p%d" % i)
                for i in range(4)]
        eng = make_engine(lm, prefix_cache=True,
                          prefill_buckets=[8, 16, 40])
        got = _run(eng, reqs)
        dense = _run(make_engine(lm, paged=False,
                                 prefill_buckets=[8, 16, 40]), reqs)
        assert got == dense
        st = eng.stats()["prefix_cache"]
        assert st["hits"] >= 1 and st["hit_tokens"] >= 32
        assert st["entries"] >= 2
        assert eng.occupancy()["active"] == 0
        # the only live pool references left are the registry's
        assert eng.cache.pool.used_blocks == st["entries"]
        # releasing the registry returns every block: no leaks
        eng._prefix.evict(eng.cache.num_blocks - 1)
        assert eng.cache.pool.used_blocks == 0

    @pytest.mark.slow
    def test_speculative_greedy_exact_vs_dense(self, lm):
        """Draft-k speculative decoding: greedy streams equal plain
        decode exactly (verify samples with the SAME per-step PRNG
        states), and the acceptance counters are live."""
        with dygraph.guard():
            np.random.seed(7)
            draft = models.TransformerLM(CFG)
        reqs = mixed_requests(6)
        eng = make_engine(lm, draft_model=draft, draft_len=3)
        got = _run(eng, reqs)
        dense = _run(make_engine(lm, paged=False), reqs)
        assert got == dense
        spec = eng.stats()["speculative"]
        assert spec["draft_len"] == 3
        assert spec["proposed"] > 0
        assert 0.0 <= spec["acceptance_rate"] <= 1.0

    @pytest.mark.slow
    def test_int8_kv_opt_in_smoke(self, lm):
        """kv_dtype='int8' is the documented-tolerance opt-in: streams
        complete at full length (greedy may lawfully differ from f32),
        the pool stores int8 + scales, bytes shrink ~4x."""
        reqs = mixed_requests(5)
        eng = make_engine(lm, kv_dtype="int8")
        got = _run(eng, reqs)
        assert [len(t) for t in got] == \
            [r.max_new_tokens for r in reqs]
        d = eng.cache.describe()
        assert d["kv_dtype"] == "int8"
        f32 = make_engine(lm)
        assert eng.cache.nbytes < f32.cache.nbytes / 2

    def test_midflight_death_returns_every_block(self, lm):
        """The leak drill: an engine killed MID-GENERATION (slots full
        of half-decoded sequences) must hand back every pool block."""
        def hook(step_no):
            if step_no >= 2:
                raise gen.EngineDeadError("drill kill at step 2")

        eng = make_engine(lm, step_hook=hook)
        handles = [eng.submit(r) for r in mixed_requests(3, max_new=8)]
        with pytest.raises(gen.EngineDeadError):
            while eng.step():
                pass
        assert eng.dead
        assert eng.cache.pool.used_blocks == 0
        for h in handles:
            with pytest.raises(Exception):
                h.result(timeout=0.1)

    @pytest.mark.slow
    def test_tiny_pool_preempts_and_completes_everything(self, lm):
        """A pool too small for all slots at once: the engine preempts
        (restart semantics) instead of corrupting or deadlocking;
        every request still completes at full length and the pool
        drains to zero."""
        eng = make_engine(lm, kv_blocks=5, block_size=16)
        reqs = [gen.GenerationRequest(list(range(1, 15)),
                                      max_new_tokens=8,
                                      request_id="tp%d" % i)
                for i in range(3)]
        handles = [eng.submit(r) for r in reqs]
        eng.run_until_idle()
        got = [h.result() for h in handles]
        assert [len(t) for t in got] == [8, 8, 8]
        # 4 usable blocks cannot hold three 22-token sequences at once:
        # the engine MUST have preempted at least one slot
        assert eng.stats()["preempted"] >= 1
        assert eng.cache.pool.used_blocks == 0
        # exactness survives preemption: restarts replay the same
        # per-request key streams
        dense = _run(make_engine(lm, paged=False), reqs)
        assert got == dense

    def test_compile_pin_with_all_features_on(self, lm):
        """The PR-17 compile gate: prefix cache + chunked prefill +
        speculative verify all live, warmed engine, measured traffic
        compiles ZERO executables (PR-4 accumulator)."""
        from paddle_tpu.observability import install_jax_compile_hooks
        from paddle_tpu.observability.metrics import default_registry

        install_jax_compile_hooks()
        ctr = default_registry().counter(
            "xla_compilations_total",
            "XLA backend compilations (jax.monitoring)")
        with dygraph.guard():
            np.random.seed(9)
            draft = models.TransformerLM(CFG)
        eng = make_engine(lm, prefix_cache=True, prefill_chunk=8,
                          draft_model=draft, draft_len=2)
        for r in mixed_requests(6):
            eng.submit(r)
        eng.run_until_idle()
        c0 = ctr.value
        for r in mixed_requests(6):        # same length mix, rides all
            eng.submit(r)                  # warmed executables
        eng.run_until_idle()
        assert ctr.value == c0, (
            "%d executables compiled in the measured run; paged + "
            "prefix + chunk + verify must reuse the warmed set"
            % (ctr.value - c0))
        ex = eng.stats()["executables"]
        assert ex["decode_step"] <= 1 and ex["verify"] == 1

    def test_paged_knobs_require_paged(self, lm):
        with pytest.raises(ValueError):
            make_engine(lm, paged=False, prefix_cache=True)
        with pytest.raises(ValueError):
            make_engine(lm, paged=False, kv_dtype="int8")
        with pytest.raises(ValueError):
            make_engine(lm, paged=False, prefill_chunk=8)
        with pytest.raises(ValueError):
            make_engine(lm, kv_dtype="float16")
        with dygraph.guard():
            np.random.seed(11)
            draft = models.TransformerLM(CFG)
        with pytest.raises(ValueError):
            make_engine(lm, draft_model=draft)     # needs draft_len
        with pytest.raises(ValueError):
            make_engine(lm, paged=False, draft_model=draft,
                        draft_len=2)


def test_tune_generation_block_and_draft_axes():
    from paddle_tpu.tune.space import generation_config_candidates

    cands = generation_config_candidates(
        slot_counts=(4,), max_len=128, block_sizes=(16, 32),
        draft_lens=(0, 4))
    assert [c.label for c in cands] == [
        "slots4_bs16_k0", "slots4_bs16_k4",
        "slots4_bs32_k0", "slots4_bs32_k4"]
    assert cands[0].params["block_size"] == 16
    assert cands[1].params["draft_len"] == 4
    # legacy call shape unchanged: no paged keys, no suffixes
    legacy = generation_config_candidates(slot_counts=(4,), max_len=128)
    assert legacy[0].label == "slots4"
    assert "block_size" not in legacy[0].params
