"""`paddle_tpu.generation`: KV cache, decode kernel, sampling, the
continuous-batching engine's exactness vs the sequential oracle, and
its compile-once discipline.

The load-bearing drills:

* **exactness** — more requests than slots with mixed greedy/sampled
  policies and staggered finish times, so slots free and REFILL
  mid-flight; every token stream must equal the one-request-at-a-time
  oracle's, token for token, at fixed seeds;
* **compile-once** — after the executable set is built (one prefill
  per bucket + ONE decode step), further traffic compiles NOTHING
  (PR-4 compile-event accumulator) and the decode jit cache holds
  exactly one entry per engine config;
* **failure paths** — slot exhaustion sheds with Retry-After;
  over-long requests are refused up front.
"""

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import models
from paddle_tpu.fluid import dygraph

gen = paddle_tpu.generation

CFG = models.TransformerLMConfig.tiny()


@pytest.fixture(scope="module")
def lm():
    with dygraph.guard():
        np.random.seed(0)
        model = models.TransformerLM(CFG)
    return model


def make_engine(model, **kw):
    kw.setdefault("slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("prefill_buckets", [8, 16])
    kw.setdefault("max_queue", 64)
    return gen.GenerationEngine(model, **kw)


def mixed_requests(n, max_new=6, stop=()):
    rng = np.random.RandomState(1)
    reqs = []
    for i in range(n):
        plen = int(rng.randint(2, 14))
        prompt = rng.randint(0, CFG.vocab_size, plen)
        sp = (gen.SamplingParams.greedy() if i % 2 == 0 else
              gen.SamplingParams(temperature=0.9, top_k=20, top_p=0.9,
                                 seed=100 + i))
        reqs.append(gen.GenerationRequest(
            prompt, max_new_tokens=max_new + (i % 3), sampling=sp,
            stop_token_ids=stop, request_id="t%d" % i))
    return reqs


# ---------------------------------------------------------------------------
# decode-attention kernel
# ---------------------------------------------------------------------------


class TestDecodeAttention:
    def _data(self, n=3, t=256, h=4, d=16, seed=0):
        rng = np.random.RandomState(seed)
        q = rng.randn(n, h, d).astype(np.float32)
        k = rng.randn(n, t, h, d).astype(np.float32)
        v = rng.randn(n, t, h, d).astype(np.float32)
        return q, k, v

    def test_reference_matches_plain_softmax(self):
        from paddle_tpu.ops.pallas.decode_attention import (
            decode_attention_reference,
        )
        import jax.numpy as jnp

        q, k, v = self._data()
        lens = jnp.asarray([5, 1, 200], jnp.int32)
        out = np.asarray(decode_attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lens))
        for n, L in enumerate([5, 1, 200]):
            s = np.einsum("hd,thd->ht", q[n], k[n, :L]) * 16 ** -0.5
            p = np.exp(s - s.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            ref = np.einsum("ht,thd->hd", p, v[n, :L])
            np.testing.assert_allclose(out[n], ref, rtol=1e-5,
                                       atol=1e-5)

    def test_pallas_interpret_matches_reference(self):
        from paddle_tpu.ops.pallas.decode_attention import (
            decode_attention,
            decode_attention_reference,
        )
        import jax.numpy as jnp

        q, k, v = self._data()
        lens = jnp.asarray([5, 0, 256], jnp.int32)
        ref = decode_attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lens)
        pal = decode_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), lens, interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(pal),
                                   rtol=1e-5, atol=1e-6)

    def test_interpret_mode_handles_undividable_cache_len(self):
        """A cache length no standard block divides (e.g. 64) runs as a
        single block in interpret mode instead of crashing — the
        engine's own test configs use max_len=64."""
        from paddle_tpu.ops.pallas.decode_attention import (
            decode_attention,
            decode_attention_reference,
        )
        import jax.numpy as jnp

        q, k, v = self._data(t=64)
        lens = jnp.asarray([3, 64, 10], jnp.int32)
        ref = decode_attention_reference(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lens)
        pal = decode_attention(jnp.asarray(q), jnp.asarray(k),
                               jnp.asarray(v), lens, interpret=True)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(pal),
                                   rtol=1e-5, atol=1e-6)
        with pytest.raises(ValueError, match="does not divide"):
            decode_attention(jnp.asarray(q), jnp.asarray(k),
                             jnp.asarray(v), lens, interpret=True,
                             block_k=48)

    def test_empty_slot_emits_zeros(self):
        from paddle_tpu.ops.pallas.decode_attention import (
            decode_attention,
        )
        import jax.numpy as jnp

        q, k, v = self._data(n=2)
        lens = jnp.asarray([0, 3], jnp.int32)
        for interp in (None, True):
            out = np.asarray(decode_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lens,
                interpret=interp))
            assert np.all(out[0] == 0.0)
            assert np.any(out[1] != 0.0)


# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------


class TestSampling:
    def _sample(self, logits, **kw):
        import jax.numpy as jnp

        n = logits.shape[0]
        keys = np.stack([gen.make_base_key(kw.get("seed", 0) + i)
                         for i in range(n)]).astype(np.uint32)
        return np.asarray(gen.sample_tokens(
            jnp.asarray(logits), jnp.asarray(keys),
            np.full(n, kw.get("step", 0), np.int32),
            np.full(n, kw.get("temperature", 1.0), np.float32),
            np.full(n, kw.get("top_k", 0), np.int32),
            np.full(n, kw.get("top_p", 1.0), np.float32)))

    def test_greedy_is_argmax(self):
        rng = np.random.RandomState(0)
        logits = rng.randn(4, 33).astype(np.float32)
        got = self._sample(logits, temperature=0.0)
        np.testing.assert_array_equal(got, logits.argmax(-1))

    def test_top_k_restricts_support(self):
        rng = np.random.RandomState(1)
        logits = rng.randn(64, 50).astype(np.float32)
        got = self._sample(logits, temperature=1.0, top_k=3, seed=5)
        top3 = np.argsort(-logits, axis=-1)[:, :3]
        for i, t in enumerate(got):
            assert t in top3[i]

    def test_top_p_always_keeps_argmax(self):
        rng = np.random.RandomState(2)
        logits = rng.randn(32, 40).astype(np.float32)
        got = self._sample(logits, temperature=1.0, top_p=1e-9, seed=7)
        np.testing.assert_array_equal(got, logits.argmax(-1))

    def test_stream_is_slot_position_independent(self):
        """The same (seed, step, logits) samples the same token in any
        row — the property engine-vs-oracle exactness rests on."""
        import jax.numpy as jnp

        rng = np.random.RandomState(3)
        row = rng.randn(17).astype(np.float32)
        key = gen.make_base_key(42).astype(np.uint32)
        outs = []
        for pos, n in ((0, 1), (2, 4), (5, 8)):
            logits = rng.randn(n, 17).astype(np.float32)
            logits[pos] = row
            keys = rng.randint(0, 2 ** 31, (n, 2)).astype(np.uint32)
            keys[pos] = key
            got = np.asarray(gen.sample_tokens(
                jnp.asarray(logits), jnp.asarray(keys),
                np.full(n, 3, np.int32), np.full(n, 0.8, np.float32),
                np.full(n, 10, np.int32), np.full(n, 0.95, np.float32)))
            outs.append(int(got[pos]))
        assert len(set(outs)) == 1


# ---------------------------------------------------------------------------
# model: decode path == full forward
# ---------------------------------------------------------------------------


class TestTransformerLM:
    def test_prefill_equals_plain_forward(self, lm):
        from paddle_tpu.fluid import framework

        rng = np.random.RandomState(0)
        ids = rng.randint(0, CFG.vocab_size, (2, 8)).astype(np.int64)
        pos = np.tile(np.arange(8, dtype=np.int64), (2, 1))
        with dygraph.guard():
            framework._dygraph_tracer.train_mode = False
            for vb in lm.state_dict().values():
                framework._dygraph_tracer.register_var(vb)
            full = lm(dygraph.to_variable(ids),
                      dygraph.to_variable(pos)).numpy()
            pf, kvs = lm(dygraph.to_variable(ids),
                         dygraph.to_variable(pos), use_cache=True)
        np.testing.assert_array_equal(pf.numpy(), full)
        assert len(kvs) == CFG.num_layers
        assert np.asarray(kvs[0][0]).shape == (
            2, 8, CFG.num_heads, CFG.head_dim)

    def test_decode_step_equals_full_forward_last_position(self, lm):
        import jax.numpy as jnp

        from paddle_tpu.fluid import framework

        rng = np.random.RandomState(0)
        B, S, T = 2, 8, 16
        L, H, Dh = CFG.num_layers, CFG.num_heads, CFG.head_dim
        ids = rng.randint(0, CFG.vocab_size, (B, S)).astype(np.int64)
        pos = np.tile(np.arange(S, dtype=np.int64), (B, 1))
        with dygraph.guard():
            framework._dygraph_tracer.train_mode = False
            for vb in lm.state_dict().values():
                framework._dygraph_tracer.register_var(vb)
            full = lm(dygraph.to_variable(ids),
                      dygraph.to_variable(pos)).numpy()
            _, kvs = lm(dygraph.to_variable(ids[:, :S - 1]),
                        dygraph.to_variable(pos[:, :S - 1]),
                        use_cache=True)
            k_stack = np.zeros((L, B, T, H, Dh), np.float32)
            v_stack = np.zeros((L, B, T, H, Dh), np.float32)
            for li, (k, v) in enumerate(kvs):
                k_stack[li, :, :S - 1] = np.asarray(k)
                v_stack[li, :, :S - 1] = np.asarray(v)
            logits, (k2, v2) = lm(
                dygraph.to_variable(ids[:, S - 1:S]),
                dygraph.to_variable(np.full((B, 1), S - 1, np.int64)),
                caches=(jnp.asarray(k_stack), jnp.asarray(v_stack)),
                cache_positions=jnp.asarray([S - 1] * B))
        # bit-identical: the cached path IS the full math at the last row
        np.testing.assert_array_equal(logits.numpy()[:, 0], full[:, -1])
        # and the step wrote this token's K/V at position S-1
        assert np.any(np.asarray(k2)[0, :, S - 1] != 0)


# ---------------------------------------------------------------------------
# engine: exactness, continuous batching, compile-once, failure paths
# ---------------------------------------------------------------------------


class TestEngine:
    def test_exact_vs_sequential_oracle_with_midflight_refill(self, lm):
        reqs = mixed_requests(7)
        eng = make_engine(lm)
        handles = [eng.submit(r) for r in reqs]
        refilled = False
        seen_busy = False
        while eng.step():
            occ = eng.occupancy()
            if occ["free"] == 0 and occ["pending"] > 0:
                seen_busy = True
            if seen_busy and occ["pending"] < len(reqs) - eng.slots:
                refilled = True
        got = [h.result() for h in handles]
        # 7 requests over 3 slots with staggered max_new: slots MUST
        # have freed and refilled while others kept decoding
        assert refilled or len(reqs) > eng.slots
        oracle = gen.sequential_oracle(lambda: make_engine(lm), reqs)
        assert got == oracle
        # mixed policies actually exercised both samplers
        assert any(r.sampling.temperature == 0 for r in reqs)
        assert any(r.sampling.temperature > 0 for r in reqs)

    def test_stop_token_ends_stream(self, lm):
        # greedy-decode once to learn the first emitted token, then use
        # it as the stop token — deterministic stop mid-stream
        probe = make_engine(lm)
        h = probe.submit(gen.GenerationRequest([5, 7, 9],
                                               max_new_tokens=6))
        probe.run_until_idle()
        first = h.result()[0]
        eng = make_engine(lm)
        h2 = eng.submit(gen.GenerationRequest(
            [5, 7, 9], max_new_tokens=6, stop_token_ids=(first,)))
        eng.run_until_idle()
        assert h2.result() == [first]
        assert h2.finish_reason == "stop_token"

    def test_compile_once_per_config(self, lm):
        from paddle_tpu.observability import install_jax_compile_hooks
        from paddle_tpu.observability.metrics import default_registry

        install_jax_compile_hooks()
        ctr = default_registry().counter(
            "xla_compilations_total",
            "XLA backend compilations (jax.monitoring)")
        eng = make_engine(lm)
        # build the whole executable set: both buckets + the decode step
        warm = [gen.GenerationRequest(list(range(1, b + 1)),
                                      max_new_tokens=2)
                for b in eng.prefill_buckets]
        for r in warm:
            eng.submit(r)
        eng.run_until_idle()
        c0 = ctr.value
        for r in mixed_requests(6, max_new=4):
            eng.submit(r)
        eng.run_until_idle()
        assert ctr.value == c0, (
            "traffic after warmup compiled %d executables; the decode "
            "loop must compile once per config" % (ctr.value - c0))
        assert eng._decode_cache_size() == 1

    def test_slot_exhaustion_sheds_with_retry_after(self, lm):
        from paddle_tpu.serving.admission import ShedError

        eng = make_engine(lm, slots=1, max_queue=2)
        for i in range(2):   # queue fills (slots claim at step time)
            eng.submit(gen.GenerationRequest([1, 2, 3],
                                             max_new_tokens=4))
        with pytest.raises(ShedError) as ei:
            eng.submit(gen.GenerationRequest([1, 2, 3],
                                             max_new_tokens=4))
        assert ei.value.reason == "slots_full"
        assert ei.value.retry_after_s >= 1
        eng.run_until_idle()

    def test_over_long_requests_refused(self, lm):
        eng = make_engine(lm)
        with pytest.raises(ValueError):
            eng.submit(gen.GenerationRequest(list(range(17)),
                                             max_new_tokens=2))
        with pytest.raises(ValueError):
            eng.submit(gen.GenerationRequest([1, 2],
                                             max_new_tokens=100))

    def test_background_thread_mode(self, lm):
        eng = make_engine(lm).start()
        try:
            handles = [eng.submit(r) for r in mixed_requests(4)]
            got = [h.result(timeout=60) for h in handles]
            assert all(len(g) > 0 for g in got)
        finally:
            eng.stop()

    def test_occupancy_and_stats(self, lm):
        eng = make_engine(lm)
        assert eng.occupancy() == {"slots": 3, "active": 0, "free": 3,
                                   "pending": 0}
        st = eng.stats()
        assert st["decode_executables"] in (0, 1)
        assert st["cache"]["bytes"] == eng.cache.nbytes


# ---------------------------------------------------------------------------
# kv cache / cost model / tune
# ---------------------------------------------------------------------------


def test_kv_cache_shape_and_bytes():
    c = gen.KVCache(num_layers=2, slots=3, max_len=64, num_heads=4,
                    head_dim=8)
    assert c.shape == (2, 3, 64, 4, 8)
    assert c.nbytes == 2 * 2 * 3 * 64 * 4 * 8 * 4
    d = c.describe()
    assert d["bytes"] == c.nbytes and d["dtype"] == "float32"


def test_decode_step_cost_units():
    from paddle_tpu.analysis.perf import ChipSpec, decode_step_cost

    chip = ChipSpec("test", 100e12, 100e9)
    c = decode_step_cost(num_layers=2, hidden_size=64, num_heads=4,
                         vocab_size=100, intermediate_size=128,
                         slots=4, cache_len=32, chip=chip)
    assert c.kv_read_bytes == 2 * 2 * 4 * 32 * 64 * 4
    params = 2 * (4 * 64 * 64 + 2 * 64 * 128) + 100 * 64
    assert c.param_read_bytes == params * 4
    assert c.bound == "memory"
    assert c.tokens_per_s > 0
    assert c.to_dict()["schema_version"] == 1


def test_tune_generation_slot_search():
    from paddle_tpu import tune
    from paddle_tpu.tune.space import generation_config_candidates

    cands = generation_config_candidates(
        slot_counts=(4, 8, 16), max_len=128,
        hbm_budget_bytes=10 * 2 ** 20, cache_bytes_per_slot=2 ** 20)
    assert [c.label for c in cands] == ["slots4", "slots8"]  # 16 pruned
    assert cands[0].params == {"slots": 4, "max_len": 128}

    timings = {4: 0.010, 8: 0.004}
    report = tune.search_generation_config(
        lambda p: timings[p["slots"]], workload="test-gen-search",
        slot_counts=(4, 8), max_len=128, use_cache=False)
    assert report.winner.candidate.label == "slots8"
    assert report.default_s == pytest.approx(0.010)

    with pytest.raises(ValueError):
        tune.search_generation_config(
            lambda p: 1.0, workload="none", slot_counts=(64,),
            hbm_budget_bytes=1, cache_bytes_per_slot=2 ** 30)


# ---------------------------------------------------------------------------
# per-token logprobs (opt-in) + in-place weight hot-swap
# ---------------------------------------------------------------------------


class TestLogprobsAndSwap:
    def test_logprobs_match_full_forward_rescore(self, lm):
        """Engine logprobs are log-softmax of the RAW logits at the
        sampled token — verified against a full causal forward over
        (prompt + generation), the `rl.ReferenceScorer` semantics."""
        import jax.numpy as jnp

        from paddle_tpu.fluid import framework
        from paddle_tpu.generation.sampling import token_logprobs

        eng = make_engine(lm, logprobs=True)
        req = gen.GenerationRequest(
            [3, 1, 4, 1, 5], max_new_tokens=5,
            sampling=gen.SamplingParams(temperature=0.8, top_k=10,
                                        seed=77))
        h = eng.submit(req)
        eng.run_until_idle()
        toks, lps = h.result(), h.logprobs()
        assert len(lps) == len(toks) and all(lp <= 0.0 for lp in lps)

        seq = req.prompt_ids + toks
        with dygraph.guard():
            framework._dygraph_tracer.train_mode = False
            for vb in lm.state_dict().values():
                framework._dygraph_tracer.register_var(vb)
            ids = np.asarray(seq[:-1], np.int64)[None]
            pos = np.arange(len(seq) - 1, dtype=np.int64)[None]
            logits = lm(dygraph.to_variable(ids),
                        dygraph.to_variable(pos))
        ref = np.asarray(token_logprobs(
            jnp.asarray(logits.data)[0],
            jnp.asarray(seq[1:], jnp.int32)))
        g0 = len(req.prompt_ids) - 1
        np.testing.assert_allclose(lps, ref[g0:g0 + len(toks)],
                                   rtol=2e-4, atol=2e-4)

    def test_disabled_engine_streams_are_byte_identical(self, lm):
        """logprobs=False (the default) is the pre-logprob engine to
        the byte: 3-tuple token events, empty handle.logprobs(), and
        the SAME tokens as a logprob engine at the same seeds."""
        reqs = mixed_requests(4)
        plain = make_engine(lm)
        with_lp = make_engine(lm, logprobs=True)
        ev_plain, out_plain, out_lp = [], [], []
        for r in reqs:
            h = plain.submit(gen.GenerationRequest(
                r.prompt_ids, max_new_tokens=r.max_new_tokens,
                sampling=r.sampling))
            plain.run_until_idle()
            ev_plain.extend(e for e in h.events(timeout=5.0)
                            if e[0] == "token")
            out_plain.append(h.result())
            assert h.logprobs() == []
        for r in reqs:
            h = with_lp.submit(gen.GenerationRequest(
                r.prompt_ids, max_new_tokens=r.max_new_tokens,
                sampling=r.sampling))
            with_lp.run_until_idle()
            out_lp.append(h.result())
            assert len(h.logprobs()) == len(out_lp[-1])
        assert all(len(e) == 3 for e in ev_plain)
        assert out_plain == out_lp

    def test_swap_params_serves_new_weights_without_recompile(self, lm):
        """Hot-swap: same shapes -> zero new executables, next request
        decodes under the new weights; name/shape mismatches refused."""
        eng = make_engine(lm, logprobs=True)
        req = lambda: gen.GenerationRequest([2, 7, 1, 8], max_new_tokens=4)
        h0 = eng.submit(req())
        eng.run_until_idle()
        before = h0.result()
        snap = eng.snapshot_params()

        rng = np.random.RandomState(123)
        bumped = {k: (v + rng.normal(scale=0.5, size=v.shape)
                      .astype(v.dtype) if v.ndim >= 2 else v)
                  for k, v in snap.items()}
        eng.swap_params(bumped)
        h1 = eng.submit(req())
        eng.run_until_idle()
        after = h1.result()
        assert eng._decode_cache_size() == 1
        assert after != before          # tiny-vocab greedy path moved

        eng.swap_params(snap)           # rollback restores the stream
        h2 = eng.submit(req())
        eng.run_until_idle()
        assert h2.result() == before

        with pytest.raises(ValueError):
            eng.swap_params({k: v for k, v in snap.items()
                             if k != "word.weight"})
        bad = dict(snap)
        name = next(k for k in bad if bad[k].ndim == 2)
        bad[name] = bad[name][:, :-1]
        with pytest.raises(ValueError):
            eng.swap_params(bad)
