"""paddle_tpu.analysis.perf — static cost model, perf lint rules, and
the pass-pipeline ranker.

Method mirrors test_static_analysis.py: for every perf rule, build a
known-good program, seed exactly the hazard (a cancelled transpose pair,
an f32 upcast, a tiny matmul, an undonated buffer, ...) and assert the
exact diagnostic code + provenance — then assert a clean program stays
quiet.  The cost model itself is anchored to ground truth: static FLOPs
must agree with XLA's own `cost_analysis()` over the model zoo (exact
for plain matmul chains, within 15% for the matmul/conv-dominated
models), so the estimator registry cannot silently drift.
"""

import json
import os

import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import analysis, models
from paddle_tpu.analysis import perf
from paddle_tpu.analysis.perf_rules import PadWasteRule
from paddle_tpu.fluid import layers


CHIP = perf.ChipSpec("test-chip", 100e12, 1e12)


def _lint(program, rules, **kw):
    return analysis.lint_program(program, rules=rules, **kw)


# ---------------------------------------------------------------------------
# cost model: closed-form exactness + report structure
# ---------------------------------------------------------------------------


def _matmul_chain():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[32, 64], append_batch_size=False)
        w1 = main.global_block.create_parameter("pc.w1", shape=[64, 128])
        w2 = main.global_block.create_parameter("pc.w2", shape=[128, 16])
        out = layers.matmul(layers.matmul(x, w1), w2)
    return main, out


def test_matmul_flops_exact():
    main, _ = _matmul_chain()
    rep = perf.program_cost(main, chip=CHIP)
    assert rep.total_flops == 2 * 32 * 64 * 128 + 2 * 32 * 128 * 16


def test_movement_ops_cost_zero_flops_but_move_bytes():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[16, 64], append_batch_size=False)
        layers.transpose(x, [1, 0])
    rep = perf.program_cost(main, chip=CHIP)
    e = [c for c in rep.entries if c.op_type == "transpose2"][0]
    assert e.flops == 0
    assert e.bytes == 2 * 16 * 64 * 4  # read + write, f32
    assert e.bound == "memory"


def test_dynamic_dims_substituted():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 64], append_batch_size=False)
        w = main.global_block.create_parameter("pc.wd", shape=[64, 32])
        layers.matmul(x, w)
    r8 = perf.program_cost(main, chip=CHIP, dynamic_dim=8)
    r16 = perf.program_cost(main, chip=CHIP, dynamic_dim=16)
    assert r16.total_flops == 2 * r8.total_flops


def test_roofline_bound_labels():
    main, _ = _matmul_chain()
    # absurdly slow HBM: everything becomes memory-bound
    slow = perf.ChipSpec("slow-hbm", 100e12, 1e3)
    rep = perf.program_cost(main, chip=slow)
    assert all(e.bound == "memory" for e in rep.entries)
    fast = perf.ChipSpec("fast-hbm", 1e6, 1e15)
    rep = perf.program_cost(main, chip=fast)
    assert all(e.bound == "compute" for e in rep.entries
               if e.flops)


def test_cost_report_dict_and_rollups():
    main, _ = _matmul_chain()
    rep = perf.program_cost(main, chip=CHIP)
    d = rep.to_dict()
    assert d["schema_version"] == perf.CostReport.SCHEMA_VERSION
    assert d["totals"]["flops"] == rep.total_flops
    assert d["totals"]["op_count"] == len(d["ops"])
    assert d["by_op_type"][0]["op_type"] == "matmul"
    assert json.loads(json.dumps(d)) == d  # JSON-serializable
    assert rep.dominant(1)[0].op_type == "matmul"
    assert "matmul" in rep.format()


def test_cond_bills_branches_once_and_container_nothing():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[64, 64], append_batch_size=False)
        pred = layers.reduce_sum(x) > 0.0
        layers.cond(pred, lambda: layers.relu(x), lambda: x * 2.0)
    rep = perf.program_cost(main, chip=CHIP)
    cond_entries = [e for e in rep.entries if e.op_type == "cond"]
    assert cond_entries and cond_entries[0].flops == 0
    assert cond_entries[0].bytes == 0
    # each branch's real sub-block op appears exactly once — the
    # serialized attr dicts mirroring them are NOT re-counted
    assert len([e for e in rep.entries if e.op_type == "relu"]) == 1
    assert len([e for e in rep.entries if e.op_type == "scale"]) == 1


def test_recompute_segment_attr_only_ops_are_billed():
    # recompute_segment REPLACES its ops: they exist only in attrs and
    # must still be counted (unlike cond/while, whose attr dicts mirror
    # real sub-block ops)
    from paddle_tpu.fluid.framework import Operator

    main, _ = _matmul_chain()
    b = main.global_block
    mm = [op for op in b.ops if op.type == "matmul"][0]
    seg = Operator(b, "recompute_segment",
                   inputs={"X": mm.all_input_names()},
                   outputs={"Out": mm.all_output_names()},
                   attrs={"ops": [mm.to_dict()],
                          "in_names": mm.all_input_names(),
                          "out_names": mm.all_output_names()})
    b.ops[b.ops.index(mm)] = seg
    rep = perf.program_cost(main, chip=CHIP)
    # the wrapped matmul's flops survive the rewrite
    assert rep.total_flops == 2 * 32 * 64 * 128 + 2 * 32 * 128 * 16
    assert [e for e in rep.entries if e.op_type == "recompute_segment"
            ][0].flops == 0


def test_default_lint_excludes_perf_rules():
    # pre-perf-catalog behavior preserved: a clean-but-tiny program
    # yields zero findings from the default lint_program call
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        t1 = layers.data("t1", shape=[2, 3], append_batch_size=False)
        t2 = main.global_block.create_parameter("dl.w", shape=[3, 5])
        out = layers.matmul(t1, t2)
    assert not analysis.lint_program(main, fetch_names=[out.name])
    assert analysis.lint_program(
        main, fetch_names=[out.name],
        categories=("program", "perf")).by_code("tiny-matmul")


def test_cost_report_by_layer_uses_provenance():
    with analysis.provenance():
        main, _ = _matmul_chain()
    rep = perf.program_cost(main, chip=CHIP)
    layers_ = rep.by_layer()
    me = os.path.basename(__file__)
    assert any(me in g["layer"] for g in layers_), layers_


# ---------------------------------------------------------------------------
# validation harness: static FLOPs vs XLA cost_analysis (ground truth)
# ---------------------------------------------------------------------------


def test_plain_matmul_chain_matches_xla_exactly():
    main, out = _matmul_chain()
    val = perf.validate_cost_model(main, [out.name])
    if val is None:
        pytest.skip("backend reports no cost analysis")
    assert val["rel_err"] < 1e-9, val


def _zoo_resnet():
    x = layers.data("img", shape=[-1, 3, 32, 32], append_batch_size=False)
    return [models.resnet18(num_classes=7)(x)]


def _zoo_lenet():
    x = layers.data("img", shape=[-1, 1, 28, 28], append_batch_size=False)
    return [models.LeNet5()(x)]


def _zoo_bert():
    # matmul-dominated sizing (hidden 128): the acceptance shape; the
    # degenerate .tiny() config is elementwise-dominated and sits at
    # ~19% (erf-expansion accounting), checked separately below
    cfg = models.BertConfig(
        vocab_size=512, hidden_size=128, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=512,
        max_position_embeddings=128, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    B, S = 4, 64
    mk = lambda n: layers.data(  # noqa: E731
        n, shape=[B, S], append_batch_size=False, dtype="int64")
    logits, nsp = models.BertForPretraining(cfg)(
        mk("ids"), mk("seg"), mk("pos"), mk("mask"))
    return [logits, nsp]


def _zoo_transformer():
    cfg = models.TransformerConfig.tiny()
    mk = lambda n: layers.data(  # noqa: E731
        n, shape=[2, 8], append_batch_size=False, dtype="int64")
    return [models.Transformer(cfg)(
        mk("src"), mk("srcp"), mk("tgt"), mk("tgtp"))]


def _zoo_moe():
    x = layers.data("x", shape=[2, 4, 16], append_batch_size=False)
    out = models.MoEFFN(16, 32, num_experts=4)(x)
    return list(out) if isinstance(out, (list, tuple)) else [out]


_ZOO = [
    ("lenet", _zoo_lenet, 0.15),
    ("resnet", _zoo_resnet, 0.15),
    ("bert", _zoo_bert, 0.15),
    ("transformer", _zoo_transformer, 0.15),
    ("moe", _zoo_moe, 0.15),
]


@pytest.mark.parametrize("name,builder,tol", _ZOO,
                         ids=[n for n, _b, _t in _ZOO])
def test_static_flops_agree_with_xla(name, builder, tol):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetches = builder()
    val = perf.validate_cost_model(main, [f.name for f in fetches])
    if val is None:
        pytest.skip("backend reports no cost analysis")
    assert val["rel_err"] <= tol, "%s: %r" % (name, val)


@pytest.mark.slow
def test_static_flops_vgg_agrees_with_xla():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("img", shape=[-1, 3, 32, 32],
                        append_batch_size=False)
        out = models.VGG(depth=16, num_classes=5, in_channels=3)(x)
    val = perf.validate_cost_model(main, [out.name])
    if val is None:
        pytest.skip("backend reports no cost analysis")
    assert val["rel_err"] <= 0.15, val


# ---------------------------------------------------------------------------
# perf lint rules: seed exactly one hazard each, assert the exact code
# ---------------------------------------------------------------------------


def _attention_with_transposes():
    """The [B,S,H,D]->[B,H,S,D]->attention->[B,S,H,D] relayout pattern."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        q = layers.data("q", shape=[2, 16, 4, 32], append_batch_size=False)
        k = layers.data("k", shape=[2, 16, 4, 32], append_batch_size=False)
        v = layers.data("v", shape=[2, 16, 4, 32], append_batch_size=False)
        qt = layers.transpose(q, [0, 2, 1, 3])
        kt = layers.transpose(k, [0, 2, 1, 3])
        vt = layers.transpose(v, [0, 2, 1, 3])
        scores = layers.matmul(qt, kt, transpose_y=True)
        probs = layers.softmax(scores)
        ctx = layers.matmul(probs, vt)
        out = layers.transpose(ctx, [0, 2, 1, 3])
    return main, out


def test_layout_transpose_hazard_fires_with_provenance():
    with analysis.provenance():
        main, _out = _attention_with_transposes()
    diags = _lint(main, ["layout-transpose-hazard"])
    hits = diags.by_code("layout-transpose-hazard")
    assert hits, diags.format()
    assert hits[0].op_type in ("transpose2", "transpose")
    assert hits[0].provenance, "diagnostic must carry the op callsite"
    assert os.path.basename(__file__) in hits[0].provenance[0]


def test_layout_transpose_hazard_survives_diamond_def_chain():
    # the transposed value feeds the matmul AND a residual add: the
    # un-crossed path through the add must not mask the crossed one
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 8, 16], append_batch_size=False)
        w = main.global_block.create_parameter("dd.w", shape=[8, 8])
        t1 = layers.transpose(x, [0, 2, 1])          # [4, 16, 8]
        v = layers.scale(t1, scale=2.0)
        b = layers.matmul(v, w)                      # [4, 16, 8]
        d = b + v                                    # residual: v reused
        layers.transpose(d, [0, 2, 1])
    hits = _lint(main, ["layout-transpose-hazard"])
    assert hits.by_code("layout-transpose-hazard"), hits.format()


def test_layout_transpose_hazard_quiet_without_cancellation():
    # single transpose, no inverse downstream: no hazard
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        q = layers.data("q", shape=[2, 16, 4, 32], append_batch_size=False)
        qt = layers.transpose(q, [0, 2, 1, 3])
        layers.reduce_sum(qt)
    assert not _lint(main, ["layout-transpose-hazard"])


def test_dtype_promotion_fires_on_f32_in_bf16_region():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 64], append_batch_size=False,
                        dtype="bfloat16")
        y = layers.data("y", shape=[8, 64], append_batch_size=False,
                        dtype="float32")
        with analysis.provenance():
            x + y
    hits = _lint(main, ["dtype-promotion"]).by_code("dtype-promotion")
    assert hits and hits[0].op_type == "elementwise_add"
    assert set(hits[0].var_names) == {"x", "y"}
    assert hits[0].provenance


def test_dtype_promotion_quiet_on_uniform_dtypes():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 64], append_batch_size=False,
                        dtype="bfloat16")
        y = layers.data("y", shape=[8, 64], append_batch_size=False,
                        dtype="bfloat16")
        x + y
    assert not _lint(main, ["dtype-promotion"])


def test_unfused_epilogue_fires_on_matmul_bias_act():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[64, 256], append_batch_size=False)
        w = main.global_block.create_parameter("pe.w", shape=[256, 512])
        b = main.global_block.create_parameter("pe.b", shape=[512])
        with analysis.provenance():
            h = layers.matmul(a, w)
        layers.gelu(h + b)
    hits = _lint(main, ["unfused-epilogue"]).by_code("unfused-epilogue")
    assert hits and hits[0].op_type == "matmul"
    assert "gelu" in hits[0].message
    assert hits[0].provenance


def test_unfused_epilogue_quiet_when_intermediate_reused():
    # bias-add output consumed twice: fusing would recompute — no finding
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[64, 256], append_batch_size=False)
        w = main.global_block.create_parameter("pe2.w", shape=[256, 512])
        b = main.global_block.create_parameter("pe2.b", shape=[512])
        h = layers.matmul(a, w) + b
        layers.gelu(h)
        layers.reduce_sum(h)
    assert not _lint(main, ["unfused-epilogue"])


def test_tiny_matmul_fires_below_mxu_tile():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        t1 = layers.data("t1", shape=[2, 3], append_batch_size=False)
        t2 = main.global_block.create_parameter("pt.w", shape=[3, 5])
        with analysis.provenance():
            layers.matmul(t1, t2)
    hits = _lint(main, ["tiny-matmul"]).by_code("tiny-matmul")
    assert hits and hits[0].op_type == "matmul"
    assert hits[0].provenance


def test_tiny_matmul_quiet_at_mxu_shapes():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[256, 256], append_batch_size=False)
        w = main.global_block.create_parameter("pt2.w", shape=[256, 256])
        layers.matmul(x, w)
    assert not _lint(main, ["tiny-matmul"])


def test_pad_waste_fires_on_coarse_ladder():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        s = layers.data("seq", shape=[-1, -1, 64], append_batch_size=False)
        layers.reduce_sum(s)
    rule = PadWasteRule(ladders={"seq": {1: [8, 64]}})
    hits = _lint(main, [rule]).by_code("pad-waste")
    # axis 1 ladder [8, 64]: worst case is a length-1 request padding to
    # the first bucket, 1 - 1/8 = 88% padding
    assert hits and hits[0].var_names == ("seq",)
    assert "88%" in hits[0].message
    # default powers-of-two ladder stays under the 50% budget
    assert not _lint(main, [PadWasteRule()])


def test_pad_waste_threshold_catches_default_ladder():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        s = layers.data("seq", shape=[-1, 64], append_batch_size=False)
        layers.reduce_sum(s)
    assert _lint(main, [PadWasteRule(threshold=0.3)]).by_code("pad-waste")


def test_missed_donation_fires_on_same_shape_feed_output():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[256, 256], append_batch_size=False)
        out = layers.relu(x)
    hits = _lint(main, ["missed-donation"],
                 fetch_names=[out.name]).by_code("missed-donation")
    assert hits and hits[0].var_names == ("x", out.name)


def test_missed_donation_quiet_on_shape_mismatch_or_live_input():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[256, 256], append_batch_size=False)
        out = layers.reduce_sum(x)          # different shape
    assert not _lint(main, ["missed-donation"], fetch_names=[out.name])
    # and without a fetch list the rule cannot judge: stays quiet
    assert not _lint(main, ["missed-donation"])


# ---------------------------------------------------------------------------
# mixed-dtype-matmul producer attribution (the def-chain walk)
# ---------------------------------------------------------------------------


def test_mixed_dtype_matmul_names_promoting_cast():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 32], append_batch_size=False,
                        dtype="bfloat16")
        w = main.global_block.create_parameter(
            "md.w", shape=[32, 16], dtype="bfloat16")
        w32 = layers.cast(w, "float32")
        w32r = layers.reshape(w32, [32, 16])   # dtype-preserving hop
        layers.matmul(x, w32r)
    hits = _lint(main, ["mixed-dtype-matmul"]).by_code("mixed-dtype-matmul")
    assert hits, "promotion must fire"
    # the walk crosses the reshape and lands on the cast that upcast
    assert "'cast'" in hits[0].message, hits[0].message
    assert "float32" in hits[0].message


def test_mixed_dtype_matmul_names_parameter_origin():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 32], append_batch_size=False,
                        dtype="bfloat16")
        w = main.global_block.create_parameter(
            "md2.w", shape=[32, 16], dtype="float32")
        layers.matmul(x, w)
    hits = _lint(main, ["mixed-dtype-matmul"]).by_code("mixed-dtype-matmul")
    assert hits and "parameter" in hits[0].message
    assert "'md2.w'" in hits[0].message


def test_mixed_dtype_matmul_param_behind_passthrough_blames_param():
    # an f32 parameter reaching the matmul through a dtype-preserving
    # reshape must be blamed itself — not the reshape hop
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 32], append_batch_size=False,
                        dtype="bfloat16")
        w = main.global_block.create_parameter(
            "md3.w", shape=[16, 32], dtype="float32")
        wr = layers.reshape(w, [32, 16])
        layers.matmul(x, wr)
    hits = _lint(main, ["mixed-dtype-matmul"]).by_code("mixed-dtype-matmul")
    assert hits and "parameter" in hits[0].message
    assert "'md3.w'" in hits[0].message
    # blamed the producer-less endpoint, not a dtype-preserving op
    assert "introduced by" not in hits[0].message


# ---------------------------------------------------------------------------
# rule catalog hygiene
# ---------------------------------------------------------------------------


def test_perf_rules_registered_under_perf_category():
    from paddle_tpu.analysis import lint_rules

    perf_rules = set(lint_rules(category="perf"))
    assert {"layout-transpose-hazard", "dtype-promotion",
            "unfused-epilogue", "tiny-matmul", "pad-waste",
            "missed-donation"} <= perf_rules
    # the correctness catalog is unchanged by the perf additions
    assert "dead-op" in lint_rules(category="program")
    assert not perf_rules & set(lint_rules(category="program"))


def test_model_zoo_stays_clean_under_perf_rules():
    # perf findings are advisory: never error-severity
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        fetches = _zoo_transformer()
    diags = analysis.lint_program(
        main, fetch_names=[f.name for f in fetches],
        categories=("perf",))
    assert not diags.errors(), diags.format()


# ---------------------------------------------------------------------------
# pass-pipeline ranking
# ---------------------------------------------------------------------------


def _conv_bn_relu():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("img", shape=[8, 16, 16, 16],
                        append_batch_size=False)
        c = layers.conv2d(x, num_filters=32, filter_size=3, padding=1,
                          data_format="NHWC")
        bn = layers.batch_norm(c, data_layout="NHWC")
        layers.relu(bn)
    return main


def test_rank_pass_pipelines_prefers_fusion():
    main = _conv_bn_relu()
    n_ops = len(main.global_block.ops)
    ranked = perf.rank_pass_pipelines(
        main, [[], ["batch_norm_act_fuse"]], chip=CHIP)
    assert ranked[0].pipeline == ("batch_norm_act_fuse",)
    assert ranked[0].time_s < ranked[1].time_s
    # candidates ran on clones: the original program is untouched
    assert len(main.global_block.ops) == n_ops
    d = ranked[0].to_dict()
    assert d["pipeline"] == ["batch_norm_act_fuse"] and d["error"] is None


def test_rank_pass_pipelines_excludes_broken_candidate():
    from paddle_tpu.fluid import ir

    class _BreakerPass(ir.Pass):
        name = "test_breaker"

        def apply(self, program):
            # strand a var: delete the op that produces the relu input
            del program.global_block.ops[1]
            return program

    main = _conv_bn_relu()
    ranked = perf.rank_pass_pipelines(
        main, [[_BreakerPass()], []], chip=CHIP, verify=True)
    assert ranked[0].pipeline == ()          # healthy baseline wins
    assert ranked[-1].report is None         # breaker excluded
    assert ranked[-1].error and "test_breaker" in ranked[-1].error


# ---------------------------------------------------------------------------
# CLIs: program_cost + program_lint perf surface
# ---------------------------------------------------------------------------


def _load_tool(name):
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(repo, "tools", "%s.py" % name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_program_cost_cli_json_roundtrip(tmp_path, capsys):
    pc = _load_tool("program_cost")
    main, _ = _matmul_chain()
    path = str(tmp_path / "prog.json")
    with open(path, "w") as f:
        f.write(main.to_json())

    assert pc.main([path, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    # the documented schema round-trips
    assert out["schema_version"] == 1
    assert out["model"] == path
    assert out["totals"]["flops"] == 2 * 32 * 64 * 128 + 2 * 32 * 128 * 16
    assert out["chip"]["peak_flops"] > 0
    assert out["by_op_type"][0]["op_type"] == "matmul"
    assert all(set(o) >= {"block_idx", "op_idx", "op_type", "flops",
                          "bytes", "time_s", "bound"} for o in out["ops"])
    assert out["within_budget"] is None

    # --no-ops drops the per-op array, text mode prints the table
    assert pc.main([path, "--json", "--no-ops"]) == 0
    assert "ops" not in json.loads(capsys.readouterr().out)
    assert pc.main([path]) == 0
    assert "matmul" in capsys.readouterr().out


def test_program_cost_cli_budget_rc(tmp_path, capsys):
    pc = _load_tool("program_cost")
    main, _ = _matmul_chain()
    path = str(tmp_path / "prog.json")
    with open(path, "w") as f:
        f.write(main.to_json())
    assert pc.main([path, "--budget-ms", "1e-12", "--json"]) == 1
    assert json.loads(capsys.readouterr().out)["within_budget"] is False
    assert pc.main([path, "--budget-ms", "1e6"]) == 0


def test_program_lint_cli_perf_flags(tmp_path, capsys):
    pl = _load_tool("program_lint")
    main, _out = _attention_with_transposes()
    path = str(tmp_path / "prog.json")
    with open(path, "w") as f:
        f.write(main.to_json())
    feeds = "q,k,v"

    # without --perf the hazard rules do not run
    assert pl.main([path, "--feed", feeds, "--fetch", _out.name,
                    "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["schema_version"] == pl.SCHEMA_VERSION
    assert {"diagnostics", "summary"} <= set(out)
    codes = {d["code"] for d in out["diagnostics"]}
    assert "layout-transpose-hazard" not in codes

    # --perf runs them (warnings: rc stays 0)
    assert pl.main([path, "--feed", feeds, "--fetch", _out.name,
                    "--json", "--perf"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert "layout-transpose-hazard" in {
        d["code"] for d in out["diagnostics"]}

    # --budget-ms below the estimate flips rc 1 and reports the numbers
    assert pl.main([path, "--feed", feeds, "--fetch", _out.name,
                    "--json", "--budget-ms", "1e-12"]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["budget"]["within_budget"] is False
    assert out["budget"]["estimated_ms"] > 0


def test_program_lint_cli_perf_composes_with_explicit_rules(tmp_path,
                                                            capsys):
    pl = _load_tool("program_lint")
    main, _out = _attention_with_transposes()
    path = str(tmp_path / "prog.json")
    with open(path, "w") as f:
        f.write(main.to_json())
    assert pl.main([path, "--feed", "q,k,v", "--fetch", _out.name,
                    "--rules", "dead-op", "--perf", "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert "layout-transpose-hazard" in {
        d["code"] for d in out["diagnostics"]}


def test_program_lint_cli_max_pad_waste(tmp_path, capsys):
    pl = _load_tool("program_lint")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        s = layers.data("seq", shape=[-1, 64], append_batch_size=False)
        out = layers.reduce_sum(s)
    path = str(tmp_path / "prog.json")
    with open(path, "w") as f:
        f.write(main.to_json())
    # powers-of-two ladder worst case is just under 0.5: a 0.3 budget
    # fires and flips rc even though the finding is a warning
    assert pl.main([path, "--feed", "seq", "--fetch", out.name,
                    "--json", "--max-pad-waste", "0.3"]) == 1
    outj = json.loads(capsys.readouterr().out)
    assert "pad-waste" in {d["code"] for d in outj["diagnostics"]}
    assert pl.main([path, "--feed", "seq", "--fetch", out.name,
                    "--max-pad-waste", "0.6"]) == 0
    capsys.readouterr()


# ---------------------------------------------------------------------------
# PR 11: rule<->pass linkage (fix hints), reshape/cast see-through, and
# the fused-GEMM cost estimator
# ---------------------------------------------------------------------------


def test_unfused_epilogue_sees_through_reshape_and_carries_fix():
    """The BERT FFN can emit a reshape between matmul and add — pure
    data movement must not hide the fusion candidate, and the finding
    names the pass that fixes it."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[8, 16, 32], append_batch_size=False)
        w = main.global_block.create_parameter("rsh.w", shape=[32, 64])
        b = main.global_block.create_parameter("rsh.b", shape=[64])
        mm = layers.mul(a, w, x_num_col_dims=2)
        r = layers.reshape(mm, [128, 64])
        layers.gelu(layers.elementwise_add(r, b, axis=1))
    hits = _lint(main, ["unfused-epilogue"]).by_code("unfused-epilogue")
    assert hits, "reshape hid the epilogue chain"
    assert hits[0].fix == "matmul_bias_act_fuse"
    assert "interposed" in hits[0].message


def test_unfused_epilogue_sees_through_cast():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[8, 32], append_batch_size=False)
        w = main.global_block.create_parameter("cst.w", shape=[32, 64])
        b = main.global_block.create_parameter("cst.b", shape=[64],
                                               dtype="float32")
        mm = layers.matmul(a, w)
        c = layers.cast(mm, "float32")
        layers.relu(layers.elementwise_add(c, b, axis=1))
    hits = _lint(main, ["unfused-epilogue"]).by_code("unfused-epilogue")
    # flagged — but the fuse pass declines cast hops (a cast changes
    # numerics inside the chain), so no fix hint is attached
    assert hits and hits[0].fix is None


def test_unfused_epilogue_reshape_with_fanout_stays_quiet():
    # the interposed reshape's output is consumed twice: not privately
    # fusable, no finding
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        a = layers.data("a", shape=[8, 16, 32], append_batch_size=False)
        w = main.global_block.create_parameter("rsf.w", shape=[32, 64])
        b = main.global_block.create_parameter("rsf.b", shape=[64])
        r = layers.reshape(layers.mul(a, w, x_num_col_dims=2), [128, 64])
        layers.gelu(layers.elementwise_add(r, b, axis=1))
        layers.reduce_sum(r)
    assert not _lint(main, ["unfused-epilogue"])


def test_layout_transpose_hazard_carries_fix():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("hx", shape=[2, 8, 16], append_batch_size=False)
        w = main.global_block.create_parameter("hz.w", shape=[16, 16])
        t1 = layers.transpose(x, [0, 2, 1])
        t1b = layers.transpose(t1, [0, 2, 1])
        layers.transpose(layers.matmul(t1b, w), [0, 2, 1])
    hits = _lint(main, ["layout-transpose-hazard"]).by_code(
        "layout-transpose-hazard")
    assert hits and hits[0].fix == "transpose_fold"
    assert hits[0].to_dict()["fix"] == "transpose_fold"


def test_matmul_bias_act_cost_is_one_pass_of_epilogue_bytes():
    """The fused op bills matmul FLOPs + one epilogue pass — NOT the
    unfused three-op [M,N] traffic — so the static ranker prefers the
    fusion (the estimator registered like batch_norm_act_fuse's)."""
    def build():
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            x = layers.data("x", shape=[64, 128], append_batch_size=False)
            w = main.global_block.create_parameter("fcost.w",
                                                   shape=[128, 256])
            b = main.global_block.create_parameter("fcost.b", shape=[256])
            layers.gelu(layers.elementwise_add(
                layers.mul(x, w), b, axis=1))
        return main

    main = build()
    from paddle_tpu.fluid import ir

    fused = ir.clone_and_apply(main, ["matmul_bias_act_fuse"],
                               verify=True)
    rep_unfused = perf.program_cost(main, chip=CHIP)
    rep_fused = perf.program_cost(fused, chip=CHIP)
    # matmul FLOPs identical; epilogue flops preserved within the op
    assert rep_fused.total_flops == pytest.approx(
        rep_unfused.total_flops, rel=1e-6)
    # but the [M,N] intermediate no longer round-trips: strictly fewer
    # bytes moved, strictly less estimated time
    assert rep_fused.total_bytes < rep_unfused.total_bytes
    assert rep_fused.total_time_s < rep_unfused.total_time_s


def test_rank_pass_pipelines_prefers_matmul_bias_act_fuse():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[64, 128], append_batch_size=False)
        w = main.global_block.create_parameter("frank.w",
                                               shape=[128, 256])
        b = main.global_block.create_parameter("frank.b", shape=[256])
        layers.gelu(layers.elementwise_add(layers.mul(x, w), b, axis=1))
    ranked = perf.rank_pass_pipelines(
        main, [[], ["matmul_bias_act_fuse"]], chip=CHIP)
    assert ranked[0].pipeline == ("matmul_bias_act_fuse",)
    assert ranked[0].time_s < ranked[1].time_s
