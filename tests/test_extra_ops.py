"""OpTest oracles for the round-2 breadth op families (linalg_ops.py,
extra_ops.py) — outputs vs numpy/scipy, finite-difference grads for a
representative sample (reference tests/unittests/test_*_op.py pattern)."""

import numpy as np
import pytest
import scipy.special

from op_test import check_grad, check_output, run_single_op

rng = np.random.RandomState(7)


def _r(*shape):
    return rng.randn(*shape).astype(np.float32)


# ---------------------------------------------------------------------------
# unary activations / math
# ---------------------------------------------------------------------------

UNARY_CASES = [
    ("sinh", np.sinh, _r(3, 4), {}),
    ("cosh", np.cosh, _r(3, 4), {}),
    ("tan", np.tan, _r(3, 4) * 0.5, {}),
    ("asin", np.arcsin, _r(3, 4) * 0.5, {}),
    ("acos", np.arccos, _r(3, 4) * 0.5, {}),
    ("atan", np.arctan, _r(3, 4), {}),
    ("asinh", np.arcsinh, _r(3, 4), {}),
    ("acosh", np.arccosh, np.abs(_r(3, 4)) + 1.5, {}),
    ("atanh", np.arctanh, _r(3, 4) * 0.5, {}),
    ("expm1", np.expm1, _r(3, 4), {}),
    ("log1p", np.log1p, np.abs(_r(3, 4)), {}),
    ("log2", np.log2, np.abs(_r(3, 4)) + 0.1, {}),
    ("log10", np.log10, np.abs(_r(3, 4)) + 0.1, {}),
    ("lgamma", scipy.special.gammaln, np.abs(_r(3, 4)) + 0.5, {}),
    ("digamma", scipy.special.digamma, np.abs(_r(3, 4)) + 0.5, {}),
    ("erfinv", scipy.special.erfinv, _r(3, 4) * 0.5, {}),
    ("trunc", np.trunc, _r(3, 4) * 3, {}),
    ("frac", lambda x: x - np.trunc(x), _r(3, 4) * 3, {}),
    ("tanh_shrink", lambda x: x - np.tanh(x), _r(3, 4), {}),
    ("hard_shrink", lambda x: np.where(np.abs(x) > 0.5, x, 0), _r(3, 4), {}),
    ("softshrink",
     lambda x: np.sign(x) * np.maximum(np.abs(x) - 0.5, 0), _r(3, 4), {}),
    ("thresholded_relu", lambda x: np.where(x > 1.0, x, 0), _r(3, 4) * 2, {}),
    ("stanh", lambda x: 1.7159 * np.tanh(0.67 * x), _r(3, 4), {}),
    ("mish",
     lambda x: x * np.tanh(np.log1p(np.exp(-np.abs(x)))
                           + np.maximum(x, 0)), _r(3, 4), {}),
    ("selu",
     lambda x: 1.0507009873554805 * np.where(
         x > 0, x, 1.6732632423543772 * (np.exp(x) - 1)), _r(3, 4), {}),
    ("erfc", scipy.special.erfc, _r(3, 4), {}),
    ("hard_swish",
     lambda x: x * np.clip(x / 6.0 + 0.5, 0, 1), _r(3, 4) * 4, {}),
]


@pytest.mark.parametrize(
    "op,ref,x,attrs", UNARY_CASES, ids=[c[0] for c in UNARY_CASES]
)
def test_unary_op(op, ref, x, attrs):
    check_output(op, {"X": x}, attrs, {"Out": ref(x)}, rtol=2e-5, atol=2e-5)


def test_unary_grads_sample():
    for op, x in [("sinh", _r(2, 3)), ("log1p", np.abs(_r(2, 3)) + 0.2),
                  ("mish", _r(2, 3))]:
        check_grad(op, {"X": x}, {}, ["Out"], ["X"])


def test_atan2_logsumexp_cumprod():
    x, y = _r(3, 4), np.abs(_r(3, 4)) + 0.1
    check_output("atan2", {"X1": x, "X2": y}, {},
                 {"Out": np.arctan2(x, y)})
    check_output("logsumexp", {"X": x}, {"axis": [1], "keepdim": False},
                 {"Out": scipy.special.logsumexp(x, axis=1)}, rtol=1e-5)
    check_output("cumprod", {"X": x}, {"dim": 1},
                 {"Out": np.cumprod(x, axis=1)}, rtol=1e-5)


# ---------------------------------------------------------------------------
# linalg
# ---------------------------------------------------------------------------


def test_kron_einsum_multidot():
    a, b = _r(2, 3), _r(4, 5)
    check_output("kron", {"X": a, "Y": b}, {}, {"Out": np.kron(a, b)})
    x, y = _r(3, 4), _r(4, 5)
    check_output("einsum", {"Operands": [x, y]}, {"equation": "ij,jk->ik"},
                 {"Out": x @ y}, rtol=1e-4)
    z = _r(5, 2)
    check_output("multi_dot", {"X": [x, y, z]}, {},
                 {"Out": x @ y @ z}, rtol=1e-4)


def test_cholesky_inverse_matrix_power_triangular_solve():
    a = _r(4, 4)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    check_output("cholesky", {"X": spd}, {},
                 {"Out": np.linalg.cholesky(spd)}, rtol=1e-4, atol=1e-4)
    check_output("inverse", {"Input": spd}, {},
                 {"Output": np.linalg.inv(spd)}, rtol=1e-3, atol=1e-4)
    check_output("matrix_power", {"X": spd}, {"n": 3},
                 {"Out": np.linalg.matrix_power(spd, 3)}, rtol=1e-3)
    L = np.tril(a) + 4 * np.eye(4, dtype=np.float32)
    b = _r(4, 2)
    check_output(
        "triangular_solve", {"X": L, "Y": b},
        {"upper": False},
        {"Out": scipy.linalg.solve_triangular(L, b, lower=True)},
        rtol=1e-4, atol=1e-5,
    )


def test_cross_trace_diag():
    x, y = _r(4, 3), _r(4, 3)
    check_output("cross", {"X": x, "Y": y}, {"dim": 1},
                 {"Out": np.cross(x, y, axis=1)}, rtol=1e-5)
    m = _r(4, 4)
    check_output("trace", {"Input": m}, {}, {"Out": np.trace(m)}, rtol=1e-5)
    v = _r(5)
    check_output("diag_v2", {"X": v}, {"offset": 1},
                 {"Out": np.diag(v, k=1)})


def test_diag_embed():
    x = _r(2, 3)
    want = np.zeros((2, 3, 3), np.float32)
    for i in range(2):
        want[i] = np.diag(x[i])
    check_output("diag_embed", {"Input": x}, {}, {"Out": want})


def test_dist_histogram_bincount_index_sample():
    x, y = _r(3, 4), _r(3, 4)
    check_output("dist", {"X": x, "Y": y}, {"p": 2.0},
                 {"Out": np.linalg.norm((x - y).reshape(-1))}, rtol=1e-5)
    ints = rng.randint(0, 10, (20,)).astype(np.int64)
    want = np.bincount(ints, minlength=10)
    check_output("bincount", {"X": ints}, {"minlength": 10}, {"Out": want})
    xi = _r(3, 5)
    idx = rng.randint(0, 5, (3, 2)).astype(np.int64)
    check_output("index_sample", {"X": xi, "Index": idx}, {},
                 {"Out": np.take_along_axis(xi, idx, axis=1)})


# ---------------------------------------------------------------------------
# manipulation
# ---------------------------------------------------------------------------


def test_manipulation_ops():
    x = _r(3, 4)
    check_output("roll", {"X": x}, {"shifts": [1], "axis": [0]},
                 {"Out": np.roll(x, 1, 0)})
    check_output("flip", {"X": x}, {"axis": [1]}, {"Out": np.flip(x, 1)})
    b = _r(1, 4)
    check_output("broadcast_to", {"X": b}, {"shape": [3, 4]},
                 {"Out": np.broadcast_to(b, (3, 4))})
    check_output("repeat_interleave", {"X": x}, {"repeats": 2, "dim": 1},
                 {"Out": np.repeat(x, 2, axis=1)})
    idx = rng.randint(0, 3, (3, 4)).astype(np.int64)
    check_output("take_along_axis", {"Input": x, "Index": idx}, {"Axis": 0},
                 {"Result": np.take_along_axis(x, idx, 0)})


def test_put_along_axis_and_scatter_nd_add():
    x = _r(3, 4)
    idx = rng.randint(0, 3, (2, 4)).astype(np.int64)
    v = _r(2, 4)
    want = x.copy()
    np.put_along_axis(want, idx, v, axis=0)
    # duplicate indices: last-write-wins differs between impls; use unique
    idx = np.stack([np.random.RandomState(1).permutation(3)[:2]
                    for _ in range(4)], axis=1).astype(np.int64)
    want = x.copy()
    np.put_along_axis(want, idx, v, axis=0)
    check_output("put_along_axis",
                 {"Input": x, "Index": idx, "Value": v},
                 {"Axis": 0, "Reduce": "assign"}, {"Result": want})

    base = _r(5, 3)
    sidx = np.array([[0], [2], [4]], np.int64)
    upd = _r(3, 3)
    want2 = base.copy()
    for i, r in enumerate(sidx[:, 0]):
        want2[r] += upd[i]
    check_output("scatter_nd_add", {"X": base, "Index": sidx, "Updates": upd},
                 {}, {"Out": want2}, rtol=1e-5)


def test_unfold_matches_manual_im2col():
    x = _r(2, 3, 6, 6)
    outs, _ = run_single_op(
        "unfold", {"X": x},
        {"kernel_sizes": [2, 2], "strides": [2, 2], "paddings": [0, 0],
         "dilations": [1, 1]},
        ["Y"],
    )
    got = outs["Y"]
    assert got.shape == (2, 3 * 4, 9)
    # spot-check one patch: output column 0 = patch at (0,0)
    patch = x[:, :, 0:2, 0:2].reshape(2, 3, 4)
    np.testing.assert_allclose(
        got[:, :, 0].reshape(2, 3, 4), patch, rtol=1e-6
    )


def test_sort_searchsorted_kthvalue_shard_index():
    x = _r(3, 5)
    outs, _ = run_single_op("sort", {"X": x}, {"axis": 1}, ["Out", "Indices"])
    np.testing.assert_allclose(outs["Out"], np.sort(x, 1), rtol=1e-6)
    seq = np.sort(_r(6))
    vals = _r(4)
    check_output("searchsorted", {"SortedSequence": seq, "Values": vals}, {},
                 {"Out": np.searchsorted(seq, vals)})
    outs, _ = run_single_op("kthvalue", {"X": x}, {"k": 2, "axis": 1},
                            ["Out", "Indices"])
    np.testing.assert_allclose(outs["Out"], np.sort(x, 1)[:, 1], rtol=1e-6)
    ids = np.arange(20).astype(np.int64)
    outs, _ = run_single_op(
        "shard_index", {"X": ids},
        {"index_num": 20, "nshards": 2, "shard_id": 1, "ignore_value": -1},
        ["Out"],
    )
    want = np.where(ids // 10 == 1, ids % 10, -1)
    np.testing.assert_array_equal(outs["Out"], want)


def test_meshgrid():
    a, b = _r(3), _r(4)
    outs, _ = run_single_op("meshgrid", {"X": [a, b]}, {}, ["Out"])
    # first output only via harness; check shape + content through numpy
    ga, gb = np.meshgrid(a, b, indexing="ij")
    np.testing.assert_allclose(outs["Out"], ga, rtol=1e-6)


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


def test_loss_ops():
    logp = np.log(scipy.special.softmax(_r(4, 5), axis=1))
    tgt = scipy.special.softmax(_r(4, 5), axis=1)
    want = np.mean(tgt * (np.log(np.maximum(tgt, 1e-10)) - logp))
    check_output("kldiv_loss", {"X": logp, "Target": tgt},
                 {"reduction": "mean"}, {"Loss": want}, rtol=1e-4)

    p = np.clip(np.abs(_r(4, 1)), 0.05, 0.95)
    l = (rng.rand(4, 1) > 0.5).astype(np.float32)
    want = -l * np.log(p + 1e-4) - (1 - l) * np.log(1 - p + 1e-4)
    check_output("log_loss", {"Predicted": p, "Labels": l},
                 {"epsilon": 1e-4}, {"Loss": want}, rtol=1e-5)

    onehot = np.eye(5, dtype=np.float32)[rng.randint(0, 5, 4)]
    check_output("label_smooth", {"X": onehot}, {"epsilon": 0.1},
                 {"Out": 0.9 * onehot + 0.1 / 5}, rtol=1e-5)

    x1, x2 = _r(4, 1), _r(4, 1)
    lab = np.sign(_r(4, 1)).astype(np.float32)
    check_output("margin_rank_loss", {"X1": x1, "X2": x2, "Label": lab},
                 {"margin": 0.1},
                 {"Out": np.maximum(0, -lab * (x1 - x2) + 0.1)}, rtol=1e-5)

    logits = _r(4, 1)
    blab = (rng.rand(4, 1) > 0.5).astype(np.float32)
    check_output("hinge_loss", {"Logits": logits, "Labels": blab}, {},
                 {"Loss": np.maximum(0, 1 - (2 * blab - 1) * logits)},
                 rtol=1e-5)

    a, b = _r(4, 8), _r(4, 8)
    cs = np.sum(a * b, -1, keepdims=True) / (
        np.linalg.norm(a, axis=-1, keepdims=True)
        * np.linalg.norm(b, axis=-1, keepdims=True) + 1e-12
    )
    check_output("cos_sim", {"X": a, "Y": b}, {}, {"Out": cs}, rtol=1e-4)

    x = np.log(scipy.special.softmax(_r(6, 4), axis=1))
    lbl = rng.randint(0, 4, (6,)).astype(np.int64)
    picked = -x[np.arange(6), lbl]
    check_output("nll_loss", {"X": x, "Label": lbl}, {"reduction": "mean"},
                 {"Out": picked.mean()}, rtol=1e-5)

    pr = np.clip(np.abs(_r(4, 1)), 0.05, 0.95)
    check_output("bce_loss", {"X": pr, "Label": blab}, {},
                 {"Out": -(blab * np.log(pr) + (1 - blab) * np.log(1 - pr))},
                 rtol=1e-4)

    d = _r(4, 3)
    y = _r(4, 3)
    diff = d - y
    sl1 = np.where(np.abs(diff) < 1.0, 0.5 * diff**2, np.abs(diff) - 0.5)
    outs, _ = run_single_op("smooth_l1_loss", {"X": d, "Y": y}, {"sigma": 1.0},
                            ["Out", "Diff"])
    np.testing.assert_allclose(outs["Out"], sl1, rtol=1e-5)


def test_loss_grads_sample():
    p = np.clip(np.abs(_r(3, 1)), 0.1, 0.9)
    l = (rng.rand(3, 1) > 0.5).astype(np.float32)
    check_grad("bce_loss", {"X": p, "Label": l}, {}, ["Out"], ["X"])
    x, y = _r(3, 4), _r(3, 4)
    check_grad("cos_sim", {"X": x, "Y": y}, {}, ["Out"], ["X", "Y"])


# ---------------------------------------------------------------------------
# norms
# ---------------------------------------------------------------------------


def test_instance_norm():
    x = _r(2, 3, 4, 4)
    scale = np.abs(_r(3)) + 0.5
    bias = _r(3)
    mean = x.mean(axis=(2, 3), keepdims=True)
    var = x.var(axis=(2, 3), keepdims=True)
    want = (x - mean) / np.sqrt(var + 1e-5)
    want = want * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)
    outs, _ = run_single_op(
        "instance_norm", {"X": x, "Scale": scale, "Bias": bias},
        {"epsilon": 1e-5}, ["Y"],
    )
    np.testing.assert_allclose(outs["Y"], want, rtol=1e-4, atol=1e-5)


def test_spectral_norm():
    w = _r(6, 4)
    u = _r(6)
    v = _r(4)
    outs, _ = run_single_op(
        "spectral_norm", {"Weight": w, "U": u, "V": v},
        {"dim": 0, "power_iters": 20}, ["Out"],
    )
    sigma = np.linalg.svd(w, compute_uv=False)[0]
    np.testing.assert_allclose(outs["Out"], w / sigma, rtol=1e-3, atol=1e-4)


def test_sync_batch_norm_single_rank_matches_bn():
    x = _r(4, 3, 2, 2)
    scale = np.abs(_r(3)) + 0.5
    bias = _r(3)
    rm = np.zeros(3, np.float32)
    rv = np.ones(3, np.float32)
    outs, _ = run_single_op(
        "sync_batch_norm",
        {"X": x, "Scale": scale, "Bias": bias, "Mean": rm, "Variance": rv},
        {"epsilon": 1e-5, "momentum": 0.9},
        ["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
    )
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    want = (x - mean.reshape(1, 3, 1, 1)) / np.sqrt(
        var.reshape(1, 3, 1, 1) + 1e-5
    ) * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)
    np.testing.assert_allclose(outs["Y"], want, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(outs["SavedMean"], mean, rtol=1e-5)


def test_sync_batch_norm_syncs_across_mesh_ranks():
    """The defining property: with per-rank different shards, normalization
    uses the GLOBAL batch statistics (cf. sync_batch_norm_op.cu)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.fluid.core.registry import get_op_def, LowerContext
    from paddle_tpu import distributed as dist
    from paddle_tpu.fluid.core.jax_compat import shard_map

    mesh = dist.auto_mesh(8)
    x = _r(16, 3, 2, 2)
    scale = np.abs(_r(3)) + 0.5
    bias = _r(3)
    rm, rv = np.zeros(3, np.float32), np.ones(3, np.float32)
    opdef = get_op_def("sync_batch_norm")

    def body(xs):
        out = opdef.lower(
            LowerContext(),
            {"X": [xs], "Scale": [jnp.asarray(scale)],
             "Bias": [jnp.asarray(bias)], "Mean": [jnp.asarray(rm)],
             "Variance": [jnp.asarray(rv)]},
            {"epsilon": 1e-5},
        )
        return out["Y"][0]

    y = jax.jit(shard_map(
        body, mesh=mesh.mesh,
        in_specs=(P("dp"),), out_specs=P("dp"), check=False,
    ))(x)
    mean = x.mean(axis=(0, 2, 3))
    var = x.var(axis=(0, 2, 3))
    want = (x - mean.reshape(1, 3, 1, 1)) / np.sqrt(
        var.reshape(1, 3, 1, 1) + 1e-5
    ) * scale.reshape(1, 3, 1, 1) + bias.reshape(1, 3, 1, 1)
    np.testing.assert_allclose(np.asarray(y), want, rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# vision
# ---------------------------------------------------------------------------


def test_affine_grid_identity():
    theta = np.tile(
        np.array([[1, 0, 0], [0, 1, 0]], np.float32)[None], (2, 1, 1)
    )
    outs, _ = run_single_op(
        "affine_grid", {"Theta": theta},
        {"output_shape": [2, 3, 4, 5], "align_corners": True}, ["Output"],
    )
    g = outs["Output"]
    assert g.shape == (2, 4, 5, 2)
    np.testing.assert_allclose(g[0, 0, 0], [-1, -1], atol=1e-6)
    np.testing.assert_allclose(g[0, -1, -1], [1, 1], atol=1e-6)


def test_grid_sampler_identity_grid_reproduces_input():
    x = _r(2, 3, 5, 5)
    ys = np.linspace(-1, 1, 5, dtype=np.float32)
    xs = np.linspace(-1, 1, 5, dtype=np.float32)
    gy, gx = np.meshgrid(ys, xs, indexing="ij")
    grid = np.tile(np.stack([gx, gy], -1)[None], (2, 1, 1, 1))
    outs, _ = run_single_op(
        "grid_sampler", {"X": x, "Grid": grid}, {"align_corners": True},
        ["Output"],
    )
    np.testing.assert_allclose(outs["Output"], x, rtol=1e-4, atol=1e-5)


def test_interp_and_pixel_shuffle():
    x = _r(1, 2, 4, 4)
    outs, _ = run_single_op(
        "nearest_interp", {"X": x}, {"out_h": 8, "out_w": 8}, ["Out"]
    )
    assert outs["Out"].shape == (1, 2, 8, 8)
    np.testing.assert_allclose(outs["Out"][:, :, ::2, ::2], x, rtol=1e-5)

    outs, _ = run_single_op(
        "bilinear_interp", {"X": x},
        {"out_h": 7, "out_w": 7, "align_corners": True}, ["Out"]
    )
    assert outs["Out"].shape == (1, 2, 7, 7)
    # corner alignment: corners exactly preserved
    np.testing.assert_allclose(outs["Out"][:, :, 0, 0], x[:, :, 0, 0],
                               rtol=1e-5)
    np.testing.assert_allclose(outs["Out"][:, :, -1, -1], x[:, :, -1, -1],
                               rtol=1e-5)

    ps = _r(1, 8, 3, 3)
    outs, _ = run_single_op(
        "pixel_shuffle", {"X": ps}, {"upscale_factor": 2}, ["Out"]
    )
    assert outs["Out"].shape == (1, 2, 6, 6)
    np.testing.assert_allclose(outs["Out"][0, 0, 0, 0], ps[0, 0, 0, 0])


def test_conv3d_pool3d():
    x = _r(1, 2, 4, 4, 4)
    f = _r(3, 2, 2, 2, 2)
    outs, _ = run_single_op(
        "conv3d", {"Input": x, "Filter": f},
        {"strides": [1, 1, 1], "paddings": [0, 0, 0], "dilations": [1, 1, 1]},
        ["Output"],
    )
    assert outs["Output"].shape == (1, 3, 3, 3, 3)
    # oracle at one position
    want = np.sum(x[0, :, 0:2, 0:2, 0:2] * f[0])
    np.testing.assert_allclose(outs["Output"][0, 0, 0, 0, 0], want,
                               rtol=1e-4)

    outs, _ = run_single_op(
        "pool3d", {"X": x},
        {"ksize": [2, 2, 2], "strides": [2, 2, 2], "paddings": [0, 0, 0],
         "pooling_type": "max"},
        ["Out"],
    )
    assert outs["Out"].shape == (1, 2, 2, 2, 2)
    np.testing.assert_allclose(
        outs["Out"][0, 0, 0, 0, 0], x[0, 0, :2, :2, :2].max(), rtol=1e-6
    )
