"""Recsys-scale online learning (paddle_tpu.streaming + the pipelined
host-embedding engine): exact-parity drill (pipelined == synchronous,
bit-identical, with and without the hot-row cache), bounded-staleness
mode, delta-checkpoint chain save/replay, and the end-to-end
train-from-stream -> delta ckpt -> export -> verify -> hot-swap drill
against a live serving router under client load."""

import threading
import time

import numpy as np

import paddle_tpu.fluid as fluid
import paddle_tpu.fluid.framework as fw
from paddle_tpu import streaming
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.host_embedding import (
    HostEmbeddingSession,
    HotRowCache,
    PipelinedHostEmbeddingSession,
)
from paddle_tpu.observability.metrics import MetricsRegistry

V, D, T, B = 5000, 8, 4, 8


def _build(seed=3, optimizer="adagrad"):
    fw.reset_default_programs()
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[-1, T], dtype="int64",
                          append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], append_batch_size=False)
        emb = layers.embedding(ids, size=[V, D], is_distributed=True,
                               param_attr="st.emb")
        pooled = layers.reduce_mean(emb, dim=1)
        pred = layers.fc(pooled, size=1, param_attr="st.fc.w",
                         bias_attr="st.fc.b")
        loss = layers.reduce_mean(layers.square(pred - y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    table, _slot = main._host_embeddings["st.emb"]
    table.optimizer = optimizer
    return main, startup, loss, table


def _batches(steps, hot=300, seed=0):
    """Consecutive batches drawn from a small hot pool so uniq(t)
    overlaps uniq(t-1) — the conflict path must actually fire."""
    rng = np.random.RandomState(seed)
    pool = rng.randint(0, V, size=hot)
    return [{"ids": pool[rng.randint(0, hot, (B, T))].astype(np.int64),
             "y": rng.randn(B, 1).astype(np.float32)}
            for _ in range(steps)]


def _run_to_final_rows(kind, feeds, cache=0, exact=True, registry=None):
    """Final host-table rows (+accum) after training `feeds` with one
    engine; fresh identically-seeded model each call."""
    main, startup, loss, table = _build()
    if cache:
        table.attach_cache(cache)
    if registry is not None:
        table.enable_stats(registry=registry)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        if kind == "sync":
            sess = HostEmbeddingSession(exe, main, loss=loss)
            losses = [float(sess.run(f, fetch_list=[loss], lr=0.1)[0])
                      for f in feeds]
        else:
            with PipelinedHostEmbeddingSession(
                    exe, main, loss=loss, exact=exact) as sess:
                losses = [float(o[0]) for o in sess.run_stream(
                    feeds, fetch_list=[loss], lr=0.1)]
    table.flush_cache()
    return table._rows.copy(), table._accum.copy(), losses


# ---------------------------------------------------------------------------
# the exact-parity drill (acceptance: bit-identical final table)
# ---------------------------------------------------------------------------


def test_pipelined_exact_parity_bit_identical():
    """Pipelined (conflict serialization ON) vs synchronous over hot
    overlapping batches: the final table must be BIT-identical, and the
    conflict path must actually have fired (else the drill proves
    nothing)."""
    feeds = _batches(16)
    ref_rows, ref_accum, ref_losses = _run_to_final_rows("sync", feeds)
    reg = MetricsRegistry()
    rows, accum, losses = _run_to_final_rows("pipe", feeds, registry=reg)
    assert np.array_equal(ref_rows, rows)
    assert np.array_equal(ref_accum, accum)
    np.testing.assert_allclose(ref_losses, losses, rtol=0, atol=0)
    snap = reg.snapshot()["hostemb_pipeline_conflicts_total"]["series"]
    assert snap and snap[0]["value"] > 0, "conflict path never exercised"


def test_pipelined_exact_parity_with_hot_row_cache():
    """Cache on: hits skip the exchange but the math must stay
    bit-identical to the synchronous no-cache oracle."""
    feeds = _batches(12, seed=5)
    ref_rows, ref_accum, _ = _run_to_final_rows("sync", feeds)
    rows, accum, _ = _run_to_final_rows("pipe", feeds, cache=256)
    assert np.array_equal(ref_rows, rows)
    assert np.array_equal(ref_accum, accum)


def test_pipelined_discards_stale_prefetch_on_reentry():
    """A caller loop that stops early (StreamingTrainer max_steps)
    leaves batch t+1's pull queued; a later run() for a DIFFERENT
    batch must not train on the stale prefetched rows — the session
    discards it and stays bit-identical to the sync oracle."""
    feeds = _batches(8, seed=41)
    # the oracle never sees feeds[4]: the stream dropped it between
    # the two loops
    ref_rows, _a, _l = _run_to_final_rows("sync",
                                          feeds[:4] + feeds[5:])

    main, startup, loss, table = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        with PipelinedHostEmbeddingSession(exe, main, loss=loss) as sess:
            # first "trainer.run": stops after 4 steps with feeds[4]
            # prefetched and never trained
            for t in range(4):
                sess.run(feeds[t], fetch_list=[loss], lr=0.1,
                         next_feed=feeds[t + 1])
            # re-entry resumes at feeds[5]: the stale feeds[4] pull
            # must be discarded, not paired with feeds[5]'s labels
            for t in range(5, len(feeds)):
                sess.run(feeds[t], fetch_list=[loss], lr=0.1)
            sess.drain()
    assert np.array_equal(ref_rows, table._rows)


def test_pipelined_inexact_mode_bounded_staleness_still_trains():
    """exact=False trades the conflict patch for one-step staleness on
    the conflicting rows only — training still converges."""
    rng = np.random.RandomState(2)
    pool = rng.randint(0, V, 64)
    w = rng.randn(64)
    lut = dict(zip(pool, w))
    feeds = []
    for _ in range(40):
        ids = pool[rng.randint(0, 64, (B, T))]
        ys = np.vectorize(lut.get)(ids).mean(1, keepdims=True)
        feeds.append({"ids": ids.astype(np.int64),
                      "y": ys.astype(np.float32)})
    _rows, _accum, losses = _run_to_final_rows("pipe", feeds, exact=False)
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_pipelined_background_push_failure_surfaces():
    """A push that fails in the background lane has no waiter unless a
    later step conflicts — the session must still raise at the next
    call instead of training past a lost gradient update."""
    import pytest

    feeds = _batches(6, seed=43)
    main, startup, loss, table = _build()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    orig = table._push_impl
    calls = [0]

    def flaky(uniq, g, lr):
        calls[0] += 1
        if calls[0] == 2:
            raise OSError("parameter server gone")
        return orig(uniq, g, lr)

    table._push_impl = flaky
    with fluid.scope_guard(scope):
        exe.run(startup)
        sess = PipelinedHostEmbeddingSession(exe, main, loss=loss)
        # the error surfaces either as the original (a conflicting
        # step waited the failed op) or wrapped by the async check
        with pytest.raises((RuntimeError, OSError)):
            for f in feeds:
                sess.run(f, fetch_list=[loss], lr=0.1)
            sess.drain()          # backstop if no later run noticed
        table._push_impl = orig
        try:
            sess.close()
        except RuntimeError:
            pass                  # the close-time drain re-reports it


# ---------------------------------------------------------------------------
# hot-row cache mechanics
# ---------------------------------------------------------------------------


def test_hot_row_cache_hits_evicts_and_flushes():
    main, startup, loss, table = _build(seed=11)
    cache = table.attach_cache(8)
    ids = np.arange(6, dtype=np.int64) * 7
    pulled1, _l, uniq = table.pull(ids)
    assert cache.misses == 6 and cache.hits == 0
    pulled2, _l, _u = table.pull(ids)          # all resident now
    assert cache.hits == 6
    np.testing.assert_array_equal(np.asarray(pulled1), np.asarray(pulled2))
    # update through push lands in the cache mirror, not the shard
    g = np.ones((len(uniq), D), np.float32)
    table.push(uniq, g, lr=0.5)
    stale_shard = table._rows[uniq // table.nproc].copy()
    fresh = table._peek_rows(uniq)
    assert not np.array_equal(stale_shard, fresh)
    # eviction (capacity 8, insert 8 new rows) writes victims back
    more = (np.arange(8, dtype=np.int64) * 11 + 2000)
    table.pull(more)
    table.flush_cache()
    np.testing.assert_array_equal(table._rows[uniq // table.nproc], fresh)
    assert 0.0 < cache.hit_rate < 1.0
    assert cache.metrics()["resident"] <= 8


def test_cache_requires_single_process_and_capacity_knob_exists():
    from paddle_tpu.tune.space import cache_capacity_candidates

    cands = cache_capacity_candidates(capacities=(0, 64, 9999),
                                      table_rows=1000)
    labels = [c.label for c in cands]
    assert labels[0] == "nocache"              # measured baseline first
    assert "cache64" in labels and "cache9999" not in labels
    assert cands[0].params["cache_capacity"] == 0


# ---------------------------------------------------------------------------
# delta checkpoints
# ---------------------------------------------------------------------------


def test_delta_checkpoint_chain_save_and_replay(tmp_path):
    """full -> delta -> delta ... restore replays the chain in order
    and lands bit-identical to the live table."""
    main, startup, loss, table = _build(seed=7)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    ck = streaming.DeltaCheckpointer(str(tmp_path / "ck"), [table],
                                     full_every=4)
    feeds = _batches(9, seed=9)
    kinds = []
    with fluid.scope_guard(scope):
        exe.run(startup)
        sess = HostEmbeddingSession(exe, main, loss=loss)
        for i, f in enumerate(feeds):
            sess.run(f, fetch_list=[loss], lr=0.1)
            if i % 3 == 2:
                _no, kind = ck.save(step=i, events_done=(i + 1) * B)
                kinds.append(kind)
    assert kinds[0] == "full" and "delta" in kinds
    want_rows = table._rows.copy()
    want_accum = table._accum.copy()

    # a fresh table (different seed => different init) must restore to
    # the exact committed state through full + delta replay
    main2, _st, _l, table2 = _build(seed=99)
    ck2 = streaming.DeltaCheckpointer(str(tmp_path / "ck"), [table2],
                                      full_every=4)
    meta = ck2.restore()
    assert meta["kind"] == kinds[-1]
    assert meta["events_done"] == 9 * B
    np.testing.assert_array_equal(table2._rows, want_rows)
    np.testing.assert_array_equal(table2._accum, want_accum)


def test_delta_checkpoint_failed_commit_requeues_touched(tmp_path):
    main, _st, _l, table = _build(seed=13)
    ck = streaming.DeltaCheckpointer(str(tmp_path / "ck"), [table])
    table.push(np.asarray([3, 5], np.int64), np.ones((2, D), np.float32))
    ck.save()                                   # full, drains touched
    table.push(np.asarray([7], np.int64), np.ones((1, D), np.float32))
    saver = ck._saver

    def boom(*a, **kw):
        raise OSError("disk gone")

    orig = saver.save_checkpoint
    saver.save_checkpoint = boom
    try:
        try:
            ck.save()
        except OSError:
            pass
        else:
            raise AssertionError("expected the injected failure")
    finally:
        saver.save_checkpoint = orig
    # the touched row survived the failed commit and lands in the next
    _no, kind = ck.save()
    assert kind == "delta"
    meta = ck._saver.list_checkpoints()[-1][1]
    assert meta["touched_rows"]["st.emb"] == 1


# ---------------------------------------------------------------------------
# the end-to-end streaming drill (acceptance criterion)
# ---------------------------------------------------------------------------


def test_streaming_train_to_freshness_drill(tmp_path):
    """Train-from-stream -> delta checkpoint -> export -> PR-5 verify
    (inside Router.deploy) -> hot-swap into a live router, with client
    load across the swap: ZERO failed requests, freshness measured,
    and the served prediction matches the trained table."""
    import jax.numpy as jnp

    from paddle_tpu import serving
    from paddle_tpu.incubate.checkpoint.checkpoint_saver import PaddleModel

    main, startup, loss, table = _build(seed=21)
    table.attach_cache(128)
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    reg = MetricsRegistry()
    router = serving.Router(max_batch=4, batch_timeout_ms=1,
                            metrics_registry=reg)
    probe = {"ids": np.zeros((1, T), np.int64)}

    def export_fn(no):
        fw.reset_default_programs()
        imain, istart = fluid.Program(), fluid.Program()
        with fluid.program_guard(imain, istart):
            ids = layers.data("ids", shape=[-1, T], dtype="int64",
                              append_batch_size=False)
            emb = layers.embedding(ids, size=[V, D],
                                   param_attr="st.emb.dense")
            pooled = layers.reduce_mean(emb, dim=1)
            pred = layers.fc(pooled, size=1, param_attr="st.fc.w",
                             bias_attr="st.fc.b")
        s = fluid.Scope()
        with fluid.scope_guard(s):
            exe.run(istart)
            s.set("st.emb.dense", jnp.asarray(table.export_rows()))
            for nm in ("st.fc.w", "st.fc.b"):
                s.set(nm, jnp.asarray(np.asarray(
                    scope.find_var(nm)).copy()))
            path = str(tmp_path / ("export_v%d" % no))
            fluid.io.save_inference_model(path, ["ids"], [pred], exe,
                                          imain)
        return path

    failures = []
    n_ok = [0]
    stop = threading.Event()

    def client():
        i = 0
        while not stop.is_set():
            i += 1
            try:
                router.infer(probe, request_id="cl-%d" % i, timeout=30)
                n_ok[0] += 1
            except serving.TransitionError:
                time.sleep(0.01)       # nothing promoted yet: not a failure
            except Exception as e:
                failures.append(repr(e))
                return
            time.sleep(0.002)

    feeds = _batches(24, seed=31)
    cl = threading.Thread(target=client)
    try:
        with fluid.scope_guard(scope):
            exe.run(startup)
            sess = PipelinedHostEmbeddingSession(exe, main, loss=loss)
            ck = streaming.DeltaCheckpointer(
                str(tmp_path / "ck"), [table],
                dense=PaddleModel(exe, main, scope), full_every=3)
            push = streaming.PushToServing(
                router, export_fn, warmup_example=probe,
                probe_example=probe)
            trainer = streaming.StreamingTrainer(
                sess, feeds, [loss], lr=0.1, window_events=4 * B,
                checkpoint=ck, push=push, push_every_windows=2,
                metrics_registry=reg)
            cl.start()
            report = trainer.run()
            time.sleep(0.05)           # client traffic on the new version
            sess.close()
            trainer.close()
            # served-prediction probe while the router is still live
            ids_v = feeds[0]["ids"][:1]
            served = np.asarray(
                router.infer({"ids": ids_v}, timeout=30)[0])
    finally:
        stop.set()
        cl.join(30)
        router.shutdown(drain_timeout=5)

    # zero failed requests across the hot swap(s)
    assert not failures, failures[:3]
    assert n_ok[0] > 0
    snap = reg.snapshot()
    errs = snap.get("serving_fleet_errors_total")
    assert not errs or sum(s["value"] for s in errs["series"]) == 0

    # the loop did everything it claims: windows, checkpoints, pushes
    assert len(report.windows) >= 2
    assert report.checkpoints and report.checkpoints[0][1] == "full"
    assert len(report.pushes) >= 1
    assert report.events == 24 * B
    # freshness (event ingested -> served by new version) was measured
    assert report.freshness_s is not None and report.freshness_s > 0
    for p in report.pushes:
        assert p["freshness_oldest_s"] > 0

    # the promoted version serves the TRAINED table: prediction through
    # the router equals a local forward with the exported weights
    rows = table.export_rows()[ids_v[0]]
    with fluid.scope_guard(scope):
        w = np.asarray(scope.find_var("st.fc.w"))
        b = np.asarray(scope.find_var("st.fc.b"))
    want = rows.mean(0) @ w + b
    np.testing.assert_allclose(served[0], want, atol=1e-4)

    # streaming metrics landed on the registry
    for fam in ("streaming_events_total", "streaming_windows_total",
                "streaming_pushes_total", "streaming_freshness_s"):
        series = snap[fam]["series"]
        assert series and series[0]["value"] > 0, fam


def test_stream_source_and_dataset_stream(tmp_path):
    """StreamSource wraps iterables with event counts + ingest stamps;
    dataset_stream bridges the native Dataset channel engine."""
    src = streaming.StreamSource(
        ({"x": np.zeros((5, 2))} for _ in range(3)))
    got = list(src)
    assert [b.n_events for b in got] == [5, 5, 5]
    assert all(b.ingested_at > 0 for b in got)
    src2 = streaming.StreamSource(iter(got), limit=2)
    assert len(list(src2)) == 2

    from paddle_tpu.fluid.dataset import DatasetFactory, pad_batch

    path = str(tmp_path / "p.txt")
    with open(path, "w") as fh:
        for i in range(8):
            fh.write("2 %d %d 1 0.5\n" % (i, i + 1))
    prog = fluid.Program()
    with fluid.program_guard(prog, fluid.Program()):
        ids = fluid.data("ids", [-1, 1], "int64")
        lab = fluid.data("label", [-1, 1], "float32")
    ds = DatasetFactory().create_dataset("QueueDataset")
    ds.set_filelist([path])
    ds.set_batch_size(4)
    ds.set_thread(1)
    ds.set_use_var([ids, lab])

    def make_feed(raw):
        vals, lod = raw["ids"]
        dense, _mask = pad_batch(vals, lod, pad_value=0)
        return {"ids": dense, "label": raw["label"][0].reshape(-1, 1)}

    stream = streaming.dataset_stream(ds, make_feed)
    batches = list(stream)
    assert sum(b.n_events for b in batches) == 8
    assert all(isinstance(b.feed["ids"], np.ndarray) for b in batches)
