"""Unified telemetry subsystem (paddle_tpu.observability): registry,
exporters, step-level training telemetry, system gauges, fleet
aggregation, and the fluid.profiler metric aliases."""

import json
import threading
import urllib.request

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import observability as obs
from paddle_tpu.observability.metrics import MetricsRegistry


# ---------------------------------------------------------------------------
# registry + metric primitives
# ---------------------------------------------------------------------------


def test_registry_get_or_create_and_mismatch():
    reg = MetricsRegistry()
    c1 = reg.counter("requests_total", "reqs", labelnames=("path",))
    c2 = reg.counter("requests_total")  # help/labels taken from first
    assert c1 is c2
    with pytest.raises(ValueError, match="exists as Counter"):
        reg.gauge("requests_total")
    with pytest.raises(ValueError, match="exists as Counter"):
        reg.counter("requests_total", labelnames=("other",))


def test_labeled_children_are_independent():
    reg = MetricsRegistry()
    c = reg.counter("hits", labelnames=("k",))
    c.labels("a").inc(2)
    c.labels(k="b").inc(5)
    assert c.labels("a").value == 2
    assert c.labels("b").value == 5
    with pytest.raises(ValueError, match="call .labels"):
        c.inc()
    with pytest.raises(ValueError, match="do not match"):
        c.labels(wrong="x")


def test_counter_monotonic_and_gauge_function():
    reg = MetricsRegistry()
    c = reg.counter("n")
    with pytest.raises(ValueError, match="only go up"):
        c.inc(-1)
    g = reg.gauge("depth")
    g.set(3)
    assert g.value == 3
    g.dec()
    assert g.value == 2
    g.set_function(lambda: 42)
    assert g.value == 42
    assert "depth 42" in reg.prometheus_text()


def test_histogram_aggregates_and_percentiles():
    reg = MetricsRegistry()
    h = reg.histogram("lat", buckets=(1, 10, 100))
    assert h.percentile(50) is None
    for v in range(1, 101):
        h.observe(v)
    s = h.summary()
    assert s["count"] == 100 and s["min"] == 1 and s["max"] == 100
    assert s["sum"] == pytest.approx(5050)
    assert 45 <= s["p50"] <= 55 and s["p99"] >= 95
    # bucket cumulativity: each bound's count includes all below it
    cum = h.cumulative_buckets()
    bounds = [b for b, _ in cum]
    counts = [c for _, c in cum]
    assert bounds == [1.0, 10.0, 100.0, float("inf")]
    assert counts == [1, 10, 100, 100]
    assert counts == sorted(counts)


# ---------------------------------------------------------------------------
# satellite: concurrent-writer thread-safety stress
# ---------------------------------------------------------------------------


def test_concurrent_writer_stress_exact_counts():
    """Serving hits Counter/Histogram from dispatch + completion threads;
    increments and observations must never be lost."""
    reg = MetricsRegistry()
    c = reg.counter("stress_total")
    h = reg.histogram("stress_ms")
    n_threads, n_iter = 8, 2000
    barrier = threading.Barrier(n_threads)

    def writer(tid):
        barrier.wait()
        for i in range(n_iter):
            c.inc()
            h.observe(float(i % 97))

    ts = [threading.Thread(target=writer, args=(t,))
          for t in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value == n_threads * n_iter
    assert h.count == n_threads * n_iter
    expected_sum = n_threads * sum(float(i % 97) for i in range(n_iter))
    assert h.summary()["sum"] == pytest.approx(expected_sum)
    # cumulative buckets account for every observation exactly once
    assert h.cumulative_buckets()[-1][1] == n_threads * n_iter


def test_json_snapshot_stable_under_concurrent_mutation():
    reg = MetricsRegistry()
    c = reg.counter("live_total", labelnames=("w",))
    h = reg.histogram("live_ms")
    stop = threading.Event()

    def writer(tid):
        while not stop.is_set():
            c.labels(str(tid)).inc()
            h.observe(tid)

    ts = [threading.Thread(target=writer, args=(t,)) for t in range(4)]
    for t in ts:
        t.start()
    try:
        last = {}
        for _ in range(200):
            snap = reg.snapshot()
            json.dumps(snap)               # always serializable
            for s in snap["live_total"]["series"]:
                w = s["labels"]["w"]
                assert s["value"] >= last.get(w, 0)  # counters never regress
                last[w] = s["value"]
            reg.prometheus_text()          # and text never raises
    finally:
        stop.set()
        for t in ts:
            t.join()


# ---------------------------------------------------------------------------
# satellite: Prometheus exposition golden-format
# ---------------------------------------------------------------------------


def _golden_registry():
    reg = MetricsRegistry()
    c = reg.counter("http_requests_total", "Total requests",
                    labelnames=("path", "code"))
    c.labels('/a"b\\c\nd', "200").inc(3)
    h = reg.histogram("lat_ms", "Latency", buckets=(1, 2.5, 5))
    for v in (0.5, 2, 2, 7):
        h.observe(v)
    g = reg.gauge("temp", "Temp")
    g.set(1.5)
    return reg


def test_prometheus_text_golden():
    golden = "\n".join([
        "# HELP http_requests_total Total requests",
        "# TYPE http_requests_total counter",
        'http_requests_total{path="/a\\"b\\\\c\\nd",code="200"} 3',
        "# HELP lat_ms Latency",
        "# TYPE lat_ms histogram",
        'lat_ms_bucket{le="1"} 1',
        'lat_ms_bucket{le="2.5"} 3',
        'lat_ms_bucket{le="5"} 3',
        'lat_ms_bucket{le="+Inf"} 4',
        "lat_ms_sum 11.5",
        "lat_ms_count 4",
        "# HELP temp Temp",
        "# TYPE temp gauge",
        "temp 1.5",
    ]) + "\n"
    assert _golden_registry().prometheus_text() == golden


def test_prometheus_text_sum_count_consistency():
    """_count equals the +Inf bucket; buckets are monotone; _sum matches
    the observations — parsed back out of the TEXT, not the objects."""
    text = _golden_registry().prometheus_text()
    buckets, total, count = [], None, None
    for line in text.splitlines():
        if line.startswith("lat_ms_bucket"):
            buckets.append(int(line.rsplit(" ", 1)[1]))
        elif line.startswith("lat_ms_sum"):
            total = float(line.rsplit(" ", 1)[1])
        elif line.startswith("lat_ms_count"):
            count = int(line.rsplit(" ", 1)[1])
    assert buckets == sorted(buckets)
    assert buckets[-1] == count == 4
    assert total == pytest.approx(0.5 + 2 + 2 + 7)


def test_prometheus_name_sanitization():
    reg = MetricsRegistry()
    reg.counter("io.step-wait ms").inc(1)
    text = reg.prometheus_text()
    assert "io_step_wait_ms 1" in text


# ---------------------------------------------------------------------------
# exporters: HTTP endpoint
# ---------------------------------------------------------------------------


def test_serve_metrics_http_endpoint():
    reg = MetricsRegistry()
    reg.counter("scraped_total", "scrapes").inc(7)
    httpd = obs.serve_metrics_http(registry=reg, port=0)
    try:
        port = httpd.server_address[1]
        body = urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % port, timeout=10).read().decode()
        assert "# TYPE scraped_total counter" in body
        assert "scraped_total 7" in body
        jbody = json.loads(urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics.json" % port, timeout=10).read())
        assert jbody["scraped_total"]["series"][0]["value"] == 7
    finally:
        httpd.shutdown()


def test_inference_server_metrics_endpoint():
    """The serving HTTP front end answers /metrics with the registry
    text exposition, and /stats keeps its PR-2 shape."""
    from paddle_tpu.inference.server import InferenceServer

    class FakePredictor:
        def run(self, feed):
            return [np.asarray(v).sum(axis=tuple(range(1, np.asarray(v).ndim)))
                    if np.asarray(v).ndim > 1 else np.asarray(v)
                    for v in feed.values()]

    reg = MetricsRegistry()
    server = InferenceServer(FakePredictor(), max_batch=4,
                             batch_timeout_ms=1.0, name="t-metrics",
                             metrics_registry=reg).start()
    try:
        server.infer({"x": np.ones((2, 3), np.float32)})
        httpd = server.serve_http(port=0, block=False)
        try:
            port = httpd.server_address[1]
            body = urllib.request.urlopen(
                "http://127.0.0.1:%d/metrics" % port,
                timeout=10).read().decode()
            assert 'serving_requests_total{server="t-metrics"} 1' in body
            assert "serving_latency_ms_bucket" in body
            stats = json.loads(urllib.request.urlopen(
                "http://127.0.0.1:%d/stats" % port, timeout=10).read())
            # PR-2 backward-compatible keys
            for k in ("requests", "batches", "errors", "queue_depth",
                      "batch_size", "latency_ms", "compile_count"):
                assert k in stats
            assert stats["requests"] == 1
        finally:
            httpd.shutdown()
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# fluid.profiler aliases + reset_profiler
# ---------------------------------------------------------------------------


def test_profiler_metric_aliases_are_shared_impl():
    from paddle_tpu.fluid import profiler

    assert profiler.Counter is obs.Counter
    assert profiler.Histogram is obs.Histogram
    # standalone construction (the PR-2 call-site shape) still works
    c = profiler.Counter("x")
    c.inc(2)
    assert c.summary() == {"name": "x", "value": 2}
    h = profiler.Histogram("y", max_samples=8)
    for v in range(100):
        h.observe(v)
    assert h.count == 100 and len(h._samples) == 8


def test_reset_profiler_resets_registry_metrics():
    from paddle_tpu.fluid import profiler

    reg = obs.default_registry()
    c = reg.counter("reset_probe_total")
    h = reg.histogram("reset_probe_ms")
    c.inc(5)
    h.observe(1.0)
    assert c.value == 5 and h.count == 1
    profiler.reset_profiler()
    assert c.value == 0 and h.count == 0
    # families stay registered: the same objects keep working
    c.inc()
    assert reg.counter("reset_probe_total").value == 1


def test_profiler_contextmanager_roundtrip(tmp_path, capsys):
    """start -> RecordEvent -> stop via the contextmanager: the
    aggregated table prints with real rows and the chrome trace lands."""
    from paddle_tpu.fluid import layers, profiler

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 8], append_batch_size=False)
        y = layers.reduce_sum(layers.fc(x, size=4))
    exe = fluid.Executor()
    out_json = tmp_path / "trace.json"
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        with profiler.profiler(sorted_key="calls",
                               profile_path=str(out_json),
                               log_dir=str(tmp_path / "tr")):
            with profiler.RecordEvent("roundtrip_region"):
                exe.run(main, feed={"x": np.ones((2, 8), np.float32)},
                        fetch_list=[y])
    out = capsys.readouterr().out
    assert "Profiling Report" in out
    rows = [l for l in out.splitlines() if "%" in l]
    assert rows, out
    data = json.loads(out_json.read_text())
    assert any("roundtrip_region" in str(e.get("name"))
               for e in data["traceEvents"])


# ---------------------------------------------------------------------------
# step-level training telemetry
# ---------------------------------------------------------------------------


def _toy_model():
    import paddle_tpu.hapi as hp
    from paddle_tpu.fluid import dygraph, layers

    class Net(dygraph.Layer):
        def __init__(self):
            super().__init__()
            self.fc = dygraph.Linear(4, 3)

        def forward(self, x):
            return self.fc(x)

    m = hp.Model(Net(), inputs=[hp.Input([None, 4], "float32", "x")],
                 labels=[hp.Input([None, 1], "int64", "y")])

    def loss_fn(pred, y):
        return layers.reduce_mean(
            layers.square(pred - layers.cast(y, "float32")))

    m.prepare(optimizer=fluid.optimizer.SGDOptimizer(0.01),
              loss_function=loss_fn)
    return m


def test_fit_emits_step_breakdown_that_sums(tmp_path):
    """Acceptance: data_wait + compile + compute + host_overhead ≈
    step_time for every step of a toy Model.fit, compile is detected on
    the first (cache-miss) step, and the scalar log carries it all."""
    m = _toy_model()
    rng = np.random.RandomState(0)
    x = rng.randn(24, 4).astype("float32")
    y = np.zeros((24, 1), np.int64)
    log = tmp_path / "scalars.jsonl"
    m.fit((x, y), batch_size=8, epochs=2, verbose=0, shuffle=False,
          scalar_log=str(log))
    timer = m.step_timer
    assert timer is not None and len(timer.history) == 6
    for bd in timer.history:
        parts = (bd["data_wait"] + bd["compile"] + bd["compute"]
                 + bd["host_overhead"])
        assert parts == pytest.approx(bd["step_time"], rel=1e-6, abs=1e-3)
        assert bd["step_time"] > 0
    # steady state executes without compiling
    assert timer.history[-1]["compute"] > 0
    # the first step pays the trace+XLA compile; steady state does not
    assert timer.history[0]["compile"] > 0
    assert timer.history[0]["compiles"] >= 1
    assert timer.history[-1]["compiles"] == 0
    assert timer.history[-1]["compile"] <= timer.history[0]["compile"]
    # always-on aggregates landed in the shared registry
    reg = obs.default_registry()
    steps = reg.counter("train_steps_total",
                        labelnames=("loop",)).labels("hapi.fit")
    assert steps.value >= 6
    h = reg.histogram("train_step_ms", labelnames=("loop",))
    assert h.labels("hapi.fit").count >= 6
    # scalar JSONL log: one line per component per step
    rows = obs.ScalarWriter.read(str(log))
    tags = {r["tag"] for r in rows}
    for comp in ("data_wait", "compile", "compute", "host_overhead",
                 "step_time"):
        assert "hapi.fit/%s_ms" % comp in tags
    by_step = [r for r in rows if r["tag"] == "hapi.fit/step_time_ms"]
    assert [r["step"] for r in by_step] == list(range(6))


def test_fit_dygraph_breakdown_attributes_compute(tmp_path):
    """Eager mode has no Executor.run; fit itself must still split the
    step into compile/compute rather than dumping it all into
    host_overhead."""
    import paddle_tpu.hapi as hp
    from paddle_tpu.fluid import dygraph

    with dygraph.guard():
        class Net(dygraph.Layer):
            def __init__(self):
                super().__init__()
                self.fc = dygraph.Linear(4, 3)

            def forward(self, x):
                return self.fc(x)

        m = hp.Model(Net())

        def loss_fn(pred, y):
            from paddle_tpu.fluid import layers

            return layers.reduce_mean(layers.square(
                pred - layers.cast(y, "float32")))

        m.prepare(optimizer=fluid.optimizer.SGDOptimizer(0.01),
                  loss_function=loss_fn)
        x = np.zeros((16, 4), np.float32)
        y = np.zeros((16, 1), np.int64)
        m.fit((x, y), batch_size=8, epochs=1, verbose=0)
    hist = m.step_timer.history
    assert len(hist) == 2
    for bd in hist:
        parts = (bd["data_wait"] + bd["compile"] + bd["compute"]
                 + bd["host_overhead"])
        assert parts == pytest.approx(bd["step_time"], rel=1e-6, abs=1e-3)
    # the eager step's device work lands in compile+compute, not in the
    # host_overhead residual
    assert hist[-1]["compile"] + hist[-1]["compute"] > 0


def test_fit_telemetry_off():
    m = _toy_model()
    x = np.zeros((8, 4), np.float32)
    y = np.zeros((8, 1), np.int64)
    m.fit((x, y), batch_size=8, epochs=1, verbose=0, telemetry=False)
    assert m.step_timer is None


def test_step_timer_nests_and_cancels():
    timer = obs.StepTimer(name="nest-test", registry=MetricsRegistry())
    with timer.step() as rec:
        obs.record_component("compute", 0.01)
        assert rec.components["compute"] == pytest.approx(0.01)
    assert timer.last_breakdown["compute"] == pytest.approx(10.0)
    with timer.step() as rec:
        rec.cancel()
    assert len(timer.history) == 1           # cancelled: not recorded
    # outside a step, recording is a no-op (never raises)
    obs.record_component("compute", 1.0)
    obs.record_compile(1.0)


def test_executor_records_compile_then_cached_runs(tmp_path):
    """Cache-miss runs bill compile; cached runs are compute-only."""
    from paddle_tpu.fluid import layers

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 4], append_batch_size=False)
        out = layers.reduce_sum(layers.fc(x, size=2))
    exe = fluid.Executor()
    timer = obs.StepTimer(name="exe-test", registry=MetricsRegistry())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        feed = {"x": np.ones((2, 4), np.float32)}
        with timer.step():
            exe.run(main, feed=feed, fetch_list=[out])
        first = timer.last_breakdown
        for _ in range(2):                   # warm the donation variants
            exe.run(main, feed=feed, fetch_list=[out])
        with timer.step():
            exe.run(main, feed=feed, fetch_list=[out])
        cached = timer.last_breakdown
    assert first["compile"] > 0 and first["compiles"] >= 1
    assert cached["compiles"] == 0
    assert cached["compile"] == 0.0
    assert cached["compute"] > 0


# ---------------------------------------------------------------------------
# system gauges + checkpoint wiring
# ---------------------------------------------------------------------------


def test_system_metrics_sampler_cpu_graceful():
    reg = MetricsRegistry()
    s = obs.SystemMetricsSampler(registry=reg, interval_s=0.05)
    out = s.sample_once()
    # CPU jax: no device memory stats — but host metrics still land
    assert "host_rss_bytes" in out
    assert out["host_rss_bytes"] > 0
    assert "jax_live_arrays" in out
    assert reg.counter("system_metrics_samples_total").value == 1
    with s:                                   # background thread runs
        import time

        time.sleep(0.15)
    assert reg.counter("system_metrics_samples_total").value >= 2
    assert "host_rss_bytes" in reg.prometheus_text()


def test_checkpoint_save_durations_wired(tmp_path):
    from paddle_tpu.fluid import layers
    from paddle_tpu.incubate.checkpoint import train_epoch_range

    reg = obs.default_registry()
    saves0 = reg.counter("checkpoint_saves_total").value
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 4], append_batch_size=False)
        loss = layers.reduce_mean(layers.fc(x, size=2))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for epoch in train_epoch_range(
                2, checkpoint_dir=str(tmp_path), main_program=main,
                async_save=False):
            exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[loss])
    assert reg.counter("checkpoint_saves_total").value >= saves0 + 2
    assert reg.histogram("checkpoint_save_ms").count >= 2
    assert reg.histogram("checkpoint_commit_ms").count >= 2
    assert reg.histogram(
        "train_epoch_ms", labelnames=("loop",)).labels("acp").count >= 2


def test_async_checkpoint_snapshot_metric(tmp_path):
    from paddle_tpu.fluid import layers
    from paddle_tpu.incubate.checkpoint import TrainEpochRange

    reg = obs.default_registry()
    snap_h = reg.histogram("checkpoint_snapshot_ms")
    n0 = snap_h.count
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 4], append_batch_size=False)
        loss = layers.reduce_mean(layers.fc(x, size=2))
        fluid.optimizer.SGDOptimizer(0.1).minimize(loss)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        r = TrainEpochRange(1, checkpoint_dir=str(tmp_path),
                            main_program=main, async_save=True)
        for _ in r:
            exe.run(main, feed={"x": np.ones((2, 4), np.float32)},
                    fetch_list=[loss])
        r.wait()
    assert snap_h.count >= n0 + 1
    assert reg.gauge("checkpoint_save_in_flight").value == 0


# ---------------------------------------------------------------------------
# fleet aggregation (distributed/monitor.py)
# ---------------------------------------------------------------------------


def test_metrics_aggregator_fleet_min_max_mean(tmp_path):
    from paddle_tpu.distributed.monitor import MetricsAggregator

    ws = str(tmp_path)
    regs = [MetricsRegistry() for _ in range(3)]
    for i, reg in enumerate(regs):
        reg.counter("steps_total").inc(10 * (i + 1))      # 10, 20, 30
        h = reg.histogram("step_ms")
        for v in (float(i + 1),) * 4:                      # mean = i+1
            h.observe(v)
    aggs = [MetricsAggregator(ws, i, 3, registry=regs[i])
            for i in range(3)]
    for a in aggs:
        a.publish()
    fleet = aggs[0].fleet_snapshot()
    assert fleet["ranks_reporting"] == [0, 1, 2]
    s = fleet["series"]["steps_total"]
    assert s["min"] == 10 and s["max"] == 30 and s["mean"] == 20
    hs = fleet["series"]["step_ms"]
    assert hs["min"] == 1 and hs["max"] == 3 and hs["mean"] == 2
    assert hs["total_count"] == 12 and hs["total_sum"] == pytest.approx(24)
    # a missing rank never blocks the view
    partial = MetricsAggregator(ws + "/other", 0, 2,
                                registry=regs[0])
    partial.publish()
    view = partial.fleet_snapshot()
    assert view["ranks_reporting"] == [0] and view["expected_ranks"] == 2


def test_pipeline_stats_instances_independent_and_scrapeable():
    from paddle_tpu.io import PipelineStats

    reg = MetricsRegistry()
    a = PipelineStats(name="io", registry=reg)
    b = PipelineStats(name="io", registry=reg)
    a.batches.inc(3)
    b.batches.inc(1)
    assert a.batches.value == 3 and b.batches.value == 1
    assert a.summary()["batches"] == 3        # back-compat shape
    assert a.summary()["name"] == "io"
    text = reg.prometheus_text()
    assert 'io_batches_total{pipeline="%s"} 3' % a.instance_label in text
    assert 'io_batches_total{pipeline="%s"} 1' % b.instance_label in text


# ---------------------------------------------------------------------------
# ScalarWriter
# ---------------------------------------------------------------------------


def test_scalar_writer_roundtrip_and_append(tmp_path):
    p = tmp_path / "log" / "scalars.jsonl"
    with obs.ScalarWriter(p) as w:
        for i in range(5):
            w.add_scalar("loss", 1.0 / (i + 1), i)
        w.add_scalars("sys", {"rss": 1.0, "cpu": 2.0}, 0)
    rows = obs.ScalarWriter.read(str(p))
    assert len(rows) == 7
    assert [r["value"] for r in rows if r["tag"] == "loss"] == \
        [pytest.approx(1.0 / (i + 1)) for i in range(5)]
    assert {r["tag"] for r in rows} == {"loss", "sys/rss", "sys/cpu"}
    # append-on-resume: a second writer extends the same file
    with obs.ScalarWriter(p) as w:
        w.add_scalar("loss", 0.1, 5)
    assert len(obs.ScalarWriter.read(str(p))) == 8


# ---------------------------------------------------------------------------
# host-embedding + streaming observability (PR-14): labeled metric
# families and trace spans for the online-learning hot path
# ---------------------------------------------------------------------------


def test_host_embedding_metrics_and_spans():
    """pull/push/exchange ms, exchange bytes, unique-id ratio, cache
    hit rate + staleness, pipeline conflicts: all land as labeled PR-4
    families, and the pull/push spans hit the PR-6 tracer."""
    from paddle_tpu.fluid.host_embedding import HostEmbedding
    from paddle_tpu.observability import trace as trace_mod

    reg = MetricsRegistry()
    t = HostEmbedding("obs_t", 500, 4, optimizer="sgd")
    t.enable_stats(registry=reg)
    t.attach_cache(16)
    tracer = trace_mod.enable_tracing()
    try:
        ids = np.asarray([[1, 2, 2, 7]], np.int64)
        _p, _l, uniq = t.pull(ids)
        t.push(uniq, np.ones((len(uniq), 4), np.float32))
        t.pull(ids)                         # all cached now: hits
    finally:
        trace_mod.disable_tracing()

    snap = reg.snapshot()

    def one(name):
        fam = snap[name]
        series, = fam["series"]
        assert series["labels"] == {"table": t.stats.instance_label}
        return series

    assert one("hostemb_pull_ms")["count"] == 2
    assert one("hostemb_push_ms")["count"] == 1
    assert one("hostemb_exchange_ms")["count"] >= 1
    assert one("hostemb_exchange_bytes_total")["value"] > 0
    # 4 ids, 3 unique, observed once per pull
    ur = one("hostemb_unique_ratio")
    assert ur["count"] == 2 and ur["sum"] == pytest.approx(1.5)
    assert one("hostemb_cache_misses_total")["value"] == 3
    assert one("hostemb_cache_hits_total")["value"] == 3
    assert one("hostemb_cache_hit_rate")["value"] == pytest.approx(0.5)
    assert one("hostemb_cache_staleness_steps")["count"] == 1
    names = [e["name"] for e in tracer.events() if e.get("ph") == "X"]
    assert "hostemb.pull" in names and "hostemb.push" in names
    # label released on close so the next instance gets a fresh child
    t.stats.close()


def test_streaming_delta_lag_and_window_metrics(tmp_path):
    """The streaming loop's delta-checkpoint lag gauge + window
    families land on the registry (the freshness loop's dashboards)."""
    from paddle_tpu import streaming
    from paddle_tpu.fluid.host_embedding import HostEmbedding

    reg = MetricsRegistry()
    table = HostEmbedding("lag_t", 100, 4, optimizer="sgd")
    ck = streaming.DeltaCheckpointer(str(tmp_path / "ck"), [table])

    class _Sess:
        def run(self, feed, fetch_list=None, lr=None):
            table.push(np.unique(feed["ids"]),
                       np.ones((len(np.unique(feed["ids"])), 4),
                               np.float32))
            return [np.float32(0.5)]

    feeds = [{"ids": np.arange(i, i + 4, dtype=np.int64).reshape(1, 4)}
             for i in range(6)]
    tr = streaming.StreamingTrainer(
        _Sess(), feeds, ["loss"], window_events=2,
        checkpoint=ck, metrics_registry=reg)
    report = tr.run()
    tr.close()
    assert len(report.windows) == 3
    snap = reg.snapshot()

    def val(name, key="value"):
        return snap[name]["series"][0][key]

    assert val("streaming_events_total") == 6
    assert val("streaming_steps_total") == 6
    assert val("streaming_windows_total") == 3
    assert val("streaming_window_loss") == pytest.approx(0.5)
    assert val("streaming_events_per_s") > 0
    # the lag gauge ticked after the first commit
    assert val("streaming_delta_lag_s") >= 0
    assert report.checkpoints
