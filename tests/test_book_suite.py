"""Book-test breadth: fit_a_line, word2vec, understand_sentiment (conv +
stacked LSTM), recommender_system, image_classification — e2e static-graph
training with loss decrease + save/load round trips, over the
paddle.dataset-parity readers (reference `tests/book/*.py`)."""

import numpy as np
import pytest

import paddle_tpu
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def _pad_ids(seqs, T, pad=0):
    out = np.full((len(seqs), T), pad, np.int64)
    lens = np.zeros((len(seqs),), np.int64)
    for i, s in enumerate(seqs):
        s = s[:T]
        out[i, : len(s)] = s
        lens[i] = len(s)
    return out, lens


# ---------------------------------------------------------------------------
# fit_a_line (reference tests/book/test_fit_a_line.py)
# ---------------------------------------------------------------------------


def test_fit_a_line(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[13])
        y = layers.data("y", shape=[1])
        pred = layers.fc(x, size=1)
        cost = layers.square_error_cost(pred, y)
        avg = layers.mean(cost)
        test_prog = main.clone(for_test=True)
        fluid.optimizer.SGDOptimizer(learning_rate=0.01).minimize(avg)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    reader = paddle_tpu.batch(
        paddle_tpu.reader.shuffle(
            paddle_tpu.dataset.uci_housing.train(), buf_size=200),
        batch_size=20, drop_last=True,
    )
    losses = []
    for epoch in range(6):
        for batch in reader():
            feed = paddle_tpu.reader.to_feed(batch, ["x", "y"])
            (lv,) = exe.run(main, feed=feed, fetch_list=[avg])
            losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])

    # save_inference_model round trip (reference train->infer flow)
    path = str(tmp_path / "fit_a_line.model")
    fluid.io.save_inference_model(path, ["x"], [pred], exe, main)
    scope2 = fluid.Scope()
    exe2 = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope2):
        prog, feeds, fetches = fluid.io.load_inference_model(path, exe2)
        xv = np.random.RandomState(5).randn(4, 13).astype(np.float32)
        (out2,) = exe2.run(prog, feed={feeds[0]: xv}, fetch_list=fetches)
    (out1,) = exe.run(test_prog, feed={"x": xv, "y": np.zeros((4, 1), np.float32)},
                      fetch_list=[pred])
    np.testing.assert_allclose(out2, out1, rtol=1e-5)


# ---------------------------------------------------------------------------
# word2vec (reference tests/book/test_word2vec.py: 4-gram, shared table)
# ---------------------------------------------------------------------------


def test_word2vec():
    dict_size, EMB, HID, N = 150, 16, 64, 5
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        words = [
            layers.data("w%d" % i, shape=[1], dtype="int64")
            for i in range(N)
        ]
        embs = [
            layers.embedding(
                w, size=[dict_size, EMB],
                param_attr=fluid.ParamAttr(name="shared_w"),
            )
            for w in words[:4]
        ]
        embs = [layers.reshape(e, [-1, EMB]) for e in embs]
        concat = layers.concat(embs, axis=1)
        hidden = layers.fc(concat, size=HID, act="sigmoid")
        logits = layers.fc(hidden, size=dict_size)
        loss = layers.mean(
            layers.softmax_with_cross_entropy(logits, words[4])
        )
        fluid.optimizer.AdamOptimizer(learning_rate=1e-2).minimize(loss)

    # synthetic 5-grams with LEARNABLE structure: next word = f(context)
    rs = np.random.RandomState(0)
    data = rs.randint(0, dict_size, (2000, 5)).astype(np.int64)
    data[:, 4] = (data[:, 0] + data[:, 3]) % dict_size

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    losses = []
    bs = 64
    for epoch in range(8):
        for i in range(0, len(data), bs):
            b = data[i: i + bs]
            feed = {"w%d" % j: b[:, j: j + 1] for j in range(5)}
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
    # the table really is shared: exactly ONE embedding parameter
    emb_params = [p for p in main.all_parameters() if p.name == "shared_w"]
    assert len(emb_params) == 1


# ---------------------------------------------------------------------------
# understand_sentiment (reference notest_understand_sentiment.py)
# ---------------------------------------------------------------------------


def _sentiment_data(T=48):
    word_dict = paddle_tpu.dataset.imdb.word_dict()
    train = list(paddle_tpu.dataset.imdb.train(192)())
    ids, lens = _pad_ids([s for s, _ in train], T)
    labels = np.array([l for _, l in train], np.int64).reshape(-1, 1)
    return len(word_dict), ids, lens, labels


def _run_sentiment(build_net):
    dict_dim, ids, lens, labels = _sentiment_data()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        data = layers.data("words", shape=[48], dtype="int64")
        seq_len = layers.data("lens", shape=[-1], dtype="int64",
                              append_batch_size=False)
        label = layers.data("label", shape=[1], dtype="int64")
        probs, loss = build_net(data, seq_len, label, dict_dim)
        acc = layers.accuracy(probs, label)
        fluid.optimizer.AdamOptimizer(learning_rate=2e-3).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    bs = 32
    first = last = None
    accs = []
    for epoch in range(6):
        for i in range(0, len(ids), bs):
            feed = {
                "words": ids[i: i + bs],
                "lens": lens[i: i + bs].reshape(-1),
                "label": labels[i: i + bs],
            }
            lv, av = exe.run(main, feed=feed, fetch_list=[loss, acc])
            first = first if first is not None else float(lv)
            last = float(lv)
            accs.append(float(av))
    assert last < first, (first, last)
    assert np.mean(accs[-6:]) > 0.8, accs[-6:]


def test_understand_sentiment_conv():
    def conv_net(data, seq_len, label, dict_dim, emb_dim=24, hid_dim=24):
        emb = layers.embedding(data, size=[dict_dim, emb_dim])
        conv3 = layers.sequence_conv(
            emb, seq_len, num_filters=hid_dim, filter_size=3, act="tanh")
        conv4 = layers.sequence_conv(
            emb, seq_len, num_filters=hid_dim, filter_size=4, act="tanh")
        p3 = layers.sequence_pool(conv3, "max", seq_len)
        p4 = layers.sequence_pool(conv4, "max", seq_len)
        logits = layers.fc(layers.concat([p3, p4], axis=1), size=2)
        probs = layers.softmax(logits)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        return probs, loss

    _run_sentiment(conv_net)


def test_understand_sentiment_stacked_lstm():
    def lstm_net(data, seq_len, label, dict_dim, emb_dim=24, hid_dim=24,
                 stacked_num=3):
        emb = layers.embedding(data, size=[dict_dim, emb_dim])
        fc1 = layers.fc(emb, size=hid_dim * 4, num_flatten_dims=2)
        lstm1, _ = layers.dynamic_lstm(fc1, size=hid_dim * 4,
                                       seq_lens=seq_len)
        inputs = lstm1
        for i in range(2, stacked_num + 1):
            fc_i = layers.fc(inputs, size=hid_dim * 4, num_flatten_dims=2)
            lstm_i, _ = layers.dynamic_lstm(
                fc_i, size=hid_dim * 4, seq_lens=seq_len,
                is_reverse=(i % 2) == 0)
            inputs = lstm_i
        pooled = layers.sequence_pool(inputs, "last", seq_len)
        logits = layers.fc(pooled, size=2)
        probs = layers.softmax(logits)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        return probs, loss

    _run_sentiment(lstm_net)


# ---------------------------------------------------------------------------
# recommender_system (reference tests/book/test_recommender_system.py)
# ---------------------------------------------------------------------------


def test_recommender_system():
    ml = paddle_tpu.dataset.movielens
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        uid = layers.data("user_id", shape=[1], dtype="int64")
        gender = layers.data("gender", shape=[1], dtype="int64")
        age = layers.data("age", shape=[1], dtype="int64")
        job = layers.data("job", shape=[1], dtype="int64")
        mid = layers.data("movie_id", shape=[1], dtype="int64")
        cat = layers.data("category", shape=[1], dtype="int64")
        rating = layers.data("score", shape=[1], dtype="float32")

        def tower(parts, size=32):
            feats = [layers.reshape(e, [-1, int(e.shape[-1])]) for e in parts]
            return layers.fc(layers.concat(feats, axis=1), size=size,
                             act="tanh")

        usr = tower([
            layers.embedding(uid, [ml.USER_COUNT, 16]),
            layers.embedding(gender, [2, 8]),
            layers.embedding(age, [ml.AGE_COUNT, 8]),
            layers.embedding(job, [ml.JOB_COUNT, 8]),
        ])
        mov = tower([
            layers.embedding(mid, [ml.MOVIE_COUNT, 16]),
            layers.embedding(cat, [ml.CATEGORY_COUNT, 8]),
        ])
        sim = layers.ops.cos_sim(usr, mov)
        pred = layers.scale(sim, scale=5.0)
        loss = layers.mean(layers.square_error_cost(pred, rating))
        fluid.optimizer.AdamOptimizer(learning_rate=5e-3).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    names = ["user_id", "gender", "age", "job", "movie_id", "category",
             "score"]
    reader = paddle_tpu.batch(ml.train(512), batch_size=64, drop_last=True)
    losses = []
    for epoch in range(8):
        for batch in reader():
            feed = paddle_tpu.reader.to_feed(batch, names)
            feed["score"] = feed["score"].astype(np.float32)
            (lv,) = exe.run(main, feed=feed, fetch_list=[loss])
            losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])


# ---------------------------------------------------------------------------
# image_classification on CIFAR-shape data (reference
# tests/book/test_image_classification.py — VGG-lite)
# ---------------------------------------------------------------------------


def test_image_classification_cifar():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        img = layers.data("img", shape=[3, 32, 32])
        label = layers.data("label", shape=[1], dtype="int64")
        c1 = layers.conv2d(img, num_filters=16, filter_size=3, padding=1,
                           act="relu")
        p1 = layers.pool2d(c1, pool_size=2, pool_stride=2)
        c2 = layers.conv2d(p1, num_filters=32, filter_size=3, padding=1,
                           act="relu")
        p2 = layers.pool2d(c2, pool_size=2, pool_stride=2)
        bn = layers.batch_norm(layers.fc(p2, size=64), act="relu")
        logits = layers.fc(layers.dropout(bn, 0.2), size=10)
        loss = layers.mean(layers.softmax_with_cross_entropy(logits, label))
        acc = layers.accuracy(layers.softmax(logits), label)
        fluid.optimizer.AdamOptimizer(learning_rate=2e-3).minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    reader = paddle_tpu.batch(paddle_tpu.dataset.cifar.train10(256),
                              batch_size=32, drop_last=True)
    accs, losses = [], []
    for epoch in range(5):
        for batch in reader():
            feed = paddle_tpu.reader.to_feed(batch, ["img", "label"])
            feed["img"] = feed["img"].reshape(-1, 3, 32, 32)
            lv, av = exe.run(main, feed=feed, fetch_list=[loss, acc])
            losses.append(float(lv))
            accs.append(float(av))
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    assert np.mean(accs[-4:]) > 0.5, accs[-4:]


# ---------------------------------------------------------------------------
# machine_translation (reference tests/book/test_machine_translation.py):
# WMT14-format reader -> seq2seq with attention -> loss decrease + decode
# ---------------------------------------------------------------------------


def test_machine_translation():
    from paddle_tpu.dataset import wmt14

    DICT = 20
    TS, TD = 12, 12
    E, H = 24, 32
    B = 32

    # wmt14 triples: src = <s> w <e>, trg = <s> t, trg_next = t <e>
    data = list(wmt14.train(DICT, n=256)())
    src_dict, trg_dict = wmt14.get_dict(DICT, reverse=True)
    assert src_dict[0] == "<s>" and trg_dict[1] == "<e>"

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        src = layers.data("src", shape=[TS], dtype="int64")
        src_lens = layers.data("src_lens", shape=[], dtype="int32")
        tgt_in = layers.data("tgt_in", shape=[TD], dtype="int64")
        tgt_out = layers.data("tgt_out", shape=[TD], dtype="int64")
        tgt_lens = layers.data("tgt_lens", shape=[], dtype="int32")

        emb = layers.embedding(src, size=[DICT, E],
                               param_attr=fluid.ParamAttr(name="mt_semb"))
        proj = layers.fc(emb, 3 * H, num_flatten_dims=2, bias_attr=False,
                         param_attr=fluid.ParamAttr(name="mt_eproj"))
        enc = layers.dynamic_gru(
            proj, H, seq_lens=src_lens,
            param_attr=fluid.ParamAttr(name="mt_egru"),
            bias_attr=fluid.ParamAttr(name="mt_egru_b"))
        h0 = layers.sequence_last_step(enc, src_lens)

        temb = layers.embedding(tgt_in, size=[DICT, E],
                                param_attr=fluid.ParamAttr(name="mt_temb"))
        temb_tm = layers.transpose(temb, [1, 0, 2])
        srnn = layers.StaticRNN()
        with srnn.step():
            x_t = srnn.step_input(temb_tm)
            h_prev = srnn.memory(init=h0)
            # dot attention over encoder states
            scores = layers.reduce_sum(
                layers.elementwise_mul(enc, layers.unsqueeze(h_prev, [1])),
                dim=2)
            w = layers.sequence_softmax(scores, src_lens)
            ctxv = layers.reduce_sum(
                layers.elementwise_mul(enc, layers.unsqueeze(w, [2])),
                dim=1)
            inp = layers.concat([x_t, ctxv], axis=1)
            pre = layers.fc(inp, 3 * H, bias_attr=False,
                            param_attr=fluid.ParamAttr(name="mt_dproj"))
            h = layers.gru_unit(
                pre, h_prev, 3 * H,
                param_attr=fluid.ParamAttr(name="mt_dgru"),
                bias_attr=fluid.ParamAttr(name="mt_dgru_b"))
            srnn.update_memory(h_prev, h)
            srnn.step_output(h)
        dec = layers.transpose(srnn(), [1, 0, 2])
        logits = layers.fc(dec, DICT, num_flatten_dims=2,
                           param_attr=fluid.ParamAttr(name="mt_out_w"),
                           bias_attr=fluid.ParamAttr(name="mt_out_b"))
        flat = layers.reshape(logits, [-1, DICT])
        lab = layers.reshape(tgt_out, [-1, 1])
        ce = layers.softmax_with_cross_entropy(flat, lab)
        mask = layers.sequence_mask(tgt_lens, TD, dtype="float32")
        ce = layers.reshape(ce, [-1, TD]) * mask
        loss = layers.reduce_sum(ce) / (layers.reduce_sum(mask) + 1e-6)
        fluid.optimizer.AdamOptimizer(8e-3).minimize(loss)

    def feed_of(batch):
        srcs = [ex[0] for ex in batch]
        tins = [ex[1] for ex in batch]
        touts = [ex[2] for ex in batch]
        s, sl = _pad_ids(srcs, TS)
        ti, _ = _pad_ids(tins, TD)
        to, tl = _pad_ids(touts, TD)
        return {"src": s, "src_lens": sl.astype(np.int32),
                "tgt_in": ti, "tgt_out": to,
                "tgt_lens": tl.astype(np.int32)}

    exe = fluid.Executor()
    losses = []
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        for epoch in range(25):
            for i in range(0, len(data) - B + 1, B):
                (lv,) = exe.run(main, feed=feed_of(data[i:i + B]),
                                fetch_list=[loss])
                losses.append(float(lv))
        # loss must decrease markedly (reference asserts < 10 after a few
        # iterations; the toy mapping is fully learnable)
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

        # greedy decode round trip on test data through an eval clone
        test_prog = main.clone(for_test=True)
        ex0 = list(wmt14.test(DICT, n=4)())
        feed = feed_of(ex0)
        (lg,) = exe.run(test_prog, feed=feed, fetch_list=[logits])
        pred = np.argmax(lg, axis=-1)
        # teacher-forced next-token accuracy on real (unpadded) positions
        to, tl = _pad_ids([e[2] for e in ex0], TD)
        correct = total = 0
        for b in range(len(ex0)):
            n = int(tl[b])
            correct += int((pred[b, :n] == to[b, :n]).sum())
            total += n
        assert correct / total > 0.5, (correct, total)


def test_wmt_readers_contract():
    """wmt14/wmt16 reader-creator protocol + token layout (reference
    dataset/wmt14.py:81 reader_creator, wmt16.py:109)."""
    from paddle_tpu.dataset import wmt14, wmt16

    for src_ids, trg_ids, trg_next in list(wmt14.train(30, n=8)()):
        assert src_ids[0] == 0 and src_ids[-1] == 1      # <s> ... <e>
        assert trg_ids[0] == 0                           # <s> ...
        assert trg_next[-1] == 1                         # ... <e>
        assert trg_ids[1:] == trg_next[:-1]
        assert all(3 <= t < 30 for t in trg_next[:-1])
    sd, td = wmt14.get_dict(30, reverse=False)
    assert sd["<s>"] == 0 and td["<e>"] == 1 and td["<unk>"] == 2
    # wmt16: direction swap is consistent
    a = list(wmt16.train(30, 30, src_lang="en", n=4)())
    b = list(wmt16.train(30, 30, src_lang="de", n=4)())
    # en->de source body equals de->en target body
    assert a[0][0][1:-1] == b[0][2][:-1]
    d = wmt16.get_dict("de", 30)
    assert d["<s>"] == 0 and len(d) == 30
