"""Go cgo client over the C ABI (reference `go/paddle/predictor.go`
capability — the last open parity row from VERDICT r5): build
libpaddle_tpu_capi.so, save a model, and run the `go/paddle_tpu`
package's test, which must reproduce the Python Predictor's outputs.

Gated on the toolchain: no g++ (cannot build the .so) or no Go
toolchain -> clean skip with the reason, per the satellite contract."""

import os
import shutil
import struct
import subprocess
import sysconfig

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE = os.path.join(REPO, "paddle_tpu", "native")
GO_PKG = os.path.join(REPO, "go", "paddle_tpu")


def _embed_flags():
    inc = sysconfig.get_paths()["include"]
    libdir = sysconfig.get_config_var("LIBDIR")
    ver = sysconfig.get_config_var("LDVERSION")
    return (["-I%s" % inc, "-I%s" % NATIVE],
            ["-L%s" % libdir, "-lpython%s" % ver, "-ldl", "-lm"])


def _save_fc_model(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 11
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 8], append_batch_size=False)
        pred = layers.fc(layers.fc(x, 16, act="relu"), 4, act="softmax")
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    path = str(tmp_path / "fc.model")
    fluid.io.save_inference_model(path, ["x"], [pred], exe, main)
    return path


def _write_bin(path, arr):
    arr = np.ascontiguousarray(arr, np.float32)
    with open(path, "wb") as f:
        f.write(struct.pack("<q", arr.ndim))
        for d in arr.shape:
            f.write(struct.pack("<q", d))
        f.write(arr.tobytes())


@pytest.mark.skipif(shutil.which("g++") is None,
                    reason="no g++ to build libpaddle_tpu_capi.so")
@pytest.mark.skipif(shutil.which("go") is None,
                    reason="no Go toolchain; the cgo client cannot be "
                           "smoke-tested in this environment")
def test_go_client_matches_python_predictor(tmp_path):
    incs, libs = _embed_flags()
    so = str(tmp_path / "libpaddle_tpu_capi.so")
    build = subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC",
         os.path.join(NATIVE, "infer_capi.cc")] + incs + libs + ["-o", so],
        capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stderr

    model_dir = _save_fc_model(tmp_path)
    rng = np.random.RandomState(4)
    x = rng.randn(3, 8).astype(np.float32)

    from paddle_tpu.inference import AnalysisConfig, create_predictor

    want, = create_predictor(AnalysisConfig(model_dir)).run([x])

    input_bin = str(tmp_path / "input.bin")
    expected_bin = str(tmp_path / "expected.bin")
    _write_bin(input_bin, x)
    _write_bin(expected_bin, want)

    env = dict(os.environ)
    env.update({
        "PADDLE_TPU_TEST_MODEL_DIR": model_dir,
        "PADDLE_TPU_TEST_INPUT": input_bin,
        "PADDLE_TPU_TEST_EXPECTED": expected_bin,
        "CGO_ENABLED": "1",
        "CGO_CFLAGS": "-I%s" % NATIVE,
        "CGO_LDFLAGS": "%s -Wl,-rpath,%s" % (so, str(tmp_path)),
        "GOCACHE": str(tmp_path / "gocache"),
        "GOFLAGS": "-count=1",
        # the embedded interpreter must match this test's backend setup
        "PYTHONPATH": REPO,
        "JAX_PLATFORMS": "cpu",
        "JAX_DEFAULT_MATMUL_PRECISION": "highest",
    })
    env.pop("PALLAS_AXON_POOL_IPS", None)
    run = subprocess.run(
        ["go", "test", "-v", "-run", "TestPredictorMatchesPython", "./..."],
        cwd=GO_PKG, capture_output=True, text=True, timeout=600, env=env)
    assert run.returncode == 0, (run.stdout, run.stderr)
    assert "PASS" in run.stdout, run.stdout
    assert "SKIP" not in run.stdout, run.stdout


def test_go_package_sources_are_wellformed():
    """Toolchain-independent floor: the Go package ships, declares the
    documented API surface, and binds every C ABI symbol — so a
    go-less CI still guards against bitrot of the source itself."""
    src = open(os.path.join(GO_PKG, "paddle_tpu.go")).read()
    for sym in ("PD_CreatePredictor", "PD_Run", "PD_DeletePredictor",
                "PD_GetInputNum", "PD_GetInputName", "PD_GetOutputNum",
                "PD_GetOutputName"):
        assert sym in src, "C ABI symbol %s unbound in the Go client" % sym
    for api in ("func NewPredictor", "func (p *Predictor) Run",
                "func (p *Predictor) InputNames",
                "func (p *Predictor) OutputNames",
                "func (p *Predictor) Close", "type Tensor struct"):
        assert api in src, "Go client API %r missing" % api
    assert os.path.exists(os.path.join(GO_PKG, "go.mod"))
    header = open(os.path.join(NATIVE, "paddle_tpu_capi.h")).read()
    # every symbol the client binds must exist in the header it compiles
    # against
    for sym in ("PD_CreatePredictor", "PD_Run", "PD_DeletePredictor"):
        assert sym in header
