"""CI perf-regression gate: static roofline budgets for zoo models.

`tools/program_cost.py --budget-ms` prices each model on an EXPLICIT
chip (--peak-flops/--hbm-bw), so the gate is platform-independent: it
fails when a future pass or lowering change inflates a model's static
FLOPs/bytes past its pinned budget — the cheap, deterministic tier-1
cousin of the measured autotuner (the cost model was anchored to XLA's
own cost_analysis within ~1%% on these models in PERF.md round 8).

Budgets are ~2.5x the estimates at pin time (see table below), so
normal estimator recalibration never trips them but an accidental
op-count/shape blowup (a fusion pass gone wrong, a transpose storm, a
de-optimized lowering) does.  If a budget fires after an INTENTIONAL
model/estimator change, re-pin it in this file with the new measured
estimate — that is the review moment the gate exists to create.
"""

import importlib.util
import os

import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import models
from paddle_tpu.fluid import layers

# the gate's fixed pricing chip — NOT a real platform on purpose
PEAK_FLOPS = "1e14"
HBM_BW = "1e12"

# model -> (builder, budget_ms).  Estimates at pin time (2026-08-04):
# lenet 0.0050 ms, resnet18 0.0565 ms, bert-small 0.0281 ms.
_GATE = {}


def _gate(name, budget_ms):
    def deco(fn):
        _GATE[name] = (fn, budget_ms)
        return fn
    return deco


@_gate("lenet", 0.015)
def _build_lenet():
    x = layers.data("img", shape=[-1, 1, 28, 28], append_batch_size=False)
    return models.LeNet5()(x)


@_gate("resnet18", 0.15)
def _build_resnet18():
    x = layers.data("img", shape=[-1, 3, 32, 32], append_batch_size=False)
    return models.resnet18(num_classes=7)(x)


@_gate("bert_small", 0.08)
def _build_bert_small():
    cfg = models.BertConfig(
        vocab_size=512, hidden_size=128, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=512,
        max_position_embeddings=128, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    mk = lambda n: layers.data(  # noqa: E731
        n, shape=[4, 64], append_batch_size=False, dtype="int64")
    logits, _nsp = models.BertForPretraining(cfg)(
        mk("ids"), mk("seg"), mk("pos"), mk("mask"))
    return logits


def _program_cost_tool():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "program_cost", os.path.join(repo, "tools", "program_cost.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _dump(name, tmp_path):
    builder, budget = _GATE[name]
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        builder()
    path = str(tmp_path / ("%s.json" % name))
    with open(path, "w") as f:
        f.write(main.to_json())
    return path, budget


@pytest.mark.parametrize("name", sorted(_GATE), ids=sorted(_GATE))
def test_zoo_model_within_static_roofline_budget(name, tmp_path, capsys):
    pc = _program_cost_tool()
    path, budget = _dump(name, tmp_path)
    rc = pc.main([path, "--budget-ms", str(budget),
                  "--peak-flops", PEAK_FLOPS, "--hbm-bw", HBM_BW])
    out = capsys.readouterr().out
    assert rc == 0, (
        "%s blew its static roofline budget (%.3f ms): a pass or "
        "lowering change inflated the program's estimated cost.\n%s"
        % (name, budget, out))
    assert "within" in out


@pytest.mark.parametrize("name", sorted(_GATE), ids=sorted(_GATE))
def test_gate_actually_binds(name, tmp_path, capsys):
    """A vacuous gate is worse than none: the same model must FAIL a
    near-zero budget, proving the estimate is non-trivial and the rc
    contract holds."""
    pc = _program_cost_tool()
    path, _budget = _dump(name, tmp_path)
    rc = pc.main([path, "--budget-ms", "1e-9",
                  "--peak-flops", PEAK_FLOPS, "--hbm-bw", HBM_BW])
    capsys.readouterr()
    assert rc == 1


# ---------------------------------------------------------------------------
# lint-cleanliness gate: the perf hazards the PR-11 passes eliminate
# must STAY eliminated — re-introducing an unfused FFN epilogue or a
# head-transpose pair fails tier-1
# ---------------------------------------------------------------------------


def _perf_findings(program, codes):
    from paddle_tpu import analysis

    diags = analysis.lint_program(program, categories=("perf",))
    return [d for d in diags if d.code in codes]


def test_zoo_bert_lints_clean_after_fusion_passes():
    """Zoo BERT carries fusable FFN epilogues (the gate binds), and
    after MatmulBiasActFusePass + TransposeFoldPass — verified after
    each pass — it emits ZERO unfused-epilogue / layout-transpose-
    hazard findings."""
    from paddle_tpu.fluid import ir

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        _GATE["bert_small"][0]()
    codes = ("unfused-epilogue", "layout-transpose-hazard")
    before = _perf_findings(main, codes)
    assert any(d.code == "unfused-epilogue" for d in before), (
        "gate is vacuous: the unfused BERT FFN no longer emits the "
        "epilogue chain the fusion pass exists for")
    for d in before:
        assert d.fix in ("matmul_bias_act_fuse", "transpose_fold")
    fused = ir.clone_and_apply(
        main, ["matmul_bias_act_fuse", "transpose_fold"], verify=True)
    after = _perf_findings(fused, codes)
    assert not after, (
        "zoo BERT still lints dirty after the fusion passes:\n"
        + "\n".join(d.format() for d in after))


def _bert_small_params():
    """Parameter name -> numpy-shaped zeros for the zoo BERT config —
    the tensors a dp=8 training step communicates."""
    import numpy as np

    from paddle_tpu.fluid import dygraph

    cfg = models.BertConfig(
        vocab_size=512, hidden_size=128, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=512,
        max_position_embeddings=128, hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0)
    with dygraph.guard():
        model = models.BertForPretraining(cfg)
        return {k: np.zeros(v.shape, np.float32)
                for k, v in model.state_dict().items()}


# collective-bytes budget for zoo BERT on a dp=8 mesh: the static comm
# model's per-step wire bytes (reduce-scatter + all-gather + scalar
# all-reduce at ZeRO-2).  Estimate at pin time (2026-08-04): 3.59 MB;
# budget ~2.5x so recalibration never trips it but a replication
# regression (a pass/lowering change that re-replicates gradients or
# doubles the gather set) does.
_COMM_BUDGET_BYTES = 9.0e6


def test_zoo_bert_dp8_collective_bytes_within_budget():
    from paddle_tpu.distributed import zero as zero_mod

    layouts = zero_mod.plan_layouts(_bert_small_params(), 8)
    est = zero_mod.zero_comm_estimate(layouts, 2, 8,
                                      state_slots_per_param=2)
    assert 0 < est["wire_bytes_total"] <= _COMM_BUDGET_BYTES, (
        "zoo BERT dp=8 ZeRO-2 step wants %.2f MB on the wire "
        "(budget %.2f MB): a layout or estimator change inflated "
        "collective traffic — re-pin only if intentional"
        % (est["wire_bytes_total"] / 1e6, _COMM_BUDGET_BYTES / 1e6))
    # binds-check: a near-zero budget must fail
    assert est["wire_bytes_total"] > 1e3


def test_replicated_gradient_lint_gate():
    """The replicated-gradient hazard gate: an optimizer program on a
    dp=8 mesh with unsharded grads MUST lint dirty (the ZeRO-2 value
    proposition stays visible), and the same program without a mesh
    stays clean (no false alarms on single-chip CI)."""
    from paddle_tpu import distributed as dist

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("gx", shape=[-1, 64], append_batch_size=False)
        y = layers.data("gy", shape=[-1, 1], append_batch_size=False)
        pred = layers.fc(x, size=1, param_attr="gate_fc.w")
        loss = layers.reduce_mean(layers.square(pred - y))
        fluid.optimizer.AdamOptimizer(1e-3).minimize(loss)
    clean = _perf_findings(main, ("replicated-gradient",))
    assert not clean, "rule fired without a mesh: false alarm"
    mesh = dist.auto_mesh(8)
    with dist.mesh_guard(mesh):
        dirty = _perf_findings(main, ("replicated-gradient",))
    assert len(dirty) == 1, "gate is vacuous: hazard not flagged"
    assert dirty[0].fix == "zero_stage>=2"


def test_zoo_bert_bhsd_layout_folds_clean(monkeypatch):
    """The head-major (BHSD) BERT build materializes the exact
    [B,S,H,D]<->[B,H,S,D] transpose pairs the hazard rule flags;
    TransposeFoldPass must cancel every one (flash layout attr flip)
    and survive verification."""
    from paddle_tpu.fluid import ir

    monkeypatch.setenv("PADDLE_TPU_BERT_HEAD_LAYOUT", "BHSD")
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        _GATE["bert_small"][0]()
    hazards = _perf_findings(main, ("layout-transpose-hazard",))
    assert hazards, "BHSD build emitted no transpose hazard: gate vacuous"
    folded = ir.clone_and_apply(
        main, ["transpose_fold", "matmul_bias_act_fuse"], verify=True)
    assert not _perf_findings(
        folded, ("layout-transpose-hazard", "unfused-epilogue"))
    types = [op.type for op in folded.global_block.ops]
    assert "transpose2" not in types


# ---------------------------------------------------------------------------
# host-exchange-bytes budget: the recsys path's fourth roofline axis
# (fluid.host_embedding pull/push traffic priced via OpCost.host_bytes)
# ---------------------------------------------------------------------------

# zoo CTR model: batch 256 x 16 ids into a [200k, 32] host table.  The
# static upper bound bills one row per looked-up id both ways (pull f32
# row + push f32 grad row + ids): 256*16 * (32*4 + 32*4 + 16) = 1.11 MB
# per step.  Budget ~2.5x so estimator recalibration never trips it but
# an accidental double-exchange (a lowering that re-pulls, a layout
# change that inflates the row payload) does.
_HOSTEX_BUDGET_BYTES = 2.8e6


def _build_ctr_recsys():
    ids = layers.data("ids", shape=[256, 16], dtype="int64",
                      append_batch_size=False)
    emb = layers.embedding(ids, size=[200_000, 32], is_distributed=True,
                           param_attr="gate_ctr.emb")
    pooled = layers.reduce_mean(emb, dim=1)
    h = layers.fc(pooled, size=64, act="relu", param_attr="gate_ctr.w")
    return layers.fc(h, size=1, param_attr="gate_ctr.out")


def test_zoo_recsys_host_exchange_bytes_within_budget():
    from paddle_tpu.analysis import perf

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        _build_ctr_recsys()
    chip = perf.ChipSpec(
        "gate", float(PEAK_FLOPS), float(HBM_BW), host_bw=1.6e10)
    rep = perf.program_cost(main, chip=chip)
    host = rep.total_host_bytes
    assert 0 < host <= _HOSTEX_BUDGET_BYTES, (
        "zoo recsys step wants %.2f MB across the host link (budget "
        "%.2f MB): an exchange or lowering change inflated the "
        "distributed-embedding traffic — re-pin only if intentional"
        % (host / 1e6, _HOSTEX_BUDGET_BYTES / 1e6))
    # binds-check: the estimate is non-trivial (at least one full
    # pull+push of every looked-up row) and prices against host_bw —
    # the lookup op must be host-bound on this chip
    assert host >= 256 * 16 * (32 * 4 + 32 * 4)
    lookup = [e for e in rep.entries if e.op_type == "lookup_table"]
    assert lookup and lookup[0].bound == "host"
    # ... and the dimension reaches the CLI gate: totals + chip carry it
    d = rep.to_dict()
    assert d["totals"]["host_bytes"] == host
    assert d["chip"]["host_bw"] == 1.6e10


def test_host_exchange_dimension_off_for_dense_embedding():
    """A plain in-HBM embedding must NOT be billed host traffic — the
    dimension prices only the is_distributed host-table path."""
    from paddle_tpu.analysis import perf

    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        ids = layers.data("dids", shape=[8, 4], dtype="int64",
                          append_batch_size=False)
        layers.embedding(ids, size=[100, 8], param_attr="gate_dense.emb")
    rep = perf.program_cost(main)
    assert rep.total_host_bytes == 0


# ---------------------------------------------------------------------------
# SIGKILL-mid-stream drill: delta-checkpoint restore loses at most one
# checkpoint window
# ---------------------------------------------------------------------------

STREAM_CRASH_WORKER = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "streaming_crash_worker.py")


def test_sigkill_mid_stream_restores_within_one_window(tmp_path):
    """Train 3 windows committing a delta checkpoint per window, then
    SIGKILL mid-window-4 (post-commit work in flight, no cleanup).
    Restore must land EXACTLY on the window-3 commit — at most one
    window of events lost — and the restored table must be
    bit-identical to an uninterrupted run truncated at that commit
    (same digest), proving replay correctness, not just liveness."""
    import json as _json
    import subprocess
    import sys as _sys

    root = str(tmp_path / "ck")
    p = subprocess.run(
        [_sys.executable, STREAM_CRASH_WORKER, "train", root, "8", "3"],
        capture_output=True, text=True)
    assert p.returncode == -9, (p.returncode, p.stderr[-500:])

    p = subprocess.run(
        [_sys.executable, STREAM_CRASH_WORKER, "restore", root, "0"],
        capture_output=True, text=True)
    assert p.returncode == 0, p.stderr[-500:]
    got = _json.loads(p.stdout.strip().splitlines()[-1])
    # window 4 was half-trained when the kill landed; the committed
    # chain ends at window 3 — exactly one window boundary behind
    assert got["window"] == 3
    assert got["events_done"] == 3 * 4 * 8     # windows x steps x batch

    # ground truth: an uninterrupted 3-window run's table digest
    p = subprocess.run(
        [_sys.executable, STREAM_CRASH_WORKER, "train",
         str(tmp_path / "ck2"), "3"],
        capture_output=True, text=True)
    assert p.returncode == 0, p.stderr[-500:]
    want = _json.loads(p.stdout.strip().splitlines()[-1])
    assert got["digest"] == want["digest"], (
        "restored table diverges from the uninterrupted run: delta "
        "replay is lossy or misordered")


# decode-step HBM-bytes budget for the generation engine on zoo
# BERT-small shapes (L=4, h=256, V=8k) at slots=8, cache_len=512: KV
# read 2*4*8*512*256*4 = 32 MB + params ~10.5 MB per step.  Estimate at
# pin time (2026-08-04): 42.9 MB; budget ~2.5x so a cache-layout or
# estimator regression (e.g. re-reading the cache per layer pass, or a
# recompute-prefix fallback sneaking into the decode path) trips it.
_DECODE_BUDGET_BYTES = 110e6


def test_generation_decode_step_hbm_bytes_within_budget():
    from paddle_tpu.analysis.perf import ChipSpec, decode_step_cost

    chip = ChipSpec("pinned", 197e12, 819e9)   # platform-independent
    cost = decode_step_cost(
        num_layers=4, hidden_size=256, num_heads=4, vocab_size=8000,
        intermediate_size=1024, slots=8, cache_len=512, chip=chip)
    assert cost.bound == "memory", (
        "decode step should be HBM-bound; got %r" % cost.bound)
    assert 0 < cost.bytes <= _DECODE_BUDGET_BYTES, (
        "decode step wants %.1f MB of HBM traffic (budget %.1f MB): a "
        "cache-layout or estimator change inflated the per-token read "
        "— re-pin only if intentional"
        % (cost.bytes / 1e6, _DECODE_BUDGET_BYTES / 1e6))
    # binds-check: a near-zero budget must fail
    assert cost.bytes > 1e3
    # the KV read must dominate growth in cache_len (the quantity the
    # budget exists to guard)
    longer = decode_step_cost(
        num_layers=4, hidden_size=256, num_heads=4, vocab_size=8000,
        intermediate_size=1024, slots=8, cache_len=1024, chip=chip)
    assert longer.kv_read_bytes == 2 * cost.kv_read_bytes


def test_generation_paged_decode_kv_bytes_beat_dense():
    """PR-17 gate: at the long-prompt/short-output mix (dense must
    provision cache_len=max_len while live sequences average far
    shorter), the paged decode step's KV traffic must be STRICTLY
    below dense — the headline paged win, priced by the estimator the
    CI runs on every platform.  int8 KV must beat f32 paged even after
    paying the per-head scale reads."""
    from paddle_tpu.analysis.perf import ChipSpec, decode_step_cost

    chip = ChipSpec("pinned", 197e12, 819e9)
    shape = dict(num_layers=4, hidden_size=256, num_heads=4,
                 vocab_size=8000, intermediate_size=1024, slots=8,
                 chip=chip)
    dense = decode_step_cost(cache_len=512, **shape)
    paged = decode_step_cost(cache_len=512, paged=True, mean_len=96,
                             block_size=16, **shape)
    assert paged.paged and not dense.paged
    assert paged.kv_read_bytes < dense.kv_read_bytes, (
        "paged KV read (%.2f MB) must be strictly below dense "
        "(%.2f MB) at mean_len 96 vs cache_len 512"
        % (paged.kv_read_bytes / 1e6, dense.kv_read_bytes / 1e6))
    # the exact ratio: dense reads cache_len rows, paged reads
    # ceil(mean/bs)*bs = 96 rows
    assert paged.kv_read_bytes * 512 == dense.kv_read_bytes * 96
    assert paged.bytes < dense.bytes
    # int8 halves-and-then-some the paged read even with scale reads
    i8 = decode_step_cost(cache_len=512, paged=True, mean_len=96,
                          block_size=16, kv_dtype_bytes=1, **shape)
    assert i8.kv_read_bytes < paged.kv_read_bytes
    assert i8.kv_dtype_bytes == 1
    # serialization carries the paged fields for the report pipeline
    d = paged.to_dict()
    assert d["paged"] is True and d["block_size"] == 16


def test_generation_tp_decode_comm_closed_form():
    """PR-18 gate: `decode_step_cost(tp=...)` prices one chip of the
    tensor-parallel decode.  The per-step wire bytes are the Megatron
    two-all-reduces-per-layer closed form
    ``2 * L * ringfactor(tp) * slots * h * dtype`` — at tp=2 the ring
    factor ``2(N-1)/N`` is exactly 1, so ``comm_bytes`` must equal
    ``2*L*slots*h*dtype`` to the byte (the same number
    `TPGenerationEngine.decode_hlo_comm_check` pins against compiled
    HLO in tests/test_tp_serving.py)."""
    from paddle_tpu.analysis.perf import ChipSpec, decode_step_cost

    chip = ChipSpec("pinned", 197e12, 819e9, ici_bw=4.5e10)
    shape = dict(num_layers=4, hidden_size=256, num_heads=4,
                 vocab_size=8000, intermediate_size=1024, slots=8,
                 cache_len=512, chip=chip)
    base = decode_step_cost(**shape)
    assert base.tp == 1 and base.comm_bytes == 0

    tp2 = decode_step_cost(tp=2, **shape)
    assert tp2.comm_bytes == 2 * 4 * 8 * 256 * 4       # 2·L·slots·h·4
    # tp=4 pays the 2(N-1)/N = 1.5 ring factor on the same payload
    tp4 = decode_step_cost(tp=4, **shape)
    assert tp4.comm_bytes == 1.5 * tp2.comm_bytes
    # sharding divides the per-chip KV read and layer weights...
    assert tp2.kv_read_bytes * 2 == base.kv_read_bytes
    assert tp2.bytes < base.bytes
    # ...but never the replicated embedding/LM-head read
    assert tp2.bytes > base.bytes / 2
    # validation and serialization
    with pytest.raises(ValueError):
        decode_step_cost(tp=3, **shape)                # 4 heads % 3
    d = tp2.to_dict()
    assert d["tp"] == 2 and d["comm_bytes"] == tp2.comm_bytes
    # an ICI-starved chip must flip the binding term to "ici"
    starved = decode_step_cost(
        tp=2, **{**shape, "chip": ChipSpec("starved", 197e12, 819e9,
                                           ici_bw=1e3)})
    assert starved.bound == "ici"
    assert starved.time_s >= tp2.time_s


def test_serving_observability_layer_within_step_budget():
    """PR-19 gate: what the observability layer adds to the serving hot
    path — one disabled-tracer check per emitted token and one
    `SLOEngine.record` per finished request (both O(1): an attribute
    read, a locked deque append) — must cost under 2%% of a measured
    bare decode step, generously assuming EVERY slot both emits a token
    AND completes a request in the same step.  Percentiles, burn rates
    and alert edges run in `evaluate()`, which only the /slo scrape and
    the cron probe call — never the decode loop."""
    import time

    import numpy as np

    import paddle_tpu
    from paddle_tpu.fluid import dygraph
    from paddle_tpu.observability import trace as T
    from paddle_tpu.observability.metrics import MetricsRegistry
    from paddle_tpu.observability.slo import SLOEngine

    gen = paddle_tpu.generation
    T.disable_tracing()
    try:
        with dygraph.guard():
            np.random.seed(0)
            lm = models.TransformerLM(models.TransformerLMConfig.tiny())
        slots = 4
        eng = gen.GenerationEngine(lm, slots=slots, max_len=64,
                                   prefill_buckets=[8], max_queue=16)
        for i in range(slots):
            eng.submit(gen.GenerationRequest([1 + i, 2, 3],
                                             max_new_tokens=48))
        for _ in range(8):          # warm prefill bucket + decode step
            eng.step()
        n_steps = 24                # 8 + 24 < 48: slots stay occupied
        t0 = time.perf_counter()
        for _ in range(n_steps):
            eng.step()
        step_s = (time.perf_counter() - t0) / n_steps
        eng.run_until_idle()

        def per_call(fn, n=20000):
            t0 = time.perf_counter()
            for _ in range(n):
                fn()
            return (time.perf_counter() - t0) / n

        slo = SLOEngine(registry=MetricsRegistry(), window=512)
        sample = {"request_id": "r0", "trace_id": "t0", "t_wall": 1.0,
                  "outcome": "ok", "ttft_ms": 50.0, "itl_ms": 5.0,
                  "n_tokens": 8, "duration_ms": 90.0}
        cost_record = per_call(lambda: slo.record(sample))

        tr = T.default_tracer()
        assert not tr.enabled

        def token_guard():              # the engine's per-token check
            if tr.enabled:
                tr.async_instant("token", "t0", cat="generation")
        cost_guard = per_call(token_guard)

        budget = 0.02 * step_s
        per_step = slots * (cost_guard + cost_record)
        assert per_step < budget, (
            "observability hot path costs %.3fus/step against a %.3fus "
            "budget (2%% of a %.3fms bare step)"
            % (per_step * 1e6, budget * 1e6, step_s * 1e3))
        # binds-check: the same predicate must FAIL for a cost that is
        # obviously not O(1) bookkeeping (1ms per slot per step)
        assert slots * 1e-3 > budget
    finally:
        T.disable_tracing()


def test_disagg_decode_worker_never_prefills():
    """PR-18 role-separation gate: in a `tp_serving.DisaggPair`, the
    decode worker adopts prefilled KV (`inject_prefilled`) and decodes
    — its prefill buckets stay at jit-cache size 0 for the life of the
    process, and the prefill worker symmetrically never traces the
    decode step.  This is the executable-set pin the DistServe split
    exists to buy: phase isolation you can assert, not just hope for."""
    import numpy as np

    import paddle_tpu
    from paddle_tpu.fluid import dygraph

    gen = paddle_tpu.generation
    tps = paddle_tpu.tp_serving
    cfg = models.TransformerLMConfig.tiny()
    with dygraph.guard():
        np.random.seed(0)
        lm = models.TransformerLM(cfg)
    kw = dict(max_len=64, prefill_buckets=[8], max_queue=32,
              block_size=16, kv_blocks=10)
    pair = tps.DisaggPair(gen.GenerationEngine(lm, slots=2, **kw),
                          gen.GenerationEngine(lm, slots=2, **kw))
    handles = [pair.submit(gen.GenerationRequest(
        [1 + i, 2, 3], max_new_tokens=3)) for i in range(3)]
    pair.run_until_idle()
    for h in handles:
        assert len(h.result(timeout=30.0)) == 3
    dex = pair.decode.stats()["executables"]
    assert dex["decode_step"] == 1
    assert all(v == 0 for v in dex["prefill"].values()), (
        "decode worker traced a prefill bucket: %r" % dex)
    pex = pair.prefill.stats()["executables"]
    assert pex["decode_step"] == 0, (
        "prefill worker traced the decode step: %r" % pex)
    assert pex["prefill"][8] == 1


def test_lock_wrapper_overhead_within_step_budget():
    """Concurrency-sanitizer gate: every hot-path lock in the fleet is a
    named `observability.locks` wrapper, so the DISABLED-mode cost (one
    registry-hot check + the raw acquire) is paid on every acquisition
    all the time.  Pin: the overhead a generous 16 wrapped
    acquire/release pairs per decode step add over bare threading.Locks
    must stay under 2%% of a measured bare decode step.
    Uses the bench's own `measure()` so the gate and the published
    number can never drift apart."""
    import sys as _sys
    import time

    import numpy as np

    import paddle_tpu
    from paddle_tpu.fluid import dygraph

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if os.path.join(repo, "benchmarks") not in _sys.path:
        _sys.path.insert(0, os.path.join(repo, "benchmarks"))
    import concurrency_bench

    gen = paddle_tpu.generation
    with dygraph.guard():
        np.random.seed(0)
        lm = models.TransformerLM(models.TransformerLMConfig.tiny())
    slots = 4
    eng = gen.GenerationEngine(lm, slots=slots, max_len=64,
                               prefill_buckets=[8], max_queue=16)
    for i in range(slots):
        eng.submit(gen.GenerationRequest([1 + i, 2, 3],
                                         max_new_tokens=48))
    for _ in range(8):              # warm prefill bucket + decode step
        eng.step()
    n_steps = 24                    # 8 + 24 < 48: slots stay occupied
    t0 = time.perf_counter()
    for _ in range(n_steps):
        eng.step()
    step_s = (time.perf_counter() - t0) / n_steps
    eng.run_until_idle()

    # overhead = wrapped minus raw, measured back-to-back so suite-load
    # contention (which hits a pure-Python spin far harder than the XLA
    # step) cancels as common mode; min over attempts pins the
    # intrinsic cost — noise only ever inflates a spin measurement
    m = min((concurrency_bench.measure(pairs=50_000) for _ in range(3)),
            key=lambda r: r["overhead_s"])
    budget = 0.02 * step_s
    per_step = concurrency_bench.LOCKS_PER_STEP * m["overhead_s"]
    assert per_step < budget, (
        "disabled lock wrappers add %.3fus/step (%d pairs at +%.0fns "
        "each over a bare threading.Lock) against a %.3fus budget "
        "(2%% of a %.3fms bare step)"
        % (per_step * 1e6, concurrency_bench.LOCKS_PER_STEP,
           m["overhead_s"] * 1e9, budget * 1e6, step_s * 1e3))
    # binds-check: a lock that cost 50us per pair (a syscall, a log
    # write) would blow the same budget
    assert concurrency_bench.LOCKS_PER_STEP * 50e-6 > budget


def test_concurrency_lint_strict_gate():
    """Tier-1 gate: the static thread-safety lint over the shipped
    paddle_tpu/ tree is clean under --strict — zero errors, zero
    non-waived warnings.  Any new nested-lock order or blocking call
    under a lock must either follow the declared hierarchy or carry an
    explicit `# concurrency-ok[...]` waiver with a reason."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "concurrency_lint_gate",
        os.path.join(repo, "tools", "concurrency_lint.py"))
    cli = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cli)
    assert cli.main(["--strict"]) == 0
