"""Host-offloaded sharded embedding (massive-sparse capability,
reference fleet_wrapper.h:59-137 + downpour_worker.cc): table in host
RAM, only touched rows on device, host-side optimizer, update parity
with the in-HBM dense path."""

import numpy as np

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.host_embedding import HostEmbeddingSession, _bucket

V, D, T, B = 200_000, 16, 6, 8  # 200k-row table; batches touch <= 48 rows


def _build_host(seed=3):
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = seed
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[-1, T], dtype="int64",
                          append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], append_batch_size=False)
        emb = layers.embedding(ids, size=[V, D], is_distributed=True,
                               param_attr="big_table")
        pooled = layers.reduce_mean(emb, dim=1)
        pred = layers.fc(pooled, size=1, param_attr="he_fc.w",
                         bias_attr="he_fc.b")
        loss = layers.reduce_mean(layers.square(pred - y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.1).minimize(loss)
    return main, startup, loss


def _data(steps=10, seed=11, vocab=V):
    rng = np.random.RandomState(seed)
    # ids drawn from a small active set (realistic sparse access) spread
    # over the huge id space
    active = rng.randint(0, vocab, size=64)
    ids = active[rng.randint(0, 64, size=(steps, B, T))]
    w = rng.randn(64)
    lut = dict(zip(active, w))
    ys = np.stack([
        np.vectorize(lut.get)(ids[s]).mean(axis=1, keepdims=True)
        for s in range(steps)
    ]).astype(np.float32)
    return ids.astype(np.int64), ys


def test_host_embedding_trains_and_touches_only_pulled_rows():
    main, startup, loss = _build_host()
    table, ids_slot = main._host_embeddings["big_table"]
    assert ids_slot == "ids"
    table.optimizer = "sgd"  # match the graph's SGD for clean parity

    ids, ys = _data()
    exe = fluid.Executor(fluid.CPUPlace())
    scope = fluid.Scope()
    with fluid.scope_guard(scope):
        exe.run(startup)
        sess = HostEmbeddingSession(exe, main, loss=loss)
        losses = []
        for _epoch in range(8):
            for t in range(len(ids)):
                (lv,) = sess.run({"ids": ids[t], "y": ys[t]},
                                 fetch_list=[loss], lr=0.5)
                losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])

    # the device-side pulled buffer stays tiny vs the 200k-row table
    pulled, local, uniq = table.pull(ids[0])
    assert pulled.shape[0] == _bucket(len(uniq)) <= 64
    assert local.max() < len(uniq)
    # untouched rows never moved
    untouched = (np.arange(V)[~np.isin(np.arange(V), np.unique(ids))])[:5]
    base = table._rows[untouched // table.nproc]
    assert np.all(np.abs(base) < 0.1)  # still at init scale


def test_host_embedding_matches_dense_updates():
    """One step of host-SGD on touched rows == the dense in-HBM update."""
    vocab = 50
    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 7
    with fluid.program_guard(main, startup):
        ids = layers.data("ids", shape=[-1, 4], dtype="int64",
                          append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], append_batch_size=False)
        emb = layers.embedding(ids, size=[vocab, 8], is_distributed=True,
                               param_attr="small_table")
        pred = layers.fc(layers.reduce_mean(emb, dim=1), size=1,
                         param_attr="de_fc.w", bias_attr="de_fc.b")
        loss = layers.reduce_mean(layers.square(pred - y))
        fluid.optimizer.SGDOptimizer(learning_rate=0.2).minimize(loss)
    table, _ = main._host_embeddings["small_table"]
    table.optimizer = "sgd"

    # dense twin with IDENTICAL init (copy host table in)
    import paddle_tpu.fluid.framework as fw

    fw.reset_default_programs()
    dmain, dstartup = fluid.Program(), fluid.Program()
    dmain.random_seed = dstartup.random_seed = 7
    with fluid.program_guard(dmain, dstartup):
        ids_d = layers.data("ids", shape=[-1, 4], dtype="int64",
                            append_batch_size=False)
        y_d = layers.data("y", shape=[-1, 1], append_batch_size=False)
        emb_d = layers.embedding(ids_d, size=[vocab, 8],
                                 param_attr="dense_table")
        pred_d = layers.fc(layers.reduce_mean(emb_d, dim=1), size=1,
                           param_attr="de_fc.w", bias_attr="de_fc.b")
        loss_d = layers.reduce_mean(layers.square(pred_d - y_d))
        fluid.optimizer.SGDOptimizer(learning_rate=0.2).minimize(loss_d)

    rng = np.random.RandomState(0)
    idv = rng.randint(0, vocab, (6, 4)).astype(np.int64)
    yv = rng.randn(6, 1).astype(np.float32)

    exe = fluid.Executor(fluid.CPUPlace())
    s1, s2 = fluid.Scope(), fluid.Scope()
    with fluid.scope_guard(s1):
        exe.run(startup)
        sess = HostEmbeddingSession(exe, main, loss=loss)
    with fluid.scope_guard(s2):
        exe.run(dstartup)
        import jax.numpy as jnp

        # EXPLICIT copy: jnp.asarray may zero-copy-alias the numpy
        # buffer on CPU (alignment-dependent), and the host session's
        # push mutates table._rows in place — aliasing made the "dense
        # twin" see one host-SGD step early (the rare full-suite flake)
        init_rows = table._rows.copy()
        s2.set("dense_table", jnp.asarray(init_rows))
        # identical fc init: deep-copy from the host-program scope (the
        # session donates s1's buffers, so sharing objects would alias a
        # to-be-deleted array)
        for n in ("de_fc.w", "de_fc.b"):
            s2.set(n, jnp.asarray(np.asarray(s1.find_var(n)).copy()))

    with fluid.scope_guard(s1):
        (l_host,) = sess.run({"ids": idv, "y": yv}, fetch_list=[loss],
                             lr=0.2)
    with fluid.scope_guard(s2):
        # guard against buffer aliasing regressions: the dense table
        # must still hold the PRE-update snapshot after the host step
        np.testing.assert_allclose(
            np.asarray(s2.find_var("dense_table")), init_rows,
            err_msg="dense_table aliased the live host table")
        (l_dense,) = exe.run(dmain, feed={"ids": idv, "y": yv},
                             fetch_list=[loss_d])
        new_dense = np.asarray(s2.find_var("dense_table"))

    np.testing.assert_allclose(float(l_host), float(l_dense), rtol=1e-5)
    np.testing.assert_allclose(table._rows, new_dense, rtol=1e-4,
                               atol=1e-6)


def test_host_embedding_save_load(tmp_path):
    main, startup, loss = _build_host(seed=9)
    table, _ = main._host_embeddings["big_table"]
    table._rows[:5] = 1.25
    p = str(tmp_path / "table")
    table.save(p)
    table._rows[:5] = 0
    table.load(p)
    assert np.all(table._rows[:5] == np.float32(1.25))


def test_push_validates_id_range_like_pull():
    """Out-of-range ids must raise on BOTH verbs — push used to index
    the shard arrays unchecked (negative ids aliased via python
    wraparound, overflow ids crashed deep in numpy)."""
    import pytest

    from paddle_tpu.fluid.host_embedding import HostEmbedding

    t = HostEmbedding("rng_t", 100, 4, optimizer="sgd")
    g = np.ones((1, 4), np.float32)
    with pytest.raises(IndexError, match="push of rng_t"):
        t.push(np.asarray([100]), g)
    with pytest.raises(IndexError, match="push of rng_t"):
        t.push(np.asarray([-1]), g)
    with pytest.raises(IndexError, match="pull of rng_t"):
        t.pull(np.asarray([250]))
    t.push(np.asarray([99]), g)  # boundary id is fine


def test_save_load_npz_suffix_consistent(tmp_path):
    """np.savez silently appends .npz; save and load must agree on the
    real filename whether or not the caller wrote the extension."""
    from paddle_tpu.fluid.host_embedding import HostEmbedding, _npz_path

    assert _npz_path("x") == "x.npz" and _npz_path("x.npz") == "x.npz"
    t = HostEmbedding("sfx_t", 50, 4, optimizer="sgd")
    t._rows[:3] = 2.5
    t.save(str(tmp_path / "bare"))          # writes bare.npz
    t.save(str(tmp_path / "ext.npz"))       # writes ext.npz, not .npz.npz
    import os

    assert sorted(os.listdir(tmp_path)) == ["bare.npz", "ext.npz"]
    for name in ("bare", "bare.npz", "ext", "ext.npz"):
        t2 = HostEmbedding("sfx_t2", 50, 4, optimizer="sgd")
        t2.load(str(tmp_path / name))
        assert np.all(t2._rows[:3] == np.float32(2.5))


def test_save_delta_apply_delta_roundtrip(tmp_path):
    """save_delta persists only touched rows; apply_delta replays them
    into a fresh table (the streaming delta-checkpoint payload)."""
    from paddle_tpu.fluid.host_embedding import HostEmbedding

    t = HostEmbedding("dlt_t", 80, 4, seed=1)
    t.track_touched = True       # opt-in (DeltaCheckpointer's job)
    ids = np.asarray([3, 9, 41], np.int64)
    t.push(ids, np.ones((3, 4), np.float32), lr=0.5)
    n = t.save_delta(str(tmp_path / "d0"), touched=t.collect_touched())
    assert n == 3
    t2 = HostEmbedding("dlt_t2", 80, 4, seed=2)  # different init
    assert not np.array_equal(t2._rows[ids], t._rows[ids])
    assert t2.apply_delta(str(tmp_path / "d0")) == 3
    np.testing.assert_array_equal(t2._rows[ids], t._rows[ids])
    np.testing.assert_array_equal(t2._accum[ids], t._accum[ids])
