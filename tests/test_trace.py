"""Span tracer / flight recorder / XLA cost attribution / fleet
timeline (paddle_tpu.observability.trace and friends).

Covers the PR-6 acceptance drills: chrome-trace schema validity +
nesting for a served HTTP request and a 3-step hapi fit, trace-id
propagation across the serving dispatch/completion threads, the
SIGTERM flight-recorder dump, the disabled-tracing overhead budget,
straggler detection in the fleet view, and the trace_summary CLI."""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.observability import trace as T
from paddle_tpu.observability.metrics import Counter, MetricsRegistry

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)

VALID_PH = {"X", "i", "C", "b", "e", "n", "M"}


def validate_chrome_trace(obj):
    """The schema chrome://tracing and Perfetto actually require of the
    event kinds this repo emits."""
    assert isinstance(obj, dict) and isinstance(obj["traceEvents"], list)
    for ev in obj["traceEvents"]:
        assert ev["ph"] in VALID_PH, ev
        assert isinstance(ev["name"], str) and ev["name"]
        assert isinstance(ev["pid"], int)
        if ev["ph"] == "M":
            continue
        assert isinstance(ev["ts"], int) and ev["ts"] >= 0, ev
        assert isinstance(ev["tid"], int)
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], int) and ev["dur"] >= 0, ev
        if ev["ph"] == "i":
            assert ev.get("s") in ("t", "p", "g"), ev
        if ev["ph"] in ("b", "e", "n"):
            assert isinstance(ev["id"], str) and ev["id"], ev
        if ev["ph"] == "C":
            assert all(isinstance(v, (int, float))
                       for v in ev["args"].values()), ev
    return obj


def spans(events, name=None, cat=None):
    return [e for e in events if e.get("ph") == "X"
            and (name is None or e["name"] == name)
            and (cat is None or e.get("cat") == cat)]


def _contains(outer, inner):
    """inner's interval nests inside outer's, on the same track."""
    return (outer["pid"] == inner["pid"] and outer["tid"] == inner["tid"]
            and outer["ts"] <= inner["ts"]
            and inner["ts"] + inner.get("dur", 0)
            <= outer["ts"] + outer["dur"])


@pytest.fixture
def tracer():
    tr = T.enable_tracing()
    tr.clear()
    yield tr
    T.disable_tracing()
    T.default_tracer().clear()


# ---------------------------------------------------------------------------
# tracer primitives + golden schema
# ---------------------------------------------------------------------------


def test_abandoned_span_emits_nothing(tracer):
    """abandon() inside a with-block must suppress the event — a
    cancelled operation leaves no phantom span in the timeline."""
    with tracer.span("kept"):
        pass
    with tracer.span("doomed") as s:
        s.abandon()
    names = [e["name"] for e in tracer.events() if e.get("ph") == "X"]
    assert "kept" in names and "doomed" not in names


def test_span_nesting_schema_and_roundtrip(tracer, tmp_path):
    with T.span("outer", cat="app", args={"k": 1}):
        time.sleep(0.002)
        with T.span("inner"):
            time.sleep(0.001)
        T.instant("mark", args={"x": 2})
    T.counter_event("depth", {"q": 3})
    ct = validate_chrome_trace(tracer.chrome_trace())
    (outer,) = spans(ct["traceEvents"], "outer")
    (inner,) = spans(ct["traceEvents"], "inner")
    assert _contains(outer, inner)
    assert outer["dur"] >= inner["dur"] > 0
    assert outer["args"]["k"] == 1
    # instants/counters landed with the right phase
    phs = {e["ph"] for e in ct["traceEvents"]}
    assert {"X", "i", "C"} <= phs
    # save/load roundtrip, plain and gzipped, both loadable
    for fname in ("t.json", "t.json.gz"):
        p = tracer.save(str(tmp_path / fname))
        evs, md = T.load_trace(p)
        assert len(evs) == len(ct["traceEvents"])
        assert md["clock"] == "perf_counter" and "anchor_unix_time" in md


def test_span_error_annotated_and_stack_unwound(tracer):
    with pytest.raises(ValueError):
        with T.span("dying"):
            raise ValueError("boom")
    (ev,) = spans(tracer.events(), "dying")
    assert ev["args"]["error"] == "ValueError"
    assert T.current_trace_id() is None     # stack fully unwound


def test_trace_id_inheritance_and_context(tracer):
    tid = T.new_trace_id()
    assert tid != T.new_trace_id()          # process-unique
    with T.trace_context(tid):
        assert T.current_trace_id() == tid
        with T.span("child"):
            pass                            # inherits the context id
    assert T.current_trace_id() is None
    (ev,) = spans(tracer.events(), "child")
    assert ev["args"]["trace_id"] == tid


def test_ring_is_bounded():
    tr = T.Tracer(capacity=32, enabled=True)
    for i in range(100):
        tr.instant("e%d" % i)
    evs = [e for e in tr.events() if e["ph"] == "i"]
    assert len(evs) == 32
    assert evs[-1]["name"] == "e99"         # newest survive


def test_merge_traces_aligns_ranks_on_wall_clock():
    shards = []
    for rank, skew in ((0, 0.0), (1, 5.0)):
        tr = T.Tracer(capacity=64, enabled=True)
        # fake a shard whose monotonic clock started `skew` seconds
        # earlier relative to wall time
        tr.anchor = (1000.0, skew)
        with tr.span("step"):
            pass
        shards.append((rank, tr.events(),
                       {"anchor_unix_time": tr.anchor[0],
                        "anchor_clock": tr.anchor[1]}))
    merged = validate_chrome_trace(T.merge_traces(shards))
    by_pid = {e["pid"]: e for e in spans(merged["traceEvents"], "step")}
    assert set(by_pid) == {0, 1}
    # rank 1's events happened 5s earlier on the common wall clock
    assert by_pid[0]["ts"] - by_pid[1]["ts"] == pytest.approx(5e6, rel=0.01)


# ---------------------------------------------------------------------------
# serving: per-request trace across the dispatch/completion threads
# ---------------------------------------------------------------------------


def _fc_server(tmp_path, **kw):
    from paddle_tpu.inference import AnalysisConfig, create_predictor
    from paddle_tpu.inference.server import InferenceServer

    main, startup = fluid.Program(), fluid.Program()
    main.random_seed = startup.random_seed = 3
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 8], append_batch_size=False)
        pred = layers.fc(layers.fc(x, 16, act="relu"), 2)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    path = str(tmp_path / "fc.model")
    fluid.io.save_inference_model(path, ["x"], [pred], exe, main)
    predictor = create_predictor(AnalysisConfig(path))
    return InferenceServer(predictor, batch_timeout_ms=1, **kw)


def test_served_request_trace_end_to_end(tracer, tmp_path):
    """Acceptance drill: one served request produces a loadable trace
    whose async timeline walks queue -> pad+dispatch -> xla_compute ->
    slice under the request's trace id, with phases recorded from more
    than one thread."""
    server = _fc_server(tmp_path).start()
    try:
        outs, trace_id = server.infer_with_trace(
            {"x": np.ones((2, 8), np.float32)})
        assert outs[0].shape == (2, 2)
        assert trace_id.startswith("req-")
    finally:
        server.stop()
    p = tracer.save(str(tmp_path / "serving.trace.json"))
    evs, _md = T.load_trace(p)
    validate_chrome_trace({"traceEvents": evs})
    mine = [e for e in evs if e.get("id") == trace_id]
    assert mine, "no async events for the returned trace id"
    begins = [e["name"] for e in mine if e["ph"] == "b"]
    ends = [e["name"] for e in mine if e["ph"] == "e"]
    for phase in ("request", "queue", "pad+dispatch", "xla_compute",
                  "slice"):
        assert phase in begins and phase in ends, phase
    # phase order: each phase begins at/after the previous one's begin
    order = [e for e in mine if e["ph"] == "b" and e["name"] != "request"]
    assert [e["name"] for e in sorted(order, key=lambda e: e["ts"])] == \
        ["queue", "pad+dispatch", "xla_compute", "slice"]
    # the batch-side spans crossed the dispatcher/completion threads and
    # carry the trace id for the join
    batch_spans = spans(evs, cat="serving")
    carrying = [e for e in batch_spans
                if trace_id in (e.get("args", {}).get("trace_ids") or ())]
    assert {e["name"] for e in carrying} >= {"batch.pad", "batch.dispatch"}
    threads = {e["tid"] for e in batch_spans} | {e["tid"] for e in mine}
    assert len(threads) >= 2, "trace did not cross threads"


def test_http_response_carries_trace_id_and_trace_endpoint(tracer,
                                                           tmp_path):
    import urllib.request

    server = _fc_server(tmp_path).start()
    httpd = server.serve_http(port=0, block=False)
    try:
        base = "http://127.0.0.1:%d" % httpd.server_address[1]
        req = urllib.request.Request(
            base + "/predict",
            data=json.dumps(
                {"inputs": {"x": [[0.5] * 8] * 3}}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:
            out = json.loads(resp.read())
        assert len(out["outputs"][0]) == 3
        trace_id = out["trace_id"]
        assert trace_id.startswith("req-")
        # /stats names the recent request so a slow p99 is findable
        with urllib.request.urlopen(base + "/stats", timeout=10) as resp:
            stats = json.loads(resp.read())
        assert stats["tracing_enabled"] is True
        assert trace_id in [r["trace_id"] for r in stats["recent_requests"]]
        assert stats["slowest_recent"][0]["latency_ms"] > 0
        # GET /trace returns the loadable chrome trace with the request
        with urllib.request.urlopen(base + "/trace", timeout=10) as resp:
            ct = json.loads(resp.read())
        validate_chrome_trace(ct)
        assert any(e.get("id") == trace_id for e in ct["traceEvents"])
    finally:
        httpd.shutdown()
        server.stop()


def test_http_trace_endpoint_409_when_disabled(tmp_path):
    import urllib.error
    import urllib.request

    T.disable_tracing()
    server = _fc_server(tmp_path).start()
    httpd = server.serve_http(port=0, block=False)
    try:
        base = "http://127.0.0.1:%d" % httpd.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/trace", timeout=10)
        assert ei.value.code == 409
        # trace ids are still allocated for correlation while disabled
        outs, trace_id = server.infer_with_trace(
            {"x": np.ones((1, 8), np.float32)})
        assert trace_id.startswith("req-")
    finally:
        httpd.shutdown()
        server.stop()


def test_serving_cost_attribution_and_mfu(tracer, tmp_path, monkeypatch):
    """warmup samples cost_analysis() per executable into gauges +
    /stats, and completed batches set the measured `mfu` gauge."""
    monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "1e12")
    reg = MetricsRegistry()
    server = _fc_server(tmp_path, metrics_registry=reg,
                        batch_buckets=[1, 2]).start()
    try:
        server.warmup({"x": np.ones((1, 8), np.float32)})
        stats = server.stats()
        costs = stats["executable_costs"]
        assert costs, "warmup sampled no executable costs"
        assert all("flops" in c for c in costs.values())
        fam = reg.get("xla_executable_flops")
        assert fam is not None and fam._series()
        server.infer({"x": np.ones((2, 8), np.float32)})
        fam = reg.get("mfu")
        assert fam is not None
        series = fam._series()
        assert series and all(0 < child.value < 1
                              for _lv, child in series)
    finally:
        server.stop()


def test_warmup_survives_metrics_name_collision(tmp_path):
    """Attribution is telemetry: a registry where the cost gauge name
    already exists as an incompatible family must not crash warmup."""
    reg = MetricsRegistry()
    reg.counter("xla_executable_flops", "collides")   # wrong type
    server = _fc_server(tmp_path, metrics_registry=reg,
                        batch_buckets=[1]).start()
    try:
        server.warmup({"x": np.ones((1, 8), np.float32)})   # no raise
        # the gauges were skipped, the colliding family is untouched,
        # and the per-signature table (spans + /stats) still filled
        assert isinstance(reg.get("xla_executable_flops"), Counter)
        assert server.stats()["executable_costs"]
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# training: 3-step hapi fit trace (acceptance drill)
# ---------------------------------------------------------------------------


def _toy_model():
    import paddle_tpu.hapi as hp
    from paddle_tpu.fluid import dygraph

    class Net(dygraph.Layer):
        def __init__(self):
            super().__init__()
            self.fc = dygraph.Linear(4, 3)

        def forward(self, x):
            return self.fc(x)

    m = hp.Model(Net(), inputs=[hp.Input([None, 4], "float32", "x")],
                 labels=[hp.Input([None, 1], "int64", "y")])

    def loss_fn(pred, y):
        return layers.reduce_mean(
            layers.square(pred - layers.cast(y, "float32")))

    m.prepare(optimizer=fluid.optimizer.SGDOptimizer(0.01),
              loss_function=loss_fn)
    return m


def test_three_step_fit_trace_nests_step_budget(tracer, tmp_path):
    m = _toy_model()
    x = np.zeros((24, 4), np.float32)
    y = np.zeros((24, 1), np.int64)
    m.fit((x, y), batch_size=8, epochs=1, verbose=0, shuffle=False)
    p = tracer.save(str(tmp_path / "fit.trace.json"))
    evs, _md = T.load_trace(p)
    validate_chrome_trace({"traceEvents": evs})
    steps = spans(evs, "step", cat="train")
    assert len(steps) == 3
    waits = spans(evs, "data_wait", cat="train")
    runs = spans(evs, "executor.run", cat="executor")
    for i, st in enumerate(sorted(steps, key=lambda e: e["ts"])):
        assert st["args"]["step"] == i
        # the step span carries the StepTimer budget...
        for comp in ("data_wait", "compile", "compute", "host_overhead",
                     "step_time"):
            assert comp in st["args"], comp
        # ...and nests the data_wait + executor spans by containment
        assert any(_contains(st, w) for w in waits)
        assert any(_contains(st, r) for r in runs)
    # first (cache-miss) run attributes compile; steady state does not
    runs = sorted(runs, key=lambda e: e["ts"])
    assert runs[0]["args"]["compile_ms"] >= runs[-1]["args"]["compile_ms"]
    assert runs[-1]["args"]["compute_ms"] > 0


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


def test_flight_recorder_sigterm_drill(tmp_path):
    """Acceptance drill: SIGTERM a training subprocess mid-run; the
    process must still die by signal AND leave one loadable dump holding
    the last steps."""
    dump_dir = str(tmp_path / "flight")
    ready = str(tmp_path / "ready")
    env = dict(os.environ, FLT_DUMP_DIR=dump_dir, FLT_READY=ready,
               JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.Popen([sys.executable,
                          os.path.join(HERE, "flight_worker.py")], env=env)
    try:
        deadline = time.time() + 120
        while not os.path.exists(ready):
            assert time.time() < deadline, "worker never trained 3 steps"
            assert p.poll() is None, "worker died before the drill"
            time.sleep(0.05)
        p.send_signal(signal.SIGTERM)
        rc = p.wait(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait(timeout=30)
    assert rc == -signal.SIGTERM    # exit semantics preserved
    dumps = [f for f in os.listdir(dump_dir)
             if f.endswith(".trace.json")]
    assert len(dumps) == 1
    evs, md = T.load_trace(os.path.join(dump_dir, dumps[0]))
    validate_chrome_trace({"traceEvents": evs})
    assert md["flight_recorder"] is True
    assert "SIGTERM" in md["reason"]
    assert "metrics_snapshot" in md
    # the span ring held the lead-up: real step spans...
    step_spans = spans(evs, "step", cat="train")
    assert len(step_spans) >= 3
    # ...and the scalar ring re-emitted the per-step budgets
    budget = [e for e in evs if e["ph"] == "C"
              and e["name"] == "step_budget_ms[flight.drill]"]
    assert len(budget) >= 3
    assert all("step_time" in e["args"] for e in budget)
    # the summarizer reads the dump and names the reason
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_summary.py"),
         os.path.join(dump_dir, dumps[0]), "--json"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    summary = json.loads(r.stdout)
    assert "SIGTERM" in summary["metadata"]["reason"]
    assert any(row["name"] == "step"
               for row in summary["top_spans_by_self_time"])


def test_flight_recorder_dumps_on_first_failed_step(tmp_path):
    """A step exiting with an exception triggers ONE dump (not one per
    subsequent failure), in-process, without signal hooks."""
    from paddle_tpu.observability import StepTimer
    from paddle_tpu.observability.flight_recorder import FlightRecorder

    rec = FlightRecorder(dump_dir=str(tmp_path)).install(
        signals=(), catch_unhandled=False)
    try:
        timer = StepTimer(name="failing.loop")
        with timer.step():
            pass                     # a good step first
        for _ in range(3):           # then a dying loop
            with pytest.raises(RuntimeError):
                with timer.step():
                    raise RuntimeError("NaN guard tripped")
        dumps = [f for f in os.listdir(str(tmp_path))
                 if f.endswith(".trace.json")]
        assert len(dumps) == 1       # first failure only
        evs, md = T.load_trace(str(tmp_path / dumps[0]))
        assert "failed step" in md["reason"]
        assert "failing.loop" in md["reason"]
        # the dump contains the CRASHING step's own span (closed before
        # the failure hook fired), error-annotated
        failed = [e for e in spans(evs, "step", cat="train")
                  if e.get("args", {}).get("error") == "RuntimeError"]
        assert failed, "dump is missing the failing step's span"
    finally:
        rec.uninstall()
        T.disable_tracing()
        T.default_tracer().clear()


def test_flight_recorder_uninstall_restores_hooks(tmp_path):
    from paddle_tpu.observability.flight_recorder import FlightRecorder

    prev = signal.getsignal(signal.SIGTERM)
    rec = FlightRecorder(dump_dir=str(tmp_path)).install()
    assert signal.getsignal(signal.SIGTERM) is not prev
    rec.uninstall()
    assert signal.getsignal(signal.SIGTERM) is prev
    T.disable_tracing()
    T.default_tracer().clear()


def test_flight_recorder_install_keeps_frozen_capture(tmp_path):
    """install() arms the flight capacity only on a VIRGIN ring — a
    capture the user recorded and froze with disable_tracing() must
    survive installing the recorder afterwards."""
    from paddle_tpu.observability.flight_recorder import FlightRecorder

    T.enable_tracing()
    T.default_tracer().clear()
    with T.span("precious"):
        pass
    T.disable_tracing()
    rec = FlightRecorder(dump_dir=str(tmp_path)).install(
        signals=(), catch_unhandled=False)
    try:
        names = [e["name"] for e in T.default_tracer().events()
                 if e.get("ph") == "X"]
        assert "precious" in names
    finally:
        rec.uninstall()
        T.disable_tracing()
        T.default_tracer().clear()


def test_flight_recorder_one_dump_per_unwind(tmp_path):
    """One death can pass through several hooks — a Ctrl-C unwinds via
    signal handler, failed-step hook AND excepthook.  Only the FIRST
    automatic trigger dumps; the rest are suppressed."""
    from paddle_tpu.observability import StepTimer
    from paddle_tpu.observability.flight_recorder import FlightRecorder

    rec = FlightRecorder(dump_dir=str(tmp_path)).install(
        signals=(), catch_unhandled=False)
    rec._prev_excepthook = lambda *a: None   # silence the chain
    try:
        timer = StepTimer(name="dying.loop")
        err = RuntimeError("boom")
        with pytest.raises(RuntimeError):
            with timer.step():
                raise err
        # the same exception then reaches the excepthook chain
        rec._on_unhandled(RuntimeError, err, None)
        dumps = [f for f in os.listdir(str(tmp_path))
                 if f.endswith(".trace.json")]
        assert len(dumps) == 1
        _evs, md = T.load_trace(str(tmp_path / dumps[0]))
        assert "failed step" in md["reason"]     # first trigger won
        # an EXPLICIT dump() is never guarded
        p = rec.dump(reason="manual post-mortem")
        assert p is not None and os.path.exists(p)
    finally:
        rec.uninstall()
        T.disable_tracing()
        T.default_tracer().clear()


# ---------------------------------------------------------------------------
# overhead budget
# ---------------------------------------------------------------------------


def test_disabled_tracing_is_shared_noop_and_within_budget():
    """Disabled tracing must cost ~nothing on the step path: span()
    returns one shared null object (no allocation), and the per-step
    instrumentation cost — ~4 span/complete calls — stays far inside
    the repo's <2% telemetry budget against a real (small) train step."""
    T.disable_tracing()
    tr = T.default_tracer()
    assert tr.span("a") is tr.span("b")          # shared no-op object

    # a real step to budget against: the telemetry-bench fc program
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 64], append_batch_size=False)
        y = layers.data("y", shape=[-1, 1], append_batch_size=False)
        h = layers.fc(layers.fc(x, 128, act="relu"), 128, act="relu")
        loss = layers.reduce_mean(layers.square(layers.fc(h, 1) - y))
        fluid.optimizer.SGDOptimizer(0.01).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(64, 64).astype(np.float32),
            "y": rng.randn(64, 1).astype(np.float32)}
    for _ in range(3):                            # compile + warm
        exe.run(main, feed=feed, fetch_list=[loss])
    t0 = time.perf_counter()
    n_steps = 30
    for _ in range(n_steps):
        exe.run(main, feed=feed, fetch_list=[loss])
    step_s = (time.perf_counter() - t0) / n_steps

    def per_call(fn, n=20000):
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return (time.perf_counter() - t0) / n

    def disabled_span():
        with tr.span("s", cat="train", args=None):
            pass

    cost_disabled = per_call(disabled_span)
    T.enable_tracing()
    try:
        tr = T.default_tracer()

        def enabled_span():
            with tr.span("s", cat="train", args={"step": 1}):
                pass

        cost_enabled = per_call(enabled_span)
    finally:
        T.disable_tracing()
        T.default_tracer().clear()
    spans_per_step = 4     # step + data_wait + executor.run + slack
    budget = 0.02 * step_s
    assert spans_per_step * cost_disabled < 0.1 * budget, (
        "disabled tracing costs %.1f%% of a %.2fms step"
        % (100 * spans_per_step * cost_disabled / step_s, step_s * 1e3))
    assert spans_per_step * cost_enabled < budget, (
        "enabled tracing costs %.1f%% of a %.2fms step"
        % (100 * spans_per_step * cost_enabled / step_s, step_s * 1e3))


# ---------------------------------------------------------------------------
# fleet: straggler detection + merged timeline
# ---------------------------------------------------------------------------


def _publish_fleet(ws, step_ms_by_rank):
    from paddle_tpu.distributed.monitor import MetricsAggregator

    aggs = {}
    for rank, ms in step_ms_by_rank.items():
        reg = MetricsRegistry()
        h = reg.histogram("train_step_ms", "t",
                          labelnames=("loop",)).labels("fit")
        for _ in range(4):
            h.observe(ms)
        aggs[rank] = MetricsAggregator(
            ws, rank, len(step_ms_by_rank), registry=reg)
        aggs[rank].publish()
    return aggs


def test_straggler_detection_flags_and_recovers(tmp_path):
    ws = str(tmp_path)
    aggs = _publish_fleet(ws, {0: 100.0, 1: 105.0, 2: 98.0, 3: 320.0})
    reader_reg = MetricsRegistry()
    from paddle_tpu.distributed.monitor import MetricsAggregator

    reader = MetricsAggregator(ws, 0, 4, registry=reader_reg)
    strag = reader.fleet_snapshot()["stragglers"]
    assert strag["ranks"] == [3]
    assert strag["ratios"]["3"] == pytest.approx(320 / 102.5, rel=0.05)
    fam = reader_reg.get("straggler_ranks")
    assert [lv for lv, _c in fam._series()] == [("3",)]
    # rank 3 recovers -> flag and gauge series clear
    reg3 = MetricsRegistry()
    h = reg3.histogram("train_step_ms", "t",
                       labelnames=("loop",)).labels("fit")
    for _ in range(4):
        h.observe(101.0)
    MetricsAggregator(ws, 3, 4, registry=reg3).publish()
    strag = reader.fleet_snapshot()["stragglers"]
    assert strag["ranks"] == [] and not strag["ratios"]
    assert fam._series() == []
    # publisher restart whose count OVERTAKES the old one within a poll
    # window: the sum went backwards, so this must re-baseline, not
    # difference two processes' sums into a negative mean
    reg3b = MetricsRegistry()
    h = reg3b.histogram("train_step_ms", "t",
                        labelnames=("loop",)).labels("fit")
    for _ in range(6):                       # count 6 > previous 4
        h.observe(50.0)                      # sum 300 < previous 404
    MetricsAggregator(ws, 3, 4, registry=reg3b).publish()
    strag = reader.fleet_snapshot()["stragglers"]
    assert strag["median_step_ms"] > 0
    assert strag["ranks"] == []
    # a single-rank fleet never self-flags
    solo = MetricsAggregator(str(tmp_path / "solo"), 0, 1,
                             registry=reg3)
    solo.publish()
    assert solo.fleet_snapshot()["stragglers"]["ranks"] == []


def test_straggler_detection_two_rank_fleet(tmp_path):
    """Leave-one-out baseline: on a 2-rank fleet each rank is compared
    against the other.  With the candidate's own mean inside the
    median, the ratio 2m/(m+fast) could never reach the default 2.0
    factor no matter how slow the straggler got."""
    from paddle_tpu.distributed.monitor import MetricsAggregator

    ws = str(tmp_path)
    _publish_fleet(ws, {0: 100.0, 1: 1000.0})
    reader = MetricsAggregator(ws, 0, 2, registry=MetricsRegistry())
    strag = reader.fleet_snapshot()["stragglers"]
    assert strag["ranks"] == [1]
    assert strag["ratios"]["1"] == pytest.approx(10.0, rel=0.01)


def test_straggler_detection_windows_recent_steps(tmp_path):
    """Detection diffs (count, sum) between snapshots: a rank that
    degrades AFTER a long healthy run is flagged at the next look, even
    while its lifetime mean is still far under the threshold."""
    from paddle_tpu.distributed.monitor import MetricsAggregator

    ws = str(tmp_path)
    hists, aggs = {}, {}
    for rank in range(3):
        reg = MetricsRegistry()
        h = reg.histogram("train_step_ms", "t",
                          labelnames=("loop",)).labels("fit")
        for _ in range(100):
            h.observe(100.0)
        hists[rank] = h
        aggs[rank] = MetricsAggregator(ws, rank, 3, registry=reg)
        aggs[rank].publish()
    reader = MetricsAggregator(ws, 0, 3, registry=MetricsRegistry())
    assert reader.fleet_snapshot()["stragglers"]["ranks"] == []
    # rank 2 hits a failing interconnect: 10 slow steps on top of 100
    # fast ones.  Lifetime mean ~127ms (ratio ~1.3, under the 2.0
    # factor) — only the windowed mean (400ms, ratio 4) catches it.
    for _ in range(10):
        hists[2].observe(400.0)
    for rank in range(3):
        if rank != 2:
            hists[rank].observe(100.0)
        aggs[rank].publish()
    strag = reader.fleet_snapshot()["stragglers"]
    assert strag["ranks"] == [2]
    assert strag["ratios"]["2"] == pytest.approx(4.0, rel=0.05)


def test_fleet_trace_merge_ranks_to_pids(tmp_path):
    ws = str(tmp_path)
    aggs = _publish_fleet(ws, {0: 100.0, 1: 100.0, 2: 300.0})
    for rank, agg in aggs.items():
        tr = T.Tracer(capacity=64, enabled=True)
        with tr.span("step", cat="train", args={"rank": rank}):
            pass
        shard = agg.publish_trace(tracer=tr)
        assert os.path.exists(shard)
    merged = aggs[0].merge_fleet_trace(
        out_path=str(tmp_path / "fleet.trace.json"))
    validate_chrome_trace(merged)
    step_pids = {e["pid"] for e in spans(merged["traceEvents"], "step")}
    assert step_pids == {0, 1, 2}           # rank -> Perfetto pid
    names = {(e["pid"], e["args"]["name"])
             for e in merged["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert names >= {(0, "rank 0"), (1, "rank 1"), (2, "rank 2")}
    # the straggler instant is stamped on the slow rank's track
    instants = [e for e in merged["traceEvents"]
                if e["ph"] == "i" and e["name"] == "straggler"]
    assert [e["pid"] for e in instants] == [2]
    assert merged["metadata"]["stragglers"]["ranks"] == [2]
    # the merged file loads like any other trace
    evs, md = T.load_trace(str(tmp_path / "fleet.trace.json"))
    assert md["stragglers"]["ranks"] == [2] and len(evs) > 0


def test_merge_traces_skips_alignment_with_unanchored_shard():
    """A shard without the wall/mono anchor pair (e.g. a bare-array
    trace) disables alignment for the whole merge: shifting only the
    anchored shards would strand them a wall-clock epoch (~54 years)
    away from the unanchored ones."""
    tr = T.Tracer(capacity=64, enabled=True)
    with tr.span("a"):
        pass
    anchored = tr.chrome_trace()
    orig_ts = sorted(e["ts"] for e in anchored["traceEvents"]
                     if "ts" in e)
    bare = [{"ph": "X", "name": "b", "ts": 10, "dur": 5,
             "pid": 99, "tid": 0}]
    merged = T.merge_traces([
        (0, bare, {}),
        (1, anchored["traceEvents"], anchored["metadata"]),
    ])
    new_ts = sorted(e["ts"] for e in merged["traceEvents"]
                    if e["pid"] == 1 and "ts" in e)
    assert new_ts == orig_ts        # nobody was shifted


def test_enable_tracing_resize_keeps_tracer_identity():
    """enable_tracing(capacity=) resizes the ring IN PLACE: loops that
    fetched default_tracer() once (fit, TrainEpochRange) must keep
    reporting to the live ring after a flight-recorder install or a
    user resize mid-run."""
    tr0 = T.default_tracer()
    try:
        tr = T.enable_tracing(capacity=128)
        assert tr is tr0 and tr0._events.maxlen == 128
        with T.span("after-resize"):
            pass
        assert any(e["name"] == "after-resize" for e in tr0.events())
    finally:
        T.disable_tracing()
        T.enable_tracing(capacity=65536)
        T.disable_tracing()
        T.default_tracer().clear()


# ---------------------------------------------------------------------------
# trace_summary CLI
# ---------------------------------------------------------------------------


def test_trace_summary_cli(tracer, tmp_path):
    with T.span("step", cat="train"):
        with T.span("executor.run", cat="executor"):
            time.sleep(0.002)
        time.sleep(0.001)
    p = tracer.save(str(tmp_path / "t.json"))
    tool = os.path.join(REPO, "tools", "trace_summary.py")
    r = subprocess.run([sys.executable, tool, p, "--json"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout)
    rows = {row["name"]: row for row in out["top_spans_by_self_time"]}
    assert rows["executor.run"]["self_ms"] >= 2
    # parent's self-time excludes the nested child
    assert rows["step"]["self_ms"] < rows["step"]["total_ms"]
    # human output mode + unreadable-file rc 1
    r = subprocess.run([sys.executable, tool, p],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0 and "top spans by self-time" in r.stdout
    bad = tmp_path / "bad.json"
    bad.write_text("not a trace")
    r = subprocess.run([sys.executable, tool, str(bad)],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 1


# ---------------------------------------------------------------------------
# xla cost attribution unit surface
# ---------------------------------------------------------------------------


def test_cost_analysis_normalization():
    from paddle_tpu.observability import xla_cost as XC

    class FakeCompiled:
        def __init__(self, ca):
            self._ca = ca

        def cost_analysis(self):
            if isinstance(self._ca, Exception):
                raise self._ca
            return self._ca

    assert XC.cost_analysis_of(FakeCompiled(
        {"flops": 10.0, "bytes accessed": 5.0,
         "bytes accessed0{}": 3.0, "not_a_number": "x"})) == \
        {"flops": 10.0, "bytes_accessed": 5.0}
    # older jax: list of per-device dicts
    assert XC.cost_analysis_of(
        FakeCompiled([{"flops": 7.0}]))["flops"] == 7.0
    assert XC.cost_analysis_of(FakeCompiled(None)) is None
    assert XC.cost_analysis_of(FakeCompiled(RuntimeError("no"))) is None


def test_record_mfu_math_and_peak_resolution(monkeypatch):
    from paddle_tpu.observability import xla_cost as XC

    monkeypatch.delenv(XC.PEAK_FLOPS_ENV, raising=False)
    assert XC.peak_flops(explicit=5e12) == 5e12
    monkeypatch.setenv(XC.PEAK_FLOPS_ENV, "2e12")
    assert XC.peak_flops() == 2e12
    assert XC.peak_flops(platform="tpu") == 2e12   # env beats table
    monkeypatch.delenv(XC.PEAK_FLOPS_ENV)
    assert XC.peak_flops(platform="tpu") == 197e12
    assert XC.peak_flops(platform="quantum") is None

    reg = MetricsRegistry()
    mfu = XC.record_mfu("exe", flops=1e12, seconds=0.01, peak=500e12,
                        registry=reg)
    assert mfu == pytest.approx(0.2)
    series = reg.get("mfu")._series()
    assert series[0][0] == ("exe",)
    assert series[0][1].value == pytest.approx(0.2)
    # degenerate inputs and unknown peak report nothing
    assert XC.record_mfu("e", 0, 1.0, peak=1e12, registry=reg) is None
    assert XC.record_mfu("e", 1e9, 0.0, peak=1e12, registry=reg) is None
    assert XC.record_mfu("e", 1e9, 1.0, peak=None, platform="quantum",
                         registry=reg) is None


def test_cost_of_jitted_real_executable():
    import jax

    from paddle_tpu.observability import xla_cost as XC

    f = jax.jit(lambda a, b: a @ b)
    x = np.ones((16, 16), np.float32)
    cost = XC.cost_of_jitted(f, x, x)
    assert cost and cost["flops"] >= 2 * 16 * 16 * 16 * 0.9
    assert XC.cost_of_jitted(object()) is None     # not jitted: telemetry


# ---------------------------------------------------------------------------
# bench guard regression (BENCH_r05: raw traceback, rc 1)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", ["init", "late"])
def test_bench_backend_failure_emits_skip_convention(mode):
    env = dict(os.environ, BENCH_FORCE_BACKEND_FAIL=mode,
               JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                       capture_output=True, text=True, timeout=300,
                       env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["skipped"] is True
    assert "injected by BENCH_FORCE_BACKEND_FAIL" in out["reason"]
    assert ("init failed" in out["reason"]) == (mode == "init")
