"""fleet API: init, strategy-driven distributed_optimizer, transpiler.

Mirrors reference tests test_fleet_base / test_dist_mnist program-structure
assertions (single host: worker_num=1 paths + explicit transpile checks).
"""

import numpy as np

import paddle_tpu.fleet as fleet
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers
from paddle_tpu.fluid.optimizer import SGDOptimizer
from paddle_tpu.fluid.transpiler import GradAllReduce


def _model():
    x = fluid.data("x", [4, 3], "float32")
    y = fluid.data("y", [4, 1], "float32")
    pred = layers.fc(x, 1)
    return layers.reduce_mean(layers.square_error_cost(pred, y))


def test_fleet_init_and_identity(monkeypatch):
    monkeypatch.setenv("PADDLE_TRAINER_ID", "0")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
    f = fleet.Fleet()
    f.init()
    assert f.is_worker()
    assert f.is_first_worker()
    assert f.worker_num() == 1


def test_distributed_optimizer_single_worker_plain(monkeypatch):
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
    f = fleet.Fleet()
    f.init()
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        loss = _model()
        opt = f.distributed_optimizer(SGDOptimizer(0.1))
        opt.minimize(loss, startup)
    types = [op.type for op in prog.global_block.ops]
    assert "sgd" in types
    assert "c_allreduce_sum" not in types  # world=1: no collective rewrite


def test_grad_allreduce_transpiler_inserts_collectives():
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        loss = _model()
        SGDOptimizer(0.1).minimize(loss, startup)
        t = GradAllReduce()
        t.transpile(startup, prog, rank=0,
                    endpoints=["127.0.0.1:6170", "127.0.0.1:6171"])
    ops = prog.global_block.ops
    types = [op.type for op in ops]
    assert types.count("c_allreduce_sum") >= 2  # one per grad (w, b)
    # allreduce must come before the sgd updates
    assert max(i for i, t_ in enumerate(types) if t_ == "c_allreduce_sum") < \
        min(i for i, t_ in enumerate(types) if t_ == "sgd")
    # ... and the program still runs on one device (identity collectives)
    exe = fluid.Executor()
    with fluid.scope_guard(fluid.Scope()):
        exe.run_startup(startup)
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(4, 3).astype(np.float32),
                "y": rng.randn(4, 1).astype(np.float32)}
        l0 = float(exe.run(prog, feed=feed, fetch_list=[loss])[0])
        for _ in range(4):
            l1 = float(exe.run(prog, feed=feed, fetch_list=[loss])[0])
    # nranks=2 scaling halves effective lr but training still descends
    assert l1 < l0


def test_strategy_fields_parity():
    s = fleet.DistributedStrategy()
    for field in ["amp", "recompute", "localsgd", "dgc", "hierachical_allreduce",
                  "nccl_comm_num", "gradient_merge", "lars", "lamb", "pipeline",
                  "elastic", "auto"]:
        assert hasattr(s, field)
    s.amp = True
    s.gradient_merge = True
    s.gradient_merge_configs.k_steps = 4
    assert "amp" in s.to_json()


def test_distributed_optimizer_with_amp_and_grad_merge(monkeypatch):
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "1")
    f = fleet.Fleet()
    strategy = fleet.DistributedStrategy()
    strategy.amp = True
    strategy.gradient_merge = True
    strategy.gradient_merge_configs.k_steps = 2
    f.init(strategy=strategy)
    prog, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(prog, startup):
        loss = _model()
        opt = f.distributed_optimizer(SGDOptimizer(0.1))
        opt.minimize(loss, startup)
    types = [op.type for op in prog.global_block.ops]
    assert "cast" in types  # amp rewrite ran
    assert "where" in types  # gradient merge masking ran
