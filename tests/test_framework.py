"""IR + executor + autodiff basics (cf. reference tests/unittests/
test_program.py, test_executor_*, test_backward.py)."""

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu.fluid import layers


def test_program_build_and_shapes():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 3], append_batch_size=False)
        y = layers.fc(x, size=8, act="relu")
    assert y.shape == (4, 8)
    assert len(main.global_block.ops) >= 2
    params = main.all_parameters()
    assert len(params) == 2  # weight + bias


def test_dynamic_batch_dim():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3])  # implicit -1 batch
        y = layers.fc(x, size=8)
    assert y.shape == (-1, 8)


def test_executor_simple_run():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2, 3], append_batch_size=False)
        y = layers.relu(x)
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([[-1.0, 2.0, -3.0], [4.0, -5.0, 6.0]], dtype=np.float32)
    (out,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(out, np.maximum(xv, 0))


def test_executor_persistable_params():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[5, 3], append_batch_size=False)
        y = layers.fc(x, size=4)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.random.RandomState(0).randn(5, 3).astype(np.float32)
    (out,) = exe.run(main, feed={"x": xv}, fetch_list=[y])
    assert out.shape == (5, 4)
    # parity check vs numpy using the actual initialized weights
    w_name = main.all_parameters()[0].name
    b_name = main.all_parameters()[1].name
    w = np.asarray(fluid.global_scope().find_var(w_name))
    b = np.asarray(fluid.global_scope().find_var(b_name))
    np.testing.assert_allclose(out, xv @ w + b, rtol=1e-5, atol=1e-5)


def test_program_serialization_roundtrip():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[2, 3], append_batch_size=False)
        y = layers.fc(x, size=4, act="tanh")
    s = main.to_json()
    clone = fluid.Program.from_json(s)
    assert len(clone.global_block.ops) == len(main.global_block.ops)
    # run the deserialized program
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.ones((2, 3), dtype=np.float32)
    (a,) = exe.run(main, feed={"x": xv}, fetch_list=[y.name])
    (b,) = exe.run(clone, feed={"x": xv}, fetch_list=[y.name])
    np.testing.assert_allclose(a, b, rtol=1e-6)


def test_append_backward_simple():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 3], append_batch_size=False)
        x.stop_gradient = False
        y = layers.fc(x, size=1, bias_attr=False)
        loss = layers.mean(y)
        pg = fluid.append_backward(loss)
    assert len(pg) == 1
    p, g = pg[0]
    assert g.name == p.name + "@GRAD"

    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    xv = np.random.RandomState(1).randn(4, 3).astype(np.float32)
    (gv,) = exe.run(main, feed={"x": xv}, fetch_list=[g])
    # d mean(xW) / dW = mean over batch of x / 1 => x.mean(0) / 1
    np.testing.assert_allclose(gv[:, 0], xv.mean(axis=0) / 1.0, rtol=1e-5, atol=1e-5)


def test_grad_accumulation_multi_consumer():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[3], append_batch_size=False)
        x.stop_gradient = False
        a = x * x  # consumer 1+2 of x
        b = x + a
        loss = layers.reduce_sum(b)
        fluid.append_backward(loss, parameter_list=[])
    exe = fluid.Executor(fluid.CPUPlace())
    xv = np.array([1.0, 2.0, 3.0], dtype=np.float32)
    (gx,) = exe.run(main, feed={"x": xv}, fetch_list=["x@GRAD"])
    np.testing.assert_allclose(gx, 1.0 + 2 * xv, rtol=1e-5)


def test_sgd_training_decreases_loss():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 4], append_batch_size=False)
        label = layers.data("y", shape=[8, 1], append_batch_size=False)
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, label))
        from paddle_tpu.fluid.optimizer import SGDOptimizer

        SGDOptimizer(learning_rate=0.1).minimize(loss)
    exe = fluid.Executor(fluid.CPUPlace())
    exe.run(startup)
    rs = np.random.RandomState(7)
    xv = rs.randn(8, 4).astype(np.float32)
    w_true = rs.randn(4, 1).astype(np.float32)
    yv = xv @ w_true
    losses = []
    for _ in range(30):
        (lv,) = exe.run(main, feed={"x": xv, "y": yv}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.2, losses


def test_clone_for_test_strips_optimizer():
    main = fluid.Program()
    startup = fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 4], append_batch_size=False)
        h = layers.fc(x, size=4)
        h = layers.dropout(h, dropout_prob=0.5)
        loss = layers.mean(h)
        from paddle_tpu.fluid.optimizer import SGDOptimizer

        SGDOptimizer(0.1).minimize(loss)
    test_prog = main.clone(for_test=True)
    types = [op.type for op in test_prog.global_block.ops]
    assert "sgd" not in types
    drop_ops = [op for op in test_prog.global_block.ops if op.type == "dropout"]
    assert all(op.attrs["is_test"] for op in drop_ops)
