"""paddle_tpu.tune — the measured compiler autotuner.

What must hold (ISSUE 11 acceptance):
  * determinism — the SECOND search of the same program+mesh+chip+jax
    is served entirely from the tuning cache: cache_hit, zero candidate
    compiles (asserted via the PR-4 ``xla_compilations_total``
    accumulator), same winner;
  * invalidation — a different jax version or chip spec re-opens the
    search (different cache key);
  * safety — a candidate broken by a seeded bad pass is EXCLUDED with
    the offending pass named, and is never compiled or timed;
  * usefulness — on a zoo workload the winner's measured step time is
    <= the measured default under the same harness, and where a known
    lever exists (bucket ladders, flash blocks) the winner is STRICTLY
    better.
"""

import json
import os
import time

import numpy as np
import pytest

import paddle_tpu.fluid as fluid
from paddle_tpu import models, tune
from paddle_tpu.fluid import ir, layers
from paddle_tpu.observability import default_registry


def _compiles():
    return default_registry().counter(
        "xla_compilations_total",
        "XLA backend compilations (jax.monitoring)").value


def _conv_bn_relu():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("img", shape=[8, 16, 16, 16],
                        append_batch_size=False)
        c = layers.conv2d(x, num_filters=32, filter_size=3, padding=1,
                          data_format="NHWC")
        bn = layers.batch_norm(c, data_layout="NHWC")
        out = layers.relu(bn)
    return main, out


# ---------------------------------------------------------------------------
# candidate spaces
# ---------------------------------------------------------------------------


def test_default_pipelines_enumerate_registry():
    pipes = tune.default_pass_pipelines()
    assert [] in pipes                      # the baseline is never optional
    assert ["batch_norm_act_fuse"] in pipes
    assert ["dead_op_elimination"] in pipes


def test_flash_block_candidates_divisors_default_first():
    cands = tune.flash_block_candidates(512, 512)
    pairs = [(c.params["block_q"], c.params["block_k"]) for c in cands]
    assert pairs[0] == (512, 512)           # heuristic default leads
    assert set(pairs) == {(a, b) for a in (512, 256, 128)
                          for b in (512, 256, 128)}
    # non-divisible lengths restrict the grid
    assert all(c.params["block_q"] != 512
               for c in tune.flash_block_candidates(256, 512))


def test_ladder_candidates_default_exact_and_quantile_cap():
    cands = tune.ladder_candidates(32, traffic=[3, 3, 7])
    labels = [c.label for c in cands]
    assert labels[0].startswith("ladder-pow2")
    exact = next(c for c in cands if "exact" in c.label)
    assert exact.params["batch_buckets"] == [3, 7, 32]
    # >8 distinct sizes: quantile-capped, max_batch always present
    many = tune.ladder_candidates(64, traffic=list(range(1, 40)))
    exact = next(c for c in many if "exact" in c.label)
    assert len(exact.params["batch_buckets"]) <= 9
    assert exact.params["batch_buckets"][-1] == 64


class _StubMesh:
    axis_names = ("dp", "mp")

    def __init__(self, sizes):
        self.shape = dict(zip(self.axis_names, sizes))

    def axis_size(self, name):
        return self.shape[name]


def test_sharding_candidates_need_mesh_and_big_weights():
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[8, 512], append_batch_size=False)
        w = main.global_block.create_parameter("tn.big", shape=[512, 2048])
        layers.matmul(x, w)
    assert tune.sharding_candidates(main, None) == []
    assert tune.sharding_candidates(main, _StubMesh((1, 1))) == []
    cands = tune.sharding_candidates(main, _StubMesh((1, 4)),
                                     min_bytes=1 << 20)
    assert len(cands) == 1
    assert cands[0].params["sharding"] == {
        "axis": "mp", "vars": ["tn.big"], "dim": -1}
    # below the size floor nothing shards
    assert tune.sharding_candidates(main, _StubMesh((1, 4)),
                                    min_bytes=1 << 30) == []


# ---------------------------------------------------------------------------
# tuning cache
# ---------------------------------------------------------------------------


def test_cache_roundtrip_and_corruption(tmp_path):
    cache = tune.TuningCache(str(tmp_path))
    parts = tune.cache_key_parts("w1", platform="cpu", jax_version="1.0")
    assert cache.get(parts) is None
    path = cache.put(parts, {"kind": "program", "params": {"pipeline": []}},
                     extra={"default_s": 1.0})
    entry = cache.get(parts)
    assert entry["winner"]["params"] == {"pipeline": []}
    assert entry["default_s"] == 1.0
    # corruption is a miss, never an error
    with open(path, "w") as f:
        f.write("{not json")
    assert cache.get(parts) is None
    cache.put(parts, {"kind": "program", "params": {}})
    assert cache.invalidate(parts) is True
    assert cache.get(parts) is None


def test_cache_key_sensitivity(tmp_path):
    base = dict(platform="cpu", jax_version="1.0")
    k0 = tune.TuningCache.key(tune.cache_key_parts("w", **base))
    assert tune.TuningCache.key(tune.cache_key_parts("w", **base)) == k0
    assert tune.TuningCache.key(
        tune.cache_key_parts("w", platform="tpu", jax_version="1.0")) != k0
    assert tune.TuningCache.key(
        tune.cache_key_parts("w", platform="cpu", jax_version="2.0")) != k0
    assert tune.TuningCache.key(
        tune.cache_key_parts("w", mesh=_StubMesh((2, 4)), **base)) != k0


def test_cache_rejects_key_part_drift(tmp_path):
    """An entry whose stored key_parts do not match the request is a
    miss — the filename alone is never trusted."""
    cache = tune.TuningCache(str(tmp_path))
    parts = tune.cache_key_parts("w1", platform="cpu", jax_version="1.0")
    path = cache.put(parts, {"kind": "program", "params": {}})
    with open(path) as f:
        entry = json.load(f)
    entry["key_parts"]["jax"] = "drifted"
    with open(path, "w") as f:
        json.dump(entry, f)
    assert cache.get(parts) is None


# ---------------------------------------------------------------------------
# program search: determinism, invalidation, exclusion, pruning, budget
# ---------------------------------------------------------------------------


def test_search_cache_determinism_zero_recompiles(tmp_path):
    main, out = _conv_bn_relu()
    rep1 = tune.search(main, [out.name], cache_dir=str(tmp_path), k=2,
                       warmup=1)
    assert not rep1.cache_hit and rep1.cache_stored
    assert rep1.winner is not None and rep1.default_s is not None
    # the winner is never worse than the measured default (argmin over a
    # space that always contains the default)
    assert rep1.winner.measured_s <= rep1.default_s + 1e-12

    before = _compiles()
    rep2 = tune.search(main, [out.name], cache_dir=str(tmp_path), k=2,
                       warmup=1)
    assert rep2.cache_hit
    assert _compiles() == before, \
        "a cache hit must compile no candidates"
    assert rep2.winner.params["pipeline"] == rep1.winner.params["pipeline"]
    assert rep2.results == []               # nothing enumerated either
    # the winner re-applies cleanly (and is re-verified on apply)
    from paddle_tpu import analysis

    tuned = tune.tuned_program(main, rep2)
    analysis.assert_program_valid(tuned)


def test_search_cache_invalidated_by_jax_and_chip(tmp_path):
    from paddle_tpu.analysis.perf import ChipSpec

    main, out = _conv_bn_relu()
    kw = dict(cache_dir=str(tmp_path), k=1, warmup=1)
    rep1 = tune.search(main, [out.name], jax_version="9.9.9", **kw)
    assert not rep1.cache_hit
    assert tune.search(main, [out.name], jax_version="9.9.9",
                       **kw).cache_hit
    # a jax upgrade re-opens the search
    rep3 = tune.search(main, [out.name], jax_version="10.0.0", **kw)
    assert not rep3.cache_hit
    # so does a different chip spec
    rep4 = tune.search(main, [out.name], jax_version="9.9.9",
                       chip=ChipSpec("other-chip", 1e12, 1e11), **kw)
    assert not rep4.cache_hit


class _BreakerPass(ir.Pass):
    """Deletes a mid-chain producer: verification must catch it."""

    name = "tune_test_breaker"

    def apply(self, program):
        del program.global_block.ops[1]
        return program


def test_broken_pass_candidate_excluded_with_name(tmp_path):
    main, out = _conv_bn_relu()
    space = tune.SearchSpace(
        pipelines=[[], ["batch_norm_act_fuse"], [_BreakerPass()]],
        donate=(True,), sharding=False)
    rep = tune.search(main, [out.name], space=space,
                      cache_dir=str(tmp_path), k=1, warmup=1)
    broken = [r for r in rep.results if r.status == "excluded"]
    assert len(broken) == 1
    assert "tune_test_breaker" in broken[0].error
    # excluded means excluded: never measured, never the winner
    assert broken[0].measured_s is None and broken[0].compiles is None
    assert rep.winner.params["pipeline"] != ["tune_test_breaker"]
    # and the original program was never mutated
    assert [o.type for o in main.global_block.ops][-1] == "relu"


class _OpInflaterPass(ir.Pass):
    """Appends N redundant heavy matmuls: statically, obviously worse."""

    name = "tune_test_inflater"

    def apply(self, program):
        block = program.global_block
        src = None
        for op in block.ops:
            if op.type == "conv2d":
                src = op.all_output_names()[0]
        v = block._find_var_recursive(src)
        for i in range(20):
            name = "inflate.%d" % i
            block.create_var(name=name, shape=v.shape, dtype=v.dtype)
            block.append_op(
                type="scale", inputs={"X": [src]}, outputs={"Out": [name]},
                attrs={"scale": 1.0, "bias": 0.0,
                       "bias_after_scale": True})
        # keep them alive so dead-op hygiene can't undo the bloat
        block.append_op(
            type="sum", inputs={"X": ["inflate.%d" % i for i in range(20)]},
            outputs={"Out": [src + ".bloat"]}, attrs={})
        out = block.create_var(name=src + ".bloat", shape=v.shape,
                               dtype=v.dtype)
        del out
        program._bump()
        return program


def test_statically_worse_candidate_pruned_never_compiled(tmp_path):
    main, out = _conv_bn_relu()
    space = tune.SearchSpace(
        pipelines=[[], [_OpInflaterPass()]], donate=(True,),
        sharding=False)
    rep = tune.search(main, [out.name], space=space,
                      cache_dir=str(tmp_path), k=1, warmup=1,
                      prune_ratio=1.2)
    pruned = [r for r in rep.results if r.status == "pruned"]
    assert len(pruned) == 1
    assert pruned[0].params["pipeline"] == ["tune_test_inflater"]
    assert pruned[0].measured_s is None     # never compiled, never timed
    assert pruned[0].est_time_s > rep.winner.est_time_s


def test_budget_limits_search_but_baseline_always_runs(tmp_path):
    main, out = _conv_bn_relu()
    rep = tune.search(main, [out.name], cache_dir=str(tmp_path), k=1,
                      warmup=1, budget_s=0.0)
    by_status = rep.counts()
    assert by_status.get("timed") == 1      # the measured baseline
    assert by_status.get("skipped_budget", 0) >= 1
    assert rep.winner.params["pipeline"] == []


def test_dead_op_elimination_keeps_fetches():
    """The tuner protects the fetch list in every pipeline it tries —
    dead-op elimination must not delete the chain feeding the fetch."""
    main, out = _conv_bn_relu()
    rep = tune.search(main, [out.name], use_cache=False, k=1, warmup=1)
    dce = [r for r in rep.results
           if r.params.get("pipeline") == ["dead_op_elimination"]]
    assert dce and dce[0].status == "timed"
    assert rep.winner.params.get("keep") == [out.name]


# ---------------------------------------------------------------------------
# zoo end-to-end (acceptance): winner <= default, exclusion, cache
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_zoo_resnet_search_winner_not_worse_and_cached(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("img", shape=[2, 3, 32, 32],
                        append_batch_size=False)
        out = models.resnet18(num_classes=5)(x)
    rep = tune.search(main, [out.name], cache_dir=str(tmp_path), k=3,
                      warmup=1)
    assert rep.winner is not None
    assert rep.winner.measured_s <= rep.default_s + 1e-12
    assert rep.winner.compiles is None or rep.winner.compiles >= 0
    d = rep.to_dict()
    assert d["schema_version"] == 1
    assert d["winner"]["status"] == "timed"
    assert all(c["status"] in ("timed", "pruned", "excluded",
                               "skipped_budget") for c in d["candidates"])
    # second run: pure cache, zero compiles, applies cleanly
    before = _compiles()
    rep2 = tune.search(main, [out.name], cache_dir=str(tmp_path), k=3,
                       warmup=1)
    assert rep2.cache_hit and _compiles() == before
    from paddle_tpu import analysis

    analysis.assert_program_valid(tune.tuned_program(main, rep2))


# ---------------------------------------------------------------------------
# flash-attention block search
# ---------------------------------------------------------------------------


def test_search_flash_blocks_winner_and_cache(tmp_path):
    shape = (1, 2, 256, 64)
    rep = tune.search_flash_blocks(shape, interpret=True, k=2, warmup=1,
                                   cache_dir=str(tmp_path))
    assert rep.winner is not None
    bq, bk = rep.winner.params["block_q"], rep.winner.params["block_k"]
    assert bq in (128, 256) and bk in (128, 256)
    assert rep.winner.measured_s <= rep.default_s + 1e-12
    before = _compiles()
    rep2 = tune.search_flash_blocks(shape, interpret=True, k=2, warmup=1,
                                    cache_dir=str(tmp_path))
    assert rep2.cache_hit and _compiles() == before
    assert rep2.winner.params == rep.winner.params
    # the winner drives the kernel (correctness is test_pallas_attention's
    # job; here: the tuned call accepts the tuned blocks)
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.attention import flash_attention

    q = jnp.zeros((1, 2, 256, 64), jnp.float32)
    flash_attention(q, q, q, interpret=True, block_q=bq, block_k=bk)


# ---------------------------------------------------------------------------
# bucket-ladder search: a known lever must win STRICTLY
# ---------------------------------------------------------------------------


class _RowCostRunner:
    """Deterministic service-time model: cost grows with padded rows —
    the shape of the real padding tax, without timer flakiness."""

    def __init__(self, per_row_s=4e-4):
        self.per_row_s = per_row_s
        self.calls = []

    def run(self, feed):
        rows = next(iter(feed.values())).shape[0]
        self.calls.append(rows)
        time.sleep(self.per_row_s * rows)
        return [np.zeros((rows, 2), np.float32)]


def test_ladder_search_exact_ladder_strictly_beats_pow2(tmp_path):
    runner = _RowCostRunner()
    traffic = [3] * 12   # every request is 3 rows: pow2 pads to 4
    rep = tune.search_bucket_ladder(
        runner, {"x": np.zeros((1, 8), np.float32)}, traffic,
        max_batch=8, workload="rowcost", k=2, cache_dir=str(tmp_path))
    assert rep.winner.params["batch_buckets"][0] == 3
    assert rep.winner.measured_s < rep.default_s   # strictly better
    before_calls = len(runner.calls)
    rep2 = tune.search_bucket_ladder(
        runner, {"x": np.zeros((1, 8), np.float32)}, traffic,
        max_batch=8, workload="rowcost", k=2, cache_dir=str(tmp_path))
    assert rep2.cache_hit
    assert len(runner.calls) == before_calls   # nothing re-measured


def test_ladder_search_without_workload_does_not_cache(tmp_path):
    runner = _RowCostRunner(per_row_s=1e-5)
    rep = tune.search_bucket_ladder(
        runner, {"x": np.zeros((1, 4), np.float32)}, [2, 2], max_batch=4,
        k=1, cache_dir=str(tmp_path))
    assert rep.cache_path is None and not rep.cache_stored
    assert os.listdir(str(tmp_path)) == []


def test_inference_server_autotune_adopts_winner_ladder(tmp_path):
    from paddle_tpu.inference.server import InferenceServer

    runner = _RowCostRunner()
    server = InferenceServer(runner, max_batch=8, name="tune-test")
    try:
        rep = server.autotune(
            {"x": np.zeros((1, 8), np.float32)}, traffic=[3] * 12,
            workload="server-rowcost", k=2, cache_dir=str(tmp_path))
        assert rep.winner is not None
        assert server._batch_buckets == rep.winner.params["batch_buckets"]
        assert server._batch_buckets[0] == 3
        # the adopted ladder was AOT-warmed through the predictor
        assert 3 in runner.calls
    finally:
        server.unregister_metrics()


# ---------------------------------------------------------------------------
# step-variant search (the bench.py --autotune front end)
# ---------------------------------------------------------------------------


def test_search_step_orders_and_caches(tmp_path):
    costs = {"default": 0.010, "remat": 0.015, "fast": 0.005}
    built = []

    def build_and_time(params):
        built.append(params["name"])
        return costs[params["name"]]

    variants = [(n, {"name": n}) for n in ("default", "remat", "fast")]
    rep = tune.search_step(build_and_time, variants, workload="steptest",
                           cache_dir=str(tmp_path))
    assert rep.winner.params["name"] == "fast"
    assert rep.default_s == 0.010
    assert rep.speedup == pytest.approx(2.0)
    rep2 = tune.search_step(build_and_time, variants, workload="steptest",
                            cache_dir=str(tmp_path))
    assert rep2.cache_hit
    assert built == ["default", "remat", "fast"]   # nothing rebuilt
    # a variant that dies is excluded, not fatal
    def dying(params):
        if params["name"] == "remat":
            raise RuntimeError("OOM")
        return costs[params["name"]]

    rep3 = tune.search_step(dying, variants, workload="steptest2",
                            cache_dir=str(tmp_path))
    assert rep3.counts() == {"timed": 2, "excluded": 1}
    assert rep3.winner.params["name"] == "fast"


# ---------------------------------------------------------------------------
# CompiledProgram.with_autotune through the Executor
# ---------------------------------------------------------------------------


def test_compiled_program_with_autotune_runs_and_caches(tmp_path):
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 16, 8, 8], append_batch_size=False)
        c = layers.conv2d(x, num_filters=8, filter_size=3, padding=1)
        bn = layers.batch_norm(c)
        out = layers.relu(bn)
    exe = fluid.Executor()
    exe.run(startup, feed={}, fetch_list=[])
    feed = {"x": np.random.RandomState(0).randn(
        4, 16, 8, 8).astype(np.float32)}
    ref = exe.run(main, feed=feed, fetch_list=[out])

    compiled = fluid.CompiledProgram(main).with_autotune(
        cache_dir=str(tmp_path), k=1)
    got = exe.run(compiled, feed=feed, fetch_list=[out])
    np.testing.assert_allclose(got[0], ref[0], rtol=1e-5, atol=1e-5)
    rep = compiled._tune_report
    assert rep is not None and not rep.cache_hit
    assert rep.winner.measured_s <= rep.default_s + 1e-12
    # the tuned clone is reused, not re-searched, on later runs — the
    # SAME object, so the executor's id-keyed jit cache never retraces
    (tuned_first,) = compiled._tuned_programs.values()
    exe.run(compiled, feed=feed, fetch_list=[out])
    assert list(compiled._tuned_programs.values()) == [tuned_first]

    # a FRESH facade (think: restarted process) hits the tuning cache
    compiled2 = fluid.CompiledProgram(main).with_autotune(
        cache_dir=str(tmp_path), k=1)
    before = _compiles()
    got2 = exe.run(compiled2, feed=feed, fetch_list=[out])
    np.testing.assert_allclose(got2[0], ref[0], rtol=1e-5, atol=1e-5)
    assert compiled2._tune_report.cache_hit
    # the only compile allowed is the winner's own executor lowering —
    # zero candidate compiles (the winner equals a pipeline the executor
    # may still have to build once for THIS executor's cache)
    assert _compiles() - before <= 1


# ---------------------------------------------------------------------------
# operator CLI
# ---------------------------------------------------------------------------


def _load_tool(name):
    import importlib.util

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(repo, "tools", "%s.py" % name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_autotune_cli_program_json_roundtrip(tmp_path, capsys):
    at = _load_tool("autotune")
    main, out = _conv_bn_relu()
    path = str(tmp_path / "prog.json")
    with open(path, "w") as f:
        f.write(main.to_json())
    cache = str(tmp_path / "cache")

    assert at.main([path, "--fetch", out.name, "--k", "1",
                    "--cache-dir", cache, "--json"]) == 0
    d = json.loads(capsys.readouterr().out)
    assert d["schema_version"] == 1
    assert d["kind"] == "program" and d["cache_hit"] is False
    assert d["winner"]["status"] == "timed"
    assert d["counts"].get("timed", 0) >= 2
    statuses = {c["status"] for c in d["candidates"]}
    assert statuses <= {"timed", "pruned", "excluded", "skipped_budget"}

    # second invocation: served from cache, text mode says HIT
    assert at.main([path, "--fetch", out.name, "--k", "1",
                    "--cache-dir", cache]) == 0
    assert "cache: HIT" in capsys.readouterr().out

    # unreadable model -> rc 1
    assert at.main([str(tmp_path / "nope.json"), "--fetch", "x"]) == 1
    capsys.readouterr()


def test_autotune_cli_flash_mode(tmp_path, capsys):
    at = _load_tool("autotune")
    assert at.main(["--flash", "1,2,128,64", "--k", "1",
                    "--cache-dir", str(tmp_path / "c"), "--json"]) == 0
    d = json.loads(capsys.readouterr().out)
    assert d["kind"] == "flash_blocks"
    assert d["winner"]["params"]["block_q"] == 128
    # malformed shape -> rc 1
    assert at.main(["--flash", "1,2,128"]) == 1
    capsys.readouterr()


# ---------------------------------------------------------------------------
# bench.py --autotune: conventions survive, tuned vs default reported
# ---------------------------------------------------------------------------

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_autotune_preserves_skip_convention():
    """--autotune must not break the driver contract: an infra failure
    still yields ONE {"skipped": true} line and rc 0."""
    import subprocess
    import sys

    env = dict(os.environ, BENCH_FORCE_BACKEND_FAIL="init",
               JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--autotune"],
        capture_output=True, text=True, timeout=300, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["skipped"] is True


@pytest.mark.slow
def test_bench_autotune_reports_tuned_vs_default(tmp_path):
    """Real CPU smoke run: the output JSON carries tuned vs default step
    time, the winner, and the platform/smoke_config fields that keep a
    CPU capture from impersonating TPU tuning numbers."""
    import subprocess
    import sys

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PADDLE_TPU_TUNE_CACHE=str(tmp_path))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--autotune"],
        capture_output=True, text=True, timeout=550, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["platform"] == "cpu" and out["smoke_config"] is True
    at = out["autotune"]
    assert at["cache_hit"] is False
    assert at["tuned_step_ms"] <= at["default_step_ms"] + 1e-9
    assert at["winner"]["status"] in ("timed", "cached")
    assert at["counts"]["timed"] >= 1
    assert at["platform"] == "cpu"


def test_autotune_cli_reports_excluded_pass_by_name(tmp_path, capsys):
    """The acceptance loop end to end through the operator CLI: a
    registered-but-broken pass in a --pipelines candidate shows up in
    the --json report as excluded WITH the pass named, and the healthy
    winner still emerges."""
    at = _load_tool("autotune")

    @ir.register_pass
    class _CliBreakerPass(ir.Pass):
        name = "tune_cli_breaker"

        def apply(self, program):
            del program.global_block.ops[1]
            return program

    try:
        main, out = _conv_bn_relu()
        path = str(tmp_path / "prog.json")
        with open(path, "w") as f:
            f.write(main.to_json())
        assert at.main([path, "--fetch", out.name, "--k", "1",
                        "--cache-dir", str(tmp_path / "c"), "--json",
                        "--pipelines",
                        ";batch_norm_act_fuse;tune_cli_breaker"]) == 0
        d = json.loads(capsys.readouterr().out)
        excluded = [c for c in d["candidates"]
                    if c["status"] == "excluded"]
        assert len(excluded) == 1
        assert excluded[0]["params"]["pipeline"] == ["tune_cli_breaker"]
        assert "tune_cli_breaker" in excluded[0]["error"]
        assert excluded[0]["measured_s"] is None
        assert d["winner"]["status"] == "timed"
        assert d["winner"]["params"]["pipeline"] != ["tune_cli_breaker"]
    finally:
        ir._PASS_REGISTRY.pop("tune_cli_breaker", None)


# ---------------------------------------------------------------------------
# cache-identity hardening (review findings): fetch set, flash grid /
# interpret mode, ladder feed contract, and excluded-default honesty
# ---------------------------------------------------------------------------


def test_different_fetch_set_is_a_different_workload(tmp_path):
    """A winner searched (and DCE-keep-protected) for one fetch set must
    not serve a different fetch set from the cache — a cached dead-op
    pipeline would delete the new fetch's producer."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[4, 8], append_batch_size=False)
        a = layers.relu(x)
        b = layers.sigmoid(x)
    kw = dict(cache_dir=str(tmp_path), k=1, warmup=1)
    rep1 = tune.search(main, [a.name], **kw)
    assert not rep1.cache_hit
    # same program, superset fetch: MISS, and the tuned clone keeps both
    rep2 = tune.search(main, [a.name, b.name], **kw)
    assert not rep2.cache_hit
    tuned = tune.tuned_program(main, rep2)
    produced = {n for op in tuned.global_block.ops
                for n in op.all_output_names()}
    assert a.name in produced and b.name in produced
    # and the original fetch set still hits its own entry
    assert tune.search(main, [a.name], **kw).cache_hit
    # belt-and-braces: tuned_program(fetch_list=...) re-binds "keep"
    tuned2 = tune.tuned_program(main, rep1, fetch_list=[a.name, b.name])
    produced2 = {n for op in tuned2.global_block.ops
                 for n in op.all_output_names()}
    assert b.name in produced2


def test_flash_grid_and_interpret_are_cache_identity(tmp_path):
    shape = (1, 1, 256, 64)
    kw = dict(interpret=True, k=1, warmup=1, cache_dir=str(tmp_path))
    rep = tune.search_flash_blocks(shape, **kw)
    assert not rep.cache_hit
    # a constrained grid is a different workload: re-search, and the
    # winner honors the constraint
    rep2 = tune.search_flash_blocks(shape, grid=(128,), **kw)
    assert not rep2.cache_hit
    assert rep2.winner.params == {"block_q": 128, "block_k": 128}
    # unconstrained call still hits its own entry
    assert tune.search_flash_blocks(shape, **kw).cache_hit


def test_ladder_feed_contract_is_cache_identity(tmp_path):
    runner = _RowCostRunner(per_row_s=1e-5)
    example = {"x": np.zeros((1, 8), np.float32)}
    kw = dict(max_batch=8, workload="contract", k=1,
              cache_dir=str(tmp_path))
    rep = tune.search_bucket_ladder(runner, example, [2, 2], **kw)
    assert not rep.cache_hit
    rep2 = tune.search_bucket_ladder(
        runner, example, [2, 2], ragged_dims={"x": {1: [4, 8]}}, **kw)
    assert not rep2.cache_hit        # different feed contract: re-search
    assert tune.search_bucket_ladder(runner, example, [2, 2],
                                     **kw).cache_hit


def test_excluded_default_is_not_impersonated(tmp_path):
    """When the default variant itself dies, default_s/speedup must be
    None — not whichever candidate happened to time first."""
    def build_and_time(params):
        if params["name"] == "default":
            raise RuntimeError("default OOM")
        return {"remat": 0.015, "fast": 0.005}[params["name"]]

    variants = [(n, {"name": n}) for n in ("default", "remat", "fast")]
    rep = tune.search_step(build_and_time, variants,
                           workload="nodefault", cache_dir=str(tmp_path))
    assert rep.winner.params["name"] == "fast"
    assert rep.default_s is None and rep.speedup is None
    assert rep.counts() == {"excluded": 1, "timed": 2}


def test_chip_spec_in_non_program_cache_keys(tmp_path, monkeypatch):
    """flash/ladder/step keys must carry the resolved chip spec (the
    cache contract): a different PADDLE_TPU_PEAK_FLOPS — how a mixed
    fleet distinguishes generations — re-opens the search."""
    shape = (1, 1, 128, 64)
    kw = dict(interpret=True, k=1, warmup=1, cache_dir=str(tmp_path))
    assert not tune.search_flash_blocks(shape, **kw).cache_hit
    assert tune.search_flash_blocks(shape, **kw).cache_hit
    monkeypatch.setenv("PADDLE_TPU_PEAK_FLOPS", "9e13")
    monkeypatch.setenv("PADDLE_TPU_HBM_BW", "5e11")
    assert not tune.search_flash_blocks(shape, **kw).cache_hit


def test_feed_dtype_in_program_workload(tmp_path):
    main, out = _conv_bn_relu()
    kw = dict(cache_dir=str(tmp_path), k=1, warmup=1)
    spec32 = {"img": ((8, 16, 16, 16), "float32")}
    assert not tune.search(main, [out.name], feed_specs=spec32,
                           **kw).cache_hit
    # ndarray-valued specs hash shape AND dtype
    arr32 = {"img": np.zeros((8, 16, 16, 16), np.float32)}
    assert tune.search(main, [out.name], feed_specs=arr32, **kw).cache_hit
    arr16 = {"img": np.zeros((8, 16, 16, 16), np.float16)}
    assert not tune.search(main, [out.name], feed_specs=arr16,
                           **kw).cache_hit


def test_ladder_search_clamps_oversize_traffic(tmp_path):
    """Traffic entries beyond max_batch must not compile buckets the
    serving path can never dispatch."""
    runner = _RowCostRunner(per_row_s=1e-5)
    rep = tune.search_bucket_ladder(
        runner, {"x": np.zeros((1, 4), np.float32)}, [2, 64],
        max_batch=8, workload="oversize", k=1, cache_dir=str(tmp_path))
    assert max(runner.calls) <= 8
    for r in rep.results:
        if r.status == "timed":
            assert all(int(b) <= 8 for b in r.detail["per_bucket_s"])


def test_executor_autotune_memo_keys_on_feed_shapes(tmp_path):
    """A pipeline tuned at one batch size must not silently serve a
    different batch size — and alternating shapes must reuse STABLE
    clone objects (no per-run re-clone)."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 8], append_batch_size=False)
        out = layers.relu(layers.fc(x, 4))
    exe = fluid.Executor()
    exe.run(startup, feed={}, fetch_list=[])
    compiled = fluid.CompiledProgram(main).with_autotune(
        cache_dir=str(tmp_path), k=1)
    f1 = {"x": np.zeros((2, 8), np.float32)}
    f2 = {"x": np.zeros((16, 8), np.float32)}
    exe.run(compiled, feed=f1, fetch_list=[out])
    exe.run(compiled, feed=f2, fetch_list=[out])
    assert len(compiled._tuned_programs) == 2   # per-shape entries
    before = dict(compiled._tuned_programs)
    exe.run(compiled, feed=f1, fetch_list=[out])
    exe.run(compiled, feed=f2, fetch_list=[out])
    # same objects reused: the executor's id-keyed jit cache stays warm
    assert compiled._tuned_programs == before


def test_server_autotune_incumbent_ladder_competes(tmp_path):
    """A hand-tuned server ladder is always a candidate: autotune can
    only keep or beat the incumbent, never regress it unmeasured."""
    from paddle_tpu.inference.server import InferenceServer

    runner = _RowCostRunner()
    incumbent = [5, 8]      # hand-tuned; distinct from every enumerated
    server = InferenceServer(runner, max_batch=8,  # candidate ladder
                             batch_buckets=list(incumbent),
                             name="tune-incumbent")
    try:
        rep = server.autotune(
            {"x": np.zeros((1, 8), np.float32)}, traffic=[3] * 12,
            workload="incumbent", k=2, cache_dir=str(tmp_path))
        labels = {r.label for r in rep.results}
        assert any("extra" in l for l in labels), labels
        # the incumbent serves bucket 3 exactly; the adopted ladder must
        # serve size-3 traffic at bucket 3 too (keep-or-beat)
        from paddle_tpu.inference.batching import pick_bucket

        assert pick_bucket(3, server._batch_buckets) == 3
    finally:
        server.unregister_metrics()


def test_flash_constrained_grid_reports_no_false_default(tmp_path):
    """When the grid excludes the heuristic default, default_s is None —
    the report never cites another candidate as 'default'."""
    rep = tune.search_flash_blocks(
        (1, 1, 512, 64), grid=(256, 128), interpret=True, k=1, warmup=1,
        cache_dir=str(tmp_path))
    assert rep.winner is not None
    assert rep.default_s is None and rep.speedup is None


def test_executor_autotune_memo_never_wholesale_clears(tmp_path):
    """Cycling >32 feed shapes must not evict the live entries' object
    identity wholesale (the jit cache keys on id(program))."""
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = layers.data("x", shape=[-1, 4], append_batch_size=False)
        out = layers.relu(layers.fc(x, 2))
    exe = fluid.Executor()
    exe.run(startup, feed={}, fetch_list=[])
    compiled = fluid.CompiledProgram(main).with_autotune(
        cache_dir=str(tmp_path), k=1,
        space=tune.SearchSpace(pipelines=[[]], donate=(True,),
                               sharding=False))
    for b in range(1, 35):
        exe.run(compiled, feed={"x": np.zeros((b, 4), np.float32)},
                fetch_list=[out])
    assert len(compiled._tuned_programs) <= 32
    # the most recent entries survived (no wholesale clear)
    survivors = {k[2][0][1][0] for k in compiled._tuned_programs}
    assert 34 in survivors


def test_candidate_space_is_cache_identity(tmp_path):
    """A winner from one pipeline space must not answer a search over a
    different space — and a space containing configured Pass INSTANCES
    never touches the cache at all (not reconstructible later)."""
    main, out = _conv_bn_relu()
    kw = dict(cache_dir=str(tmp_path), k=1, warmup=1)
    s1 = tune.SearchSpace(pipelines=[[]], donate=(True,), sharding=False)
    assert not tune.search(main, [out.name], space=s1, **kw).cache_hit
    assert tune.search(main, [out.name], space=s1, **kw).cache_hit
    # a wider names-only space re-opens the search
    s2 = tune.SearchSpace(pipelines=[[], ["batch_norm_act_fuse"]],
                          donate=(True,), sharding=False)
    assert not tune.search(main, [out.name], space=s2, **kw).cache_hit
    # an instance-bearing space bypasses the cache entirely
    before = sorted(os.listdir(str(tmp_path)))
    s3 = tune.SearchSpace(pipelines=[[], [_BreakerPass()]],
                          donate=(True,), sharding=False)
    rep = tune.search(main, [out.name], space=s3, **kw)
    assert not rep.cache_hit and not rep.cache_stored
    assert sorted(os.listdir(str(tmp_path))) == before


def test_configured_pass_instances_do_not_collapse(tmp_path):
    """Two differently-.set() instances of the SAME registered pass are
    distinct candidates: each is applied and measured on its own clone,
    and the winner re-materializes from its measured instance."""
    applied = []

    @ir.register_pass
    class _KnobPass(ir.Pass):
        name = "tune_test_knob"

        def apply(self, program):
            applied.append(self.get("knob"))
            return program

    try:
        main, out = _conv_bn_relu()
        p1 = ir.get_pass("tune_test_knob").set("knob", 1)
        p2 = ir.get_pass("tune_test_knob").set("knob", 2)
        space = tune.SearchSpace(pipelines=[[], [p1], [p2]],
                                 donate=(True,), sharding=False)
        rep = tune.search(main, [out.name], space=space, use_cache=False,
                          k=1, warmup=1)
        # both configurations were actually applied (no dedup collapse)
        assert applied.count(1) == 1 and applied.count(2) == 1
        assert rep.counts()["timed"] == 3
        # the winner re-applies its OWN instance (attrs preserved)
        applied.clear()
        tune.tuned_program(main, rep)
        if rep.winner.params["pipeline"] == ["tune_test_knob"]:
            assert applied in ([1], [2])
    finally:
        ir._PASS_REGISTRY.pop("tune_test_knob", None)


def test_step_variant_set_is_cache_identity(tmp_path):
    costs = {"default": 0.01, "fast": 0.005, "faster": 0.003}

    def bt(params):
        return costs[params["name"]]

    v2 = [(n, {"name": n}) for n in ("default", "fast")]
    v3 = [(n, {"name": n}) for n in ("default", "fast", "faster")]
    kw = dict(workload="varset", cache_dir=str(tmp_path))
    assert not tune.search_step(bt, v2, **kw).cache_hit
    assert tune.search_step(bt, v2, **kw).cache_hit
    # a new variant re-opens the search and can win
    rep = tune.search_step(bt, v3, **kw)
    assert not rep.cache_hit
    assert rep.winner.params["name"] == "faster"


def test_ladder_cache_hits_on_proportional_traffic(tmp_path):
    """A restarted server tunes against a longer but proportionally
    identical traffic log: same distribution, same cache entry."""
    runner = _RowCostRunner(per_row_s=1e-5)
    example = {"x": np.zeros((1, 4), np.float32)}
    kw = dict(max_batch=8, workload="prop", k=1, cache_dir=str(tmp_path))
    assert not tune.search_bucket_ladder(
        runner, example, [1, 1, 2], **kw).cache_hit
    assert tune.search_bucket_ladder(
        runner, example, [1, 1, 1, 1, 2, 2], **kw).cache_hit
    # a genuinely shifted mix re-opens the search
    assert not tune.search_bucket_ladder(
        runner, example, [1, 2, 2], **kw).cache_hit


# ---------------------------------------------------------------------------
# PR 11: fused-GEMM block search + the new passes in the default space
# ---------------------------------------------------------------------------


def test_default_pipelines_include_fusion_passes():
    pipes = tune.default_pass_pipelines()
    assert ["matmul_bias_act_fuse"] in pipes
    assert ["transpose_fold"] in pipes
    # the all-passes pipeline keeps fuse-then-clean order
    full = max(pipes, key=len)
    assert full.index("matmul_bias_act_fuse") < full.index(
        "dead_op_elimination")
    assert full.index("transpose_fold") < full.index(
        "dead_op_elimination")


def test_gemm_block_candidates_divisors_default_first():
    cands = tune.gemm_block_candidates(512, 512, 512)
    triples = [(c.params["block_m"], c.params["block_n"],
                c.params["block_k"]) for c in cands]
    assert triples[0] == (512, 512, 512)    # heuristic default leads
    assert set(triples) == {(a, b, c) for a in (512, 256, 128)
                            for b in (512, 256, 128)
                            for c in (512, 256, 128)}
    # a non-512-divisible dim restricts its axis of the grid — args are
    # (m, k, n), the same order as search_gemm_blocks/matmul_bias_act
    assert all(c.params["block_k"] != 512
               for c in tune.gemm_block_candidates(512, 256, 512))
    assert all(c.params["block_n"] != 512
               for c in tune.gemm_block_candidates(512, 512, 256))


def test_search_gemm_blocks_winner_and_cache(tmp_path):
    kw = dict(activation="gelu", grid=(256, 128), interpret=True,
              k_times=1, warmup=1, cache_dir=str(tmp_path))
    rep = tune.search_gemm_blocks(256, 256, 256, **kw)
    assert not rep.cache_hit
    timed = [r for r in rep.results if r.status == "timed"]
    assert timed and rep.winner is not None
    assert set(rep.winner.params) == {"block_m", "block_n", "block_k"}
    # same shape+grid hits the cache; a different activation re-opens it
    rep2 = tune.search_gemm_blocks(256, 256, 256, **kw)
    assert rep2.cache_hit
    assert rep2.winner.params == rep.winner.params
    kw3 = dict(kw)
    kw3["activation"] = "relu"
    assert not tune.search_gemm_blocks(256, 256, 256, **kw3).cache_hit


def test_search_gemm_blocks_winner_params_drive_the_kernel(tmp_path):
    """The winner's params slot straight into matmul_bias_act — and an
    invalid triple for the shape would raise, so a winner that runs IS
    the grid that was timed."""
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.matmul import matmul_bias_act

    rep = tune.search_gemm_blocks(
        256, 256, 256, activation="relu", grid=(128,), interpret=True,
        k_times=1, warmup=1, cache_dir=str(tmp_path))
    p = rep.winner.params
    x = jnp.zeros((256, 256), jnp.float32)
    w = jnp.zeros((256, 256), jnp.float32)
    out = matmul_bias_act(x, w, activation="relu", interpret=True,
                          block_m=p["block_m"], block_n=p["block_n"],
                          block_k=p["block_k"])
    assert out.shape == (256, 256)
