"""paddle_tpu.incubate.complex — value oracles against numpy.

The reference's `python/paddle/incubate/complex/` pairs two real tensors
into a ComplexVariable; here JAX's native complex64/complex128 carry the
values, so every wrapper is checked against the numpy result on the
same operands (the cheapest possible oracle)."""

import numpy as np
import pytest

from paddle_tpu.fluid import dygraph
from paddle_tpu.incubate import complex as pc


def _c(shape, seed, dtype=np.complex64):
    r = np.random.RandomState(seed)
    return (r.randn(*shape) + 1j * r.randn(*shape)).astype(dtype)


@pytest.mark.parametrize("dtype", [np.complex64, np.complex128],
                         ids=["c64", "c128"])
def test_elementwise_values(dtype):
    import jax

    a, b = _c((3, 4), 0, dtype), _c((3, 4), 1, dtype)
    # without JAX_ENABLE_X64, jax canonicalizes complex128 -> complex64
    want = dtype if (dtype == np.complex64
                     or jax.config.jax_enable_x64) else np.complex64
    tol = 1e-5 if want == np.complex64 else 1e-12
    for fn, ref in [(pc.elementwise_add, np.add),
                    (pc.elementwise_sub, np.subtract),
                    (pc.elementwise_mul, np.multiply),
                    (pc.elementwise_div, np.divide)]:
        got = np.asarray(fn(a, b))
        assert got.dtype == want
        np.testing.assert_allclose(got, ref(a, b), rtol=tol, atol=tol)


def test_matmul_values_and_transpose_flags():
    a, b = _c((3, 4), 0), _c((4, 5), 1)
    np.testing.assert_allclose(
        np.asarray(pc.matmul(a, b)), a @ b, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(pc.matmul(a.T, b, transpose_x=True)), a @ b,
        rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(pc.matmul(a, b.T, transpose_y=True)), a @ b,
        rtol=1e-5, atol=1e-5)
    # batched
    ba, bb = _c((2, 3, 4), 2), _c((2, 4, 5), 3)
    np.testing.assert_allclose(
        np.asarray(pc.matmul(ba, bb)), ba @ bb, rtol=1e-5, atol=1e-5)


def test_kron_values():
    a, b = _c((2, 3), 0), _c((3, 2), 1)
    np.testing.assert_allclose(
        np.asarray(pc.kron(a, b)), np.kron(a, b), rtol=1e-5, atol=1e-5)


def test_reshape_and_transpose_move_values_untouched():
    a = _c((2, 3, 4), 0)
    np.testing.assert_array_equal(
        np.asarray(pc.reshape(a, [4, 6])), a.reshape(4, 6))
    # transpose permutes axes with NO conjugation
    np.testing.assert_array_equal(
        np.asarray(pc.transpose(a, [2, 0, 1])), np.transpose(a, (2, 0, 1)))


def test_real_complex_promotion_matches_numpy():
    a = _c((3, 3), 0)
    r = np.random.RandomState(9).randn(3, 3).astype(np.float32)
    got = np.asarray(pc.elementwise_mul(a, r))
    assert got.dtype == np.complex64
    np.testing.assert_allclose(got, a * r, rtol=1e-5, atol=1e-5)


def test_is_complex():
    assert pc.is_complex(_c((2,), 0))
    assert not pc.is_complex(np.ones(3, np.float32))


def test_varbase_in_varbase_out():
    a, b = _c((3, 4), 0), _c((4, 5), 1)
    with dygraph.guard():
        va = dygraph.to_variable(a)
        out = pc.matmul(va, b)
        assert isinstance(out, dygraph.varbase.VarBase)
        assert out.dtype == "complex64"
        np.testing.assert_allclose(out.numpy(), a @ b,
                                   rtol=1e-5, atol=1e-5)
        t = pc.transpose(va, [1, 0])
        np.testing.assert_array_equal(t.numpy(), a.T)
    # raw arrays in -> raw array out (no tracer required)
    assert not isinstance(pc.kron(a, b[:3, :2]),
                          dygraph.varbase.VarBase)
